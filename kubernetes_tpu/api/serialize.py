"""Object → manifest serialization (the inverse of each type's from_dict).

Reference: staging/src/k8s.io/apimachinery/pkg/runtime serializer/json — the
apiserver's wire form.  Every served kind round-trips:
``scheme.decode(to_manifest(obj))`` reconstructs the object (status
subresources of workload kinds excepted, matching the reference's
spec-vs-status split on ordinary writes).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from . import objects as v1
from ..component_base import logging as klog

# snake_case fields whose wire names are not plain camelCase
_RENAMES = {
    "host_ip": "hostIP",
    "pod_ip": "podIP",
    "pod_cidr": "podCIDR",
}
# NodeAffinity/PodAffinity/PodAntiAffinity wire names for required/preferred
_AFFINITY_RENAMES = {
    "required": "requiredDuringSchedulingIgnoredDuringExecution",
    "preferred": "preferredDuringSchedulingIgnoredDuringExecution",
}


def _camel(s: str) -> str:
    if s in _RENAMES:
        return _RENAMES[s]
    head, *rest = s.split("_")
    return head + "".join(w.capitalize() for w in rest)


def _is_default(f: dataclasses.Field, value) -> bool:
    if f.default is not dataclasses.MISSING:
        return value == f.default
    if f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
        try:
            return value == f.default_factory()  # type: ignore[misc]
        except Exception as e:
            # treat an unevaluable default as "not default" (the field gets
            # serialized — lossless), but say so: a raising default_factory
            # is a schema bug worth seeing, not swallowing
            klog.V(1).info_s("default_factory failed during serialization",
                             field=f.name, err=f"{type(e).__name__}: {e}")
            return False
    return False


def _ser(value: Any) -> Any:
    """Generic dataclass → camelCase dict, skipping default-valued fields."""
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        renames = (_AFFINITY_RENAMES
                   if isinstance(value, (v1.NodeAffinity, v1.PodAffinity))
                   else {})
        out = {}
        for f in dataclasses.fields(value):
            val = getattr(value, f.name)
            if val is None or _is_default(f, val):
                continue
            out[renames.get(f.name) or _camel(f.name)] = _ser(val)
        return out
    if isinstance(value, dict):
        return {k: _ser(x) for k, x in value.items()}
    if isinstance(value, (list, tuple)):
        return [_ser(x) for x in value]
    return value


def _meta(meta: v1.ObjectMeta) -> Dict[str, Any]:
    out: Dict[str, Any] = {"name": meta.name}
    if meta.namespace:
        out["namespace"] = meta.namespace
    if meta.uid:
        out["uid"] = meta.uid
    if meta.labels:
        out["labels"] = dict(meta.labels)
    if meta.annotations:
        out["annotations"] = dict(meta.annotations)
    if meta.resource_version:
        out["resourceVersion"] = str(meta.resource_version)
    if meta.creation_timestamp:
        out["creationTimestamp"] = meta.creation_timestamp
    if meta.deletion_timestamp is not None:
        out["deletionTimestamp"] = meta.deletion_timestamp
    if meta.owner_references:
        out["ownerReferences"] = [
            {"apiVersion": o.api_version, "kind": o.kind, "name": o.name,
             "uid": o.uid, "controller": o.controller}
            for o in meta.owner_references
        ]
    return out


def _volume(vol: v1.Volume) -> Dict[str, Any]:
    out: Dict[str, Any] = {"name": vol.name}
    if vol.pvc_name is not None:
        out["persistentVolumeClaim"] = {"claimName": vol.pvc_name}
    if vol.host_path is not None:
        out["hostPath"] = {"path": vol.host_path}
    if vol.gce_pd_name is not None:
        out["gcePersistentDisk"] = {"pdName": vol.gce_pd_name}
    if vol.aws_ebs_volume_id is not None:
        out["awsElasticBlockStore"] = {"volumeID": vol.aws_ebs_volume_id}
    return out


def _pod_spec(spec: v1.PodSpec) -> Dict[str, Any]:
    out = _ser(spec)
    if spec.volumes:
        out["volumes"] = [_volume(vol) for vol in spec.volumes]
    return out


def _template(t: v1.PodTemplateSpec) -> Dict[str, Any]:
    return {"metadata": {"labels": dict(t.labels)},
            "spec": _pod_spec(t.spec)}


def _workload_spec(obj) -> Dict[str, Any]:
    spec: Dict[str, Any] = {"template": _template(obj.template)}
    if getattr(obj, "selector", None) is not None:
        spec["selector"] = _ser(obj.selector)
    if hasattr(obj, "replicas"):
        spec["replicas"] = obj.replicas
    return spec


def _storageclass_topologies(sc: v1.StorageClass):
    return [
        {"matchLabelExpressions": [
            {"key": r.key, "values": list(r.values)}
            for r in term.match_expressions
        ]}
        for term in sc.allowed_topologies.node_selector_terms
    ]


def _spec_status(obj) -> Dict[str, Any]:
    """Kind-specific body (everything except metadata/apiVersion/kind)."""
    if getattr(obj, "_custom_resource", False):
        # dynamically-registered kind (apiextensions/api.CustomResource):
        # the body IS the manifest body, kept verbatim at decode time —
        # serving it back is a copy, not a schema-aware walk.  Marker-attr
        # dispatch, not an import: the apiextensions package imports the
        # scheme, which imports this module.
        import copy as _copy

        return {k: _copy.deepcopy(val) for k, val in obj.body.items()}
    if isinstance(obj, (v1.Pod, v1.Node)):
        body = {"spec": (_pod_spec(obj.spec) if isinstance(obj, v1.Pod)
                         else _ser(obj.spec))}
        status = _ser(obj.status)
        if isinstance(obj, v1.Node):
            # allocatable defaults to capacity in from_dict; keep both
            status = {"capacity": dict(obj.status.capacity),
                      "allocatable": dict(obj.status.allocatable),
                      "images": _ser(obj.status.images),
                      "conditions": list(obj.status.conditions),
                      "volumesAttached": [
                          {"name": n} for n in obj.status.volumes_attached
                      ]}
        return {**body, "status": status}
    if isinstance(obj, v1.Service):
        return {"spec": {"selector": dict(obj.selector)}}
    if isinstance(obj, v1.PodDisruptionBudget):
        return {
            "spec": {k: val for k, val in (
                ("selector", _ser(obj.selector) if obj.selector else None),
                ("minAvailable", obj.min_available),
                ("maxUnavailable", obj.max_unavailable)) if val is not None},
            "status": {"disruptionsAllowed": obj.disruptions_allowed,
                       "currentHealthy": obj.current_healthy,
                       "desiredHealthy": obj.desired_healthy,
                       "expectedPods": obj.expected_pods},
        }
    if isinstance(obj, v1.PersistentVolumeClaim):
        spec: Dict[str, Any] = {
            "volumeName": obj.volume_name,
            "accessModes": list(obj.access_modes),
            "resources": {"requests": {"storage": obj.requested_storage}},
        }
        if obj.storage_class_name is not None:
            spec["storageClassName"] = obj.storage_class_name
        return {"spec": spec, "status": {"phase": obj.phase}}
    if isinstance(obj, v1.PersistentVolume):
        spec = {"capacity": dict(obj.capacity),
                "storageClassName": obj.storage_class_name,
                "accessModes": list(obj.access_modes)}
        if obj.node_affinity is not None:
            spec["nodeAffinity"] = {"required": _ser(obj.node_affinity)}
        if obj.claim_ref:
            ns, _, name = obj.claim_ref.partition("/")
            spec["claimRef"] = {"namespace": ns, "name": name}
        return {"spec": spec}
    if isinstance(obj, v1.PodGroup):
        spec: Dict[str, Any] = {"minMember": obj.min_member}
        if obj.schedule_timeout_seconds is not None:
            spec["scheduleTimeoutSeconds"] = obj.schedule_timeout_seconds
        return {"spec": spec, "status": {"phase": obj.phase}}
    if isinstance(obj, v1.PriorityClass):
        return {"value": obj.value, "globalDefault": obj.global_default,
                "preemptionPolicy": obj.preemption_policy}
    if isinstance(obj, v1.StorageClass):
        out: Dict[str, Any] = {"volumeBindingMode": obj.volume_binding_mode,
                               "provisioner": obj.provisioner}
        if obj.allowed_topologies is not None:
            out["allowedTopologies"] = _storageclass_topologies(obj)
        return out
    if isinstance(obj, v1.CSINode):
        return {"spec": {"drivers": [
            {"name": name, "allocatable": {"count": count}}
            for name, count in obj.driver_limits.items()
        ]}}
    if isinstance(obj, (v1.ReplicaSet, v1.Deployment, v1.StatefulSet,
                        v1.DaemonSet)):
        return {"spec": _workload_spec(obj)}
    if isinstance(obj, v1.Job):
        spec = {"completions": obj.completions,
                "parallelism": obj.parallelism,
                "template": _template(obj.template)}
        if obj.ttl_seconds_after_finished is not None:
            spec["ttlSecondsAfterFinished"] = obj.ttl_seconds_after_finished
        return {"spec": spec,
                "status": {"succeeded": obj.status_succeeded,
                           "active": obj.status_active}}
    if isinstance(obj, v1.CronJob):
        spec = {"schedule": obj.schedule, "suspend": obj.suspend,
                "concurrencyPolicy": obj.concurrency_policy,
                "jobTemplate": {"spec": {
                    "completions": obj.job_completions,
                    "parallelism": obj.job_parallelism,
                    "template": _template(obj.job_template)}}}
        if obj.starting_deadline_seconds is not None:
            spec["startingDeadlineSeconds"] = obj.starting_deadline_seconds
        return {"spec": spec}
    if isinstance(obj, v1.Namespace):
        return {"spec": {"finalizers": list(obj.finalizers)},
                "status": {"phase": obj.status_phase}}
    if isinstance(obj, v1.ResourceQuota):
        return {"spec": {"hard": dict(obj.hard)},
                "status": {"hard": dict(obj.status_hard),
                           "used": dict(obj.status_used)}}
    if isinstance(obj, v1.Endpoints):
        return {"subsets": [
            {"addresses": [_ep_addr(a) for a in s.addresses],
             "notReadyAddresses": [_ep_addr(a)
                                   for a in s.not_ready_addresses],
             "ports": [{"port": p} for p in s.ports]}
            for s in obj.subsets
        ]}
    if isinstance(obj, v1.EndpointSlice):
        return {"addressType": obj.address_type,
                "ports": [{"port": p} for p in obj.ports],
                "endpoints": [
                    {"addresses": list(e.addresses),
                     "conditions": {"ready": e.ready},
                     "nodeName": e.node_name,
                     "targetRef": {"kind": "Pod", "name": e.target_name}}
                    for e in obj.endpoints
                ]}
    if isinstance(obj, v1.ServiceAccount):
        return {"secrets": list(obj.secrets)}
    if obj.__class__.__name__ == "DeviceClass":
        # resource.k8s.io family: name-based dispatch like NodeGroup below
        # (the types live in kubernetes_tpu/dra and importing them here
        # would cycle through the scheme)
        return {"spec": {"selectors": dict(obj.selectors)}}
    if obj.__class__.__name__ == "ResourceSlice":
        return {"spec": {
            "nodeName": obj.node_name,
            "pool": {"name": obj.pool},
            "driver": obj.driver,
            "devices": [{"name": dev.name,
                         "attributes": dict(dev.attributes)}
                        for dev in obj.devices],
        }}
    if obj.__class__.__name__ == "ResourceClaim":
        status: Dict[str, Any] = {"state": obj.state}
        if obj.allocated_node or obj.allocated_devices:
            status["allocation"] = {"nodeName": obj.allocated_node,
                                    "devices": list(obj.allocated_devices)}
        if obj.reserved_for:
            status["reservedFor"] = obj.reserved_for
        return {"spec": {"devices": {"requests": [_device_request(obj.request)]}},
                "status": status}
    if obj.__class__.__name__ == "ResourceClaimTemplate":
        return {"spec": {"spec": {
            "devices": {"requests": [_device_request(obj.request)]}}}}
    if obj.__class__.__name__ == "CustomResourceDefinition":
        # apiextensions family: name-based dispatch like NodeGroup below
        versions = [
            {"name": v, "served": True, "storage": v == obj.storage_version}
            for v in obj.versions
        ]
        if obj.schema:
            for entry in versions:
                if entry["storage"]:
                    entry["schema"] = {"openAPIV3Schema": obj.schema}
        return {"spec": {
            "group": obj.group,
            "scope": obj.scope,
            "names": {"plural": obj.names.plural,
                      "singular": obj.names.singular,
                      "kind": obj.names.kind,
                      "listKind": obj.names.list_kind},
            "versions": versions,
        }}
    if obj.__class__.__name__ in ("Role", "ClusterRole"):
        return {"rules": [
            {"verbs": list(r.verbs), "apiGroups": list(r.api_groups),
             "resources": list(r.resources),
             **({"resourceNames": list(r.resource_names)}
                if r.resource_names else {})}
            for r in obj.rules
        ]}
    if obj.__class__.__name__ in ("RoleBinding", "ClusterRoleBinding"):
        return {
            "subjects": [{"kind": s.kind, "name": s.name} for s in
                         obj.subjects],
            "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                        "kind": obj.role_ref.kind,
                        "name": obj.role_ref.name},
        }
    if obj.__class__.__name__ == "NodeGroup":
        # name-based dispatch like the HPA below: the type lives in the
        # autoscaler package and importing it here would cycle
        tmpl: Dict[str, Any] = {"capacity": dict(obj.capacity),
                                "labels": dict(obj.labels),
                                "taints": _ser(obj.taints)}
        if obj.slice_size:
            tmpl["sliceSize"] = obj.slice_size
        return {"spec": {"minSize": obj.min_size, "maxSize": obj.max_size,
                         "costPerNode": obj.cost_per_node,
                         "template": tmpl}}
    if obj.__class__.__name__ == "HorizontalPodAutoscaler":
        return {"spec": {
            "scaleTargetRef": {"kind": obj.target_kind,
                               "name": obj.target_name},
            "minReplicas": obj.min_replicas,
            "maxReplicas": obj.max_replicas,
            "metrics": [{"resource": {"name": "cpu", "target": {
                "averageUtilization": obj.target_utilization}}}],
        }}
    # unknown kind: best-effort generic walk
    body = _ser(obj)
    body.pop("metadata", None)
    return body


def _device_request(r) -> Dict[str, Any]:
    return {"name": r.name, "deviceClassName": r.device_class_name,
            "count": r.count}


def _ep_addr(a: v1.EndpointAddress) -> Dict[str, Any]:
    return {"ip": a.ip, "nodeName": a.node_name,
            "targetRef": {"kind": "Pod", "name": a.target_name}}


def to_manifest(obj, scheme=None) -> Dict[str, Any]:
    """Serialize a served object to its wire manifest.  ``scheme`` supplies
    the apiVersion (group/version); without one the kind alone is emitted."""
    out: Dict[str, Any] = {"kind": obj.kind}
    if scheme is not None:
        gv = scheme.gv_of(type(obj))
        if gv is not None:
            group, version = gv
            out["apiVersion"] = f"{group}/{version}" if group else version
    out["metadata"] = _meta(obj.metadata)
    out.update(_spec_status(obj))
    return out


def roundtrips(obj, scheme) -> bool:
    """decode(to_manifest(obj)) == obj — test helper."""
    return scheme.decode(to_manifest(obj, scheme)) == obj
