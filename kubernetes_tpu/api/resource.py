"""Resource quantity parsing and the scheduler's int64 resource vector.

Reference semantics: apimachinery's ``resource.Quantity`` (suffix grammar) and the
scheduler's ``framework.Resource`` struct (reference
``pkg/scheduler/framework/types.go:416-425``): MilliCPU, Memory, EphemeralStorage,
AllowedPodNumber, plus a map of scalar/extended resources. All values are held as
int64 — milli-units for CPU and HugePages-compatible integer units elsewhere — so
device tensors can be exact int64/float64 vectors.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from decimal import Decimal
from typing import Dict, Mapping, Optional

# Canonical resource names (reference: pkg/apis/core/types.go ResourceName consts).
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"
HUGEPAGES_PREFIX = "hugepages-"
ATTACHABLE_VOLUMES_PREFIX = "attachable-volumes-"

DEFAULT_MILLI_CPU_REQUEST = 100  # 0.1 core — reference pkg/scheduler/util/pod_resources.go
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024  # 200 MB

_BIN_SUFFIX = {
    "Ki": Decimal(1024),
    "Mi": Decimal(1024**2),
    "Gi": Decimal(1024**3),
    "Ti": Decimal(1024**4),
    "Pi": Decimal(1024**5),
    "Ei": Decimal(1024**6),
}
_DEC_SUFFIX = {
    "n": Decimal("1e-9"),
    "u": Decimal("1e-6"),
    "m": Decimal("1e-3"),
    "": Decimal(1),
    "k": Decimal("1e3"),
    "M": Decimal("1e6"),
    "G": Decimal("1e9"),
    "T": Decimal("1e12"),
    "P": Decimal("1e15"),
    "E": Decimal("1e18"),
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d*)?|\.\d+)(?:[eE](?P<exp>[+-]?\d+))?"
    r"(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|n|u|m|k|M|G|T|P|E)?$"
)


def parse_quantity_exact(s) -> Decimal:
    """Parse a Kubernetes quantity string ('100m', '2Gi', '1.5', '2e3') exactly.

    Decimal arithmetic matches apimachinery resource.Quantity (which is
    inf.Dec-backed) — float rounding would inflate values like '9m' under
    MilliValue's round-up. Accepts ints/floats pass-through for convenience when
    building synthetic objects.
    """
    if isinstance(s, int):
        return Decimal(s)
    if isinstance(s, float):
        return Decimal(repr(s))
    return _parse_quantity_str(str(s).strip())


from functools import lru_cache as _lru_cache


@_lru_cache(maxsize=8192)
def _parse_quantity_str(s: str) -> Decimal:
    """Cached string→Decimal core: quantity strings repeat massively ("1",
    "2Gi", "100m"…) and preemption dry-runs re-derive pod requests per
    candidate — this was 385k regex parses in one profiled cycle.  Decimal
    is immutable, so sharing results is safe."""
    m = _QUANTITY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity: {s!r}")
    value = Decimal(m.group("sign") + m.group("num"))
    if m.group("exp"):
        value = value.scaleb(int(m.group("exp")))
    suffix = m.group("suffix") or ""
    if suffix in _BIN_SUFFIX:
        value *= _BIN_SUFFIX[suffix]
    else:
        value *= _DEC_SUFFIX[suffix]
    return value


def parse_quantity(s) -> float:
    """Quantity → float (convenience; use the *_milli/_int exact paths for accounting)."""
    return float(parse_quantity_exact(s))


def _ceil_decimal(v: Decimal) -> int:
    iv = int(v)
    return iv if iv == v or v < 0 else iv + 1


def quantity_to_milli(s) -> int:
    """Quantity → integer milli-units (ceil, matching Quantity.MilliValue rounding up)."""
    return _ceil_decimal(parse_quantity_exact(s) * 1000)


def quantity_to_int(s) -> int:
    """Quantity → integer units (ceil for fractional, e.g. '1.5Gi' of memory)."""
    return _ceil_decimal(parse_quantity_exact(s))


def is_scalar_resource_name(name: str) -> bool:
    """Extended/scalar resources tracked in the ScalarResources map.

    Reference: pkg/scheduler/framework/types.go:518-536 (Add switch default) and
    helper.IsScalarResourceName.
    """
    return name not in (CPU, MEMORY, EPHEMERAL_STORAGE, PODS)


@dataclass
class Resource:
    """int64 resource vector (reference pkg/scheduler/framework/types.go:416-425)."""

    milli_cpu: int = 0
    memory: int = 0
    ephemeral_storage: int = 0
    allowed_pod_number: int = 0
    scalar_resources: Dict[str, int] = field(default_factory=dict)

    @classmethod
    def from_resource_list(cls, rl: Optional[Mapping[str, object]]) -> "Resource":
        """Build from a k8s ResourceList mapping (reference types.go:446-466 Add)."""
        r = cls()
        r.add_resource_list(rl)
        return r

    def add_resource_list(self, rl: Optional[Mapping[str, object]]) -> None:
        if not rl:
            return
        for name, q in rl.items():
            if name == CPU:
                self.milli_cpu += quantity_to_milli(q)
            elif name == MEMORY:
                self.memory += quantity_to_int(q)
            elif name == EPHEMERAL_STORAGE:
                self.ephemeral_storage += quantity_to_int(q)
            elif name == PODS:
                self.allowed_pod_number += quantity_to_int(q)
            else:
                self.scalar_resources[name] = self.scalar_resources.get(
                    name, 0
                ) + quantity_to_int(q)

    def set_max_resource_list(self, rl: Optional[Mapping[str, object]]) -> None:
        """Per-dimension max — used for initContainers (reference types.go:470-490)."""
        if not rl:
            return
        for name, q in rl.items():
            if name == CPU:
                self.milli_cpu = max(self.milli_cpu, quantity_to_milli(q))
            elif name == MEMORY:
                self.memory = max(self.memory, quantity_to_int(q))
            elif name == EPHEMERAL_STORAGE:
                self.ephemeral_storage = max(
                    self.ephemeral_storage, quantity_to_int(q)
                )
            elif name == PODS:
                self.allowed_pod_number = max(
                    self.allowed_pod_number, quantity_to_int(q)
                )
            else:
                self.scalar_resources[name] = max(
                    self.scalar_resources.get(name, 0), quantity_to_int(q)
                )

    def add(self, other: "Resource") -> "Resource":
        self.milli_cpu += other.milli_cpu
        self.memory += other.memory
        self.ephemeral_storage += other.ephemeral_storage
        self.allowed_pod_number += other.allowed_pod_number
        for k, v in other.scalar_resources.items():
            self.scalar_resources[k] = self.scalar_resources.get(k, 0) + v
        return self

    def sub(self, other: "Resource") -> "Resource":
        self.milli_cpu -= other.milli_cpu
        self.memory -= other.memory
        self.ephemeral_storage -= other.ephemeral_storage
        self.allowed_pod_number -= other.allowed_pod_number
        for k, v in other.scalar_resources.items():
            self.scalar_resources[k] = self.scalar_resources.get(k, 0) - v
        return self

    def clone(self) -> "Resource":
        return Resource(
            milli_cpu=self.milli_cpu,
            memory=self.memory,
            ephemeral_storage=self.ephemeral_storage,
            allowed_pod_number=self.allowed_pod_number,
            scalar_resources=dict(self.scalar_resources),
        )

    def get(self, name: str) -> int:
        if name == CPU:
            return self.milli_cpu
        if name == MEMORY:
            return self.memory
        if name == EPHEMERAL_STORAGE:
            return self.ephemeral_storage
        if name == PODS:
            return self.allowed_pod_number
        return self.scalar_resources.get(name, 0)

    def resource_names(self):
        names = [CPU, MEMORY, EPHEMERAL_STORAGE, PODS]
        names.extend(self.scalar_resources.keys())
        return names


def compute_pod_resource_request(pod) -> Resource:
    """Total request = max(sum(app containers), max(init containers)) + overhead.

    Reference: pkg/scheduler/framework/plugins/noderesources/fit.go:162-178
    (computePodResourceRequest) and types.go CalculateResource.

    Cached per pod object: NodeInfo add/remove/clone in preemption dry-runs
    re-derive the same pod's vector hundreds of times per scheduling attempt.
    The cache is keyed on a cheap fingerprint of the resource lists (not
    object identity alone), so in-place mutation of container resources —
    testutil builders and direct spec edits — invalidates it instead of
    silently serving stale vectors.
    """
    cached = getattr(pod, "_cached_resource_request", None)
    if cached is not None:
        # identity fast path: the request-dict objects themselves unchanged
        # (the hot case — preemption dry-runs call this hundreds of times per
        # attempt); fall back to the content fingerprint only on identity
        # miss, so in-place dict mutation still invalidates
        if _identity_match(cached[0], _resource_identity(pod)) or \
                cached[1] == _resource_fingerprint(pod):
            return cached[2]
    fp = _resource_fingerprint(pod)
    r = _compute_pod_resource_request(pod)
    try:
        pod._cached_resource_request = (_resource_identity(pod), fp, r)
    except (AttributeError, TypeError):
        pass  # __slots__/frozen pod stand-ins can't carry the cache
    return r


def _resource_identity(pod) -> tuple:
    """Object identities of everything the request computation reads.  All
    in-repo mutation paths REPLACE these dicts (testutil ``.req()`` assigns a
    fresh dict; store updates replace whole objects), so an identity match
    means unchanged content without paying the per-call fingerprint.  Code
    that mutates a requests dict's VALUES in place must replace the dict (or
    delete ``pod._cached_resource_request``) — same contract as the
    reference's immutable-spec assumption, but enforced at dict granularity.

    Holds the dict OBJECTS (matched via ``is``), not bare ``id()`` values: a
    cached id of a freed dict could be reused by a new dict with different
    content, serving a stale Resource; live references make reuse impossible.
    """
    return (
        tuple(c.resources.requests for c in pod.spec.containers),
        tuple(c.resources.requests for c in pod.spec.init_containers),
        pod.spec.overhead,
    )


def _identity_match(a: tuple, b: tuple) -> bool:
    """Element-wise ``is`` over two _resource_identity tuples."""
    ca, ia, oa = a
    cb, ib, ob = b
    return (
        oa is ob
        and len(ca) == len(cb) and all(x is y for x, y in zip(ca, cb))
        and len(ia) == len(ib) and all(x is y for x, y in zip(ia, ib))
    )


def _resource_fingerprint(pod) -> tuple:
    """Cheap content hash of everything _compute_pod_resource_request reads:
    container/initContainer request lists + overhead.  One pass over small
    dicts — far cheaper than re-parsing quantity strings."""
    return (
        tuple(tuple(sorted((c.resources.requests or {}).items()))
              for c in pod.spec.containers),
        tuple(tuple(sorted((c.resources.requests or {}).items()))
              for c in pod.spec.init_containers),
        tuple(sorted((pod.spec.overhead or {}).items())),
    )


def _compute_pod_resource_request(pod) -> Resource:
    r = Resource()
    for c in pod.spec.containers:
        r.add_resource_list(c.resources.requests)
    for c in pod.spec.init_containers:
        r.set_max_resource_list(c.resources.requests)
    if pod.spec.overhead:
        r.add_resource_list(pod.spec.overhead)
    return r


def compute_pod_resource_request_non_zero(pod) -> Resource:
    """Like compute_pod_resource_request but with cpu/memory floors for scoring
    (cached per pod object like compute_pod_resource_request).

    Reference: pkg/scheduler/util/pod_resources.go GetNonzeroRequests — pods with no
    request are treated as 100m CPU / 200MB memory so spreading still works — and
    pkg/scheduler/framework/types.go:738-746 (calculateResource adds pod overhead to
    the non-zero cpu/memory totals too).
    """
    cached = getattr(pod, "_cached_resource_request_nz", None)
    if cached is not None:
        if _identity_match(cached[0], _resource_identity(pod)) or \
                cached[1] == _resource_fingerprint(pod):
            return cached[2]
    r = _compute_pod_resource_request_non_zero(pod)
    try:
        pod._cached_resource_request_nz = (
            _resource_identity(pod), _resource_fingerprint(pod), r
        )
    except (AttributeError, TypeError):
        pass  # __slots__/frozen pod stand-ins can't carry the cache
    return r


def _compute_pod_resource_request_non_zero(pod) -> Resource:
    r = Resource()
    for c in pod.spec.containers:
        req = dict(c.resources.requests or {})
        if CPU not in req:
            req[CPU] = f"{DEFAULT_MILLI_CPU_REQUEST}m"
        if MEMORY not in req:
            req[MEMORY] = DEFAULT_MEMORY_REQUEST
        r.add_resource_list(req)
    for c in pod.spec.init_containers:
        r.set_max_resource_list(c.resources.requests)
    if pod.spec.overhead:
        r.add_resource_list(pod.spec.overhead)
    return r
