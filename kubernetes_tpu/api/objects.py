"""API object model — the subset of v1.Pod / v1.Node (+ friends) the scheduler reads.

Reference: staging/src/k8s.io/api/core/v1/types.go. Python dataclasses with
k8s-manifest-compatible ``from_dict`` constructors (camelCase keys), so workloads and
componentconfig written for the reference load unchanged. Only fields the scheduling
path consumes are modeled; unknown manifest fields are ignored rather than rejected.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional

_uid_counter = itertools.count(1)


def _new_uid() -> str:
    return f"uid-{next(_uid_counter)}"


def _parse_time(v, default=None) -> Optional[float]:
    """Accept epoch numbers or RFC3339 strings ('2026-01-01T00:00:00Z') → epoch float."""
    if v is None:
        return default
    if isinstance(v, (int, float)):
        return float(v)
    from datetime import datetime

    s = str(v).replace("Z", "+00:00")
    return datetime.fromisoformat(s).timestamp()


# --- metadata ---------------------------------------------------------------


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=_new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = field(default_factory=time.time)
    resource_version: int = 0
    owner_references: List["OwnerReference"] = field(default_factory=list)
    deletion_timestamp: Optional[float] = None

    @classmethod
    def from_dict(cls, d: Mapping) -> "ObjectMeta":
        return cls(
            name=d.get("name", ""),
            namespace=d.get("namespace", "default"),
            uid=d.get("uid") or _new_uid(),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
            creation_timestamp=_parse_time(d.get("creationTimestamp"), time.time()),
            owner_references=[
                OwnerReference.from_dict(o) for o in d.get("ownerReferences") or []
            ],
            deletion_timestamp=_parse_time(d.get("deletionTimestamp")),
        )


@dataclass
class OwnerReference:
    api_version: str = "v1"
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False

    @classmethod
    def from_dict(cls, d: Mapping) -> "OwnerReference":
        return cls(
            api_version=d.get("apiVersion", "v1"),
            kind=d.get("kind", ""),
            name=d.get("name", ""),
            uid=d.get("uid", ""),
            controller=bool(d.get("controller", False)),
        )


# --- selectors --------------------------------------------------------------

# LabelSelector operators (apimachinery metav1.LabelSelectorOperator).
OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
# NodeSelector-only operators (core v1.NodeSelectorOperator).
OP_GT = "Gt"
OP_LT = "Lt"


@dataclass
class LabelSelectorRequirement:
    key: str = ""
    operator: str = OP_EXISTS
    values: List[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Mapping) -> "LabelSelectorRequirement":
        return cls(
            key=d.get("key", ""),
            operator=d.get("operator", OP_EXISTS),
            values=[str(v) for v in d.get("values") or []],
        )


@dataclass
class LabelSelector:
    """metav1.LabelSelector: AND of match_labels and match_expressions.

    An empty selector matches everything; None (absent) matches nothing.
    """

    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[LabelSelectorRequirement] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> Optional["LabelSelector"]:
        if d is None:
            return None
        return cls(
            match_labels={k: str(v) for k, v in (d.get("matchLabels") or {}).items()},
            match_expressions=[
                LabelSelectorRequirement.from_dict(e)
                for e in d.get("matchExpressions") or []
            ],
        )


@dataclass
class NodeSelectorRequirement:
    key: str = ""
    operator: str = OP_EXISTS
    values: List[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Mapping) -> "NodeSelectorRequirement":
        return cls(
            key=d.get("key", ""),
            operator=d.get("operator", OP_EXISTS),
            values=[str(v) for v in d.get("values") or []],
        )


@dataclass
class NodeSelectorTerm:
    """OR-ed term; inside a term, expressions AND together (v1.NodeSelectorTerm)."""

    match_expressions: List[NodeSelectorRequirement] = field(default_factory=list)
    match_fields: List[NodeSelectorRequirement] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Mapping) -> "NodeSelectorTerm":
        return cls(
            match_expressions=[
                NodeSelectorRequirement.from_dict(e)
                for e in d.get("matchExpressions") or []
            ],
            match_fields=[
                NodeSelectorRequirement.from_dict(e)
                for e in d.get("matchFields") or []
            ],
        )


@dataclass
class NodeSelector:
    node_selector_terms: List[NodeSelectorTerm] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> Optional["NodeSelector"]:
        if d is None:
            return None
        return cls(
            node_selector_terms=[
                NodeSelectorTerm.from_dict(t)
                for t in d.get("nodeSelectorTerms") or []
            ]
        )


@dataclass
class PreferredSchedulingTerm:
    weight: int = 1
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)

    @classmethod
    def from_dict(cls, d: Mapping) -> "PreferredSchedulingTerm":
        return cls(
            weight=int(d.get("weight", 1)),
            preference=NodeSelectorTerm.from_dict(d.get("preference") or {}),
        )


# --- affinity ---------------------------------------------------------------


@dataclass
class NodeAffinity:
    required: Optional[NodeSelector] = None  # requiredDuringSchedulingIgnoredDuringExecution
    preferred: List[PreferredSchedulingTerm] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> Optional["NodeAffinity"]:
        if d is None:
            return None
        return cls(
            required=NodeSelector.from_dict(
                d.get("requiredDuringSchedulingIgnoredDuringExecution")
            ),
            preferred=[
                PreferredSchedulingTerm.from_dict(t)
                for t in d.get("preferredDuringSchedulingIgnoredDuringExecution") or []
            ],
        )


@dataclass
class PodAffinityTerm:
    label_selector: Optional[LabelSelector] = None
    namespaces: List[str] = field(default_factory=list)
    topology_key: str = ""
    namespace_selector: Optional[LabelSelector] = None

    @classmethod
    def from_dict(cls, d: Mapping) -> "PodAffinityTerm":
        return cls(
            label_selector=LabelSelector.from_dict(d.get("labelSelector")),
            namespaces=[str(n) for n in d.get("namespaces") or []],
            topology_key=d.get("topologyKey", ""),
            namespace_selector=LabelSelector.from_dict(d.get("namespaceSelector")),
        )


@dataclass
class WeightedPodAffinityTerm:
    weight: int = 1
    pod_affinity_term: PodAffinityTerm = field(default_factory=PodAffinityTerm)

    @classmethod
    def from_dict(cls, d: Mapping) -> "WeightedPodAffinityTerm":
        return cls(
            weight=int(d.get("weight", 1)),
            pod_affinity_term=PodAffinityTerm.from_dict(d.get("podAffinityTerm") or {}),
        )


@dataclass
class PodAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> Optional["PodAffinity"]:
        if d is None:
            return None
        return cls(
            required=[
                PodAffinityTerm.from_dict(t)
                for t in d.get("requiredDuringSchedulingIgnoredDuringExecution") or []
            ],
            preferred=[
                WeightedPodAffinityTerm.from_dict(t)
                for t in d.get("preferredDuringSchedulingIgnoredDuringExecution") or []
            ],
        )


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAffinity] = None

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> Optional["Affinity"]:
        if d is None:
            return None
        return cls(
            node_affinity=NodeAffinity.from_dict(d.get("nodeAffinity")),
            pod_affinity=PodAffinity.from_dict(d.get("podAffinity")),
            pod_anti_affinity=PodAffinity.from_dict(d.get("podAntiAffinity")),
        )


# --- taints & tolerations ---------------------------------------------------

TAINT_NO_SCHEDULE = "NoSchedule"
TAINT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_NO_EXECUTE = "NoExecute"

TOLERATION_OP_EXISTS = "Exists"
TOLERATION_OP_EQUAL = "Equal"


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = TAINT_NO_SCHEDULE
    # v1.Taint.TimeAdded: set for NoExecute taints by the node lifecycle
    # controller; tolerationSeconds countdowns anchor on it so a controller
    # restart resumes the SAME deadline instead of granting a fresh window
    time_added: Optional[float] = None

    @classmethod
    def from_dict(cls, d: Mapping) -> "Taint":
        return cls(
            key=d.get("key", ""),
            value=str(d.get("value", "")),
            effect=d.get("effect", TAINT_NO_SCHEDULE),
            time_added=_parse_time(d.get("timeAdded")),
        )


@dataclass
class Toleration:
    key: str = ""
    operator: str = TOLERATION_OP_EQUAL
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: Optional[int] = None

    @classmethod
    def from_dict(cls, d: Mapping) -> "Toleration":
        return cls(
            key=d.get("key", ""),
            operator=d.get("operator", TOLERATION_OP_EQUAL),
            value=str(d.get("value", "")),
            effect=d.get("effect", ""),
            toleration_seconds=d.get("tolerationSeconds"),
        )

    def tolerates(self, taint: Taint) -> bool:
        """Reference: component-helpers scheduling/corev1 Toleration.ToleratesTaint."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == TOLERATION_OP_EXISTS:
            return True
        # Equal (default): empty key with Exists already handled; empty key+Equal
        # matches only empty taint key (handled by key check above).
        return self.value == taint.value


# --- topology spread --------------------------------------------------------

DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"


@dataclass
class TopologySpreadConstraint:
    max_skew: int = 1
    topology_key: str = ""
    when_unsatisfiable: str = DO_NOT_SCHEDULE
    label_selector: Optional[LabelSelector] = None
    min_domains: Optional[int] = None

    @classmethod
    def from_dict(cls, d: Mapping) -> "TopologySpreadConstraint":
        return cls(
            max_skew=int(d.get("maxSkew", 1)),
            topology_key=d.get("topologyKey", ""),
            when_unsatisfiable=d.get("whenUnsatisfiable", DO_NOT_SCHEDULE),
            label_selector=LabelSelector.from_dict(d.get("labelSelector")),
            min_domains=d.get("minDomains"),
        )


# --- pod --------------------------------------------------------------------


@dataclass
class ContainerPort:
    container_port: int = 0
    host_port: int = 0
    host_ip: str = ""
    protocol: str = "TCP"

    @classmethod
    def from_dict(cls, d: Mapping) -> "ContainerPort":
        return cls(
            container_port=int(d.get("containerPort", 0)),
            host_port=int(d.get("hostPort", 0)),
            host_ip=d.get("hostIP", ""),
            protocol=d.get("protocol", "TCP"),
        )


@dataclass
class ResourceRequirements:
    requests: Dict[str, object] = field(default_factory=dict)
    limits: Dict[str, object] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> "ResourceRequirements":
        d = d or {}
        return cls(
            requests=dict(d.get("requests") or {}),
            limits=dict(d.get("limits") or {}),
        )


@dataclass
class Container:
    name: str = ""
    image: str = ""
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    ports: List[ContainerPort] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Mapping) -> "Container":
        return cls(
            name=d.get("name", ""),
            image=d.get("image", ""),
            resources=ResourceRequirements.from_dict(d.get("resources")),
            ports=[ContainerPort.from_dict(p) for p in d.get("ports") or []],
        )


@dataclass
class Volume:
    name: str = ""
    pvc_name: Optional[str] = None  # persistentVolumeClaim.claimName
    host_path: Optional[str] = None
    gce_pd_name: Optional[str] = None
    aws_ebs_volume_id: Optional[str] = None

    @classmethod
    def from_dict(cls, d: Mapping) -> "Volume":
        pvc = d.get("persistentVolumeClaim") or {}
        hp = d.get("hostPath") or {}
        gce = d.get("gcePersistentDisk") or {}
        ebs = d.get("awsElasticBlockStore") or {}
        return cls(
            name=d.get("name", ""),
            pvc_name=pvc.get("claimName"),
            host_path=hp.get("path"),
            gce_pd_name=gce.get("pdName"),
            aws_ebs_volume_id=ebs.get("volumeID"),
        )


@dataclass
class PodResourceClaim:
    """spec.resourceClaims entry: a pod-local name bound to either an
    existing ResourceClaim or a ResourceClaimTemplate the claim controller
    stamps a per-pod claim from (resource.k8s.io DRA)."""

    name: str = ""
    resource_claim_name: Optional[str] = None
    resource_claim_template_name: Optional[str] = None

    @classmethod
    def from_dict(cls, d: Mapping) -> "PodResourceClaim":
        return cls(
            name=d.get("name", ""),
            resource_claim_name=d.get("resourceClaimName"),
            resource_claim_template_name=d.get("resourceClaimTemplateName"),
        )


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = field(default_factory=list)
    priority: int = 0
    priority_class_name: str = ""
    scheduler_name: str = "default-scheduler"
    topology_spread_constraints: List[TopologySpreadConstraint] = field(
        default_factory=list
    )
    overhead: Dict[str, object] = field(default_factory=dict)
    volumes: List[Volume] = field(default_factory=list)
    host_network: bool = False
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"
    resource_claims: List[PodResourceClaim] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Mapping) -> "PodSpec":
        return cls(
            containers=[Container.from_dict(c) for c in d.get("containers") or []],
            init_containers=[
                Container.from_dict(c) for c in d.get("initContainers") or []
            ],
            node_name=d.get("nodeName", ""),
            node_selector={
                k: str(v) for k, v in (d.get("nodeSelector") or {}).items()
            },
            affinity=Affinity.from_dict(d.get("affinity")),
            tolerations=[Toleration.from_dict(t) for t in d.get("tolerations") or []],
            priority=int(d.get("priority", 0)),
            priority_class_name=d.get("priorityClassName", ""),
            scheduler_name=d.get("schedulerName", "default-scheduler"),
            topology_spread_constraints=[
                TopologySpreadConstraint.from_dict(t)
                for t in d.get("topologySpreadConstraints") or []
            ],
            overhead=dict(d.get("overhead") or {}),
            volumes=[Volume.from_dict(v) for v in d.get("volumes") or []],
            host_network=bool(d.get("hostNetwork", False)),
            preemption_policy=d.get("preemptionPolicy", "PreemptLowerPriority"),
            resource_claims=[
                PodResourceClaim.from_dict(c)
                for c in d.get("resourceClaims") or []
            ],
        )


POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"


@dataclass
class PodStatus:
    phase: str = POD_PENDING
    nominated_node_name: str = ""
    conditions: List[Dict] = field(default_factory=list)
    pod_ip: str = ""

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> "PodStatus":
        d = d or {}
        return cls(
            phase=d.get("phase", POD_PENDING),
            nominated_node_name=d.get("nominatedNodeName", ""),
            conditions=list(d.get("conditions") or []),
            pod_ip=str(d.get("podIP", "")),
        )


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    kind = "Pod"

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def uid(self) -> str:
        return self.metadata.uid

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @classmethod
    def from_dict(cls, d: Mapping) -> "Pod":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=PodSpec.from_dict(d.get("spec") or {}),
            status=PodStatus.from_dict(d.get("status")),
        )


# --- node -------------------------------------------------------------------


@dataclass
class ContainerImage:
    names: List[str] = field(default_factory=list)
    size_bytes: int = 0

    @classmethod
    def from_dict(cls, d: Mapping) -> "ContainerImage":
        return cls(
            names=[str(n) for n in d.get("names") or []],
            size_bytes=int(d.get("sizeBytes", 0)),
        )


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: List[Taint] = field(default_factory=list)
    pod_cidr: str = ""

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> "NodeSpec":
        d = d or {}
        return cls(
            unschedulable=bool(d.get("unschedulable", False)),
            taints=[Taint.from_dict(t) for t in d.get("taints") or []],
            pod_cidr=d.get("podCIDR", ""),
        )


@dataclass
class NodeStatus:
    capacity: Dict[str, object] = field(default_factory=dict)
    allocatable: Dict[str, object] = field(default_factory=dict)
    images: List[ContainerImage] = field(default_factory=list)
    conditions: List[Dict] = field(default_factory=list)
    # v1.NodeStatus.volumesAttached (AttachedVolume names), maintained by
    # the attach-detach controller (controllers/volumebinder.py)
    volumes_attached: List[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> "NodeStatus":
        d = d or {}
        cap = dict(d.get("capacity") or {})
        alloc = dict(d.get("allocatable") or cap)
        return cls(
            capacity=cap,
            allocatable=alloc,
            images=[ContainerImage.from_dict(i) for i in d.get("images") or []],
            conditions=list(d.get("conditions") or []),
            volumes_attached=[
                (v.get("name") if isinstance(v, Mapping) else str(v))
                for v in d.get("volumesAttached") or []
            ],
        )


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    kind = "Node"

    @property
    def name(self) -> str:
        return self.metadata.name

    @classmethod
    def from_dict(cls, d: Mapping) -> "Node":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=NodeSpec.from_dict(d.get("spec")),
            status=NodeStatus.from_dict(d.get("status")),
        )


# --- policy / misc objects the scheduler consumes ---------------------------


@dataclass
class PodDisruptionBudget:
    """policy/v1 PDB: spec (minAvailable/maxUnavailable, int or percent) +
    the status the disruption controller maintains and preemption reads
    (pkg/controller/disruption/disruption.go)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    min_available: Optional[object] = None  # int | "NN%" | None
    max_unavailable: Optional[object] = None  # int | "NN%" | None
    # status
    disruptions_allowed: int = 0
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0

    kind = "PodDisruptionBudget"

    @classmethod
    def from_dict(cls, d: Mapping) -> "PodDisruptionBudget":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            selector=LabelSelector.from_dict(spec.get("selector")),
            min_available=spec.get("minAvailable"),
            max_unavailable=spec.get("maxUnavailable"),
            disruptions_allowed=int(status.get("disruptionsAllowed", 0)),
            current_healthy=int(status.get("currentHealthy", 0)),
            desired_healthy=int(status.get("desiredHealthy", 0)),
            expected_pods=int(status.get("expectedPods", 0)),
        )


@dataclass
class Eviction:
    """policy/v1 Eviction — the pods/{name}/eviction subresource body.

    Reference: staging/src/k8s.io/api/policy/v1/types.go Eviction.  The
    metadata names the pod to evict; deleteOptions passes through to the
    delete (only gracePeriodSeconds is modeled — the sim terminates pods
    instantly either way).  Handled by descheduler/evictions.py (the gate)
    and served at POST pods/{name}/eviction by the apiserver (429
    TooManyRequests when a matching PDB has no budget, exactly the
    reference handler's contract)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    grace_period_seconds: Optional[int] = None  # deleteOptions.gracePeriodSeconds

    kind = "Eviction"

    @classmethod
    def from_dict(cls, d: Mapping) -> "Eviction":
        opts = d.get("deleteOptions") or {}
        # both the wire form (deleteOptions.gracePeriodSeconds) and the
        # generic serializer's flat camelCase field round-trip
        gps = opts.get("gracePeriodSeconds", d.get("gracePeriodSeconds"))
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            grace_period_seconds=(None if gps is None else int(gps)),
        )


# PodGroup phases (the coscheduling CRD's PodGroupStatus.Phase subset the
# gang subsystem drives; see kubernetes_tpu/gang/).
POD_GROUP_PENDING = "Pending"
POD_GROUP_SCHEDULING = "Scheduling"
POD_GROUP_SCHEDULED = "Scheduled"
POD_GROUP_UNSCHEDULABLE = "Unschedulable"


@dataclass
class PodGroup:
    """scheduling.x-k8s.io/v1alpha1 PodGroup — the gang-scheduling unit.

    Reference: sigs.k8s.io/scheduler-plugins apis/scheduling/v1alpha1
    (PodGroupSpec.MinMember / ScheduleTimeoutSeconds, PodGroupStatus.Phase).
    Pods join a group via the ``pod-group.scheduling/name`` label
    (gang.POD_GROUP_LABEL); the group schedules all-or-nothing once at
    least ``min_member`` members exist.
    """

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    min_member: int = 1
    schedule_timeout_seconds: Optional[int] = None  # None → subsystem default
    phase: str = POD_GROUP_PENDING  # status.phase

    kind = "PodGroup"

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @classmethod
    def from_dict(cls, d: Mapping) -> "PodGroup":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        sts = spec.get("scheduleTimeoutSeconds")
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            min_member=int(spec.get("minMember", 1)),
            schedule_timeout_seconds=(None if sts is None else int(sts)),
            phase=status.get("phase", POD_GROUP_PENDING),
        )


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    volume_name: str = ""
    storage_class_name: Optional[str] = None
    phase: str = "Pending"  # Bound once volume_name set
    requested_storage: object = 0  # spec.resources.requests.storage quantity
    access_modes: List[str] = field(default_factory=list)

    kind = "PersistentVolumeClaim"

    @classmethod
    def from_dict(cls, d: Mapping) -> "PersistentVolumeClaim":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            volume_name=spec.get("volumeName", ""),
            storage_class_name=spec.get("storageClassName"),
            phase=status.get("phase", "Pending"),
            requested_storage=((spec.get("resources") or {}).get("requests") or {}).get("storage", 0),
            access_modes=[str(x) for x in spec.get("accessModes") or []],
        )


@dataclass
class PersistentVolume:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    capacity: Dict[str, object] = field(default_factory=dict)
    node_affinity: Optional[NodeSelector] = None
    storage_class_name: str = ""
    claim_ref: Optional[str] = None  # "namespace/name" of the bound PVC
    access_modes: List[str] = field(default_factory=list)

    kind = "PersistentVolume"

    @classmethod
    def from_dict(cls, d: Mapping) -> "PersistentVolume":
        spec = d.get("spec") or {}
        na = (spec.get("nodeAffinity") or {}).get("required")
        cr = spec.get("claimRef") or {}
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            capacity=dict(spec.get("capacity") or {}),
            node_affinity=NodeSelector.from_dict(na),
            storage_class_name=spec.get("storageClassName", ""),
            claim_ref=(
                f"{cr.get('namespace', '')}/{cr.get('name', '')}" if cr else None
            ),
            access_modes=[str(x) for x in spec.get("accessModes") or []],
        )


@dataclass
class PriorityClass:
    """scheduling.k8s.io/v1 PriorityClass — resolved into pod.spec.priority at
    admission (the reference's Priority admission plugin)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False
    preemption_policy: str = "PreemptLowerPriority"

    kind = "PriorityClass"

    @classmethod
    def from_dict(cls, d: Mapping) -> "PriorityClass":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            value=int(d.get("value", 0)),
            global_default=bool(d.get("globalDefault", False)),
            preemption_policy=d.get("preemptionPolicy", "PreemptLowerPriority"),
        )


VOLUME_BINDING_IMMEDIATE = "Immediate"
VOLUME_BINDING_WAIT = "WaitForFirstConsumer"


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    volume_binding_mode: str = VOLUME_BINDING_IMMEDIATE
    provisioner: str = ""
    # storagev1 AllowedTopologies ([]TopologySelectorTerm): terms OR, a
    # term's matchLabelExpressions AND — exactly NodeSelector semantics with
    # In operators, so it is modeled as one (used by topology-aware dynamic
    # provisioning, volumebinding/binder.go checkVolumeProvisions)
    allowed_topologies: Optional[NodeSelector] = None

    kind = "StorageClass"

    @classmethod
    def from_dict(cls, d: Mapping) -> "StorageClass":
        terms = []
        for t in d.get("allowedTopologies") or []:
            reqs = [
                NodeSelectorRequirement(
                    key=e.get("key", ""), operator=OP_IN,
                    values=[str(v) for v in e.get("values") or []],
                )
                for e in t.get("matchLabelExpressions") or []
            ]
            terms.append(NodeSelectorTerm(match_expressions=reqs))
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            volume_binding_mode=d.get("volumeBindingMode", VOLUME_BINDING_IMMEDIATE),
            provisioner=d.get("provisioner", ""),
            allowed_topologies=NodeSelector(node_selector_terms=terms) if terms else None,
        )


@dataclass
class CSINode:
    """storage.k8s.io/v1 CSINode — per-driver attach limits the scheduler reads."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    driver_limits: Dict[str, int] = field(default_factory=dict)  # driver → count

    kind = "CSINode"

    @classmethod
    def from_dict(cls, d: Mapping) -> "CSINode":
        spec = d.get("spec") or {}
        limits = {}
        for drv in spec.get("drivers") or []:
            alloc = drv.get("allocatable") or {}
            if "count" in alloc:
                limits[drv.get("name", "")] = int(alloc["count"])
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   driver_limits=limits)


@dataclass
class Service:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Dict[str, str] = field(default_factory=dict)

    kind = "Service"

    @classmethod
    def from_dict(cls, d: Mapping) -> "Service":
        spec = d.get("spec") or {}
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            selector={k: str(v) for k, v in (spec.get("selector") or {}).items()},
        )


@dataclass
class PodTemplateSpec:
    """spec.template of workload controllers."""

    labels: Dict[str, str] = field(default_factory=dict)
    spec: PodSpec = field(default_factory=PodSpec)

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> "PodTemplateSpec":
        d = d or {}
        meta = d.get("metadata") or {}
        return cls(
            labels=dict(meta.get("labels") or {}),
            spec=PodSpec.from_dict(d.get("spec") or {}),
        )


@dataclass
class ReplicaSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    replicas: int = 1
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    status_replicas: int = 0
    status_ready_replicas: int = 0

    kind = "ReplicaSet"

    @classmethod
    def from_dict(cls, d: Mapping) -> "ReplicaSet":
        spec = d.get("spec") or {}
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            selector=LabelSelector.from_dict(spec.get("selector")),
            replicas=int(spec.get("replicas", 1)),
            template=PodTemplateSpec.from_dict(spec.get("template")),
        )


@dataclass
class Deployment:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    replicas: int = 1
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    status_updated_replicas: int = 0

    kind = "Deployment"

    @classmethod
    def from_dict(cls, d: Mapping) -> "Deployment":
        spec = d.get("spec") or {}
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            selector=LabelSelector.from_dict(spec.get("selector")),
            replicas=int(spec.get("replicas", 1)),
            template=PodTemplateSpec.from_dict(spec.get("template")),
        )


@dataclass
class StatefulSet:
    """apps/v1 StatefulSet — ordered, stable-identity replicas
    (pkg/controller/statefulset)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    replicas: int = 1
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    status_replicas: int = 0
    status_ready_replicas: int = 0

    kind = "StatefulSet"

    @classmethod
    def from_dict(cls, d: Mapping) -> "StatefulSet":
        spec = d.get("spec") or {}
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            selector=LabelSelector.from_dict(spec.get("selector")),
            replicas=int(spec.get("replicas", 1)),
            template=PodTemplateSpec.from_dict(spec.get("template")),
        )


@dataclass
class DaemonSet:
    """apps/v1 DaemonSet — one pod per (eligible) node (pkg/controller/daemon)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Optional[LabelSelector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    status_desired: int = 0
    status_current: int = 0

    kind = "DaemonSet"

    @classmethod
    def from_dict(cls, d: Mapping) -> "DaemonSet":
        spec = d.get("spec") or {}
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            selector=LabelSelector.from_dict(spec.get("selector")),
            template=PodTemplateSpec.from_dict(spec.get("template")),
        )


@dataclass
class Job:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    completions: int = 1
    parallelism: int = 1
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    status_succeeded: int = 0
    status_active: int = 0
    completed: bool = False
    # batch/v1 JobSpec.ttlSecondsAfterFinished + JobStatus.completionTime
    # (consumed by the TTL-after-finished controller)
    ttl_seconds_after_finished: Optional[int] = None
    completion_time: Optional[float] = None

    kind = "Job"

    @classmethod
    def from_dict(cls, d: Mapping) -> "Job":
        spec = d.get("spec") or {}
        ttl = spec.get("ttlSecondsAfterFinished")
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            completions=int(spec.get("completions", 1)),
            parallelism=int(spec.get("parallelism", 1)),
            template=PodTemplateSpec.from_dict(spec.get("template")),
            ttl_seconds_after_finished=(None if ttl is None else int(ttl)),
        )


@dataclass
class Namespace:
    """core/v1 Namespace (reference: pkg/apis/core/types.go Namespace;
    deletion semantics in pkg/controller/namespace)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    finalizers: List[str] = field(default_factory=lambda: ["kubernetes"])
    status_phase: str = "Active"  # Active | Terminating

    kind = "Namespace"

    @classmethod
    def from_dict(cls, d: Mapping) -> "Namespace":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            finalizers=[str(f) for f in (spec.get("finalizers")
                                         or ["kubernetes"])],
            status_phase=str(status.get("phase", "Active")),
        )


@dataclass
class ResourceQuota:
    """core/v1 ResourceQuota: spec.hard limits; status mirrors hard + observed
    used (reference: pkg/apis/core/types.go ResourceQuota; controller at
    pkg/controller/resourcequota)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    hard: Dict[str, str] = field(default_factory=dict)
    status_hard: Dict[str, str] = field(default_factory=dict)
    status_used: Dict[str, str] = field(default_factory=dict)

    kind = "ResourceQuota"

    @classmethod
    def from_dict(cls, d: Mapping) -> "ResourceQuota":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            hard={k: str(v) for k, v in (spec.get("hard") or {}).items()},
            status_hard={k: str(v)
                         for k, v in (status.get("hard") or {}).items()},
            status_used={k: str(v)
                         for k, v in (status.get("used") or {}).items()},
        )


@dataclass
class EndpointAddress:
    ip: str = ""
    node_name: str = ""
    target_name: str = ""  # backing pod's name (targetRef)

    @classmethod
    def from_dict(cls, d: Mapping) -> "EndpointAddress":
        ref = d.get("targetRef") or {}
        return cls(
            ip=str(d.get("ip", "")),
            node_name=str(d.get("nodeName", "")),
            target_name=str(ref.get("name", "")),
        )


@dataclass
class EndpointSubset:
    addresses: List[EndpointAddress] = field(default_factory=list)
    not_ready_addresses: List[EndpointAddress] = field(default_factory=list)
    ports: List[int] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Mapping) -> "EndpointSubset":
        return cls(
            addresses=[EndpointAddress.from_dict(a)
                       for a in d.get("addresses") or []],
            not_ready_addresses=[EndpointAddress.from_dict(a)
                                 for a in d.get("notReadyAddresses") or []],
            ports=[int(p.get("port", 0)) if isinstance(p, Mapping) else int(p)
                   for p in d.get("ports") or []],
        )


@dataclass
class Endpoints:
    """core/v1 Endpoints (reference: pkg/controller/endpoint)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    subsets: List[EndpointSubset] = field(default_factory=list)

    kind = "Endpoints"

    @classmethod
    def from_dict(cls, d: Mapping) -> "Endpoints":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            subsets=[EndpointSubset.from_dict(s)
                     for s in d.get("subsets") or []],
        )


@dataclass
class Endpoint:
    """discovery/v1 Endpoint (one entry of an EndpointSlice)."""

    addresses: List[str] = field(default_factory=list)
    ready: bool = True
    node_name: str = ""
    target_name: str = ""

    @classmethod
    def from_dict(cls, d: Mapping) -> "Endpoint":
        cond = d.get("conditions") or {}
        ref = d.get("targetRef") or {}
        return cls(
            addresses=[str(a) for a in d.get("addresses") or []],
            ready=bool(cond.get("ready", True)),
            node_name=str(d.get("nodeName", "")),
            target_name=str(ref.get("name", "")),
        )


@dataclass
class EndpointSlice:
    """discovery/v1 EndpointSlice, ≤100 endpoints per slice (reference:
    pkg/controller/endpointslice; maxEndpointsPerSlice default)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    address_type: str = "IPv4"
    endpoints: List[Endpoint] = field(default_factory=list)
    ports: List[int] = field(default_factory=list)

    kind = "EndpointSlice"

    @classmethod
    def from_dict(cls, d: Mapping) -> "EndpointSlice":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            address_type=str(d.get("addressType", "IPv4")),
            endpoints=[Endpoint.from_dict(e)
                       for e in d.get("endpoints") or []],
            ports=[int(p.get("port", 0)) if isinstance(p, Mapping) else int(p)
                   for p in d.get("ports") or []],
        )


@dataclass
class CronJob:
    """batch/v1 CronJob (reference: pkg/apis/batch/types.go CronJobSpec;
    controller at pkg/controller/cronjob)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    schedule: str = "* * * * *"
    suspend: bool = False
    concurrency_policy: str = "Allow"  # Allow | Forbid | Replace
    starting_deadline_seconds: Optional[int] = None
    job_template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    job_completions: int = 1
    job_parallelism: int = 1
    last_schedule_time: Optional[float] = None

    kind = "CronJob"

    @classmethod
    def from_dict(cls, d: Mapping) -> "CronJob":
        spec = d.get("spec") or {}
        jt = (spec.get("jobTemplate") or {}).get("spec") or {}
        sd = spec.get("startingDeadlineSeconds")
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            schedule=str(spec.get("schedule", "* * * * *")),
            suspend=bool(spec.get("suspend", False)),
            concurrency_policy=str(spec.get("concurrencyPolicy", "Allow")),
            starting_deadline_seconds=(None if sd is None else int(sd)),
            job_template=PodTemplateSpec.from_dict(jt.get("template")),
            job_completions=int(jt.get("completions", 1)),
            job_parallelism=int(jt.get("parallelism", 1)),
        )


@dataclass
class ServiceAccount:
    """core/v1 ServiceAccount (reference: pkg/controller/serviceaccount
    ensures 'default' per namespace)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    secrets: List[str] = field(default_factory=list)

    kind = "ServiceAccount"

    @classmethod
    def from_dict(cls, d: Mapping) -> "ServiceAccount":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            secrets=[str(s) for s in d.get("secrets") or []],
        )


def node_is_ready(node: Node) -> bool:
    """Ready unless the Ready condition says "False"/"Unknown".

    A node with NO Ready condition counts ready: hand-built test nodes and
    freshly-registered kubelets haven't reported yet, and treating them as
    dead would mask the whole cluster before the first heartbeat (the
    lifecycle controller only ever writes Unknown for nodes whose LEASE
    went stale)."""
    for c in node.status.conditions:
        if c.get("type") == "Ready":
            return c.get("status") not in ("False", "Unknown")
    return True


def is_pod_terminating(pod: Pod) -> bool:
    return pod.metadata.deletion_timestamp is not None


def is_pod_terminal(pod: Pod) -> bool:
    return pod.status.phase in (POD_SUCCEEDED, POD_FAILED)
