"""Binary wire plane: compact manifest codec + the encode-once payload cache.

Reference: staging/src/k8s.io/apimachinery/pkg/runtime — the protobuf
serializer and its content negotiation (``application/vnd.kubernetes.protobuf``
vs JSON), plus the cacher's pre-encoded-object serving.  This build's analog
is a length-prefixed, field-tagged binary encoding of the SAME manifest dicts
``api/serialize.to_manifest`` produces, so the two codecs are freely
convertible and every consumer keeps one canonical in-memory form:

    scheme.decode(wire_decode(wire_encode(m))) == scheme.decode(m)

for every kind the scheme registers (the bit-compatibility contract
tests/test_wire.py pins, both codecs, both backends).

Wire format v1 (versioned header, little machinery, strict decode):

    doc   := magic(3) version(1) value
    value := tag(1) body
    tags:
      0x00 null    0x01 false   0x02 true
      0x03 int+    uvarint(n)                (LEB128)
      0x04 int-    uvarint(-1-n)
      0x05 float   8-byte big-endian IEEE-754
      0x06 str     uvarint(len) utf8   — defines the next per-doc table slot
      0x07 strref  uvarint(index into the per-doc string table)
      0x08 strwk   uvarint(index into WELL_KNOWN — the static field-tag table)
      0x09 list    uvarint(count) value*
      0x0a map     uvarint(count) (value value)*   — keys must be str-tagged
      0x0b bytes   uvarint(len) raw    — nested pre-encoded blobs (WAL records)

String interning is two-level: WELL_KNOWN is the frozen field-tag vocabulary
(manifest keys + ubiquitous values — one byte-ish per occurrence); everything
else interns per document (first occurrence inline, repeats as back-refs).
The encoder's well-known lookup rides the existing ``native/`` interner when
the toolchain is present and falls back to a plain dict (KTPU_NO_NATIVE) —
both backends emit byte-identical documents, so either side of a connection
may be running either backend.

Integers are bounded to 64-bit magnitude in v1 (a manifest carrying more is
a WireError); decode is STRICT — truncated or trailing bytes, bad tags, and
overrunning lengths all raise WireError, which is what lets WAL replay and
the replication LogShipper treat an undecodable record as a torn tail.

The fast path: ``native/wire_codec.cpp`` (a CPython extension compiled on
first use, like the other native kernels) implements the same format
object↔bytes for Pod/Node — skipping the reflective ``to_manifest`` /
``from_dict`` walks entirely — plus a C manifest↔bytes codec for every other
kind.  Pure Python remains the reference: byte parity is pinned in tests.
"""

from __future__ import annotations

import json
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..component_base import logging as klog

WIRE_MAGIC = b"\xd7KW"
WIRE_VERSION = 1
WIRE_HEADER = WIRE_MAGIC + bytes([WIRE_VERSION])
WIRE_CONTENT_TYPE = "application/vnd.ktpu.wire"
JSON_CONTENT_TYPE = "application/json"

T_NULL, T_FALSE, T_TRUE = 0x00, 0x01, 0x02
T_INT, T_NINT, T_FLOAT = 0x03, 0x04, 0x05
T_STR, T_STRREF, T_STRWK = 0x06, 0x07, 0x08
T_LIST, T_MAP, T_BYTES = 0x09, 0x0A, 0x0B

_F64 = struct.Struct(">d")

# The static field-tag vocabulary: part of the v1 FORMAT (documents persist
# in WALs and ship to followers), so this tuple is append-only — reordering
# or removing entries is a wire-format break and requires a version bump.
WELL_KNOWN: Tuple[str, ...] = (
    # envelope + metadata
    "kind", "apiVersion", "metadata", "name", "namespace", "uid", "labels",
    "annotations", "resourceVersion", "creationTimestamp",
    "deletionTimestamp", "ownerReferences", "controller", "spec", "status",
    "items", "continue", "type", "object",
    # pod spec/status
    "nodeName", "nodeSelector", "schedulerName", "priority",
    "priorityClassName", "preemptionPolicy", "containers", "initContainers",
    "image", "resources", "requests", "limits", "ports", "containerPort",
    "hostPort", "hostIP", "protocol", "tolerations", "affinity",
    "topologySpreadConstraints", "overhead", "volumes", "hostNetwork",
    "resourceClaims", "phase", "nominatedNodeName", "conditions", "podIP",
    # node
    "capacity", "allocatable", "images", "names", "sizeBytes",
    "volumesAttached", "unschedulable", "taints", "podCIDR", "timeAdded",
    # selectors / affinity
    "key", "operator", "values", "value", "effect", "matchLabels",
    "matchExpressions", "matchFields", "nodeSelectorTerms", "weight",
    "preference", "requiredDuringSchedulingIgnoredDuringExecution",
    "preferredDuringSchedulingIgnoredDuringExecution", "topologyKey",
    "labelSelector", "maxSkew", "whenUnsatisfiable",
    # workloads / policy / storage / misc kinds
    "minAvailable", "maxUnavailable", "selector", "replicas", "template",
    "completions", "parallelism", "schedule", "suspend",
    "concurrencyPolicy", "jobTemplate", "ttlSecondsAfterFinished",
    "startingDeadlineSeconds", "succeeded", "active", "finalizers", "hard",
    "used", "subsets", "addresses", "notReadyAddresses", "targetRef",
    "addressType", "endpoints", "ready", "secrets", "minMember",
    "scheduleTimeoutSeconds", "globalDefault", "persistentVolumeClaim",
    "claimName", "storageClassName", "accessModes", "volumeName",
    "provisioner", "volumeBindingMode", "allowedTopologies",
    "matchLabelExpressions", "drivers", "count", "nodeAffinity", "claimRef",
    "required", "deviceClassName", "devices", "pool", "driver",
    "attributes", "state", "allocation", "reservedFor", "minSize",
    "maxSize", "costPerNode", "sliceSize", "minReplicas", "maxReplicas",
    "scaleTargetRef", "metrics", "resource", "target",
    "averageUtilization", "disruptionsAllowed", "currentHealthy",
    "desiredHealthy", "expectedPods",
    # WAL record envelope
    "op", "ns", "rv", "obj", "objw", "node",
    # ubiquitous values
    "v1", "Pod", "Node", "default", "default-scheduler", "Pending",
    "Running", "Succeeded", "Failed", "PreemptLowerPriority", "Never",
    "TCP", "ADDED", "MODIFIED", "DELETED", "BOOKMARK", "ERROR", "cpu",
    "memory", "pods", "google.com/tpu", "In", "NotIn", "Exists",
    "DoesNotExist", "NoSchedule", "PreferNoSchedule", "NoExecute",
    "ScheduleAnyway", "DoNotSchedule", "create", "update", "delete",
    "bind", "kubernetes.io/hostname",
)

_U64_MAX = (1 << 64) - 1


class WireError(ValueError):
    """Malformed/truncated wire document, or a value v1 cannot carry."""


# --- well-known lookup: native interner with a dict fallback -----------------


class _WellKnownTable:
    """str → WELL_KNOWN index (or -1).  Backed by the native C++ interner
    when available — the table strings are interned in order into a fresh
    handle, so the interner's ids ARE the wire indices — with a plain-dict
    fallback that answers identically (the parity oracle)."""

    def __init__(self):
        self._dict = {s: i for i, s in enumerate(WELL_KNOWN)}
        self._native = None
        try:
            from ..native import NativeInterner, load_interner

            lib = load_interner()
            if lib is not None:
                interner = NativeInterner(lib)
                for s in WELL_KNOWN:
                    interner.intern(s)
                self._native = interner
        # ktpu-analysis: ignore[exception-hygiene] -- capability probe: a broken/absent native toolchain is a supported configuration; the dict fallback below is the parity oracle and answers identically
        except Exception:
            self._native = None

    def index(self, s: str) -> int:
        native = self._native
        if native is not None:
            try:
                return native.lookup(s)
            except UnicodeEncodeError:
                return -1  # non-UTF-8-encodable key is never well-known
        return self._dict.get(s, -1)


_wk_table: Optional[_WellKnownTable] = None


def _well_known() -> _WellKnownTable:
    global _wk_table
    if _wk_table is None:
        _wk_table = _WellKnownTable()
    return _wk_table


# --- varints -----------------------------------------------------------------


def _uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_uvarint(data: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    n = 0
    while True:
        if pos >= len(data):
            raise WireError("truncated varint")
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7
        if shift > 63:
            raise WireError("varint exceeds 64 bits")


# --- pure-Python reference codec ---------------------------------------------


def _encode_value(value: Any, out: List[bytes], table: Dict[str, int],
                  wk: _WellKnownTable) -> None:
    if value is None:
        out.append(b"\x00")
    elif value is True:
        out.append(b"\x02")
    elif value is False:
        out.append(b"\x01")
    elif isinstance(value, str):
        idx = wk.index(value)
        if idx >= 0:
            out.append(bytes([T_STRWK]) + _uvarint(idx))
            return
        ref = table.get(value)
        if ref is not None:
            out.append(bytes([T_STRREF]) + _uvarint(ref))
            return
        raw = value.encode("utf-8")
        table[value] = len(table)
        out.append(bytes([T_STR]) + _uvarint(len(raw)) + raw)
    elif isinstance(value, bool):  # pragma: no cover - caught above
        out.append(b"\x02" if value else b"\x01")
    elif isinstance(value, int):
        if value >= 0:
            if value > _U64_MAX:
                raise WireError(f"int {value} exceeds wire v1's 64-bit range")
            out.append(bytes([T_INT]) + _uvarint(value))
        else:
            mag = -1 - value
            if mag > _U64_MAX:
                raise WireError(f"int {value} exceeds wire v1's 64-bit range")
            out.append(bytes([T_NINT]) + _uvarint(mag))
    elif isinstance(value, float):
        out.append(bytes([T_FLOAT]) + _F64.pack(value))
    elif isinstance(value, (bytes, bytearray)):
        out.append(bytes([T_BYTES]) + _uvarint(len(value)) + bytes(value))
    elif isinstance(value, (list, tuple)):
        out.append(bytes([T_LIST]) + _uvarint(len(value)))
        for item in value:
            _encode_value(item, out, table, wk)
    elif isinstance(value, dict):
        out.append(bytes([T_MAP]) + _uvarint(len(value)))
        for k, v in value.items():
            if not isinstance(k, str):
                raise WireError(
                    f"map keys must be strings, got {type(k).__name__}")
            _encode_value(k, out, table, wk)
            _encode_value(v, out, table, wk)
    else:
        raise WireError(f"unencodable type {type(value).__name__}")


def _decode_value(data: bytes, pos: int, table: List[str]) -> Tuple[Any, int]:
    if pos >= len(data):
        raise WireError("truncated document")
    tag = data[pos]
    pos += 1
    if tag == T_NULL:
        return None, pos
    if tag == T_FALSE:
        return False, pos
    if tag == T_TRUE:
        return True, pos
    if tag == T_INT:
        return _read_uvarint(data, pos)
    if tag == T_NINT:
        mag, pos = _read_uvarint(data, pos)
        return -1 - mag, pos
    if tag == T_FLOAT:
        if pos + 8 > len(data):
            raise WireError("truncated float")
        return _F64.unpack_from(data, pos)[0], pos + 8
    if tag == T_STR:
        length, pos = _read_uvarint(data, pos)
        end = pos + length
        if end > len(data):
            raise WireError("truncated string")
        try:
            s = data[pos:end].decode("utf-8")
        except UnicodeDecodeError as e:
            raise WireError(f"invalid utf-8 in string: {e}")
        table.append(s)
        return s, end
    if tag == T_STRREF:
        ref, pos = _read_uvarint(data, pos)
        if ref >= len(table):
            raise WireError(f"string back-ref {ref} out of range")
        return table[ref], pos
    if tag == T_STRWK:
        idx, pos = _read_uvarint(data, pos)
        if idx >= len(WELL_KNOWN):
            raise WireError(f"well-known index {idx} out of range")
        return WELL_KNOWN[idx], pos
    if tag == T_BYTES:
        length, pos = _read_uvarint(data, pos)
        end = pos + length
        if end > len(data):
            raise WireError("truncated bytes")
        return data[pos:end], end
    if tag == T_LIST:
        count, pos = _read_uvarint(data, pos)
        out: List[Any] = []
        for _ in range(count):
            item, pos = _decode_value(data, pos, table)
            out.append(item)
        return out, pos
    if tag == T_MAP:
        count, pos = _read_uvarint(data, pos)
        d: Dict[str, Any] = {}
        for _ in range(count):
            k, pos = _decode_value(data, pos, table)
            if not isinstance(k, str):
                raise WireError("map key is not a string")
            v, pos = _decode_value(data, pos, table)
            d[k] = v
        return d, pos
    raise WireError(f"unknown tag 0x{tag:02x}")


def _py_encode(value: Any) -> bytes:
    out: List[bytes] = [WIRE_HEADER]
    _encode_value(value, out, {}, _well_known())
    return b"".join(out)


def _py_decode(data: bytes) -> Any:
    if len(data) < 4 or data[:3] != WIRE_MAGIC:
        raise WireError("not a wire document (bad magic)")
    if data[3] != WIRE_VERSION:
        raise WireError(f"unsupported wire version {data[3]}")
    value, pos = _decode_value(data, 4, [])
    if pos != len(data):
        raise WireError(f"{len(data) - pos} trailing bytes after document")
    return value


# --- native fast path --------------------------------------------------------

# one-shot cell guarded by _native_lock; a dict (mutated, never rebound)
# so any thread — main or a watch stream — may trigger the first load.
# The hot path reads "tried" un-locked: dict reads are atomic, and the
# worst case is two threads racing into the locked re-check.
_native_state: Dict[str, Any] = {"tried": False, "mod": None}
_native_lock = threading.RLock()  # RLock: setup may re-enter via imports


def _native():
    """The compiled wire codec module, configured, or None (pure Python).
    One attempt per process; configuration hands the extension the frozen
    well-known table plus the object-plan hooks for Pod/Node."""
    if _native_state["tried"]:
        return _native_state["mod"]
    with _native_lock:
        if _native_state["tried"]:
            return _native_state["mod"]
        _native_state["tried"] = True
        try:
            from ..native import load_wire_codec

            mod = load_wire_codec()
            if mod is not None:
                from . import objects as v1

                mod.setup(list(WELL_KNOWN), _fast_path_refs(v1))
                _native_state["mod"] = mod
        # broad catch is deliberate: no toolchain / failed compile is a
        # supported configuration (KTPU_NO_NATIVE parity runs force it);
        # the pure-Python codec serves every call identically
        except Exception as e:
            klog.V(1).info_s("native wire codec unavailable",
                             err=f"{type(e).__name__}: {e}")
        return _native_state["mod"]


def _fast_path_refs(v1) -> dict:
    """Class and helper references the C object fast paths build with."""
    import time

    from .objects import _new_uid

    return {
        "Pod": v1.Pod, "ObjectMeta": v1.ObjectMeta, "PodSpec": v1.PodSpec,
        "PodStatus": v1.PodStatus, "Container": v1.Container,
        "ResourceRequirements": v1.ResourceRequirements,
        "ContainerPort": v1.ContainerPort,
        "Node": v1.Node, "NodeSpec": v1.NodeSpec,
        "NodeStatus": v1.NodeStatus, "Taint": v1.Taint,
        "ContainerImage": v1.ContainerImage,
        "new_uid": _new_uid, "now": time.time,
        "WireError": WireError,
    }


def _scheme_serves_fast(scheme) -> bool:
    """The object fast paths hard-code ``apiVersion: v1`` for Pod/Node, so
    they only apply when the scheme serves both at the default ("", "v1")
    registration (every real control plane here does).  Result memoized on
    the scheme instance — gv_of takes the registry lock."""
    if scheme is None:
        return False
    ok = getattr(scheme, "_wire_fast_ok", None)
    if ok is None:
        from . import objects as v1

        ok = (scheme.gv_of(v1.Pod) == ("", "v1")
              and scheme.gv_of(v1.Node) == ("", "v1"))
        try:
            scheme._wire_fast_ok = ok
        except (AttributeError, TypeError):
            pass  # slotted/frozen scheme stand-in: re-derive per call
    return ok


def wire_encode(value: Any, *, force_python: bool = False) -> bytes:
    """Encode a manifest (any JSON-shaped value) to a wire v1 document."""
    if not force_python:
        mod = _native()
        if mod is not None:
            try:
                return mod.encode_value(value)
            except (OverflowError, TypeError, ValueError) as e:
                # v1 range/type errors surface uniformly as WireError; the
                # Python encoder below re-derives the precise message
                if not isinstance(e, WireError):
                    return _py_encode(value)
                raise
    return _py_encode(value)


def wire_decode(data: bytes, *, force_python: bool = False) -> Any:
    """Strictly decode a wire v1 document back to its manifest value."""
    if not force_python:
        mod = _native()
        if mod is not None:
            return mod.decode_value(data)
    return _py_decode(data)


def is_wire(data: bytes) -> bool:
    """True when ``data`` leads with the wire magic (vs JSON's ``{``)."""
    return data[:3] == WIRE_MAGIC


# --- object-level codec ------------------------------------------------------


def encode_object(obj, scheme, *, force_python: bool = False) -> bytes:
    """Object → wire document.  Pod/Node take the native direct walk
    (no intermediate manifest dict); every other kind — and any pod/node
    shape outside the fast subset — encodes its ``to_manifest`` form.
    The bytes are identical either way (tests pin it)."""
    if not force_python:
        mod = _native()
        if mod is not None and _scheme_serves_fast(scheme):
            kind = getattr(obj, "kind", None)
            try:
                if kind == "Pod":
                    fast = mod.encode_pod(obj)
                    if fast is not None:
                        return fast
                elif kind == "Node":
                    fast = mod.encode_node(obj)
                    if fast is not None:
                        return fast
            except (AttributeError, OverflowError, TypeError, ValueError):
                pass  # fall through to the reference path
    from .serialize import to_manifest

    return wire_encode(to_manifest(obj, scheme), force_python=force_python)


def decode_object(data: bytes, scheme, *, force_python: bool = False):
    """Wire document → typed object, equal to ``scheme.decode`` of the
    decoded manifest (the parity tests pin equality).  Pod/Node documents
    inside the fast subset are built directly by the native plan walk."""
    if not force_python:
        mod = _native()
        if mod is not None and _scheme_serves_fast(scheme):
            obj = mod.decode_object(data)
            if obj is not None:
                return obj
    return scheme.decode(wire_decode(data, force_python=force_python))


# --- content negotiation -----------------------------------------------------


def negotiate_codec(accept: Optional[str]) -> str:
    """Per-client codec from an Accept header: ``"wire"`` when the binary
    media type is offered, else ``"json"`` (the default every pre-existing
    client keeps).  Mirrors the reference's protobuf negotiation: the
    client opts in, the server never forces it."""
    if accept and WIRE_CONTENT_TYPE in accept:
        return "wire"
    return "json"


def content_type_for(codec: str) -> str:
    return WIRE_CONTENT_TYPE if codec == "wire" else JSON_CONTENT_TYPE


def codec_of_content_type(content_type: Optional[str]) -> str:
    if content_type and WIRE_CONTENT_TYPE in content_type:
        return "wire"
    return "json"


# --- the encode-once payload cache -------------------------------------------


class EncodedPayload:
    """One object version's encoded forms, materialized lazily per codec.

    The watch cache creates one per event (sim/watchcache.py stamps it on
    the WatchEvent); every serving plane — HTTP watch fan-out, LIST pages,
    WAL shipping — asks for bytes instead of re-serializing, so a thousand
    watchers cost ONE encode per codec, not a thousand.

    Snapshot semantics: whichever form is captured at construction (wire
    bytes from the native object walk, or the manifest dict) is immutable
    from that instant — later in-place mutation of the source object can
    never leak into what watchers are served.

    Thread model: built under the watch-cache lock; lazy materialization
    may race across serving threads.  That race is benign BY CONSTRUCTION —
    both threads derive identical bytes from the same immutable source and
    either assignment wins — so the slots are left unlocked (a lock here
    would serialize every watcher on the hottest serving path)."""

    __slots__ = ("_manifest", "_json", "_wire", "_scheme")

    def __init__(self, manifest: Optional[dict] = None,
                 wire_bytes: Optional[bytes] = None, scheme=None):
        if manifest is None and wire_bytes is None:
            raise ValueError("EncodedPayload needs a manifest or wire bytes")
        self._manifest = manifest
        self._wire = wire_bytes
        self._json: Optional[bytes] = None
        self._scheme = scheme

    @classmethod
    def from_object(cls, obj, scheme) -> "EncodedPayload":
        """Capture ``obj``'s wire form NOW (the apply-time snapshot): the
        native object walk when it applies — mutation-proof bytes, zero
        manifest dicts on the hot path — else the manifest dict."""
        mod = _native()
        kind = getattr(obj, "kind", None)
        if (mod is not None and kind in ("Pod", "Node")
                and _scheme_serves_fast(scheme)):
            try:
                fast = (mod.encode_pod(obj) if kind == "Pod"
                        else mod.encode_node(obj))
            except (AttributeError, OverflowError, TypeError, ValueError):
                fast = None
            if fast is not None:
                _count_encode("wire", cached=False)
                return cls(wire_bytes=fast, scheme=scheme)
        from .serialize import to_manifest

        return cls(manifest=to_manifest(obj, scheme), scheme=scheme)

    def manifest(self) -> dict:
        m = self._manifest
        if m is None:
            m = self._manifest = wire_decode(self._wire)
        return m

    def wire_bytes(self) -> bytes:
        b = self._wire
        if b is None:
            _count_encode("wire", cached=False)
            b = self._wire = wire_encode(self.manifest())
        else:
            _count_encode("wire", cached=True)
        return b

    def json_bytes(self) -> bytes:
        b = self._json
        if b is None:
            _count_encode("json", cached=False)
            b = self._json = json.dumps(self.manifest()).encode()
        else:
            _count_encode("json", cached=True)
        return b

    def bytes_for(self, codec: str) -> bytes:
        return self.wire_bytes() if codec == "wire" else self.json_bytes()


def _count_encode(codec: str, cached: bool) -> None:
    from ..metrics import scheduler_metrics as m

    m.apiserver_wire_encode.inc((codec, "true" if cached else "false"))


def memo_encode(obj, attr: str, key, build):
    """Per-object encode memo — THE shared memoization mechanism: the value
    ``build()`` returns is cached on ``obj`` under ``attr`` keyed by
    ``key`` (conventionally ``(resourceVersion, ...)`` — the store bumps
    resourceVersion on every update, so store-mediated mutation
    invalidates).  Objects that cannot carry attributes (__slots__/frozen
    stand-ins) are served uncached."""
    cached = getattr(obj, attr, None)
    if cached is not None and cached[0] == key:
        return cached[1]
    value = build()
    try:
        setattr(obj, attr, (key, value))
    except (AttributeError, TypeError):
        pass  # uncacheable stand-in: correctness over memoization
    return value


def payload_for(obj, scheme) -> EncodedPayload:
    """The object's EncodedPayload, memoized on the object keyed by its
    resourceVersion: the watch cache, LIST pages, the extender, and the WAL
    all reach the SAME payload for the same object version, so each codec
    is encoded at most once per write no matter how many planes serve it.
    In-place mutation without a store write (same rv) serves the capture —
    the elided-history caveat client/informer.py already documents."""
    rv = getattr(getattr(obj, "metadata", None), "resource_version", 0)
    return memo_encode(obj, "_wire_payload", rv,
                       lambda: EncodedPayload.from_object(obj, scheme))


# --- watch stream framing ----------------------------------------------------

# frame := uvarint(len(rest)) rest;  rest := type(1) uvarint(rv) wire-doc
# The event's resourceVersion rides the frame header because object decode
# deliberately drops it (from_dict parity: server write paths re-stamp) —
# a binary watcher reads the rv without parsing the document.
FRAME_TYPES = {"ADDED": 1, "MODIFIED": 2, "DELETED": 3,
               "BOOKMARK": 4, "ERROR": 5}
FRAME_NAMES = {v: k for k, v in FRAME_TYPES.items()}


def encode_watch_frame(event_type: str, doc: bytes, rv: int = 0) -> bytes:
    """One binary watch event: the pre-encoded object document is embedded
    VERBATIM (the encode-once contract — framing adds bytes, never
    re-serializes)."""
    code = FRAME_TYPES.get(event_type)
    if code is None:
        raise WireError(f"unknown watch event type {event_type!r}")
    rest = bytes([code]) + _uvarint(rv) + doc
    return _uvarint(len(rest)) + rest


def read_watch_frame(stream) -> Optional[Tuple[str, int, bytes]]:
    """Read one frame from a blocking byte stream: (type, rv, doc bytes),
    or None on clean EOF at a frame boundary.  Torn frames raise
    WireError."""
    length = _read_stream_uvarint(stream)
    if length is None:
        return None
    if length < 2:
        raise WireError("empty watch frame")
    body = _read_exact(stream, length)
    code = body[0]
    name = FRAME_NAMES.get(code)
    if name is None:
        raise WireError(f"unknown watch frame type {code}")
    rv = 0
    shift = 0
    off = 1
    while True:
        if off >= len(body):
            raise WireError("watch frame truncated in rv varint")
        b = body[off]
        off += 1
        rv |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 63:
            raise WireError("watch frame rv varint exceeds 64 bits")
    return name, rv, body[off:]


def _read_stream_uvarint(stream) -> Optional[int]:
    shift = 0
    n = 0
    first = True
    while True:
        b = stream.read(1)
        if not b:
            if first:
                return None  # clean EOF between frames
            raise WireError("stream ended mid-frame-header")
        first = False
        n |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            return n
        shift += 7
        if shift > 63:
            raise WireError("frame length varint exceeds 64 bits")


def _read_exact(stream, n: int) -> bytes:
    chunks = []
    remaining = n
    while remaining:
        chunk = stream.read(remaining)
        if not chunk:
            raise WireError(f"stream ended {remaining} bytes short of frame")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)
