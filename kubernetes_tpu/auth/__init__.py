"""RBAC authorization (rbac.authorization.k8s.io).

Reference: plugin/pkg/auth/authorizer/rbac/rbac.go — the policy object
model (``api.py``: Role/ClusterRole + bindings), the rule evaluator
(``rbac.py``), and the bootstrap policy granting the built-in components
exactly their verbs (``bootstrap.py``, the bootstrappolicy analog).
"""

from .api import (  # noqa: F401
    ClusterRole,
    ClusterRoleBinding,
    PolicyRule,
    Role,
    RoleBinding,
    RoleRef,
    Subject,
)
from .bootstrap import bootstrap_objects, install_bootstrap_policy  # noqa: F401
from .rbac import RBACAuthorizer  # noqa: F401
