"""rbac.authorization.k8s.io/v1 object model.

Reference: staging/src/k8s.io/api/rbac/v1/types.go — PolicyRule (verbs ×
apiGroups × resources × resourceNames, ``*`` wildcards), Role/ClusterRole
as rule bags, and the bindings attaching subjects to them.  Roles and
RoleBindings are namespaced; their Cluster* counterparts are
cluster-scoped (sim/store.py CLUSTER_SCOPED carries them).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Mapping

from ..api.objects import ObjectMeta

WILDCARD = "*"


@dataclass
class PolicyRule:
    """One grant: the cross product of verbs × apiGroups × resources,
    optionally narrowed to specific object names."""

    verbs: List[str] = field(default_factory=list)
    api_groups: List[str] = field(default_factory=lambda: [""])
    resources: List[str] = field(default_factory=list)
    resource_names: List[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Mapping) -> "PolicyRule":
        return cls(
            verbs=[str(v) for v in d.get("verbs") or []],
            api_groups=[str(g) for g in d.get("apiGroups") or [""]],
            resources=[str(r) for r in d.get("resources") or []],
            resource_names=[str(n) for n in d.get("resourceNames") or []],
        )

    def matches(self, verb: str, api_group: str, resource: str,
                name: str = "") -> bool:
        """rbac.go RuleAllows: every dimension must admit the request; an
        empty resourceNames list means ALL names (narrowing is opt-in)."""
        if WILDCARD not in self.verbs and verb not in self.verbs:
            return False
        if WILDCARD not in self.api_groups \
                and api_group not in self.api_groups:
            return False
        if WILDCARD not in self.resources and resource not in self.resources:
            return False
        if self.resource_names and WILDCARD not in self.resource_names \
                and name not in self.resource_names:
            return False
        return True


def _rules_from(d: Mapping) -> List[PolicyRule]:
    return [PolicyRule.from_dict(r) for r in d.get("rules") or []]


@dataclass
class Role:
    """Namespaced rule bag: grants apply only inside metadata.namespace."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    rules: List[PolicyRule] = field(default_factory=list)

    kind = "Role"

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @classmethod
    def from_dict(cls, d: Mapping) -> "Role":
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   rules=_rules_from(d))


@dataclass
class ClusterRole:
    """Cluster-scoped rule bag: bindable in any namespace or cluster-wide."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    rules: List[PolicyRule] = field(default_factory=list)

    kind = "ClusterRole"

    @property
    def name(self) -> str:
        return self.metadata.name

    def key(self) -> str:
        return self.metadata.name

    @classmethod
    def from_dict(cls, d: Mapping) -> "ClusterRole":
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   rules=_rules_from(d))


@dataclass
class Subject:
    """Who a binding grants to: a User or a Group (ServiceAccounts reduce
    to their ``system:serviceaccount:...`` user names here)."""

    kind: str = "User"
    name: str = ""

    @classmethod
    def from_dict(cls, d: Mapping) -> "Subject":
        return cls(kind=d.get("kind", "User"), name=d.get("name", ""))


@dataclass
class RoleRef:
    kind: str = "ClusterRole"  # "Role" | "ClusterRole"
    name: str = ""

    @classmethod
    def from_dict(cls, d: Mapping) -> "RoleRef":
        return cls(kind=d.get("kind", "ClusterRole"),
                   name=d.get("name", ""))


def _subjects_from(d: Mapping) -> List[Subject]:
    return [Subject.from_dict(s) for s in d.get("subjects") or []]


@dataclass
class RoleBinding:
    """Namespaced grant: subjects get the referenced Role's (or
    ClusterRole's) rules INSIDE metadata.namespace only."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    subjects: List[Subject] = field(default_factory=list)
    role_ref: RoleRef = field(default_factory=RoleRef)

    kind = "RoleBinding"

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    @classmethod
    def from_dict(cls, d: Mapping) -> "RoleBinding":
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   subjects=_subjects_from(d),
                   role_ref=RoleRef.from_dict(d.get("roleRef") or {}))


@dataclass
class ClusterRoleBinding:
    """Cluster-wide grant: subjects get the ClusterRole's rules in every
    namespace and for cluster-scoped resources."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    subjects: List[Subject] = field(default_factory=list)
    role_ref: RoleRef = field(default_factory=RoleRef)

    kind = "ClusterRoleBinding"

    @property
    def name(self) -> str:
        return self.metadata.name

    def key(self) -> str:
        return self.metadata.name

    @classmethod
    def from_dict(cls, d: Mapping) -> "ClusterRoleBinding":
        return cls(metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
                   subjects=_subjects_from(d),
                   role_ref=RoleRef.from_dict(d.get("roleRef") or {}))
