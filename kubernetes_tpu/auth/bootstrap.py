"""Bootstrap RBAC policy: built-in components get exactly their verbs.

Reference: plugin/pkg/auth/authorizer/rbac/bootstrappolicy/policy.go — the
cluster ships with ClusterRoles for each control-plane component and the
bindings attaching the component identities to them, so turning
authorization on does not lock the cluster out of itself.  The grants are
least-privilege by construction: each role lists only the (verb, resource)
pairs the component's reconcile loops actually issue, so the RBAC battery
can assert both directions — every built-in passes, and anything outside
its envelope is denied like any other user.

Identities match what the components send on the wire: HTTPApiClient
stamps its ``user`` as the request identity, so a scheduler built with
``user="system:kube-scheduler"`` authenticates as exactly the subject
bound here.  ``cluster-admin`` (wildcard everything) is bound to the
``system:masters`` group — the break-glass identity tests and operators
use, mirroring the reference bootstrap.
"""

from __future__ import annotations

from typing import List, Tuple

from ..api.objects import ObjectMeta
from .api import ClusterRole, ClusterRoleBinding, PolicyRule, RoleRef, Subject

# component identities (defined here; nothing else in the tree hardcodes
# them, so tests and main() wiring import these names)
USER_SCHEDULER = "system:kube-scheduler"
USER_CONTROLLER_MANAGER = "system:kube-controller-manager"
USER_DESCHEDULER = "system:descheduler"
USER_AUTOSCALER = "system:autoscaler"
GROUP_MASTERS = "system:masters"

_RW = ["get", "list", "watch", "create", "update", "patch", "delete"]
_RO = ["get", "list", "watch"]


def _rule(resources: List[str], verbs: List[str],
          api_groups: Tuple[str, ...] = ("",)) -> PolicyRule:
    return PolicyRule(verbs=list(verbs), api_groups=list(api_groups),
                      resources=list(resources))


def _role(name: str, rules: List[PolicyRule]) -> ClusterRole:
    return ClusterRole(metadata=ObjectMeta(name=name), rules=rules)


def _bind(name: str, role: str, subject: Subject) -> ClusterRoleBinding:
    return ClusterRoleBinding(
        metadata=ObjectMeta(name=name),
        subjects=[subject],
        role_ref=RoleRef(kind="ClusterRole", name=role))


def bootstrap_objects() -> List[object]:
    """The bootstrap ClusterRoles + ClusterRoleBindings, in install order.

    Verb envelopes trace to the components' actual request patterns:
    the scheduler binds pods (POST pods/{name}/binding authorizes as
    ``create pods``) and CASes pod/claim/podgroup status; the
    descheduler evicts (POST pods/{name}/eviction authorizes as
    ``delete pods``); the autoscaler creates and deletes nodes and
    updates its nodegroups; the controller-manager owns the workload
    expansion loops (replicasets/trainingjobs → pods + claims).
    """
    objs: List[object] = [
        _role("system:kube-scheduler", [
            _rule(["pods"], _RO + ["create", "update", "patch"]),
            _rule(["nodes", "podgroups", "priorityclasses",
                   "storageclasses", "csinodes", "persistentvolumes",
                   "persistentvolumeclaims", "poddisruptionbudgets"], _RO),
            _rule(["podgroups"], ["update", "patch"]),
            _rule(["resourceclaims", "resourceslices", "deviceclasses"],
                  _RO),
            _rule(["resourceclaims"], ["update", "patch"]),
            _rule(["leases"], _RW),
        ]),
        _role("system:kube-controller-manager", [
            _rule(["pods", "resourceclaims", "resourceclaimtemplates",
                   "podgroups"], _RW),
            _rule(["replicasets", "trainingjobs", "horizontalpodautoscalers"],
                  _RO + ["update", "patch"], api_groups=("*",)),
            _rule(["nodes", "namespaces", "deviceclasses",
                   "resourceslices"], _RO),
            _rule(["leases"], _RW),
        ]),
        _role("system:descheduler", [
            # eviction authorizes as delete on pods (the subresource gate)
            _rule(["pods"], _RO + ["delete"]),
            _rule(["nodes", "podgroups", "poddisruptionbudgets"], _RO),
            _rule(["leases"], _RW),
        ]),
        _role("system:autoscaler", [
            _rule(["nodes"], _RO + ["create", "delete"]),
            _rule(["nodegroups"], _RO + ["update", "patch"],
                  api_groups=("*",)),
            _rule(["pods", "podgroups"], _RO),
            _rule(["leases"], _RW),
        ]),
        _role("cluster-admin", [
            _rule(["*"], ["*"], api_groups=("*",)),
        ]),
        _bind("system:kube-scheduler", "system:kube-scheduler",
              Subject(kind="User", name=USER_SCHEDULER)),
        _bind("system:kube-controller-manager",
              "system:kube-controller-manager",
              Subject(kind="User", name=USER_CONTROLLER_MANAGER)),
        _bind("system:descheduler", "system:descheduler",
              Subject(kind="User", name=USER_DESCHEDULER)),
        _bind("system:autoscaler", "system:autoscaler",
              Subject(kind="User", name=USER_AUTOSCALER)),
        _bind("cluster-admin", "cluster-admin",
              Subject(kind="Group", name=GROUP_MASTERS)),
    ]
    return objs


def install_bootstrap_policy(store) -> int:
    """Create the bootstrap objects in ``store``; objects already present
    are left untouched (idempotent — safe on every boot, including a boot
    whose WAL replay already restored them).  Returns how many were
    created this call."""
    created = 0
    for obj in bootstrap_objects():
        try:
            store.create(obj.kind, obj)
            created += 1
        except ValueError:
            pass  # already bootstrapped (or operator-modified: keep theirs)
    return created
