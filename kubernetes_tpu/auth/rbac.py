"""RBAC evaluator: the apiserver's authorization decision point.

Reference: plugin/pkg/auth/authorizer/rbac/rbac.go — RBACAuthorizer walks
ClusterRoleBindings (cluster-wide grants) then the request namespace's
RoleBindings (namespaced grants), resolves each binding's role, and allows
on the first rule admitting (verb, apiGroup, resource, resourceName).
Deny is the default: no binding → no access.

The evaluator is a plain callable compatible with the apiserver's
authorizer protocol — positionally ``(user, verb, resource, namespace)``,
with the richer attributes (``name``, ``api_group``, ``groups``) passed by
keyword when the server detects support (signature probing, the same idiom
the informer uses for optional kwargs).  Policy objects live in the
ObjectStore like everything else, so policy edits are watchable, durable,
and take effect on the next request with no reload step.
"""

from __future__ import annotations

from typing import Iterable, List

from ..metrics import scheduler_metrics as m
from .api import ClusterRoleBinding, RoleBinding

# every authenticated request carries this implicit group (the reference
# authn layer stamps it; here the evaluator supplies it so group-shaped
# grants like discovery roles work without authn-layer coupling)
GROUP_AUTHENTICATED = "system:authenticated"


class RBACAuthorizer:
    """Policy-backed authorizer over an ObjectStore."""

    def __init__(self, store):
        self.store = store

    # the callable protocol the apiserver invokes
    def __call__(self, user: str, verb: str, resource: str, namespace: str,
                 *, name: str = "", api_group: str = "",
                 groups: Iterable[str] = ()) -> bool:
        allowed = self.authorize(user, verb, resource, namespace, name=name,
                                 api_group=api_group, groups=groups)
        m.rbac_decisions.inc(("allow" if allowed else "deny",))
        return allowed

    def authorize(self, user: str, verb: str, resource: str, namespace: str,
                  *, name: str = "", api_group: str = "",
                  groups: Iterable[str] = ()) -> bool:
        member_of = tuple(groups) + (GROUP_AUTHENTICATED,)

        def subject_match(binding) -> bool:
            for s in binding.subjects:
                if s.kind == "User" and s.name == user:
                    return True
                if s.kind == "Group" and s.name in member_of:
                    return True
            return False

        # cluster-wide grants apply to every namespace AND cluster-scoped
        # resources (namespace "")
        crbs: List[ClusterRoleBinding]
        crbs, _ = self.store.list("ClusterRoleBinding")
        for crb in crbs:
            if not subject_match(crb):
                continue
            if self._role_allows(crb.role_ref, "", verb, api_group,
                                 resource, name):
                return True
        if namespace:
            rbs: List[RoleBinding]
            rbs, _ = self.store.list("RoleBinding")
            for rb in rbs:
                if rb.metadata.namespace != namespace:
                    continue
                if not subject_match(rb):
                    continue
                if self._role_allows(rb.role_ref, namespace, verb,
                                     api_group, resource, name):
                    return True
        return False

    def _role_allows(self, role_ref, namespace: str, verb: str,
                     api_group: str, resource: str, name: str) -> bool:
        if role_ref.kind == "ClusterRole":
            role = self.store.get("ClusterRole", "", role_ref.name)
        elif role_ref.kind == "Role" and namespace:
            # a Role can only be referenced from ITS namespace's bindings
            role = self.store.get("Role", namespace, role_ref.name)
        else:
            role = None
        if role is None:
            return False  # dangling roleRef denies, never errors
        return any(r.matches(verb, api_group, resource, name)
                   for r in role.rules)
