"""Shared utilities (pytree registration, clocks, heaps)."""

from .pytrees import register_pytree_dataclass  # noqa: F401
