"""Shared utilities (pytree registration, clocks, heaps)."""

from .pytrees import register_pytree_dataclass  # noqa: F401


def takes_kwarg(fn, name: str) -> bool:
    """Signature-probe: does ``fn`` accept keyword ``name``?  The shared
    idiom behind optional-kwarg handoffs across pluggable boundaries
    (store facades' ``bind_pod(trace_parent=)``, informer callbacks) —
    probe once and cache at the call site, never per call.  Unprobeable
    callables (builtins, C extensions) answer False: the caller falls
    back to the plain form."""
    import inspect

    try:
        return name in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False
