"""Register host dataclasses as JAX pytrees.

PodBatch / DeviceSnapshot / compiled-selector batches are dataclasses whose fields
are numpy/jnp arrays; registering them as pytrees lets the whole structure be passed
straight into ``jax.jit`` so the entire filter→score→assign pipeline is ONE traced
program.  Non-array fields (e.g. the host-side ``pods`` list) are dropped at
flatten time and restored as empty defaults — device code never reads them.
"""

from __future__ import annotations

import dataclasses

import jax


def register_pytree_dataclass(cls, skip=(), skip_default=None, static=()):
    """Register dataclass ``cls`` as a pytree; ``skip`` fields are dropped (rebuilt
    as ``skip_default()`` or their type default on unflatten); ``static`` fields
    ride in aux_data — they survive flatten/unflatten and participate in jit
    cache keys (trace-time constants, e.g. a has-numeric-ops flag)."""
    names = [
        f.name for f in dataclasses.fields(cls)
        if f.name not in skip and f.name not in static
    ]
    skip_names = tuple(skip)
    static_names = tuple(static)

    def flatten(obj):
        aux = tuple(getattr(obj, n) for n in static_names)
        return tuple(getattr(obj, n) for n in names), aux

    def unflatten(aux, children):
        kwargs = dict(zip(names, children))
        kwargs.update(zip(static_names, aux))
        for s in skip_names:
            kwargs[s] = skip_default() if skip_default is not None else []
        return cls(**kwargs)

    jax.tree_util.register_pytree_node(cls, flatten, unflatten)
    return cls
