"""XLA compile accounting + persistent compilation cache.

The round-2 profile showed ~90% of north-star bench wall time was XLA
recompilation (77 backend compiles across 12 scheduling cycles), caused by
per-cycle shape drift (dirty-row scatter lengths, pod-tier growth, batch cap
thrash).  The shape fixes live in state/encoding.py and framework/podbatch.py;
this module is the regression guard: a process-wide counter of backend
compiles (count + seconds) that the perf harness samples around each measured
window, so a reintroduced shape leak shows up in bench output as a nonzero
steady-state compile count instead of silently eating wall time.

The reference has no compile phase at all; its analog of "warmup" is Go
runtime JIT-free startup.  Our contract is therefore: O(1) compiles after the
first cycle at a given cluster tier, zero in steady state.
"""

from __future__ import annotations

import os
import threading

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class CompileMonitor:
    """Counts XLA backend compiles via jax.monitoring (thread-safe)."""

    def __init__(self):
        self.count = 0
        self.seconds = 0.0
        from ..analysis import lockcheck

        self._lock = lockcheck.maybe_wrap(
            threading.Lock(), "CompileMonitor._lock")
        self._registered = False

    def _listener(self, event: str, duration: float, **kw):
        if event == _COMPILE_EVENT:
            with self._lock:
                self.count += 1
                self.seconds += duration

    def install(self):
        if self._registered:
            return self
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(self._listener)
        self._registered = True
        return self

    def snapshot(self):
        with self._lock:
            return (self.count, self.seconds)


monitor = CompileMonitor()


def enable_persistent_cache(path: str | None = None):
    """Point JAX's persistent compilation cache at a repo-local dir.

    Idempotent; safe to call before or after first device use.  Makes bench
    reruns (and the driver's repeated invocations) skip cold compiles.
    """
    import jax

    path = path or os.environ.get(
        "KTPU_JAX_CACHE", os.path.join(os.path.dirname(__file__), "..", "..", ".jax_cache")
    )
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    return path
