"""String interning: host strings ↔ int32 ids for device tensor programs.

Every string the device path compares (label keys/values, taint keys/values,
namespaces, node names, image names, topology values, resource names) is interned
once host-side; device programs only see int32 ids. A parallel float32 side-table
holds the numeric value of ids whose string parses as an integer, enabling the
NodeSelector Gt/Lt operators as tensor compares.

Id space: ids start at 0; -1 is the universal "absent / padding" sentinel in all
encoded arrays (never a valid id).
"""

from __future__ import annotations

import math
from typing import Dict, List

import numpy as np

MISSING = -1

# Well-known strings interned at Dictionary construction so their ids are
# compile-time constants usable inside jitted plugin programs.
WELL_KNOWN = (
    "",
    "metadata.name",
    "kubernetes.io/hostname",
    "node.kubernetes.io/unschedulable",
    "topology.kubernetes.io/zone",
    "topology.kubernetes.io/region",
    "0.0.0.0",
)
ID_EMPTY = 0
ID_META_NAME = 1
ID_HOSTNAME = 2
ID_UNSCHEDULABLE_TAINT = 3
ID_ZONE = 4
ID_REGION = 5
ID_WILDCARD_IP = 6  # HostPortInfo DefaultBindAllHostIP (framework/types.go)

_INT_RE = __import__("re").compile(r"^[+-]?[0-9]+$")
_INT64_MAX = 2**63 - 1


def _parse_numeric(s: str) -> float:
    """Numeric side-table semantics = Go strconv.Atoi (the reference parses
    Gt/Lt operands with it, nodeaffinity): ASCII digits with optional sign,
    no underscores/whitespace, int64 range.  Keeps PyDictionary and the C++
    interner (strtoll with the same checks) bit-identical across hosts."""
    if not _INT_RE.match(s):
        return math.nan
    v = int(s)
    if v > _INT64_MAX or v < -_INT64_MAX - 1:
        return math.nan
    return float(v)


class PyDictionary:
    """Append-only string interner. Thread-compatible with the scheduler's single
    event-ingest thread (mirrors the single-writer discipline of the reference's
    scheduler cache, internal/cache/cache.go:62)."""

    def __init__(self):
        self._to_id: Dict[str, int] = {}
        self._to_str: List[str] = []
        self._numeric: List[float] = []
        for s in WELL_KNOWN:
            self.intern(s)

    def __len__(self) -> int:
        return len(self._to_str)

    def intern(self, s: str) -> int:
        i = self._to_id.get(s)
        if i is not None:
            return i
        i = len(self._to_str)
        self._to_id[s] = i
        self._to_str.append(s)
        self._numeric.append(_parse_numeric(s))
        return i

    def lookup(self, s: str) -> int:
        """Id of s, or MISSING if never interned (read-only: does not grow)."""
        return self._to_id.get(s, MISSING)

    def intern_many(self, strings) -> List[int]:
        return [self.intern(s) for s in strings]

    def string(self, i: int) -> str:
        return self._to_str[i]

    def numeric_table(self, min_size: int = 1) -> np.ndarray:
        """float32[num_ids] — numeric value per id (NaN when non-integer)."""
        n = max(len(self._numeric), min_size)
        t = np.full((n,), np.nan, dtype=np.float32)
        if self._numeric:
            t[: len(self._numeric)] = np.asarray(self._numeric, dtype=np.float32)
        return t


class NativeDictionary:
    """Dictionary backed by the C++ interner (native/interner.cpp).

    Same contract as PyDictionary — sequential int32 ids from 0, MISSING on
    failed lookup, integer side-table — but the per-string hot loop runs in
    C++ (SURVEY §2.4: the host feeder's innermost loop).  Constructed only
    when the shared library is available; see the Dictionary() factory.
    """

    def __init__(self, native_interner):
        self._impl = native_interner
        for s in WELL_KNOWN:
            self.intern(s)

    def __len__(self) -> int:
        return len(self._impl)

    def intern(self, s: str) -> int:
        return self._impl.intern(s)

    def intern_many(self, strings) -> List[int]:
        return self._impl.intern_many(strings)

    def lookup(self, s: str) -> int:
        i = self._impl.lookup(s)
        return i if i >= 0 else MISSING

    def string(self, i: int) -> str:
        return self._impl.string(i)

    def numeric_table(self, min_size: int = 1) -> np.ndarray:
        return self._impl.numeric_table(min_size)


def Dictionary(native: "bool | None" = None):
    """Build an interner.  Default is the Python dict: measured on this
    workload, per-call ctypes overhead makes single-string interning ~10x
    slower through the C ABI than a dict hit, and the hot path interns one
    string at a time; the C++ backend only wins on the batched
    ``intern_many`` entry point (1.7x, tests/test_dictionary.py microbench).
    Set KTPU_NATIVE_INTERNER=1 (or native=True, which raises if the build
    fails) to opt in for ingest paths that batch their interning.
    """
    import os

    if native is None:
        native = os.environ.get("KTPU_NATIVE_INTERNER", "0") == "1"
        forced = False
    else:
        forced = native
    if native:
        from ..native import NativeInterner, load_interner

        lib = load_interner()
        if lib is not None:
            return NativeDictionary(NativeInterner(lib))
        if forced:
            raise RuntimeError("native interner requested but g++ build failed")
    return PyDictionary()
