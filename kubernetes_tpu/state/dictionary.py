"""String interning: host strings ↔ int32 ids for device tensor programs.

Every string the device path compares (label keys/values, taint keys/values,
namespaces, node names, image names, topology values, resource names) is interned
once host-side; device programs only see int32 ids. A parallel float32 side-table
holds the numeric value of ids whose string parses as an integer, enabling the
NodeSelector Gt/Lt operators as tensor compares.

Id space: ids start at 0; -1 is the universal "absent / padding" sentinel in all
encoded arrays (never a valid id).
"""

from __future__ import annotations

import math
from typing import Dict, List

import numpy as np

MISSING = -1

# Well-known strings interned at Dictionary construction so their ids are
# compile-time constants usable inside jitted plugin programs.
WELL_KNOWN = (
    "",
    "metadata.name",
    "kubernetes.io/hostname",
    "node.kubernetes.io/unschedulable",
    "topology.kubernetes.io/zone",
    "topology.kubernetes.io/region",
)
ID_EMPTY = 0
ID_META_NAME = 1
ID_HOSTNAME = 2
ID_UNSCHEDULABLE_TAINT = 3
ID_ZONE = 4
ID_REGION = 5


class Dictionary:
    """Append-only string interner. Thread-compatible with the scheduler's single
    event-ingest thread (mirrors the single-writer discipline of the reference's
    scheduler cache, internal/cache/cache.go:62)."""

    def __init__(self):
        self._to_id: Dict[str, int] = {}
        self._to_str: List[str] = []
        self._numeric: List[float] = []
        for s in WELL_KNOWN:
            self.intern(s)

    def __len__(self) -> int:
        return len(self._to_str)

    def intern(self, s: str) -> int:
        i = self._to_id.get(s)
        if i is not None:
            return i
        i = len(self._to_str)
        self._to_id[s] = i
        self._to_str.append(s)
        try:
            self._numeric.append(float(int(s)))
        except ValueError:
            self._numeric.append(math.nan)
        return i

    def lookup(self, s: str) -> int:
        """Id of s, or MISSING if never interned (read-only: does not grow)."""
        return self._to_id.get(s, MISSING)

    def string(self, i: int) -> str:
        return self._to_str[i]

    def numeric_table(self, min_size: int = 1) -> np.ndarray:
        """float32[num_ids] — numeric value per id (NaN when non-integer)."""
        n = max(len(self._numeric), min_size)
        t = np.full((n,), np.nan, dtype=np.float32)
        if self._numeric:
            t[: len(self._numeric)] = np.asarray(self._numeric, dtype=np.float32)
        return t
