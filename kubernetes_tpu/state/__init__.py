"""Cluster-state encoding: dictionary, compiled selectors, NodeInfo, cache, snapshot."""

from .dictionary import MISSING, Dictionary  # noqa: F401
