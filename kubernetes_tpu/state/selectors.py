"""Selector compilation: label/node selectors → int32 tensor programs.

The reference evaluates selectors per (pod, node/pod) pair in Go
(apimachinery labels.Selector; component-helpers nodeaffinity). Here a batch of
selectors is *compiled once* host-side into padded int32 arrays, and evaluation is a
pure jnp function over dictionary-encoded label arrays — vmap/jit-able along both
the selector batch and the node/pod axes, so a whole ``[pods, nodes]`` or
``[terms, pods]`` match matrix is one fused device program.

Encoding (MISSING = -1 is the universal pad):
  requirement ops: IN=0 NOT_IN=1 EXISTS=2 DOES_NOT_EXIST=3 GT=4 LT=5, PAD=-1
  a padded requirement row is the AND-identity (always true)
  a LabelSelector with match_none=True matches nothing (the None selector)
  a NodeSelector with match_all=True matches everything (the nil selector);
  otherwise OR over valid terms, AND over each term's requirements
  matchFields(metadata.name) is handled by interning the node name as a
  pseudo-label under the key "metadata.name" at node-encoding time.

Conservative-capacity note: S (requirements/term), V (values/requirement) and T
(terms) are sized to the max present in the compiled batch, rounded up to powers of
two to bound XLA recompiles; nothing is silently truncated.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..api import objects as v1
from .dictionary import MISSING, Dictionary

OP_IN = 0
OP_NOT_IN = 1
OP_EXISTS = 2
OP_DOES_NOT_EXIST = 3
OP_GT = 4
OP_LT = 5
OP_PAD = -1

_OP_CODE = {
    v1.OP_IN: OP_IN,
    v1.OP_NOT_IN: OP_NOT_IN,
    v1.OP_EXISTS: OP_EXISTS,
    v1.OP_DOES_NOT_EXIST: OP_DOES_NOT_EXIST,
    v1.OP_GT: OP_GT,
    v1.OP_LT: OP_LT,
}


from .units import pow2_round_up as _round_up  # shared shape discipline


@dataclass
class CompiledLabelSelectors:
    """Batch of B compiled metav1.LabelSelectors, deduplicated to U unique rows.

    A scheduling batch's selectors repeat heavily (all pods of one deployment
    share one selector), so evaluation arrays hold only the U unique selectors
    and ``index`` i32[B] maps batch row → unique row.  Matrix evaluators run at
    U then expand — at 5k nodes this turned the dominant prepare cost into
    noise (the reference has no analog: it evaluates per (pod, node) pair in
    Go, labels.Selector.Matches).

    req_key  i32[U, S]; req_op i32[U, S]; req_vals i32[U, S, V]
    req_num  f32[U, S]  — numeric RHS for Gt/Lt (NaN when unparseable)
    match_none bool[U]  — True for the None selector (matches nothing)
    index    i32[B]
    has_numeric — STATIC (pytree aux): any Gt/Lt op present.  Gates the
    numeric path at trace time so the common no-Gt/Lt case compiles without
    the per-element dictionary-table gather (serial on TPU).
    """

    req_key: np.ndarray
    req_op: np.ndarray
    req_vals: np.ndarray
    req_num: np.ndarray
    match_none: np.ndarray
    index: np.ndarray
    has_numeric: bool = False

    def __len__(self):
        return self.index.shape[0]


@dataclass
class CompiledNodeSelectors:
    """Batch of B compiled v1.NodeSelectors (terms OR, requirements AND),
    deduplicated like CompiledLabelSelectors.

    req_key i32[U, T, S]; req_op i32[U, T, S]; req_vals i32[U, T, S, V]
    req_num f32[U, T, S]; term_valid bool[U, T]; match_all bool[U]; index i32[B]
    """

    req_key: np.ndarray
    req_op: np.ndarray
    req_vals: np.ndarray
    req_num: np.ndarray
    term_valid: np.ndarray
    match_all: np.ndarray
    index: np.ndarray
    has_numeric: bool = False

    def __len__(self):
        return self.index.shape[0]


from ..utils.pytrees import register_pytree_dataclass as _reg  # noqa: E402

_reg(CompiledLabelSelectors, static=("has_numeric",))
_reg(CompiledNodeSelectors, static=("has_numeric",))


def _selector_requirements(sel: v1.LabelSelector):
    """Flatten matchLabels + matchExpressions into (key, op, values) triples."""
    reqs = []
    for k, val in sorted(sel.match_labels.items()):
        reqs.append((k, v1.OP_IN, [val]))
    for e in sel.match_expressions:
        reqs.append((e.key, e.operator, list(e.values)))
    return reqs


def compile_label_selectors(
    selectors: Sequence[Optional[v1.LabelSelector]],
    dic: Dictionary,
    min_s: int = 4,
    min_v: int = 4,
    min_u: int = 4,
) -> CompiledLabelSelectors:
    b = max(len(selectors), 1)
    req_lists = [
        _selector_requirements(s) if s is not None else None for s in selectors
    ]
    # dedup: canonical requirement tuple → unique row (order-insensitive AND)
    keys = [
        None if r is None
        else tuple(sorted((k, op, tuple(vals)) for (k, op, vals) in r))
        for r in req_lists
    ]
    uniq: dict = {}
    index = np.zeros(b, dtype=np.int32)
    for i, key in enumerate(keys):
        uid = uniq.get(key)
        if uid is None:
            uid = uniq[key] = len(uniq)
        index[i] = uid
    uniq_reqs = [None] * len(uniq)
    for i, key in enumerate(keys):
        uniq_reqs[uniq[key]] = req_lists[i] if key is not None else None
    u = _round_up(len(uniq), min_u)
    s_cap = _round_up(
        max((len(r) for r in uniq_reqs if r is not None), default=0), min_s
    )
    v_cap = _round_up(
        max((len(vals) for r in uniq_reqs if r is not None for (_, _, vals) in r),
            default=0),
        min_v,
    )
    req_key = np.full((u, s_cap), MISSING, dtype=np.int32)
    req_op = np.full((u, s_cap), OP_PAD, dtype=np.int32)
    req_vals = np.full((u, s_cap, v_cap), MISSING, dtype=np.int32)
    req_num = np.full((u, s_cap), np.nan, dtype=np.float32)
    match_none = np.zeros((u,), dtype=bool)
    match_none[len(uniq):] = True  # pad rows match nothing
    has_numeric = False
    for i, reqs in enumerate(uniq_reqs):
        if reqs is None:
            match_none[i] = True
            continue
        for j, (key, op, vals) in enumerate(reqs):
            req_key[i, j] = dic.intern(key)
            req_op[i, j] = _OP_CODE[op]
            has_numeric = has_numeric or op in (v1.OP_GT, v1.OP_LT)
            for k, val in enumerate(vals):
                req_vals[i, j, k] = dic.intern(val)
            if vals:
                try:
                    req_num[i, j] = float(int(vals[0]))
                except ValueError:
                    pass
    return CompiledLabelSelectors(
        req_key, req_op, req_vals, req_num, match_none, index, has_numeric
    )


def compile_node_selectors(
    selectors: Sequence[Optional[v1.NodeSelector]],
    dic: Dictionary,
    min_t: int = 2,
    min_s: int = 4,
    min_v: int = 4,
    min_u: int = 2,
) -> CompiledNodeSelectors:
    b = max(len(selectors), 1)
    all_terms: List[List[List]] = []
    for s in selectors:
        terms = []
        if s is not None:
            for t in s.node_selector_terms:
                reqs = [(e.key, e.operator, list(e.values)) for e in t.match_expressions]
                reqs += [
                    ("metadata.name" if e.key in ("metadata.name", "name") else e.key,
                     e.operator, list(e.values))
                    for e in t.match_fields
                ]
                terms.append(reqs)
        all_terms.append(terms)
    t_cap = _round_up(max((len(t) for t in all_terms), default=0), min_t)
    s_cap = _round_up(
        max((len(r) for terms in all_terms for r in terms), default=0), min_s
    )
    v_cap = _round_up(
        max(
            (len(vals) for terms in all_terms for reqs in terms for (_, _, vals) in reqs),
            default=0,
        ),
        min_v,
    )
    # dedup: canonical terms tuple → unique row (term order kept — OR of ANDs)
    keys = [
        None if selectors[i] is None
        else tuple(
            tuple(sorted((k, op, tuple(vals)) for (k, op, vals) in reqs))
            for reqs in all_terms[i]
        )
        for i in range(len(selectors))
    ]
    if not keys:
        keys = [None]
    uniq: dict = {}
    index = np.zeros(b, dtype=np.int32)
    for i, key in enumerate(keys):
        uid = uniq.get(key)
        if uid is None:
            uid = uniq[key] = len(uniq)
        index[i] = uid
    uniq_terms = [None] * len(uniq)
    for i, key in enumerate(keys):
        uniq_terms[uniq[key]] = all_terms[i] if key is not None else None
    u = _round_up(len(uniq), min_u)
    req_key = np.full((u, t_cap, s_cap), MISSING, dtype=np.int32)
    req_op = np.full((u, t_cap, s_cap), OP_PAD, dtype=np.int32)
    req_vals = np.full((u, t_cap, s_cap, v_cap), MISSING, dtype=np.int32)
    req_num = np.full((u, t_cap, s_cap), np.nan, dtype=np.float32)
    term_valid = np.zeros((u, t_cap), dtype=bool)
    match_all = np.zeros((u,), dtype=bool)
    has_numeric = False
    for i, terms in enumerate(uniq_terms):
        if terms is None:
            match_all[i] = True
            continue
        for ti, reqs in enumerate(terms):
            # Reference: an empty term matches nothing → leave term_valid False
            # only for terms with no requirements at all.
            term_valid[i, ti] = len(reqs) > 0
            for j, (key, op, vals) in enumerate(reqs):
                req_key[i, ti, j] = dic.intern(key)
                req_op[i, ti, j] = _OP_CODE[op]
                has_numeric = has_numeric or op in (v1.OP_GT, v1.OP_LT)
                for k, val in enumerate(vals):
                    req_vals[i, ti, j, k] = dic.intern(val)
                if vals:
                    try:
                        req_num[i, ti, j] = float(int(vals[0]))
                    except ValueError:
                        pass
    return CompiledNodeSelectors(
        req_key, req_op, req_vals, req_num, term_valid, match_all, index, has_numeric
    )


# --- device evaluation (pure jnp; jit/vmap-compatible) ----------------------


def _op_select(req_op, present, in_vals, gt, lt):
    """Pick each requirement's result by op code via a where-chain.

    (A take_along_axis over a stacked [6, ...] would lower to a minor-axis
    element gather — serial on TPU; the chain is 6 fused VPU selects.)"""
    picked = jnp.where(
        req_op == OP_IN, present & in_vals,
        jnp.where(
            req_op == OP_NOT_IN, (~present) | (~in_vals),  # absent key matches
            jnp.where(
                req_op == OP_EXISTS, present,
                jnp.where(
                    req_op == OP_DOES_NOT_EXIST, ~present,
                    jnp.where(req_op == OP_GT, gt, jnp.where(req_op == OP_LT, lt, True)),
                ),
            ),
        ),
    )
    return jnp.where(req_op == OP_PAD, True, picked)


def requirements_match_matrix(
    req_key, req_op, req_vals, req_num, keys, vals,
    vals_num=None, numeric=None, has_numeric: bool = True,
):
    """Batched requirement sets × batched label sets → bool match matrix.

    req_key/req_op [U, S]; req_vals [U, S, V]; req_num [U, S];
    keys/vals i32[O, L] (-1 padded); vals_num f32[O, L] — numeric parse of each
    label value (NaN unparseable), used for Gt/Lt.  has_numeric is a TRACE-TIME
    constant: when False the whole numeric path is elided from the program.
    When True and vals_num is None, falls back to one [O, L] gather from the
    dictionary numeric side-table (small — O·L elements once, NOT per pair).

    Returns bool[U, O].  One fused program: every op is a broadcast compare /
    masked reduce on the VPU; no per-element gathers (TPU lowers minor-axis
    element gathers to ~0.4µs/element serial loops — the round-3 profile
    showed this was most of the device program at 5k nodes).
    """
    rk = jnp.asarray(req_key)[:, :, None, None]      # [U, S, 1, 1]
    km = (jnp.asarray(keys)[None, None, :, :] == rk) & (rk >= 0)  # [U, S, O, L]
    present = jnp.any(km, axis=-1)                   # [U, S, O]
    # Label keys are unique per object → at most one L column matches.
    val = jnp.max(
        jnp.where(km, jnp.asarray(vals)[None, None, :, :], MISSING), axis=-1
    )  # [U, S, O]
    in_vals = jnp.any(
        (jnp.asarray(req_vals)[:, :, None, :] == val[:, :, :, None])
        & (val[:, :, :, None] >= 0),
        axis=-1,
    )  # [U, S, O]
    if has_numeric:
        if vals_num is None:
            safe = jnp.clip(jnp.asarray(vals), 0, numeric.shape[0] - 1)
            vals_num = jnp.where(jnp.asarray(vals) >= 0, numeric[safe], jnp.nan)
        vn = jnp.max(
            jnp.where(km, jnp.asarray(vals_num)[None, None, :, :], -jnp.inf), axis=-1
        )  # [U, S, O]; matched-but-unparseable → NaN (compares False)
        rn = jnp.asarray(req_num)[:, :, None]
        gt = present & (vn > rn)
        lt = present & (vn < rn)
    else:
        gt = lt = jnp.zeros(present.shape, bool)
    ok = _op_select(jnp.asarray(req_op)[:, :, None], present, in_vals, gt, lt)
    return jnp.all(ok, axis=1)  # [U, O]


def label_match_matrix(
    cs: CompiledLabelSelectors, keys, vals, vals_num=None, numeric=None
):
    """Compiled selector batch (B rows, U unique) × label sets [O, L] → bool[B, O]."""
    m_u = requirements_match_matrix(
        cs.req_key, cs.req_op, cs.req_vals, cs.req_num, keys, vals,
        vals_num=vals_num, numeric=numeric, has_numeric=cs.has_numeric,
    )
    m_u = m_u & ~jnp.asarray(cs.match_none)[:, None]
    return m_u[jnp.asarray(cs.index)]  # [B, O] — major-axis gather, cheap


def node_match_matrix(
    cns: CompiledNodeSelectors, keys, vals, vals_num=None, numeric=None
):
    """Compiled NodeSelector batch (B rows, U unique) × label sets [O, L] →
    bool[B, O].  OR over valid terms, AND within a term; match_all rows → True."""
    u, t, s = cns.req_key.shape
    per_term = requirements_match_matrix(
        np.reshape(cns.req_key, (u * t, s)),
        np.reshape(cns.req_op, (u * t, s)),
        np.reshape(cns.req_vals, (u * t, s, -1)),
        np.reshape(cns.req_num, (u * t, s)),
        keys, vals, vals_num=vals_num, numeric=numeric,
        has_numeric=cns.has_numeric,
    ).reshape(u, t, -1)  # [U, T, O]
    any_term = jnp.any(per_term & jnp.asarray(cns.term_valid)[:, :, None], axis=1)
    m_u = jnp.asarray(cns.match_all)[:, None] | any_term
    return m_u[jnp.asarray(cns.index)]


def eval_requirements(req_key, req_op, req_vals, req_num, keys, vals, numeric):
    """AND of one selector's requirements against one label set.

    req_key/req_op [S], req_vals [S, V], req_num [S]; keys/vals [L] (-1 padded);
    numeric f32[num_ids] — dictionary numeric side-table. Returns scalar bool.
    Broadcasts cleanly under vmap along both selector and label-set axes —
    kept for the row-sliced scan paths; matrix paths use
    requirements_match_matrix (no per-element gathers).
    """
    key_match = (keys[None, :] == req_key[:, None]) & (req_key[:, None] >= 0)  # [S, L]
    present = jnp.any(key_match, axis=1)
    # Label keys are unique per object → at most one column matches.
    val = jnp.max(jnp.where(key_match, vals[None, :], MISSING), axis=1)  # [S]
    in_vals = jnp.any((req_vals == val[:, None]) & (val[:, None] >= 0), axis=1)
    safe_val = jnp.clip(val, 0, numeric.shape[0] - 1)
    val_num = numeric[safe_val]
    gt = present & (val_num > req_num)  # NaN compares → False
    lt = present & (val_num < req_num)
    ok = _op_select(req_op, present, in_vals, gt, lt)
    return jnp.all(ok)


def eval_label_selector(sel: CompiledLabelSelectors, i, keys, vals, numeric):
    """Selector i of the batch vs one label set → bool (use under vmap/jit).

    Arrays go through jnp.asarray so i may be a tracer (vmap over the batch axis).
    """
    u = jnp.asarray(sel.index)[i]
    return (~jnp.asarray(sel.match_none)[u]) & eval_requirements(
        jnp.asarray(sel.req_key)[u],
        jnp.asarray(sel.req_op)[u],
        jnp.asarray(sel.req_vals)[u],
        jnp.asarray(sel.req_num)[u],
        keys, vals, numeric,
    )


def eval_node_selector_arrays(
    req_key, req_op, req_vals, req_num, term_valid, match_all, keys, vals, numeric
):
    """One compiled NodeSelector (term arrays [T, S, ...]) vs one label set → bool."""
    import jax

    per_term = jax.vmap(
        lambda rk, ro, rv, rn: eval_requirements(rk, ro, rv, rn, keys, vals, numeric)
    )(req_key, req_op, req_vals, req_num)  # [T]
    any_term = jnp.any(per_term & term_valid)
    return match_all | any_term
