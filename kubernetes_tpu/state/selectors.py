"""Selector compilation: label/node selectors → int32 tensor programs.

The reference evaluates selectors per (pod, node/pod) pair in Go
(apimachinery labels.Selector; component-helpers nodeaffinity). Here a batch of
selectors is *compiled once* host-side into padded int32 arrays, and evaluation is a
pure jnp function over dictionary-encoded label arrays — vmap/jit-able along both
the selector batch and the node/pod axes, so a whole ``[pods, nodes]`` or
``[terms, pods]`` match matrix is one fused device program.

Encoding (MISSING = -1 is the universal pad):
  requirement ops: IN=0 NOT_IN=1 EXISTS=2 DOES_NOT_EXIST=3 GT=4 LT=5, PAD=-1
  a padded requirement row is the AND-identity (always true)
  a LabelSelector with match_none=True matches nothing (the None selector)
  a NodeSelector with match_all=True matches everything (the nil selector);
  otherwise OR over valid terms, AND over each term's requirements
  matchFields(metadata.name) is handled by interning the node name as a
  pseudo-label under the key "metadata.name" at node-encoding time.

Conservative-capacity note: S (requirements/term), V (values/requirement) and T
(terms) are sized to the max present in the compiled batch, rounded up to powers of
two to bound XLA recompiles; nothing is silently truncated.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..api import objects as v1
from .dictionary import MISSING, Dictionary

OP_IN = 0
OP_NOT_IN = 1
OP_EXISTS = 2
OP_DOES_NOT_EXIST = 3
OP_GT = 4
OP_LT = 5
OP_PAD = -1

_OP_CODE = {
    v1.OP_IN: OP_IN,
    v1.OP_NOT_IN: OP_NOT_IN,
    v1.OP_EXISTS: OP_EXISTS,
    v1.OP_DOES_NOT_EXIST: OP_DOES_NOT_EXIST,
    v1.OP_GT: OP_GT,
    v1.OP_LT: OP_LT,
}


from .units import pow2_round_up as _round_up  # shared shape discipline


@dataclass
class CompiledLabelSelectors:
    """Batch of B compiled metav1.LabelSelectors.

    req_key  i32[B, S]; req_op i32[B, S]; req_vals i32[B, S, V]
    req_num  f32[B, S]  — numeric RHS for Gt/Lt (NaN when unparseable)
    match_none bool[B]  — True for the None selector (matches nothing)
    """

    req_key: np.ndarray
    req_op: np.ndarray
    req_vals: np.ndarray
    req_num: np.ndarray
    match_none: np.ndarray

    def __len__(self):
        return self.req_key.shape[0]


@dataclass
class CompiledNodeSelectors:
    """Batch of B compiled v1.NodeSelectors (terms OR, requirements AND).

    req_key i32[B, T, S]; req_op i32[B, T, S]; req_vals i32[B, T, S, V]
    req_num f32[B, T, S]; term_valid bool[B, T]; match_all bool[B]
    """

    req_key: np.ndarray
    req_op: np.ndarray
    req_vals: np.ndarray
    req_num: np.ndarray
    term_valid: np.ndarray
    match_all: np.ndarray

    def __len__(self):
        return self.req_key.shape[0]


from ..utils.pytrees import register_pytree_dataclass as _reg  # noqa: E402

_reg(CompiledLabelSelectors)
_reg(CompiledNodeSelectors)


def _selector_requirements(sel: v1.LabelSelector):
    """Flatten matchLabels + matchExpressions into (key, op, values) triples."""
    reqs = []
    for k, val in sorted(sel.match_labels.items()):
        reqs.append((k, v1.OP_IN, [val]))
    for e in sel.match_expressions:
        reqs.append((e.key, e.operator, list(e.values)))
    return reqs


def compile_label_selectors(
    selectors: Sequence[Optional[v1.LabelSelector]],
    dic: Dictionary,
    min_s: int = 4,
    min_v: int = 4,
) -> CompiledLabelSelectors:
    b = max(len(selectors), 1)
    req_lists = [
        _selector_requirements(s) if s is not None else [] for s in selectors
    ]
    s_cap = _round_up(max((len(r) for r in req_lists), default=0), min_s)
    v_cap = _round_up(
        max((len(vals) for reqs in req_lists for (_, _, vals) in reqs), default=0),
        min_v,
    )
    req_key = np.full((b, s_cap), MISSING, dtype=np.int32)
    req_op = np.full((b, s_cap), OP_PAD, dtype=np.int32)
    req_vals = np.full((b, s_cap, v_cap), MISSING, dtype=np.int32)
    req_num = np.full((b, s_cap), np.nan, dtype=np.float32)
    match_none = np.zeros((b,), dtype=bool)
    for i, sel in enumerate(selectors):
        if sel is None:
            match_none[i] = True
            continue
        for j, (key, op, vals) in enumerate(req_lists[i]):
            req_key[i, j] = dic.intern(key)
            req_op[i, j] = _OP_CODE[op]
            for k, val in enumerate(vals):
                req_vals[i, j, k] = dic.intern(val)
            if vals:
                try:
                    req_num[i, j] = float(int(vals[0]))
                except ValueError:
                    pass
    return CompiledLabelSelectors(req_key, req_op, req_vals, req_num, match_none)


def compile_node_selectors(
    selectors: Sequence[Optional[v1.NodeSelector]],
    dic: Dictionary,
    min_t: int = 2,
    min_s: int = 4,
    min_v: int = 4,
) -> CompiledNodeSelectors:
    b = max(len(selectors), 1)
    all_terms: List[List[List]] = []
    for s in selectors:
        terms = []
        if s is not None:
            for t in s.node_selector_terms:
                reqs = [(e.key, e.operator, list(e.values)) for e in t.match_expressions]
                reqs += [
                    ("metadata.name" if e.key in ("metadata.name", "name") else e.key,
                     e.operator, list(e.values))
                    for e in t.match_fields
                ]
                terms.append(reqs)
        all_terms.append(terms)
    t_cap = _round_up(max((len(t) for t in all_terms), default=0), min_t)
    s_cap = _round_up(
        max((len(r) for terms in all_terms for r in terms), default=0), min_s
    )
    v_cap = _round_up(
        max(
            (len(vals) for terms in all_terms for reqs in terms for (_, _, vals) in reqs),
            default=0,
        ),
        min_v,
    )
    req_key = np.full((b, t_cap, s_cap), MISSING, dtype=np.int32)
    req_op = np.full((b, t_cap, s_cap), OP_PAD, dtype=np.int32)
    req_vals = np.full((b, t_cap, s_cap, v_cap), MISSING, dtype=np.int32)
    req_num = np.full((b, t_cap, s_cap), np.nan, dtype=np.float32)
    term_valid = np.zeros((b, t_cap), dtype=bool)
    match_all = np.zeros((b,), dtype=bool)
    for i, sel in enumerate(selectors):
        if sel is None:
            match_all[i] = True
            continue
        for ti, reqs in enumerate(all_terms[i]):
            # Reference: an empty term matches nothing → leave term_valid False
            # only for terms with no requirements at all.
            term_valid[i, ti] = len(reqs) > 0
            for j, (key, op, vals) in enumerate(reqs):
                req_key[i, ti, j] = dic.intern(key)
                req_op[i, ti, j] = _OP_CODE[op]
                for k, val in enumerate(vals):
                    req_vals[i, ti, j, k] = dic.intern(val)
                if vals:
                    try:
                        req_num[i, ti, j] = float(int(vals[0]))
                    except ValueError:
                        pass
    return CompiledNodeSelectors(
        req_key, req_op, req_vals, req_num, term_valid, match_all
    )


# --- device evaluation (pure jnp; jit/vmap-compatible) ----------------------


def eval_requirements(req_key, req_op, req_vals, req_num, keys, vals, numeric):
    """AND of one selector's requirements against one label set.

    req_key/req_op [S], req_vals [S, V], req_num [S]; keys/vals [L] (-1 padded);
    numeric f32[num_ids] — dictionary numeric side-table. Returns scalar bool.
    Broadcasts cleanly under vmap along both selector and label-set axes.
    """
    key_match = (keys[None, :] == req_key[:, None]) & (req_key[:, None] >= 0)  # [S, L]
    present = jnp.any(key_match, axis=1)
    # Label keys are unique per object → at most one column matches.
    val = jnp.max(jnp.where(key_match, vals[None, :], MISSING), axis=1)  # [S]
    in_vals = jnp.any((req_vals == val[:, None]) & (val[:, None] >= 0), axis=1)
    safe_val = jnp.clip(val, 0, numeric.shape[0] - 1)
    val_num = numeric[safe_val]
    gt = present & (val_num > req_num)  # NaN compares → False
    lt = present & (val_num < req_num)
    results = jnp.stack(
        [
            present & in_vals,  # IN
            (~present) | (~in_vals),  # NOT_IN (absent key matches)
            present,  # EXISTS
            ~present,  # DOES_NOT_EXIST
            gt,  # GT
            lt,  # LT
        ],
        axis=0,
    )  # [6, S]
    op = jnp.clip(req_op, 0, 5)
    picked = jnp.take_along_axis(results, op[None, :], axis=0)[0]  # [S]
    ok = jnp.where(req_op == OP_PAD, True, picked)
    return jnp.all(ok)


def eval_label_selector(sel: CompiledLabelSelectors, i, keys, vals, numeric):
    """Selector i of the batch vs one label set → bool (use under vmap/jit).

    Arrays go through jnp.asarray so i may be a tracer (vmap over the batch axis).
    """
    return (~jnp.asarray(sel.match_none)[i]) & eval_requirements(
        jnp.asarray(sel.req_key)[i],
        jnp.asarray(sel.req_op)[i],
        jnp.asarray(sel.req_vals)[i],
        jnp.asarray(sel.req_num)[i],
        keys, vals, numeric,
    )


def eval_node_selector_arrays(
    req_key, req_op, req_vals, req_num, term_valid, match_all, keys, vals, numeric
):
    """One compiled NodeSelector (term arrays [T, S, ...]) vs one label set → bool."""
    import jax

    per_term = jax.vmap(
        lambda rk, ro, rv, rn: eval_requirements(rk, ro, rv, rn, keys, vals, numeric)
    )(req_key, req_op, req_vals, req_num)  # [T]
    any_term = jnp.any(per_term & term_valid)
    return match_all | any_term
