"""Scheduler cache: authoritative in-scheduler cluster state + assumed-pod lifecycle.

Reference: pkg/scheduler/internal/cache/cache.go (cacheImpl :56-75, UpdateSnapshot
:197-276) and interface.go:59. Responsibilities:

- node add/update/remove, pod add/update/remove from the watch stream
- optimistic **assume** (scheduler-local placement before the bind write lands),
  finishBinding starts a TTL (default 15 min, scheduler.go:64-66) after which an
  unconfirmed assumed pod expires and its resources are released
- O(changed) snapshot refresh via per-NodeInfo generation numbers: only NodeInfos
  whose generation exceeds the snapshot's high-water mark are re-encoded (the
  Pythonic equivalent of the reference's generation-sorted doubly-linked list)
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..api import objects as v1
from ..metrics import scheduler_metrics as m
from .node_info import NodeInfo, next_generation

DEFAULT_ASSUME_TTL_SECONDS = 15 * 60.0


class SchedulerCacheError(Exception):
    pass


@dataclass
class _PodState:
    pod: v1.Pod
    deadline: Optional[float] = None  # set by finish_binding
    binding_finished: bool = False


@dataclass
class Snapshot:
    """Immutable per-cycle host view (reference internal/cache/snapshot.go:29-40)."""

    node_info_map: Dict[str, NodeInfo] = field(default_factory=dict)
    node_info_list: List[NodeInfo] = field(default_factory=list)
    have_pods_with_affinity_list: List[NodeInfo] = field(default_factory=list)
    have_pods_with_required_anti_affinity_list: List[NodeInfo] = field(default_factory=list)
    generation: int = 0

    def num_nodes(self) -> int:
        return len(self.node_info_list)

    def get(self, name: str) -> Optional[NodeInfo]:
        return self.node_info_map.get(name)


class Cache:
    """Single-writer cache (the event-ingest path), snapshot-reader scheduling path."""

    def __init__(self, ttl: float = DEFAULT_ASSUME_TTL_SECONDS, clock=time.monotonic):
        self._ttl = ttl
        self._clock = clock
        self._nodes: Dict[str, NodeInfo] = {}
        self._pod_states: Dict[str, _PodState] = {}  # pod uid -> state
        self._assumed_pods: Set[str] = set()

    # --- nodes --------------------------------------------------------------

    def add_node(self, node: v1.Node) -> None:
        info = self._nodes.get(node.metadata.name)
        if info is None:
            info = NodeInfo()
            self._nodes[node.metadata.name] = info
            # pods may have arrived before their node (reference cache.go AddPod
            # creating an imaginary node entry)
        info.set_node(node)

    def update_node(self, node: v1.Node) -> None:
        self.add_node(node)

    def remove_node(self, name: str) -> None:
        info = self._nodes.get(name)
        if info is None:
            return
        if info.pods:
            # keep entry for remaining (possibly stale) pods; clear node object
            info.node = None
            info.generation = next_generation()
        else:
            del self._nodes[name]

    # --- pods ---------------------------------------------------------------

    def assume_pod(self, pod: v1.Pod, node_name: str) -> None:
        """Optimistically place pod on node before the bind completes
        (reference cache.go AssumePod; scheduler.go:424,571)."""
        uid = pod.uid
        if uid in self._pod_states:
            raise SchedulerCacheError(f"pod {pod.key()} already assumed/added")
        # assume on a COPY: the caller's (queued) pod must keep NodeName empty so
        # a failed bind can be retried anywhere (the reference assumes on a
        # deep-copied pod, scheduler.go:566-581).  A pod+spec shallow copy is
        # enough here: only spec.node_name diverges, and the shared sub-objects
        # (metadata, containers) are treated as immutable by the cache — a full
        # deepcopy measured ~1 ms/pod, 20% of a 128-pod batch's host budget.
        assumed = copy.copy(pod)
        assumed.spec = copy.copy(pod.spec)
        assumed.spec.node_name = node_name
        self._add_pod_to_node(assumed)
        self._pod_states[uid] = _PodState(pod=assumed)
        self._assumed_pods.add(uid)

    def finish_binding(self, pod: v1.Pod) -> None:
        uid = pod.uid
        st = self._pod_states.get(uid)
        if st is None or uid not in self._assumed_pods:
            return
        st.binding_finished = True
        st.deadline = self._clock() + self._ttl

    def forget_pod(self, pod: v1.Pod) -> None:
        """Binding failed — roll the assume back (reference scheduler.go:676-689)."""
        uid = pod.uid
        if uid not in self._assumed_pods:
            raise SchedulerCacheError(f"pod {pod.key()} not assumed")
        self._remove_pod_from_node(self._pod_states[uid].pod)
        del self._pod_states[uid]
        self._assumed_pods.discard(uid)

    def add_pod(self, pod: v1.Pod) -> None:
        """Watch-confirmed scheduled pod (Add event with nodeName set)."""
        uid = pod.uid
        st = self._pod_states.get(uid)
        if st is not None and uid in self._assumed_pods:
            # confirmation of an assumed pod
            if st.pod.spec.node_name != pod.spec.node_name:
                # scheduled somewhere else than we assumed — fix up
                self._remove_pod_from_node(st.pod)
                self._add_pod_to_node(pod)
            self._assumed_pods.discard(uid)
            self._pod_states[uid] = _PodState(pod=pod)
            return
        if st is not None:
            return  # duplicate add
        self._add_pod_to_node(pod)
        self._pod_states[uid] = _PodState(pod=pod)

    def update_pod(self, old: v1.Pod, new: v1.Pod) -> None:
        st = self._pod_states.get(old.uid)
        if st is None:
            self.add_pod(new)
            return
        self._remove_pod_from_node(st.pod)
        self._add_pod_to_node(new)
        self._pod_states[new.uid] = _PodState(pod=new)

    def remove_pod(self, pod: v1.Pod) -> None:
        st = self._pod_states.pop(pod.uid, None)
        self._assumed_pods.discard(pod.uid)
        if st is not None:
            self._remove_pod_from_node(st.pod)

    def is_assumed(self, pod: v1.Pod) -> bool:
        return pod.uid in self._assumed_pods

    def cleanup_expired(self, now: Optional[float] = None) -> List[v1.Pod]:
        """Expire assumed pods whose binding never confirmed (cache.go cleanup)."""
        now = self._clock() if now is None else now
        expired = []
        for uid in list(self._assumed_pods):
            st = self._pod_states[uid]
            if st.binding_finished and st.deadline is not None and now >= st.deadline:
                expired.append(st.pod)
                self.remove_pod(st.pod)
        return expired

    def _add_pod_to_node(self, pod: v1.Pod) -> None:
        name = pod.spec.node_name
        info = self._nodes.get(name)
        if info is None:
            info = NodeInfo()  # node not seen yet; imaginary entry
            self._nodes[name] = info
        info.add_pod(pod)

    def _remove_pod_from_node(self, pod: v1.Pod) -> None:
        info = self._nodes.get(pod.spec.node_name)
        if info is not None:
            info.remove_pod(pod)
            if info.node is None and not info.pods:
                del self._nodes[pod.spec.node_name]

    # --- snapshot -----------------------------------------------------------

    def node_count(self) -> int:
        return sum(1 for n in self._nodes.values() if n.node is not None)

    def pod_count(self) -> int:
        return sum(len(n.pods) for n in self._nodes.values())

    def update_snapshot(self, snapshot: Snapshot) -> List[str]:
        """Refresh snapshot in place; returns names of changed nodes (O(changed)).

        Reference: cache.go:197-276 — only NodeInfos with generation > the
        snapshot's high-water mark are cloned; removed nodes are pruned.
        """
        changed: List[str] = []
        max_gen = snapshot.generation
        for name, info in self._nodes.items():
            if info.node is None:
                continue
            if info.generation > snapshot.generation:
                snapshot.node_info_map[name] = info.clone()
                changed.append(name)
                max_gen = max(max_gen, info.generation)
        removed = [
            name
            for name in snapshot.node_info_map
            if name not in self._nodes or self._nodes[name].node is None
        ]
        for name in removed:
            del snapshot.node_info_map[name]
            changed.append(name)
        if changed:
            snapshot.node_info_list = list(snapshot.node_info_map.values())
            snapshot.have_pods_with_affinity_list = [
                n for n in snapshot.node_info_list if n.pods_with_affinity
            ]
            snapshot.have_pods_with_required_anti_affinity_list = [
                n for n in snapshot.node_info_list if n.pods_with_required_anti_affinity
            ]
        snapshot.generation = max_gen
        # cache.go updateMetrics: size gauges refresh on every snapshot
        m.scheduler_cache_size.set(float(len(self._nodes)), ("nodes",))
        m.scheduler_cache_size.set(
            float(len(self._assumed_pods)), ("assumed_pods",))
        m.scheduler_cache_size.set(float(len(self._pod_states)), ("pods",))
        return changed
