"""Struct-of-arrays encoding of cluster state for the device compute path.

The reference's per-cycle inputs are Go structs walked by 16 goroutines
(scheduler.go:983-1023). Here the snapshot is mirrored into padded, fixed-shape
int32/float32 arrays (host numpy), incrementally updated from the cache's
changed-node list (the analog of cache.go:197-276 generation snapshotting), and
uploaded to device either as whole buffers or as row-scatter updates — so a 100k-node
cluster does not re-upload per cycle.

Shape discipline (XLA static shapes): capacities are rounded up to powers of two and
grown by doubling, so recompilation happens O(log n) times over a cluster's life.

Encoded semantic notes:
- node "metadata.name" and "kubernetes.io/hostname" are injected as labels so
  matchFields and hostname topology work uniformly.
- host ports are encoded as (proto*2^16+port, hostIP id) pairs; the device
  filter implements the exact HostPortInfo wildcard rule (equal (proto, port)
  conflicts iff the IPs are equal or either is 0.0.0.0 — framework/types.go
  CheckConflict), bit-identical to the host oracle's host_ports_conflict.
- taint effects: NoSchedule=0, PreferNoSchedule=1, NoExecute=2.
- resource units per state/units.py; requests ceil, allocatable floor; a pod's
  "pods" dimension request is always 1.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..api import objects as v1
from ..api.resource import (
    Resource,
    compute_pod_resource_request,
    compute_pod_resource_request_non_zero,
)
from .cache import Snapshot
from .dictionary import MISSING, Dictionary, _parse_numeric
from .node_info import NodeInfo
from . import units

EFFECT_CODE = {
    v1.TAINT_NO_SCHEDULE: 0,
    v1.TAINT_PREFER_NO_SCHEDULE: 1,
    v1.TAINT_NO_EXECUTE: 2,
}
_PROTO_CODE = {"TCP": 0, "UDP": 1, "SCTP": 2}

HOSTNAME_LABEL = "kubernetes.io/hostname"


def _pow2(n: int, minimum: int = 8) -> int:
    return units.pow2_round_up(n, minimum)


@dataclass
class EncodingConfig:
    min_nodes: int = 64
    min_pods: int = 256
    label_cap: int = 16
    pod_label_cap: int = 8
    taint_cap: int = 8
    port_cap: int = 8
    image_cap: int = 8
    extended_resource_cap: int = 4  # spare scalar-resource dims beyond the base 4
    topo_key_cap: int = 8  # registered topology keys (zone/hostname/region/…)

    @property
    def num_resource_dims(self) -> int:
        return units.NUM_BASE_DIMS + self.extended_resource_cap


class EncodingCapacityError(Exception):
    """A per-object cap (labels/taints/ports/images/extended resources) overflowed.

    Raise rather than truncate: silent truncation would corrupt filter semantics.
    Callers raise the cap in EncodingConfig.
    """


@dataclass
class DeviceSnapshot:
    """The jnp view handed to plugin tensor programs (all shapes static)."""

    # nodes
    node_valid: jnp.ndarray  # bool[N]
    node_name_ids: jnp.ndarray  # i32[N] (interned node name; MISSING for free rows)
    allocatable: jnp.ndarray  # i32[N, R]
    requested: jnp.ndarray  # i32[N, R]
    non_zero_requested: jnp.ndarray  # i32[N, 2] (cpu milli, mem KiB)
    node_label_keys: jnp.ndarray  # i32[N, L]
    node_label_vals: jnp.ndarray  # i32[N, L]
    node_label_num: jnp.ndarray  # f32[N, L] Atoi parse of label values (NaN = not a number)
    node_topo: jnp.ndarray  # i32[N, K] compact domain index per registered topo key
    taint_keys: jnp.ndarray  # i32[N, T]
    taint_vals: jnp.ndarray  # i32[N, T]
    taint_effects: jnp.ndarray  # i32[N, T] (-1 pad)
    ports: jnp.ndarray  # i32[N, P] (proto<<16 | port, -1 pad)
    ports_ip: jnp.ndarray  # i32[N, P] (hostIP dictionary id; ID_WILDCARD_IP = any)
    image_ids: jnp.ndarray  # i32[N, I]
    image_sizes: jnp.ndarray  # f32[N, I] bytes
    unschedulable: jnp.ndarray  # bool[N]
    # Ready-condition mask (node lifecycle): False once the lifecycle
    # controller marks Ready Unknown/False — feasibility composes
    # node_valid & node_ready so an in-flight cycle can't bind onto a dead
    # host even before the NoExecute taint plane is consulted
    node_ready: jnp.ndarray  # bool[N]
    # DRA claim planes (dra/index.py writes the mirrors): published TPU
    # device inventory and currently-allocated device count per node row.
    # free chips = claim_capacity - claim_allocated is the filter plane the
    # DynamicResources plugin and the gang anchor-slice score consume
    claim_capacity: jnp.ndarray  # i32[N]
    claim_allocated: jnp.ndarray  # i32[N]
    # scheduled pods
    pod_valid: jnp.ndarray  # bool[P]
    pod_node: jnp.ndarray  # i32[P] (-1 when unknown)
    pod_ns: jnp.ndarray  # i32[P]
    pod_label_keys: jnp.ndarray  # i32[P, PL]
    pod_label_vals: jnp.ndarray  # i32[P, PL]
    pod_priority: jnp.ndarray  # i32[P]
    pod_request: jnp.ndarray  # i32[P, R]
    pod_non_zero: jnp.ndarray  # i32[P, 2]
    # incremental existing-pod affinity groups (state/affinity_index.py):
    # deduplicated term signatures with per-domain count tables, maintained
    # by scatter deltas at assume/forget/bind/node-delete time — the
    # device-resident replacement for InterPodAffinity.host_prepare's
    # per-cycle rebuild walk
    aff_valid: jnp.ndarray  # bool[G]
    aff_kind: jnp.ndarray  # i32[G] (0 = anti-affinity block, 1 = score)
    aff_weight: jnp.ndarray  # f32[G]
    aff_slot: jnp.ndarray  # i32[G] topology-key slot (MISSING = unset row)
    aff_counts: jnp.ndarray  # f32[G, D] owner-term count per domain value
    # dictionary numeric side-table
    numeric: jnp.ndarray  # f32[num_ids]

    @property
    def num_nodes(self) -> int:
        return self.node_valid.shape[0]

    @property
    def num_pods(self) -> int:
        return self.pod_valid.shape[0]


from ..utils.pytrees import register_pytree_dataclass as _reg  # noqa: E402

_reg(DeviceSnapshot)


@dataclass
class PendingScatter:
    """Deferred row-scatter payload (see to_device_deferred): each group is
    None or ``(rows i32[k], vals tuple)`` with k pow2-padded by repeating the
    first row (idempotent for .set); numeric is a full replacement or None."""

    node_rows: object = None
    pod_rows: object = None
    aff_rows: object = None
    numeric: object = None


_reg(PendingScatter)


def apply_scatter(dsnap: DeviceSnapshot, upd: Optional[PendingScatter]) -> DeviceSnapshot:
    """Apply a PendingScatter inside a jitted program (pure, traceable)."""
    if upd is None:
        return dsnap
    out = {k: getattr(dsnap, k) for k in _NODE_ARRAYS + _POD_ARRAYS + _AFF_ARRAYS}
    for names, group in ((_NODE_ARRAYS, upd.node_rows), (_POD_ARRAYS, upd.pod_rows),
                         (_AFF_ARRAYS, upd.aff_rows)):
        if group is None:
            continue
        rows, vals = group
        for k, v in zip(names, vals):
            out[k] = out[k].at[rows].set(v)
    numeric = dsnap.numeric if upd.numeric is None else jnp.asarray(upd.numeric)
    return DeviceSnapshot(**out, numeric=numeric)


class ClusterEncoder:
    """Maintains host numpy mirrors + device buffers; applies incremental updates."""

    def __init__(self, dic: Optional[Dictionary] = None, cfg: Optional[EncodingConfig] = None):
        self.dic = dic or Dictionary()
        self.cfg = cfg or EncodingConfig()
        self.extended_index: Dict[str, int] = {}
        # Topology registry: constraint topology keys get a compact slot k, and
        # each distinct label value under that key gets a compact domain index —
        # so domain segment-sums scatter into small dense tables instead of the
        # unbounded dictionary id space (SURVEY §5 long-context note).
        self.topo_key_strings: List[str] = []
        self._topo_slots: Dict[str, int] = {}
        self.topo_value_maps: List[Dict[str, int]] = []
        self.node_rows: Dict[str, int] = {}
        self._row_to_name: Dict[int, str] = {}  # kept in lockstep with node_rows
        self._free_node_rows: List[int] = []
        self.pod_rows: Dict[str, int] = {}  # pod uid -> row
        self._free_pod_rows: List[int] = []
        self._pods_by_node: Dict[str, List[str]] = {}  # node name -> pod uids
        self._pod_owner: Dict[str, str] = {}  # pod uid -> owning node name
        self._n = self.cfg.min_nodes
        self._p = self.cfg.min_pods
        self._alloc_arrays()
        # incremental existing-pod affinity groups (see state/affinity_index)
        from .affinity_index import AffinityIndex

        self.aff = AffinityIndex(self)
        self._device: Optional[DeviceSnapshot] = None
        self._uploaded_numeric_len = -1
        self._dirty_node_rows: set = set()
        self._dirty_pod_rows: set = set()
        self._scatter_bucket: Dict[str, int] = {}
        # affinity-group scatter rows are few (one per dirtied signature) and
        # each carries a [D] count row — a 256 floor would upload megabytes
        # of unchanged tables per cycle
        self._scatter_bucket.setdefault("aff_valid", 8)
        self._numeric_min = 1024  # floor for the numeric side-table pow2 size
        self._shape_changed = True
        # optional node-axis device mesh (parallel/mesh.py): when set, every
        # full upload places node-tier arrays with dim-0 NamedSharding over
        # the mesh and replicates the pod/aff/numeric tables; the scatter
        # paths update the sharded buffers in place (GSPMD keeps the output
        # sharding of a row-scatter into a sharded operand), so steady-state
        # incremental sync never re-replicates the node tier.
        self.mesh = None

    def set_mesh(self, mesh) -> None:
        """Adopt a node-axis mesh for device uploads (ClusterEncoder owns
        the sharding decision so every upload path — full, eager scatter,
        deferred scatter — agrees).  Requires a power-of-two device count:
        the pow-2 tier growth discipline then keeps every node tier
        shard-divisible for free (pow2 ≥ mesh size divides evenly)."""
        n_dev = mesh.devices.size
        if n_dev & (n_dev - 1):
            raise ValueError(
                f"node-axis mesh needs a power-of-two device count, got "
                f"{n_dev} — pow2 tier growth cannot stay shard-divisible")
        if self._n % n_dev:
            # pre-mesh tiers are pow2 ≥ min_nodes(64); only a mesh larger
            # than the tier can fail this — grow to cover it
            self._grow_nodes(n_dev)
        self.mesh = mesh
        self._shape_changed = True  # next upload must (re-)place per shard

    def _puts(self):
        """(put_node, put_other) placement fns for the current mesh."""
        if self.mesh is None:
            return jnp.asarray, jnp.asarray
        import jax
        from ..parallel.mesh import node_sharding, replicate

        repl = replicate(self.mesh)

        def put_node(arr):
            return jax.device_put(arr, node_sharding(self.mesh, arr.ndim))

        def put_other(arr):
            return jax.device_put(arr, repl)

        return put_node, put_other

    # affinity-group arrays live on the index; exposed here so the generic
    # array-group upload machinery (_gather_rows / to_device) reads them by
    # name exactly like the node/pod mirrors
    @property
    def aff_valid(self):
        return self.aff.aff_valid

    @property
    def aff_kind(self):
        return self.aff.aff_kind

    @property
    def aff_weight(self):
        return self.aff.aff_weight

    @property
    def aff_slot(self):
        return self.aff.aff_slot

    @property
    def aff_counts(self):
        return self.aff.aff_counts

    # --- allocation ---------------------------------------------------------

    def _alloc_arrays(self):
        n, p, cfg = self._n, self._p, self.cfg
        r = cfg.num_resource_dims
        self.node_valid = np.zeros(n, dtype=bool)
        self.node_name_ids = np.full(n, MISSING, dtype=np.int32)
        self.allocatable = np.zeros((n, r), dtype=np.int32)
        self.requested = np.zeros((n, r), dtype=np.int32)
        self.non_zero_requested = np.zeros((n, 2), dtype=np.int32)
        self.node_label_keys = np.full((n, cfg.label_cap), MISSING, dtype=np.int32)
        self.node_label_vals = np.full((n, cfg.label_cap), MISSING, dtype=np.int32)
        self.node_label_num = np.full((n, cfg.label_cap), np.nan, dtype=np.float32)
        self.node_topo = np.full((n, cfg.topo_key_cap), MISSING, dtype=np.int32)
        self.taint_keys = np.full((n, cfg.taint_cap), MISSING, dtype=np.int32)
        self.taint_vals = np.full((n, cfg.taint_cap), MISSING, dtype=np.int32)
        self.taint_effects = np.full((n, cfg.taint_cap), MISSING, dtype=np.int32)
        self.ports = np.full((n, cfg.port_cap), MISSING, dtype=np.int32)
        self.ports_ip = np.full((n, cfg.port_cap), MISSING, dtype=np.int32)
        self.image_ids = np.full((n, cfg.image_cap), MISSING, dtype=np.int32)
        self.image_sizes = np.zeros((n, cfg.image_cap), dtype=np.float32)
        self.unschedulable = np.zeros(n, dtype=bool)
        # ready defaults True: a free/unencoded row is gated by node_valid,
        # and encode_node always rewrites the bit from live conditions
        self.node_ready = np.ones(n, dtype=bool)
        # claim planes are owned by the DRA index, not encode_node: a node
        # re-encode must not clobber inventory written from ResourceSlices
        self.claim_capacity = np.zeros(n, dtype=np.int32)
        self.claim_allocated = np.zeros(n, dtype=np.int32)
        self.pod_valid = np.zeros(p, dtype=bool)
        self.pod_node = np.full(p, MISSING, dtype=np.int32)
        self.pod_ns = np.full(p, MISSING, dtype=np.int32)
        self.pod_label_keys = np.full((p, cfg.pod_label_cap), MISSING, dtype=np.int32)
        self.pod_label_vals = np.full((p, cfg.pod_label_cap), MISSING, dtype=np.int32)
        self.pod_priority = np.zeros(p, dtype=np.int32)
        self.pod_request = np.zeros((p, r), dtype=np.int32)
        self.pod_non_zero = np.zeros((p, 2), dtype=np.int32)

    def _grow_nodes(self, need: int):
        old = {k: getattr(self, k).copy() for k in _NODE_ARRAYS}
        self._n = _pow2(need, self._n * 2)
        p_save = {k: getattr(self, k) for k in _POD_ARRAYS}
        self._alloc_arrays()
        for k, v in old.items():
            getattr(self, k)[: v.shape[0]] = v
        for k, v in p_save.items():
            setattr(self, k, v)
        self._shape_changed = True

    def _grow_pods(self, need: int):
        old = {k: getattr(self, k).copy() for k in _POD_ARRAYS}
        self._p = _pow2(need, self._p * 2)
        n_save = {k: getattr(self, k) for k in _NODE_ARRAYS}
        self._alloc_arrays()
        for k, v in old.items():
            getattr(self, k)[: v.shape[0]] = v
        for k, v in n_save.items():
            setattr(self, k, v)
        self._shape_changed = True

    def reserve(self, n_nodes: int = 0, n_pods: int = 0, n_ids: int = 0):
        """Pre-size tiers so mid-run growth (a full recompile of every program
        over the snapshot) never lands inside a measured window.  Callers that
        know the run's extent (perf harness: sum of createNodes/createPods
        counts) reserve up front; growth remains correct either way."""
        if n_nodes > self._n:
            self._grow_nodes(n_nodes)
        if n_pods > self._p:
            self._grow_pods(n_pods)
        if n_ids:
            # the numeric side-table's pow2 size is part of every fused
            # program's shape: crossing a pow2 boundary mid-run recompiles
            self._numeric_min = max(self._numeric_min, _pow2(n_ids, 1024))

    # --- resource helpers ----------------------------------------------------

    def _resource_units(self, r: Resource, ceil: bool) -> List[int]:
        for name in r.scalar_resources:
            if name not in self.extended_index:
                idx = units.NUM_BASE_DIMS + len(self.extended_index)
                if idx >= self.cfg.num_resource_dims:
                    raise EncodingCapacityError(
                        f"too many extended resources (cap "
                        f"{self.cfg.extended_resource_cap}): {name}"
                    )
                self.extended_index[name] = idx
        return units.resource_to_units(
            r, self.cfg.num_resource_dims, self.extended_index, ceil=ceil
        )

    def pod_request_units(self, pod: v1.Pod) -> np.ndarray:
        """i32[R] request vector for a pod (pods dim = 1)."""
        r = compute_pod_resource_request(pod)
        vec = self._resource_units(r, ceil=True)
        vec[units.DIM_PODS] = 1
        return np.asarray(vec, dtype=np.int32)

    def pod_non_zero_units(self, pod: v1.Pod) -> np.ndarray:
        r = compute_pod_resource_request_non_zero(pod)
        vec = self._resource_units(r, ceil=True)
        return np.asarray([vec[units.DIM_CPU], vec[units.DIM_MEMORY]], dtype=np.int32)

    # --- label encoding ------------------------------------------------------

    def _encode_labels(self, labels: Dict[str, str], cap: int, what: str):
        if len(labels) > cap:
            raise EncodingCapacityError(
                f"{what} has {len(labels)} labels > cap {cap}; raise EncodingConfig"
            )
        keys = np.full(cap, MISSING, dtype=np.int32)
        vals = np.full(cap, MISSING, dtype=np.int32)
        for i, (k, val) in enumerate(labels.items()):
            keys[i] = self.dic.intern(k)
            vals[i] = self.dic.intern(val)
        return keys, vals

    def _encode_label_nums(self, labels: Dict[str, str], cap: int) -> np.ndarray:
        """f32[cap] Atoi-parity numeric parse of each label VALUE, NaN otherwise.

        Precomputed per node so Gt/Lt selector evaluation is a broadcast
        compare against this plane instead of a per-(selector, node, slot)
        dictionary-table gather (serial on TPU)."""
        nums = np.full(cap, np.nan, dtype=np.float32)
        for i, val in enumerate(labels.values()):
            nums[i] = _parse_numeric(val)
        return nums

    # --- node encoding -------------------------------------------------------

    def encode_node(self, info: NodeInfo) -> int:
        """(Re-)encode one NodeInfo into its row; returns the row index."""
        name = info.node_name
        row = self.node_rows.get(name)
        if row is None:
            if self._free_node_rows:
                row = self._free_node_rows.pop()
            else:
                row = len(self.node_rows)
                if row >= self._n:
                    self._grow_nodes(row + 1)
            self.node_rows[name] = row
            self._row_to_name[row] = name
        node = info.node
        cfg = self.cfg
        labels = dict(node.metadata.labels)
        labels.setdefault(HOSTNAME_LABEL, name)
        labels["metadata.name"] = name
        lk, lv = self._encode_labels(labels, cfg.label_cap, f"node {name}")
        self.node_label_keys[row] = lk
        self.node_label_vals[row] = lv
        self.node_label_num[row] = self._encode_label_nums(labels, cfg.label_cap)
        for k, key in enumerate(self.topo_key_strings):
            val = labels.get(key)
            self.node_topo[row, k] = (
                MISSING if val is None else self._domain_index(k, val)
            )

        self.node_valid[row] = True
        self.node_name_ids[row] = self.dic.intern(name)
        self.unschedulable[row] = node.spec.unschedulable
        self.node_ready[row] = v1.node_is_ready(node)
        self.allocatable[row] = self._resource_units(info.allocatable, ceil=False)
        self.requested[row] = self._resource_units(info.requested, ceil=True)
        # pods dimension of "requested" = live pod count
        self.requested[row, units.DIM_PODS] = len(info.pods)
        nz = self._resource_units(info.non_zero_requested, ceil=True)
        self.non_zero_requested[row] = (nz[units.DIM_CPU], nz[units.DIM_MEMORY])

        if len(node.spec.taints) > cfg.taint_cap:
            raise EncodingCapacityError(f"node {name}: too many taints")
        self.taint_keys[row] = MISSING
        self.taint_vals[row] = MISSING
        self.taint_effects[row] = MISSING
        for i, t in enumerate(node.spec.taints):
            self.taint_keys[row, i] = self.dic.intern(t.key)
            self.taint_vals[row, i] = self.dic.intern(t.value)
            self.taint_effects[row, i] = EFFECT_CODE.get(t.effect, 0)

        ports = sorted(
            {(_PROTO_CODE.get(proto, 0) * 65536 + port, self.dic.intern(ip))
             for (ip, proto, port) in info.used_ports}
        )
        if len(ports) > cfg.port_cap:
            raise EncodingCapacityError(f"node {name}: too many host ports")
        self.ports[row] = MISSING
        self.ports_ip[row] = MISSING
        for i, (code, ip_id) in enumerate(ports):
            self.ports[row, i] = code
            self.ports_ip[row, i] = ip_id

        self.image_ids[row] = MISSING
        self.image_sizes[row] = 0.0
        img_items = list(info.image_states.items())
        if len(img_items) > cfg.image_cap:
            # images beyond the cap only weaken ImageLocality scoring; keep largest
            img_items.sort(key=lambda kv: -kv[1])
            img_items = img_items[: cfg.image_cap]
        for i, (img, size) in enumerate(img_items):
            self.image_ids[row, i] = self.dic.intern(img)
            self.image_sizes[row, i] = float(size)

        self._dirty_node_rows.add(row)
        return row

    # --- topology registry ---------------------------------------------------

    def _domain_index(self, slot: int, value: str) -> int:
        m = self.topo_value_maps[slot]
        idx = m.get(value)
        if idx is None:
            idx = len(m)
            m[value] = idx
        return idx

    def topo_slot(self, key: str) -> int:
        """Slot of topology key, registering (and backfilling all nodes) on first
        use. Called at PodBatch compile time for spread/affinity topology keys."""
        slot = self._topo_slots.get(key)
        if slot is not None:
            return slot
        slot = len(self.topo_key_strings)
        if slot >= self.cfg.topo_key_cap:
            raise EncodingCapacityError(
                f"too many topology keys (cap {self.cfg.topo_key_cap}): {key}"
            )
        self._topo_slots[key] = slot
        self.topo_key_strings.append(key)
        self.topo_value_maps.append({})
        key_id = self.dic.lookup(key)
        for name, row in self.node_rows.items():
            val_id = MISSING
            if key_id != MISSING:
                hit = np.where(self.node_label_keys[row] == key_id)[0]
                if hit.size:
                    val_id = int(self.node_label_vals[row, hit[0]])
            self.node_topo[row, slot] = (
                MISSING if val_id == MISSING
                else self._domain_index(slot, self.dic.string(val_id))
            )
            self._dirty_node_rows.add(row)
        return slot

    @property
    def domain_cap(self) -> int:
        """Power-of-two bound on compact domain indices across all topo keys."""
        return _pow2(max((len(m) for m in self.topo_value_maps), default=1), 8)

    def remove_node(self, name: str):
        row = self.node_rows.pop(name, None)
        if row is None:
            return
        self._row_to_name.pop(row, None)
        self.node_valid[row] = False
        # claim planes persist across encode_node (the DRA index owns them),
        # so a freed row must drop its inventory here or the next node to
        # reuse the row would inherit the dead host's chips
        self.claim_capacity[row] = 0
        self.claim_allocated[row] = 0
        self._free_node_rows.append(row)
        self._dirty_node_rows.add(row)
        for uid in self._pods_by_node.pop(name, []):
            if self._pod_owner.get(uid) == name:
                self._remove_pod_row(uid)

    # --- DRA claim planes (dra/index.py is the writer) -----------------------

    def set_claim_row(self, name: str, capacity: int, allocated: int) -> bool:
        """Write a node's claim planes by NAME; False when the node has no
        row yet (the index retries on its next flush once the node encodes).
        No-change writes skip the dirty mark so a steady-state flush costs
        nothing on the scatter path."""
        row = self.node_rows.get(name)
        if row is None:
            return False
        if (self.claim_capacity[row] == capacity
                and self.claim_allocated[row] == allocated):
            return True
        self.claim_capacity[row] = capacity
        self.claim_allocated[row] = allocated
        self._dirty_node_rows.add(row)
        return True

    # --- scheduled-pod encoding ---------------------------------------------

    def _encode_pod(self, pod: v1.Pod, node_row: int) -> int:
        uid = pod.uid
        row = self.pod_rows.get(uid)
        if row is None:
            if self._free_pod_rows:
                row = self._free_pod_rows.pop()
            else:
                row = len(self.pod_rows)
                if row >= self._p:
                    self._grow_pods(row + 1)
            self.pod_rows[uid] = row
        cfg = self.cfg
        lk, lv = self._encode_labels(
            pod.metadata.labels, cfg.pod_label_cap, f"pod {pod.key()}"
        )
        ns = self.dic.intern(pod.namespace)
        req = self.pod_request_units(pod)
        nz = self.pod_non_zero_units(pod)
        # Skip the dirty mark when nothing changed: sync() re-encodes EVERY
        # pod of a changed node, so without this a bind dirties all of the
        # node's (unchanged) pods and the scatter bucket grows with cluster
        # fill — each pow2 crossing recompiles the whole fused cycle program.
        if (
            self.pod_valid[row]
            and self.pod_node[row] == node_row
            and self.pod_ns[row] == ns
            and self.pod_priority[row] == pod.spec.priority
            and np.array_equal(self.pod_label_keys[row], lk)
            and np.array_equal(self.pod_label_vals[row], lv)
            and np.array_equal(self.pod_request[row], req)
            and np.array_equal(self.pod_non_zero[row], nz)
        ):
            return row
        self.pod_label_keys[row] = lk
        self.pod_label_vals[row] = lv
        self.pod_valid[row] = True
        self.pod_node[row] = node_row
        self.pod_ns[row] = ns
        self.pod_priority[row] = pod.spec.priority
        self.pod_request[row] = req
        self.pod_non_zero[row] = nz
        self._dirty_pod_rows.add(row)
        return row

    def _remove_pod_row(self, uid: str):
        row = self.pod_rows.pop(uid, None)
        self._pod_owner.pop(uid, None)
        self.aff.remove_pod(uid)
        if row is None:
            return
        self.pod_valid[row] = False
        self._free_pod_rows.append(row)
        self._dirty_pod_rows.add(row)

    # --- snapshot sync -------------------------------------------------------

    def sync(self, snapshot: Snapshot, changed_nodes: Sequence[str]):
        """Apply a cache snapshot refresh: re-encode changed nodes + their pods.

        Removal is ownership-gated: a pod that MOVED between two changed nodes
        may be re-encoded under its new node before or after its old node is
        processed; only the current owner may free the row.
        """
        for name in changed_nodes:
            info = snapshot.node_info_map.get(name)
            if info is None:
                self.remove_node(name)
                continue
            row = self.encode_node(info)
            new_uids = {pi.pod.uid for pi in info.pods}
            for uid in self._pods_by_node.get(name, []):
                if uid not in new_uids and self._pod_owner.get(uid) == name:
                    self._remove_pod_row(uid)
            for pi in info.pods:
                self._encode_pod(pi.pod, row)
                self._pod_owner[pi.pod.uid] = name
                # incremental affinity-table delta: O(changed pods), replaces
                # the per-cycle host_prepare walk over ALL scheduled pods
                self.aff.set_pod(pi, row)
            self._pods_by_node[name] = list(new_uids)

    def full_sync(self, snapshot: Snapshot):
        self.sync(snapshot, [n.node_name for n in snapshot.node_info_list])

    # --- device upload -------------------------------------------------------

    def force_full_next(self) -> None:
        """Make the next to_device_deferred take the full-upload path
        (upd=None).  Warmups use this to pre-trace the fused program's
        None-scatter pytree variant against the measured window's host-aux
        structure — a mid-window dirty burst (batch binds + churn events
        exceeding the scatter bucket) otherwise pays that re-trace as an
        in-window compile (measured 0.13s + one poisoned 256-attempt cycle
        in MixedChurn)."""
        self._force_full_once = True

    def to_device_deferred(self, consume_force: bool = True):
        """Like to_device, but returns the row-scatter payload instead of
        executing it: ``(dsnap, upd)`` where ``upd`` is None (full upload
        happened; dsnap is current) or a PendingScatter the caller applies
        INSIDE its own jitted program via ``apply_scatter`` — so a steady
        cycle issues ONE device program total.  On the tunnel-attached TPU
        each separate program execution pays a ~100ms pacing round, which
        made the eager two-scatter + numeric-upload path 3× slower than the
        fused compute itself.  Caller MUST ``commit_device()`` the updated
        DeviceSnapshot returned by its program (the arrays are async —
        committing the futures immediately is safe).

        ``consume_force=False`` is the overlapped-sync background build:
        it must neither honor nor clear ``force_full_next()`` — a caller
        may set the flag while the thread runs, and only the DISPATCH-time
        build may consume it (the dispatch reuse gate re-checks the flag,
        so a flag set after the background build still forces the full
        path there)."""
        if consume_force and getattr(self, "_force_full_once", False):
            self._force_full_once = False
            return self.to_device(force_full=True), None
        # Small-cluster fast path: when the node tier is small (≤1024 rows) a
        # typical batch's dirty set spans a sizeable fraction of it, so the
        # row-scatter payload approaches the whole-buffer upload — take the
        # precompiled full-upload path instead, which also compiles the
        # fused cycle program WITHOUT the in-program scatter (one variant,
        # no per-size recompiles: the decision depends only on the tier
        # size, which presize fixes up front).  A 500-node cluster then
        # stops paying the 5k-sized scatter-bucket dispatch overhead.
        if self._n <= _SMALL_NODE_TIER:
            return self.to_device(force_full=True), None
        numeric, use_scatter = self._upload_gate()
        # A dirty burst past the scatter bucket (preemption victim storms)
        # takes the FULL-upload path — already compiled — rather than
        # growing the bucket: bucket growth would both recompile the whole
        # fused program (~10s) AND bloat every later steady cycle's payload
        # (a 1024-row floor measured ~130ms/cycle of upload on the tunnel).
        bucket = self._scatter_bucket.get("node_valid", 256)
        pbucket = self._scatter_bucket.get("pod_valid", 256)
        abucket = self._scatter_bucket.get("aff_valid", 8)
        force_full = (
            len(self._dirty_node_rows) > bucket
            or len(self._dirty_pod_rows) > pbucket
            or len(self.aff.dirty) > abucket
        )
        if not use_scatter or force_full:
            # force_full bypasses to_device's own scatter gate: a burst must
            # take the precompiled whole-buffer device_put, not grow a fresh
            # scatter shape (a mid-run compile stall)
            return self.to_device(force_full=force_full), None
        d = self._device
        # Always emit BOTH groups and the numeric table: a None group or an
        # elided numeric would be a different pytree structure → a fresh
        # trace+compile of the whole fused program the first time it occurs
        # (e.g. the first cycle where no node changed).  A no-op group writes
        # row 0 with its own current values; numeric is ≤128KB.
        upd = PendingScatter(
            node_rows=self._gather_rows(_NODE_ARRAYS, self._dirty_node_rows),
            pod_rows=self._gather_rows(_POD_ARRAYS, self._dirty_pod_rows),
            aff_rows=self._gather_rows(_AFF_ARRAYS, self.aff.dirty),
            numeric=numeric,
        )
        self._uploaded_numeric_len = len(self.dic)
        self._dirty_node_rows.clear()
        self._dirty_pod_rows.clear()
        self.aff.dirty.clear()
        return d, upd

    def _upload_gate(self):
        """(padded numeric table, use_scatter) — the one place that decides
        between a full upload and row-scatters, shared by both upload paths so
        the threshold and padding rules can't drift apart."""
        numeric = self.dic.numeric_table(min_size=self._numeric_min)
        n_num = _pow2(numeric.shape[0], self._numeric_min)
        numeric = np.pad(numeric, (0, n_num - numeric.shape[0]), constant_values=np.nan)
        dirty_frac = (
            (len(self._dirty_node_rows) + len(self._dirty_pod_rows))
            / max(self._n + self._p, 1)
        )
        use_scatter = (
            self._device is not None
            and not self._shape_changed
            and self._device.numeric.shape[0] == n_num
            and dirty_frac < 0.5
        )
        return numeric, use_scatter

    def _gather_rows(self, names: List[str], dirty: set):
        """(padded row indices, per-array value rows) for one array group.

        The pad length is a sticky pow-2 HIGH-WATER mark with a 256 floor:
        the scatter is now traced into the caller's fused program, so a new
        pad length recompiles the WHOLE cycle program (~10s) — the floor
        makes the warmup cycle and every steady cycle share one shape, and
        growth beyond it compiles O(log) times per run.  An empty dirty set
        yields a no-op payload (scatter row 0 onto itself) at the same shape."""
        rows = np.fromiter(dirty, dtype=np.int32, count=len(dirty))
        rows.sort()
        floor = self._scatter_bucket.get(names[0], 256)
        k = max(_pow2(max(rows.shape[0], 1), 32), floor)
        self._scatter_bucket[names[0]] = k
        padded = np.full(k, rows[0] if rows.shape[0] else 0, dtype=np.int32)
        padded[: rows.shape[0]] = rows
        vals = tuple(getattr(self, k_)[padded] for k_ in names)
        return (padded, vals)

    def has_dirty(self) -> bool:
        """Any mirror rows dirtied since the last upload consumed them."""
        return bool(self._dirty_node_rows or self._dirty_pod_rows
                    or self.aff.dirty)

    def capture_dirty(self):
        """Copies of the dirty-row sets an imminent to_device_deferred will
        consume — the overlapped-sync path stashes them so a DISCARDED
        payload can be undone (restore_dirty)."""
        return (set(self._dirty_node_rows), set(self._dirty_pod_rows),
                set(self.aff.dirty))

    def restore_dirty(self, saved) -> None:
        """Re-mark rows whose to_device_deferred payload the caller
        discarded without executing (the overlapped-sync fallback/merge
        paths): the rows never reached the device, so they must ride the
        next payload.  The numeric-table high-water mark is invalidated too
        — to_device_deferred stamped it as uploaded when it built the now-
        discarded payload."""
        n, p, a = saved
        self._dirty_node_rows |= n
        self._dirty_pod_rows |= p
        self.aff.dirty |= a
        self._uploaded_numeric_len = -1

    def commit_device(self, dsnap: DeviceSnapshot):
        """Adopt a program-updated DeviceSnapshot as the current device state."""
        self._device = dsnap

    def to_device(self, sharding=None, force_full: bool = False) -> DeviceSnapshot:
        """Upload: full device_put when shapes changed or dirt is large, else
        row-scatter updates into the existing buffers.

        ``sharding``: a jax.sharding.Mesh adopts node-axis sharding for THIS
        and every later upload (equivalent to set_mesh); any other
        jax.sharding.Sharding is applied uniformly to all arrays (the raw
        escape hatch).  With a mesh installed, node-tier arrays get dim-0
        NamedSharding and everything else replicates."""
        import jax
        from jax.sharding import Mesh

        if isinstance(sharding, Mesh):
            if sharding is not self.mesh:
                self.set_mesh(sharding)
            sharding = None
        numeric, use_scatter = self._upload_gate()
        if force_full:
            use_scatter = False
        numeric_stale = len(self.dic) != self._uploaded_numeric_len
        if not use_scatter:
            if sharding is not None:
                put_node = put_other = (lambda x: jax.device_put(x, sharding))
            else:
                put_node, put_other = self._puts()
            node_set = set(_NODE_ARRAYS)
            self._device = DeviceSnapshot(
                **{k: (put_node if k in node_set else put_other)(
                    getattr(self, k))
                   for k in _NODE_ARRAYS + _POD_ARRAYS + _AFF_ARRAYS},
                numeric=put_other(numeric),
            )
        else:
            d = self._device
            upd = self._scatter_group(d, _NODE_ARRAYS, self._dirty_node_rows)
            upd.update(self._scatter_group(d, _POD_ARRAYS, self._dirty_pod_rows))
            upd.update(self._scatter_group(d, _AFF_ARRAYS, self.aff.dirty))
            # ids interned since the last upload need a fresh numeric side-table
            # (same padded size ⇒ same shapes; the table is small)
            num = jnp.asarray(numeric) if numeric_stale else d.numeric
            self._device = DeviceSnapshot(**upd, numeric=num)
        self._uploaded_numeric_len = len(self.dic)
        self._dirty_node_rows.clear()
        self._dirty_pod_rows.clear()
        self.aff.dirty.clear()
        self._shape_changed = False
        return self._device

    def _scatter_group(self, d: DeviceSnapshot, names: List[str], dirty: set) -> dict:
        """Scatter dirty rows of one array group into the device buffers.

        Shape discipline: the row-index vector is padded to a power-of-two
        length (min 32) by REPEATING the first dirty row — `.set` scatters of
        identical values are idempotent, so duplicates are harmless — and all
        arrays of the group go through ONE jitted donated-args updater.  Steady
        state therefore compiles exactly once per pow-2 dirty-count bucket
        (O(log n) executables over a run) instead of ~23 fresh executables per
        cycle, which round 2's profile showed was 90% of bench wall time.
        """
        if not dirty:
            return {k: getattr(d, k) for k in names}
        rows = np.fromiter(dirty, dtype=np.int32, count=len(dirty))
        rows.sort()
        k = _pow2(rows.shape[0], 32)
        padded = np.full(k, rows[0], dtype=np.int32)
        padded[: rows.shape[0]] = rows
        vals = tuple(getattr(self, k_)[padded] for k_ in names)
        new = _scatter_rows(tuple(getattr(d, k_) for k_ in names), padded, vals)
        return dict(zip(names, new))

    def row_to_name(self) -> Dict[int, str]:
        """Live row → node-name view (maintained incrementally; do not mutate)."""
        return self._row_to_name


from functools import partial as _partial


@jax.jit
def _scatter_rows(arrays, rows, vals):
    """Fused row-scatter for a whole array group.

    NOT donated: the pipelined scheduler keeps the previous cycle's
    DeviceSnapshot alive for its deferred binding cycle (diagnosis /
    preemption read it), so the old buffers must survive this update.  The
    full-copy cost this forgoes is ~50MB of HBM traffic (~0.06ms) per cycle.
    """
    return tuple(a.at[rows].set(v) for a, v in zip(arrays, vals))


_NODE_ARRAYS = [
    "node_valid", "node_name_ids", "allocatable", "requested", "non_zero_requested",
    "node_label_keys", "node_label_vals", "node_label_num", "node_topo",
    "taint_keys", "taint_vals",
    "taint_effects", "ports", "ports_ip", "image_ids", "image_sizes", "unschedulable",
    "node_ready", "claim_capacity", "claim_allocated",
]
_POD_ARRAYS = [
    "pod_valid", "pod_node", "pod_ns", "pod_label_keys", "pod_label_vals",
    "pod_priority", "pod_request", "pod_non_zero",
]
_AFF_ARRAYS = [
    "aff_valid", "aff_kind", "aff_weight", "aff_slot", "aff_counts",
]

# Public alias for the node array group: the whatif fork engine
# (whatif/fork.py) captures scratch-encoded template rows and re-activates
# them inside forked DeviceSnapshots aligned with exactly this list — a
# new node-plane array added above is automatically carried by node-add
# forks (and by the scatter upload paths) with no further wiring.
NODE_ARRAYS = _NODE_ARRAYS

# node tiers at or below this take the always-full upload path in
# to_device_deferred (see the small-cluster note there)
_SMALL_NODE_TIER = 1024
