"""Resource-dimension layout and unit scaling for device tensors.

Device resource tensors are **int32** in scaled units so fit comparisons are exact
and TPU-native (no float rounding, no emulated int64):

  dim 0: cpu                milli-cores   (int32 max ≈ 2.1M cores)
  dim 1: memory             KiB           (int32 max = 2 TiB per node)
  dim 2: ephemeral-storage  MiB           (int32 max = 2 PiB per node)
  dim 3: pods               count
  dims 4..: extended/scalar resources, unit = 1 (dictionary-assigned slots)

Pod **requests are ceil'd** to the unit and node **allocatable is floor'd**, so the
device filter is conservative: it never admits a pod the exact-integer host oracle
would reject (it can reject a fit within one unit of the boundary — sub-KiB memory
granularity does not occur in practice).

Reference semantics being encoded: the int64 Resource vector of
pkg/scheduler/framework/types.go:416-425.
"""

from __future__ import annotations

from ..api import resource as res

# Base dimension indices.
DIM_CPU = 0
DIM_MEMORY = 1
DIM_EPHEMERAL = 2
DIM_PODS = 3
NUM_BASE_DIMS = 4

_KI = 1024
_MI = 1024 * 1024


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def pow2_round_up(n: int, minimum: int = 1) -> int:
    """Shared device-shape discipline: capacities grow by doubling so XLA
    recompiles O(log n) times (used by encoding, selector compilation, batches)."""
    p = max(minimum, 1)
    while p < n:
        p *= 2
    return p


def resource_to_units(r: res.Resource, num_dims: int, extended_index, ceil: bool):
    """Resource → list[int] of length num_dims in scaled units.

    extended_index: mapping resource-name → dim index (≥ NUM_BASE_DIMS) for scalar
    resources; unknown scalar resources raise KeyError (callers register first).
    """
    div = _ceil_div if ceil else lambda a, b: a // b
    out = [0] * num_dims
    out[DIM_CPU] = r.milli_cpu
    out[DIM_MEMORY] = div(r.memory, _KI)
    out[DIM_EPHEMERAL] = div(r.ephemeral_storage, _MI)
    out[DIM_PODS] = r.allowed_pod_number
    for name, v in r.scalar_resources.items():
        out[extended_index[name]] = v
    return out


def request_to_units(r: res.Resource, num_dims: int, extended_index):
    return resource_to_units(r, num_dims, extended_index, ceil=True)


def allocatable_to_units(r: res.Resource, num_dims: int, extended_index):
    return resource_to_units(r, num_dims, extended_index, ceil=False)
