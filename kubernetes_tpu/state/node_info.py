"""Host-side per-node aggregate state.

Reference: ``framework.NodeInfo`` (pkg/scheduler/framework/types.go:365-405) — Pods,
PodsWithAffinity, PodsWithRequiredAntiAffinity, UsedPorts, Requested /
NonZeroRequested / Allocatable resource vectors, ImageStates, PVCRefCounts, and a
Generation for O(changed) snapshotting. This is the authoritative host mirror that
feeds the device encoder; the sequential parity oracle also reads it directly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..api import objects as v1
from ..api.resource import (
    Resource,
    compute_pod_resource_request,
    compute_pod_resource_request_non_zero,
)

# Global generation counter (reference types.go nextGeneration; single-writer cache).
_generation = 0


def next_generation() -> int:
    global _generation
    _generation += 1
    return _generation


@dataclass
class PodInfo:
    """Pod plus pre-parsed affinity terms (reference types.go PodInfo)."""

    pod: v1.Pod
    required_affinity_terms: List[v1.PodAffinityTerm] = field(default_factory=list)
    required_anti_affinity_terms: List[v1.PodAffinityTerm] = field(default_factory=list)
    preferred_affinity_terms: List[v1.WeightedPodAffinityTerm] = field(default_factory=list)
    preferred_anti_affinity_terms: List[v1.WeightedPodAffinityTerm] = field(default_factory=list)

    @classmethod
    def of(cls, pod: v1.Pod) -> "PodInfo":
        info = cls(pod=pod)
        aff = pod.spec.affinity
        if aff is not None:
            if aff.pod_affinity is not None:
                info.required_affinity_terms = list(aff.pod_affinity.required)
                info.preferred_affinity_terms = list(aff.pod_affinity.preferred)
            if aff.pod_anti_affinity is not None:
                info.required_anti_affinity_terms = list(aff.pod_anti_affinity.required)
                info.preferred_anti_affinity_terms = list(aff.pod_anti_affinity.preferred)
        return info

    def has_affinity_constraints(self) -> bool:
        return bool(self.required_affinity_terms or self.required_anti_affinity_terms
                    or self.preferred_affinity_terms or self.preferred_anti_affinity_terms)


def _pod_host_ports(pod: v1.Pod) -> Set[Tuple[str, str, int]]:
    ports = set()
    for c in pod.spec.containers:
        for p in c.ports:
            if p.host_port > 0:
                ip = p.host_ip or "0.0.0.0"
                ports.add((ip, p.protocol or "TCP", p.host_port))
    return ports


def host_ports_conflict(a: Set[Tuple[str, str, int]], b: Set[Tuple[str, str, int]]) -> bool:
    """Reference: framework.HostPortInfo — 0.0.0.0 conflicts with any IP on same
    (proto, port); distinct concrete IPs don't conflict."""
    for ip1, proto1, port1 in a:
        for ip2, proto2, port2 in b:
            if proto1 == proto2 and port1 == port2:
                if ip1 == "0.0.0.0" or ip2 == "0.0.0.0" or ip1 == ip2:
                    return True
    return False


@dataclass
class NodeInfo:
    node: Optional[v1.Node] = None
    pods: List[PodInfo] = field(default_factory=list)
    pods_with_affinity: List[PodInfo] = field(default_factory=list)
    pods_with_required_anti_affinity: List[PodInfo] = field(default_factory=list)
    requested: Resource = field(default_factory=Resource)
    non_zero_requested: Resource = field(default_factory=Resource)
    allocatable: Resource = field(default_factory=Resource)
    used_ports: Set[Tuple[str, str, int]] = field(default_factory=set)
    image_states: Dict[str, int] = field(default_factory=dict)  # image name -> bytes
    pvc_ref_counts: Dict[str, int] = field(default_factory=dict)  # ns/name -> count
    generation: int = 0

    @classmethod
    def of(cls, node: v1.Node, pods: List[v1.Pod] = ()) -> "NodeInfo":
        info = cls()
        info.set_node(node)
        for p in pods:
            info.add_pod(p)
        return info

    def set_node(self, node: v1.Node) -> None:
        self.node = node
        self.allocatable = Resource.from_resource_list(node.status.allocatable)
        self.image_states = {
            name: img.size_bytes
            for img in node.status.images
            for name in img.names
        }
        self.generation = next_generation()

    def add_pod(self, pod: v1.Pod) -> None:
        self.add_pod_info(PodInfo.of(pod))

    def add_pod_info(self, pi: PodInfo) -> None:
        self.pods.append(pi)
        if pi.has_affinity_constraints():
            self.pods_with_affinity.append(pi)
        if pi.required_anti_affinity_terms:
            self.pods_with_required_anti_affinity.append(pi)
        self.requested.add(compute_pod_resource_request(pi.pod))
        self.non_zero_requested.add(compute_pod_resource_request_non_zero(pi.pod))
        self.used_ports |= _pod_host_ports(pi.pod)
        for vol in pi.pod.spec.volumes:
            if vol.pvc_name:
                key = f"{pi.pod.namespace}/{vol.pvc_name}"
                self.pvc_ref_counts[key] = self.pvc_ref_counts.get(key, 0) + 1
        self.generation = next_generation()

    def remove_pod(self, pod: v1.Pod) -> bool:
        for i, pi in enumerate(self.pods):
            if pi.pod.uid == pod.uid:
                del self.pods[i]
                self.pods_with_affinity = [
                    p for p in self.pods_with_affinity if p.pod.uid != pod.uid
                ]
                self.pods_with_required_anti_affinity = [
                    p for p in self.pods_with_required_anti_affinity if p.pod.uid != pod.uid
                ]
                self.requested.sub(compute_pod_resource_request(pi.pod))
                self.non_zero_requested.sub(compute_pod_resource_request_non_zero(pi.pod))
                # Rebuild ports (another pod may share a (proto, port) on another IP).
                self.used_ports = set()
                for q in self.pods:
                    self.used_ports |= _pod_host_ports(q.pod)
                for vol in pi.pod.spec.volumes:
                    if vol.pvc_name:
                        key = f"{pi.pod.namespace}/{vol.pvc_name}"
                        n = self.pvc_ref_counts.get(key, 0) - 1
                        if n <= 0:
                            self.pvc_ref_counts.pop(key, None)
                        else:
                            self.pvc_ref_counts[key] = n
                self.generation = next_generation()
                return True
        return False

    @property
    def node_name(self) -> str:
        return self.node.metadata.name if self.node else ""

    def clone(self) -> "NodeInfo":
        c = NodeInfo(
            node=self.node,
            pods=list(self.pods),
            pods_with_affinity=list(self.pods_with_affinity),
            pods_with_required_anti_affinity=list(self.pods_with_required_anti_affinity),
            requested=self.requested.clone(),
            non_zero_requested=self.non_zero_requested.clone(),
            allocatable=self.allocatable.clone(),
            used_ports=set(self.used_ports),
            image_states=dict(self.image_states),
            pvc_ref_counts=dict(self.pvc_ref_counts),
            generation=self.generation,
        )
        return c
