"""Incremental device-resident affinity state (the existing-pod side of
InterPodAffinity).

The plugin's ``host_prepare`` used to rebuild its per-signature topology
count tables by walking the snapshot's HavePodsWith(Required)AffinityList on
EVERY cycle — O(all scheduled pods with affinity terms), the measured host
bottleneck of the 5k-node anti-affinity suite, growing as the run scheduled
more pods.  This module maintains the same tables INCREMENTALLY: each
scheduled pod's term contributions are applied once when the pod lands on a
node (assume/bind flow through ``ClusterEncoder.sync``'s changed-node list)
and reverted when it leaves (forget/delete/node-delete), so per-cycle host
work is O(batch delta).  The tables live in encoder-owned numpy mirrors
uploaded by the SAME deferred row-scatter path the node/pod planes ride
(state/encoding.py ``to_device_deferred``), and the [B, N] block/score
planes are expanded ON DEVICE in ``InterPodAffinityPlugin.prepare`` — the
dense planes never cross the host→device link.

Group model (unchanged semantics from the old dedup walk): two terms with
equal ``_term_signature`` match exactly the same target pods, so all owners
of one signature aggregate into ONE count row ``counts[g, domain_value]``
under the term's topology-key slot.  ``kind`` 0 = required-anti BLOCK rows,
1 = SCORE rows (existing required affinity × hardPodAffinityWeight,
preferred ±weight).

A full rebuild (``rebuild``) is retained as the resync/repair path and as
the parity oracle for tests: after any churn, rebuild-from-snapshot must
equal the incrementally maintained arrays bit-for-bit.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api.labels import affinity_term_matches
from .dictionary import MISSING

KIND_BLOCK = 0
KIND_SCORE = 1
# existing pods' REQUIRED affinity terms score via hardPodAffinityWeight —
# stored weight-free (1.0) so the index never depends on a plugin arg
# (profiles may configure different weights over ONE shared index); the
# plugin multiplies at expansion time (a trace-time constant)
KIND_SCORE_REQ = 2

_MATCH_CACHE_CAP = 8192  # (group, pod-identity) memo bound; cleared on overflow


def _pow2(x: int, minimum: int = 8) -> int:
    from . import units

    return units.pow2_round_up(x, minimum)


def _selector_signature(sel) -> Optional[tuple]:
    """Hashable identity of a LabelSelector's match semantics."""
    if sel is None:
        return None
    return (
        tuple(sorted(sel.match_labels.items())),
        tuple(
            (e.key, e.operator, tuple(e.values)) for e in sel.match_expressions
        ),
    )


def _term_signature(term, owner_ns: str) -> tuple:
    """Two terms with equal signatures match exactly the same target pods
    (affinity_term_matches semantics: namespaces list, namespaceSelector, the
    owner-namespace default when both are unset, and the label selector)."""
    if term.namespaces:
        ns_key = ("list", tuple(sorted(term.namespaces)))
        if term.namespace_selector is not None:
            ns_key = ns_key + ("sel", _selector_signature(term.namespace_selector))
    elif term.namespace_selector is not None:
        ns_key = ("sel", _selector_signature(term.namespace_selector))
    else:
        ns_key = ("owner", owner_ns)
    return (term.topology_key, ns_key, _selector_signature(term.label_selector))


class _OwnerStub:
    """Minimal owner-pod stand-in for affinity_term_matches: the namespace
    default is the ONLY owner attribute the match reads, and the signature
    registry guarantees all owners of a group share it."""

    __slots__ = ("namespace",)

    def __init__(self, namespace: str):
        self.namespace = namespace


class AffinityIndex:
    """Registry of deduplicated existing-pod affinity term groups plus their
    incrementally maintained per-domain count tables.

    Owned by ClusterEncoder; the numpy arrays below are uploaded to device as
    the ``_AFF_ARRAYS`` scatter group.  Group rows are sticky (never reused):
    signature-count churn grows G by pow-2 doubling, which recompiles the
    fused programs O(log) times per run, exactly like the node/pod tiers.
    """

    def __init__(self, encoder):
        self.enc = encoder
        self._sig_row: Dict[tuple, int] = {}
        # per-row host metadata (parallel to the device arrays)
        self._row_term: List[object] = []  # representative term
        self._row_owner: List[_OwnerStub] = []
        self._row_total: List[int] = []  # live contribution count
        # uid -> tuple of (group_row, domain_val) contributions
        self._contrib: Dict[str, Tuple[Tuple[int, int], ...]] = {}
        # per-row batch-match memo: (ns, labels-key) -> bool
        self._match_cache: List[Dict[tuple, bool]] = []
        self._g = 8
        self._d = 8
        self.dirty: set = set()
        self._alloc()

    # --- array management -----------------------------------------------------

    def _alloc(self):
        g, d = self._g, self._d
        self.aff_valid = np.zeros(g, dtype=bool)
        self.aff_kind = np.zeros(g, dtype=np.int32)
        self.aff_weight = np.zeros(g, dtype=np.float32)
        self.aff_slot = np.full(g, MISSING, dtype=np.int32)
        self.aff_counts = np.zeros((g, d), dtype=np.float32)

    def _grow(self, g: Optional[int] = None, d: Optional[int] = None):
        old = (self.aff_valid, self.aff_kind, self.aff_weight, self.aff_slot,
               self.aff_counts)
        self._g = _pow2(g, self._g) if g else self._g
        self._d = _pow2(d, self._d) if d else self._d
        self._alloc()
        og = old[0].shape[0]
        self.aff_valid[:og] = old[0]
        self.aff_kind[:og] = old[1]
        self.aff_weight[:og] = old[2]
        self.aff_slot[:og] = old[3]
        self.aff_counts[:og, : old[4].shape[1]] = old[4]
        # a tier shape change invalidates every compiled program over the
        # DeviceSnapshot — same contract as node/pod tier growth
        self.enc._shape_changed = True
        self.dirty.update(range(og))

    @property
    def num_groups(self) -> int:
        return len(self._row_term)

    @property
    def live_groups(self) -> int:
        return sum(1 for t in self._row_total if t > 0)

    # --- group registry -------------------------------------------------------

    def _row_of(self, kind: int, weight: float, term, owner_ns: str) -> int:
        sig = (kind, weight, _term_signature(term, owner_ns))
        row = self._sig_row.get(sig)
        if row is not None:
            return row
        row = len(self._row_term)
        if row >= self._g:
            self._grow(g=row + 1)
        self._sig_row[sig] = row
        self._row_term.append(term)
        self._row_owner.append(_OwnerStub(owner_ns))
        self._row_total.append(0)
        self._match_cache.append({})
        self.aff_valid[row] = True
        self.aff_kind[row] = kind
        self.aff_weight[row] = weight
        slot = self.enc.topo_slot(term.topology_key)
        self.aff_slot[row] = slot
        # Reserve the count-table width for the slot's WHOLE live domain
        # space up front: topo_slot backfills every node at registration, so
        # the value map is already complete — growing lazily per observed
        # contribution instead crossed a pow2 (= full program recompile)
        # whenever a hostname-keyed suite filled new nodes MID-WINDOW
        # (measured two ~2s in-window compiles in the scaled anti suite).
        # Nodes added later (churn) still grow the width O(log) times.
        need = len(self.enc.topo_value_maps[slot])
        if need > self._d:
            self._grow(d=need)
        self.dirty.add(row)
        return row

    # --- incremental maintenance ---------------------------------------------

    def _pod_contributions(self, pi, node_row: int) -> Tuple[Tuple[int, int], ...]:
        """(group_row, domain_val) per term of a scheduled pod on node_row.
        Terms whose topology key is absent on the node contribute nothing
        (same skip as the old walk)."""
        out: List[Tuple[int, int]] = []
        enc = self.enc
        ns = pi.pod.namespace

        def add(term, kind, weight):
            row = self._row_of(kind, weight, term, ns)
            val = int(enc.node_topo[node_row, int(self.aff_slot[row])])
            if val == MISSING:
                return
            out.append((row, val))

        for term in pi.required_anti_affinity_terms:
            add(term, KIND_BLOCK, 0.0)
        for term in pi.required_affinity_terms:
            add(term, KIND_SCORE_REQ, 1.0)
        for wt in pi.preferred_affinity_terms:
            add(wt.pod_affinity_term, KIND_SCORE, float(wt.weight))
        for wt in pi.preferred_anti_affinity_terms:
            add(wt.pod_affinity_term, KIND_SCORE, -float(wt.weight))
        return tuple(out)

    def _apply(self, contribs, sign: int):
        for row, val in contribs:
            if val >= self._d:
                self._grow(d=val + 1)
            self.aff_counts[row, val] += sign
            self._row_total[row] += sign
            self.dirty.add(row)

    def set_pod(self, pi, node_row: int) -> None:
        """(Re-)apply one scheduled pod's contributions (idempotent: the old
        contributions are reverted first, so node-label/topology changes and
        pod moves re-home the counts)."""
        uid = pi.pod.uid
        if not pi.has_affinity_constraints():
            if uid in self._contrib:
                self.remove_pod(uid)
            return
        new = self._pod_contributions(pi, node_row)
        old = self._contrib.get(uid)
        if old == new:
            return
        if old:
            self._apply(old, -1)
        self._apply(new, +1)
        if new:
            self._contrib[uid] = new
        else:
            self._contrib.pop(uid, None)

    def remove_pod(self, uid: str) -> None:
        old = self._contrib.pop(uid, None)
        if old:
            self._apply(old, -1)

    def contributions(self, uid: str) -> Tuple[Tuple[int, int], ...]:
        """A scheduled pod's live (group_row, domain_val) contributions —
        what remove_pod would subtract.  The what-if engine masks exactly
        these cells out of a forked ``aff_counts`` so an affinity-carrying
        victim's fork equals the post-eviction state bit-for-bit."""
        return self._contrib.get(uid, ())

    def rebuild(self, snapshot) -> None:
        """Resync/repair path: recompute every count from the snapshot's
        sparse affinity lists into the SAME registry rows (registry stays
        sticky so device shapes and row meanings are stable).  Also the
        parity oracle for the incremental path."""
        self.aff_counts[:] = 0.0
        for i in range(len(self._row_total)):
            self._row_total[i] = 0
        self._contrib.clear()
        self.dirty.update(range(self.num_groups))
        enc = self.enc
        seen = set()
        for info in (list(snapshot.have_pods_with_required_anti_affinity_list)
                     + list(snapshot.have_pods_with_affinity_list)):
            row = enc.node_rows.get(info.node_name)
            if row is None:
                continue
            for pi in info.pods:
                if pi.pod.uid in seen or not pi.has_affinity_constraints():
                    continue
                seen.add(pi.pod.uid)
                self.set_pod(pi, row)

    # --- per-batch host work --------------------------------------------------

    def match_batch(self, pods, size: int, namespace_labels=None):
        """→ host_aux {"match": bool[G, B]} for InterPodAffinityPlugin, or
        None when no live group exists (the plugin then compiles the
        affinity-free program variant, as before).

        Cost: O(live groups × distinct pod identities) Python matches with a
        per-group memo — templated batches hit the cache after the first pod.
        """
        live = [g for g in range(self.num_groups) if self._row_total[g] > 0]
        if not live:
            return None
        # per-pod memo keys hoisted out of the group loop: they depend only
        # on the pod (O(batch) sorts, not O(groups × batch))
        keys = [
            (pod.namespace, tuple(sorted(pod.metadata.labels.items())))
            for pod in pods
        ]
        m = np.zeros((self._g, size), dtype=bool)
        for g in live:
            term = self._row_term[g]
            owner = self._row_owner[g]
            cache = self._match_cache[g]
            if len(cache) > _MATCH_CACHE_CAP:
                cache.clear()
            row = m[g]
            for i, pod in enumerate(pods):
                hit = cache.get(keys[i])
                if hit is None:
                    hit = affinity_term_matches(term, owner, pod, namespace_labels)
                    cache[keys[i]] = hit
                row[i] = hit
        if not m.any():
            return None
        return {"match": m}
