"""Scheduler binary entry: flags → componentconfig → run loop.

Reference: cmd/kube-scheduler (app.NewSchedulerCommand, server.go:66) — the
cobra/pflag layer over KubeSchedulerConfiguration.  Flags mirror the subset
that shapes behavior here; everything else comes from --config (v1beta3
YAML/JSON).  Against the in-process sim store (the only store this build
ships), --sim-nodes/--sim-pods bootstrap a synthetic cluster so the binary
demonstrates an end-to-end scheduling run:

    python -m kubernetes_tpu --sim-nodes 500 --sim-pods 1000 --v 2
    python -m kubernetes_tpu --config scheduler-config.yaml --sim-nodes 100
"""

from __future__ import annotations

import argparse
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kubernetes-tpu-scheduler",
        description="TPU-native batched scheduler (kube-scheduler analog)",
    )
    p.add_argument("--config", help="KubeSchedulerConfiguration file (YAML/JSON)")
    p.add_argument("--v", type=int, default=0, help="log verbosity (klog analog)")
    p.add_argument("--batch-size", type=int, default=128,
                   help="pods scheduled per device program")
    p.add_argument("--pipeline", action="store_true",
                   help="overlap binding with the next batch's device window")
    p.add_argument("--leader-elect", action="store_true",
                   help="acquire the Lease before scheduling (leaderelection.go)")
    p.add_argument("--sim-nodes", type=int, default=0,
                   help="bootstrap N synthetic nodes into the sim store")
    p.add_argument("--sim-pods", type=int, default=0,
                   help="bootstrap N synthetic pending pods")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from .component_base import logging as klog

    klog.set_verbosity(args.v)
    from .sim.store import ObjectStore

    store = ObjectStore()
    if args.config:
        from .config import load_config, scheduler_from_config

        cfg = load_config(args.config)
        sched = scheduler_from_config(
            store, cfg, batch_size=args.batch_size, pipeline=args.pipeline
        )
    else:
        from .scheduler import TPUScheduler

        sched = TPUScheduler(
            store, batch_size=args.batch_size, pipeline=args.pipeline
        )
    if args.leader_elect:
        from .client.leaderelection import LeaderElector, LeaseLock

        elector = LeaderElector(
            LeaseLock(store, "kube-system", "tpu-scheduler"),
            identity="tpu-scheduler",
        )
        if not elector.try_acquire_or_renew():
            print("leader election: lease held elsewhere; standing by",
                  file=sys.stderr)
            return 1
    if args.sim_nodes or args.sim_pods:
        from .testutil import make_node, make_pod

        for i in range(args.sim_nodes):
            store.create("Node", make_node().name(f"node-{i:05d}")
                         .capacity({"cpu": "32", "memory": "64Gi", "pods": "110"})
                         .label("topology.kubernetes.io/zone", f"z{i % 8}")
                         .obj())
        for i in range(args.sim_pods):
            store.create("Pod", make_pod().name(f"pod-{i:05d}")
                         .uid(f"pod-{i:05d}").namespace("default")
                         .req({"cpu": "1", "memory": "2Gi"}).obj())
    t0 = time.perf_counter()
    total = sched.run_until_idle(max_cycles=100000)
    dt = time.perf_counter() - t0
    klog.info_s(
        "scheduler run complete", scheduled=total.scheduled,
        unschedulable=total.unschedulable, seconds=round(dt, 3),
    )
    print(f"scheduled={total.scheduled} unschedulable={total.unschedulable} "
          f"seconds={dt:.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
