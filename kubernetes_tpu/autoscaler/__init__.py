"""Cluster autoscaler on the unified whatif engine.

Layer map (COMPONENTS.md has the upstream-analogue table):
  api.py        — NodeGroup API object (min/max size, template node shape
                  incl. the ``tpu.kubernetes.io/slice`` topology) +
                  deterministic node materialization
  controller.py — demand watch (starved PodGroups + unschedulableQ),
                  vmapped scale-up simulation, eviction-gated scale-down
"""

from .api import (
    NODE_GROUP_LABEL,
    NodeGroup,
    materialize_nodes,
    member_nodes,
    next_node_index,
    next_slice_index,
)
from .controller import ClusterAutoscaler, ScaleDecision

__all__ = [
    "NODE_GROUP_LABEL",
    "NodeGroup",
    "materialize_nodes",
    "member_nodes",
    "next_node_index",
    "next_slice_index",
    "ClusterAutoscaler",
    "ScaleDecision",
]
