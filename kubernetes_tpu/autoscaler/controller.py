"""Cluster-autoscaler controller: demand → simulate → scale.

Reference: kubernetes/autoscaler cluster-autoscaler core —
  ScaleUp (core/scaleup): unschedulable pods are binpacked against each
    group's template NodeInfo (estimator/binpacking) and the expander
    picks the cheapest option;
  ScaleDown (core/scaledown): underutilized nodes are eligible only when
    every resident pod provably reschedules elsewhere (simulator/drain),
    then the node drains and is removed.

This build runs both halves through the unified whatif engine
(kubernetes_tpu/whatif): a scale-up candidate set {add M₁, M₂, …} is ONE
vmapped [K, B, N] solve over K node-add forks, and a scale-down candidate
is a node-remove + victim-mask fork whose pending set is the displaced
pods' replacement clones.  Applying a scale-down goes through the shared
PDB-aware ``EvictionAPI`` drain path (descheduler/evictions.py) — a
blocked budget refuses the scale-down outright, never half-drains.

Exactly-once under chaos: scale-ups materialize deterministically-named
nodes (autoscaler/api.py) and recount live membership each sync, so a
store fault mid-apply resumes exactly where it stopped — the decision's
node set is created once, never duplicated (pinned in
tests/test_autoscaler.py's watch-drop/429 storm).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api import objects as v1
from ..api.resource import compute_pod_resource_request, parse_quantity
from ..component_base import logging as klog
from ..descheduler import clone_for_replacement
from ..descheduler.evictions import EvictionAPI
from ..gang import POD_GROUP_LABEL, SLICE_LABEL
from ..metrics import scheduler_metrics as m
from ..whatif import ForkSpec, WhatIfEngine
from .api import (
    NODE_GROUP_LABEL,
    NodeGroup,
    materialize_nodes,
    member_nodes,
    next_node_index,
    next_slice_index,
)


@dataclass
class ScaleDecision:
    """One sync's verdict for observability (CLI status, tests)."""

    direction: str  # "up" | "down"
    group: str
    result: str  # the metric result label
    count: int = 0  # nodes added / removed
    note: str = ""


class ClusterAutoscaler:
    name = "cluster-autoscaler"

    def __init__(self, store, scheduler,
                 eviction_api: Optional[EvictionAPI] = None,
                 clock=None,
                 dry_run: bool = False,
                 max_scale_downs_per_sync: int = 1,
                 scale_down_utilization_threshold: float = 0.5,
                 max_simulated_sizes: int = 6,
                 min_interval: float = 0.0,
                 slice_label: Optional[str] = None,
                 expander: str = "least-cost"):
        self.store = store
        self.scheduler = scheduler
        self.clock = clock or getattr(scheduler, "clock", time.monotonic)
        self.evictions = eviction_api or EvictionAPI(
            store, recorder=getattr(scheduler, "recorder", None),
            clock=self.clock)
        self.engine = WhatIfEngine(scheduler)
        self.dry_run = dry_run
        # disruption pacing, same rationale as the descheduler's limits: a
        # scale-down drains workloads, so at most this many nodes leave per
        # sync, spaced by min_interval between ACTIVE syncs
        self.max_scale_downs_per_sync = max_scale_downs_per_sync
        self.scale_down_utilization_threshold = scale_down_utilization_threshold
        # cap on the K of one vmapped scale-up solve (candidate sizes per
        # group ramp est → 2·est → … → headroom)
        self.max_simulated_sizes = max_simulated_sizes
        self.min_interval = min_interval
        self.slice_label = slice_label or SLICE_LABEL
        # expander strategy (upstream expander/ analog): how to pick among
        # groups whose simulated scale-up places the whole demand —
        #   least-cost   cheapest (count × costPerNode), the original rule
        #   least-waste  minimize the unused fraction of the ADDED template
        #                capacity (upstream expander/waste), tie-break cost
        if expander not in ("least-cost", "least-waste"):
            raise ValueError(f"unknown expander {expander!r}; "
                             f"expected 'least-cost' or 'least-waste'")
        self.expander = expander
        self._last_active = float("-inf")
        self.last_decisions: List[ScaleDecision] = []

    # --- demand ---------------------------------------------------------------

    def _demand(self) -> List[v1.Pod]:
        """Unschedulable demand: starved PodGroups' unbound members (the
        gang directory's phase writes + the queue's unschedulableQ both
        feed this — phase writes are lossy under chaos by contract, the
        queue signal survives) plus plain parked pods.  Only pods the
        scheduler has actually FAILED count — a transiently pending pod on
        a roomy cluster must not trigger a scale-up."""
        parked = {p.uid: p for p in self.scheduler.queue.unschedulable_pods()}
        groups, _ = self.store.list("PodGroup")
        pods, _ = self.store.list("Pod")
        # one pass over pods, not one scan per PodGroup
        members_by_group: Dict[Tuple[str, str], List[v1.Pod]] = {}
        for p in pods:
            g = p.metadata.labels.get(POD_GROUP_LABEL)
            if g:
                members_by_group.setdefault((p.namespace, g), []).append(p)
        demand: Dict[str, v1.Pod] = {}
        for pg in groups:
            members = members_by_group.get((pg.namespace, pg.name), [])
            if len(members) < pg.min_member:
                continue  # below quorum: capacity can't help yet
            unbound = [p for p in members if not p.spec.node_name]
            if not unbound:
                continue
            starved = (pg.phase == v1.POD_GROUP_UNSCHEDULABLE
                       or any(p.uid in parked for p in unbound))
            if starved:
                # the WHOLE unbound remainder is the demand: a gang binds
                # all-or-nothing, so capacity must fit every member
                for p in unbound:
                    demand[p.uid] = p
        for uid, p in parked.items():
            if uid not in demand and POD_GROUP_LABEL not in p.metadata.labels:
                demand[uid] = p
        ordered = self.engine.order_pending(list(demand.values()))
        batch = self.scheduler.batch_size
        if len(ordered) <= batch:
            return ordered
        # the engine solves at most one batch — truncate on a GANG
        # boundary: a gang split by a plain prefix cut can never satisfy
        # the solve's all-or-nothing mask, which would read as "no fit"
        # for capacity the real scheduler could use (the queue-order sort
        # keeps whole gangs adjacent, so only the boundary gang drops;
        # later syncs serve it once the prefix demand binds)
        prefix = ordered[:batch]
        gangs = self.scheduler.gangs
        full_c: Dict[str, int] = {}
        for p in ordered:
            k = gangs.group_key_of(p)
            if k is not None:
                full_c[k] = full_c.get(k, 0) + 1
        pre_c: Dict[str, int] = {}
        for p in prefix:
            k = gangs.group_key_of(p)
            if k is not None:
                pre_c[k] = pre_c.get(k, 0) + 1
        return [p for p in prefix
                if gangs.group_key_of(p) is None
                or pre_c[gangs.group_key_of(p)]
                == full_c[gangs.group_key_of(p)]]

    # --- the loop -------------------------------------------------------------

    def sync_once(self) -> bool:
        now = self.clock()
        if now - self._last_active < self.min_interval:
            return False
        # engine quiescence: flush in-flight pipelined batches first (same
        # precondition as the descheduler controller)
        for _ in range(4):
            if not getattr(self.scheduler, "_inflight_q", None):
                break
            self.scheduler.schedule_cycle()
        if getattr(self.scheduler, "_inflight_q", None):
            return False
        groups, _ = self.store.list("NodeGroup")
        if not groups:
            return False
        self.last_decisions = []
        demand = self._demand()
        if demand:
            # zero-add baseline first: when the demand already fits the
            # CURRENT cluster (a prior sync's scale-up landed, the pods
            # just haven't re-attempted yet), adding more nodes would
            # over-provision — let the scheduler bind instead
            baseline = self.engine.evaluate_one(
                demand, ForkSpec(note="baseline"))
            if baseline is None:
                return False  # engine refused; retry next sync
            if baseline.unplaced == 0:
                return False
            changed = self._scale_up(groups, demand, baseline.placed)
        else:
            # never shrink while ANY pod is queued (active/backoff/
            # unschedulable or holding a gang Permit wait): a scale-up's
            # fresh empty nodes would otherwise read as underutilized and
            # flap right back down before the pods bind
            a, b, u = self.scheduler.queue.pending_count()
            if a or b or u or getattr(self.scheduler, "_waiting_binds", None):
                return False
            changed = self._scale_down(groups)
        if changed:
            self._last_active = now
        return changed

    # --- scale-up -------------------------------------------------------------

    @staticmethod
    def _demand_totals(pending: List[v1.Pod]) -> Dict[str, float]:
        """Total pending demand per resource dim (cpu in milli; extended/
        device resources included — the dominant dimension on a TPU
        cluster is chips-per-pod over chips-per-host)."""
        need: Dict[str, float] = {"cpu": 0.0, "memory": 0.0,
                                  "pods": float(len(pending))}
        for p in pending:
            r = compute_pod_resource_request(p)
            need["cpu"] += r.milli_cpu
            need["memory"] += r.memory
            for res, amt in r.scalar_resources.items():
                need[res] = need.get(res, 0.0) + float(amt)
        return need

    @staticmethod
    def _template_caps(group: NodeGroup) -> Dict[str, float]:
        """One template node's capacity per dim (cpu in milli), zero/
        absent dims dropped."""
        caps: Dict[str, float] = {}
        for res, q in group.capacity.items():
            v = float(parse_quantity(q))
            if res == "cpu":
                v *= 1000.0
            if v > 0:
                caps[res] = v
        return caps

    def _estimate_nodes(self, group: NodeGroup,
                        pending: List[v1.Pod]) -> int:
        """Binpacking lower bound (estimator/ analog): per resource dim,
        total pending demand over one template node's capacity."""
        need = self._demand_totals(pending)
        caps = self._template_caps(group)
        est = 1
        for res, n in need.items():
            cap = caps.get(res, 0.0)
            if cap > 0 and n > 0:
                est = max(est, -(-int(n) // int(cap)))
        return int(est)

    def _waste_of(self, group: NodeGroup, count: int,
                  need: Dict[str, float]) -> float:
        """Unused fraction of the ADDED capacity, averaged over the dims
        the template declares (upstream expander/waste's 1 - utilization,
        extended to device resources).  0.0 = the demand exactly fills the
        new nodes; 1.0 = they'd sit empty."""
        caps = self._template_caps(group)
        fracs = []
        for res, cap in caps.items():
            total = cap * count
            if total <= 0:
                continue
            fracs.append(max(0.0, 1.0 - min(need.get(res, 0.0) / total,
                                            1.0)))
        return sum(fracs) / len(fracs) if fracs else 1.0

    def _candidate_counts(self, group: NodeGroup, est: int,
                          headroom: int) -> List[int]:
        """Candidate node counts for one group's vmapped solve: the
        binpacking estimate rounded up to whole slices, doubling toward
        the group's headroom (an infeasible estimate — fragmentation,
        gang shapes — still converges in O(log) candidates)."""
        s = max(group.slice_size, 1)
        cands: List[int] = []
        cur = max(est, 1)
        while len(cands) < self.max_simulated_sizes:
            rounded = min(-(-cur // s) * s, headroom)
            if rounded >= 1 and rounded not in cands:
                cands.append(rounded)
            if rounded >= headroom:
                break
            cur = max(cur * 2, rounded + 1)
        return sorted(cands)

    def _scale_up(self, groups: List[NodeGroup], demand: List[v1.Pod],
                  base_placed: int = 0) -> bool:
        """Cheapest group/count whose fork places the WHOLE demand; when
        none does (one unplaceable pod must not starve everyone —
        upstream scales up for a helped subset too), fall back to the
        candidate placing the MOST pods beyond the zero-add baseline,
        cheapest cost breaking ties."""
        nodes, _ = self.store.list("Node")
        need = self._demand_totals(demand)
        best = None  # (expander sort key, group, nodes)
        best_partial = None  # (placed, cost, group, nodes)
        any_headroom = False
        for group in sorted(groups, key=lambda g: (g.cost_per_node,
                                                   g.metadata.name)):
            size = len(member_nodes(group, nodes))
            headroom = group.max_size - size
            if headroom <= 0:
                continue
            any_headroom = True
            counts = self._candidate_counts(
                group, self._estimate_nodes(group, demand), headroom)
            start_idx = next_node_index(group, nodes)
            start_slice = next_slice_index(group, nodes, self.slice_label)
            forks = [
                ForkSpec(
                    add_nodes=materialize_nodes(
                        group, count, start_idx, start_slice,
                        self.slice_label),
                    note=f"scale-up {group.name}+{count}")
                for count in counts
            ]
            try:
                preds = self.engine.evaluate(demand, forks)
            except Exception as e:
                # one group's unbuildable fork (residual name collision,
                # encoding-capacity overflow) must not take the controller
                # loop down — the engine rolled its scratch state back
                m.autoscaler_scale_decisions.inc(("up", "error"))
                self.last_decisions.append(ScaleDecision(
                    "up", group.name, "error",
                    note=f"{type(e).__name__}: {e}"))
                klog.V(1).info_s("Scale-up simulation failed",
                                 group=group.name,
                                 error=f"{type(e).__name__}: {e}")
                continue
            if preds is None:
                return False  # engine refused (pipeline not quiescent)
            for count, fork, pred in zip(counts, forks, preds):
                cost = count * group.cost_per_node
                if pred.unplaced == 0:
                    if self.expander == "least-waste":
                        # minimize stranded template capacity; an equal
                        # fit goes to the cheaper group
                        key = (self._waste_of(group, count, need), cost,
                               group.name)
                    else:
                        key = (cost, group.name)
                    if best is None or key < best[0]:
                        best = (key, group, fork.add_nodes)
                    break  # ascending counts: first viable is this
                    # group's cheapest AND least-waste option
                if pred.placed > base_placed and (
                        best_partial is None
                        or (pred.placed, -cost)
                        > (best_partial[0], -best_partial[1])):
                    best_partial = (pred.placed, cost, group, fork.add_nodes)
        if best is not None:
            _key, group, new_nodes = best
            note = (f"add {len(new_nodes)} × {group.name} for "
                    f"{len(demand)} pending pods")
        elif best_partial is not None:
            placed, _cost, group, new_nodes = best_partial
            note = (f"add {len(new_nodes)} × {group.name}: places "
                    f"{placed}/{len(demand)} pending pods (partial)")
        else:
            result = "no_fit" if any_headroom else "at_max"
            m.autoscaler_scale_decisions.inc(("up", result))
            self.last_decisions.append(ScaleDecision(
                "up", "", result, note=f"{len(demand)} pods unplaceable"))
            return False
        decision = ScaleDecision(
            "up", group.name, "applied", count=len(new_nodes), note=note)
        if self.dry_run:
            decision.result = "dry_run"
            self.last_decisions.append(decision)
            return False
        created = 0
        for node in new_nodes:
            if self.store.get("Node", "", node.metadata.name) is not None:
                continue  # a prior (faulted) apply created it: exactly-once
            try:
                self.store.create("Node", node)
                created += 1
                # kill-point: some of the decision's nodes created, the
                # process dies — deterministic node names make the resume
                # exactly-once: the successor's next sync recounts live
                # membership and creates only the missing names
                from ..chaos.faults import maybe_crash

                maybe_crash("crash.mid_scaleup")
            except ValueError:
                continue  # raced into existence — same exactly-once guard
            except Exception as e:
                # transient store fault mid-apply: stop here; the next sync
                # recounts live membership and resumes with the SAME
                # deterministic names, so the decision still applies
                # exactly once overall
                m.autoscaler_scale_decisions.inc(("up", "error"))
                decision.result = "error"
                decision.count = created
                self.last_decisions.append(decision)
                klog.V(1).info_s("Scale-up apply fault; will resume",
                                 group=group.name, created=created,
                                 error=f"{type(e).__name__}: {e}")
                return created > 0
        m.autoscaler_scale_decisions.inc(("up", "applied"))
        decision.count = created
        self.last_decisions.append(decision)
        klog.V(2).info_s("Scale-up applied", group=group.name,
                         nodes=created, note=decision.note)
        return created > 0

    # --- scale-down -----------------------------------------------------------

    def _utilization(self, node: v1.Node, pods_on: List[v1.Pod]) -> float:
        cap = float(parse_quantity(node.status.allocatable.get("cpu", 0)))
        if cap <= 0:
            return 1.0
        used = sum(compute_pod_resource_request(p).milli_cpu
                   for p in pods_on) / 1000.0
        return used / cap

    def _scale_down(self, groups: List[NodeGroup]) -> bool:
        nodes, _ = self.store.list("Node")
        pods, _ = self.store.list("Pod")
        by_node: Dict[str, List[v1.Pod]] = {}
        for p in pods:
            if p.spec.node_name:
                by_node.setdefault(p.spec.node_name, []).append(p)
        downs = 0
        changed = False
        for group in groups:
            members = member_nodes(group, nodes)
            spare = len(members) - group.min_size
            cands = []
            for node in members:
                pods_on = by_node.get(node.metadata.name, [])
                if any(POD_GROUP_LABEL in p.metadata.labels
                       for p in pods_on):
                    continue  # never break a placed gang for capacity
                util = self._utilization(node, pods_on)
                if util < self.scale_down_utilization_threshold:
                    cands.append((util, node, pods_on))
            cands.sort(key=lambda t: (t[0], t[1].metadata.name))
            for util, node, pods_on in cands:
                if downs >= self.max_scale_downs_per_sync or spare <= 0:
                    break
                verdict = self._try_scale_down(group, node, pods_on)
                self.last_decisions.append(verdict)
                if verdict.result in ("applied", "dry_run"):
                    downs += 1
                    spare -= 1
                    changed = changed or verdict.result == "applied"
        return changed

    def _try_scale_down(self, group: NodeGroup, node: v1.Node,
                        pods_on: List[v1.Pod]) -> ScaleDecision:
        name = node.metadata.name
        decision = ScaleDecision("down", group.name, "", count=1, note=name)
        # JOINT budget pre-check: a drain evicts every resident pod, so
        # each matching PDB must afford the node's whole matching count at
        # once — per-pod blocking_pdb would pass two pods sharing a
        # budget of one, evict the first, and abort the drain mid-way
        # (a pod killed for a scale-down that never happens)
        pdbs = self.store.list("PodDisruptionBudget")[0]
        pdb_load: Dict[str, Tuple[object, int]] = {}
        for p in pods_on:
            for pdb in self.evictions.matching_pdbs(p, pdbs):
                key = f"{pdb.metadata.namespace}/{pdb.metadata.name}"
                pdb_load[key] = (pdb, pdb_load.get(key, (pdb, 0))[1] + 1)
        blocked = next((key for key, (pdb, cnt) in pdb_load.items()
                        if pdb.disruptions_allowed < cnt), None)
        if blocked is not None:
            m.autoscaler_scale_decisions.inc(("down", "blocked"))
            decision.result = "blocked"
            decision.note = (f"{name}: pdb {blocked} cannot afford "
                             f"{pdb_load[blocked][1]} disruptions")
            return decision
        if pods_on:
            # what-if proof: every displaced pod's replacement clone
            # re-places with the node removed and its pods masked out
            clones = [clone_for_replacement(p) for p in pods_on]
            pred = self.engine.evaluate_one(clones, ForkSpec(
                victims=list(pods_on), remove_nodes=[name],
                note=f"scale-down {name}"))
            if pred is None or pred.unplaced:
                m.autoscaler_scale_decisions.inc(("down", "no_replacement"))
                decision.result = "no_replacement"
                decision.note = (
                    f"{name}: "
                    f"{pred.unplaced if pred else len(clones)} displaced "
                    f"pods don't re-place")
                return decision
        if self.dry_run:
            decision.result = "dry_run"
            return decision
        # apply: cordon → drain through the shared eviction gate → delete.
        # A refusal or fault mid-drain aborts (uncordon back) — the gate's
        # budget math already drained what it drained; surviving pods stay.
        try:
            node.spec.unschedulable = True
            self.store.update("Node", node)
            for p in pods_on:
                r = self.evictions.evict(
                    p, reason=f"scale-down {name}", policy="autoscaler")
                if not r.evicted:
                    node.spec.unschedulable = False
                    self.store.update("Node", node)
                    m.autoscaler_scale_decisions.inc(("down", "blocked"))
                    decision.result = "blocked"
                    decision.note = f"{name}: drain refused ({r.reason})"
                    return decision
            self.store.delete("Node", "", name)
        except Exception as e:
            m.autoscaler_scale_decisions.inc(("down", "error"))
            decision.result = "error"
            decision.note = f"{name}: {type(e).__name__}: {e}"
            klog.V(1).info_s("Scale-down fault", node=name,
                             error=f"{type(e).__name__}: {e}")
            # best-effort uncordon (same restore as the drain-refused
            # path): a node stranded cordoned-but-undeleted would leak
            # capacity while its displaced pods re-trigger scale-ups
            try:
                live = self.store.get("Node", "", name)
                if live is not None and live.spec.unschedulable:
                    live.spec.unschedulable = False
                    self.store.update("Node", live)
            except Exception as e2:
                # next sync's what-if re-evaluates from live state
                klog.V(1).info_s("Scale-down uncordon restore failed",
                                 node=name,
                                 error=f"{type(e2).__name__}: {e2}")
            return decision
        m.autoscaler_scale_decisions.inc(("down", "applied"))
        decision.result = "applied"
        klog.V(2).info_s("Scale-down applied", group=group.name, node=name,
                         displaced=len(pods_on))
        return decision
