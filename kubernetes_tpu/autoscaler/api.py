"""NodeGroup: the cluster-autoscaler's scalable capacity unit.

Reference: kubernetes/autoscaler cluster-autoscaler — a NodeGroup is the
provider-side "set of nodes with the same template" (cloudprovider.NodeGroup:
MinSize/MaxSize/TemplateNodeInfo); the simulator builds template NodeInfos
from it to what-if scale-ups.  Here the group is a first-class API object
(served at autoscaling.x-k8s.io/v1alpha1 like the PodGroup CRD) whose
template carries the TPU host shape — capacity, labels, taints, and the
``tpu.kubernetes.io/slice`` topology: ``slice_size`` > 0 batches new hosts
into fresh whole slices so a scaled-up group is immediately gang-anchorable.

Membership: live nodes carry ``autoscaler.tpu.kubernetes.io/node-group`` =
group name (the analog of the provider's instance-group tagging); the
controller derives current size from that label, never from a stored
status counter — exactly-once under chaos falls out of deterministic node
names plus live-state recount.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Tuple

from ..api import objects as v1

# Live nodes are tied to their group via this label (provider tag analog).
NODE_GROUP_LABEL = "autoscaler.tpu.kubernetes.io/node-group"


@dataclass
class NodeGroup:
    """autoscaling.x-k8s.io/v1alpha1 NodeGroup — min/max size + the
    template node shape scale-ups materialize."""

    metadata: v1.ObjectMeta = field(default_factory=v1.ObjectMeta)
    min_size: int = 0
    max_size: int = 1
    # template node shape
    capacity: Dict[str, object] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    taints: List[v1.Taint] = field(default_factory=list)
    # >0: new hosts are batched into fresh ``tpu.kubernetes.io/slice``
    # groups of this many (one multi-host TPU slice per batch)
    slice_size: int = 0
    # relative cost unit for "cheapest group that fits" ranking (the
    # expander's price analog); scale-up cost = count × cost_per_node
    cost_per_node: float = 1.0

    kind = "NodeGroup"

    @property
    def name(self) -> str:
        return self.metadata.name

    @classmethod
    def from_dict(cls, d: Mapping) -> "NodeGroup":
        spec = d.get("spec") or {}
        tmpl = spec.get("template") or {}
        return cls(
            metadata=v1.ObjectMeta.from_dict(d.get("metadata") or {}),
            min_size=int(spec.get("minSize", 0)),
            max_size=int(spec.get("maxSize", 1)),
            capacity=dict(tmpl.get("capacity") or {}),
            labels=dict(tmpl.get("labels") or {}),
            taints=[v1.Taint.from_dict(t) for t in tmpl.get("taints") or []],
            slice_size=int(tmpl.get("sliceSize", 0)),
            cost_per_node=float(spec.get("costPerNode", 1.0)),
        )


def member_nodes(group: NodeGroup, nodes: List[v1.Node]) -> List[v1.Node]:
    """Live nodes belonging to the group (label-tagged membership)."""
    return [n for n in nodes
            if n.metadata.labels.get(NODE_GROUP_LABEL) == group.name]


def _trailing_index(name: str, prefix: str) -> int:
    """Parse the numeric suffix of ``{prefix}{i}``; -1 when not ours."""
    if not name.startswith(prefix):
        return -1
    tail = name[len(prefix):]
    return int(tail) if tail.isdigit() else -1


def next_node_index(group: NodeGroup, nodes: List[v1.Node]) -> int:
    """1 + the highest ``{group}-{i}`` node index in the cluster.

    Deterministic naming is the exactly-once mechanism: a scale-up retried
    after a store fault proposes the SAME names, and already-created nodes
    are detected instead of duplicated.  Scans ALL nodes by name pattern —
    not just labeled members — so a same-named node without the group
    label (operator-created, label stripped) is skipped over instead of
    colliding with the simulation's template encode."""
    prefix = f"{group.name}-"
    return 1 + max(
        (_trailing_index(n.metadata.name, prefix) for n in nodes),
        default=-1,
    )


def next_slice_index(group: NodeGroup, nodes: List[v1.Node],
                     slice_label: str) -> int:
    prefix = f"{group.name}-slice-"
    return 1 + max(
        (_trailing_index(n.metadata.labels.get(slice_label, ""), prefix)
         for n in nodes),
        default=-1,
    )


def materialize_nodes(group: NodeGroup, count: int, start_index: int,
                      start_slice: int, slice_label: str) -> List[v1.Node]:
    """``count`` template nodes with deterministic names/slice labels —
    the SAME objects the simulation forks and the apply creates, so a
    simulated placement on an added node names the real node it becomes."""
    out: List[v1.Node] = []
    for i in range(count):
        idx = start_index + i
        labels = dict(group.labels)
        labels[NODE_GROUP_LABEL] = group.name
        if group.slice_size > 0:
            sl = start_slice + i // group.slice_size
            labels[slice_label] = f"{group.name}-slice-{sl}"
        out.append(v1.Node(
            metadata=v1.ObjectMeta(name=f"{group.name}-{idx}",
                                   labels=labels),
            spec=v1.NodeSpec(taints=list(group.taints)),
            status=v1.NodeStatus(capacity=dict(group.capacity),
                                 allocatable=dict(group.capacity)),
        ))
    return out
