"""Node-axis sharding over a jax.sharding.Mesh.

SURVEY §2.5: the reference parallelizes Filter/Score by fanning goroutines over
the node dimension (parallelize/parallelism.go, 16 workers).  Here the same axis
becomes a *mesh axis*: every per-node array of the DeviceSnapshot is sharded on
dim 0 across chips, so the ``[B, N]`` feasibility/score planes are computed
shard-local and the few cross-node reductions (row max/min in normalize,
argmax in select_host, domain scatter-adds) lower to XLA collectives over ICI.
This is the structural analog of sequence parallelism with "sequence" = nodes
(SURVEY §5 long-context note): a 100k-node cluster is scored densely in one
shot instead of sampled (scheduler.go:852-872).

GSPMD does the partitioning: we annotate inputs (shard_snapshot) and jit the
unchanged runtime program; XLA inserts all-reduce / all-gather where the
reductions cross the node axis.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"


def node_sharded_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def replicate(mesh: Mesh):
    return NamedSharding(mesh, P())


def _node_spec(ndim: int) -> P:
    return P(NODE_AXIS, *([None] * (ndim - 1)))


def node_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Dim-0 node-axis NamedSharding for an array of the given rank — the
    placement ClusterEncoder uses for every node-tier array when it owns a
    mesh (state/encoding.py set_mesh)."""
    return NamedSharding(mesh, _node_spec(ndim))


def shard_divisible(n: int, mesh: Mesh) -> bool:
    """Does a tier of n rows split evenly over the mesh's node axis?  The
    pow-2 tier/bucket discipline guarantees this for power-of-two device
    counts (set_mesh validates that), so padding shapes stay recompile-
    stable per shard count — this predicate exists for tests and guards."""
    return n % mesh.devices.size == 0


def shard_snapshot(snap, mesh: Mesh):
    """device_put every per-node array with dim-0 node sharding; the pod tables
    and the dictionary side-table are replicated (they are small and read by
    every shard)."""
    from ..state.encoding import _NODE_ARRAYS

    node_fields = set(_NODE_ARRAYS)
    out = {}
    for name in snap.__dataclass_fields__:
        arr = getattr(snap, name)
        if name in node_fields:
            sharding = NamedSharding(mesh, _node_spec(arr.ndim))
        else:
            sharding = replicate(mesh)
        out[name] = jax.device_put(arr, sharding)
    return type(snap)(**out)


def shard_dynamic_state(dyn, mesh: Mesh):
    from ..framework.interface import DynamicState

    return DynamicState(
        requested=jax.device_put(dyn.requested, NamedSharding(mesh, _node_spec(2))),
        non_zero=jax.device_put(dyn.non_zero, NamedSharding(mesh, _node_spec(2))),
    )


def shard_host_auxes(host_auxes, mesh: Mesh, n_nodes: int):
    """Shard host-prepared aux planes: any array whose LAST dim equals the
    node tier (volume masks, IPA exist-anti-block / static-score planes, the
    Coscheduling slice-domain vector — ``[..., N]``) gets node sharding on
    that axis; everything else replicates.

    Accepts the full host_prepare pytree (plugin name → None | dict | tuple
    | array) — generalized beyond dicts so the Coscheduling
    ``(slice_dom[N], anchor[B])`` tuple and any stacked ``[K, ..., N]``
    whatif fork aux ride the same shard spec instead of silently falling
    back to replicated.  The node tier is pow-2 padded (shard-divisible for
    power-of-two meshes), so the sharded shapes are exactly the unsharded
    ones — no recompile-relevant padding is introduced per shard count.
    """
    if host_auxes is None:
        return None

    def put(arr):
        if not hasattr(arr, "shape"):
            return arr
        if arr.ndim >= 1 and arr.shape[-1] == n_nodes:
            spec = P(*([None] * (arr.ndim - 1) + [NODE_AXIS]))
            return jax.device_put(arr, NamedSharding(mesh, spec))
        return jax.device_put(arr, replicate(mesh))

    return jax.tree_util.tree_map(put, host_auxes)
