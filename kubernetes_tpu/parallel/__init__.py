"""Device-mesh sharding of the scheduling computation."""

from .mesh import (  # noqa: F401
    node_sharded_mesh,
    shard_snapshot,
    replicate,
    NODE_AXIS,
)
