"""Device-mesh sharding of the scheduling computation."""

from .mesh import (  # noqa: F401
    node_sharded_mesh,
    node_sharding,
    shard_divisible,
    shard_host_auxes,
    shard_snapshot,
    replicate,
    NODE_AXIS,
)
