"""KubeSchedulerConfiguration: v1beta3-schema-compatible componentconfig.

Reference: pkg/scheduler/apis/config/types.go:41-196 (KubeSchedulerConfiguration
:41, Parallelism :53, PercentageOfNodesToScore :70, Profiles :102, Plugins :129,
PluginSet :171, PluginConfig :191), defaulting in v1beta3/default_plugins.go:32-51
and v1beta3/defaults.go, typed args in types_pluginargs.go.

Scope: the subset that shapes scheduling behavior on the device path — profiles,
plugin enable/disable with weights, and the typed args of the vectorized plugin
set.  Accepts the same YAML documents an unmodified kube-scheduler takes
(apiVersion kubescheduler.config.k8s.io/v1beta2|v1beta3); structural knobs that
do not apply to the dense device path (parallelism, percentageOfNodesToScore)
are parsed and retained for compatibility but not used to degrade coverage.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional

from ..framework.interface import PluginWithWeight
from .. import plugins as P

DEFAULT_SCHEDULER_NAME = "default-scheduler"

# default enablement + weights: apis/config/v1beta3/default_plugins.go:32-51
DEFAULT_PLUGIN_ORDER = [
    ("NodeUnschedulable", 0),
    ("NodeName", 0),
    ("TaintToleration", 3),
    ("NodeAffinity", 2),
    ("NodePorts", 0),
    ("NodeResourcesFit", 1),
    ("PodTopologySpread", 2),
    ("InterPodAffinity", 2),
    ("NodeResourcesBalancedAllocation", 1),
    ("ImageLocality", 1),
]


@dataclass
class PluginEnable:
    name: str
    weight: Optional[int] = None


@dataclass
class PluginSet:
    enabled: List[PluginEnable] = field(default_factory=list)
    disabled: List[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Optional[Mapping]) -> "PluginSet":
        if not d:
            return cls()
        return cls(
            enabled=[
                PluginEnable(e["name"], e.get("weight")) for e in d.get("enabled") or []
            ],
            disabled=[e["name"] for e in d.get("disabled") or []],
        )


@dataclass
class KubeSchedulerProfile:
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    plugins: Dict[str, PluginSet] = field(default_factory=dict)  # per extension point
    plugin_config: Dict[str, Dict[str, Any]] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Mapping) -> "KubeSchedulerProfile":
        plugins = {
            point: PluginSet.from_dict(ps)
            for point, ps in (d.get("plugins") or {}).items()
        }
        plugin_config = {
            pc["name"]: pc.get("args") or {} for pc in d.get("pluginConfig") or []
        }
        return cls(
            scheduler_name=d.get("schedulerName", DEFAULT_SCHEDULER_NAME),
            plugins=plugins,
            plugin_config=plugin_config,
        )

    def effective_plugins(self) -> List[PluginEnable]:
        """Default set, minus disabled, plus explicitly enabled (with weights).

        Mirrors the multipoint merge of v1beta3 defaulting: "*" in disabled wipes
        the defaults; explicit enables append/override.
        """
        multi = self.plugins.get("multiPoint", PluginSet())
        score = self.plugins.get("score", PluginSet())
        disabled = set(multi.disabled) | set(score.disabled)
        out: List[PluginEnable] = []
        if "*" not in disabled:
            for name, weight in DEFAULT_PLUGIN_ORDER:
                if name not in disabled:
                    out.append(PluginEnable(name, weight))
        for e in list(multi.enabled) + list(score.enabled):
            existing = next((x for x in out if x.name == e.name), None)
            if existing is None:
                out.append(PluginEnable(e.name, e.weight))
            elif e.weight is not None:
                existing.weight = e.weight
        return out


@dataclass
class KubeSchedulerConfiguration:
    profiles: List[KubeSchedulerProfile] = field(default_factory=list)
    parallelism: int = 16  # types.go:53 (compat only — device path is dense)
    percentage_of_nodes_to_score: int = 0  # types.go:70 (compat only)
    pod_initial_backoff_seconds: float = 1.0
    pod_max_backoff_seconds: float = 10.0
    # node-axis sharding of the device path (no upstream analog — the
    # structural replacement for percentageOfNodesToScore sampling: instead
    # of scoring fewer nodes, score all of them across more chips).
    # "auto" (default) shards on multi-device accelerators only; "on"
    # forces it (tests use the virtual CPU mesh); "off" disables; an int
    # shards over the first n devices.  Mirrors chain_affinity's
    # backend-gating pattern (TPUScheduler sharding=).
    node_axis_sharding: object = "auto"
    # attempt-latency target for the adaptive micro-bucket dispatch policy
    # (no upstream analog — the batched device path's lever on per-attempt
    # latency: dedup-eligible constraint-free batches split into pow-2
    # sub-buckets riding the deep pipeline until the recent attempt p99
    # fits under this budget).  None = off: every cycle pads to the full
    # batch size.  Mirrors TPUScheduler latency_target_ms.
    latency_target_ms: Optional[float] = None

    @classmethod
    def from_dict(cls, d: Mapping) -> "KubeSchedulerConfiguration":
        api = d.get("apiVersion", "")
        if api and not api.startswith("kubescheduler.config.k8s.io/"):
            raise ValueError(f"unsupported apiVersion {api}")
        profiles = [
            KubeSchedulerProfile.from_dict(p) for p in d.get("profiles") or []
        ]
        if not profiles:
            profiles = [KubeSchedulerProfile()]
        sharding = d.get("nodeAxisSharding", "auto")
        if not (sharding in ("auto", "on", "off", True, False)
                or isinstance(sharding, int)):
            raise ValueError(f"unsupported nodeAxisSharding {sharding!r}")
        if (isinstance(sharding, int) and not isinstance(sharding, bool)
                and sharding > 1 and sharding & (sharding - 1)):
            # fail at parse time with the constraint named, not at
            # scheduler construction inside ClusterEncoder.set_mesh
            raise ValueError(
                f"nodeAxisSharding {sharding} is not a power of two (the "
                "node-axis mesh requires a power-of-two device count)")
        lt = d.get("latencyTargetMs")
        if lt is not None:
            lt = float(lt)
            if lt < 0:
                raise ValueError(f"latencyTargetMs must be >= 0, got {lt}")
            if lt == 0:
                lt = None  # 0 = explicit off, same as absent
        return cls(
            profiles=profiles,
            parallelism=int(d.get("parallelism", 16)),
            percentage_of_nodes_to_score=int(d.get("percentageOfNodesToScore", 0)),
            pod_initial_backoff_seconds=float(d.get("podInitialBackoffSeconds", 1)),
            pod_max_backoff_seconds=float(d.get("podMaxBackoffSeconds", 10)),
            node_axis_sharding=sharding,
            latency_target_ms=lt,
        )

    def profile(self, scheduler_name: str = DEFAULT_SCHEDULER_NAME) -> KubeSchedulerProfile:
        for p in self.profiles:
            if p.scheduler_name == scheduler_name:
                return p
        return self.profiles[0]


def load_config(source) -> KubeSchedulerConfiguration:
    """Accepts a dict, YAML string, or file path."""
    if isinstance(source, Mapping):
        return KubeSchedulerConfiguration.from_dict(source)
    text = source
    if isinstance(source, str) and "\n" not in source and source.endswith((".yaml", ".yml", ".json")):
        with open(source) as f:
            text = f.read()
    try:
        import yaml  # type: ignore

        data = yaml.safe_load(text)
    except ImportError:  # yaml not available → JSON fallback
        import json

        data = json.loads(text)
    return KubeSchedulerConfiguration.from_dict(data or {})


def build_plugins_for_profile(
    profile: KubeSchedulerProfile, domain_cap: int, extended_index=None,
    num_resource_dims: int = 8,
) -> List[PluginWithWeight]:
    """Instantiate the vectorized plugin set per profile + typed args
    (types_pluginargs.go analog)."""
    out: List[PluginWithWeight] = []
    for e in profile.effective_plugins():
        args = profile.plugin_config.get(e.name, {})
        plugin = _construct(e.name, args, domain_cap, extended_index, num_resource_dims)
        if plugin is None:
            continue
        default_w = dict(DEFAULT_PLUGIN_ORDER).get(e.name, 1)
        out.append(PluginWithWeight(plugin, e.weight if e.weight is not None else default_w))
    return out


def _construct(name, args, domain_cap, extended_index, num_dims):
    if name == "NodeResourcesFit":
        strat = (args.get("scoringStrategy") or {})
        resources = {
            r["name"]: r.get("weight", 1)
            for r in strat.get("resources") or [{"name": "cpu", "weight": 1},
                                                {"name": "memory", "weight": 1}]
        }
        return P.FitPlugin(
            strategy=strat.get("type", "LeastAllocated"),
            resources=resources,
            num_resource_dims=num_dims,
            extended_index=extended_index,
        )
    if name == "NodeResourcesBalancedAllocation":
        resources = {
            r["name"]: r.get("weight", 1)
            for r in args.get("resources") or [{"name": "cpu", "weight": 1},
                                               {"name": "memory", "weight": 1}]
        }
        return P.BalancedAllocationPlugin(
            resources=resources, num_resource_dims=num_dims,
            extended_index=extended_index,
        )
    if name == "InterPodAffinity":
        return P.InterPodAffinityPlugin(
            domain_cap=domain_cap,
            hard_pod_affinity_weight=args.get("hardPodAffinityWeight", 1),
        )
    if name == "PodTopologySpread":
        return P.PodTopologySpreadPlugin(domain_cap=domain_cap)
    simple = {
        "TaintToleration": P.TaintTolerationPlugin,
        "NodeAffinity": P.NodeAffinityPlugin,
        "NodeName": P.NodeNamePlugin,
        "NodePorts": P.NodePortsPlugin,
        "NodeUnschedulable": P.NodeUnschedulablePlugin,
        "ImageLocality": P.ImageLocalityPlugin,
        "SelectorSpread": P.SelectorSpreadPlugin,
        "VolumeBinding": P.VolumeBindingPlugin,
        "VolumeZone": P.VolumeZonePlugin,
        "VolumeRestrictions": P.VolumeRestrictionsPlugin,
        "NodeVolumeLimits": P.NodeVolumeLimitsPlugin,
        # reference cloud-specific limit plugins all map onto the generic
        # NodeVolumeLimits implementation (nodevolumelimits/non_csi.go)
        "EBSLimits": P.NodeVolumeLimitsPlugin,
        "GCEPDLimits": P.NodeVolumeLimitsPlugin,
        "AzureDiskLimits": P.NodeVolumeLimitsPlugin,
    }
    ctor = simple.get(name)
    return ctor() if ctor else None


def scheduler_from_config(store, cfg: "KubeSchedulerConfiguration", **kwargs):
    """Build a TPUScheduler from a KubeSchedulerConfiguration: every profile
    becomes a framework keyed by its schedulerName (profile.NewMap analog,
    profile/profile.go:48); queue backoff knobs carry over."""
    from ..scheduler import TPUScheduler

    profiles = {
        p.scheduler_name: (
            lambda d, _p=p: build_plugins_for_profile(_p, domain_cap=d)
        )
        for p in cfg.profiles
    }
    kwargs.setdefault("sharding", cfg.node_axis_sharding)
    kwargs.setdefault("latency_target_ms", cfg.latency_target_ms)
    return TPUScheduler(
        store, profiles=profiles,
        pod_initial_backoff=cfg.pod_initial_backoff_seconds,
        pod_max_backoff=cfg.pod_max_backoff_seconds,
        **kwargs,
    )
