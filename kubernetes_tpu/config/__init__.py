"""ComponentConfig (reference: pkg/scheduler/apis/config)."""

from .componentconfig import (  # noqa: F401
    KubeSchedulerConfiguration,
    KubeSchedulerProfile,
    PluginSet,
    load_config,
    build_plugins_for_profile,
    scheduler_from_config,
)
