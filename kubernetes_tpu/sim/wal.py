"""Append-only write-ahead log for the object store (the durability layer).

Reference analog: etcd's raft WAL + bbolt backend (server/storage/wal) — the
property this buys is the same one upstream's control plane rests on: a
mutation is durable BEFORE it is visible, so a kill -9 at any instruction
boundary loses at most un-acknowledged writes, never acknowledged ones, and
a fresh process reconstructs the exact store by replay.

Record format (length-prefixed + checksummed, wal/decoder.go shape):

    ``>I`` payload length | ``>I`` crc32(payload) | payload

The payload is a binary wire document (api/wire.py, sniffed by its magic;
logs written before the wire plane carry JSON payloads and replay
identically — mixed-format logs are a supported upgrade path).  It carries
the op (``create``/``update``/``delete``/``bind``), the final
resourceVersion the store assigned, and — for create/update — the object's
self-contained wire doc under ``objw`` (the SAME bytes the watch cache
fanned out: appending is a memo hit, not an encode; legacy records carry
the manifest dict under ``obj`` instead).  WAL fidelity is wire fidelity —
``scheme.decode(wire_decode(objw)) == scheme.decode(manifest)`` is pinned
for every kind.  ``replay_on_boot`` re-applies records through
``ObjectStore.replay_record`` and TRUNCATES a torn tail record (a crash
mid-append leaves a prefix whose length or crc cannot verify — everything
before it is intact by construction).

fsync cadence is configurable (``fsync_every``: 1 = every append, the
acknowledged-implies-durable contract; N = every N appends — bounded loss
window, tier-1-fast; 0 = never, OS-buffered only) because a per-append
fsync is ~1ms of wall per write and the test tiers must stay fast.

Crash points wired here (chaos/faults.py):
  - ``crash.pre_wal_fsync``: after the record bytes reach the file, before
    fsync — the acknowledged-but-not-yet-durable window;
  - torn write (``arm_torn_write``): a strict prefix of the record is
    written, then death — replay must checksum-truncate it.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from dataclasses import dataclass, field
from typing import List, Optional

from ..analysis import lockcheck
from ..api import wire
from ..chaos.faults import (
    CRASH_PRE_WAL_FSYNC,
    CRASH_TORN_WAL_WRITE,
    ProcessCrash,
    maybe_crash,
    maybe_torn_write,
)
from ..component_base import logging as klog
from ..metrics import scheduler_metrics as m

_HEADER = struct.Struct(">II")  # payload length, crc32(payload)


@dataclass
class WALRecord:
    op: str            # create | update | delete | bind
    kind: str
    namespace: str
    name: str
    rv: int
    manifest: Optional[dict] = None  # create/update: the object's wire form
    node_name: str = ""              # bind: the target node
    # create/update in a binary record: the object's self-contained wire
    # doc — the encode-once bytes (manifest stays populated on decode so
    # forensic consumers keep reading one field)
    obj_bytes: Optional[bytes] = None
    codec: str = "wire"              # payload() emission format

    def payload(self) -> bytes:
        body = {"op": self.op, "kind": self.kind, "ns": self.namespace,
                "name": self.name, "rv": self.rv}
        if self.codec == "wire":
            if self.obj_bytes is not None:
                # BYTES-embedded verbatim: the envelope encode copies the
                # cached object bytes, it never re-serializes the object
                body["objw"] = self.obj_bytes
            elif self.manifest is not None:
                body["obj"] = self.manifest
            if self.node_name:
                body["node"] = self.node_name
            return wire.wire_encode(body)
        if self.manifest is not None:
            body["obj"] = self.manifest
        elif self.obj_bytes is not None:
            body["obj"] = wire.wire_decode(self.obj_bytes)
        if self.node_name:
            body["node"] = self.node_name
        return json.dumps(body, separators=(",", ":")).encode()

    @classmethod
    def from_payload(cls, raw: bytes) -> "WALRecord":
        """Decode one record payload, binary or JSON (magic sniff): logs
        from before the wire plane — and mixed-format logs mid-upgrade —
        replay through the same path."""
        if wire.is_wire(raw):
            body = wire.wire_decode(raw)
            objw = body.get("objw")
            manifest = body.get("obj")
            if manifest is None and objw is not None:
                manifest = wire.wire_decode(objw)
            return cls(op=body["op"], kind=body["kind"],
                       namespace=body["ns"], name=body["name"],
                       rv=body["rv"], manifest=manifest, obj_bytes=objw,
                       node_name=body.get("node", ""), codec="wire")
        body = json.loads(raw)
        return cls(op=body["op"], kind=body["kind"], namespace=body["ns"],
                   name=body["name"], rv=body["rv"],
                   manifest=body.get("obj"), node_name=body.get("node", ""),
                   codec="json")

    def decode_obj(self, scheme):
        """The record's object (None for delete/bind), decoded by the
        fastest available path: the wire doc takes the native decoder,
        legacy manifests take scheme.decode — pinned to agree."""
        if self.obj_bytes is not None:
            return wire.decode_object(self.obj_bytes, scheme)
        if self.manifest is not None:
            return scheme.decode(self.manifest)
        return None


class WriteAheadLog:
    """One log file, append-only; thread-safe (the store appends under its
    own lock, but the CLI/status path reads sizes concurrently)."""

    def __init__(self, path: str, scheme=None, fsync_every: int = 64,
                 exempt_kinds=frozenset({"Event"}), tracer=None):
        from ..component_base.trace import NOOP_TRACER

        self.path = path
        self._scheme = scheme  # lazy: default_scheme pulls in controllers
        self.fsync_every = fsync_every
        # span tracer (component_base/trace.py): wal_append/wal_fsync spans
        # per durable write, linked into the caller's attempt tree via the
        # explicit trace_parent handoff (bind_pod threads it through).
        # NOOP by default — a disabled tracer costs one attribute read.
        self.tracer = tracer or NOOP_TRACER
        # kinds NOT logged (their appends are silent no-ops): Events are
        # best-effort by contract (client/events.py retains-and-flushes,
        # losses are counted, the reference keeps them in a dedicated
        # short-TTL etcd) and the wire scheme does not serve them — a
        # replayed store starts event-empty, exactly like a reference boot
        self.exempt_kinds = frozenset(exempt_kinds)
        self._lock = lockcheck.maybe_wrap(threading.Lock(),
                                          "WriteAheadLog._lock")
        self._f = open(path, "ab")
        self._records = 0           # appended this process
        self._since_fsync = 0
        self._last_fsync_rv = 0
        self._size = self._f.tell()

    # --- write side -----------------------------------------------------------

    def scheme(self):
        if self._scheme is None:
            from ..api.scheme import default_scheme

            self._scheme = default_scheme()
        return self._scheme

    def append(self, op: str, kind: str, *, obj=None, namespace: str = "",
               name: str = "", node_name: str = "", rv: int = 0,
               trace_parent=None) -> None:
        """Durably log one mutation BEFORE the store applies it in memory.

        Raises on any failure (I/O error, injected torn write) — the store
        treats a raising append as a failed write and never applies the
        mutation, so the log can only ever be AHEAD of memory (replay then
        treats the logged write as committed — the etcd "commit unknown"
        outcome a client retry must tolerate)."""
        if kind in self.exempt_kinds:
            return
        if obj is not None:
            # encode-once: the object's payload memo (api.wire) is shared
            # with the watch cache and the HTTP planes — whichever plane
            # touches this object version first pays the encode
            obj_bytes = wire.payload_for(obj, self.scheme()).wire_bytes()
            meta = obj.metadata
            namespace = namespace or getattr(meta, "namespace", "")
            name = name or meta.name
        else:
            obj_bytes = None
        rec = WALRecord(op=op, kind=kind, namespace=namespace, name=name,
                        rv=rv, obj_bytes=obj_bytes, node_name=node_name)
        # wal_append span: parented to the caller's attempt tree when the
        # explicit trace_parent handoff carried one (store bind path); a
        # direct store write without a context records a root span.  Guarded
        # so the disabled tracer costs one attribute read per append.
        span = (self.tracer.span("wal_append", parent=trace_parent,
                                 op=op, kind=kind, rv=rv)
                if self.tracer.enabled else None)
        payload = rec.payload()
        blob = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        keep = maybe_torn_write(len(blob))
        with self._lock:
            if keep is not None:
                # torn write: a strict prefix reaches the disk, then the
                # process dies — flush+fsync makes the TORN state durable
                # (that is the fault being modeled; replay truncates it)
                self._f.write(blob[:keep])
                self._f.flush()
                os.fsync(self._f.fileno())
                raise ProcessCrash(CRASH_TORN_WAL_WRITE)
            self._f.write(blob)
            self._f.flush()
            self._size += len(blob)
            self._records += 1
            self._since_fsync += 1
            m.wal_records.inc((op,))
            m.wal_size_bytes.set(float(self._size))
        if span is not None:
            span.finish()
        # the acknowledged-but-unsynced window: record bytes are in the OS
        # buffer, fsync has not run — the registered kill-point sits exactly
        # here so the crash battery exercises replay from this state
        maybe_crash(CRASH_PRE_WAL_FSYNC)
        if self.fsync_every and self._since_fsync >= self.fsync_every:
            self.sync(rv, trace_parent=trace_parent)

    def sync(self, rv: int = 0, trace_parent=None) -> None:
        """fsync the file; ``rv`` (when known) records the durability
        watermark served by ``ktpu controlplane status``.  ``trace_parent``
        links the fsync span to the append (and attempt tree) that
        triggered the cadence."""
        span = (self.tracer.span("wal_fsync", parent=trace_parent, rv=rv)
                if self.tracer.enabled else None)
        with self._lock:
            os.fsync(self._f.fileno())
            self._since_fsync = 0
            if rv:
                self._last_fsync_rv = rv
                m.wal_last_fsync_rv.set(float(rv))
        if span is not None:
            span.finish()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                os.fsync(self._f.fileno())
                self._f.close()

    # --- status (CLI / metrics) ----------------------------------------------

    @property
    def size_bytes(self) -> int:
        with self._lock:
            return self._size

    @property
    def records_appended(self) -> int:
        with self._lock:
            return self._records

    @property
    def last_fsync_rv(self) -> int:
        with self._lock:
            return self._last_fsync_rv


@dataclass
class ReplayResult:
    store: object
    records_applied: int = 0
    last_rv: int = 0
    truncated_tail: bool = False
    truncated_at: int = 0  # byte offset the torn tail was cut at
    errors: List[str] = field(default_factory=list)
    # the dynamic-kind registrar attached during replay (CRD records
    # re-install their kinds before the custom-resource records that
    # follow them decode); callers keep it attached for live serving
    registrar: object = None


def scan_records(data: bytes, base_offset: int = 0):
    """Walk a length-prefixed + crc-checked byte stream: returns
    ([(absolute_offset, WALRecord)], verified_length) where
    ``verified_length`` counts only bytes of fully-verifiable records — a
    torn/corrupt tail (short header, overrunning length, crc mismatch,
    undecodable payload) stops the walk.  ``base_offset`` shifts the
    reported record offsets so callers tailing a file mid-stream (the
    replication LogShipper, a follower verifying a shipped batch) get
    file-absolute positions from a relative slice."""
    good_end = 0
    records = []
    off = 0
    while off + _HEADER.size <= len(data):
        length, crc = _HEADER.unpack_from(data, off)
        start = off + _HEADER.size
        end = start + length
        if end > len(data):
            break  # torn: header promises more bytes than exist
        payload = data[start:end]
        if zlib.crc32(payload) != crc:
            break  # torn/corrupt: checksum fails
        try:
            records.append((base_offset + off,
                            WALRecord.from_payload(payload)))
        except (ValueError, KeyError):
            break  # undecodable payload that passed crc: treat as tail
        off = end
        good_end = end
    return records, good_end


def read_records(path: str):
    """Yield (offset, WALRecord) for every verifiable record; returns the
    byte offset where a torn/corrupt tail begins (== file size when the
    whole log verifies).  Used by replay and by forensic tooling."""
    with open(path, "rb") as f:
        data = f.read()
    return scan_records(data)


def replay_on_boot(path: str, *, store=None, scheme=None,
                   truncate: bool = True) -> ReplayResult:
    """Reconstruct an ObjectStore from the WAL (the boot path after real
    process death).  A torn tail record — crash mid-append — is detected by
    length/crc and TRUNCATED from the file (when ``truncate``) so the
    reopened log appends cleanly; every record before it applies.

    The replayed store's watch history (``_log``) is rebuilt too, so the
    PR-8 cold-start reconstruction (scheduler constructor watch replay)
    runs on it unchanged."""
    from ..api.scheme import default_scheme
    from .store import ObjectStore

    if store is None:
        store = ObjectStore()
    scheme = scheme or default_scheme()
    result = ReplayResult(store=store)
    if not os.path.exists(path):
        return result
    records, good_end = read_records(path)
    size = os.path.getsize(path)
    if good_end < size:
        result.truncated_tail = True
        result.truncated_at = good_end
        if truncate:
            # fsync the cut: a LogShipper (sim/replication.py) tails this
            # same file by byte offset, and a re-resurrected torn suffix
            # after a crash-mid-truncation would sit exactly where the
            # next clean append lands — the shipper would then stream
            # garbage bytes it can never verify past.  Durable truncation
            # keeps the file re-openable for appends AND for shipping.
            with open(path, "r+b") as f:
                f.truncate(good_end)
                f.flush()
                os.fsync(f.fileno())
        klog.V(1).info_s("WAL torn tail truncated", path=path,
                         at=good_end, lost_bytes=size - good_end)
    # dynamic kinds: a CRD record precedes every record of the kind it
    # defines (rv order), and replay_record emits synchronously, so an
    # attached registrar re-installs each kind into the scheme BEFORE the
    # first custom-resource record decodes.  ``replaying`` suppresses the
    # registrar's own writes (the log already holds whatever cascade
    # completed pre-crash); resync() after replay finishes any cascade the
    # crash interrupted — replayed exactly once, because deleting a
    # missing object is a no-op.
    from ..apiextensions.registrar import DynamicKindRegistrar

    registrar = DynamicKindRegistrar(store, scheme)
    registrar.replaying = True
    registrar.attach(drain=False)
    result.registrar = registrar
    for _, rec in records:
        obj = rec.decode_obj(scheme)
        store.replay_record(rec.op, rec.kind, obj=obj,
                            namespace=rec.namespace, name=rec.name,
                            node_name=rec.node_name, rv=rec.rv)
        result.records_applied += 1
        result.last_rv = rec.rv
    store.rebuild_admission_caches()
    registrar.replaying = False
    registrar.resync()
    klog.V(1).info_s("WAL replay complete", path=path,
                     records=result.records_applied, last_rv=result.last_rv,
                     truncated=result.truncated_tail)
    return result
