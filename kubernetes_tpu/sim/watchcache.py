"""Versioned watch cache: bounded event ring + object snapshot in front of
the store, serving lists and watch replays WITHOUT the store lock.

Reference: staging/src/k8s.io/apiserver/pkg/storage/cacher/cacher.go — the
layer that lets one apiserver fan a write out to thousands of watchers while
etcd sees exactly one watch.  Mirrored behaviors:

  - LIST and ``since_rv`` watch replay are served from the cache's own
    snapshot + ring under the cache's own lock: zero store-lock
    acquisitions on the read path (asserted against ``ObjectStore.read_ops``
    deltas — the scale property ROADMAP item 2 names);
  - resourceVersion-consistent pagination (``limit``/``continue``): every
    page of one list is served AT THE SAME rv — the ring's per-event
    pre-state manifests roll the snapshot back, so concurrent writes can
    never tear a paginated relist (etcd3 pagination contract);
  - a watch/list at an rv older than the ring answers
    ``TooOldResourceVersion`` (410 Gone) → the client relists, exactly the
    reference's too-old-resourceVersion contract (cacher.go:161-185);
  - periodic BOOKMARK delivery (``bookmark_now``/``start_bookmarks``) keeps
    idle watchers' restart points fresh so a reconnect replays almost
    nothing instead of relisting the world.

Encode once, fan out bytes: ``_apply`` captures the object's encoded
payload (``api.wire.EncodedPayload`` — wire bytes and JSON bytes, lazily
materialized per codec) exactly once per write and stamps it on the
WatchEvent; the HTTP watch/list planes, the WAL, and replication all serve
those cached bytes verbatim, so a thousand watchers cost ONE encode per
codec instead of a thousand ``json.dumps`` calls (upstream: the cacher
serving pre-encoded protobuf objects).

Ring sizing: each entry holds the event plus the PREVIOUS payload of the
object (its apply-time capture — the only moment the pre-state exists;
in-process callers that mutate objects in place carry the same
elided-history caveat client/informer.py documents).  A ring of R events
serves any watcher or continue token that lags by < R writes; older ones
pay one relist.  Default 4096 ≈ a few MB of manifests under churn.
"""

from __future__ import annotations

import base64
import bisect
import json
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis import lockcheck
from ..component_base import logging as klog
from ..metrics import scheduler_metrics as m
from .store import ADDED, DELETED, MODIFIED, ObjectStore, WatchEvent


class TooOldResourceVersion(ValueError):
    """Requested rv is older than the ring can replay (410 Gone analog):
    the caller must relist from a fresh LIST and re-watch from its rv."""


@dataclass
class _RingEntry:
    ev: WatchEvent
    # the object's EncodedPayload BEFORE this event applied (None for
    # ADDED): what list-at-rv rollback restores.  Holding the payload —
    # not a manifest dict — means the pre-state was already encoded when
    # ITS event applied; rollback decodes it lazily and the ring stays an
    # index over payloads, never a second copy of the data.
    prev_payload: Optional[object]


class _CacheWatcher:
    __slots__ = ("handler", "on_error", "on_bookmark", "syncing", "pending")

    def __init__(self, handler, on_error, on_bookmark):
        self.handler = handler
        self.on_error = on_error
        self.on_bookmark = on_bookmark
        # True while the initial ring replay is still being delivered:
        # concurrent live events buffer in ``pending`` (appended under the
        # cache lock) and drain IN ORDER before the watcher goes live — the
        # no-gap, no-reorder handoff the store gets from replaying under
        # its big lock, without holding any lock across handler calls
        self.syncing = True
        self.pending: List[WatchEvent] = []


def _encode_continue(rv: int, after: Tuple[str, str]) -> str:
    raw = json.dumps({"rv": rv, "after": list(after)},
                     separators=(",", ":")).encode()
    return base64.urlsafe_b64encode(raw).decode()


def _decode_continue(token: str) -> Tuple[int, Tuple[str, str]]:
    try:
        body = json.loads(base64.urlsafe_b64decode(token.encode()))
        return int(body["rv"]), (body["after"][0], body["after"][1])
    except (ValueError, KeyError, IndexError, TypeError) as e:
        raise ValueError(f"malformed continue token: {e}")


class WatchCache:
    """One cache per store; construct AFTER the store holds its seed state
    or before — the constructor's subscription replays full history."""

    def __init__(self, store: ObjectStore, scheme=None,
                 ring_size: int = 4096):
        self._store = store
        if scheme is None:
            # resolve eagerly: scheme() must be a pure read — _apply calls
            # it on the writer's thread outside the cache lock
            from ..api.scheme import default_scheme

            scheme = default_scheme()
        self._scheme = scheme
        self.ring_size = ring_size
        self._lock = lockcheck.maybe_wrap(threading.RLock(),
                                          "WatchCache._lock")
        self._objects: Dict[Tuple[str, str, str], object] = {}
        # rv-ascending event ring: a plain list + parallel rv index so
        # since_rv replay BISECTS to its start instead of scanning (a
        # thousand-watcher resync must cost its GAP, not the ring length);
        # compaction drops the oldest half-chunk when length exceeds
        # 2×ring_size — O(1) amortized, retained window ∈ [ring_size, 2×]
        self._ring: List[_RingEntry] = []
        self._ring_rvs: List[int] = []
        self._rv = 0
        # highest rv whose fan-out to live watchers has COMPLETED: _apply
        # advances _rv under the lock but delivers outside it, so a
        # bookmark must never claim an rv whose event a watcher has not
        # been handed yet — bookmarks read this watermark, lists read _rv
        self._fanned_rv = 0
        # rv of the NEWEST event compacted out of the ring: since_rv below
        # this cannot be served (events after it are gone) → 410
        self._compacted_rv = 0
        self._watchers: List[_CacheWatcher] = []
        # optional external watermark (sim/replication.py): a follower
        # replica clamps bookmarks to min(fanned_rv, gate()) — the PR-10
        # no-overclaim invariant extended ACROSS processes.  The gate is a
        # zero-arg callable returning the replication applied_rv; None (the
        # default, single-process caches) costs one attribute read.
        self.bookmark_gate: Optional[Callable[[], int]] = None
        # Event, not a bare bool: the bookmark thread polls it cross-thread
        # (its wait() doubles as the cadence sleep, so close() interrupts a
        # mid-interval sleep instead of waiting it out)
        self._stop = threading.Event()
        self._bookmark_thread: Optional[threading.Thread] = None
        # single-entry page memo: (rv, kind) → (snapshot, sorted keys).
        # A paginated walk hits list_page once per page at ONE rv — without
        # this, every page re-copies and re-sorts the whole kind
        # (O(N²/limit) per walk); with it, the walk costs one snapshot
        # total.  One entry suffices (walks are sequential per token) and
        # a stale entry is just replaced.
        self._page_memo: Optional[Tuple[int, str, dict, list]] = None
        # subscribing replays the store's full history through _apply under
        # the store lock — the cache is consistent from its first instant.
        # No on_error: an in-process synchronous subscriber is never
        # chaos-dropped (store contract), so the cache itself cannot lose
        # the stream it re-serves.
        self._unwatch = store.watch(self._apply)

    def scheme(self):
        return self._scheme

    # --- write side: the store's fan-out ------------------------------------

    def _key(self, ev: WatchEvent) -> Tuple[str, str, str]:
        meta = ev.obj.metadata
        ns = ("" if ev.kind in ObjectStore.CLUSTER_SCOPED
              else getattr(meta, "namespace", ""))
        return (ev.kind, ns, meta.name)

    def _apply(self, ev: WatchEvent) -> None:
        """Apply one store event to snapshot + ring, then fan out.

        Runs on the writer's thread under the STORE lock (we are a store
        watcher) — but handler/callback invocation happens OUTSIDE the
        cache lock, so no lock order cache→anything is ever created."""
        from ..api import wire

        key = self._key(ev)
        scheme = self.scheme()
        # THE encode-once moment: capture the object's payload exactly once
        # per write and stamp it on the event — every serving plane
        # downstream (HTTP fan-out, LIST, WAL, replication) reuses it
        ev.payload = wire.payload_for(ev.obj, scheme)
        with self._lock:
            prev = self._objects.get(key)
            # the pre-state's payload was captured when ITS event applied,
            # so this is a memo hit, not an encode
            prev_payload = (wire.payload_for(prev, scheme)
                            if prev is not None else None)
            if ev.type == DELETED:
                self._objects.pop(key, None)
            else:
                self._objects[key] = ev.obj
            self._rv = ev.resource_version
            self._ring.append(_RingEntry(ev, prev_payload))
            self._ring_rvs.append(ev.resource_version)
            if len(self._ring) > 2 * self.ring_size:
                drop = len(self._ring) - self.ring_size
                self._compacted_rv = self._ring_rvs[drop - 1]
                del self._ring[:drop]
                del self._ring_rvs[:drop]
                m.watch_cache_oldest_rv.set(float(self._compacted_rv))
            m.watch_cache_ring_occupancy.set(float(len(self._ring)))
            live: List[_CacheWatcher] = []
            dropped: List[_CacheWatcher] = []
            drop = False
            fault = self._store.fault
            if fault is not None and any(w.on_error is not None
                                         for w in self._watchers):
                name = getattr(ev.obj.metadata, "name", "")
                # memoized by (kind, name, rv): the cache layer reaches the
                # SAME deterministic decision as the store/apiserver layers
                drop = fault.should_drop_watch(ev.kind, name,
                                               rv=ev.resource_version)
            for w in self._watchers:
                if w.syncing:
                    # still mid-attach: buffer instead of dropping — its
                    # watch() call has not returned, so an on_error fired
                    # now would race the caller's own handle assignment
                    w.pending.append(ev)
                elif drop and w.on_error is not None:
                    dropped.append(w)
                else:
                    live.append(w)
            for w in dropped:
                self._watchers.remove(w)
        for w in dropped:
            from ..chaos.faults import WatchDropped

            w.on_error(WatchDropped(
                f"chaos: watch dropped at {ev.kind} "
                f"rv={ev.resource_version}"))
        for w in live:
            w.handler(ev)
        with self._lock:
            # fan-out complete: bookmarks may now cover this rv (store
            # emits are serialized under its lock, so no later event's
            # watermark can be overtaken by an earlier in-flight one)
            self._fanned_rv = ev.resource_version

    # --- read side: served with ZERO store-lock acquisitions ------------------

    def current_rv(self) -> int:
        with self._lock:
            return self._rv

    def fanned_rv(self) -> int:
        """Highest rv every live watcher has been handed (the rv a
        BOOKMARK may safely carry — see _apply)."""
        with self._lock:
            return self._fanned_rv

    def bookmark_rv(self) -> int:
        """The rv a BOOKMARK may carry RIGHT NOW: fanned_rv, clamped to the
        replication watermark when a ``bookmark_gate`` is wired (a follower
        must never bookmark past what it has provably applied — the
        cross-process half of the no-overclaim invariant)."""
        gate = self.bookmark_gate
        rv = self.fanned_rv()
        return min(rv, gate()) if gate is not None else rv

    @property
    def ring_occupancy(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def oldest_rv(self) -> int:
        """Oldest since_rv still servable (410 below this)."""
        with self._lock:
            return self._compacted_rv

    def list(self, kind: str) -> Tuple[List[object], int]:
        """The store's (objects, rv) list contract, from the snapshot."""
        with self._lock:
            objs = [o for (k, _, _), o in self._objects.items() if k == kind]
            return objs, self._rv

    def _objects_at(self, rv: int) -> Dict[Tuple[str, str, str], object]:
        """Snapshot as of ``rv``: the current map rolled back through the
        ring's pre-state manifests.  Caller holds the cache lock."""
        if rv >= self._rv:
            return dict(self._objects)
        if rv < self._compacted_rv:
            raise TooOldResourceVersion(
                f"resourceVersion {rv} is too old "
                f"(oldest replayable: {self._compacted_rv})")
        out = dict(self._objects)
        start = bisect.bisect_right(self._ring_rvs, rv)
        for entry in reversed(self._ring[start:]):
            key = self._key(entry.ev)
            if entry.prev_payload is None:  # ADDED: did not exist before
                out.pop(key, None)
            else:
                manifest = entry.prev_payload.manifest()
                obj = self.scheme().decode(manifest)
                # decode drops resourceVersion on purpose (server write
                # paths re-stamp it); a rolled-back object must carry the
                # rv it HAD, or list-at-rv would not be bit-faithful
                prev_rv = (manifest.get("metadata") or {}) \
                    .get("resourceVersion")
                if prev_rv:
                    obj.metadata.resource_version = int(prev_rv)
                out[key] = obj
        return out

    def list_page(self, kind: str, limit: int = 0,
                  continue_: Optional[str] = None,
                  resource_version: Optional[int] = None
                  ) -> Tuple[List[object], int, str]:
        """rv-consistent pagination: (objects, rv, continue token; '' when
        exhausted).  Every page of one walk is served at the token's rv —
        writes between pages cannot add, drop, or duplicate items.  A token
        whose rv has been compacted out of the ring raises
        TooOldResourceVersion (the 410 the reference returns for an expired
        continue token)."""
        after: Tuple[str, str] = ("", "")
        with self._lock:
            if continue_:
                rv, after = _decode_continue(continue_)
            elif resource_version is not None:
                rv = resource_version
            else:
                rv = self._rv
            if rv < self._compacted_rv:
                # the 410 horizon is the RING's, deterministically — a
                # memoized snapshot must not keep an expired continue
                # token alive past it (clients would see expiry depend on
                # cache-internal eviction timing)
                raise TooOldResourceVersion(
                    f"resourceVersion {rv} is too old "
                    f"(oldest replayable: {self._compacted_rv})")
            memo = self._page_memo
            if memo is not None and memo[0] == rv and memo[1] == kind:
                snapshot, keys = memo[2], memo[3]
            else:
                snapshot = self._objects_at(rv)
                keys = sorted(k for k in snapshot if k[0] == kind)
                self._page_memo = (rv, kind, snapshot, keys)
        if after != ("", ""):
            lo = bisect.bisect_right(keys, (kind,) + after)
            keys = keys[lo:]
        if limit and len(keys) > limit:
            page, rest = keys[:limit], keys[limit:]
            token = _encode_continue(rv, (page[-1][1], page[-1][2]))
        else:
            page, rest, token = keys, [], ""
        return [snapshot[k] for k in page], rv, (token if rest else "")

    # --- watch side -----------------------------------------------------------

    def watch(self, handler: Callable[[WatchEvent], None], since_rv: int = 0,
              on_error: Optional[Callable[[Exception], None]] = None,
              on_bookmark: Optional[Callable[[int], None]] = None):
        """Replay ring events after ``since_rv``, then subscribe — the
        store's watch contract, without its lock.  ``since_rv`` 0 means
        "from the beginning", which the ring can only serve while nothing
        has been compacted; callers starting cold should LIST first and
        watch from the returned rv (the reflector already does).

        Raises TooOldResourceVersion when events after ``since_rv`` have
        been compacted away — the 410 that tells the client to relist."""
        w = _CacheWatcher(handler, on_error, on_bookmark)
        with self._lock:
            if since_rv < self._compacted_rv:
                raise TooOldResourceVersion(
                    f"resourceVersion {since_rv} is too old "
                    f"(oldest replayable: {self._compacted_rv})")
            start = bisect.bisect_right(self._ring_rvs, since_rv)
            backlog = [e.ev for e in self._ring[start:]]
            self._watchers.append(w)
        # deliver the backlog OUTSIDE the lock; live events that raced in
        # buffered to w.pending (under the lock) and drain in order below —
        # then the watcher goes live atomically
        for ev in backlog:
            handler(ev)
        while True:
            with self._lock:
                if not w.pending:
                    w.syncing = False
                    break
                batch, w.pending = w.pending, []
            for ev in batch:
                handler(ev)

        def unwatch():
            with self._lock:
                if w in self._watchers:
                    self._watchers.remove(w)

        return unwatch

    # --- bookmarks ------------------------------------------------------------

    def bookmark_now(self) -> int:
        """Deliver the current rv to every bookmark-consuming watcher (the
        cacher's bookmarkFrequency tick, callable on demand so tests are
        deterministic).  Returns the rv delivered."""
        rv = self.bookmark_rv()
        with self._lock:
            targets = [w for w in self._watchers
                       if w.on_bookmark is not None and not w.syncing]
        for w in targets:
            w.on_bookmark(rv)
        return rv

    def start_bookmarks(self, interval: float = 1.0) -> None:
        """Background bookmark cadence (idempotent)."""
        if self._bookmark_thread is not None:
            return

        def run():
            while not self._stop.wait(interval):
                self.bookmark_now()

        self._bookmark_thread = threading.Thread(
            target=run, name="watchcache-bookmarks", daemon=True)
        self._bookmark_thread.start()

    def close(self) -> None:
        self._stop.set()
        thread, self._bookmark_thread = self._bookmark_thread, None
        if thread is not None:
            # bounded join: the thread wakes from its interval wait as soon
            # as the event is set; the timeout only guards a bookmark
            # delivery already in flight
            thread.join(timeout=5.0)
        if self._unwatch is not None:
            self._unwatch()
            self._unwatch = None
        klog.V(2).info_s("watch cache closed",
                         ring=len(self._ring), rv=self._rv)
