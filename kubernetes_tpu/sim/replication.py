"""WAL-shipped follower read replicas: log shipping, rv-gated watermarks,
and leader failover with follower promotion.

Reference analog: etcd's one-leader-many-reader topology under the
apiserver (raft log shipping in server/etcdserver/raft.go, the follower's
applied-index watermark) combined with the watch cache's bookmark
discipline (storage/cacher/cacher.go) and the apiserver's lease-based
identity (apiserver/pkg/reconcilers/lease.go).  Reads scale horizontally
only if a follower can serve rv-consistent lists/watches while ONE leader
takes writes — and the hard part is surviving lag, torn ship batches, log
truncation, and leader death without ever overclaiming a resourceVersion.

Topology and protocol:

  - the leader is an ordinary ``ObjectStore`` + ``WriteAheadLog`` (PR-10):
    every mutation is length-prefixed, crc-checksummed, and durable before
    it is visible;
  - a ``LogShipper`` tails the leader's WAL file by byte offset, verifies
    records with the same header/crc walk replay uses, and ships them to
    followers in bounded batches after a configurable ``ship_delay`` (the
    model of real replication lag).  Delivery is at-least-once over an
    unreliable "wire" (chaos/replication.py drops and tears batches);
    offset-contiguous apply on the follower makes it exactly-once;
  - a ``FollowerReplica`` persists every verified batch to its OWN log
    file FIRST (durable before visible — the same discipline the leader's
    WAL enforces), then applies it through ``ObjectStore.replay_record``,
    which re-emits watch events so the follower's ``WatchCache`` populates
    and fans out exactly the event stream a leader-side cache would;
  - the replication watermark (``applied_rv``, ``leader_rv``, lag) gates
    follower serving: rv ≤ applied_rv serves locally, bookmarks clamp to
    the watermark (WatchCache.bookmark_gate — the PR-10 no-overclaim
    invariant extended across processes), rv > applied_rv waits
    bounded-then-504s (apiserver/server.py), and rv below the follower's
    ring serves 410 so clients relist against either replica
    interchangeably.

Failover (the raft-shaped part, PR-8 fencing):

  - leader election runs over a coordination store (the analog of etcd
    serving apiserver identity leases) via client/leaderelection.py;
    ``lease_transitions`` is the fencing token — promotion refuses to run
    for an elector that cannot prove it currently holds the lease;
  - ``FollowerReplica.promote()`` replays the shipped log tail from its
    local file (anything persisted but not yet applied), truncation-checks
    the tail exactly like ``replay_on_boot``, then re-opens a
    ``WriteAheadLog`` for appends at the clean end — the follower's file
    IS the new authoritative log (its bytes are a verified prefix of the
    dead leader's, so offsets keep lining up for every other follower:
    the raft log-matching property);
  - the dead leader's UNSHIPPED suffix — records past what the promoted
    follower had persisted — is detected and discarded exactly-once
    (``discard_unshipped_suffix``), and ``divergence_probe`` asserts none
    of those discarded writes (phantom binds above all) leaked into the
    promoted state.  A discarded acknowledged write is the classic
    asynchronous-replication data-loss window; the probe proves it is a
    clean loss, never a divergence.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from ..analysis import lockcheck
from ..chaos.faults import CRASH_MID_PROMOTE, maybe_crash
from ..component_base import logging as klog
from ..metrics import scheduler_metrics as m
from .store import ObjectStore
from .wal import WriteAheadLog, WALRecord, read_records, scan_records
from .watchcache import WatchCache


class PromotionFenced(RuntimeError):
    """promote() refused: the elector cannot prove current leadership
    (not leading, or the lease's transition count moved past its fencing
    token).  In a promotion race, exactly one follower's elector wins the
    CAS on the election lease — every other candidate lands here."""


@dataclass
class ShipBatch:
    """One in-flight batch of raw WAL bytes (length-prefixed + crc-checked
    records, sliced on record boundaries)."""
    data: bytes
    from_offset: int   # leader-file byte offset of data[0]
    leader_rv: int     # leader's verified-tail rv when the batch was cut
    seq: int           # global batch sequence (chaos decisions key on it)
    due: int           # shipper tick at which delivery happens (lag model)


@dataclass
class PromotionResult:
    name: str
    records_replayed: int = 0     # shipped-but-unapplied tail re-applied
    last_rv: int = 0
    truncated_tail: bool = False  # local persist was torn mid-crash
    truncated_at: int = 0
    wal: Optional[WriteAheadLog] = None


@dataclass
class DiscardResult:
    """Outcome of discarding a dead leader's unshipped WAL suffix."""
    cut_at: int = 0
    discarded: List[WALRecord] = field(default_factory=list)
    truncated_bytes: int = 0   # 0 on the second call: discard is exactly-once


class FollowerReplica:
    """One read replica: its own store + watch cache, fed only by shipped
    WAL records, persisting them locally before applying (so promotion can
    replay the tail and re-open the log for appends)."""

    def __init__(self, name: str, wal_path: str, *, scheme=None,
                 ring_size: int = 4096):
        self.name = name
        self.wal_path = wal_path
        self._scheme = scheme  # lazy: default_scheme pulls in controllers
        self.role = "follower"
        self.store = ObjectStore()
        self._applied_offset = 0
        self._applied_rv = 0
        self._leader_rv = 0
        self.ship_errors = 0
        self.batches_applied = 0
        # Condition over an RLock: deliver holds it across the store apply
        # (replay_record re-emits into the watch cache synchronously on
        # this thread), and rv-gated HTTP readers wait on it bounded —
        # FollowerReplica.wait_for_rv is the 504 gate's clock.
        # maybe_wrap keeps the RLock visible to an active LockMonitor and
        # the access sanitizer (CheckedLock implements the Condition
        # owner/release/restore protocol, so wait() keeps held-stack
        # bookkeeping exact across the full reentrant release)
        self._cond = threading.Condition(
            lockcheck.maybe_wrap(threading.RLock(), "FollowerReplica._cond"))
        # rejoin path: a previous incarnation's persisted log reconstructs
        # the store exactly like a leader boot would — including the
        # torn-tail truncation (our own persist may have died mid-write).
        # Under _cond like every other scheme()/watermark writer — the
        # ctor is single-threaded, but one lock story beats two.
        if os.path.exists(wal_path) and os.path.getsize(wal_path) > 0:
            from .wal import replay_on_boot

            with self._cond:
                replay = replay_on_boot(wal_path, store=self.store,
                                        scheme=self.scheme())
                self._applied_offset = os.path.getsize(wal_path)
                self._applied_rv = replay.last_rv
                self._leader_rv = replay.last_rv
        # the cache replays the (possibly rebooted) store's history, then
        # follows every replay_record emit; bookmarks clamp to the
        # replication watermark — the cross-process no-overclaim rule
        self.watch_cache = WatchCache(self.store, scheme=self._scheme,
                                      ring_size=ring_size)
        self.watch_cache.bookmark_gate = self.applied_rv
        # followers are read-only: a local write would fork this store's
        # history from the leader's log (FollowerReadOnly on every verb;
        # replay_record is exempt).  promote() clears the flag.
        self.store.read_only = True
        self._f = open(wal_path, "ab")
        m.replication_applied_rv.set(float(self._applied_rv), (name,))
        m.apiserver_role.set(1.0, (name, "follower"))

    def scheme(self):
        if self._scheme is None:
            from ..api.scheme import default_scheme

            self._scheme = default_scheme()
        return self._scheme

    # --- watermark -----------------------------------------------------------

    def applied_rv(self) -> int:
        with self._cond:
            return self._applied_rv

    def leader_rv(self) -> int:
        """Leader's verified-tail rv as of the last batch this follower
        RECEIVED (a fully-partitioned follower reports a stale leader_rv —
        lag is a lower bound, exactly like a raft follower's view)."""
        with self._cond:
            return self._leader_rv

    def lag_rv(self) -> int:
        with self._cond:
            return max(0, self._leader_rv - self._applied_rv)

    def acked_offset(self) -> int:
        """Byte offset of the leader's file this follower has durably
        applied through — the shipper's resend cursor."""
        with self._cond:
            return self._applied_offset

    def wait_for_rv(self, rv: int, timeout: float) -> bool:
        """Block until applied_rv ≥ rv or ``timeout`` elapses (the
        bounded-then-504 gate for follower reads above the watermark)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._applied_rv < rv:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return False
                self._cond.wait(remain)
            return True

    # --- ship-apply (the wire's receive side) --------------------------------

    def deliver(self, data: bytes, from_offset: int, leader_rv: int) -> int:
        """Verify + persist + apply one shipped batch; returns records
        applied.  Tolerates the wire's failure modes without ever applying
        an unverifiable or non-contiguous byte:

          - batch from a FUTURE offset (an earlier batch was dropped):
            rejected whole — the shipper resends from acked_offset;
          - batch overlapping the PAST (resend after a torn prefix
            applied): the already-applied prefix is skipped by offset
            arithmetic, never re-applied — exactly-once;
          - torn batch (cut mid-record): the verified prefix persists and
            applies, the torn remainder is dropped and resent.
        """
        with self._cond:
            if self.role != "follower":
                # a stale shipper delivering to a promoted leader: its own
                # WAL is now authoritative; applying shipped bytes on top
                # would double-apply its history
                self.ship_errors += 1
                m.replication_ship_errors.inc(("stale",))
                return 0
            self._leader_rv = max(self._leader_rv, leader_rv)
            if from_offset > self._applied_offset:
                self.ship_errors += 1
                m.replication_ship_errors.inc(("gap",))
                self._refresh_gauges()
                return 0
            skip = self._applied_offset - from_offset
            if skip >= len(data):
                self._refresh_gauges()
                return 0  # entirely already-applied (duplicate resend)
            chunk = data[skip:]
            records, good_len = scan_records(chunk,
                                             base_offset=self._applied_offset)
            if good_len < len(chunk):
                # torn ship batch: apply the verified prefix, count the
                # tear; the shipper resends the remainder from our ack
                self.ship_errors += 1
                m.replication_ship_errors.inc(("torn",))
            if good_len == 0:
                self._refresh_gauges()
                return 0
            # durable before visible, follower edition: the verified bytes
            # reach OUR log file before the store applies them, so a crash
            # mid-apply leaves a shipped tail promote()/reboot replays —
            # never an applied-but-unpersisted rv the watermark overclaims
            self._f.write(chunk[:good_len])
            self._f.flush()
            scheme = self.scheme()
            for _, rec in records:
                obj = rec.decode_obj(scheme)
                self.store.replay_record(
                    rec.op, rec.kind, obj=obj, namespace=rec.namespace,
                    name=rec.name, node_name=rec.node_name, rv=rec.rv)
                self._applied_rv = rec.rv
            self._applied_offset += good_len
            self.batches_applied += 1
            self._refresh_gauges()
            self._cond.notify_all()
            return len(records)

    def _refresh_gauges(self):
        m.replication_applied_rv.set(float(self._applied_rv), (self.name,))
        m.replication_lag_rv.set(
            float(max(0, self._leader_rv - self._applied_rv)), (self.name,))

    # --- promotion -----------------------------------------------------------

    def promote(self, elector=None, *, fsync_every: int = 1
                ) -> PromotionResult:
        """Become the leader: replay the shipped log tail, fence, re-open
        the WAL for appends at the truncation-checked tail.

        ``elector`` (client/leaderelection.LeaderElector over the replica
        set's coordination store, or None for unfenced test use) must
        PROVE current leadership — ``check_fence`` re-reads the live lease
        and compares holder + lease_transitions against the token captured
        at acquire.  Two followers racing here serialize through the lease
        CAS: exactly one promotes, the loser raises PromotionFenced.

        Idempotent across a crash mid-promotion (``crash.mid_promote``):
        everything before the WAL reattach is derived from the durable
        local file, so a fresh FollowerReplica on the same path can simply
        promote again."""
        if elector is not None and not (elector.is_leader()
                                        and elector.check_fence()):
            raise PromotionFenced(
                f"{self.name}: cannot promote without holding the "
                f"replica-set lease (fence token "
                f"{getattr(elector, 'fence_token', None)})")
        with self._cond:
            # the shipped tail is durable before anything changes role
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
            maybe_crash(CRASH_MID_PROMOTE)
            result = PromotionResult(name=self.name)
            records, good_end = read_records(self.wal_path)
            size = os.path.getsize(self.wal_path)
            if good_end < size:
                # our own persist died mid-write: the torn tail truncates
                # exactly like a leader boot's (replay_on_boot contract),
                # durably, so the re-opened log appends at a clean end
                result.truncated_tail = True
                result.truncated_at = good_end
                with open(self.wal_path, "r+b") as f:
                    f.truncate(good_end)
                    f.flush()
                    os.fsync(f.fileno())
            # replay the shipped-but-unapplied tail (persisted by deliver,
            # not yet applied when the old incarnation stopped)
            scheme = self.scheme()
            for off, rec in records:
                if off < self._applied_offset:
                    continue
                obj = rec.decode_obj(scheme)
                self.store.replay_record(
                    rec.op, rec.kind, obj=obj, namespace=rec.namespace,
                    name=rec.name, node_name=rec.node_name, rv=rec.rv)
                self._applied_rv = rec.rv
                result.records_replayed += 1
            self._applied_offset = good_end
            self.store.rebuild_admission_caches()
            # the follower's file becomes the authoritative log: appends
            # land at the truncation-checked tail; a successor of OUR
            # death must lose nothing acknowledged, so fsync every append
            self.store.read_only = False
            self.store.wal = WriteAheadLog(self.wal_path,
                                           scheme=self._scheme,
                                           fsync_every=fsync_every)
            result.wal = self.store.wal
            result.last_rv = self._applied_rv
            self.role = "leader"
            # bookmarks now follow the cache's own fanned watermark — the
            # leader's no-overclaim story is PR-10's single-process one
            self.watch_cache.bookmark_gate = None
            self._refresh_gauges()
            self._cond.notify_all()
        m.apiserver_role.set(0.0, (self.name, "follower"))
        m.apiserver_role.set(1.0, (self.name, "leader"))
        klog.V(1).info_s("follower promoted", name=self.name,
                         last_rv=result.last_rv,
                         replayed=result.records_replayed,
                         truncated=result.truncated_tail)
        return result

    def close(self):
        with self._cond:
            if not self._f.closed:
                self._f.flush()
                self._f.close()
        self.watch_cache.close()


class LogShipper:
    """Tail a leader's WAL file and stream verified records to followers.

    Pump-driven and deterministic: ``pump()`` advances one tick — scan the
    file's new bytes (verifying with the same length/crc walk replay
    uses), deliver batches whose ship delay has elapsed, and cut new
    batches for followers that are behind.  A batch is only cut when the
    follower has NOTHING in flight, always from its acked offset — so a
    dropped or torn batch is re-cut automatically next tick (at-least-once
    ship + offset-contiguous apply = exactly-once records).

    ``faults`` (chaos/replication.py ShipFaults, or None) decides drops,
    tears, and lag spikes per batch, deterministically by batch seq."""

    def __init__(self, wal_path: str, *, name: str = "leader",
                 batch_max_records: int = 64, ship_delay: int = 0,
                 faults=None):
        self.wal_path = wal_path
        self.name = name
        self.batch_max_records = batch_max_records
        self.ship_delay = ship_delay
        self.faults = faults
        self._followers: List[FollowerReplica] = []
        self._pending: Dict[str, Deque[ShipBatch]] = {}
        self._scan_offset = 0        # verified prefix length so far
        self._boundaries: List[int] = []  # record END offsets, ascending
        self._leader_rv = 0
        self._tick = 0
        self._seq = 0
        self.batches_shipped = 0
        self.records_shipped = 0
        self.scan_regressions = 0

    def leader_rv(self) -> int:
        return self._leader_rv

    @property
    def verified_offset(self) -> int:
        return self._scan_offset

    def attach(self, follower: FollowerReplica) -> None:
        """Register a follower; it resumes from its own acked offset
        (fresh = 0, a rejoining replica = its replayed file size — byte
        offsets line up because its file is a verified prefix of ours)."""
        self._followers.append(follower)
        self._pending[follower.name] = deque()

    def detach(self, follower: FollowerReplica) -> None:
        self._followers = [f for f in self._followers if f is not follower]
        self._pending.pop(follower.name, None)

    # --- the tick ------------------------------------------------------------

    def pump(self) -> int:
        """One ship round; returns records applied by followers this
        tick."""
        self._tick += 1
        self._scan()
        applied = 0
        for f in self._followers:
            q = self._pending[f.name]
            while q and q[0].due <= self._tick:
                batch = q.popleft()
                data = batch.data
                if self.faults is not None:
                    verdict = self.faults.ship_fault(f.name, batch.seq,
                                                     len(data))
                    if verdict is not None:
                        kind, keep = verdict
                        if kind == "drop":
                            continue  # lost on the wire; re-cut next tick
                        if kind == "torn":
                            data = data[:keep]
                applied += f.deliver(data, batch.from_offset,
                                     batch.leader_rv)
            if not q:
                cursor = f.acked_offset()
                if cursor < self._scan_offset:
                    delay = self.ship_delay
                    if self.faults is not None:
                        delay += self.faults.lag_spike(f.name)
                    for data, off in self._slice(cursor):
                        self._seq += 1
                        q.append(ShipBatch(data=data, from_offset=off,
                                           leader_rv=self._leader_rv,
                                           seq=self._seq,
                                           due=self._tick + delay))
                        self.batches_shipped += 1
            m.replication_lag_rv.set(
                float(max(0, self._leader_rv - f.applied_rv())), (f.name,))
        self.records_shipped += applied
        return applied

    def pump_until_synced(self, max_pumps: int = 10_000) -> int:
        """Pump until every follower acked the verified tail (bounded);
        returns pumps used.  The convergence helper tests and the soak's
        drain phase call."""
        for i in range(max_pumps):
            self.pump()
            if all(f.acked_offset() >= self._scan_offset
                   and not self._pending[f.name]
                   for f in self._followers):
                return i + 1
        return max_pumps

    # --- file tailing --------------------------------------------------------

    def _scan(self) -> None:
        """Advance the verified prefix over the file's new bytes.

        Only VERIFIED bytes ever advance the cursor, so a torn tail is
        re-read every tick until it either verifies (it never will) or the
        owner truncates it away (replay_on_boot's durable cut) and clean
        appends land at the same offset — the follower-attaching-
        mid-truncation contract the regression test pins."""
        try:
            size = os.path.getsize(self.wal_path)
        except OSError:
            return
        if size < self._scan_offset:
            # the file shrank BELOW the verified prefix: an out-of-protocol
            # rewrite (never the torn-tail truncation, which cuts at our
            # own good_end or later).  Refuse to guess: count it and stop
            # shipping rather than stream bytes that no longer line up.
            self.scan_regressions += 1
            m.replication_ship_errors.inc(("regressed",))
            return
        if size == self._scan_offset:
            return
        with open(self.wal_path, "rb") as f:
            f.seek(self._scan_offset)
            data = f.read()
        records, good_len = scan_records(data, base_offset=self._scan_offset)
        if not records:
            return
        # each record's END is the next record's offset; the last ends the
        # verified prefix — batches slice on these boundaries only
        ends = [records[i + 1][0] for i in range(len(records) - 1)]
        ends.append(self._scan_offset + good_len)
        self._boundaries.extend(ends)
        self._scan_offset += good_len
        self._leader_rv = records[-1][1].rv

    def _slice(self, cursor: int) -> List[Tuple[bytes, int]]:
        """Cut [cursor, verified_end) into batches of at most
        ``batch_max_records`` records, on record boundaries."""
        lo = bisect.bisect_right(self._boundaries, cursor)
        ends = self._boundaries[lo:]
        out: List[Tuple[bytes, int]] = []
        with open(self.wal_path, "rb") as f:
            start = cursor
            while ends:
                take = ends[:self.batch_max_records]
                ends = ends[self.batch_max_records:]
                end = take[-1]
                f.seek(start)
                out.append((f.read(end - start), start))
                start = end
        return out


# --- unshipped-suffix discard + divergence probe ------------------------------


def discard_unshipped_suffix(wal_path: str,
                             shipped_offset: int) -> DiscardResult:
    """Detect and discard, exactly once, a dead leader's WAL records past
    what the promoted follower had persisted (``shipped_offset`` — the new
    leader's file size at promotion; byte offsets line up because the
    follower's file is a verified prefix of the leader's).

    The discarded records are acknowledged writes the asynchronous ship
    stream never carried: the classic replication-lag loss window.  They
    are returned for the divergence probe (and forensics); the file is
    truncated durably so a rejoin of the old leader as a follower resumes
    from the common prefix — calling again discards nothing (the
    exactly-once contract the chaos battery pins)."""
    result = DiscardResult()
    if not os.path.exists(wal_path):
        return result
    records, good_end = read_records(wal_path)
    cut = min(shipped_offset, good_end)
    result.cut_at = cut
    result.discarded = [rec for off, rec in records if off >= cut]
    size = os.path.getsize(wal_path)
    if size > cut:
        result.truncated_bytes = size - cut
        with open(wal_path, "r+b") as f:
            f.truncate(cut)
            f.flush()
            os.fsync(f.fileno())
        klog.V(1).info_s("unshipped WAL suffix discarded", path=wal_path,
                         cut_at=cut, records=len(result.discarded),
                         bytes=result.truncated_bytes)
    return result


def divergence_probe(store: ObjectStore, discarded: List[WALRecord],
                     shipped_rv: int) -> List[str]:
    """Assert the promoted store carries NO trace of the discarded
    suffix: run immediately after promotion, before the new leader takes
    writes.  Returns human-readable phantom descriptions (empty = clean).

    A phantom is state only the discarded records could explain — a pod
    bound to the node a discarded bind named at or past that bind's rv, an
    object standing at a discarded write's rv, or any rv past the shipped
    watermark."""
    phantoms: List[str] = []
    current = store.current_rv()
    if current > shipped_rv:
        phantoms.append(
            f"store rv {current} is past the shipped watermark "
            f"{shipped_rv}")
    for rec in discarded:
        obj = store.get(rec.kind, rec.namespace, rec.name)
        if rec.op == "bind":
            if obj is not None and \
                    getattr(obj.spec, "node_name", "") == rec.node_name and \
                    obj.metadata.resource_version >= rec.rv:
                phantoms.append(
                    f"phantom bind: {rec.namespace}/{rec.name} -> "
                    f"{rec.node_name} (discarded rv {rec.rv})")
        elif rec.op in ("create", "update"):
            if obj is not None and \
                    obj.metadata.resource_version >= rec.rv:
                phantoms.append(
                    f"phantom {rec.op}: {rec.kind} "
                    f"{rec.namespace}/{rec.name} at rv "
                    f"{obj.metadata.resource_version} "
                    f"(discarded rv {rec.rv})")
    return phantoms


def rebase_follower(follower: FollowerReplica,
                    to_offset: int) -> Tuple[FollowerReplica,
                                             List[WALRecord]]:
    """Roll a promotion LOSER back to the new leader's log length.

    A loser that had applied FURTHER than the winner persisted holds
    records the new authoritative log lacks (it was simply luckier on the
    wire) — raft resolves this by truncating the follower's log to match
    the leader's.  The in-memory store cannot un-apply, so the rebase
    truncates the local file durably and reconstructs a fresh
    FollowerReplica from it; returns (new_replica, rolled_back_records)
    so the harness can re-point watchers and account the rollback."""
    follower.close()
    records, good_end = read_records(follower.wal_path)
    cut = min(to_offset, good_end)
    rolled = [rec for off, rec in records if off >= cut]
    if os.path.getsize(follower.wal_path) > cut:
        with open(follower.wal_path, "r+b") as f:
            f.truncate(cut)
            f.flush()
            os.fsync(f.fileno())
    fresh = FollowerReplica(follower.name, follower.wal_path,
                            scheme=follower._scheme,
                            ring_size=follower.watch_cache.ring_size)
    return fresh, rolled
