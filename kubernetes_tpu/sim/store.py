"""Object store + watch fan-out (the etcd/apiserver stand-in).

Reference behaviors mirrored:
  - monotonically increasing resourceVersion per write (etcd3 store semantics)
  - LIST returns a consistent snapshot + the rv to start WATCH from
  - WATCH delivers ordered Added/Modified/Deleted events from a given rv
    (storage/etcd3/watcher.go:118; watch cache cacher.go)
  - binding subresource: POST pods/{name}/binding → sets spec.nodeName
    (plugins/defaultbinder)
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ..api import objects as v1

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


@dataclass
class WatchEvent:
    type: str
    kind: str
    obj: object
    resource_version: int


class ObjectStore:
    """Thread-safe store; watchers receive events synchronously in rv order."""

    def __init__(self):
        self._lock = threading.RLock()
        self._rv = 0
        self._objects: Dict[Tuple[str, str, str], object] = {}
        self._log: List[WatchEvent] = []  # full event history (bounded use: sim)
        self._watchers: List[Callable[[WatchEvent], None]] = []

    # --- helpers -------------------------------------------------------------

    CLUSTER_SCOPED = {"Node", "PersistentVolume", "StorageClass", "CSINode",
                      "PriorityClass"}

    @classmethod
    def _key(cls, kind: str, obj) -> Tuple[str, str, str]:
        meta = obj.metadata
        ns = "" if kind in cls.CLUSTER_SCOPED else getattr(meta, "namespace", "")
        return (kind, ns, meta.name)

    def _emit(self, ev: WatchEvent):
        self._log.append(ev)
        for w in list(self._watchers):
            w(ev)

    # --- CRUD ----------------------------------------------------------------

    def create(self, kind: str, obj) -> int:
        with self._lock:
            if kind == "Pod":
                self._admit_pod(obj)
            key = self._key(kind, obj)
            if key in self._objects:
                raise ValueError(f"{key} already exists")
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._objects[key] = obj
            self._emit(WatchEvent(ADDED, kind, obj, self._rv))
            return self._rv

    def update(self, kind: str, obj) -> int:
        with self._lock:
            key = self._key(kind, obj)
            if key not in self._objects:
                raise KeyError(key)
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._objects[key] = obj
            self._emit(WatchEvent(MODIFIED, kind, obj, self._rv))
            return self._rv

    def delete(self, kind: str, namespace: str, name: str) -> Optional[object]:
        if kind in self.CLUSTER_SCOPED:
            namespace = ""
        with self._lock:
            obj = self._objects.pop((kind, namespace, name), None)
            if obj is None:
                return None
            self._rv += 1
            self._emit(WatchEvent(DELETED, kind, obj, self._rv))
            return obj

    def get(self, kind: str, namespace: str, name: str) -> Optional[object]:
        if kind in self.CLUSTER_SCOPED:
            namespace = ""
        with self._lock:
            return self._objects.get((kind, namespace, name))

    def list(self, kind: str) -> Tuple[List[object], int]:
        with self._lock:
            objs = [o for (k, _, _), o in self._objects.items() if k == kind]
            return objs, self._rv

    # --- watch ---------------------------------------------------------------

    def watch(self, handler: Callable[[WatchEvent], None], since_rv: int = 0):
        """Replays history after since_rv, then subscribes (list+watch contract)."""
        with self._lock:
            for ev in self._log:
                if ev.resource_version > since_rv:
                    handler(ev)
            self._watchers.append(handler)
            return lambda: self._watchers.remove(handler)

    def _admit_pod(self, pod) -> None:
        """Priority admission: resolve priorityClassName → spec.priority
        (reference: plugin/pkg/admission/priority)."""
        spec = pod.spec
        if spec.priority:
            return
        name = spec.priority_class_name
        pc = None
        if name:
            pc = self._objects.get(("PriorityClass", "", name))
        else:
            pc = next(
                (o for (k, _, _), o in self._objects.items()
                 if k == "PriorityClass" and o.global_default),
                None,
            )
        if pc is not None:
            spec.priority = pc.value
            spec.preemption_policy = pc.preemption_policy

    # --- binding subresource --------------------------------------------------

    def bind_pod(self, namespace: str, name: str, node_name: str) -> bool:
        with self._lock:
            pod = self.get("Pod", namespace, name)
            if pod is None:
                return False
            pod.spec.node_name = node_name
            self._rv += 1
            pod.metadata.resource_version = self._rv
            self._emit(WatchEvent(MODIFIED, "Pod", pod, self._rv))
            return True
