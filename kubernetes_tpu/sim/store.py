"""Object store + watch fan-out (the etcd/apiserver stand-in).

Reference behaviors mirrored:
  - monotonically increasing resourceVersion per write (etcd3 store semantics)
  - LIST returns a consistent snapshot + the rv to start WATCH from
  - WATCH delivers ordered Added/Modified/Deleted events from a given rv
    (storage/etcd3/watcher.go:118; watch cache cacher.go)
  - binding subresource: POST pods/{name}/binding → sets spec.nodeName
    (plugins/defaultbinder)
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ..analysis import lockcheck
from ..api import objects as v1
from ..component_base import logging as klog

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
# stream-level failure marker (the reference watch protocol's ERROR event,
# apimachinery/pkg/watch): consumers must relist — never a state change
ERROR = "ERROR"


class QuotaExceeded(ValueError):
    """Pod rejected by ResourceQuota admission (403 Forbidden analog)."""


class StaleResourceVersion(ValueError):
    """CAS precondition failed in ObjectStore.update (409 Conflict analog)."""


class FollowerReadOnly(PermissionError):
    """Direct write against a read-only follower store (503 analog).

    A replication follower's store may only change through
    ``replay_record`` (shipped WAL records) — a local write would fork its
    history from the leader's log and every rv it serves afterwards would
    be unprovable.  Promotion (sim/replication.py) clears the flag."""


@dataclass
class WatchEvent:
    type: str
    kind: str
    obj: object
    resource_version: int
    # encode-once serving: the watch cache stamps the object's
    # api.wire.EncodedPayload here at apply time, so every downstream
    # consumer (HTTP fan-out, WAL, replication) serves cached bytes
    # instead of re-serializing.  None for events that never crossed a
    # watch cache (direct store watchers encode on demand).
    payload: object = None


class ObjectStore:
    """Thread-safe store; watchers receive events synchronously in rv order."""

    def __init__(self, fault_injector=None, wal=None):
        # instrumented under an active lockcheck monitor (chaos tests run
        # with lock-order inversion detection); raw RLock otherwise
        self._lock = lockcheck.maybe_wrap(threading.RLock(),
                                          "ObjectStore._lock")
        self._rv = 0
        # optional write-ahead log (sim/wal.WriteAheadLog): every mutation
        # appends its record BEFORE the in-memory apply, so a process death
        # at any point loses at most unacknowledged writes — replay_on_boot
        # reconstructs this store from the file.  None (default) costs one
        # attribute check per write.
        self.wal = wal
        # read-only guard (sim/replication.py FollowerReplica): True while
        # this store is a replication follower — every direct write verb
        # raises FollowerReadOnly; replay_record (the ship-apply path) is
        # exempt.  Defense in depth for the no-divergence invariant: the
        # apiserver already 503s follower writes, but in-process callers
        # holding the store object must hit the same wall.
        self.read_only = False
        # store-lock READ acquisitions (list/get/watch/current_rv): the
        # watch cache's zero-store-lock contract on the list/watch-replay
        # path is asserted against deltas of this counter
        self.read_ops = 0
        self._objects: Dict[Tuple[str, str, str], object] = {}
        self._log: List[WatchEvent] = []  # full event history (bounded use: sim)
        self._watchers: List[Callable[[WatchEvent], None]] = []
        # watcher → on_error callback for watchers that can survive a stream
        # drop (reflectors relist); watchers without one are never dropped —
        # an in-process synchronous callback has no stream to cut
        self._error_cbs: Dict[Callable, Callable] = {}
        # chaos hook (chaos.faults.FaultSchedule-shaped, or None): consulted
        # before every write mutation and on every watch fan-out.  None (the
        # default) costs one attribute check per op.
        self.fault = fault_injector
        # namespaces holding at least one ResourceQuota: pod admission is
        # zero-cost until a quota actually exists somewhere
        self._quota_namespaces: set = set()
        # cached globalDefault PriorityClass: priority admission runs on
        # EVERY pod create, and priority-0 pods would otherwise scan the
        # whole object map for a default each time (profiled: 12s of a 100s
        # 25k-pod preemption suite)
        self._default_priority_class = None
        # per-thread deferred-drop-callback state for _locked_emit
        # (reentrant writes share the outermost frame's pending list)
        self._emit_tls = threading.local()

    # --- helpers -------------------------------------------------------------

    # NOTE: mutated in place by the dynamic-kind registrar for
    # cluster-scoped CRDs — client facades alias this same set object, so
    # scoping changes propagate everywhere at once.
    CLUSTER_SCOPED = {"Node", "PersistentVolume", "StorageClass", "CSINode",
                      "PriorityClass", "Namespace",
                      "DeviceClass", "ResourceSlice",
                      "CustomResourceDefinition",
                      "ClusterRole", "ClusterRoleBinding"}

    @classmethod
    def _key(cls, kind: str, obj) -> Tuple[str, str, str]:
        meta = obj.metadata
        ns = "" if kind in cls.CLUSTER_SCOPED else getattr(meta, "namespace", "")
        return (kind, ns, meta.name)

    def _emit(self, ev: WatchEvent,
              deferred: List[Callable[[], None]]) -> None:
        """Deliver ``ev`` to live watchers; drop callbacks are DEFERRED.

        Ordinary events are delivered synchronously under the store lock
        (the current_rv/watch-bookmark contract needs writes to be fully
        fanned out before the lock releases).  Watch-DROP callbacks are
        NOT: the dropped reflector's recovery acquires its own relist lock
        and then calls back into store.list/watch (relist-lock → store-lock
        order), so invoking it here — under the store lock — inverts that
        order and can deadlock against an in-flight relist.  Found by the
        runtime lockcheck monitor over tests/test_chaos.py.

        Drop thunks go into the CALLER-owned ``deferred`` list, appended
        BEFORE any live delivery and run by the CRUD callers in a finally
        after the lock releases — so a watcher handler that raises
        mid-fan-out (handler bugs propagate by design) cannot strand an
        already-cut watcher without its WatchDropped notification.  The
        stream is cut under the lock either way, so the dropped watcher
        missed this event regardless — and its relist now lists a fully
        committed write."""
        self._log.append(ev)
        drop = False
        if self.fault is not None and self._error_cbs:
            name = getattr(getattr(ev.obj, "metadata", None), "name", "")
            drop = self.fault.should_drop_watch(ev.kind, name,
                                                rv=ev.resource_version)
        live = list(self._watchers)
        if drop:
            # pass 1: cut every resumable stream and queue its callback
            # (the reflector's ListAndWatch restart) before ANY delivery
            for w in live:
                cb = self._error_cbs.get(w)
                if cb is None:
                    continue  # plain callbacks have no relist path
                self._watchers.remove(w)
                del self._error_cbs[w]
                from ..chaos.faults import WatchDropped

                exc = WatchDropped(
                    f"chaos: watch dropped at {ev.kind} rv={ev.resource_version}")
                deferred.append(lambda cb=cb, exc=exc: cb(exc))
            live = [w for w in live if w in self._watchers]
        # pass 2: synchronous delivery to the surviving watchers
        for w in live:
            w(ev)

    @contextmanager
    def _locked_emit(self):
        """Store lock + deferred drop-callback drain, as ONE structural
        unit: every write path MUST use this (never a bare ``with
        self._lock`` around ``_emit``) so the drop callbacks queued by
        _emit always run after the lock releases — even when a watcher
        handler raises mid-fan-out — and never under it (the lock-order
        inversion the runtime lockcheck caught).

        Two hardenings the simple try/finally form lacked:
        - RLock reentrancy: a synchronous watcher callback may write back
          into the store on the same thread; the inner frame's ``with
          self._lock`` exit only decrements the RLock, so draining there
          would run drop callbacks with the lock still held by the outer
          frame.  Callbacks therefore accumulate in per-thread state and
          drain only at the OUTERMOST frame, after the lock fully
          releases.
        - A drop callback that raises must not strand the remaining
          dropped watchers un-notified, nor mask an in-flight write
          exception: every callback runs; the first callback error
          propagates only when the write itself succeeded."""
        tls = self._emit_tls
        depth = getattr(tls, "depth", 0)
        if depth == 0:
            tls.pending = []
        tls.depth = depth + 1
        try:
            with self._lock:
                yield tls.pending
        except BaseException:
            tls.depth = depth
            if depth == 0:
                # the write failed — deliver the notifications anyway, but
                # the write's exception wins; callback errors are logged
                for err in self._drain(tls.pending):
                    klog.error_s(err, "watch-drop callback failed during "
                                      "failing write")
            raise
        else:
            tls.depth = depth
            if depth == 0:
                errors = self._drain(tls.pending)
                if errors:
                    raise errors[0]

    def _drain(self, pending: List[Callable[[], None]]) -> List[BaseException]:
        """Run every deferred callback (outside the lock); collect errors."""
        cbs, pending[:] = list(pending), []
        errors: List[BaseException] = []
        for cb in cbs:
            try:
                cb()
            except Exception as e:
                # collected for the caller (re-raised after a clean write,
                # logged after a failing one) — the loop must finish so one
                # bad callback can't strand the other dropped watchers
                klog.V(2).info_s("deferred watch-drop callback raised",
                                 err=f"{type(e).__name__}: {e}")
                errors.append(e)
        return errors

    # --- CRUD ----------------------------------------------------------------

    def _check_writable(self, op: str, kind: str, name: str) -> None:
        if self.read_only:
            raise FollowerReadOnly(
                f"store is a read-only replication follower: "
                f"{op} {kind}/{name} must go to the leader")

    def create(self, kind: str, obj) -> int:
        self._check_writable("create", kind, obj.metadata.name)
        if self.fault is not None:
            # outside the lock: an injected delay/429 must not stall other
            # writers; raising HERE means the mutation never half-applied,
            # so a client retry is always safe
            self.fault.write_fault("create", kind, obj.metadata.name)
            if self.wal is not None:
                self.fault.wal_fault("create", kind, obj.metadata.name)
        with self._locked_emit() as deferred:
            if kind == "Pod":
                self._admit_pod(obj)
                if self._quota_namespaces:
                    self._admit_quota(obj)
            key = self._key(kind, obj)
            if key in self._objects:
                raise ValueError(f"{key} already exists")
            self._rv += 1
            obj.metadata.resource_version = self._rv
            if self.wal is not None:
                # durable before visible: a raising append aborts the write
                # (object never stored); the log can only run AHEAD of
                # memory — replay treats the logged write as committed, the
                # etcd commit-unknown outcome a retrying client tolerates
                self.wal.append("create", kind, obj=obj, rv=self._rv)
            self._objects[key] = obj
            if kind == "ResourceQuota":
                self._quota_namespaces.add(key[1])
            elif kind == "PriorityClass" and getattr(obj, "global_default",
                                                     False):
                self._default_priority_class = obj
            self._emit(WatchEvent(ADDED, kind, obj, self._rv), deferred)
            return self._rv

    def update(self, kind: str, obj, expected_rv=None) -> int:
        """``expected_rv`` (when not None) is an atomic compare-and-swap
        precondition checked under the store lock: the write applies only if
        the stored object's resourceVersion still equals it, else
        StaleResourceVersion — the etcd3 GuaranteedUpdate contract that makes
        the apiserver's 409 actually prevent lost updates (a handler-level
        check-then-act would race concurrent writers)."""
        self._check_writable("update", kind, obj.metadata.name)
        if self.fault is not None:
            self.fault.write_fault("update", kind, obj.metadata.name)
            if self.wal is not None:
                self.fault.wal_fault("update", kind, obj.metadata.name)
        with self._locked_emit() as deferred:
            key = self._key(kind, obj)
            if key not in self._objects:
                raise KeyError(key)
            if expected_rv is not None:
                cur_rv = self._objects[key].metadata.resource_version
                if str(expected_rv) != str(cur_rv):
                    raise StaleResourceVersion(
                        f"{key}: submitted resourceVersion {expected_rv}, "
                        f"current {cur_rv}")
            self._rv += 1
            obj.metadata.resource_version = self._rv
            if self.wal is not None:
                self.wal.append("update", kind, obj=obj, rv=self._rv)
            self._objects[key] = obj
            if kind == "PriorityClass":
                cached = self._default_priority_class
                if getattr(obj, "global_default", False):
                    self._default_priority_class = obj
                elif cached is not None and \
                        obj.metadata.name == cached.metadata.name:
                    # compare by NAME: an update decodes a fresh object, so
                    # identity would miss the replacement and serve a stale
                    # (possibly demoted) default forever
                    self._default_priority_class = next(
                        (o for (k, _, _), o in self._objects.items()
                         if k == "PriorityClass" and o.global_default), None)
            self._emit(WatchEvent(MODIFIED, kind, obj, self._rv), deferred)
            return self._rv

    def delete(self, kind: str, namespace: str, name: str) -> Optional[object]:
        self._check_writable("delete", kind, name)
        if kind in self.CLUSTER_SCOPED:
            namespace = ""
        if self.fault is not None:
            self.fault.write_fault("delete", kind, name)
            if self.wal is not None:
                self.fault.wal_fault("delete", kind, name)
        with self._locked_emit() as deferred:
            obj = self._objects.get((kind, namespace, name))
            if obj is None:
                return None
            if self.wal is not None:
                self.wal.append("delete", kind, namespace=namespace,
                                name=name, rv=self._rv + 1)
            self._objects.pop((kind, namespace, name))
            if kind == "ResourceQuota" and not any(
                k == "ResourceQuota" and ns == namespace
                for (k, ns, _) in self._objects
            ):
                self._quota_namespaces.discard(namespace)
            elif kind == "PriorityClass" and (
                self._default_priority_class is not None
                and name == self._default_priority_class.metadata.name
            ):
                self._default_priority_class = next(
                    (o for (k, _, _), o in self._objects.items()
                     if k == "PriorityClass" and o.global_default), None)
            self._rv += 1
            self._emit(WatchEvent(DELETED, kind, obj, self._rv), deferred)
            return obj

    def current_rv(self) -> int:
        """The latest resourceVersion, read under the store lock — while
        held, no write is mid-emit, so every event ≤ this rv has been fully
        delivered to watch callbacks (the watch-bookmark correctness
        condition)."""
        with self._lock:
            self.read_ops += 1
            return self._rv

    def get(self, kind: str, namespace: str, name: str) -> Optional[object]:
        if kind in self.CLUSTER_SCOPED:
            namespace = ""
        with self._lock:
            self.read_ops += 1
            return self._objects.get((kind, namespace, name))

    def list(self, kind: str) -> Tuple[List[object], int]:
        with self._lock:
            self.read_ops += 1
            objs = [o for (k, _, _), o in self._objects.items() if k == kind]
            return objs, self._rv

    def list_namespaced(self, namespace: str) -> List[Tuple[str, object]]:
        """Every namespaced object in ``namespace`` as (kind, obj) — the
        namespace controller's deletion-cascade view (reference:
        pkg/controller/namespace/deletion listing served group resources)."""
        with self._lock:
            self.read_ops += 1
            return [
                (k, o) for (k, ns, _), o in self._objects.items()
                if ns == namespace and k not in self.CLUSTER_SCOPED
            ]

    # --- WAL replay (sim/wal.replay_on_boot) ----------------------------------

    def replay_record(self, op: str, kind: str, *, obj=None, namespace="",
                      name="", node_name="", rv: int = 0) -> None:
        """Apply one WAL record verbatim: no admission (the original write
        was admitted before it was logged — re-running quota math against a
        half-rebuilt world would diverge), no fault injection, no WAL
        re-append.  The record's rv is authoritative; the watch history is
        re-emitted so a scheduler cold-starting on the replayed store sees
        the same event stream a live replica did."""
        with self._locked_emit() as deferred:
            if op == "create" or op == "update":
                key = self._key(kind, obj)
                obj.metadata.resource_version = rv
                self._objects[key] = obj
                self._rv = rv
                self._emit(WatchEvent(
                    ADDED if op == "create" else MODIFIED, kind, obj, rv),
                    deferred)
            elif op == "delete":
                if kind in self.CLUSTER_SCOPED:
                    namespace = ""
                old = self._objects.pop((kind, namespace, name), None)
                self._rv = rv
                if old is not None:
                    self._emit(WatchEvent(DELETED, kind, old, rv), deferred)
            elif op == "bind":
                pod = self._objects.get(("Pod", namespace, name))
                self._rv = rv
                if pod is not None:
                    pod.spec.node_name = node_name
                    pod.metadata.resource_version = rv
                    self._emit(WatchEvent(MODIFIED, "Pod", pod, rv), deferred)
            else:
                raise ValueError(f"unknown WAL op {op!r}")

    def rebuild_admission_caches(self) -> None:
        """Recompute the derived admission caches (quota-namespace set,
        default PriorityClass) from the object map — replay applies records
        verbatim and fixes the caches once at the end."""
        with self._lock:
            self._quota_namespaces = {
                ns for (k, ns, _) in self._objects if k == "ResourceQuota"}
            self._default_priority_class = next(
                (o for (k, _, _), o in self._objects.items()
                 if k == "PriorityClass"
                 and getattr(o, "global_default", False)), None)

    # --- watch ---------------------------------------------------------------

    def watch(self, handler: Callable[[WatchEvent], None], since_rv: int = 0,
              on_error: Optional[Callable[[Exception], None]] = None):
        """Replays history after since_rv, then subscribes (list+watch contract).

        ``on_error`` (optional) marks the watcher as RESUMABLE: under chaos
        fault injection its stream may be cut, in which case the callback
        receives a WatchDropped and the watcher must relist + resubscribe
        (client/informer.py Reflector does).  Watchers without one are never
        dropped — a synchronous in-process callback has no stream."""
        with self._lock:
            self.read_ops += 1
            for ev in self._log:
                if ev.resource_version > since_rv:
                    handler(ev)
            self._watchers.append(handler)
            if on_error is not None:
                self._error_cbs[handler] = on_error

            def unwatch():
                with self._lock:
                    if handler in self._watchers:
                        self._watchers.remove(handler)
                    self._error_cbs.pop(handler, None)

            return unwatch

    def _admit_pod(self, pod) -> None:
        """Priority admission: resolve priorityClassName → spec.priority
        (reference: plugin/pkg/admission/priority)."""
        spec = pod.spec
        if spec.priority:
            return
        name = spec.priority_class_name
        if name:
            pc = self._objects.get(("PriorityClass", "", name))
        else:
            pc = self._default_priority_class
        if pc is not None:
            spec.priority = pc.value
            spec.preemption_policy = pc.preemption_policy

    def _admit_quota(self, pod) -> None:
        """ResourceQuota admission: reject the pod if any quota in its
        namespace would be exceeded (reference:
        plugin/pkg/admission/resourcequota).  Used totals are recomputed from
        live pods at admission time — the sim has no async quota status lag,
        and the surrounding create() already holds the store lock."""
        ns = getattr(pod.metadata, "namespace", "")
        if ns not in self._quota_namespaces:
            return
        quotas = [
            o for (k, qns, _), o in self._objects.items()
            if k == "ResourceQuota" and qns == ns
        ]
        if not quotas:
            return
        from ..api.resource import (
            compute_pod_resource_request,
            parse_quantity,
            quantity_to_int,
            quantity_to_milli,
        )

        pods = [
            o for (k, pns, _), o in self._objects.items()
            if k == "Pod" and pns == ns
            and o.status.phase not in ("Succeeded", "Failed")
        ]
        new = compute_pod_resource_request(pod)
        used_cpu = new.milli_cpu + sum(
            compute_pod_resource_request(p).milli_cpu for p in pods)
        used_mem = new.memory + sum(
            compute_pod_resource_request(p).memory for p in pods)
        used_count = 1 + len(pods)
        for q in quotas:
            for key, hard in q.hard.items():
                if key in ("pods", "count/pods"):
                    if used_count > int(parse_quantity(hard)):
                        raise QuotaExceeded(
                            f"exceeded quota {q.metadata.name}: {key} "
                            f"(used {used_count}, hard {hard})")
                elif key in ("cpu", "requests.cpu"):
                    if used_cpu > quantity_to_milli(hard):
                        raise QuotaExceeded(
                            f"exceeded quota {q.metadata.name}: {key} "
                            f"(used {used_cpu}m, hard {hard})")
                elif key in ("memory", "requests.memory"):
                    if used_mem > quantity_to_int(hard):
                        raise QuotaExceeded(
                            f"exceeded quota {q.metadata.name}: {key} "
                            f"(used {used_mem}, hard {hard})")

    # --- binding subresource --------------------------------------------------

    def bind_pod(self, namespace: str, name: str, node_name: str,
                 trace_parent=None) -> bool:
        """``trace_parent`` (a component_base.trace.SpanContext, or None) is
        the scheduler's explicit span handoff: the WAL's append/fsync spans
        for this bind link into the caller's attempt tree instead of
        floating as roots.  Callers probe for the kwarg (the informer's
        signature-probing idiom) so facades without it keep working."""
        self._check_writable("bind", "Pod", name)
        if self.fault is not None:
            self.fault.write_fault("bind", "Pod", name)
            if self.wal is not None:
                self.fault.wal_fault("bind", "Pod", name)
        with self._locked_emit() as deferred:
            pod = self.get("Pod", namespace, name)
            if pod is None:
                return False
            if self.wal is not None:
                # logged before the in-place mutation: a crash between the
                # append and the apply replays the bind — exactly once, to
                # the same node — instead of losing an acknowledged binding
                self.wal.append("bind", "Pod", namespace=namespace,
                                name=name, node_name=node_name,
                                rv=self._rv + 1, trace_parent=trace_parent)
            pod.spec.node_name = node_name
            self._rv += 1
            pod.metadata.resource_version = self._rv
            self._emit(WatchEvent(MODIFIED, "Pod", pod, self._rv), deferred)
            return True
