"""Object store + watch fan-out (the etcd/apiserver stand-in).

Reference behaviors mirrored:
  - monotonically increasing resourceVersion per write (etcd3 store semantics)
  - LIST returns a consistent snapshot + the rv to start WATCH from
  - WATCH delivers ordered Added/Modified/Deleted events from a given rv
    (storage/etcd3/watcher.go:118; watch cache cacher.go)
  - binding subresource: POST pods/{name}/binding → sets spec.nodeName
    (plugins/defaultbinder)
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ..api import objects as v1

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
# stream-level failure marker (the reference watch protocol's ERROR event,
# apimachinery/pkg/watch): consumers must relist — never a state change
ERROR = "ERROR"


class QuotaExceeded(ValueError):
    """Pod rejected by ResourceQuota admission (403 Forbidden analog)."""


class StaleResourceVersion(ValueError):
    """CAS precondition failed in ObjectStore.update (409 Conflict analog)."""


@dataclass
class WatchEvent:
    type: str
    kind: str
    obj: object
    resource_version: int


class ObjectStore:
    """Thread-safe store; watchers receive events synchronously in rv order."""

    def __init__(self, fault_injector=None):
        self._lock = threading.RLock()
        self._rv = 0
        self._objects: Dict[Tuple[str, str, str], object] = {}
        self._log: List[WatchEvent] = []  # full event history (bounded use: sim)
        self._watchers: List[Callable[[WatchEvent], None]] = []
        # watcher → on_error callback for watchers that can survive a stream
        # drop (reflectors relist); watchers without one are never dropped —
        # an in-process synchronous callback has no stream to cut
        self._error_cbs: Dict[Callable, Callable] = {}
        # chaos hook (chaos.faults.FaultSchedule-shaped, or None): consulted
        # before every write mutation and on every watch fan-out.  None (the
        # default) costs one attribute check per op.
        self.fault = fault_injector
        # namespaces holding at least one ResourceQuota: pod admission is
        # zero-cost until a quota actually exists somewhere
        self._quota_namespaces: set = set()
        # cached globalDefault PriorityClass: priority admission runs on
        # EVERY pod create, and priority-0 pods would otherwise scan the
        # whole object map for a default each time (profiled: 12s of a 100s
        # 25k-pod preemption suite)
        self._default_priority_class = None

    # --- helpers -------------------------------------------------------------

    CLUSTER_SCOPED = {"Node", "PersistentVolume", "StorageClass", "CSINode",
                      "PriorityClass", "Namespace"}

    @classmethod
    def _key(cls, kind: str, obj) -> Tuple[str, str, str]:
        meta = obj.metadata
        ns = "" if kind in cls.CLUSTER_SCOPED else getattr(meta, "namespace", "")
        return (kind, ns, meta.name)

    def _emit(self, ev: WatchEvent):
        self._log.append(ev)
        drop = False
        if self.fault is not None and self._error_cbs:
            name = getattr(getattr(ev.obj, "metadata", None), "name", "")
            drop = self.fault.should_drop_watch(ev.kind, name,
                                                rv=ev.resource_version)
        for w in list(self._watchers):
            cb = self._error_cbs.get(w)
            if drop and cb is not None:
                # cut the stream BEFORE delivering: the dropped watcher
                # misses this event and must recover it by relisting (the
                # reflector's ListAndWatch restart).  Resumable watchers
                # only — a plain callback has no relist path.
                self._watchers.remove(w)
                del self._error_cbs[w]
                from ..chaos.faults import WatchDropped

                cb(WatchDropped(
                    f"chaos: watch dropped at {ev.kind} rv={ev.resource_version}"))
            else:
                w(ev)

    # --- CRUD ----------------------------------------------------------------

    def create(self, kind: str, obj) -> int:
        if self.fault is not None:
            # outside the lock: an injected delay/429 must not stall other
            # writers; raising HERE means the mutation never half-applied,
            # so a client retry is always safe
            self.fault.write_fault("create", kind, obj.metadata.name)
        with self._lock:
            if kind == "Pod":
                self._admit_pod(obj)
                if self._quota_namespaces:
                    self._admit_quota(obj)
            key = self._key(kind, obj)
            if key in self._objects:
                raise ValueError(f"{key} already exists")
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._objects[key] = obj
            if kind == "ResourceQuota":
                self._quota_namespaces.add(key[1])
            elif kind == "PriorityClass" and getattr(obj, "global_default",
                                                     False):
                self._default_priority_class = obj
            self._emit(WatchEvent(ADDED, kind, obj, self._rv))
            return self._rv

    def update(self, kind: str, obj, expected_rv=None) -> int:
        """``expected_rv`` (when not None) is an atomic compare-and-swap
        precondition checked under the store lock: the write applies only if
        the stored object's resourceVersion still equals it, else
        StaleResourceVersion — the etcd3 GuaranteedUpdate contract that makes
        the apiserver's 409 actually prevent lost updates (a handler-level
        check-then-act would race concurrent writers)."""
        if self.fault is not None:
            self.fault.write_fault("update", kind, obj.metadata.name)
        with self._lock:
            key = self._key(kind, obj)
            if key not in self._objects:
                raise KeyError(key)
            if expected_rv is not None:
                cur_rv = self._objects[key].metadata.resource_version
                if str(expected_rv) != str(cur_rv):
                    raise StaleResourceVersion(
                        f"{key}: submitted resourceVersion {expected_rv}, "
                        f"current {cur_rv}")
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._objects[key] = obj
            if kind == "PriorityClass":
                cached = self._default_priority_class
                if getattr(obj, "global_default", False):
                    self._default_priority_class = obj
                elif cached is not None and \
                        obj.metadata.name == cached.metadata.name:
                    # compare by NAME: an update decodes a fresh object, so
                    # identity would miss the replacement and serve a stale
                    # (possibly demoted) default forever
                    self._default_priority_class = next(
                        (o for (k, _, _), o in self._objects.items()
                         if k == "PriorityClass" and o.global_default), None)
            self._emit(WatchEvent(MODIFIED, kind, obj, self._rv))
            return self._rv

    def delete(self, kind: str, namespace: str, name: str) -> Optional[object]:
        if kind in self.CLUSTER_SCOPED:
            namespace = ""
        if self.fault is not None:
            self.fault.write_fault("delete", kind, name)
        with self._lock:
            obj = self._objects.pop((kind, namespace, name), None)
            if obj is None:
                return None
            if kind == "ResourceQuota" and not any(
                k == "ResourceQuota" and ns == namespace
                for (k, ns, _) in self._objects
            ):
                self._quota_namespaces.discard(namespace)
            elif kind == "PriorityClass" and (
                self._default_priority_class is not None
                and name == self._default_priority_class.metadata.name
            ):
                self._default_priority_class = next(
                    (o for (k, _, _), o in self._objects.items()
                     if k == "PriorityClass" and o.global_default), None)
            self._rv += 1
            self._emit(WatchEvent(DELETED, kind, obj, self._rv))
            return obj

    def current_rv(self) -> int:
        """The latest resourceVersion, read under the store lock — while
        held, no write is mid-emit, so every event ≤ this rv has been fully
        delivered to watch callbacks (the watch-bookmark correctness
        condition)."""
        with self._lock:
            return self._rv

    def get(self, kind: str, namespace: str, name: str) -> Optional[object]:
        if kind in self.CLUSTER_SCOPED:
            namespace = ""
        with self._lock:
            return self._objects.get((kind, namespace, name))

    def list(self, kind: str) -> Tuple[List[object], int]:
        with self._lock:
            objs = [o for (k, _, _), o in self._objects.items() if k == kind]
            return objs, self._rv

    def list_namespaced(self, namespace: str) -> List[Tuple[str, object]]:
        """Every namespaced object in ``namespace`` as (kind, obj) — the
        namespace controller's deletion-cascade view (reference:
        pkg/controller/namespace/deletion listing served group resources)."""
        with self._lock:
            return [
                (k, o) for (k, ns, _), o in self._objects.items()
                if ns == namespace and k not in self.CLUSTER_SCOPED
            ]

    # --- watch ---------------------------------------------------------------

    def watch(self, handler: Callable[[WatchEvent], None], since_rv: int = 0,
              on_error: Optional[Callable[[Exception], None]] = None):
        """Replays history after since_rv, then subscribes (list+watch contract).

        ``on_error`` (optional) marks the watcher as RESUMABLE: under chaos
        fault injection its stream may be cut, in which case the callback
        receives a WatchDropped and the watcher must relist + resubscribe
        (client/informer.py Reflector does).  Watchers without one are never
        dropped — a synchronous in-process callback has no stream."""
        with self._lock:
            for ev in self._log:
                if ev.resource_version > since_rv:
                    handler(ev)
            self._watchers.append(handler)
            if on_error is not None:
                self._error_cbs[handler] = on_error

            def unwatch():
                with self._lock:
                    if handler in self._watchers:
                        self._watchers.remove(handler)
                    self._error_cbs.pop(handler, None)

            return unwatch

    def _admit_pod(self, pod) -> None:
        """Priority admission: resolve priorityClassName → spec.priority
        (reference: plugin/pkg/admission/priority)."""
        spec = pod.spec
        if spec.priority:
            return
        name = spec.priority_class_name
        if name:
            pc = self._objects.get(("PriorityClass", "", name))
        else:
            pc = self._default_priority_class
        if pc is not None:
            spec.priority = pc.value
            spec.preemption_policy = pc.preemption_policy

    def _admit_quota(self, pod) -> None:
        """ResourceQuota admission: reject the pod if any quota in its
        namespace would be exceeded (reference:
        plugin/pkg/admission/resourcequota).  Used totals are recomputed from
        live pods at admission time — the sim has no async quota status lag,
        and the surrounding create() already holds the store lock."""
        ns = getattr(pod.metadata, "namespace", "")
        if ns not in self._quota_namespaces:
            return
        quotas = [
            o for (k, qns, _), o in self._objects.items()
            if k == "ResourceQuota" and qns == ns
        ]
        if not quotas:
            return
        from ..api.resource import (
            compute_pod_resource_request,
            parse_quantity,
            quantity_to_int,
            quantity_to_milli,
        )

        pods = [
            o for (k, pns, _), o in self._objects.items()
            if k == "Pod" and pns == ns
            and o.status.phase not in ("Succeeded", "Failed")
        ]
        new = compute_pod_resource_request(pod)
        used_cpu = new.milli_cpu + sum(
            compute_pod_resource_request(p).milli_cpu for p in pods)
        used_mem = new.memory + sum(
            compute_pod_resource_request(p).memory for p in pods)
        used_count = 1 + len(pods)
        for q in quotas:
            for key, hard in q.hard.items():
                if key in ("pods", "count/pods"):
                    if used_count > int(parse_quantity(hard)):
                        raise QuotaExceeded(
                            f"exceeded quota {q.metadata.name}: {key} "
                            f"(used {used_count}, hard {hard})")
                elif key in ("cpu", "requests.cpu"):
                    if used_cpu > quantity_to_milli(hard):
                        raise QuotaExceeded(
                            f"exceeded quota {q.metadata.name}: {key} "
                            f"(used {used_cpu}m, hard {hard})")
                elif key in ("memory", "requests.memory"):
                    if used_mem > quantity_to_int(hard):
                        raise QuotaExceeded(
                            f"exceeded quota {q.metadata.name}: {key} "
                            f"(used {used_mem}, hard {hard})")

    # --- binding subresource --------------------------------------------------

    def bind_pod(self, namespace: str, name: str, node_name: str) -> bool:
        if self.fault is not None:
            self.fault.write_fault("bind", "Pod", name)
        with self._lock:
            pod = self.get("Pod", namespace, name)
            if pod is None:
                return False
            pod.spec.node_name = node_name
            self._rv += 1
            pod.metadata.resource_version = self._rv
            self._emit(WatchEvent(MODIFIED, "Pod", pod, self._rv))
            return True
