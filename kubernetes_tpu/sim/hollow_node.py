"""Hollow node: a kubemark-style fake kubelet.

Reference: pkg/kubemark/hollow_kubelet.go:65,95 — REAL node-agent behaviors
(registration, lease heartbeat, pod lifecycle acks) with a FAKE runtime; this is
how 5k-node clusters are simulated without machines (test/kubemark/).

Behaviors:
  - register(): creates the Node object (capacity, labels, hostname label)
  - heartbeat(): renews the node Lease (kubelet.go:809-810: every ¼ duration)
  - sync(): bound pods transition Pending→Running (fake runtime start);
    pods of terminal Jobs can be driven to Succeeded via complete_pod()
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..api import objects as v1
from ..client.leaderelection import Lease
from ..sim.store import ObjectStore

LEASE_NAMESPACE = "kube-node-lease"


class HollowNode:
    def __init__(self, store: ObjectStore, name: str,
                 capacity: Optional[Dict[str, object]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 lease_duration: float = 40.0,
                 clock=time.monotonic):
        self.store = store
        self.name = name
        self.capacity = capacity or {"cpu": "32", "memory": "64Gi", "pods": "110"}
        self.labels = labels or {}
        self.lease_duration = lease_duration
        self.clock = clock
        self.alive = True

    # --- registration + heartbeat --------------------------------------------

    def register(self) -> v1.Node:
        node = v1.Node()
        node.metadata.name = self.name
        node.metadata.labels = dict(self.labels)
        node.metadata.labels.setdefault("kubernetes.io/hostname", self.name)
        node.status.capacity = dict(self.capacity)
        node.status.allocatable = dict(self.capacity)
        node.status.conditions.append({"type": "Ready", "status": "True"})
        self.store.create("Node", node)
        self.heartbeat()
        return node

    def heartbeat(self) -> None:
        if not self.alive:
            return
        lease = self.store.get("Lease", LEASE_NAMESPACE, self.name)
        if lease is None:
            lease = Lease(
                holder_identity=self.name,
                lease_duration_seconds=self.lease_duration,
                renew_time=self.clock(),
            )
            lease.metadata.namespace = LEASE_NAMESPACE
            lease.metadata.name = self.name
            self.store.create("Lease", lease)
        else:
            lease.renew_time = self.clock()
            self.store.update("Lease", lease)

    def fail(self) -> None:
        """Stop heartbeating (simulated node death — chaos hook)."""
        self.alive = False

    def recover(self) -> None:
        """Resume heartbeating and renew the lease NOW (partition heal —
        chaos hook; the lifecycle controller untaints on the next sync)."""
        self.alive = True
        self.heartbeat()

    # --- fake pod lifecycle ---------------------------------------------------

    def my_pods(self) -> List[v1.Pod]:
        pods, _ = self.store.list("Pod")
        return [p for p in pods if p.spec.node_name == self.name]

    def sync(self) -> int:
        """Start (fake) any bound pods still Pending. Returns #started."""
        started = 0
        if not self.alive:
            return 0
        for p in self.my_pods():
            if p.status.phase == v1.POD_PENDING:
                p.status.phase = v1.POD_RUNNING
                self.store.update("Pod", p)
                started += 1
        return started

    def complete_pod(self, pod: v1.Pod) -> None:
        pod.status.phase = v1.POD_SUCCEEDED
        self.store.update("Pod", pod)


class HollowCluster:
    """N hollow nodes driven together (test/kubemark/start-kubemark.sh analog)."""

    def __init__(self, store: ObjectStore, n: int, clock=time.monotonic,
                 zones: int = 16, **node_kwargs):
        self.nodes = []
        for i in range(n):
            hn = HollowNode(
                store, f"hollow-{i:05d}",
                labels={"topology.kubernetes.io/zone": f"zone-{i % zones}"},
                clock=clock, **node_kwargs,
            )
            hn.register()
            self.nodes.append(hn)

    def heartbeat_all(self):
        for n in self.nodes:
            n.heartbeat()

    def sync_all(self) -> int:
        return sum(n.sync() for n in self.nodes)
