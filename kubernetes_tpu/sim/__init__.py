"""In-process control plane for tests/benchmarks.

Reference analog: test/integration runs a real apiserver+etcd in-process
(test/integration/framework/etcd.go, util.go:56 StartApiserver); nodes are plain
API objects, no kubelet.  Here a plain object store with watch fan-out plays the
apiserver role for the scheduler harness (SURVEY §7 step 2).
"""

from .store import ObjectStore, WatchEvent  # noqa: F401

# wal.py (WriteAheadLog, replay_on_boot) and watchcache.py (WatchCache,
# TooOldResourceVersion) are imported by module path, not re-exported here:
# wal pulls in chaos.faults (whose crash points it hooks), which imports
# sim.store — an eager import here would be circular.
