"""Dynamic resource allocation: resource.k8s.io API kinds, the DraIndex
ledger, named-device scheduling end to end (incl. the gang all-or-nothing
acceptance), whatif claim-plane parity, the crash/chaos battery, the
claim controller, CLI verbs, and metrics."""

import time

import numpy as np
import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.api.scheme import default_scheme
from kubernetes_tpu.api.serialize import roundtrips, to_manifest
from kubernetes_tpu.chaos import (
    FaultSchedule,
    ProcessCrash,
    RetryingStore,
    crash_schedule,
)
from kubernetes_tpu.chaos.faults import (
    CRASH_MID_CLAIM_COMMIT,
    CRASH_MID_PROVISION,
)
from kubernetes_tpu.cli import Kubectl
from kubernetes_tpu.dra import DraIndex, ResourceClaimController
from kubernetes_tpu.dra.api import (
    CLAIM_PENDING,
    CLAIM_RESERVED,
    ATTR_CHIP_INDEX,
    ATTR_HOST,
    ATTR_SLICE,
    Device,
    DeviceClass,
    DeviceRequest,
    ResourceClaim,
    ResourceClaimTemplate,
    ResourceSlice,
    pod_claim_names,
    stamped_claim_name,
)
from kubernetes_tpu.gang import POD_GROUP_LABEL, SLICE_LABEL
from kubernetes_tpu.metrics import scheduler_metrics as m
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _no_sleep(_seconds):
    pass


def mk_class(name="tpu", selectors=None):
    dc = DeviceClass(selectors=dict(selectors or {}))
    dc.metadata.name = name
    return dc


def mk_slice(name, node, pool, chips=4):
    # device names carry the host (several hosts publish into one pool),
    # so "<pool>/<device>" is unambiguous — the workload generator's idiom
    sl = ResourceSlice(node_name=node, pool=pool, devices=[
        Device(name=f"{node}-chip{i}", attributes={
            ATTR_SLICE: pool, ATTR_HOST: node, ATTR_CHIP_INDEX: str(i),
        }) for i in range(chips)
    ])
    sl.metadata.name = name
    return sl


def mk_claim(name, cls="tpu", count=4, ns="default"):
    c = ResourceClaim(request=DeviceRequest(device_class_name=cls,
                                            count=count))
    c.metadata.name = name
    c.metadata.namespace = ns
    return c


def _tpu_cluster(n_nodes=4, chips=4, slice_hosts=2, cpu="8"):
    """n_nodes hosts, SLICE_LABEL s{i//slice_hosts}, one ResourceSlice per
    host publishing ``chips`` chips into the pool named after the slice."""
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, clock=clock, batch_wait=0)
    store.create("DeviceClass", mk_class())
    for i in range(n_nodes):
        pool = f"s{i // slice_hosts}"
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": cpu, "pods": "20"})
                     .label(SLICE_LABEL, pool).obj())
        store.create("ResourceSlice",
                     mk_slice(f"rs-n{i}", f"n{i}", pool, chips))
    return clock, store, sched


# --- L0: API objects, scheme, serialization ----------------------------------


def test_dra_kinds_scheme_decode_and_roundtrip():
    scheme = default_scheme()
    claim = scheme.decode({
        "apiVersion": "resource.k8s.io/v1alpha2",
        "kind": "ResourceClaim",
        "metadata": {"name": "c", "namespace": "ml"},
        "spec": {"devices": {"requests": [
            {"name": "devices", "deviceClassName": "tpu", "count": 4}]}},
        "status": {"state": "Reserved",
                   "allocation": {"nodeName": "n0",
                                  "devices": ["s0/chip0", "s0/chip1"]},
                   "reservedFor": "pod-uid"},
    })
    assert claim.request.count == 4
    assert claim.request.device_class_name == "tpu"
    assert claim.state == CLAIM_RESERVED
    assert claim.allocated_node == "n0"
    assert claim.allocated_devices == ["s0/chip0", "s0/chip1"]
    assert claim.reserved_for == "pod-uid"
    assert roundtrips(claim, scheme)
    wire = to_manifest(claim, scheme)
    assert wire["apiVersion"] == "resource.k8s.io/v1alpha2"

    for obj in (mk_class(selectors={ATTR_SLICE: "s0"}),
                mk_slice("rs-n0", "n0", "s0"),
                mk_claim("c2")):
        assert roundtrips(obj, scheme), obj.kind
    tpl = ResourceClaimTemplate(request=DeviceRequest(
        device_class_name="tpu", count=2))
    tpl.metadata.name = "t"
    tpl.metadata.namespace = "default"
    assert roundtrips(tpl, scheme)


def test_pod_claim_name_resolution():
    p = (make_pod().name("job-0").uid("job-0")
         .claim("explicit")
         .claim_template("tmpl", name="tpu").obj())
    assert pod_claim_names(p) == [
        "explicit", stamped_claim_name("job-0", "tpu")]
    assert stamped_claim_name("job-0", "tpu") == "job-0-tpu"
    # a malformed entry (neither claim nor template) resolves to None
    p.spec.resource_claims.append(v1.PodResourceClaim(name="bad"))
    assert pod_claim_names(p)[-1] is None


def test_deviceclass_attribute_matching():
    dev = Device(name="chip0", attributes={ATTR_SLICE: "s0", ATTR_HOST: "n0"})
    assert mk_class(selectors={}).matches(dev)
    assert mk_class(selectors={ATTR_SLICE: "s0"}).matches(dev)
    assert not mk_class(selectors={ATTR_SLICE: "s1"}).matches(dev)


# --- L1: the DraIndex ledger --------------------------------------------------


def test_index_inventory_and_allocation_ledger():
    store = ObjectStore()
    idx = DraIndex(store)
    idx.apply_class(mk_class())
    idx.apply_slice(mk_slice("rs-n0", "n0", "s0", chips=4))
    assert idx.node_capacity("n0") == 4
    assert idx.node_allocated("n0") == 0
    c = mk_claim("c1", count=2)
    c.allocated_node = "n0"
    c.allocated_devices = ["s0/chip0", "s0/chip1"]
    idx.apply_claim(c)
    assert idx.node_allocated("n0") == 2
    # idempotent replay (watch redelivery) does not double-count
    idx.apply_claim(c)
    assert idx.node_allocated("n0") == 2
    idx.remove_claim(c.key())
    assert idx.node_allocated("n0") == 0
    idx.remove_slice("rs-n0")
    assert idx.node_capacity("n0") == 0


def test_index_reserve_all_or_nothing_rolls_back_partial_assumes():
    store = ObjectStore()
    idx = DraIndex(store)
    idx.apply_class(mk_class())
    idx.apply_slice(mk_slice("rs-n0", "n0", "s0", chips=4))
    idx.apply_claim(mk_claim("c1", count=3))
    idx.apply_claim(mk_claim("c2", count=3))  # 3+3 > 4: second must fail
    pod = (make_pod().name("p").uid("p").claim("c1").claim("c2").obj())
    decisions, reason = idx.reserve(pod, "n0")
    assert decisions is None and "free devices" in reason
    # the first claim's assume rolled back — nothing leaked
    assert idx.node_allocated("n0") == 0
    # a fitting pod then takes named devices deterministically
    pod2 = make_pod().name("q").uid("q").claim("c1").obj()
    decisions, reason = idx.reserve(pod2, "n0")
    assert reason is None
    [(claim, devices)] = decisions
    assert claim.metadata.name == "c1"
    assert devices == ["s0/n0-chip0", "s0/n0-chip1", "s0/n0-chip2"]
    assert idx.node_allocated("n0") == 3
    idx.unreserve(pod2)
    assert idx.node_allocated("n0") == 0


def test_index_resolve_unresolvable_shapes():
    store = ObjectStore()
    idx = DraIndex(store)
    missing = make_pod().name("p").uid("p").claim("ghost").obj()
    assert idx.resolve(missing) == (0, None, False)
    foreign = mk_claim("c1")
    foreign.reserved_for = "somebody-else"
    idx.apply_claim(foreign)
    assert idx.resolve(
        make_pod().name("p2").uid("p2").claim("c1").obj())[2] is False
    # two claims pinned to two different nodes can never co-place
    a, b = mk_claim("a", count=1), mk_claim("b", count=1)
    a.allocated_node, b.allocated_node = "n0", "n1"
    idx.apply_claim(a)
    idx.apply_claim(b)
    assert idx.resolve(
        make_pod().name("p3").uid("p3").claim("a").claim("b").obj()
    )[2] is False


# --- L2: end-to-end named-device scheduling ----------------------------------


def test_e2e_pod_binds_with_named_devices_and_metrics():
    _clock, store, sched = _tpu_cluster(n_nodes=2)
    store.create("ResourceClaim", mk_claim("c1", count=4))
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1"}).claim("c1").obj())
    before = m.dra_claims_allocated.value(("allocated",))
    dur0 = m.dra_allocation_duration.count(())
    stats = sched.run_until_idle()
    assert stats.scheduled == 1
    pod = store.get("Pod", "default", "p")
    assert pod.spec.node_name
    claim = store.get("ResourceClaim", "default", "c1")
    assert claim.state == CLAIM_RESERVED
    assert claim.allocated_node == pod.spec.node_name
    assert claim.reserved_for == "p"
    assert len(set(claim.allocated_devices)) == 4
    pool = f"s{int(pod.spec.node_name[1:]) // 2}"
    assert all(d.startswith(f"{pool}/") for d in claim.allocated_devices)
    assert m.dra_claims_allocated.value(("allocated",)) == before + 1
    assert m.dra_allocation_duration.count(()) == dur0 + 1


def test_e2e_allocated_claim_pins_pod_to_its_node():
    _clock, store, sched = _tpu_cluster(n_nodes=4)
    c = mk_claim("c1", count=2)
    c.allocated_node = "n3"
    c.allocated_devices = ["s1/chip0", "s1/chip1"]
    c.reserved_for = "p"  # already reserved for this pod (retry shape)
    store.create("ResourceClaim", c)
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1"}).claim("c1").obj())
    assert sched.run_until_idle().scheduled == 1
    assert store.get("Pod", "default", "p").spec.node_name == "n3"


def test_e2e_insufficient_chips_unschedulable():
    _clock, store, sched = _tpu_cluster(n_nodes=1, chips=2)
    store.create("ResourceClaim", mk_claim("c1", count=4))
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1"}).claim("c1").obj())
    stats = sched.run_until_idle(max_cycles=5)
    assert stats.scheduled == 0
    assert store.get("ResourceClaim", "default", "c1").state == CLAIM_PENDING


def test_e2e_gang_claims_all_or_nothing_into_one_slice():
    """THE acceptance scenario: a 2-member gang, each member claiming a
    full host's 4 chips, lands all-or-nothing in ONE slice — and it is the
    slice with enough FREE chips (slice s0 is blighted by a pre-existing
    allocation), every claim Reserved with named non-overlapping chips."""
    clock, store, sched = _tpu_cluster(n_nodes=4, chips=4, slice_hosts=2)
    ghost = mk_claim("ghost", count=1)
    ghost.state = CLAIM_RESERVED
    ghost.allocated_node = "n0"
    ghost.allocated_devices = ["s0/n0-chip0"]
    ghost.reserved_for = "ghost-pod"
    store.create("ResourceClaim", ghost)
    store.create("Pod", make_pod().name("ghost-pod").uid("ghost-pod")
                 .namespace("default").node("n0").claim("ghost").obj())
    pg = v1.PodGroup(metadata=v1.ObjectMeta(name="g", namespace="default"),
                     min_member=2, schedule_timeout_seconds=30)
    store.create("PodGroup", pg)
    for i in range(2):
        store.create("ResourceClaim", mk_claim(f"g-{i}-tpu", count=4))
        store.create("Pod", (make_pod().name(f"g-{i}").uid(f"g-{i}")
                             .namespace("default")
                             .label(POD_GROUP_LABEL, "g")
                             .req({"cpu": "1"}).claim(f"g-{i}-tpu").obj()))
    for _ in range(8):
        sched.schedule_cycle()
        clock.advance(0.5)
    nodes = [store.get("Pod", "default", f"g-{i}").spec.node_name
             for i in range(2)]
    assert all(nodes), nodes
    slices = {store.get("Node", "", n).metadata.labels[SLICE_LABEL]
              for n in nodes}
    assert slices == {"s1"}  # s0 has only 7 free chips for 8 demanded
    devices = []
    for i in range(2):
        claim = store.get("ResourceClaim", "default", f"g-{i}-tpu")
        assert claim.state == CLAIM_RESERVED
        assert claim.reserved_for == f"g-{i}"
        assert claim.allocated_node == nodes[i]
        assert len(claim.allocated_devices) == 4
        devices += claim.allocated_devices
    assert len(set(devices)) == 8  # no chip handed out twice
    assert all(d.startswith("s1/") for d in devices)


def test_e2e_starved_gang_timeout_releases_all_claims():
    """A gang that can never fully place times out with ZERO claims left
    allocated — the members that reserved chips at Permit release them
    through the unreserve chain atomically."""
    clock, store, sched = _tpu_cluster(n_nodes=2, chips=4, slice_hosts=2)
    pg = v1.PodGroup(metadata=v1.ObjectMeta(name="g", namespace="default"),
                     min_member=3, schedule_timeout_seconds=2)
    store.create("PodGroup", pg)
    for i in range(3):  # 3 members × 4 chips > the 8 chips that exist
        store.create("ResourceClaim", mk_claim(f"g-{i}-tpu", count=4))
        store.create("Pod", (make_pod().name(f"g-{i}").uid(f"g-{i}")
                             .namespace("default")
                             .label(POD_GROUP_LABEL, "g")
                             .req({"cpu": "1"}).claim(f"g-{i}-tpu").obj()))
    for _ in range(4):
        sched.schedule_cycle()
        clock.advance(0.5)
    clock.advance(10.0)
    sched.schedule_cycle()
    assert len(sched._waiting_binds) == 0
    for i in range(3):
        assert not store.get("Pod", "default", f"g-{i}").spec.node_name
        claim = store.get("ResourceClaim", "default", f"g-{i}-tpu")
        assert claim.state == CLAIM_PENDING
        assert not claim.allocated_devices
    assert sched.dra.node_allocated("n0") == 0
    assert sched.dra.node_allocated("n1") == 0


# --- L3: whatif claim-plane parity -------------------------------------------


def test_kfork_claim_planes_vmapped_equals_sequential():
    """K-fork contract extended to DRA: pending pods carrying claims and a
    victim holding allocated chips produce identical placements vmapped
    vs sequential — the claim planes ride every fork shape."""
    from kubernetes_tpu.whatif import ForkSpec, WhatIfEngine

    _clock, store, sched = _tpu_cluster(n_nodes=4, chips=4, slice_hosts=2)
    # a bound victim holding a full host of chips
    vic_claim = mk_claim("vic-tpu", count=4)
    vic_claim.state = CLAIM_RESERVED
    vic_claim.allocated_node = "n1"
    vic_claim.allocated_devices = [f"s0/chip{i}" for i in range(4)]
    vic_claim.reserved_for = "vic"
    store.create("ResourceClaim", vic_claim)
    vic = (make_pod().name("vic").uid("vic").namespace("default")
           .req({"cpu": "1"}).claim("vic-tpu").node("n1").obj())
    store.create("Pod", vic)
    sched.schedule_cycle()  # prime encoder/index state
    pend = []
    for i in range(3):
        store.create("ResourceClaim", mk_claim(f"pend-{i}-tpu", count=4))
        pend.append(make_pod().name(f"pend-{i}").uid(f"pend-{i}")
                    .namespace("default").req({"cpu": "1"})
                    .claim(f"pend-{i}-tpu").obj())
    engine = WhatIfEngine(sched)
    forks = [
        ForkSpec(victims=[vic], note="evict claim holder"),
        ForkSpec(remove_nodes=["n3"], note="remove"),
        ForkSpec(victims=[vic], remove_nodes=["n2"], note="mixed"),
    ]
    vm = engine.evaluate(pend, forks, vmapped=True)
    seq = engine.evaluate(pend, forks, vmapped=False)
    assert len(vm) == len(seq) == len(forks)
    for a, b in zip(vm, seq):
        assert a.placements == b.placements, (a.fork.note, a.placements,
                                              b.placements)
    # the victim fork actually freed its chips: some fork seats a pod on
    # the victim's host, which without the release plane could not fit
    evict_fork = vm[0]
    assert "n1" in set(evict_fork.placements.values())


# --- L4: crash + chaos battery -----------------------------------------------


def _two_claim_pod(store):
    store.create("ResourceClaim", mk_claim("c1", count=2))
    store.create("ResourceClaim", mk_claim("c2", count=2))
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1"}).claim("c1").claim("c2").obj())


def test_crash_mid_claim_commit_retry_completes_exactly_once():
    """Kill between the two claim commits of one pod: the first claim is
    durably Reserved, the pod unbound.  A fresh scheduler incarnation plus
    the claim controller converge — the pod binds to the node the crashed
    commit pinned, the second claim allocates, nothing double-allocates."""
    _clock, store, sched = _tpu_cluster(n_nodes=2)
    _two_claim_pod(store)
    fault = FaultSchedule(7)
    fault.arm_crash(CRASH_MID_CLAIM_COMMIT, at_hit=1)
    with crash_schedule(fault):
        with pytest.raises(ProcessCrash):
            sched.run_until_idle(max_cycles=5)
    assert fault.crashes_fired()
    c1 = store.get("ResourceClaim", "default", "c1")
    c2 = store.get("ResourceClaim", "default", "c2")
    committed = [c for c in (c1, c2) if c.allocated_node]
    assert len(committed) == 1  # exactly the pre-crash prefix
    assert not store.get("Pod", "default", "p").spec.node_name
    # new incarnation (the dead scheduler's memory is gone)
    sched2 = TPUScheduler(store, batch_size=8, batch_wait=0)
    ctrl = ResourceClaimController(store, index=sched2.dra)
    ctrl.sync_once()  # live unbound pod: repair must NOT steal its claim
    assert store.get("ResourceClaim", "default",
                     committed[0].metadata.name).allocated_node
    assert sched2.run_until_idle().scheduled == 1
    pod = store.get("Pod", "default", "p")
    devices = []
    for name in ("c1", "c2"):
        claim = store.get("ResourceClaim", "default", name)
        assert claim.state == CLAIM_RESERVED
        assert claim.allocated_node == pod.spec.node_name
        assert claim.reserved_for == "p"
        devices += claim.allocated_devices
    assert len(set(devices)) == 4  # disjoint named chips, no double-alloc
    assert ctrl.sync_once() is False  # converged: repair finds nothing


def test_crash_mid_claim_commit_dead_pod_repaired_exactly_once():
    """Same kill, but the consuming pod is deleted before recovery: the
    repair arm returns the committed claim to Pending exactly once."""
    _clock, store, sched = _tpu_cluster(n_nodes=2)
    _two_claim_pod(store)
    fault = FaultSchedule(7)
    fault.arm_crash(CRASH_MID_CLAIM_COMMIT, at_hit=1)
    with crash_schedule(fault):
        with pytest.raises(ProcessCrash):
            sched.run_until_idle(max_cycles=5)
    store.delete("Pod", "default", "p")
    ctrl = ResourceClaimController(store)
    assert ctrl.sync_once() is True
    for name in ("c1", "c2"):
        claim = store.get("ResourceClaim", "default", name)
        assert claim.state == CLAIM_PENDING
        assert not claim.allocated_devices and not claim.reserved_for
    assert ctrl.sync_once() is False  # second sweep: nothing left to do


def test_prebind_terminal_fault_rolls_back_written_claims():
    """A store fault that outlasts the CAS loop on the SECOND claim rolls
    back the first claim's allocation — the pod's claims land in the
    store all-or-nothing, and the retried cycle converges."""
    from kubernetes_tpu.chaos.faults import TransientApiError

    _clock, store, sched = _tpu_cluster(n_nodes=2)

    class FailSecondClaim:
        def __init__(self, inner):
            self._inner = inner
            self.armed = True

        def update(self, kind, obj, expected_rv=None, **kw):
            if (self.armed and kind == "ResourceClaim"
                    and obj.metadata.name == "c2" and obj.allocated_node):
                self.armed = False
                raise TransientApiError(429, message="injected storm")
            return self._inner.update(kind, obj, expected_rv=expected_rv,
                                      **kw)

        def __getattr__(self, attr):
            return getattr(self._inner, attr)

    _two_claim_pod(store)
    rb0 = m.dra_claims_allocated.value(("rollback",))
    sched.dra.store = FailSecondClaim(store)
    for _ in range(10):  # advance past the failed pod's backoff window
        sched.schedule_cycle()
        _clock.advance(5.0)
        if store.get("Pod", "default", "p").spec.node_name:
            break
    assert m.dra_claims_allocated.value(("rollback",)) >= rb0 + 1
    # the retry (fault disarms itself) converges with both claims landed
    assert store.get("Pod", "default", "p").spec.node_name
    pod = store.get("Pod", "default", "p")
    for name in ("c1", "c2"):
        claim = store.get("ResourceClaim", "default", name)
        assert claim.allocated_node == pod.spec.node_name
        assert claim.reserved_for == "p"


def test_chaos_storm_every_claim_allocated_exactly_once():
    """Watch drops + 429/500 storms + CAS conflicts: all claim-carrying
    pods eventually bind, every claim is owned by exactly its consumer,
    and no chip is handed to two claims."""
    fault = FaultSchedule(
        13, watch_drop_rate=0.15, write_429_rate=0.3, write_500_rate=0.1,
        conflict_rate=0.15, retry_after=0.0, max_faults_per_key=3,
    )
    raw = ObjectStore(fault_injector=fault)
    store = RetryingStore(raw, sleep=_no_sleep)
    store.create("DeviceClass", mk_class())
    for i in range(3):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "8", "pods": "20"})
                     .label(SLICE_LABEL, "s0").obj())
        store.create("ResourceSlice", mk_slice(f"rs-n{i}", f"n{i}", "s0", 4))
    for i in range(6):  # 6 × 2 chips on 12 chips: tight but feasible
        store.create("ResourceClaim", mk_claim(f"c{i}", count=2))
        store.create("Pod", make_pod().name(f"p{i}").uid(f"p{i}")
                     .namespace("default").req({"cpu": "1"})
                     .claim(f"c{i}").obj())
    sched = TPUScheduler(store, batch_size=4, pod_initial_backoff=0.01,
                         pod_max_backoff=0.05, batch_wait=0)
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        sched.run_until_idle(max_cycles=50, backoff_wait=0.5)
        bound = sum(1 for i in range(6)
                    if raw.get("Pod", "default", f"p{i}").spec.node_name)
        if bound == 6:
            break
        time.sleep(0.01)
    assert bound == 6
    devices_by_node = {}
    for i in range(6):
        pod = raw.get("Pod", "default", f"p{i}")
        claim = raw.get("ResourceClaim", "default", f"c{i}")
        assert claim.state == CLAIM_RESERVED
        assert claim.reserved_for == f"p{i}"
        assert claim.allocated_node == pod.spec.node_name
        assert len(claim.allocated_devices) == 2
        devices_by_node.setdefault(claim.allocated_node, []).extend(
            claim.allocated_devices)
    for node, devs in devices_by_node.items():
        assert len(devs) == len(set(devs)), (node, devs)
        assert len(devs) <= 4
    assert sum(fault.injected_counts().values()) > 0  # the storm fired


def test_crash_mid_provision_cold_start_repair_exactly_once():
    """Volume twin of the claim-commit kill: the binder dies after the PV
    claimRef write, before the PVC write.  A cold-start binder's repair
    arm completes the half-applied binding exactly once."""
    from kubernetes_tpu.controllers.volumebinder import (
        PersistentVolumeBinderController,
    )

    store = ObjectStore()
    pv = v1.PersistentVolume(capacity={"storage": "10Gi"})
    pv.metadata.name = "pv0"
    store.create("PersistentVolume", pv)
    pvc = v1.PersistentVolumeClaim(requested_storage="5Gi")
    pvc.metadata.name = "data"
    pvc.metadata.namespace = "default"
    store.create("PersistentVolumeClaim", pvc)
    fault = FaultSchedule(3)
    fault.arm_crash(CRASH_MID_PROVISION, at_hit=1)
    with crash_schedule(fault):
        with pytest.raises(ProcessCrash):
            PersistentVolumeBinderController(store).sync_once()
    assert fault.crashes_fired()  # died between the PV and PVC writes
    # durable state at the kill: the PV side landed, the PVC side did not
    # (the dead binder's in-memory PVC mutation never reached a commit —
    # reset the claim side to what the store durably held)
    assert store.get("PersistentVolume", "", "pv0").claim_ref == \
        "default/data"
    dead = store.get("PersistentVolumeClaim", "default", "data")
    dead.volume_name = ""
    dead.phase = ""
    store.update("PersistentVolumeClaim", dead)
    cold = PersistentVolumeBinderController(store)  # fresh incarnation
    assert cold.sync_once() is True
    got = store.get("PersistentVolumeClaim", "default", "data")
    assert got.volume_name == "pv0" and got.phase == "Bound"
    assert cold.sync_once() is False  # idempotent: repaired exactly once


# --- claim controller: stamping ----------------------------------------------


def test_controller_stamps_template_claims_idempotently():
    store = ObjectStore()
    tpl = ResourceClaimTemplate(request=DeviceRequest(
        device_class_name="tpu", count=4))
    tpl.metadata.name = "tpu-tmpl"
    tpl.metadata.namespace = "default"
    store.create("ResourceClaimTemplate", tpl)
    store.create("Pod", make_pod().name("job-0").uid("job-0")
                 .namespace("default")
                 .claim_template("tpu-tmpl", name="tpu").obj())
    ctrl = ResourceClaimController(store)
    assert ctrl.sync_once() is True
    claim = store.get("ResourceClaim", "default", "job-0-tpu")
    assert claim is not None
    assert claim.request.count == 4
    assert claim.request.device_class_name == "tpu"
    assert ctrl.sync_once() is False  # deterministic name: no duplicate
    assert len(store.list("ResourceClaim")[0]) == 1


def test_e2e_template_stamped_gang_member_schedules():
    """Template → controller stamp → scheduler resolves the stamped name
    and allocates: the full TrainingJob-shaped flow."""
    _clock, store, sched = _tpu_cluster(n_nodes=2)
    tpl = ResourceClaimTemplate(request=DeviceRequest(
        device_class_name="tpu", count=4))
    tpl.metadata.name = "tpu-tmpl"
    tpl.metadata.namespace = "default"
    store.create("ResourceClaimTemplate", tpl)
    store.create("Pod", make_pod().name("job-0").uid("job-0")
                 .namespace("default").req({"cpu": "1"})
                 .claim_template("tpu-tmpl", name="tpu").obj())
    # before the stamp the pod is unresolvable — never partially placed
    assert sched.run_until_idle(max_cycles=3).scheduled == 0
    ResourceClaimController(store, index=sched.dra).sync_once()
    for _ in range(10):  # the claim ADD event requeues; ride out backoff
        sched.schedule_cycle()
        _clock.advance(5.0)
        if store.get("Pod", "default", "job-0").spec.node_name:
            break
    assert store.get("Pod", "default", "job-0").spec.node_name
    claim = store.get("ResourceClaim", "default", "job-0-tpu")
    assert claim.state == CLAIM_RESERVED
    assert claim.reserved_for == "job-0"
    assert len(claim.allocated_devices) == 4


# --- CLI ---------------------------------------------------------------------


def test_cli_dra_verbs():
    store = ObjectStore()
    store.create("DeviceClass", mk_class(selectors={ATTR_SLICE: "s0"}))
    store.create("ResourceSlice", mk_slice("rs-n0", "n0", "s0", 4))
    claim = mk_claim("job-0-tpu", count=2)
    claim.state = CLAIM_RESERVED
    claim.allocated_node = "n0"
    claim.allocated_devices = ["s0/chip0", "s0/chip1"]
    store.create("ResourceClaim", claim)
    store.create("ResourceClaim", mk_claim("idle", count=1))
    k = Kubectl(store)
    out = k.get("resourceclaims")
    assert "NAME" in out and "STATE" in out and "ALLOCATED-DEVICE" in out
    assert "job-0-tpu" in out and "Reserved" in out
    assert "s0/chip0,s0/chip1" in out
    assert "idle" in out and "Pending" in out and "<none>" in out
    out = k.get("deviceclasses")
    assert "tpu" in out and "slice=s0" in out
    out = k.get("resourceslices")
    assert "rs-n0" in out and "s0" in out and "4" in out
    wire = k.get_json("resourceclaim", "default", "job-0-tpu")
    assert '"resource.k8s.io/v1alpha2"' in wire


# --- perf plumbing smoke ------------------------------------------------------


def test_device_claim_gang_workload_shape():
    """The DeviceClaimGang suite's generators agree with each other: pod i
    references claim gangclaim-i, warm pods are singleton gangs pinned to
    the warm node, and the suite is flagged dra for the harness."""
    from kubernetes_tpu.perf.workloads import SUITES, build_workload

    w = build_workload("DeviceClaimGang", "64Nodes")
    assert w.dra is True and w.gang_size
    assert "DeviceClaimGang" in SUITES
    op = next(o for o in w.ops if o.opcode == "createPods")
    pod = op.pod_template(0)
    assert pod_claim_names(pod) == ["gangclaim-000000"]
    warm = op.pod_template(9_990_000)
    assert pod_claim_names(warm) == ["warmclaim-0"]
    assert warm.spec.node_selector == {"dra-warm": "1"}
    assert warm.metadata.labels[POD_GROUP_LABEL] == "wg-0"
