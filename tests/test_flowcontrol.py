"""Request flow control: split inflight pools, per-user fairness, 429 +
Retry-After shedding, and the reader-flood acceptance (mutating never
starves, zero requests lost).

Reference behaviors exercised: APF (apiserver/pkg/util/flowcontrol) seat
semantics reduced to split max-inflight pools + fair queuing, and the
--max-*-requests-inflight filters' 429 contract the PR-1 retrying
transports already honor.
"""

import threading
import time

import pytest

from kubernetes_tpu.analysis import lockcheck
from kubernetes_tpu.apiserver.flowcontrol import FlowController, RequestRejected
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.chaos.flood import run_reader_flood, timed_writes
from kubernetes_tpu.metrics import scheduler_metrics as m
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_pod


@pytest.fixture(autouse=True)
def lock_order_monitor():
    mon = lockcheck.activate()
    try:
        yield mon
    finally:
        lockcheck.deactivate()
    assert not mon.violations, mon.report()


def _pod(i):
    return (make_pod().name(f"f{i:03d}").uid(f"f{i:03d}").namespace("default")
            .req({"cpu": "1"}).obj())


def _rejected(reason):
    return m.apiserver_rejected.value((reason,))


# --- gate unit battery --------------------------------------------------------


def test_seats_queue_full_and_timeout():
    fc = FlowController(max_readonly_inflight=1, max_queue_per_user=1,
                        queue_timeout=0.05, retry_after=0.02)
    held = fc.admit("a", mutating=False)
    # a's only queue slot times out → 429 with the retry hint
    with pytest.raises(RequestRejected) as ei:
        fc.admit("a", mutating=False)
    assert ei.value.reason == "readonly_timeout"
    assert ei.value.retry_after == 0.02

    # refill the queue slot with a parked waiter, then overflow it (the
    # waiter itself may be granted or time out — either outcome is fine,
    # the assertion under test is the OVERFLOW rejection below)
    def park():
        try:
            fc.admit("a", mutating=False).release()
        except RequestRejected:
            pass

    blocker = threading.Thread(target=park)
    blocker.start()
    time.sleep(0.01)  # the waiter is queued now
    with pytest.raises(RequestRejected) as ei:
        fc.admit("a", mutating=False)
    assert ei.value.reason == "readonly_queue_full"
    blocker.join(2)
    held.release()
    assert fc.readonly.inflight() == 0 and fc.readonly.queued() == 0
    # pools are independent: readonly exhaustion never touched mutating
    seat = fc.admit("a", mutating=True)
    seat.release()
    seat.release()  # idempotent
    assert fc.mutating.inflight() == 0


def test_rotating_users_cannot_bypass_queue_bounds():
    """The per-user queue bound alone is spoofable (fairness keys on an
    unauthenticated header): the TOTAL queued bound sheds a flood that
    mints a fresh user per request."""
    fc = FlowController(max_readonly_inflight=1, max_queue_per_user=8,
                        queue_timeout=3.0, max_queued_total=3)
    held = fc.admit("seat-holder", mutating=False)
    parked = []

    def park(u):
        try:
            fc.admit(u, mutating=False).release()
        except RequestRejected:
            pass

    threads = [threading.Thread(target=park, args=(f"sybil-{i}",))
               for i in range(3)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 2
    while fc.readonly.queued() < 3 and time.monotonic() < deadline:
        time.sleep(0.005)
    # the 4th distinct user hits the TOTAL bound immediately — no thread
    # parked, no unbounded growth
    with pytest.raises(RequestRejected) as ei:
        fc.admit("sybil-99", mutating=False)
    assert ei.value.reason == "readonly_queue_full"
    held.release()
    for t in threads:
        t.join(10)
    assert fc.readonly.queued() == 0 and fc.readonly.inflight() == 0


def test_seat_handoff_is_fair_across_users():
    """One seat, user a floods the queue, user b asks once: b is served
    before a's backlog drains (round-robin handoff, not FIFO)."""
    fc = FlowController(max_readonly_inflight=1, max_queue_per_user=8,
                        queue_timeout=5.0)
    held = fc.admit("a", mutating=False)
    order = []
    lock = threading.Lock()

    def worker(user):
        seat = fc.admit(user, mutating=False)
        with lock:
            order.append(user)
        time.sleep(0.01)
        seat.release()

    threads = [threading.Thread(target=worker, args=("a",))
               for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.05)  # a's four waiters are queued
    tb = threading.Thread(target=worker, args=("b",))
    tb.start()
    time.sleep(0.05)
    held.release()
    for t in threads + [tb]:
        t.join(10)
    assert len(order) == 5
    assert "b" in order[:2], f"b starved behind a's flood: {order}"
    assert fc.readonly.inflight() == 0


def test_inflight_gauge_tracks_seats():
    fc = FlowController(max_readonly_inflight=4, max_mutating_inflight=4)
    seats = [fc.admit("u", mutating=False) for _ in range(3)]
    assert m.apiserver_inflight.value(("readonly",)) == 3.0
    wseat = fc.admit("u", mutating=True)
    assert m.apiserver_inflight.value(("mutating",)) == 1.0
    for s in seats:
        s.release()
    wseat.release()
    assert m.apiserver_inflight.value(("readonly",)) == 0.0
    assert m.apiserver_inflight.value(("mutating",)) == 0.0


# --- apiserver integration ----------------------------------------------------


def test_flow_rejection_over_http_carries_retry_after():
    import urllib.error
    import urllib.request

    store = ObjectStore()
    fc = FlowController(max_readonly_inflight=1, max_queue_per_user=1,
                        queue_timeout=0.05, retry_after=0.07)
    api = APIServer(store, flow_control=fc).start()
    try:
        store.create("Pod", _pod(0))
        held = fc.admit("hog", mutating=False)  # pin the only seat

        def park():  # parks the one queue slot; succeeds once hog releases
            try:
                urllib.request.urlopen(f"{api.url}/api/v1/pods").read()
            except urllib.error.HTTPError:
                pass

        q = threading.Thread(target=park)
        q.start()
        time.sleep(0.02)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{api.url}/api/v1/pods").read()
        assert ei.value.code == 429
        assert float(ei.value.headers["Retry-After"]) == pytest.approx(0.07)
        held.release()
        q.join(5)
        # health + metrics stay exempt even while the pool is exhausted
        held = fc.admit("hog", mutating=False)
        assert urllib.request.urlopen(
            f"{api.url}/healthz").read() == b"ok"
        assert b"apiserver_rejected_requests_total" in urllib.request.urlopen(
            f"{api.url}/metrics").read()
        held.release()
    finally:
        api.stop()


def test_watch_stream_does_not_pin_the_readonly_pool():
    """A long-lived watch holds its seat only through the handshake: with
    every readonly seat's worth of watches open, plain lists still run."""
    import json
    import urllib.request

    store = ObjectStore()
    fc = FlowController(max_readonly_inflight=2, max_queue_per_user=1,
                        queue_timeout=0.1)
    api = APIServer(store, flow_control=fc).start()
    try:
        store.create("Pod", _pod(0))
        streams = []
        for _ in range(2):  # as many watches as the pool has seats
            r = urllib.request.urlopen(
                f"{api.url}/api/v1/pods?watch=true&timeoutSeconds=20",
                timeout=30)
            streams.append(r)
        deadline = time.monotonic() + 5
        while fc.readonly.inflight() and time.monotonic() < deadline:
            time.sleep(0.01)  # handshake seats drain as streams enter loops
        assert fc.readonly.inflight() == 0
        with urllib.request.urlopen(f"{api.url}/api/v1/pods") as r:
            assert len(json.loads(r.read())["items"]) == 1
        for s in streams:
            s.close()
    finally:
        api.stop()


# --- the flood acceptance -----------------------------------------------------


def test_reader_flood_mutating_never_starves_and_nothing_is_lost():
    """ISSUE 11 acceptance: N greedy readers + one mutating writer.  The
    readonly pool saturates and sheds with 429 + Retry-After; every reader
    request retries to success (zero lost); the writer — in its own pool —
    never sees a 429 and keeps ≥ half its unloaded throughput (the 2×
    acceptance bound, plus a scheduling grace for the shared CPU)."""
    store = ObjectStore()
    # max_queued_total=4 guarantees saturation: 10 concurrent readers vs
    # 2 seats + 4 total queue slots MUST shed some requests with 429
    # regardless of how fast this box serves a list
    fc = FlowController(max_readonly_inflight=2, max_mutating_inflight=8,
                        max_queue_per_user=2, queue_timeout=0.05,
                        retry_after=0.02, max_queued_total=4)
    api = APIServer(store, flow_control=fc).start()
    try:
        names = []
        for i in range(8):
            store.create("Pod", _pod(i))
            names.append(f"f{i:03d}")
        unloaded = timed_writes(api.url, "default", names, rounds=3)
        shed0 = (_rejected("readonly_queue_full")
                 + _rejected("readonly_timeout"))
        mut_rejects0 = sum(
            v for (lab,), v in m.apiserver_rejected.items().items()
            if lab.startswith("mutating_"))
        flood_out = {}

        def flood():
            flood_out["stats"] = run_reader_flood(
                api.url, n_readers=10, duration=1.6)

        ft = threading.Thread(target=flood)
        ft.start()
        time.sleep(0.15)  # the flood is saturating the readonly pool
        loaded = timed_writes(api.url, "default", names, rounds=3)
        ft.join(60)
        stats = flood_out["stats"]
        # zero lost: every reader request completed (retried-to-success)
        assert stats.failures == 0
        assert stats.requests > 0 and len(stats.per_reader) == 10
        # the flood was real: readonly sheds happened DURING IT (delta,
        # not the battery-cumulative counter) and were answered
        shed = (_rejected("readonly_queue_full")
                + _rejected("readonly_timeout")) - shed0
        assert shed > 0, "flood never saturated the readonly pool"
        # mutating never starved: no writer request was shed...
        mut_rejects = sum(
            v for (lab,), v in m.apiserver_rejected.items().items()
            if lab.startswith("mutating_"))
        assert mut_rejects == mut_rejects0
        # ...and throughput stayed within the acceptance bound.  On this
        # 1-core box the writer's wall time under 10 reader THREADS is
        # dominated by GIL scheduling, not flow control (unloaded ≈ 30ms,
        # so a pure-CPU-contention run can exceed a tight 2×+ε bound with
        # zero sheds) — the absolute backstop still catches real
        # starvation: a writer queued behind readers would pay
        # queue_timeout × retries per PATCH, far past it.  The
        # zero-mutating-sheds assert above is the deterministic half of
        # the acceptance.
        assert loaded <= max(2.0 * unloaded + 0.5, 2.5), (loaded, unloaded)
        # pools drain clean (the client can see its response BEFORE the
        # handler thread's finally releases the seat — wait it out)
        deadline = time.monotonic() + 5
        while (fc.readonly.inflight() or fc.mutating.inflight()) \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fc.readonly.inflight() == 0 and fc.mutating.inflight() == 0
    finally:
        api.stop()


# --- CLI surface ---------------------------------------------------------------


def test_controlplane_status_renders_flow_and_rejections():
    from kubernetes_tpu.cli import Kubectl

    store = ObjectStore()
    fc = FlowController(max_readonly_inflight=1, max_queue_per_user=1,
                        queue_timeout=0.01)
    held = fc.admit("x", mutating=False)
    with pytest.raises(RequestRejected):
        fc.admit("y", mutating=False)
    out = Kubectl(store).controlplane_status(flow=fc)
    assert "flow-readonly" in out and "inflight" in out
    assert "readonly_timeout" in out
    held.release()
    # metrics-backed path (no live objects) renders the same series
    out2 = Kubectl(store).controlplane_status()
    assert "readonly_timeout" in out2
