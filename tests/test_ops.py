"""ops/ kernels: einsum domain gather/scatter vs the direct XLA forms.

The einsum forms exist because XLA lowers minor-axis element gathers and
scatters to serial loops on TPU (measured ~100 ms for a [128, 2, 1024]
lookup vs sub-ms for the contraction — see kubernetes_tpu/ops/segment.py).
These tests pin exact numerical equivalence; tests/test_batch_assign.py
pins the plugin update_batch folds built on them.
"""

import numpy as np
import jax.numpy as jnp

from kubernetes_tpu.ops import (
    domain_any,
    domain_gather,
    domain_scatter_add,
    point_scatter_add,
)


def test_domain_gather_matches_take_along_axis():
    rng = np.random.default_rng(0)
    table = rng.integers(0, 1000, (8, 3, 17)).astype(np.int32)
    dom = rng.integers(0, 17, (8, 3, 64)).astype(np.int32)
    got = np.asarray(domain_gather(jnp.asarray(table), jnp.asarray(dom)))
    want = np.take_along_axis(table, dom, axis=-1)
    assert np.array_equal(got.astype(np.int32), want)


def test_domain_scatter_add_matches_np_add_at():
    rng = np.random.default_rng(1)
    vals = rng.integers(0, 9, (4, 2, 32)).astype(np.int32)
    dom = rng.integers(0, 9, (4, 2, 32)).astype(np.int32)
    got = np.asarray(domain_scatter_add(jnp.asarray(vals), jnp.asarray(dom), 9))
    want = np.zeros((4, 2, 9), np.int64)
    for b in range(4):
        for c in range(2):
            np.add.at(want[b, c], dom[b, c], vals[b, c])
    assert np.array_equal(got.astype(np.int64), want)


def test_domain_any():
    dom = np.array([[0, 2, 2, 5]], dtype=np.int32)
    mask = np.array([[True, False, True, False]])
    got = np.asarray(domain_any(jnp.asarray(mask), jnp.asarray(dom), 6))
    assert got.shape == (1, 6)
    assert got[0].tolist() == [True, False, True, False, False, False]


def test_point_scatter_add():
    rng = np.random.default_rng(2)
    table = rng.integers(0, 50, (6, 4, 11)).astype(np.int32)
    dom_at = rng.integers(0, 11, (6, 4)).astype(np.int32)
    inc = rng.integers(0, 3, (6, 4)).astype(np.int32)
    got = np.asarray(
        point_scatter_add(jnp.asarray(table), jnp.asarray(dom_at), jnp.asarray(inc))
    )
    want = table.copy()
    for i in range(6):
        for j in range(4):
            want[i, j, dom_at[i, j]] += inc[i, j]
    assert np.array_equal(got, want)
