"""Gang scheduling subsystem: PodGroup API, coscheduling plugin, solver
all-or-nothing mask, queue group cohesion, CLI, and the end-to-end
starved-gang acceptance scenario."""

import json

import numpy as np
import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.api.scheme import default_scheme
from kubernetes_tpu.api.serialize import roundtrips, to_manifest
from kubernetes_tpu.cli import Kubectl
from kubernetes_tpu.gang import (
    POD_GROUP_LABEL,
    SLICE_LABEL,
    GangDirectory,
    gang_all_or_nothing,
)
from kubernetes_tpu.queueing import PriorityQueue
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_group(store, name, min_member, timeout=30, created=1000.0,
               ns="default"):
    pg = v1.PodGroup(
        metadata=v1.ObjectMeta(name=name, namespace=ns),
        min_member=min_member, schedule_timeout_seconds=timeout,
    )
    pg.metadata.creation_timestamp = created
    store.create("PodGroup", pg)
    return pg


def gang_pod(group, i, cpu="3", created=None):
    p = (make_pod().name(f"{group}-{i}").uid(f"{group}-{i}")
         .namespace("default").label(POD_GROUP_LABEL, group)
         .req({"cpu": cpu}).obj())
    if created is not None:
        p.metadata.creation_timestamp = created
    return p


# --- L0: API object, scheme, serialization -----------------------------------


def test_podgroup_scheme_decode_and_roundtrip():
    scheme = default_scheme()
    manifest = {
        "apiVersion": "scheduling.x-k8s.io/v1alpha1",
        "kind": "PodGroup",
        "metadata": {"name": "g1", "namespace": "ml"},
        "spec": {"minMember": 8, "scheduleTimeoutSeconds": 120},
        "status": {"phase": "Pending"},
    }
    pg = scheme.decode(manifest)
    assert pg.min_member == 8
    assert pg.schedule_timeout_seconds == 120
    assert pg.phase == v1.POD_GROUP_PENDING
    assert pg.namespace == "ml"
    # camelCase round-trip through to_manifest → decode
    assert roundtrips(pg, scheme)
    wire = to_manifest(pg, scheme)
    assert wire["apiVersion"] == "scheduling.x-k8s.io/v1alpha1"
    assert wire["spec"]["minMember"] == 8
    assert wire["status"]["phase"] == "Pending"


def test_podgroup_wrong_group_rejected():
    from kubernetes_tpu.api.scheme import SchemeError

    with pytest.raises(SchemeError):
        default_scheme().decode(
            {"apiVersion": "apps/v1", "kind": "PodGroup",
             "metadata": {"name": "g"}})


# --- solver: device all-or-nothing mask --------------------------------------


def test_gang_all_or_nothing_masks_incomplete_gangs():
    # gang 0 fully placed, gang 1 has one miss, solos untouched
    node_row = np.array([3, 5, 7, -1, 2, -1], dtype=np.int32)
    gang_seg = np.array([0, 0, 1, 1, -1, -1], dtype=np.int32)
    out = np.asarray(gang_all_or_nothing(node_row, gang_seg))
    assert out.tolist() == [3, 5, -1, -1, 2, -1]


def test_gang_all_or_nothing_noop_without_gangs():
    node_row = np.array([1, -1, 4], dtype=np.int32)
    seg = np.full(3, -1, dtype=np.int32)
    assert np.asarray(gang_all_or_nothing(node_row, seg)).tolist() == [1, -1, 4]


# --- queue: group-aware activate / event moves -------------------------------


def _group_key(info):
    name = info.pod.metadata.labels.get(POD_GROUP_LABEL)
    return name or None


def test_activate_moves_whole_group_out_of_backoff():
    from kubernetes_tpu.queueing.priority_queue import QueuedPodInfo

    clock = FakeClock()
    q = PriorityQueue(clock=clock, group_key=_group_key)
    a = gang_pod("g", 0)
    b = gang_pod("g", 1)
    q.add(a)
    q.add(b)
    ia, ib = q.pop(), q.pop()
    # one member to backoff (transient error), one parked unschedulable
    q.requeue_after_error(ia)
    q.add_unschedulable(ib)
    assert q.pending_count() == (0, 1, 1)
    # activating ONE member drags the whole gang to active together
    q.activate([ia.pod])
    assert q.pending_count() == (2, 0, 0)


def test_event_move_drags_gang_siblings_from_backoff():
    from kubernetes_tpu.framework.events import (
        ActionType,
        ClusterEvent,
        EventResource,
    )

    clock = FakeClock()
    q = PriorityQueue(clock=clock, group_key=_group_key)
    a, b, solo = gang_pod("g", 0), gang_pod("g", 1), \
        make_pod().name("solo").uid("solo").obj()
    for p in (a, b, solo):
        q.add(p)
    ia, ib, isolo = q.pop(), q.pop(), q.pop()
    q.add_unschedulable(ia)  # event-movable
    q.requeue_after_error(ib)  # sibling stuck in backoff
    q.add_unschedulable(isolo)  # non-member: keeps per-pod backoff gating
    q.move_all_to_active_or_backoff(
        ClusterEvent(EventResource.NODE, ActionType.ADD, "NodeAdd"))
    q.flush()
    active, backoff, unsched = q.pending_count()
    # both gang members are ACTIVE (sibling bypassed its backoff window);
    # the solo pod moved by its own rules (fresh failure → backoff)
    assert active == 2 and unsched == 0
    assert backoff == 1


# --- directory: quorum, permit, preemption guard ------------------------------


def test_directory_quorum_and_release():
    clock = FakeClock()
    store = ObjectStore()
    d = GangDirectory(store, clock=clock)
    from kubernetes_tpu.framework.waiting_pods import WaitingPodsMap

    wp_map = WaitingPodsMap(clock=clock)
    d.bind_runtime(wp_map)
    make_group(store, "g", 3)
    pods = [gang_pod("g", i) for i in range(3)]
    for p in pods:
        d.on_pod_event("ADDED", p, False)

    # below quorum: a 2-member group rejects unresolvably
    lone = gang_pod("tiny", 0)
    make_group(store, "tiny", 3)
    d.on_pod_event("ADDED", lone, False)
    st = d.prefilter(lone)
    assert st is not None and not st.is_success()
    # missing PodGroup object also rejects
    ghost = gang_pod("ghost", 0)
    assert d.prefilter(ghost) is not None
    # full group passes
    assert d.prefilter(pods[0]) is None

    # permit: first two wait, third releases all
    assert d.on_permit(pods[0])[0] == "wait"
    wp_map.add(pods[0], "Coscheduling", 30.0)
    d.note_waiting(pods[0], "n0")
    assert d.on_permit(pods[1])[0] == "wait"
    wp_map.add(pods[1], "Coscheduling", 30.0)
    d.note_waiting(pods[1], "n1")
    # preemption guard: with 2/3 placed the last member may preempt
    assert d.allows_preemption(pods[2])
    assert not d.allows_preemption(lone)
    decision, _ = d.on_permit(pods[2])
    assert decision == "allow"
    assert wp_map.wait_on_permit(pods[0]) is None  # released
    assert wp_map.wait_on_permit(pods[1]) is None


def test_directory_release_once_with_more_members_than_min():
    """minMember is a MINIMUM: extra members past the quorum must not
    re-count the gang attempt or regress the phase."""
    from kubernetes_tpu.framework.waiting_pods import WaitingPodsMap
    from kubernetes_tpu.metrics import scheduler_metrics as m

    clock = FakeClock()
    store = ObjectStore()
    d = GangDirectory(store, clock=clock)
    d.bind_runtime(WaitingPodsMap(clock=clock))
    make_group(store, "g", 2)  # minMember 2, but 4 members exist
    pods = [gang_pod("g", i) for i in range(4)]
    for p in pods:
        d.on_pod_event("ADDED", p, False)
    before = m.gang_scheduling_attempts.value(("scheduled",))
    assert d.on_permit(pods[0])[0] == "wait"
    d.note_waiting(pods[0], "n0")
    for p in pods[1:]:  # members 2..4 all cross the threshold
        assert d.on_permit(p)[0] == "allow"
        d.on_bound(p, "n0")
    assert m.gang_scheduling_attempts.value(("scheduled",)) == before + 1
    # phase reached Scheduled (via on_bound) and was not regressed
    assert store.get("PodGroup", "default", "g").phase == \
        v1.POD_GROUP_SCHEDULED


def test_deleting_waiting_member_fails_gang_fast_and_unreserves():
    """Deleting a member that holds its Permit wait aborts its binding
    cycle through the unreserve chain (reserved plugin state rolls back)
    and fails the remaining waiters immediately — no timeout burn."""
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=2, clock=clock, batch_wait=0)
    for i in range(3):  # capacity for 3 of the 4 members
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "4", "pods": "10"}).obj())
    make_group(store, "g", 4, timeout=1000)
    for i in range(4):
        store.create("Pod", gang_pod("g", i))
    for _ in range(6):
        sched.schedule_cycle()
        clock.advance(0.5)
    # the first batch's two members hold at Permit; the second batch can
    # place only one member, so the in-batch mask withdrew it
    assert len(sched._waiting_binds) == 2
    held = next(iter(sched._waiting_binds))
    name = sched._waiting_binds[held].qi.pod.metadata.name
    store.delete("Pod", "default", name)
    # the held cycle is gone and the survivors failed fast (no waiters
    # left) — well before the 1000s deadline
    assert held not in sched._waiting_binds
    sched.schedule_cycle()
    assert len(sched._waiting_binds) == 0
    assert store.get("PodGroup", "default", "g").phase == \
        v1.POD_GROUP_UNSCHEDULABLE


def test_directory_evicts_drained_dead_groups():
    clock = FakeClock()
    store = ObjectStore()
    d = GangDirectory(store, clock=clock)
    pg = make_group(store, "g", 2)
    p = gang_pod("g", 0)
    d.on_pod_event("ADDED", p, False)
    assert d.active
    store.delete("PodGroup", "default", "g")
    d.on_group_event("DELETED", pg)
    d.on_pod_event("DELETED", p, False)
    assert not d.active  # fully drained dead group state was dropped


# --- end-to-end: the acceptance scenario -------------------------------------


def _build_gang_cluster(clock, n_nodes=20, batch_size=4):
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=batch_size, clock=clock,
                         batch_wait=0)
    for i in range(n_nodes):
        store.create(
            "Node",
            make_node().name(f"n{i:02d}")
            .capacity({"cpu": "4", "pods": "10"})
            .label(SLICE_LABEL, f"s{i // 8}").obj(),
        )
    for gi, g in enumerate(["ga", "gb", "gc"]):
        make_group(store, g, 8, timeout=30, created=1000.0 + gi)
        for i in range(8):
            store.create("Pod", gang_pod(g, i, created=1000.0 + gi))
    return store, sched


def _bound_count(store, groups=("ga", "gb", "gc")):
    return sum(
        1 for g in groups for i in range(8)
        if store.get("Pod", "default", f"{g}-{i}").spec.node_name
    )


def test_e2e_two_gangs_bind_starved_gang_times_out():
    """3 gangs × 8 pods on 20 single-member hosts (capacity for only two
    FULL gangs): exactly 16 pods bind — two complete gangs, zero partial
    placements — and the starved gang's members requeue together with the
    PodGroup phase reflecting the timeout."""
    clock = FakeClock()
    store, sched = _build_gang_cluster(clock)
    for _ in range(30):
        sched.schedule_cycle()
        clock.advance(0.5)
    assert _bound_count(store) == 16
    for g in ("ga", "gb"):
        assert all(store.get("Pod", "default", f"{g}-{i}").spec.node_name
                   for i in range(8))
        assert store.get("PodGroup", "default", g).phase == \
            v1.POD_GROUP_SCHEDULED
    # the starved gang holds some members at Permit, binds NONE
    assert all(not store.get("Pod", "default", f"gc-{i}").spec.node_name
               for i in range(8))
    assert len(sched._waiting_binds) > 0
    # deadline fires: the whole gang rolls back and requeues TOGETHER
    clock.advance(40.0)
    s = sched.schedule_cycle()
    assert len(sched._waiting_binds) == 0
    assert s.unschedulable > 0
    assert _bound_count(store) == 16  # still zero partial placements
    assert store.get("PodGroup", "default", "gc").phase == \
        v1.POD_GROUP_UNSCHEDULABLE
    active, backoff, _ = sched.queue.pending_count()
    assert active == 8 and backoff == 0  # atomic group requeue
    from kubernetes_tpu.metrics import scheduler_metrics as m

    assert m.gang_timeouts.value() >= 1.0


def test_e2e_gang_packs_one_slice():
    """A single 8-gang on sliced hosts lands entirely inside one slice
    (the Coscheduling anchor-slice score plane)."""
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, clock=clock, batch_wait=0)
    for i in range(16):
        store.create(
            "Node",
            make_node().name(f"n{i:02d}")
            .capacity({"cpu": "4", "pods": "10"})
            .label(SLICE_LABEL, f"s{i // 8}").obj(),
        )
    make_group(store, "g", 8)
    for i in range(8):
        store.create("Pod", gang_pod("g", i))
    stats = sched.run_until_idle(backoff_wait=1.0)
    assert stats.scheduled == 8
    slices = set()
    for i in range(8):
        node = store.get("Pod", "default", f"g-{i}").spec.node_name
        slices.add(store.get("Node", "", node).metadata.labels[SLICE_LABEL])
    assert len(slices) == 1


def test_quorum_reject_then_sibling_arrival_unblocks():
    """A partial gang parks unschedulable at the PreFilter quorum gate
    (no solver work) and schedules once the missing members appear."""
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4, clock=clock, batch_wait=0)
    for i in range(4):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "4", "pods": "10"}).obj())
    make_group(store, "g", 4)
    for i in range(2):  # only half the gang exists
        store.create("Pod", gang_pod("g", i))
    s = sched.schedule_cycle()
    assert s.unschedulable == 2 and s.scheduled == 0
    _, _, unsched = sched.queue.pending_count()
    assert unsched == 2
    for i in range(2, 4):  # siblings arrive → POD ADD event requeues
        store.create("Pod", gang_pod("g", i))
    stats = sched.run_until_idle(backoff_wait=1.0)
    assert stats.scheduled == 4
    assert store.get("PodGroup", "default", "g").phase == \
        v1.POD_GROUP_SCHEDULED


def test_gang_never_preempts_unless_last_member():
    """An incomplete gang's members must not evict victims (the gang may
    never complete): low-priority victims survive a starved high-priority
    gang."""
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4, clock=clock, batch_wait=0)
    for i in range(4):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "4", "pods": "10"}).obj())
    # fill the cluster with low-priority victims
    for i in range(4):
        store.create("Pod", make_pod().name(f"low-{i}").uid(f"low-{i}")
                     .namespace("default").req({"cpu": "3"}).obj())
    sched.run_until_idle(backoff_wait=1.0)
    assert all(store.get("Pod", "default", f"low-{i}").spec.node_name
               for i in range(4))
    # an 8-member high-priority gang that can NEVER fully fit (4 hosts)
    make_group(store, "g", 8, timeout=10)
    for i in range(8):
        p = gang_pod("g", i)
        p.spec.priority = 100
        store.create("Pod", p)
    for _ in range(12):
        sched.schedule_cycle()
        clock.advance(1.0)
    # victims untouched — no preemption happened for the doomed gang
    assert all(store.get("Pod", "default", f"low-{i}").spec.node_name
               for i in range(4))
    assert _bound_count(store, groups=("g",)) == 0


# --- CLI ----------------------------------------------------------------------


def test_cli_get_podgroups_table_and_json():
    store = ObjectStore()
    pg = make_group(store, "trainer", 8, timeout=120)
    pg.phase = v1.POD_GROUP_SCHEDULING
    store.update("PodGroup", pg)
    k = Kubectl(store)
    out = k.get("podgroups")
    assert "MIN-MEMBER" in out and "PHASE" in out
    assert "trainer" in out and "Scheduling" in out and "8" in out
    j = json.loads(k.get_json("pg", "default", "trainer"))
    assert j["kind"] == "PodGroup"
    assert j["spec"]["minMember"] == 8
    assert j["status"]["phase"] == "Scheduling"


def test_cli_get_podgroups_over_apiserver():
    from kubernetes_tpu.apiserver import APIServer, HTTPApiClient
    from kubernetes_tpu.apiserver.client import HTTPStoreFacade

    store = ObjectStore()
    make_group(store, "trainer", 4)
    api = APIServer(store).start()
    try:
        k = Kubectl(HTTPStoreFacade(HTTPApiClient(api.url)))
        out = k.get("podgroups")
        assert "trainer" in out and "Pending" in out
        j = json.loads(k.get_json("podgroup", "default", "trainer"))
        assert j["spec"]["minMember"] == 4
        assert j["apiVersion"] == "scheduling.x-k8s.io/v1alpha1"
    finally:
        api.stop()
