"""Extender protocol: client ↔ TPUScore server round-trip
(reference: test/integration/scheduler/extender_test.go pattern)."""

import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.extender import (
    ExtenderConfig,
    ExtenderError,
    HTTPExtender,
    TPUScoreExtenderServer,
)
from kubernetes_tpu.testutil import make_pod


@pytest.fixture
def server():
    def score_fn(pod_dict, names):
        # toy device-scorer stand-in: nodes ending in odd digits are infeasible,
        # score = index
        feasible = [n for n in names if int(n[-1]) % 2 == 0]
        return feasible, {n: i * 10 for i, n in enumerate(names)}

    srv = TPUScoreExtenderServer(score_fn)
    srv.start()
    yield srv
    srv.stop()


def client(srv, **kw):
    return HTTPExtender(ExtenderConfig(
        url_prefix=srv.url, filter_verb="filter", prioritize_verb="prioritize",
        node_cache_capable=True, **kw,
    ))


def test_filter_round_trip(server):
    ext = client(server)
    pod = make_pod().name("p").uid("p").obj()
    feasible, failed = ext.filter(pod, ["n0", "n1", "n2", "n3"])
    assert feasible == ["n0", "n2"]
    assert set(failed) == {"n1", "n3"}


def test_prioritize_weighted(server):
    ext = client(server, weight=3)
    pod = make_pod().name("p").uid("p").obj()
    scores = ext.prioritize(pod, ["n0", "n2"])
    assert scores == {"n0": 0, "n2": 30}


def test_ignorable_extender_swallows_errors():
    ext = HTTPExtender(ExtenderConfig(
        url_prefix="http://127.0.0.1:1", filter_verb="filter", ignorable=True,
        http_timeout=0.2,
    ))
    pod = make_pod().name("p").uid("p").obj()
    feasible, failed = ext.filter(pod, ["n0"])
    assert feasible == ["n0"] and not failed


def test_non_ignorable_extender_raises():
    ext = HTTPExtender(ExtenderConfig(
        url_prefix="http://127.0.0.1:1", filter_verb="filter", http_timeout=0.2,
    ))
    pod = make_pod().name("p").uid("p").obj()
    with pytest.raises(ExtenderError):
        ext.filter(pod, ["n0"])
