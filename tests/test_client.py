"""Client machinery: informer, workqueue, leader election, events."""

from kubernetes_tpu.client import (
    EventRecorder,
    InformerFactory,
    LeaderElector,
    LeaseLock,
    RateLimitingQueue,
)
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_informer_list_then_watch():
    store = ObjectStore()
    store.create("Node", make_node().name("pre").obj())
    factory = InformerFactory(store)
    inf = factory.informer("Node")
    added = []
    inf.add_event_handler(on_add=lambda o: added.append(o.metadata.name))
    factory.start()
    assert factory.wait_for_cache_sync()
    assert added == ["pre"]  # LIST replay
    store.create("Node", make_node().name("post").obj())
    assert added == ["pre", "post"]  # WATCH
    assert inf.get("", "post") is not None
    assert len(inf.list()) == 2


def test_informer_restart_relists():
    """Stateless recovery: a fresh informer rebuilds state from LIST+WATCH."""
    store = ObjectStore()
    store.create("Node", make_node().name("a").obj())
    f1 = InformerFactory(store)
    inf1 = f1.informer("Node")
    f1.start()
    inf1.reflector.stop()  # "crash"
    store.create("Node", make_node().name("b").obj())
    f2 = InformerFactory(store)
    inf2 = f2.informer("Node")
    f2.start()
    assert {o.metadata.name for o in inf2.list()} == {"a", "b"}


def test_workqueue_dedup_and_reprocess():
    clock = FakeClock()
    q = RateLimitingQueue(clock=clock)
    q.add("x")
    q.add("x")
    assert len(q) == 1
    item = q.get()
    q.add("x")  # added while processing → dirty
    q.done("x")
    assert q.get() == "x"
    q.done("x")
    assert q.get() is None


def test_workqueue_rate_limited_backoff():
    clock = FakeClock()
    q = RateLimitingQueue(base_delay=0.01, clock=clock)
    q.add_rate_limited("x")
    assert q.get() is None  # not due yet
    clock.advance(0.02)
    assert q.get() == "x"
    q.done("x")
    q.add_rate_limited("x")  # second failure → 0.02 delay
    clock.advance(0.011)
    assert q.get() is None
    clock.advance(0.02)
    assert q.get() == "x"


def test_leader_election_acquire_and_steal():
    store = ObjectStore()
    clock = FakeClock()
    lock = LeaseLock(store, "kube-system", "tpu-scheduler")
    a = LeaderElector(lock, "a", lease_duration=15, clock=clock)
    b = LeaderElector(lock, "b", lease_duration=15, clock=clock)
    assert a.try_acquire_or_renew()
    assert not b.try_acquire_or_renew()
    clock.advance(10)
    assert a.try_acquire_or_renew()  # renewed
    clock.advance(10)
    assert not b.try_acquire_or_renew()  # lease still fresh (renewed at t=10)
    clock.advance(16)
    assert b.try_acquire_or_renew()  # stale → stolen
    assert not a.is_leader() or not a.try_acquire_or_renew()


def test_event_recorder_aggregates():
    store = ObjectStore()
    rec = EventRecorder(store)
    pod = make_pod().name("p").uid("p").obj()
    rec.eventf(pod, "Warning", "FailedScheduling", "0/3 nodes available")
    rec.eventf(pod, "Warning", "FailedScheduling", "0/4 nodes available")
    evs = rec.events_for(pod)
    assert len(evs) == 1 and evs[0].count == 2
    assert len(store.list("Event")[0]) == 1
