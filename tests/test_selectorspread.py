"""SelectorSpread: pods of a Service spread across nodes/zones."""

from kubernetes_tpu import plugins as P
from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.framework.interface import PluginWithWeight as PW
from kubernetes_tpu.framework.runtime import BatchedFramework, initial_dynamic_state
from kubernetes_tpu.framework.podbatch import PodBatchCompiler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.state.cache import Cache, Snapshot
from kubernetes_tpu.state.encoding import ClusterEncoder
from kubernetes_tpu.testutil import make_node, make_pod

import jax.numpy as jnp
import numpy as np


def test_selector_spread_prefers_empty_node():
    store = ObjectStore()
    svc = v1.Service(selector={"app": "web"})
    svc.metadata.name = "web"
    store.create("Service", svc)

    cache = Cache()
    for i in range(3):
        cache.add_node(make_node().name(f"n{i}")
                       .label("topology.kubernetes.io/zone", f"z{i % 2}").obj())
    # two service pods already on n0
    for i in range(2):
        cache.add_pod(make_pod().name(f"sp{i}").uid(f"sp{i}").namespace("default")
                      .label("app", "web").req({"cpu": "1"}).node("n0").obj())
    snap = Snapshot()
    cache.update_snapshot(snap)
    enc = ClusterEncoder()
    comp = PodBatchCompiler(enc)
    pod = make_pod().name("p").uid("p").namespace("default").label("app", "web").req({"cpu": "1"}).obj()
    batch = comp.compile([pod])
    enc.full_sync(snap)

    plugin = P.SelectorSpreadPlugin(store)
    fw = BatchedFramework([PW(P.FitPlugin(), 1), PW(plugin, 1)])
    host_auxes = fw.host_prepare(batch, snap, enc)
    dsnap = enc.to_device()
    dyn = initial_dynamic_state(dsnap)
    auxes = fw.prepare(batch, dsnap, dyn, host_auxes)
    res = fw.greedy_assign(batch, dsnap, dyn, auxes, jnp.arange(batch.size))
    name_of = {r: n for n, r in enc.node_rows.items()}
    # n0 is crowded (2 service pods, zone z0); n1 shares zone z1 alone → best
    assert name_of[int(np.asarray(res.node_row)[0])] == "n1"
