"""Versioned-manifest conversion (apimachinery runtime conversion analog).

Reference: pkg/apis/autoscaling/v1/conversion.go (the structural HPA
conversion), generated identity conversions for graduated groups
(batch/v1beta1 CronJob, policy/v1beta1 PDB, discovery v1beta1).
"""

import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.api.scheme import SchemeError, default_scheme
from kubernetes_tpu.api.serialize import to_manifest

SCHEME = default_scheme()


def test_hpa_v1_manifest_decodes_structurally():
    """autoscaling/v1's targetCPUUtilizationPercentage converts into the
    v2 metrics list the internal type reads."""
    m = {
        "apiVersion": "autoscaling/v1", "kind": "HorizontalPodAutoscaler",
        "metadata": {"name": "web", "namespace": "default"},
        "spec": {"scaleTargetRef": {"kind": "Deployment", "name": "web"},
                 "minReplicas": 2, "maxReplicas": 8,
                 "targetCPUUtilizationPercentage": 65},
    }
    hpa = SCHEME.decode(m)
    assert hpa.target_utilization == 65.0
    assert hpa.min_replicas == 2 and hpa.max_replicas == 8
    assert hpa.target_name == "web"


def test_hpa_served_back_at_v1():
    """convert_manifest re-serves a v2-stored HPA at the v1 spoke shape."""
    hpa = SCHEME.decode({
        "apiVersion": "autoscaling/v2", "kind": "HorizontalPodAutoscaler",
        "metadata": {"name": "api"},
        "spec": {"scaleTargetRef": {"kind": "Deployment", "name": "api"},
                 "minReplicas": 1, "maxReplicas": 4,
                 "metrics": [{"type": "Resource", "resource": {
                     "name": "cpu",
                     "target": {"type": "Utilization",
                                "averageUtilization": 70}}}]},
    })
    out = SCHEME.convert_manifest(hpa, "autoscaling/v1")
    assert out["apiVersion"] == "autoscaling/v1"
    assert out["spec"]["targetCPUUtilizationPercentage"] == 70
    assert "metrics" not in out["spec"]
    # and the spoke round-trips: v1 → hub → v1 preserves the target
    back = SCHEME.converter.to_hub("HorizontalPodAutoscaler",
                                   "autoscaling/v1", out)
    again = SCHEME.converter.from_hub("HorizontalPodAutoscaler",
                                      "autoscaling/v1", back)
    assert again["spec"]["targetCPUUtilizationPercentage"] == 70


def test_graduated_spoke_versions_decode():
    """batch/v1beta1 CronJob and policy/v1beta1 PDB manifests (field-
    identical pre-graduation schemas) decode through the identity spokes."""
    cj = SCHEME.decode({
        "apiVersion": "batch/v1beta1", "kind": "CronJob",
        "metadata": {"name": "nightly", "namespace": "default"},
        "spec": {"schedule": "0 3 * * *"},
    })
    assert cj.schedule == "0 3 * * *"
    pdb = SCHEME.decode({
        "apiVersion": "policy/v1beta1", "kind": "PodDisruptionBudget",
        "metadata": {"name": "pdb", "namespace": "default"},
        "spec": {"minAvailable": 2,
                 "selector": {"matchLabels": {"app": "a"}}},
    })
    assert pdb.min_available == 2


def test_wrong_group_still_rejected():
    with pytest.raises(SchemeError):
        SCHEME.decode({"apiVersion": "batch/v1", "kind": "Deployment",
                       "metadata": {"name": "x"}})
    with pytest.raises(SchemeError):
        SCHEME.convert_manifest(
            to_manifest(v1.Namespace(metadata=v1.ObjectMeta(name="n")),
                        SCHEME),
            "policy/v1beta1")


def test_spoke_round_trip_battery():
    """Every registered spoke: hub → spoke → hub is lossless for what the
    spoke can express (the apimachinery fuzzed round-trip contract, at the
    battery level this build's manifests support)."""
    conv = SCHEME.converter
    hpa_hub = {
        "apiVersion": "autoscaling/v2", "kind": "HorizontalPodAutoscaler",
        "metadata": {"name": "a", "namespace": "default"},
        "spec": {"scaleTargetRef": {"kind": "Deployment", "name": "a"},
                 "minReplicas": 1, "maxReplicas": 3,
                 "metrics": [{"type": "Resource", "resource": {
                     "name": "cpu", "target": {"type": "Utilization",
                                               "averageUtilization": 55}}}]},
        "status": {"currentMetrics": [{"type": "Resource", "resource": {
            "name": "cpu", "current": {"averageUtilization": 40}}}]},
    }
    spoke = conv.from_hub("HorizontalPodAutoscaler", "autoscaling/v1", hpa_hub)
    assert spoke["status"]["currentCPUUtilizationPercentage"] == 40
    back = conv.to_hub("HorizontalPodAutoscaler", "autoscaling/v1", spoke)
    assert back["spec"]["metrics"][0]["resource"]["target"][
        "averageUtilization"] == 55
    assert back["status"]["currentMetrics"][0]["resource"]["current"][
        "averageUtilization"] == 40

    for kind, spoke_v in (("CronJob", "batch/v1beta1"),
                          ("PodDisruptionBudget", "policy/v1beta1"),
                          ("EndpointSlice", "discovery.k8s.io/v1beta1")):
        assert conv.spoke_versions(kind) == [spoke_v]
        m = {"apiVersion": spoke_v, "kind": kind,
             "metadata": {"name": "x", "namespace": "default"},
             "spec": {"anything": 1}}
        hub = conv.to_hub(kind, spoke_v, m)
        assert hub["apiVersion"] != spoke_v
        again = conv.from_hub(kind, spoke_v, hub)
        assert again["apiVersion"] == spoke_v
        assert again["spec"] == {"anything": 1}
