"""ComponentConfig parsing + profile plugin construction."""

from kubernetes_tpu.config import load_config, build_plugins_for_profile


YAML_DOC = """
apiVersion: kubescheduler.config.k8s.io/v1beta3
kind: KubeSchedulerConfiguration
parallelism: 8
podInitialBackoffSeconds: 2
profiles:
  - schedulerName: default-scheduler
    pluginConfig:
      - name: InterPodAffinity
        args:
          hardPodAffinityWeight: 5
      - name: NodeResourcesFit
        args:
          scoringStrategy:
            type: MostAllocated
            resources:
              - name: cpu
                weight: 2
              - name: memory
                weight: 1
  - schedulerName: spread-scheduler
    plugins:
      score:
        disabled:
          - name: ImageLocality
        enabled:
          - name: PodTopologySpread
            weight: 5
"""


def test_load_yaml_defaults():
    cfg = load_config(YAML_DOC)
    assert cfg.parallelism == 8
    assert cfg.pod_initial_backoff_seconds == 2
    assert len(cfg.profiles) == 2
    prof = cfg.profile("default-scheduler")
    plugins = build_plugins_for_profile(prof, domain_cap=8)
    by_name = {pw.plugin.name: pw for pw in plugins}
    assert by_name["InterPodAffinity"].plugin.hard_weight == 5.0
    assert by_name["NodeResourcesFit"].plugin.strategy == "MostAllocated"
    assert by_name["TaintToleration"].weight == 3  # default weight kept


def test_profile_disable_and_weight_override():
    cfg = load_config(YAML_DOC)
    prof = cfg.profile("spread-scheduler")
    plugins = build_plugins_for_profile(prof, domain_cap=8)
    names = {pw.plugin.name for pw in plugins}
    assert "ImageLocality" not in names
    by_name = {pw.plugin.name: pw for pw in plugins}
    assert by_name["PodTopologySpread"].weight == 5


def test_empty_config_gets_default_profile():
    cfg = load_config({})
    assert len(cfg.profiles) == 1
    plugins = build_plugins_for_profile(cfg.profiles[0], domain_cap=8)
    assert {pw.plugin.name for pw in plugins} >= {
        "NodeResourcesFit", "TaintToleration", "NodeAffinity",
        "PodTopologySpread", "InterPodAffinity",
    }


def test_scheduler_from_config_two_profiles():
    from kubernetes_tpu.config import scheduler_from_config
    from kubernetes_tpu.sim.store import ObjectStore
    from kubernetes_tpu.testutil import make_node, make_pod

    cfg = load_config({
        "apiVersion": "kubescheduler.config.k8s.io/v1beta3",
        "profiles": [
            {"schedulerName": "default-scheduler"},
            {"schedulerName": "no-spread",
             "plugins": {"multiPoint": {"disabled": [
                 {"name": "PodTopologySpread"}, {"name": "InterPodAffinity"}]}}},
        ],
        "podInitialBackoffSeconds": 2,
    })
    store = ObjectStore()
    sched = scheduler_from_config(store, cfg, batch_size=4)
    assert set(sched.profiles) == {"default-scheduler", "no-spread"}
    assert sched.queue._initial_backoff == 2
    store.create("Node", make_node().name("n0").obj())
    p = make_pod().name("p").uid("p").namespace("default").req({"cpu": "1m"}).obj()
    p.spec.scheduler_name = "no-spread"
    store.create("Pod", p)
    stats = sched.run_until_idle()
    assert stats.scheduled == 1
    names = {pw.plugin.name for pw in sched._fws["no-spread"].plugins}
    assert "PodTopologySpread" not in names


def test_v1beta2_config_accepted():
    """Both served componentconfig versions load (apis/config v1beta2 +
    v1beta3 share the internal type here; the scheme prefix is validated)."""
    cfg = load_config({
        "apiVersion": "kubescheduler.config.k8s.io/v1beta2",
        "kind": "KubeSchedulerConfiguration",
        "profiles": [
            {"schedulerName": "default-scheduler",
             "plugins": {"score": {"disabled": [{"name": "ImageLocality"}]}}},
        ],
        "percentageOfNodesToScore": 50,
    })
    prof = cfg.profile()
    names = [e.name for e in prof.effective_plugins()]
    assert "ImageLocality" not in names
    assert "NodeResourcesFit" in names
    import pytest

    with pytest.raises(ValueError):
        load_config({"apiVersion": "not.a.scheduler/v1"})
