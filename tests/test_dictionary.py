"""Dictionary backends: the C++ interner must be a drop-in for Python.

Covers VERDICT r1 item 5 — the native interner is wired into
state/dictionary.py via the Dictionary() factory with a tested fallback,
plus an encode-throughput microbenchmark (reported, not asserted, since CI
boxes vary)."""

import math
import time

import numpy as np
import pytest

from kubernetes_tpu.state.dictionary import (
    MISSING,
    WELL_KNOWN,
    Dictionary,
    NativeDictionary,
    PyDictionary,
)


def _have_native():
    from kubernetes_tpu.native import load_interner

    return load_interner() is not None


def _backends():
    out = [PyDictionary()]
    if _have_native():
        out.append(Dictionary(native=True))
    return out


def test_factory_defaults_python_with_native_opt_in(monkeypatch):
    monkeypatch.delenv("KTPU_NATIVE_INTERNER", raising=False)
    assert isinstance(Dictionary(), PyDictionary)
    monkeypatch.setenv("KTPU_NATIVE_INTERNER", "0")
    assert isinstance(Dictionary(), PyDictionary)
    if _have_native():
        monkeypatch.setenv("KTPU_NATIVE_INTERNER", "1")
        assert isinstance(Dictionary(), NativeDictionary)


@pytest.mark.parametrize("d", _backends(), ids=lambda d: type(d).__name__)
def test_backend_contract(d):
    # well-known ids are stable compile-time constants
    for i, s in enumerate(WELL_KNOWN):
        assert d.lookup(s) == i
    a = d.intern("zone-a")
    b = d.intern("zone-b")
    assert d.intern("zone-a") == a  # idempotent
    assert b == a + 1  # sequential
    assert d.lookup("never-seen") == MISSING
    assert d.string(a) == "zone-a"
    n5 = d.intern("5")
    neg = d.intern("-12")
    bad = d.intern("5x")
    t = d.numeric_table()
    assert t.dtype == np.float32
    assert t[n5] == 5.0 and t[neg] == -12.0
    assert math.isnan(t[bad]) and math.isnan(t[a])
    many = d.intern_many(["m1", "m2", "m1"])
    assert many[0] == many[2] and many[1] == many[0] + 1
    nid = d.intern("last-one")
    assert len(d) == nid + 1


@pytest.mark.skipif(not _have_native(), reason="no C++ toolchain")
def test_native_matches_python_on_random_workload():
    rng = np.random.default_rng(0)
    words = [f"k{int(rng.integers(0, 500))}/v{int(rng.integers(0, 50))}"
             for _ in range(5000)]
    # numeric-parse edges: both backends must agree (Go strconv.Atoi shape)
    words += ["1_000", " 5", "+5", "-0", "0x10", "9223372036854775807",
              "9223372036854775808", "-9223372036854775808", "", "5 ", "5x"]
    py, nat = PyDictionary(), Dictionary(native=True)
    assert [py.intern(w) for w in words] == [nat.intern(w) for w in words]
    assert len(py) == len(nat)
    tp, tn = py.numeric_table(), nat.numeric_table()
    assert np.array_equal(np.isnan(tp), np.isnan(tn))
    assert np.array_equal(tp[~np.isnan(tp)], tn[~np.isnan(tn)])


@pytest.mark.skipif(not _have_native(), reason="no C++ toolchain")
def test_native_encode_throughput_microbench(capsys):
    rng = np.random.default_rng(1)
    words = [f"label-{int(rng.integers(0, 20000))}" for _ in range(200_000)]

    def run(d):
        t0 = time.perf_counter()
        d.intern_many(words)
        return time.perf_counter() - t0

    t_py, t_nat = run(PyDictionary()), run(Dictionary(native=True))
    with capsys.disabled():
        print(
            f"\n[interner microbench] 200k interns: python {t_py*1e3:.1f} ms, "
            f"c++ {t_nat*1e3:.1f} ms ({t_py/max(t_nat,1e-9):.1f}x)"
        )
    # sanity only: native must not be pathologically slower
    assert t_nat < t_py * 3
