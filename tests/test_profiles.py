"""Per-profile frameworks: schedulerName → framework dispatch.

Reference: pkg/scheduler/profile/profile.go:45 (profile.Map), scheduler.go:719
(frameworkForPod), eventhandlers.go responsibleForPod filtering.
"""

import numpy as np

from kubernetes_tpu.framework.interface import PluginWithWeight
from kubernetes_tpu.scheduler import TPUScheduler, default_plugins
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu import plugins as P
from kubernetes_tpu.testutil import make_node, make_pod


def _pin_to_suffix(suffix: str):
    """A tiny profile: Fit + a filter plugin accepting only nodes whose name
    ends with ``suffix`` (distinct plugin sets per profile)."""
    import jax.numpy as jnp

    class PinPlugin(P.NodeNamePlugin.__bases__[0]):  # framework Plugin base
        name = f"PinTo{suffix}"

        def filter(self, batch, snap, dyn, aux=None):
            # node names are interned; test uses names n0/n1 → match by the
            # hostname pseudo-label value id parity is overkill: use name ids
            ok = jnp.zeros(snap.node_valid.shape, bool)
            # host-side closure: rows whose name ends with suffix
            import numpy as _np

            rows = _np.zeros(snap.node_valid.shape, bool)
            for row, name in _ROWS.items():
                if name.endswith(suffix):
                    rows[row] = True
            return jnp.asarray(rows)[None, :] | ok

    return PinPlugin()


_ROWS = {}


def test_two_profiles_distinct_plugin_sets():
    store = ObjectStore()

    def profile_a(domain_cap):
        return [PluginWithWeight(P.FitPlugin(), 1),
                PluginWithWeight(_pin_to_suffix("0"), 0)]

    def profile_b(domain_cap):
        return [PluginWithWeight(P.FitPlugin(), 1),
                PluginWithWeight(_pin_to_suffix("1"), 0)]

    sched = TPUScheduler(
        store, batch_size=4,
        profiles={"sched-a": profile_a, "sched-b": profile_b},
    )
    store.create("Node", make_node().name("n0").obj())
    store.create("Node", make_node().name("n1").obj())
    # encode rows for the pin plugins (host-side closure over encoder state)
    sched.cache.update_snapshot(sched.snapshot)
    sched.encoder.sync(sched.snapshot, [n.node_name for n in sched.snapshot.node_info_list])
    _ROWS.clear()
    _ROWS.update(sched.encoder.row_to_name())

    pa = make_pod().name("pa").uid("pa").namespace("default").req({"cpu": "1"}).obj()
    pa.spec.scheduler_name = "sched-a"
    pb = make_pod().name("pb").uid("pb").namespace("default").req({"cpu": "1"}).obj()
    pb.spec.scheduler_name = "sched-b"
    # a pod for an unknown scheduler is ignored entirely (responsibleForPod)
    px = make_pod().name("px").uid("px").namespace("default").req({"cpu": "1"}).obj()
    px.spec.scheduler_name = "someone-else"
    for p in (pa, pb, px):
        store.create("Pod", p)

    stats = sched.run_until_idle()
    assert stats.scheduled == 2
    assert store.get("Pod", "default", "pa").spec.node_name == "n0"
    assert store.get("Pod", "default", "pb").spec.node_name == "n1"
    assert store.get("Pod", "default", "px").spec.node_name == ""
    # each profile got its own framework instance
    assert set(sched._fws) == {"sched-a", "sched-b"}


def test_pop_batch_groups_by_profile():
    store = ObjectStore()
    sched = TPUScheduler(
        store, batch_size=8,
        profiles={"sched-a": default_plugins, "sched-b": default_plugins},
    )
    store.create("Node", make_node().name("n0").obj())
    for i in range(6):
        p = (make_pod().name(f"p{i}").uid(f"p{i}").namespace("default")
             .req({"cpu": "1m"}).obj())
        p.spec.scheduler_name = "sched-a" if i % 2 == 0 else "sched-b"
        store.create("Pod", p)
    infos = sched.queue.pop_batch(
        8, group_key=lambda qi: qi.pod.spec.scheduler_name
    )
    names = {qi.pod.spec.scheduler_name for qi in infos}
    assert len(names) == 1  # one profile per batch
    assert len(infos) == 3
    # the other profile's pods are still queued
    rest = sched.queue.pop_batch(8, group_key=lambda qi: qi.pod.spec.scheduler_name)
    assert len(rest) == 3
    assert {qi.pod.spec.scheduler_name for qi in rest} != names
