"""metrics/registry render_text ↔ parse_text round-trip battery (ISSUE-14
satellite): the `ktpu slo --server` and `ktpu controlplane status --server`
paths re-derive histogram quantiles and counter/gauge values from the text
exposition, so the codec must round-trip histogram buckets (incl. +Inf),
escaped/empty/weird label values, and large/small magnitudes exactly."""

import math
import random
import string

import pytest

from kubernetes_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    bucket_counts_from_series,
    exponential_buckets,
    parse_text,
    quantile_from_counts,
    render_text,
)

WEIRD = ['', 'plain', 'with,comma', 'with"quote', 'back\\slash',
         'new\nline', 'tab\tchar', 'ünïcode-ζ', 'le="0.5"', 'a,b\\,c"']


def test_counter_gauge_roundtrip_weird_labels():
    reg = Registry()
    c = reg.register(Counter("requests_total"))
    g = reg.register(Gauge("depth"))
    c.inc(("with,comma", 'with"quote'), by=3)
    c.inc(("back\\slash",), by=2.5)
    c.inc((), by=1)
    g.set(-4.25, ("new\nline", ""))
    g.set(7, ())
    parsed = parse_text(render_text(reg))
    assert parsed[("requests_total", ("with,comma", 'with"quote'))] == 3
    assert parsed[("requests_total", ("back\\slash",))] == 2.5
    assert parsed[("requests_total", ())] == 1
    assert parsed[("depth", ("new\nline", ""))] == -4.25
    assert parsed[("depth", ())] == 7


def test_single_empty_label_value_is_the_documented_lossy_corner():
    """('',) renders label="" which parses back to () — kept for
    back-compat (ktpu nodehealth looks both keys up)."""
    reg = Registry()
    g = reg.register(Gauge("zone_state"))
    g.set(2.0, ("",))
    parsed = parse_text(render_text(reg))
    assert ("zone_state", ()) in parsed
    # inside a tuple, empty values survive exactly
    g2 = reg.register(Gauge("pair"))
    g2.set(1.0, ("", "x"))
    parsed = parse_text(render_text(reg))
    assert parsed[("pair", ("", "x"))] == 1.0


def test_histogram_buckets_count_sum_and_inf_roundtrip():
    reg = Registry()
    h = reg.register(Histogram("lat_seconds", [0.1, 1.0, 10.0]))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):  # one lands in +Inf overflow
        h.observe(v, ("phase,x",))
    text = render_text(reg)
    assert 'le="+Inf"' in text
    parsed = parse_text(text)
    assert parsed[("lat_seconds_count", ("phase,x",))] == 5
    assert parsed[("lat_seconds_sum", ("phase,x",))] == pytest.approx(56.05)
    # cumulative bucket series, in le order
    assert parsed[("lat_seconds_bucket", ("phase,x", "0.1"))] == 1
    assert parsed[("lat_seconds_bucket", ("phase,x", "1"))] == 3
    assert parsed[("lat_seconds_bucket", ("phase,x", "10"))] == 4
    assert parsed[("lat_seconds_bucket", ("phase,x", "+Inf"))] == 5
    # reconstruction: exact per-bucket counts + remote quantile == live
    per = bucket_counts_from_series(parsed, "lat_seconds")
    uppers, counts = per[("phase,x",)]
    assert counts == [1, 2, 1, 1]
    for q in (0.1, 0.5, 0.9, 0.99):
        assert quantile_from_counts(uppers, counts, q) == pytest.approx(
            h.quantile(q, ("phase,x",)))


def test_roundtrip_property_randomized():
    """Seeded property sweep: random registries of all three metric types
    with adversarial label tuples render → parse to the exact same series
    map, and every histogram's remote quantiles match the live ones."""
    rng = random.Random(1405)
    alphabet = string.ascii_letters + string.digits + ',"\\\n =+{}'
    for trial in range(20):
        reg = Registry()
        live_hists = []
        expected = {}
        for mi in range(rng.randint(1, 6)):
            name = f"m{trial}_{mi}"
            kind = rng.choice(["counter", "gauge", "hist"])
            labelsets = []
            for _ in range(rng.randint(1, 4)):
                n = rng.randint(0, 3)
                t = tuple(
                    "".join(rng.choice(alphabet)
                            for _ in range(rng.randint(0, 8)))
                    for _ in range(n))
                if len(t) == 1 and t[0] == "":
                    t = ("x",)  # the documented lossy corner, tested above
                labelsets.append(t)
            if kind == "counter":
                met = reg.register(Counter(name))
                for t in labelsets:
                    v = round(rng.uniform(0, 1e6), 3)
                    met.inc(t, by=v)
                    expected[(name, t)] = expected.get((name, t), 0) + v
            elif kind == "gauge":
                met = reg.register(Gauge(name))
                for t in labelsets:
                    v = round(rng.uniform(-1e3, 1e3), 6)
                    met.set(v, t)
                    expected[(name, t)] = v
            else:
                met = reg.register(Histogram(
                    name, exponential_buckets(0.001, 4, rng.randint(2, 8))))
                for t in labelsets:
                    for _ in range(rng.randint(1, 30)):
                        met.observe(rng.uniform(0, 10.0), t)
                live_hists.append((name, met, labelsets))
        parsed = parse_text(render_text(reg))
        for (name, t), v in expected.items():
            assert parsed[(name, t)] == pytest.approx(v), (trial, name, t)
        for name, met, labelsets in live_hists:
            per = bucket_counts_from_series(parsed, name)
            for t in set(labelsets):
                assert parsed[(f"{name}_count", t)] == met.count(t)
                assert parsed[(f"{name}_sum", t)] == pytest.approx(
                    met.sum(t), rel=1e-6)
                uppers, counts = per[t]
                assert sum(counts) == met.count(t)
                for q in (0.5, 0.9, 0.99):
                    assert quantile_from_counts(
                        uppers, counts, q) == pytest.approx(
                            met.quantile(q, t), rel=1e-6, abs=1e-12)


def test_parse_ignores_comments_blanks_and_garbage():
    parsed = parse_text(
        "# HELP x y\n\nnot a metric line at all { } ] [\n"
        "ok_total 3\nbad_value{label=\"a\"} notafloat\n")
    assert parsed == {("ok_total", ()): 3.0}


def test_quantile_from_counts_edge_cases():
    assert quantile_from_counts([1.0], None, 0.5) == 0.0
    assert quantile_from_counts([1.0], [0, 0], 0.5) == 0.0
    # all mass in +Inf overflow: quantile rails at the top finite edge
    assert quantile_from_counts([1.0, 2.0], [0, 0, 5], 0.5) == 2.0
    assert not math.isinf(quantile_from_counts([1.0, 2.0], [0, 0, 5], 0.99))
