"""Unified counterfactual engine: K-fork vmapped==sequential parity
(victim-mask / node-add / node-remove forks, randomized churn), the
ported-path contracts (preemption + descheduler route through whatif/),
and the engine's refusal conditions."""

import numpy as np
import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.autoscaler import NodeGroup, materialize_nodes
from kubernetes_tpu.gang import SLICE_LABEL
from kubernetes_tpu.metrics import scheduler_metrics as m
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod
from kubernetes_tpu.whatif import ForkSpec, WhatIfEngine


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _pod(name, cpu="2", node="", labels=None):
    w = (make_pod().name(name).uid(name).namespace("default")
         .req({"cpu": cpu}))
    for k, val in (labels or {}).items():
        w = w.label(k, val)
    if node:
        w = w.node(node)
    return w.obj()


def _cluster(n_nodes=6, batch_size=8):
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=batch_size, clock=clock,
                         batch_wait=0)
    for i in range(n_nodes):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "4", "pods": "10"}).obj())
    return clock, store, sched


def _assert_forks_equal(vm, seq):
    assert vm is not None and seq is not None
    assert len(vm) == len(seq)
    for a, b in zip(vm, seq):
        assert a.placements == b.placements, (a.fork.note, a.placements,
                                              b.placements)


# --- the parity battery -------------------------------------------------------


def test_kfork_vmapped_equals_sequential_under_randomized_churn():
    """THE engine contract: the vmapped [K, B, N] solve over K stacked
    forks equals K sequential single-fork solves bit-for-bit — for
    victim-mask forks (incl. an affinity-carrying victim whose aff_*
    contributions the fork masks), node-add forks, and node-remove forks,
    stable across randomized cluster churn."""
    clock, store, sched = _cluster()
    rng = np.random.default_rng(7)
    # an affinity-carrying bound pod: its fork masks aff_* contributions
    aff = (make_pod().name("affv").uid("affv").namespace("default")
           .req({"cpu": "1"}).label("color", "g")
           .pod_affinity("kubernetes.io/hostname", {"color": "g"}, anti=True)
           .node("n0").obj())
    store.create("Pod", aff)
    for i in range(4):
        store.create("Pod", _pod(f"b{i}", cpu="2", node=f"n{i % 3}"))
    sched.schedule_cycle()
    engine = WhatIfEngine(sched)
    group = NodeGroup(metadata=v1.ObjectMeta(name="ng"), max_size=8,
                      capacity={"cpu": "4", "pods": "10"}, slice_size=2)
    churn_seq = 0
    for rnd in range(3):
        pend = [_pod(f"pend-{rnd}-{i}", cpu="3",
                     labels={"color": "g"} if i == 0 else None)
                for i in range(3)]
        bound = [p for p in store.list("Pod")[0] if p.spec.node_name]
        victims = list(rng.choice(bound, size=min(2, len(bound)),
                                  replace=False))
        if aff.uid not in {v.uid for v in victims} and \
                store.get("Pod", "default", "affv") is not None:
            victims.append(store.get("Pod", "default", "affv"))
        live_nodes = [n.metadata.name for n in store.list("Node")[0]]
        forks = [
            ForkSpec(victims=victims, note="victims"),
            ForkSpec(add_nodes=materialize_nodes(
                group, 2, 10 * rnd, rnd, SLICE_LABEL), note="adds"),
            ForkSpec(remove_nodes=[str(rng.choice(live_nodes))],
                     note="removes"),
            ForkSpec(victims=victims[:1],
                     remove_nodes=[str(rng.choice(live_nodes))],
                     add_nodes=materialize_nodes(
                         group, 1, 100 + 10 * rnd, 100 + rnd, SLICE_LABEL),
                     note="mixed"),
        ]
        before = m.whatif_forks.value(())
        vm = engine.evaluate(pend, forks, vmapped=True)
        seq = engine.evaluate(pend, forks, vmapped=False)
        _assert_forks_equal(vm, seq)
        assert m.whatif_forks.value(()) >= before + 2 * len(forks)
        # randomized churn between rounds: bind a pod, delete a pod
        churn_seq += 1
        store.create("Pod", _pod(f"churn-{churn_seq}", cpu="1",
                                 node=f"n{churn_seq % 3}"))
        doomed = rng.choice([p for p in store.list("Pod")[0]
                             if p.spec.node_name])
        store.delete("Pod", "default", doomed.metadata.name)
        sched.schedule_cycle()


def test_victim_fork_matches_post_eviction_bindings():
    """Ported-path regression (descheduler contract at the engine level):
    a victim-mask fork's prediction equals the scheduler's actual
    post-eviction bindings."""
    clock, store, sched = _cluster(n_nodes=3)
    for i in range(3):
        store.create("Pod", _pod(f"v{i}", cpu="3", node=f"n{i}"))
    sched.schedule_cycle()
    engine = WhatIfEngine(sched)
    victims = [store.get("Pod", "default", f"v{i}") for i in range(3)]
    pend = [_pod(f"p{i}", cpu="3") for i in range(3)]
    pred = engine.evaluate_one(pend, ForkSpec(victims=victims))
    assert pred is not None and pred.unplaced == 0
    assert pred.masked_victims == 3
    for i in range(3):
        store.delete("Pod", "default", f"v{i}")
    for p in pend:
        store.create("Pod", p)
    sched.run_until_idle(backoff_wait=1.0)
    for p in pend:
        actual = store.get("Pod", "default", p.metadata.name).spec.node_name
        assert actual == pred.placements[p.uid], (p.metadata.name, actual)


def test_node_add_fork_matches_post_scale_up_bindings():
    """A node-add fork simulates with the SAME deterministic node names a
    real scale-up creates — predicted placements name the nodes the pods
    actually bind to once the nodes exist."""
    clock, store, sched = _cluster(n_nodes=1)
    store.create("Pod", _pod("filler", cpu="4", node="n0"))
    sched.schedule_cycle()
    engine = WhatIfEngine(sched)
    group = NodeGroup(metadata=v1.ObjectMeta(name="ng"), max_size=4,
                      capacity={"cpu": "4", "pods": "10"}, slice_size=2)
    adds = materialize_nodes(group, 2, 0, 0, SLICE_LABEL)
    pend = [_pod(f"p{i}", cpu="3") for i in range(2)]
    pred = engine.evaluate_one(pend, ForkSpec(add_nodes=adds))
    assert pred is not None and pred.unplaced == 0
    assert all(n in {"ng-0", "ng-1"} for n in pred.placements.values())
    # the simulation touched nothing real
    assert store.get("Node", "", "ng-0") is None
    for node in adds:
        store.create("Node", node)
    for p in pend:
        store.create("Pod", p)
    sched.run_until_idle(backoff_wait=1.0)
    for p in pend:
        actual = store.get("Pod", "default", p.metadata.name).spec.node_name
        assert actual == pred.placements[p.uid], (p.metadata.name, actual)


def test_node_remove_fork_masks_host():
    clock, store, sched = _cluster(n_nodes=2)
    sched.schedule_cycle()
    engine = WhatIfEngine(sched)
    pend = [_pod(f"p{i}", cpu="3") for i in range(2)]
    pred = engine.evaluate_one(pend, ForkSpec(remove_nodes=["n1"]))
    assert pred is not None
    # only n0 survives the fork; a 4-cpu host seats one 3-cpu pod
    assert sorted(pred.placements.values(), key=str) == [None, "n0"]
    # live state untouched: both nodes still seat pods for real
    pred2 = engine.evaluate_one(pend, ForkSpec())
    assert pred2.unplaced == 0


def test_scale_down_shaped_fork_remove_plus_displace():
    """The autoscaler's scale-down fork: remove a host AND mask its pods,
    pending = the displaced pods' clones — viable iff they re-place on the
    surviving hosts."""
    clock, store, sched = _cluster(n_nodes=3)
    store.create("Pod", _pod("d0", cpu="2", node="n2"))
    store.create("Pod", _pod("big", cpu="3", node="n0"))
    sched.schedule_cycle()
    engine = WhatIfEngine(sched)
    displaced = store.get("Pod", "default", "d0")
    clone = _pod("whatif-d0", cpu="2")
    pred = engine.evaluate_one(clone and [clone], ForkSpec(
        victims=[displaced], remove_nodes=["n2"]))
    assert pred is not None and pred.unplaced == 0
    assert pred.placements["whatif-d0"] in ("n0", "n1")


# --- refusal conditions -------------------------------------------------------


def test_engine_refuses_inflight_pipeline():
    clock, store, sched = _cluster(n_nodes=2)
    sched.schedule_cycle()
    engine = WhatIfEngine(sched)
    sched._inflight_q.append(object())
    try:
        assert engine.evaluate([_pod("p0")], [ForkSpec()]) is None
    finally:
        sched._inflight_q.clear()


def test_engine_refuses_oversize_and_empty():
    clock, store, sched = _cluster(n_nodes=2, batch_size=2)
    sched.schedule_cycle()
    engine = WhatIfEngine(sched)
    assert engine.evaluate([], [ForkSpec()]) is None
    assert engine.evaluate([_pod(f"p{i}") for i in range(3)],
                           [ForkSpec()]) is None
    assert engine.evaluate([_pod("p0")], []) is None


def test_node_add_refuses_existing_node_name():
    clock, store, sched = _cluster(n_nodes=2)
    sched.schedule_cycle()
    engine = WhatIfEngine(sched)
    clash = make_node().name("n0").capacity({"cpu": "4"}).obj()
    with pytest.raises(ValueError):
        engine.evaluate([_pod("p0")], [ForkSpec(add_nodes=[clash])])


# --- ported-path contracts ----------------------------------------------------


def test_preemption_dry_run_routes_through_whatif():
    """No remaining private fork-and-resolve copies: preemption's device
    fan-out IS the whatif module's (identity, not a parallel copy), and
    the scheduler's candidate-mask program uses it."""
    from kubernetes_tpu import preemption, scheduler
    from kubernetes_tpu.whatif import dryrun

    assert preemption.candidate_mask_device is dryrun.candidate_mask_device
    assert preemption._sweep_and_rank is dryrun.sweep_and_rank
    assert preemption.PRIORITY_LEVEL_CAP is dryrun.PRIORITY_LEVEL_CAP
    assert scheduler.candidate_mask_device is dryrun.candidate_mask_device


def test_descheduler_planner_routes_through_whatif():
    from kubernetes_tpu.descheduler import planner as planner_mod
    from kubernetes_tpu.descheduler.planner import WhatIfPlanner

    clock, store, sched = _cluster(n_nodes=2)
    p = WhatIfPlanner(sched)
    assert isinstance(p.engine, WhatIfEngine)
    # the pre-unification private fork machinery is gone
    assert not hasattr(planner_mod, "_fork_snapshot")
    assert not hasattr(planner_mod, "_MaskedEncoderView")
