"""Thread-ownership engine battery: role graph, ownership lattice,
handoff discipline, lifecycle, seeded repo regressions, and the runtime
access sanitizer that cross-checks the static report.

The seeded regressions re-inject the EXACT bug shapes this PR fixed
(the scheduler's background phase_wall write, the replication watermark,
the watch-cache stop flag, an unjoined server thread) and pin the
finding to the injected file:line — the ratchet that keeps them fixed.
"""

import ast
import os
import textwrap
import threading
import time

import pytest

from kubernetes_tpu.analysis import lockcheck
from kubernetes_tpu.analysis.core import (
    DEFAULT_SCAN_PATHS,
    ModuleInfo,
    load_project,
    project_from_sources,
    run_checks,
)
from kubernetes_tpu.analysis.registry import default_checks
from kubernetes_tpu.analysis.threads import (
    MAIN,
    ThreadAnalysis,
    thread_analysis_for,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

THREAD_CHECKS = ["thread-ownership", "handoff-discipline",
                 "thread-local-context", "daemon-lifecycle"]


def analyze(sources, checks):
    project = project_from_sources(
        {p: textwrap.dedent(s) for p, s in sources.items()})
    return run_checks(project, default_checks(checks))


def sites(findings):
    return [(f.path, f.line, f.rule) for f in findings]


def _ta(sources):
    project = project_from_sources(
        {p: textwrap.dedent(s) for p, s in sources.items()})
    return thread_analysis_for(project)


# --- role graph ---------------------------------------------------------------


ROLE_SRC = {
    "pkg/pump.py": """
    import threading

    class Pump:
        def start(self):
            self._thread = threading.Thread(target=self._drain, daemon=True)
            self._thread.start()

        def _drain(self):
            self._shared_helper()

        def _shared_helper(self):
            pass

        def run_main(self):
            self._shared_helper()

        def close(self):
            self._thread.join()
    """
}


def test_roles_propagate_through_call_graph():
    ta = _ta(ROLE_SRC)
    path = "pkg/pump.py"
    drain = ta.roles_of(path, "Pump._drain")
    assert drain and MAIN not in drain, drain
    helper = ta.roles_of(path, "Pump._shared_helper")
    assert MAIN in helper and len(helper) == 2, helper
    assert ta.roles_of(path, "Pump.run_main") == {MAIN}


# --- thread-ownership ---------------------------------------------------------


OWNERSHIP_POS = {
    "pkg/counter.py": """
    import threading

    class Counter:
        def start(self):
            self._thread = threading.Thread(target=self._drain, daemon=True)
            self._thread.start()

        def _drain(self):
            self.total = 1

        def close(self):
            self._thread.join()
            return self.total
    """
}


def test_unlocked_cross_role_field_is_flagged_on_both_sides():
    got = sites(analyze(OWNERSHIP_POS, ["thread-ownership"]))
    assert ("pkg/counter.py", 10, "unsynchronized-cross-role-write") in got
    assert ("pkg/counter.py", 14, "cross-role-read") in got
    assert len(got) == 2, got


def test_planted_unlocked_cross_role_write_is_exactly_one_finding():
    """The planted write is the ONLY unlocked conflicting site (the main-
    thread reader holds the class lock), so the check pins exactly one
    finding at the planted file:line."""
    src = {
        "pkg/gauge.py": """
        import threading

        class Gauge:
            def __init__(self):
                self._lock = threading.Lock()

            def start(self):
                self._thread = threading.Thread(
                    target=self._tick, daemon=True)
                self._thread.start()

            def _tick(self):
                self.beat = 1

            def close(self):
                self._thread.join()
                with self._lock:
                    return self.beat
        """
    }
    got = sites(analyze(src, ["thread-ownership"]))
    assert got == [("pkg/gauge.py", 14,
                    "unsynchronized-cross-role-write")], got


def test_lock_protected_cross_role_field_is_clean():
    src = {
        "pkg/counter.py": """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0

            def start(self):
                self._thread = threading.Thread(
                    target=self._drain, daemon=True)
                self._thread.start()

            def _drain(self):
                with self._lock:
                    self.total += 1

            def close(self):
                self._thread.join()
                with self._lock:
                    return self.total
        """
    }
    assert analyze(src, ["thread-ownership"]) == []


def test_cross_role_global_write_is_flagged():
    src = {
        "pkg/g.py": """
        import threading

        TOTAL = 0

        def bump():
            global TOTAL
            TOTAL = TOTAL + 1

        def fire():
            global TOTAL
            t = threading.Thread(target=bump, daemon=True)
            t.start()
            t.join()
            TOTAL = 0
        """
    }
    got = sites(analyze(src, ["thread-ownership"]))
    assert ("pkg/g.py", 8, "global-cross-role") in got
    assert ("pkg/g.py", 15, "global-cross-role") in got


def test_suppressed_finding_with_justification_is_silent():
    src = dict(OWNERSHIP_POS)
    src["pkg/counter.py"] = src["pkg/counter.py"].replace(
        "self.total = 1",
        "self.total = 1  # ktpu-analysis: ignore[thread-ownership] -- "
        "single-shot probe, reader joins first").replace(
        "return self.total",
        "return self.total  # ktpu-analysis: ignore[thread-ownership] -- "
        "single-shot probe, reader joins first")
    assert analyze(src, ["thread-ownership"]) == []


def test_suppression_of_unknown_thread_check_name_is_linted():
    src = {
        "pkg/x.py": """
        X = 1  # ktpu-analysis: ignore[thread-onwership] -- typo'd name
        """
    }
    got = sites(analyze(src, ["thread-ownership"]))
    assert ("pkg/x.py", 2, "unknown-check") in got


def test_stale_thread_suppression_is_linted():
    src = {
        "pkg/x.py": """
        X = 1  # ktpu-analysis: ignore[daemon-lifecycle] -- nothing here
        """
    }
    got = sites(analyze(src, ["daemon-lifecycle"]))
    assert ("pkg/x.py", 2, "unused") in got


# --- handoff discipline -------------------------------------------------------


HANDOFF_CLEAN = {
    "pkg/runner.py": """
    import threading

    class Result:
        pass

    class Runner:
        def kick(self):
            if self._inflight is not None:
                self._inflight.thread.join()
            rec = Result()
            def _bg():
                rec.value = 42
            rec.thread = threading.Thread(target=_bg, daemon=True)
            rec.thread.start()
            self._inflight = rec
            return rec

        def collect(self):
            rec = self._inflight
            rec.thread.join()
            rec.thread = None
            return rec.value
    """
}


def test_joined_handoff_is_clean():
    assert analyze(HANDOFF_CLEAN, THREAD_CHECKS) == []


def test_read_before_join_is_flagged_at_the_read():
    src = {
        "pkg/runner.py": HANDOFF_CLEAN["pkg/runner.py"].replace(
            """\
        def collect(self):
            rec = self._inflight
            rec.thread.join()
            rec.thread = None
            return rec.value
""",
            """\
        def collect(self):
            rec = self._inflight
            early = rec.value
            rec.thread.join()
            return early
""")
    }
    got = sites(analyze(src, ["handoff-discipline"]))
    assert got == [("pkg/runner.py", 21, "read-before-join")], got


def test_republish_without_guard_is_flagged():
    src = {
        "pkg/runner.py": HANDOFF_CLEAN["pkg/runner.py"].replace(
            """\
            if self._inflight is not None:
                self._inflight.thread.join()
""", "")
    }
    got = sites(analyze(src, ["handoff-discipline"]))
    assert got == [("pkg/runner.py", 14, "republish-while-live")], got


# --- thread-local-context -----------------------------------------------------


def test_module_level_threading_local_is_flagged():
    src = {
        "pkg/ctx.py": """
        import threading

        _ctx = threading.local()

        def put(v):
            _ctx.v = v
        """
    }
    got = sites(analyze(src, ["thread-local-context"]))
    assert got == [("pkg/ctx.py", 4, "implicit-thread-local")], got


def test_class_thread_local_escaping_the_class_is_flagged():
    src = {
        "pkg/holder.py": """
        import threading

        class Holder:
            def __init__(self):
                self._tls_blob = threading.local()

            def put(self, v):
                self._tls_blob.v = v
        """,
        "pkg/peek.py": """
        def peek(h):
            return h._tls_blob.v
        """,
    }
    got = sites(analyze(src, ["thread-local-context"]))
    assert got == [("pkg/peek.py", 3, "thread-local-escape")], got


# --- daemon-lifecycle ---------------------------------------------------------


def test_fire_and_forget_thread_is_flagged():
    src = {
        "pkg/d.py": """
        import threading

        def work():
            return 1

        def fire():
            threading.Thread(target=work, daemon=True).start()
        """
    }
    got = sites(analyze(src, ["daemon-lifecycle"]))
    assert got == [("pkg/d.py", 8, "unjoined-thread")], got


def test_stop_event_wired_to_sibling_setter_is_managed():
    src = {
        "pkg/d.py": """
        import threading

        def serve(tick):
            stop = threading.Event()

            def loop():
                while not stop.wait(0.1):
                    tick()

            threading.Thread(target=loop, daemon=True).start()

            def unwatch():
                stop.set()
            return unwatch
        """
    }
    assert analyze(src, ["daemon-lifecycle"]) == []


def test_executor_without_shutdown_is_flagged():
    src = {
        "pkg/e.py": """
        from concurrent.futures import ThreadPoolExecutor

        def build():
            return ThreadPoolExecutor(max_workers=2)
        """
    }
    got = sites(analyze(src, ["daemon-lifecycle"]))
    assert got == [("pkg/e.py", 5, "unmanaged-executor")], got


def test_executor_with_class_shutdown_is_managed():
    src = {
        "pkg/e.py": """
        from concurrent.futures import ThreadPoolExecutor

        class Owner:
            def open(self):
                self._pool = ThreadPoolExecutor(max_workers=2)

            def close(self):
                self._pool.shutdown(wait=False)
        """
    }
    assert analyze(src, ["daemon-lifecycle"]) == []


# --- the repo is clean under all four checks ---------------------------------


def _repo_project():
    return load_project(REPO_ROOT, DEFAULT_SCAN_PATHS)


def test_repo_is_clean_under_thread_checks():
    findings = run_checks(_repo_project(), default_checks(THREAD_CHECKS))
    assert findings == [], "\n".join(
        f"{f.location()} [{f.check}/{f.rule}] {f.message}" for f in findings)


def test_repo_ownership_report_reflects_the_fixes():
    """The fields this PR's burn-down fixed carry the classification the
    fix earned: the scheduler's extender pool is lock-protected, the
    Scheme registry is lock-protected, and phase_wall is main-only again
    (the background sync wall now rides the _SyncAhead record)."""
    report = thread_analysis_for(_repo_project()).ownership_report()
    sched = report["TPUScheduler"]
    assert sched["_ext_pool_obj"]["classification"] == "locked"
    assert report["Scheme"]["_kinds"]["classification"] == "locked"
    pw = sched["phase_wall"]
    assert pw["classification"] == "single-role"
    assert pw["roles"] == [MAIN]


# --- seeded repo regressions: re-inject the fixed bugs ------------------------


def _patched_repo_project(path_suffix, anchor, injected):
    project = _repo_project()
    mod = project.find(path_suffix)
    lines = mod.source.splitlines(keepends=True)
    at = next(i for i, ln in enumerate(lines) if ln.startswith(anchor))
    lines.insert(at, injected if injected.endswith("\n") else injected + "\n")
    patched = ModuleInfo(mod.path, "".join(lines))
    project.modules[project.modules.index(mod)] = patched
    return project, at + 1


def test_seeded_background_phase_wall_write_fires_thread_ownership():
    """The exact pre-fix scheduler bug: the overlapped-sync closure
    writing phase_wall (a main-thread dict) from the background thread.
    Re-injecting it makes phase_wall racy again — the injected line is
    flagged, and every finding stays inside scheduler.py."""
    project, lineno = _patched_repo_project(
        "kubernetes_tpu/scheduler.py",
        "            rec.wall = done - t_s",
        '            self.phase_wall["sync_overlap"] += done - t_s\n')
    findings = run_checks(project, default_checks(["thread-ownership"]))
    assert findings, "injected background phase_wall write went unflagged"
    assert {f.path for f in findings} == {"kubernetes_tpu/scheduler.py"}
    assert lineno in {f.line for f in findings}
    assert {f.rule for f in findings} <= {
        "unsynchronized-cross-role-write", "cross-role-read"}


def test_seeded_unlocked_watermark_write_fires_exactly_once():
    """An injected background closure bumping FollowerReplica._applied_rv
    outside _cond — every legitimate site holds the condition, so the
    ONLY finding is the injected write, at its exact line."""
    injected = (
        "    def _lag_probe(self):\n"
        "        def _bump():\n"
        "            self._applied_rv = self._applied_rv + 1\n"
        "        threading.Thread(target=_bump, daemon=True).start()\n")
    project, lineno = _patched_repo_project(
        "kubernetes_tpu/sim/replication.py",
        "    def _refresh_gauges(self):", injected)
    findings = run_checks(project, default_checks(["thread-ownership"]))
    assert [(f.path, f.line) for f in findings] == \
        [("kubernetes_tpu/sim/replication.py", lineno + 2)], sites(findings)


def test_seeded_stop_flag_read_fires_thread_ownership():
    """The exact pre-fix watch-cache bug shape: the bookmark loop polling
    a plain attribute the main thread writes (now a threading.Event).
    The injected cross-role read is flagged at its line."""
    project, lineno = _patched_repo_project(
        "kubernetes_tpu/sim/watchcache.py",
        "                self.bookmark_now()",
        "                if self._bookmark_thread is None:\n"
        "                    return\n")
    findings = run_checks(project, default_checks(["thread-ownership"]))
    assert findings, "injected cross-role stop-flag read went unflagged"
    assert {f.path for f in findings} == {"kubernetes_tpu/sim/watchcache.py"}
    assert (lineno, "cross-role-read") in {(f.line, f.rule) for f in findings}


def test_seeded_unjoined_server_thread_fires_daemon_lifecycle():
    """An injected fire-and-forget thread in APIServer — no join, no stop
    signal — is exactly one daemon-lifecycle finding at the spawn."""
    injected = (
        "    def _fire_probe(self):\n"
        "        threading.Thread(target=self._probe_loop, "
        "daemon=True).start()\n"
        "\n"
        "    def _probe_loop(self):\n"
        "        while True:\n"
        "            pass\n"
        "\n")
    project, lineno = _patched_repo_project(
        "kubernetes_tpu/apiserver/server.py",
        "    def stop(self):", injected)
    findings = run_checks(project, default_checks(["daemon-lifecycle"]))
    assert [(f.path, f.line, f.rule) for f in findings] == \
        [("kubernetes_tpu/apiserver/server.py", lineno + 1,
          "unjoined-thread")], sites(findings)


# --- CheckedLock Condition protocol -------------------------------------------


def test_condition_over_checked_rlock_keeps_monitor_stacks_exact():
    """threading.Condition probes _is_owned/_release_save/_acquire_restore
    on its lock; CheckedLock must implement them or wait() on a wrapped
    RLock under-releases and the held-stack bookkeeping drifts.  A
    reentrant wait must fully release (the notifier can acquire), then
    restore BOTH the inner lock depth and the monitor stack."""
    mon = lockcheck.activate()
    try:
        lock = lockcheck.maybe_wrap(threading.RLock(), "CondOwner.cond")
        assert isinstance(lock, lockcheck.CheckedLock)
        cond = threading.Condition(lock)
        ready = []
        observed = {}

        def waiter():
            with cond:
                with cond:  # depth 2 across the wait
                    while not ready:
                        cond.wait(timeout=5)
                    observed["inside"] = list(mon._stack())
                observed["after_inner"] = list(mon._stack())
            observed["after_outer"] = list(mon._stack())

        t = threading.Thread(target=waiter)
        t.start()
        deadline = time.time() + 5
        while time.time() < deadline:
            with cond:
                if cond._waiters:
                    ready.append(1)
                    cond.notify_all()
                    break
            time.sleep(0.01)
        t.join(timeout=5)
        assert not t.is_alive()
        assert len(observed["inside"]) == 2, observed
        assert len(observed["after_inner"]) == 1, observed
        assert observed["after_outer"] == [], observed
        assert mon._stack() == []  # main thread fully released
        assert mon.violations == [], mon.report()
    finally:
        lockcheck.deactivate()


def test_replica_condition_is_instrumented_under_a_monitor():
    """FollowerReplica constructs its condition through maybe_wrap: under
    an active monitor the replica's _cond runs on a CheckedLock, so the
    replication battery's deliver/wait_for_rv paths feed the inversion
    detector and the access sanitizer's lock attribution."""
    import tempfile

    from kubernetes_tpu.sim.replication import FollowerReplica

    lockcheck.activate()
    try:
        with tempfile.TemporaryDirectory() as td:
            rep = FollowerReplica("san-f", os.path.join(td, "f.wal"))
            assert isinstance(rep._cond._lock, lockcheck.CheckedLock)
            with rep._cond:
                assert rep._cond._lock._key in lockcheck.active_monitor(
                    )._stack()
    finally:
        lockcheck.deactivate()


# --- access sanitizer ---------------------------------------------------------


class _Plant:
    def __init__(self):
        self.lock = None
        self.level = 0


def test_sanitizer_records_unsynchronized_multi_thread_writes():
    lockcheck.activate()
    san = lockcheck.sanitize([_Plant])
    try:
        p = _Plant()
        p.lock = lockcheck.maybe_wrap(threading.Lock(), "_Plant.lock")

        def unlocked():
            p.level = 1

        def locked():
            with p.lock:
                p.level = 2

        t1 = threading.Thread(target=unlocked)
        t2 = threading.Thread(target=locked)
        t1.start(); t1.join()
        t2.start(); t2.join()
        p.level = 3  # main, unlocked: 2 unsynchronized writers (main + t1)
        assert san.needs_verify()
        assert ("_Plant", "level", 2) in san.candidates()
        # the locked write was attributed to the held _Plant.* lock and
        # never counted — only one entry reaches 2 writers
        report = {"_Plant": {
            "level": {"classification": "locked", "roles": ["main", "bg"]},
        }}
        violations = san.verify(report)
        assert len(violations) == 1 and "_Plant.level" in violations[0]
        with pytest.raises(lockcheck.OwnershipViolation):
            san.assert_consistent(report)
    finally:
        lockcheck.unsanitize()
        lockcheck.deactivate()
    # restore() really detached the recorder
    q = _Plant()
    q.level = 9
    assert san.candidates() == [("_Plant", "level", 2)]


def test_sanitizer_skips_handoff_loaned_and_unreported_fields():
    san = lockcheck.sanitize([_Plant])
    try:
        p = _Plant()

        def w():
            p.level = 1

        t = threading.Thread(target=w)
        t.start(); t.join()
        p.level = 2
        assert san.needs_verify()
        report = {"_Plant": {
            "level": {"classification": "handoff", "roles": ["main", "bg"]},
        }}
        assert san.verify(report) == []
        report["_Plant"]["level"]["classification"] = "loaned"
        assert san.verify(report) == []
        assert san.verify({}) == []  # field unknown to the static engine
    finally:
        lockcheck.unsanitize()


def test_sanitizer_single_thread_use_never_needs_verify():
    san = lockcheck.sanitize([_Plant])
    try:
        p = _Plant()
        for i in range(5):
            p.level = i
        assert not san.needs_verify()
        assert san.verify({"_Plant": {"level": {
            "classification": "single-role", "roles": ["main"]}}}) == []
    finally:
        lockcheck.unsanitize()


def test_sanitizer_distinguishes_instances():
    """One writer thread per instance is NOT a race — candidates key on a
    single instance seeing two unsynchronized writers."""
    san = lockcheck.sanitize([_Plant])
    try:
        def spin():  # each thread builds and mutates its OWN instance
            p = _Plant()
            p.level = 1

        for _ in range(2):
            t = threading.Thread(target=spin)
            t.start(); t.join()
        assert not san.needs_verify()
    finally:
        lockcheck.unsanitize()
