"""Deterministic replay: the single-writer + immutable-snapshot design's
testable counterpart to the reference's -race CI default (SURVEY §5 race
detection; hack/make-rules/test.sh:76).

The scheduler has one writer (the event-driven loop) and pure device
programs, so the same store history must produce bit-identical bindings —
a data race, iteration-order leak, or nondeterministic device reduction
would break this.
"""

import numpy as np

from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod


def _run_once(pipeline: bool):
    rng = np.random.default_rng(42)
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=16, pipeline=pipeline)
    for i in range(24):
        w = (make_node().name(f"n{i:03d}")
             .capacity({"cpu": f"{int(rng.choice([4, 8]))}", "memory": "16Gi",
                        "pods": "32"})
             .label("zone", f"z{i % 3}"))
        store.create("Node", w.obj())
    for i in range(60):
        w = (make_pod().name(f"p{i:03d}").uid(f"p{i:03d}").namespace("default")
             .label("app", f"a{i % 4}")
             .req({"cpu": "1", "memory": "1Gi"}))
        if i % 5 == 1:
            w = w.topology_spread(2, "zone", labels={"app": f"a{i % 4}"})
        if i % 5 == 3:
            w = w.pod_affinity("zone", {"app": "a0"})
        store.create("Pod", w.obj())
    while True:
        s = sched.schedule_cycle()
        if s.attempted == 0 and s.in_flight == 0:
            break
    pods, _ = store.list("Pod")
    return {p.metadata.name: p.spec.node_name for p in pods}


def test_identical_bindings_across_replays():
    a = _run_once(pipeline=False)
    b = _run_once(pipeline=False)
    assert a == b


def test_pipeline_matches_synchronous_bindings():
    """The pipelined binding cycle reorders WORK, not decisions: the same
    history must bind identically with and without overlap."""
    a = _run_once(pipeline=False)
    c = _run_once(pipeline=True)
    assert a == c


def test_pod_deleted_mid_flight_is_not_requeued():
    """A pod deleted between dispatch and bind (pipeline mode) must be
    dropped after the failed bind, not requeued forever — its DELETE event
    was consumed while it was in flight (binding-cycle error path,
    scheduler.go:676-689 + the ghost-pod guard)."""
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4, pipeline=True)
    store.create("Node", make_node().name("n0").obj())
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1m"}).obj())
    s1 = sched.schedule_cycle()       # dispatched, in flight
    assert s1.in_flight == 1
    store.delete("Pod", "default", "p")   # deleted while in flight
    s2 = sched.schedule_cycle()       # completes: assume + bind fails
    assert s2.scheduled == 0
    # queue must be empty — no ghost retries
    a, b, u = sched.queue.pending_count()
    assert (a, b, u) == (0, 0, 0)
    s3 = sched.schedule_cycle()
    assert s3.attempted == 0 and s3.in_flight == 0
    # and the cache holds no leaked assumed pod
    assert "p" not in sched.cache._pod_states
