"""Descheduler subsystem: eviction gate, what-if planner parity, policies,
controller loop, retrofitted callers, CLI, and the fragmented-cluster
acceptance scenario (ISSUE 5)."""

import json

import numpy as np
import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.api.scheme import default_scheme
from kubernetes_tpu.api.serialize import roundtrips, to_manifest
from kubernetes_tpu.cli import Kubectl
from kubernetes_tpu.controllers.disruption import sync_pdbs
from kubernetes_tpu.descheduler import (
    DRAIN_ANNOTATION,
    DeschedulerController,
    EvictionAPI,
    NodeDrainPolicy,
    SliceDefragmentation,
    SpreadViolationRepair,
    WhatIfPlanner,
)
from kubernetes_tpu.analysis import lockcheck
from kubernetes_tpu.gang import POD_GROUP_LABEL, SLICE_LABEL
from kubernetes_tpu.metrics import scheduler_metrics as m
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod


@pytest.fixture(autouse=True)
def lock_order_monitor():
    """Same contract as the chaos battery's autouse monitor: every
    descheduler test runs with runtime lock-order instrumentation, so
    EvictionAPI._lock, the store/reflector locks, and metric locks
    constructed during the test report any acquired-after inversion at
    teardown (controllers call through eviction → store → recorder →
    metrics, a four-deep lock chain the static check cannot order)."""
    mon = lockcheck.activate()
    try:
        yield mon
    finally:
        lockcheck.deactivate()
    assert not mon.violations, mon.report()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _pod(name, labels=None, node="", cpu="2", ns="default", created=None):
    w = make_pod().name(name).uid(name).namespace(ns).req({"cpu": cpu})
    for k, v_ in (labels or {}).items():
        w = w.label(k, v_)
    if node:
        w = w.node(node)
    p = w.obj()
    if created is not None:
        p.metadata.creation_timestamp = created
    return p


def _protected(store, match, allowed_now=True, name="pdb"):
    """A PDB over ``match`` whose budget is exhausted (minAvailable =
    matching count) unless ``allowed_now``."""
    pods, _ = store.list("Pod")
    n = sum(1 for p in pods
            if all(p.metadata.labels.get(k) == v for k, v in match.items()))
    pdb = v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        selector=v1.LabelSelector(match_labels=match),
        min_available=(n - 1 if allowed_now else n),
    )
    store.create("PodDisruptionBudget", pdb)
    sync_pdbs(store)
    return store.get("PodDisruptionBudget", "default", name)


# --- L0: the eviction gate ---------------------------------------------------


def test_evict_refused_when_budget_exhausted():
    store = ObjectStore()
    p = _pod("p0", {"app": "web"}, node="n0")
    store.create("Pod", p)
    pdb = _protected(store, {"app": "web"}, allowed_now=False)
    assert pdb.disruptions_allowed == 0
    gate = EvictionAPI(store)
    r = gate.evict(p, reason="test", policy="drain")
    assert not r.allowed and not r.evicted
    assert r.blocking_pdb == "default/pdb"
    assert "disruption budget" in r.reason
    assert store.get("Pod", "default", "p0") is not None
    assert m.descheduler_evictions.value(("drain", "refused")) >= 1.0


def test_evict_consumes_budget_within_one_sync_interval():
    """Two pods allowed, then the drained budget refuses the third — a
    burst inside one disruption-controller resync cannot overshoot."""
    store = ObjectStore()
    for i in range(4):
        store.create("Pod", _pod(f"p{i}", {"app": "web"}, node="n0"))
    pdb = v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="pdb", namespace="default"),
        selector=v1.LabelSelector(match_labels={"app": "web"}),
        min_available=2)
    store.create("PodDisruptionBudget", pdb)
    sync_pdbs(store)
    gate = EvictionAPI(store)
    pods, _ = store.list("Pod")
    results = [gate.evict(p, policy="drain") for p in pods]
    assert sum(1 for r in results if r.evicted) == 2
    assert sum(1 for r in results if not r.allowed) == 2
    # the budget was drained in-object, without waiting for a resync
    assert store.get("PodDisruptionBudget", "default",
                     "pdb").disruptions_allowed == 0


def test_evict_dry_run_touches_nothing():
    store = ObjectStore()
    p = _pod("p0", {"app": "web"}, node="n0")
    store.create("Pod", p)
    _protected(store, {"app": "web"}, allowed_now=True)
    gate = EvictionAPI(store)
    before = store.get("PodDisruptionBudget", "default",
                       "pdb").disruptions_allowed
    r = gate.evict(p, policy="drain", dry_run=True)
    assert r.allowed and not r.evicted
    assert store.get("Pod", "default", "p0") is not None
    assert store.get("PodDisruptionBudget", "default",
                     "pdb").disruptions_allowed == before


def test_evict_override_records_violation():
    store = ObjectStore()
    p = _pod("p0", {"app": "web"}, node="n0")
    store.create("Pod", p)
    _protected(store, {"app": "web"}, allowed_now=False)
    gate = EvictionAPI(store)
    r = gate.evict(p, policy="preemption", override_pdb=True)
    assert r.allowed and r.evicted
    assert r.blocking_pdb == "default/pdb"  # the violation is recorded
    assert store.get("Pod", "default", "p0") is None
    assert m.descheduler_evictions.value(("preemption", "overridden")) >= 1.0


def test_evict_missing_pod_is_not_an_eviction():
    """Exactly-once: a racing second eviction of the same pod reports
    'missing' and consumes no budget."""
    store = ObjectStore()
    p = _pod("p0", {"app": "web"}, node="n0")
    store.create("Pod", p)
    _protected(store, {"app": "web"}, allowed_now=True)
    gate = EvictionAPI(store)
    assert gate.evict(p, policy="drain").evicted
    budget = store.get("PodDisruptionBudget", "default",
                       "pdb").disruptions_allowed
    r = gate.evict(p, policy="drain")
    assert not r.evicted and r.reason == "pod already gone"
    assert store.get("PodDisruptionBudget", "default",
                     "pdb").disruptions_allowed == budget


def test_evict_emits_events():
    from kubernetes_tpu.client.events import EventRecorder

    store = ObjectStore()
    p = _pod("p0", {"app": "web"}, node="n0")
    store.create("Pod", p)
    _protected(store, {"app": "web"}, allowed_now=False)
    rec = EventRecorder(store, source="descheduler")
    gate = EvictionAPI(store, recorder=rec)
    gate.evict(p, reason="maintenance", policy="drain")
    reasons = [e.reason for e in rec.events_for(p)]
    assert "EvictionBlocked" in reasons
    # free the budget → the eviction lands and the Evicted event follows
    pdb = store.get("PodDisruptionBudget", "default", "pdb")
    pdb.min_available = 0
    store.update("PodDisruptionBudget", pdb)
    sync_pdbs(store)
    gate.evict(p, reason="maintenance", policy="drain")
    assert "Evicted" in [e.reason for e in rec.events_for(p)]


def test_eviction_object_scheme_roundtrip():
    scheme = default_scheme()
    ev = scheme.decode({
        "apiVersion": "policy/v1", "kind": "Eviction",
        "metadata": {"name": "p0", "namespace": "ml"},
        "deleteOptions": {"gracePeriodSeconds": 30},
    })
    assert ev.metadata.name == "p0" and ev.grace_period_seconds == 30
    assert roundtrips(ev, scheme)
    assert to_manifest(ev, scheme)["apiVersion"] == "policy/v1"


# --- L1: retrofitted callers -------------------------------------------------


def test_nodelifecycle_eviction_respects_pdb():
    """The ISSUE 5 bugfix: a not-ready node's sync evicts unprotected pods
    but can never zero out a PDB-protected workload in one pass; refused
    pods drain on LATER syncs as budget replenishes."""
    from kubernetes_tpu.controllers.nodelifecycle import (
        NodeLifecycleController,
        UNREACHABLE_TAINT,
    )

    clock = FakeClock()
    store = ObjectStore()
    store.create("Node", make_node().name("n0")
                 .capacity({"cpu": "8", "pods": "10"}).obj())
    store.create("Lease", _lease("n0", renew_time=0.0))
    for i in range(3):
        store.create("Pod", _pod(f"web-{i}", {"app": "web"}, node="n0"))
    store.create("Pod", _pod("loose", {}, node="n0"))
    # budget allows exactly ONE web disruption
    pdb = v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="pdb", namespace="default"),
        selector=v1.LabelSelector(match_labels={"app": "web"}),
        min_available=2)
    store.create("PodDisruptionBudget", pdb)
    sync_pdbs(store)
    ctrl = NodeLifecycleController(store, grace_period=1.0, clock=clock)
    clock.advance(10.0)  # lease stale
    assert ctrl.sync_once()
    node = store.get("Node", "", "n0")
    assert any(t.key == UNREACHABLE_TAINT for t in node.spec.taints)
    # the unprotected pod and exactly ONE protected pod were evicted
    assert store.get("Pod", "default", "loose") is None
    survivors = [i for i in range(3)
                 if store.get("Pod", "default", f"web-{i}") is not None]
    assert len(survivors) == 2
    # later sync with ONE budget unit replenished (a replacement came up
    # elsewhere): exactly one more survivor drains
    store.create("Pod", _pod("web-new", {"app": "web"}, node="n1"))
    sync_pdbs(store)
    ctrl.sync_once()
    left = [i for i in survivors
            if store.get("Pod", "default", f"web-{i}") is not None]
    assert len(left) == 1  # one more drained; budget still respected


def _lease(node, renew_time):
    from kubernetes_tpu.client.leaderelection import Lease

    return Lease(metadata=v1.ObjectMeta(name=node,
                                        namespace="kube-node-lease"),
                 renew_time=renew_time)


def test_preemption_victims_flow_through_gate():
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4, clock=clock, batch_wait=0)
    store.create("Node", make_node().name("n0")
                 .capacity({"cpu": "4", "pods": "10"}).obj())
    store.create("Pod", _pod("low", {"app": "low"}, cpu="3"))
    sched.run_until_idle(backoff_wait=1.0)
    assert store.get("Pod", "default", "low").spec.node_name == "n0"
    before = m.descheduler_evictions.value(("preemption", "evicted"))
    high = make_pod().name("high").uid("high").namespace("default") \
        .req({"cpu": "3"}).priority(10).obj()
    store.create("Pod", high)
    sched.run_until_idle(backoff_wait=1.0)
    assert store.get("Pod", "default", "low") is None
    assert store.get("Pod", "default", "high").spec.node_name == "n0"
    assert m.descheduler_evictions.value(("preemption", "evicted")) \
        >= before + 1.0


def test_apiserver_eviction_subresource():
    from kubernetes_tpu.apiserver import APIServer

    store = ObjectStore()
    store.create("Pod", _pod("p0", {"app": "web"}, node="n0"))
    store.create("Pod", _pod("p1", {"app": "web"}, node="n0"))
    pdb = v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="pdb", namespace="default"),
        selector=v1.LabelSelector(match_labels={"app": "web"}),
        min_available=1)
    store.create("PodDisruptionBudget", pdb)
    sync_pdbs(store)
    srv = APIServer(store).start()
    try:
        import urllib.request

        def post_eviction(name):
            body = json.dumps({
                "apiVersion": "policy/v1", "kind": "Eviction",
                "metadata": {"name": name, "namespace": "default"},
            }).encode()
            req = urllib.request.Request(
                f"{srv.url}/api/v1/namespaces/default/pods/{name}/eviction",
                data=body, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req) as resp:
                    return resp.status
            except urllib.error.HTTPError as e:
                return e.code

        assert post_eviction("p0") == 201
        assert store.get("Pod", "default", "p0") is None
        # budget exhausted now (1 healthy = minAvailable) → 429
        assert post_eviction("p1") == 429
        assert store.get("Pod", "default", "p1") is not None
        assert post_eviction("p0") == 404
    finally:
        srv.stop()


# --- L2: the what-if planner -------------------------------------------------


def _fragmented_cluster(clock, batch_size=8):
    """3 slices × 4 hosts; s0 fully occupied by PDB-protected stragglers,
    s1 half-occupied (cheapest viable defrag), s2 fully occupied by loose
    stragglers; a 4-member gang (cpu 3/host) waits unschedulable — only 2
    whole-free hosts exist cluster-wide."""
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=batch_size, clock=clock,
                         batch_wait=0)
    for i in range(12):
        store.create("Node", make_node().name(f"n{i:02d}")
                     .capacity({"cpu": "4", "pods": "10"})
                     .label(SLICE_LABEL, f"s{i // 4}").obj())
    for i in range(4):
        store.create("Pod",
                     _pod(f"prot-{i}", {"app": "prot"}, node=f"n{i:02d}"))
    store.create("Pod", _pod("str-1a", {}, node="n04"))
    store.create("Pod", _pod("str-1b", {}, node="n05"))
    for i in range(4):
        store.create("Pod",
                     _pod(f"str-2{chr(97 + i)}", {}, node=f"n{8 + i:02d}"))
    pdb = v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="prot", namespace="default"),
        selector=v1.LabelSelector(match_labels={"app": "prot"}),
        min_available=4)
    store.create("PodDisruptionBudget", pdb)
    sync_pdbs(store)
    pg = v1.PodGroup(metadata=v1.ObjectMeta(name="g", namespace="default"),
                     min_member=4, schedule_timeout_seconds=30)
    pg.metadata.creation_timestamp = 1000.0
    store.create("PodGroup", pg)
    for i in range(4):
        store.create("Pod", _pod(f"g-{i}", {POD_GROUP_LABEL: "g"}, cpu="3",
                                 created=1000.0))
    return store, sched


def _drive_to_unschedulable(store, sched, clock):
    for _ in range(6):
        sched.schedule_cycle()
        clock.advance(0.5)
    clock.advance(40.0)  # fail any Permit hold so nothing stays assumed
    sched.schedule_cycle()
    assert not any(store.get("Pod", "default", f"g-{i}").spec.node_name
                   for i in range(4))


def test_e2e_defrag_parity_and_minimal_victims():
    """THE acceptance scenario: a fragmented cluster where a waiting gang
    is Unschedulable converges — the defrag policy evicts a minimal
    victim set (never violating a PDB), the freed slice is bound by the
    gang all-or-nothing, and the dry-run planner's predicted placements
    match the scheduler's actual post-eviction bindings bit-for-bit."""
    clock = FakeClock()
    store, sched = _fragmented_cluster(clock)
    _drive_to_unschedulable(store, sched, clock)

    ctrl = DeschedulerController(store, sched,
                                 policies=[SliceDefragmentation()])
    assert ctrl.sync_once() is True
    scored = ctrl.last_plans["defrag"]
    # minimal victim set: slice s1's two stragglers, NOT the protected s0
    # single... s0 needs 4 evictions and is PDB-blocked anyway
    assert sorted(p.metadata.name for p in scored.plan.victims) == \
        ["str-1a", "str-1b"]
    assert scored.slices_freed == 1
    assert scored.replacements_found == 2  # both stragglers re-place
    assert store.get("Pod", "default", "str-1a") is None
    assert store.get("Pod", "default", "str-1b") is None
    assert m.descheduler_plans.value(("defrag", "applied")) >= 1.0

    sched.run_until_idle(backoff_wait=2.0)
    # PDB never violated: every protected pod survived
    assert all(store.get("Pod", "default", f"prot-{i}") is not None
               for i in range(4))
    # the gang bound all-or-nothing into the freed slice
    slices = set()
    for i in range(4):
        node = store.get("Pod", "default", f"g-{i}").spec.node_name
        assert node, f"g-{i} unbound"
        slices.add(store.get("Node", "", node).metadata.labels[SLICE_LABEL])
    assert slices == {"s1"}
    # parity: predicted placements == actual bindings, bit for bit
    pred = scored.prediction
    assert pred is not None and pred.unplaced == 0
    for pod in pred.pods:
        actual = store.get("Pod", "default", pod.metadata.name).spec.node_name
        assert actual == pred.placements[pod.uid], (
            pod.metadata.name, actual, pred.placements[pod.uid])
    assert store.get("PodGroup", "default", "g").phase == \
        v1.POD_GROUP_SCHEDULED


def test_dry_run_mode_scores_but_evicts_nothing():
    clock = FakeClock()
    store, sched = _fragmented_cluster(clock)
    _drive_to_unschedulable(store, sched, clock)
    pods_before = {p.metadata.name for p in store.list("Pod")[0]}
    ctrl = DeschedulerController(store, sched, dry_run=True,
                                 policies=[SliceDefragmentation()])
    assert ctrl.sync_once() is False  # nothing changed
    scored = ctrl.last_plans["defrag"]
    assert scored.prediction is not None and scored.prediction.placed == 4
    assert {p.metadata.name for p in store.list("Pod")[0]} == pods_before
    assert m.descheduler_plans.value(("defrag", "dry_run")) >= 1.0


def test_planner_masks_affinity_victims():
    """The historical WhatIfPlanner refused affinity-carrying victims
    (aff_* tables were not masked in the fork).  The whatif engine masks
    the victim's term-count contributions, so the prediction is trusted —
    and equals the scheduler's actual post-eviction bindings bit-for-bit.

    Setup: the victim on n0 carries required anti-affinity against
    color=g; n1 is nearly full.  With the victim in place the pending
    color=g pod fits NOWHERE (n0 blocked by the existing-pod anti term,
    n1 out of cpu); with the victim evicted it lands on n0.  An unmasked
    fork would mispredict "no fit"."""
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4, clock=clock, batch_wait=0)
    for i in range(2):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "4", "pods": "10"}).obj())
    vic = (make_pod().name("vic").uid("vic").namespace("default")
           .req({"cpu": "1"}).label("color", "g")
           .pod_affinity("kubernetes.io/hostname", {"color": "g"}, anti=True)
           .node("n0").obj())
    store.create("Pod", vic)
    store.create("Pod", _pod("filler", {}, node="n1", cpu="3"))
    sched.schedule_cycle()  # sync the pre-bound pods into cache/encoder
    pend = (make_pod().name("pend").uid("pend").namespace("default")
            .req({"cpu": "2"}).label("color", "g").obj())
    planner = WhatIfPlanner(sched)
    pred = planner.predict([pend], [vic])
    assert pred is not None and pred.masked_victims == 1
    assert pred.placements["pend"] == "n0"
    # now evict for real and schedule: actual binding == prediction
    gate = EvictionAPI(store)
    assert gate.evict(vic, policy="test").evicted
    store.create("Pod", pend)
    sched.run_until_idle(backoff_wait=1.0)
    assert store.get("Pod", "default", "pend").spec.node_name == \
        pred.placements["pend"]


def test_planner_does_not_disturb_live_state():
    """A predict() must not change what the real scheduler then does with
    NO evictions applied: the fork is never committed."""
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4, clock=clock, batch_wait=0)
    for i in range(2):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "4", "pods": "10"}).obj())
    vic = _pod("vic", {}, node="n0", cpu="3")
    store.create("Pod", vic)
    sched.schedule_cycle()
    planner = WhatIfPlanner(sched)
    pend = _pod("pend", {}, cpu="3")
    pred = planner.predict([pend], [vic])
    assert pred is not None
    # counterfactually the pending pod may take n0 (victim masked)…
    assert pred.placements["pend"] in ("n0", "n1")
    # …but live state still has the victim: scheduling `pend` for real
    # must land it on n1 (n0's 3 cpu are still taken)
    store.create("Pod", pend)
    sched.run_until_idle(backoff_wait=1.0)
    assert store.get("Pod", "default", "vic") is not None
    assert store.get("Pod", "default", "pend").spec.node_name == "n1"


# --- L3: policies + controller ----------------------------------------------


def test_spread_violation_repair():
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4, clock=clock, batch_wait=0)
    for i in range(4):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "8", "pods": "10"})
                     .label("topology.kubernetes.io/zone",
                            "za" if i < 2 else "zb").obj())

    def spread_pod(name, node, created):
        p = (make_pod().name(name).uid(name).namespace("default")
             .req({"cpu": "1"}).label("app", "s")
             .topology_spread(1, "topology.kubernetes.io/zone",
                              labels={"app": "s"})
             .obj())
        p.spec.node_name = node
        p.metadata.creation_timestamp = created
        return p

    # drifted: 3 matching pods in za, 0 in zb → skew 3 > maxSkew 1
    for i in range(3):
        store.create("Pod", spread_pod(f"s{i}", f"n{i % 2}", 100.0 + i))
    sched.schedule_cycle()  # snapshot the bound pods
    ctrl = DeschedulerController(store, sched,
                                 policies=[SpreadViolationRepair()])
    assert ctrl.sync_once() is True
    scored = ctrl.last_plans["spread"]
    # the youngest crowded-domain pod was evicted
    assert [p.metadata.name for p in scored.plan.victims] == ["s2"]
    assert store.get("Pod", "default", "s2") is None
    # its what-if replacement landed OUTSIDE the crowded domain
    clone_uid = scored.plan.pending[0].uid
    target = scored.prediction.placements[clone_uid]
    assert target in ("n2", "n3")


def test_spread_repair_noop_when_within_skew():
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4, clock=clock, batch_wait=0)
    for i in range(2):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "8", "pods": "10"})
                     .label("topology.kubernetes.io/zone", f"z{i}").obj())
    p = (make_pod().name("s0").uid("s0").namespace("default")
         .req({"cpu": "1"}).label("app", "s")
         .topology_spread(1, "topology.kubernetes.io/zone",
                          labels={"app": "s"}).obj())
    p.spec.node_name = "n0"
    store.create("Pod", p)
    ctrl = DeschedulerController(store, sched,
                                 policies=[SpreadViolationRepair()])
    assert ctrl.sync_once() is False
    assert store.get("Pod", "default", "s0") is not None


def test_drain_policy_cordons_and_defers_protected():
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4, clock=clock, batch_wait=0)
    node = make_node().name("n0").capacity({"cpu": "8", "pods": "10"}).obj()
    node.metadata.annotations[DRAIN_ANNOTATION] = "true"
    store.create("Node", node)
    store.create("Pod", _pod("loose", {}, node="n0"))
    store.create("Pod", _pod("web-0", {"app": "web"}, node="n0"))
    store.create("Pod", _pod("web-1", {"app": "web"}, node="n1"))
    pdb = v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="pdb", namespace="default"),
        selector=v1.LabelSelector(match_labels={"app": "web"}),
        min_available=2)
    store.create("PodDisruptionBudget", pdb)
    sync_pdbs(store)
    ctrl = DeschedulerController(store, sched, policies=[NodeDrainPolicy()])
    assert ctrl.sync_once() is True
    assert store.get("Node", "", "n0").spec.unschedulable  # cordoned
    assert store.get("Pod", "default", "loose") is None
    # the protected pod is DEFERRED (policy pre-filter), not violated
    assert store.get("Pod", "default", "web-0") is not None
    # budget replenishes → a later sync finishes the drain
    store.create("Pod", _pod("web-2", {"app": "web"}, node="n1"))
    sync_pdbs(store)
    ctrl.sync_once()
    assert store.get("Pod", "default", "web-0") is None


def test_controller_rate_limit_caps_evictions_per_sync():
    clock = FakeClock()
    store, sched = _fragmented_cluster(clock)
    _drive_to_unschedulable(store, sched, clock)
    ctrl = DeschedulerController(store, sched, max_evictions_per_sync=1,
                                 policies=[SliceDefragmentation()])
    # the cheapest plan needs 2 evictions > cap 1: nothing may be applied
    # (a partial slice eviction would disrupt without freeing anything)
    assert ctrl.sync_once() is False
    assert store.get("Pod", "default", "str-1a") is not None
    assert store.get("Pod", "default", "str-1b") is not None


def test_controller_min_interval_spaces_active_syncs():
    clock = FakeClock()
    store, sched = _fragmented_cluster(clock)
    _drive_to_unschedulable(store, sched, clock)
    ctrl = DeschedulerController(store, sched, min_interval=100.0,
                                 policies=[SliceDefragmentation()])
    assert ctrl.sync_once() is True
    # a second gang's worth of demand appears immediately — but the rate
    # limiter holds until the interval elapses
    assert ctrl.sync_once() is False
    clock.advance(101.0)
    ctrl.sync_once()  # allowed again (no demand left is fine)


def test_mid_plan_refusal_abandons_plan():
    """A victim refused mid-plan (budget raced away between scoring and
    apply) stops the plan: remaining victims stay, outcome 'abandoned'."""
    clock = FakeClock()
    store, sched = _fragmented_cluster(clock)
    _drive_to_unschedulable(store, sched, clock)
    ctrl = DeschedulerController(store, sched,
                                 policies=[SliceDefragmentation()])
    before = m.descheduler_plans.value(("defrag", "abandoned"))

    # race: after scoring, a PDB claims the s1 stragglers with zero budget.
    # Hook the verdict seam (_scored) — the round-9 vmapped group scan
    # solves all candidates in one evaluate, so per-plan score() no longer
    # runs for grouped candidates, but every verdict still passes here.
    real_scored = ctrl._scored

    def scored_then_protect(plan, prediction):
        scored = real_scored(plan, prediction)
        if scored.viable and not store.get(
                "PodDisruptionBudget", "default", "race"):
            for v_ in plan.victims:
                v_.metadata.labels["raced"] = "1"
                store.update("Pod", v_)
            pdb = v1.PodDisruptionBudget(
                metadata=v1.ObjectMeta(name="race", namespace="default"),
                selector=v1.LabelSelector(match_labels={"raced": "1"}),
                min_available=len(plan.victims))
            store.create("PodDisruptionBudget", pdb)
            sync_pdbs(store)
        return scored

    ctrl._scored = scored_then_protect
    ctrl.sync_once()
    assert m.descheduler_plans.value(("defrag", "abandoned")) == before + 1.0
    # not half-applied: both stragglers still present, cluster intact
    assert store.get("Pod", "default", "str-1a") is not None
    assert store.get("Pod", "default", "str-1b") is not None


# --- L4: CLI -----------------------------------------------------------------


def test_cli_drain_dry_run_and_pdb_block():
    store = ObjectStore()
    store.create("Node", make_node().name("n0")
                 .capacity({"cpu": "8", "pods": "10"}).obj())
    store.create("Pod", _pod("loose", {}, node="n0"))
    store.create("Pod", _pod("web-0", {"app": "web"}, node="n0"))
    _protected(store, {"app": "web"}, allowed_now=False)
    k = Kubectl(store)
    out = k.drain("n0", dry_run=True)
    assert "1 pods would evict" in out
    assert "default/web-0 (pdb default/pdb)" in out
    assert store.get("Pod", "default", "loose") is not None
    assert not store.get("Node", "", "n0").spec.unschedulable
    out = k.drain("n0")
    assert "1 pods evicted" in out and "blocked by disruption budget" in out
    assert store.get("Node", "", "n0").spec.unschedulable
    assert store.get("Pod", "default", "loose") is None
    assert store.get("Pod", "default", "web-0") is not None


def test_cli_get_slices_fragmentation_view():
    store = ObjectStore()
    for i in range(4):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "4", "pods": "10"})
                     .label(SLICE_LABEL, f"s{i // 2}").obj())
    # s0: one host half-used (stranded free cpu), s1: whole-free
    store.create("Pod", _pod("p0", {}, node="n0", cpu="2"))
    k = Kubectl(store)
    out = k.get("slices")
    lines = out.splitlines()
    assert lines[0].split() == ["NAME", "HOSTS", "FREE-HOSTS", "FREE-CHIPS",
                                "FRAGMENTATION"]
    rows = {ln.split()[0]: ln.split() for ln in lines[1:]}
    # s0: 2 hosts, 1 empty; free = 2 + 4 = 6, stranded = 2 → 33%
    assert rows["s0"] == ["s0", "2", "1", "6", "33%"]
    # s1: all free on empty hosts → 0% fragmentation
    assert rows["s1"] == ["s1", "2", "2", "8", "0%"]


def test_cli_main_drain_and_slices(capsys):
    from kubernetes_tpu import cli

    # in-process store per invocation: just verify the verbs parse + print
    rc = cli.main(["drain", "missing-node"])
    assert rc == 0
    assert "not found" in capsys.readouterr().out
    rc = cli.main(["get", "slices"])
    assert rc == 0
    assert "FRAGMENTATION" in capsys.readouterr().out


def test_drain_plan_chunks_to_eviction_budget():
    """A drain bigger than max_evictions_per_sync drains in chunks across
    syncs (drain evictions are independent) instead of never."""
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4, clock=clock, batch_wait=0)
    node = make_node().name("n0").capacity({"cpu": "32", "pods": "20"}).obj()
    node.metadata.annotations[DRAIN_ANNOTATION] = "true"
    store.create("Node", node)
    for i in range(5):
        store.create("Pod", _pod(f"p{i}", {}, node="n0", cpu="1"))
    ctrl = DeschedulerController(store, sched, max_evictions_per_sync=2,
                                 policies=[NodeDrainPolicy()])
    assert ctrl.sync_once() is True
    remaining = [i for i in range(5)
                 if store.get("Pod", "default", f"p{i}") is not None]
    assert len(remaining) == 3  # chunked to the budget, not skipped
    ctrl.sync_once()
    ctrl.sync_once()
    assert all(store.get("Pod", "default", f"p{i}") is None
               for i in range(5))


def test_drain_policy_dry_run_does_not_cordon():
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4, clock=clock, batch_wait=0)
    node = make_node().name("n0").capacity({"cpu": "8", "pods": "10"}).obj()
    node.metadata.annotations[DRAIN_ANNOTATION] = "true"
    store.create("Node", node)
    store.create("Pod", _pod("p0", {}, node="n0"))
    ctrl = DeschedulerController(store, sched, dry_run=True,
                                 policies=[NodeDrainPolicy()])
    assert ctrl.sync_once() is False
    # the preview must not cordon the node or touch the pod
    assert not store.get("Node", "", "n0").spec.unschedulable
    assert store.get("Pod", "default", "p0") is not None


def test_defrag_never_evicts_another_gangs_members():
    """A slice hosting a PLACED gang is disqualified outright: destroying
    a running gang to seat a waiting one is never a plan."""
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, clock=clock, batch_wait=0)
    for i in range(8):
        store.create("Node", make_node().name(f"n{i:02d}")
                     .capacity({"cpu": "4", "pods": "10"})
                     .label(SLICE_LABEL, f"s{i // 4}").obj())
    # gang A placed across slice s0 (bound members)
    pga = v1.PodGroup(metadata=v1.ObjectMeta(name="ga", namespace="default"),
                      min_member=4)
    pga.phase = v1.POD_GROUP_SCHEDULED
    store.create("PodGroup", pga)
    for i in range(4):
        store.create("Pod", _pod(f"ga-{i}", {POD_GROUP_LABEL: "ga"},
                                 node=f"n{i:02d}", cpu="3"))
    # slice s1 fragmented by plain stragglers
    for i in range(4):
        store.create("Pod", _pod(f"str-{i}", {}, node=f"n{4 + i:02d}"))
    # gang B waits
    pgb = v1.PodGroup(metadata=v1.ObjectMeta(name="gb", namespace="default"),
                      min_member=4, schedule_timeout_seconds=30)
    pgb.metadata.creation_timestamp = 1000.0
    store.create("PodGroup", pgb)
    for i in range(4):
        store.create("Pod", _pod(f"gb-{i}", {POD_GROUP_LABEL: "gb"},
                                 cpu="3", created=1000.0))
    for _ in range(4):
        sched.schedule_cycle()
        clock.advance(0.5)
    clock.advance(40.0)
    sched.schedule_cycle()
    ctrl = DeschedulerController(store, sched,
                                 policies=[SliceDefragmentation()])
    ctrl.sync_once()
    # gang A untouched — the only viable plan was s1's plain stragglers
    assert all(store.get("Pod", "default", f"ga-{i}") is not None
               for i in range(4))
    assert all(store.get("Pod", "default", f"str-{i}") is None
               for i in range(4))
    sched.run_until_idle(backoff_wait=2.0)
    assert all(store.get("Pod", "default", f"gb-{i}").spec.node_name
               for i in range(4))


def test_defrag_ignores_undersized_free_slice():
    """A straggler-free slice TOO SMALL to seat the gang must not satisfy
    the free-slice short-circuit — the evictable fix still applies."""
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, clock=clock, batch_wait=0)
    # slice s0: only 2 hosts (undersized, empty); slice s1: 4 fragmented
    for i in range(2):
        store.create("Node", make_node().name(f"small-{i}")
                     .capacity({"cpu": "4", "pods": "10"})
                     .label(SLICE_LABEL, "s0").obj())
    for i in range(4):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "4", "pods": "10"})
                     .label(SLICE_LABEL, "s1").obj())
        store.create("Pod", _pod(f"str-{i}", {}, node=f"n{i}"))
    pg = v1.PodGroup(metadata=v1.ObjectMeta(name="g", namespace="default"),
                     min_member=4, schedule_timeout_seconds=30)
    pg.metadata.creation_timestamp = 1000.0
    store.create("PodGroup", pg)
    for i in range(4):
        store.create("Pod", _pod(f"g-{i}", {POD_GROUP_LABEL: "g"}, cpu="3",
                                 created=1000.0))
    for _ in range(4):
        sched.schedule_cycle()
        clock.advance(0.5)
    clock.advance(40.0)
    sched.schedule_cycle()
    ctrl = DeschedulerController(store, sched,
                                 policies=[SliceDefragmentation()])
    assert ctrl.sync_once() is True  # s0 (2 hosts) must not block the plan
    assert all(store.get("Pod", "default", f"str-{i}") is None
               for i in range(4))
    sched.run_until_idle(backoff_wait=2.0)
    assert all(store.get("Pod", "default", f"g-{i}").spec.node_name
               for i in range(4))


def test_apiserver_eviction_body_name_mismatch_400():
    from kubernetes_tpu.apiserver import APIServer

    store = ObjectStore()
    store.create("Pod", _pod("p0", {}, node="n0"))
    srv = APIServer(store).start()
    try:
        import urllib.error
        import urllib.request

        body = json.dumps({
            "apiVersion": "policy/v1", "kind": "Eviction",
            "metadata": {"name": "other-pod", "namespace": "default"},
        }).encode()
        req = urllib.request.Request(
            f"{srv.url}/api/v1/namespaces/default/pods/p0/eviction",
            data=body, method="POST",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 400
        assert store.get("Pod", "default", "p0") is not None
    finally:
        srv.stop()


def test_cli_drain_over_server_uses_eviction_subresource():
    """--server drains route through the SERVER's gate (POST eviction),
    so a PDB with zero budget answers 429 and the pod survives — no
    client-local check-then-delete race."""
    from kubernetes_tpu.apiserver import APIServer, HTTPApiClient
    from kubernetes_tpu.apiserver.client import HTTPStoreFacade

    store = ObjectStore()
    store.create("Node", make_node().name("n0")
                 .capacity({"cpu": "8", "pods": "10"}).obj())
    store.create("Pod", _pod("loose", {}, node="n0"))
    store.create("Pod", _pod("web-0", {"app": "web"}, node="n0"))
    _protected(store, {"app": "web"}, allowed_now=False)
    srv = APIServer(store).start()
    try:
        facade = HTTPStoreFacade(HTTPApiClient(srv.url, max_retries=1))
        k = Kubectl(facade)
        out = k.drain("n0")
        assert "1 pods evicted" in out
        assert "disruption budget" in out
        assert store.get("Pod", "default", "loose") is None
        assert store.get("Pod", "default", "web-0") is not None
        assert store.get("Node", "", "n0").spec.unschedulable
    finally:
        srv.stop()
