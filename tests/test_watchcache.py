"""Watch cache: snapshot/ring consistency, rv-pinned pagination, 410 Gone
→ clean relist, bookmark resyncs, and the zero-store-lock contract.

Reference behaviors exercised: storage/cacher/cacher.go (lists and watch
replays served from the cache, bookmarks, too-old-resourceVersion → 410)
and the etcd3 pagination contract (every page of one LIST walk at one rv).
"""

import threading

import pytest

from kubernetes_tpu.analysis import lockcheck
from kubernetes_tpu.api.scheme import default_scheme
from kubernetes_tpu.api.serialize import to_manifest
from kubernetes_tpu.chaos import FaultSchedule
from kubernetes_tpu.chaos.flood import watch_churn_soak
from kubernetes_tpu.client.informer import Reflector
from kubernetes_tpu.metrics import scheduler_metrics as m
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.sim.watchcache import TooOldResourceVersion, WatchCache
from kubernetes_tpu.testutil import make_pod


@pytest.fixture(autouse=True)
def lock_order_monitor():
    """Cache fan-out runs under the store lock and its readers under the
    cache lock — every battery here runs with inversion detection, plus
    the access sanitizer: cache/store field writes are recorded per
    thread with held-lock attribution, and unsynchronized multi-thread
    patterns are verified against the static thread-ownership report."""
    mon = lockcheck.activate()
    san = lockcheck.sanitize([ObjectStore, WatchCache])
    try:
        yield mon
    finally:
        lockcheck.unsanitize()
        lockcheck.deactivate()
    assert not mon.violations, mon.report()
    if san.needs_verify():  # lazy: clean runs never build the report
        from kubernetes_tpu.analysis.threads import repo_ownership_report
        san.assert_consistent(repo_ownership_report())


SCHEME = default_scheme()


def _pod(i, ns="default"):
    return (make_pod().name(f"p{i:03d}").uid(f"p{i:03d}").namespace(ns)
            .req({"cpu": "1"}).creation_timestamp(100.0 + i).obj())


def _fresh_update(store, name, label_val):
    """Update through a DECODED copy — a fresh object per write, so the
    pre-state genuinely exists (the in-place-mutation caveat the informer
    documents does not apply) and rollback equality is exact."""
    cur = store.get("Pod", "default", name)
    obj = SCHEME.decode(to_manifest(cur, SCHEME))
    obj.metadata.labels["v"] = label_val
    store.update("Pod", obj)


def _names(objs):
    return [o.metadata.name for o in objs]


def _mans(objs):
    return {o.metadata.name: to_manifest(o, SCHEME) for o in objs}


# --- snapshot + list-at-rv consistency ----------------------------------------


def test_cache_mirrors_store_and_serves_reads_lock_free():
    store = ObjectStore()
    cache = WatchCache(store)
    for i in range(6):
        store.create("Pod", _pod(i))
    store.delete("Pod", "default", "p003")
    store_names = sorted(_names(store.list("Pod")[0]))
    reads0 = store.read_ops
    objs, rv = cache.list("Pod")
    assert sorted(_names(objs)) == store_names
    assert rv == store._rv
    page, prv, tok = cache.list_page("Pod", limit=100)
    assert _names(page) == sorted(_names(objs)) and tok == ""
    assert store.read_ops == reads0, "cache reads touched the store lock"


def test_list_at_rv_equals_store_list_at_that_rv():
    """The consistency oracle: capture the store's list at rv R, churn,
    then ask the cache for rv R — bit-identical manifests."""
    store = ObjectStore()
    cache = WatchCache(store)
    for i in range(5):
        store.create("Pod", _pod(i))
    _fresh_update(store, "p001", "one")
    at_rv = store.current_rv()
    captured = _mans(store.list("Pod")[0])
    # churn past the capture: adds, fresh-object updates, deletes
    store.create("Pod", _pod(7))
    _fresh_update(store, "p001", "two")
    _fresh_update(store, "p004", "x")
    store.delete("Pod", "default", "p002")
    objs, rv, tok = cache.list_page("Pod", resource_version=at_rv)
    assert rv == at_rv and tok == ""
    assert _mans(objs) == captured
    # and the live list reflects the churn
    live, _, _ = cache.list_page("Pod")
    assert _mans(live) == _mans(store.list("Pod")[0])


def test_pagination_stable_across_concurrent_writes():
    store = ObjectStore()
    cache = WatchCache(store)
    for i in range(9):
        store.create("Pod", _pod(i))
    page, rv0, tok = cache.list_page("Pod", limit=3)
    walked = _names(page)
    # interleave every mutation class between pages
    store.create("Pod", _pod(20))          # sorts after the walk window
    store.delete("Pod", "default", "p005")  # not yet visited at rv0
    _fresh_update(store, "p007", "mid-walk")
    while tok:
        page, rv, tok = cache.list_page("Pod", limit=3, continue_=tok)
        assert rv == rv0  # every page pinned to the walk's rv
        walked += _names(page)
    assert walked == [f"p{i:03d}" for i in range(9)]
    # a FRESH walk sees the post-churn world
    fresh, _, tok = cache.list_page("Pod", limit=100)
    assert "p005" not in _names(fresh) and "p020" in _names(fresh)


def test_too_old_rv_answers_410_for_list_watch_and_continue():
    store = ObjectStore()
    cache = WatchCache(store, ring_size=4)
    for i in range(3):
        store.create("Pod", _pod(i))
    _, _, tok = cache.list_page("Pod", limit=1)
    early_rv = store.current_rv()
    for _ in range(12):  # churn past 2×ring_size → compaction
        _fresh_update(store, "p000", "churn")
    assert cache.oldest_rv > 0
    with pytest.raises(TooOldResourceVersion):
        cache.list_page("Pod", resource_version=early_rv - 1)
    with pytest.raises(TooOldResourceVersion):
        cache.watch(lambda ev: None, since_rv=1)
    with pytest.raises(TooOldResourceVersion):
        cache.list_page("Pod", limit=1, continue_=tok)  # expired token
    # a fresh LIST + watch-from-its-rv recovers (the 410 contract)
    objs, rv = cache.list("Pod")
    got = []
    un = cache.watch(got.append, since_rv=rv)
    _fresh_update(store, "p001", "after")
    assert [ev.obj.metadata.name for ev in got] == ["p001"]
    un()


def test_watch_replay_has_no_gaps_or_reorders_under_concurrent_writes():
    """Watchers attach mid-churn: each must see a gapless rv-ascending
    suffix (ring replay + pending handoff + live delivery, no seams)."""
    store = ObjectStore()
    cache = WatchCache(store, ring_size=1 << 12)
    for i in range(4):
        store.create("Pod", _pod(i))
    stop = threading.Event()

    def churner():
        j = 0
        while not stop.is_set():
            _fresh_update(store, f"p{j % 4:03d}", f"c{j}")
            j += 1

    t = threading.Thread(target=churner, daemon=True)
    t.start()
    try:
        for _ in range(20):
            got = []
            since = cache.current_rv()
            un = cache.watch(got.append, since_rv=since)
            while len(got) < 5:
                pass  # the churner keeps writing
            un()
            rvs = [ev.resource_version for ev in got[:5]]
            assert rvs[0] > since
            assert rvs == sorted(set(rvs)), f"gap/reorder: {rvs}"
    finally:
        stop.set()
        t.join(5)


# --- bookmarks + reflector integration ----------------------------------------


def test_bookmark_advances_reflector_and_resume_skips_relist():
    store = ObjectStore()
    cache = WatchCache(store)
    for i in range(3):
        store.create("Pod", _pod(i))
    refl = Reflector(cache, "Pod", rewatch_on_error=True)
    refl.run()
    # pre-decode the post-resume write so the read_ops bracket below sees
    # only the CACHE's work, not this driver's store.get
    staged = SCHEME.decode(to_manifest(store.get("Pod", "default", "p001"),
                                       SCHEME))
    staged.metadata.labels["v"] = "after-resume"
    reads0 = store.read_ops
    bm0 = m.informer_relists.value(("bookmark",))
    # another kind's write advances the world PAST this reflector's last
    # event — exactly what bookmarks exist to communicate to idle watchers
    from kubernetes_tpu.testutil import make_node

    store.create("Node", make_node().name("bm-node").obj())
    before = refl.last_rv
    rv = cache.bookmark_now()
    assert rv > before
    assert refl.last_rv == rv == cache.fanned_rv()
    # cut the stream (simulate a drop): resume must come from last_rv via
    # the ring — no relist, and the bookmark-saved resync is counted
    refl._on_watch_error(ConnectionError("injected stream cut"))
    assert refl.relists == 0
    assert m.informer_relists.value(("bookmark",)) == bm0 + 1
    store.update("Pod", staged)
    assert refl.items[("default", "p001")].metadata.labels["v"] == \
        "after-resume"
    assert store.read_ops == reads0
    refl.stop()


def test_chaos_drop_through_cache_resumes_without_event_loss():
    """A chaos-dropped cache watcher resumes from its rv: the ring replays
    the very event whose fan-out cut the stream — convergence WITHOUT the
    O(objects) relist the plain store path needs."""
    fault = FaultSchedule(0, watch_drop_rate=1.0, max_faults_per_key=2)
    store = ObjectStore(fault_injector=fault)
    cache = WatchCache(store)
    for i in range(3):
        store.create("Pod", _pod(i))
    refl = Reflector(cache, "Pod", rewatch_on_error=True)
    refl.run()
    for i in range(3, 9):
        store.create("Pod", _pod(i))  # drops fire on these fan-outs
    assert fault.injected_counts().get("watch_drop", 0) >= 1
    assert len(refl.items) == 9, "dropped event lost despite ring resume"
    assert refl.relists == 0  # every recovery was a resume, not a relist
    refl.stop()


def test_reflector_paged_relist_and_410_fallback():
    store = ObjectStore()
    cache = WatchCache(store, ring_size=4)
    for i in range(9):
        store.create("Pod", _pod(i))
    paged0 = m.informer_relists.value(("paged",))
    refl = Reflector(cache, "Pod", relist_page_size=3, rewatch_on_error=True)
    refl.run()
    assert len(refl.items) == 9
    # the initial sync is paged but is NOT a relist: not counted
    assert m.informer_relists.value(("paged",)) == paged0
    # churn the ring past the reflector's rv while its stream is "down",
    # then break the stream: resume gets 410 → full (paged) relist
    refl._unwatch()
    refl._unwatch = None
    for _ in range(12):
        _fresh_update(store, "p000", "churn")
    assert refl.last_rv < cache.oldest_rv
    refl._on_watch_error(ConnectionError("stream cut while lagging"))
    assert refl.relists == 1  # the 410 forced exactly one relist
    assert m.informer_relists.value(("paged",)) == paged0 + 1
    assert refl.items[("default", "p000")].metadata.labels["v"] == "churn"
    _fresh_update(store, "p001", "live-again")
    assert refl.items[("default", "p001")].metadata.labels["v"] == \
        "live-again"
    refl.stop()


# --- HTTP: pagination, 410, paged relists over the wire -----------------------


def test_http_list_pagination_and_410(free_port_apiserver=None):
    from kubernetes_tpu.apiserver.client import HTTPApiClient
    from kubernetes_tpu.apiserver.server import APIServer

    store = ObjectStore()
    api = APIServer(store).start()
    try:
        for i in range(7):
            store.create("Pod", _pod(i))
        client = HTTPApiClient(api.url)
        walked, tok = [], None
        while True:
            page, rv, tok = client.list_page("Pod", limit=3, continue_=tok)
            walked += _names(page)
            if not tok:
                break
        assert walked == [f"p{i:03d}" for i in range(7)]
        # paged reflector over HTTP: the initial sync pages but does not
        # count as a relist; an error-driven relist pages AND counts
        paged0 = m.informer_relists.value(("paged",))
        refl = Reflector(client.for_kind("Pod"), "Pod", relist_page_size=3)
        refl.run()
        assert len(refl.items) == 7
        assert m.informer_relists.value(("paged",)) == paged0
        refl._on_watch_error(ConnectionError("forced relist"))
        assert m.informer_relists.value(("paged",)) == paged0 + 1
        refl.stop()
        # 410 on a watch from a compacted rv
        import urllib.error
        import urllib.request

        api.watch_cache.ring_size = 4
        for _ in range(12):
            _fresh_update(store, "p000", "churn")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{api.url}/api/v1/pods?watch=true&resourceVersion=1"
                f"&timeoutSeconds=1").read()
        assert ei.value.code == 410
        assert m.apiserver_rejected.value(("watch_expired",)) >= 1
        # LIST resourceVersion=0 is "serve current from cache" (the
        # client-go reflector form) — never a rollback to pre-history,
        # never 410, even after the ring compacted
        import json as _json

        with urllib.request.urlopen(
                f"{api.url}/api/v1/pods?resourceVersion=0") as r:
            body = _json.loads(r.read())
        assert len(body["items"]) == 7
    finally:
        api.stop()


# --- the churn soak (fast shape; acceptance shape is slow-marked) -------------


def test_watcher_churn_fast_shape():
    result = watch_churn_soak(n_watchers=200, n_objects=100, growth=10,
                              churn_rounds=2, resyncs=30)
    assert result["store_read_ops_delta"] == 0
    assert result["watchers_complete"] == 200
    assert result["events_per_watcher"] == result["events_expected"]
    assert result["resync_ratio"] < 3.0, result


@pytest.mark.slow
def test_thousand_watcher_soak_acceptance_shape():
    """ISSUE 11 acceptance: 1000 watchers, 10× object growth, flat resync
    cost, zero store-lock reads (tools/watch_soak.py runs this same shape
    as the CI gate)."""
    result = watch_churn_soak(n_watchers=1000, n_objects=200, growth=10,
                              churn_rounds=2, resyncs=50)
    assert result["store_read_ops_delta"] == 0
    assert result["watchers_complete"] == 1000
    assert result["resync_ratio"] < 3.0, result


# --- informer vs a lagging replication follower (ISSUE 16 satellite) ----------


def _shipped_follower(tmp_path, n_pods, ring_size=4096):
    from kubernetes_tpu.sim.replication import FollowerReplica, LogShipper
    from kubernetes_tpu.sim.wal import WriteAheadLog

    wal = WriteAheadLog(str(tmp_path / "leader.wal"), fsync_every=0)
    store = ObjectStore(wal=wal)
    ship = LogShipper(wal.path)
    f = FollowerReplica("f1", str(tmp_path / "f1.wal"), ring_size=ring_size)
    ship.attach(f)
    for i in range(n_pods):
        store.create("Pod", _pod(i))
    ship.pump_until_synced()
    return store, ship, f


def test_paged_walk_straddling_watermark_advance_stays_rv_pinned(tmp_path):
    """A paged LIST walk against a FOLLOWER cache whose replication
    watermark advances between pages: every page serves the walk's pinned
    rv — pods shipped mid-walk never leak in (the etcd3 pagination
    contract, unchanged by which replica answers)."""
    store, ship, f = _shipped_follower(tmp_path, 9)
    page1, rv, tok = f.watch_cache.list_page("Pod", limit=4)
    assert rv == f.applied_rv() and tok
    # the watermark advances mid-walk: new pods ship and apply
    for i in range(20, 24):
        store.create("Pod", _pod(i))
    ship.pump_until_synced()
    assert f.applied_rv() > rv
    walked = _names(page1)
    while tok:
        page, prv, tok = f.watch_cache.list_page("Pod", limit=4,
                                                 continue_=tok)
        assert prv == rv, "page escaped the walk's pinned rv"
        walked += _names(page)
    assert walked == [f"p{i:03d}" for i in range(9)], \
        "mid-walk shipped pods leaked into an rv-pinned walk"
    # a FRESH walk serves the advanced watermark
    objs, rv2, _ = f.watch_cache.list_page("Pod", limit=0)
    assert rv2 == f.applied_rv() and len(objs) == 13


def test_follower_shorter_ring_410_relists_without_double_delivery(
        tmp_path):
    """A reflector on a FOLLOWER whose ring is shorter than the leader's:
    falling behind the follower's horizon answers 410 → ONE fresh paged
    walk against the SAME endpoint (FailoverEndpoints must not rotate on
    410 — compaction is not a dead replica), and the relist diff delivers
    no duplicate events for objects the reflector already holds."""
    from kubernetes_tpu.client.informer import FailoverEndpoints

    store, ship, f = _shipped_follower(tmp_path, 6, ring_size=4)
    fo = FailoverEndpoints([f.watch_cache])
    seen = []
    refl = Reflector(fo, "Pod", relist_page_size=3, rewatch_on_error=True)
    refl.add_handler(
        lambda et, obj, old: seen.append(
            (et, obj.metadata.name, obj.metadata.resource_version)))
    refl.run()
    assert len(refl.items) == 6
    # churn the follower past its short ring while the stream is "down"
    refl._unwatch()
    refl._unwatch = None
    for _ in range(12):
        _fresh_update(store, "p000", "churn")
    ship.pump_until_synced()
    assert refl.last_rv < f.watch_cache.oldest_rv
    paged0 = m.informer_relists.value(("paged",))
    refl._on_watch_error(ConnectionError("stream cut while lagging"))
    assert refl.relists == 1
    assert m.informer_relists.value(("paged",)) == paged0 + 1
    assert fo.failovers == 0, "rotated on a 410 (compaction, not death)"
    assert refl.items[("default", "p000")].metadata.labels["v"] == "churn"
    # exactly-once delivery per (object, rv): the relist diffed against
    # held state instead of replaying the walked world
    assert len(seen) == len(set(seen)), seen
    # live again after the relist: shipped updates keep flowing
    _fresh_update(store, "p001", "live-again")
    ship.pump_until_synced()
    assert refl.items[("default", "p001")].metadata.labels["v"] == \
        "live-again"
    refl.stop()


def test_failover_endpoints_rotate_off_dead_replica(tmp_path):
    """The rotation half: a dead endpoint (ConnectionError on every verb)
    rotates the facade to the live follower, once, on the first failing
    call — the reflector never notices."""
    from kubernetes_tpu.client.informer import FailoverEndpoints

    class DeadEndpoint:
        def list_page(self, *a, **kw):
            raise ConnectionError("replica gone")

        list = watch = get = list_page

    store, ship, f = _shipped_follower(tmp_path, 5)
    rotated = []
    fo = FailoverEndpoints([DeadEndpoint(), f.watch_cache],
                           on_failover=lambda ep, e: rotated.append(ep))
    refl = Reflector(fo, "Pod", relist_page_size=3, rewatch_on_error=True)
    refl.run()
    assert len(refl.items) == 5
    assert fo.failovers == 1 and len(rotated) == 1
    assert fo.current is f.watch_cache
    refl.stop()
