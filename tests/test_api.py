"""API object model tests: quantities, resource vectors, selectors, builders."""

from kubernetes_tpu.api import (
    Resource,
    Toleration,
    Taint,
    compute_pod_resource_request,
    compute_pod_resource_request_non_zero,
    match_label_selector,
    match_node_selector,
    parse_quantity,
    quantity_to_int,
    quantity_to_milli,
)
from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.testutil import make_node, make_pod


def test_parse_quantity():
    assert parse_quantity("100m") == 0.1
    assert parse_quantity("1") == 1.0
    assert parse_quantity("2Gi") == 2 * 1024**3
    assert parse_quantity("1.5Gi") == 1.5 * 1024**3
    assert parse_quantity("500M") == 5e8
    assert parse_quantity("2e3") == 2000.0
    assert parse_quantity("0.5") == 0.5
    assert parse_quantity(4) == 4.0


def test_quantity_milli_ceil():
    assert quantity_to_milli("100m") == 100
    assert quantity_to_milli("1") == 1000
    assert quantity_to_milli("0.1") == 100
    # 1m of a 3-way split rounds up
    assert quantity_to_milli("0.3333") == 334  # ceil(333.3)
    assert quantity_to_int("1.5Gi") == int(1.5 * 1024**3)


def test_resource_from_resource_list():
    r = Resource.from_resource_list(
        {"cpu": "500m", "memory": "1Gi", "pods": "10", "nvidia.com/gpu": "2"}
    )
    assert r.milli_cpu == 500
    assert r.memory == 1024**3
    assert r.allowed_pod_number == 10
    assert r.scalar_resources["nvidia.com/gpu"] == 2


def test_pod_request_max_of_init_containers():
    # reference: fit.go:162-178 — max(sum(containers), each init container) + overhead
    pod = (
        make_pod()
        .name("p")
        .req({"cpu": "1", "memory": "1Gi"})
        .container_req({"cpu": "500m"})
        .init_req({"cpu": "2", "memory": "512Mi"})
        .overhead({"cpu": "100m"})
        .obj()
    )
    r = compute_pod_resource_request(pod)
    assert r.milli_cpu == 2000 + 100  # init container dominates cpu; +overhead
    assert r.memory == 1024**3  # sum of containers dominates memory


def test_nonzero_request_defaults():
    pod = make_pod().name("p").obj()  # no requests
    r = compute_pod_resource_request_non_zero(pod)
    assert r.milli_cpu == 100
    assert r.memory == 200 * 1024 * 1024


def test_label_selector():
    sel = v1.LabelSelector(
        match_labels={"app": "web"},
        match_expressions=[
            v1.LabelSelectorRequirement(key="tier", operator=v1.OP_IN, values=["fe", "be"]),
            v1.LabelSelectorRequirement(key="legacy", operator=v1.OP_DOES_NOT_EXIST),
        ],
    )
    assert match_label_selector(sel, {"app": "web", "tier": "fe"})
    assert not match_label_selector(sel, {"app": "web", "tier": "db"})
    assert not match_label_selector(sel, {"app": "web", "tier": "fe", "legacy": "y"})
    assert not match_label_selector(None, {"app": "web"})
    assert match_label_selector(v1.LabelSelector(), {"anything": "x"})


def test_node_selector_gt_lt():
    node = make_node().name("n1").label("zone", "a").label("cores", "16").obj()
    sel = v1.NodeSelector(
        node_selector_terms=[
            v1.NodeSelectorTerm(
                match_expressions=[
                    v1.NodeSelectorRequirement(key="cores", operator=v1.OP_GT, values=["8"])
                ]
            )
        ]
    )
    assert match_node_selector(sel, node)
    sel.node_selector_terms[0].match_expressions[0].values = ["32"]
    assert not match_node_selector(sel, node)
    # nil selector matches everything
    assert match_node_selector(None, node)


def test_node_selector_terms_or_and_fields():
    node = make_node().name("n1").label("zone", "a").obj()
    sel = v1.NodeSelector(
        node_selector_terms=[
            v1.NodeSelectorTerm(
                match_expressions=[
                    v1.NodeSelectorRequirement(key="zone", operator=v1.OP_IN, values=["b"])
                ]
            ),
            v1.NodeSelectorTerm(
                match_fields=[
                    v1.NodeSelectorRequirement(
                        key="metadata.name", operator=v1.OP_IN, values=["n1"]
                    )
                ]
            ),
        ]
    )
    assert match_node_selector(sel, node)  # second term matches by field


def test_tolerations():
    t_noschedule = Taint(key="k", value="v", effect="NoSchedule")
    assert Toleration(key="k", operator="Equal", value="v").tolerates(t_noschedule)
    assert Toleration(key="k", operator="Exists").tolerates(t_noschedule)
    assert Toleration(operator="Exists").tolerates(t_noschedule)  # empty key+Exists: all
    assert not Toleration(key="k", operator="Equal", value="x").tolerates(t_noschedule)
    assert not Toleration(
        key="k", operator="Equal", value="v", effect="NoExecute"
    ).tolerates(t_noschedule)


def test_from_dict_roundtrip():
    pod = v1.Pod.from_dict(
        {
            "metadata": {"name": "web-1", "namespace": "prod", "labels": {"app": "web"}},
            "spec": {
                "schedulerName": "default-scheduler",
                "priority": 10,
                "containers": [
                    {
                        "name": "c",
                        "resources": {"requests": {"cpu": "250m", "memory": "64Mi"}},
                        "ports": [{"containerPort": 80, "hostPort": 8080}],
                    }
                ],
                "nodeSelector": {"disk": "ssd"},
                "tolerations": [{"key": "gpu", "operator": "Exists", "effect": "NoSchedule"}],
                "affinity": {
                    "podAntiAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "labelSelector": {"matchLabels": {"app": "web"}},
                                "topologyKey": "kubernetes.io/hostname",
                            }
                        ]
                    }
                },
                "topologySpreadConstraints": [
                    {
                        "maxSkew": 1,
                        "topologyKey": "zone",
                        "whenUnsatisfiable": "DoNotSchedule",
                        "labelSelector": {"matchLabels": {"app": "web"}},
                    }
                ],
            },
        }
    )
    assert pod.key() == "prod/web-1"
    assert pod.spec.priority == 10
    assert pod.spec.containers[0].ports[0].host_port == 8080
    assert pod.spec.affinity.pod_anti_affinity.required[0].topology_key == "kubernetes.io/hostname"
    assert pod.spec.topology_spread_constraints[0].max_skew == 1

    node = v1.Node.from_dict(
        {
            "metadata": {"name": "n1", "labels": {"zone": "us-a"}},
            "spec": {"taints": [{"key": "dedicated", "value": "gpu", "effect": "NoSchedule"}]},
            "status": {
                "capacity": {"cpu": "32", "memory": "128Gi", "pods": "110"},
                "images": [{"names": ["nginx:1.21"], "sizeBytes": 100000000}],
            },
        }
    )
    assert node.name == "n1"
    assert node.spec.taints[0].effect == "NoSchedule"
    assert node.status.allocatable["cpu"] == "32"


def test_scheme_decode_and_validation():
    """runtime.Scheme analog: GVK dispatch, group validation, discoverability
    (api/scheme.py)."""
    import pytest

    from kubernetes_tpu.api.scheme import SchemeError, default_scheme

    s = default_scheme()
    pod = s.decode({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "p", "namespace": "default"},
        "spec": {"containers": [{"name": "c", "image": "img"}]},
    })
    assert pod.kind == "Pod" and pod.metadata.name == "p"
    dep = s.decode({
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "d"}, "spec": {"replicas": 3},
    })
    assert dep.replicas == 3
    hpa = s.decode({
        "apiVersion": "autoscaling/v2", "kind": "HorizontalPodAutoscaler",
        "metadata": {"name": "h"},
        "spec": {"scaleTargetRef": {"kind": "Deployment", "name": "d"},
                 "maxReplicas": 7},
    })
    assert hpa.max_replicas == 7
    # wrong group for the kind → rejected, like a scheme GVK miss
    with pytest.raises(SchemeError):
        s.decode({"apiVersion": "batch/v1", "kind": "Deployment",
                  "metadata": {"name": "x"}})
    with pytest.raises(SchemeError):
        s.decode({"apiVersion": "v1", "kind": "NoSuchKind"})
    assert "apps/v1:Deployment" in s.recognized()
