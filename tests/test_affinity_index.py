"""Incremental affinity-table parity (state/affinity_index.py).

Contract: after ANY sequence of assume/forget/bind/delete/node-delete churn
— including deep-pipelined in-flight batches and gang atomic withdrawal —
the incrementally maintained per-signature count tables must equal a
from-scratch rebuild from the snapshot BIT-FOR-BIT (rebuild() is the
resync/repair oracle).  Also covers the device upload (DeviceSnapshot.aff_*
mirrors the host arrays) and the hybrid host_prepare plumbing.
"""

import numpy as np
import pytest

from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod


def _snapshot_index_parity(sched):
    """Assert incremental arrays == from-scratch rebuild, bit-for-bit."""
    enc = sched.encoder
    # refresh the snapshot view the rebuild oracle reads
    changed = sched.cache.update_snapshot(sched.snapshot)
    enc.sync(sched.snapshot, changed)
    idx = enc.aff
    inc_counts = idx.aff_counts.copy()
    inc_totals = list(idx._row_total)
    inc_valid = idx.aff_valid.copy()
    inc_kind = idx.aff_kind.copy()
    inc_slot = idx.aff_slot.copy()
    idx.rebuild(sched.snapshot)
    assert np.array_equal(inc_counts, idx.aff_counts), (
        "incremental counts diverged from rebuild:\n"
        f"inc={inc_counts[inc_valid]}\nreb={idx.aff_counts[idx.aff_valid]}")
    assert inc_totals == idx._row_total
    assert np.array_equal(inc_valid, idx.aff_valid)
    assert np.array_equal(inc_kind, idx.aff_kind)
    assert np.array_equal(inc_slot, idx.aff_slot)


def _mixed_pod(rng, i):
    kind = rng.integers(0, 5)
    p = (make_pod().name(f"p{i:04d}").uid(f"p{i:04d}").namespace("default")
         .req({"cpu": "100m"}).label("color", ["green", "blue"][i % 2]))
    if kind == 0:
        p = p.pod_affinity("kubernetes.io/hostname", {"color": "green"},
                           anti=True)
    elif kind == 1:
        p = p.pod_affinity("zone", {"color": "blue"})
    elif kind == 2:
        p = p.pod_affinity("zone", {"color": "green"}, weight=2)
    elif kind == 3:
        p = p.pod_affinity("kubernetes.io/hostname", {"color": "blue"},
                           weight=5, anti=True)
    # kind 4: plain pod
    return p.obj()


@pytest.mark.parametrize("seed", [0, 1])
def test_index_parity_under_randomized_churn(seed):
    """Random create/schedule/delete/node-delete churn; after every wave the
    incremental tables equal the rebuild oracle exactly."""
    rng = np.random.default_rng(seed)
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, pipeline=True, pipeline_depth=3)
    sched.presize(32, 128)
    for i in range(16):
        store.create(
            "Node",
            make_node().name(f"n{i:03d}")
            .label("kubernetes.io/hostname", f"n{i:03d}")
            .label("zone", f"z{i % 4}")
            .capacity({"cpu": "16", "memory": "32Gi", "pods": "110"}).obj(),
        )
    created = 0
    for wave in range(6):
        for _ in range(int(rng.integers(4, 10))):
            store.create("Pod", _mixed_pod(rng, created))
            created += 1
        sched.run_until_idle(max_cycles=40)
        _snapshot_index_parity(sched)
        # delete a random subset of bound pods
        pods, _ = store.list("Pod")
        bound = [p for p in pods if p.spec.node_name]
        for p in rng.choice(bound, size=min(3, len(bound)), replace=False):
            store.delete("Pod", p.namespace, p.metadata.name)
        _snapshot_index_parity(sched)
        if wave == 3:
            # node delete mid-run: its pods' contributions must unwind
            store.delete("Node", "", "n003")
        if wave == 4:
            store.create(
                "Node",
                make_node().name("n103")
                .label("kubernetes.io/hostname", "n103")
                .label("zone", "z9")
                .capacity({"cpu": "16", "memory": "32Gi", "pods": "110"})
                .obj(),
            )
        sched.run_until_idle(max_cycles=40)
        _snapshot_index_parity(sched)


def test_index_parity_with_gang_withdrawal():
    """A gang below quorum parks at PreFilter and an expired gang rolls its
    assumes back (forget) — the index must track both directions."""
    import kubernetes_tpu.api.objects as v1

    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, pipeline=True)
    sched.presize(16, 64)
    for i in range(8):
        store.create(
            "Node",
            make_node().name(f"n{i:03d}")
            .label("kubernetes.io/hostname", f"n{i:03d}")
            .capacity({"cpu": "4", "memory": "8Gi", "pods": "110"}).obj(),
        )
    store.create("PodGroup", v1.PodGroup(
        metadata=v1.ObjectMeta(name="pg-a", namespace="default"),
        min_member=3, schedule_timeout_seconds=60))
    from kubernetes_tpu.gang import POD_GROUP_LABEL

    for i in range(3):
        store.create(
            "Pod",
            make_pod().name(f"g{i}").uid(f"g{i}").namespace("default")
            .label(POD_GROUP_LABEL, "pg-a").label("color", "green")
            .pod_affinity("kubernetes.io/hostname", {"color": "green"},
                          anti=True)
            .req({"cpu": "1"}).obj(),
        )
    sched.run_until_idle(max_cycles=30)
    _snapshot_index_parity(sched)
    pods, _ = store.list("Pod")
    assert all(p.spec.node_name for p in pods), "gang should fully place"
    # delete one member (post-bind): contributions must decrement
    store.delete("Pod", "default", "g1")
    sched.run_until_idle(max_cycles=10)
    _snapshot_index_parity(sched)


def test_device_tables_mirror_host_arrays():
    """The uploaded DeviceSnapshot.aff_* arrays equal the host mirrors after
    scatter-deferred cycles (the fused program applied the deltas)."""
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4, pipeline=False)
    sched.presize(16, 32)
    for i in range(8):
        store.create(
            "Node",
            make_node().name(f"n{i:03d}")
            .label("kubernetes.io/hostname", f"n{i:03d}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "110"}).obj(),
        )
    for i in range(6):
        store.create(
            "Pod",
            make_pod().name(f"a{i}").uid(f"a{i}").namespace("default")
            .req({"cpu": "100m"}).label("color", "green")
            .pod_affinity("kubernetes.io/hostname", {"color": "green"},
                          anti=True).obj(),
        )
    sched.run_until_idle(max_cycles=20)
    # one more REAL dispatch (a fresh pod) so the last binds' deltas sync
    # and upload — the index is maintained at dispatch-time snapshot syncs
    store.create("Pod", make_pod().name("tail").uid("tail")
                 .namespace("default").req({"cpu": "1m"}).obj())
    sched.run_until_idle(max_cycles=10)
    sched.cache.update_snapshot(sched.snapshot)
    enc = sched.encoder
    d = enc._device
    assert d is not None
    assert np.array_equal(np.asarray(d.aff_valid), enc.aff_valid)
    assert np.array_equal(np.asarray(d.aff_kind), enc.aff_kind)
    assert np.array_equal(np.asarray(d.aff_slot), enc.aff_slot)
    assert np.array_equal(np.asarray(d.aff_counts), enc.aff_counts)
    # and the index actually recorded the six bound anti pods
    assert sum(enc.aff._row_total) == 6
