"""Compiled-selector tensor programs vs the host-side oracle (labels.py)."""

import random

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.api.labels import match_label_selector, match_node_selector
from kubernetes_tpu.state.dictionary import MISSING, Dictionary
from kubernetes_tpu.state import selectors as sel
from kubernetes_tpu.testutil import make_node


def encode_labels(labels, dic, cap=8):
    keys = np.full((cap,), MISSING, dtype=np.int32)
    vals = np.full((cap,), MISSING, dtype=np.int32)
    for i, (k, v) in enumerate(labels.items()):
        keys[i] = dic.intern(k)
        vals[i] = dic.intern(v)
    return keys, vals


def random_label_selector(rng, keys, values):
    kind = rng.randrange(4)
    if kind == 0:
        return None
    s = v1.LabelSelector()
    for _ in range(rng.randrange(3)):
        s.match_labels[rng.choice(keys)] = rng.choice(values)
    for _ in range(rng.randrange(3)):
        op = rng.choice([v1.OP_IN, v1.OP_NOT_IN, v1.OP_EXISTS, v1.OP_DOES_NOT_EXIST])
        s.match_expressions.append(
            v1.LabelSelectorRequirement(
                key=rng.choice(keys),
                operator=op,
                values=[rng.choice(values) for _ in range(rng.randrange(1, 3))]
                if op in (v1.OP_IN, v1.OP_NOT_IN)
                else [],
            )
        )
    return s


def test_label_selector_matrix_vs_oracle():
    rng = random.Random(7)
    keys = ["app", "tier", "env", "team"]
    values = ["a", "b", "c", "d"]
    selectors = [random_label_selector(rng, keys, values) for _ in range(40)]
    label_sets = [
        {k: rng.choice(values) for k in rng.sample(keys, rng.randrange(len(keys) + 1))}
        for _ in range(25)
    ]
    dic = Dictionary()
    compiled = sel.compile_label_selectors(selectors, dic)
    enc = [encode_labels(ls, dic) for ls in label_sets]
    keys_arr = jnp.asarray(np.stack([e[0] for e in enc]))
    vals_arr = jnp.asarray(np.stack([e[1] for e in enc]))
    numeric = jnp.asarray(dic.numeric_table())

    # full [selectors, label_sets] matrix in one jitted program
    @jax.jit
    def matrix(keys_arr, vals_arr, numeric):
        def one_sel(i):
            return jax.vmap(
                lambda k, vv: sel.eval_label_selector(compiled, i, k, vv, numeric)
            )(keys_arr, vals_arr)

        return jax.vmap(one_sel)(jnp.arange(len(selectors)))

    got = np.asarray(matrix(keys_arr, vals_arr, numeric))
    for i, s in enumerate(selectors):
        for j, ls in enumerate(label_sets):
            want = match_label_selector(s, ls)
            assert got[i, j] == want, (i, j, s, ls)


def random_node_selector(rng, keys, values):
    kind = rng.randrange(5)
    if kind == 0:
        return None
    ns = v1.NodeSelector()
    for _ in range(rng.randrange(3)):
        term = v1.NodeSelectorTerm()
        for _ in range(rng.randrange(3)):
            op = rng.choice(
                [v1.OP_IN, v1.OP_NOT_IN, v1.OP_EXISTS, v1.OP_DOES_NOT_EXIST, v1.OP_GT, v1.OP_LT]
            )
            if op in (v1.OP_GT, v1.OP_LT):
                vals = [str(rng.randrange(20))]
                key = "num"
            else:
                vals = (
                    [rng.choice(values) for _ in range(rng.randrange(1, 3))]
                    if op in (v1.OP_IN, v1.OP_NOT_IN)
                    else []
                )
                key = rng.choice(keys)
            term.match_expressions.append(
                v1.NodeSelectorRequirement(key=key, operator=op, values=vals)
            )
        ns.node_selector_terms.append(term)
    return ns


def test_node_selector_matrix_vs_oracle():
    rng = random.Random(11)
    keys = ["zone", "disk", "arch"]
    values = ["a", "b", "ssd", "arm"]
    selectors = [random_node_selector(rng, keys, values) for _ in range(40)]
    nodes = []
    for i in range(20):
        n = make_node().name(f"n{i}").obj()
        for k in rng.sample(keys, rng.randrange(len(keys) + 1)):
            n.metadata.labels[k] = rng.choice(values)
        if rng.random() < 0.7:
            n.metadata.labels["num"] = str(rng.randrange(20))
        nodes.append(n)

    dic = Dictionary()
    compiled = sel.compile_node_selectors(selectors, dic)
    c_req_key = jnp.asarray(compiled.req_key)
    c_req_op = jnp.asarray(compiled.req_op)
    c_req_vals = jnp.asarray(compiled.req_vals)
    c_req_num = jnp.asarray(compiled.req_num)
    c_term_valid = jnp.asarray(compiled.term_valid)
    c_match_all = jnp.asarray(compiled.match_all)
    # node name as pseudo-label supports matchFields
    enc = []
    for n in nodes:
        labels = dict(n.metadata.labels)
        labels["metadata.name"] = n.metadata.name
        enc.append(encode_labels(labels, dic))
    keys_arr = jnp.asarray(np.stack([e[0] for e in enc]))
    vals_arr = jnp.asarray(np.stack([e[1] for e in enc]))
    numeric = jnp.asarray(dic.numeric_table())

    c_index = jnp.asarray(compiled.index)

    @jax.jit
    def matrix(keys_arr, vals_arr, numeric):
        def one_sel(i):
            u = c_index[i]  # dedup: batch row → unique selector row
            return jax.vmap(
                lambda k, vv: sel.eval_node_selector_arrays(
                    c_req_key[u], c_req_op[u], c_req_vals[u],
                    c_req_num[u], c_term_valid[u], c_match_all[u],
                    k, vv, numeric,
                )
            )(keys_arr, vals_arr)

        return jax.vmap(one_sel)(jnp.arange(len(selectors)))

    got = np.asarray(matrix(keys_arr, vals_arr, numeric))
    # the batched matrix evaluator must agree with the scalar path
    got2 = np.asarray(
        jax.jit(lambda k, vv: sel.node_match_matrix(compiled, k, vv, numeric=numeric))(
            keys_arr, vals_arr
        )
    )
    np.testing.assert_array_equal(got, got2)
    for i, s in enumerate(selectors):
        for j, n in enumerate(nodes):
            want = match_node_selector(s, n)
            assert got[i, j] == want, (i, j, s, n.metadata.labels)


def test_match_fields_compiles():
    dic = Dictionary()
    ns = v1.NodeSelector(
        node_selector_terms=[
            v1.NodeSelectorTerm(
                match_fields=[
                    v1.NodeSelectorRequirement(
                        key="metadata.name", operator=v1.OP_IN, values=["n1"]
                    )
                ]
            )
        ]
    )
    compiled = sel.compile_node_selectors([ns], dic)
    n1 = make_node().name("n1").obj()
    labels = {"metadata.name": "n1"}
    keys, vals = encode_labels(labels, dic)
    numeric = jnp.asarray(dic.numeric_table())
    got = sel.eval_node_selector_arrays(
        compiled.req_key[0], compiled.req_op[0], compiled.req_vals[0],
        compiled.req_num[0], compiled.term_valid[0], compiled.match_all[0],
        jnp.asarray(keys), jnp.asarray(vals), numeric,
    )
    assert bool(got) == match_node_selector(ns, n1) == True  # noqa: E712
