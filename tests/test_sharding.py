"""Node-axis mesh sharding: sharded compute must equal unsharded
(parallel/mesh.py; conftest provides 8 virtual CPU devices).

The aux host planes (volume masks, InterPodAffinity exist-anti-block and
static-score) are POPULATED and sharded in these tests — a sharded-reduction
bug hiding behind all-zero aux planes would go unseen otherwise.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from kubernetes_tpu.parallel import node_sharded_mesh, shard_snapshot
from kubernetes_tpu.parallel.mesh import shard_dynamic_state, shard_host_auxes

from tests.test_parity import (
    build_cluster,
    default_framework,
    device_pipeline,
    pending_pods,
)
from kubernetes_tpu.testutil import make_pod


def _cluster_with_affinity(rng, n_nodes):
    """build_cluster + scheduled pods carrying required anti-affinity and
    preferred affinity, so InterPodAffinity.host_prepare emits real (non-None)
    [B, N] planes."""
    cache = build_cluster(rng, n_nodes=n_nodes)
    for i in range(4):
        w = (make_pod().name(f"aff{i}").uid(f"aff{i}").namespace("default")
             .label("app", "web")
             .req({"cpu": "1", "memory": "1Gi"})
             .pod_affinity("zone", {"app": "web"}, anti=(i % 2 == 0))
             .node(f"n{int(rng.integers(n_nodes)):02d}"))
        cache.add_pod(w.obj())
    return cache


def _pipeline_with_auxes(rng, n_nodes, k):
    cache = _cluster_with_affinity(rng, n_nodes)
    pods = pending_pods(rng, k=k)
    fw, batch, snap, enc, dsnap, dyn, _ = device_pipeline(cache, pods)
    host_auxes = fw.host_prepare(batch, snap, enc)
    # the escape hatch is gone: the IPA host planes must actually be present
    assert host_auxes.get("InterPodAffinity") is not None
    return fw, batch, snap, enc, dsnap, dyn, host_auxes


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_compute_matches_unsharded():
    rng = np.random.default_rng(11)
    fw, batch, snap, enc, dsnap, dyn, host_auxes = _pipeline_with_auxes(rng, 16, 8)

    auxes = jax.jit(fw.prepare)(batch, dsnap, dyn, host_auxes)
    mask0, scores0 = fw.jit_compute(batch, dsnap, dyn, auxes)

    mesh = node_sharded_mesh(jax.devices()[:8])
    sh_snap = shard_snapshot(dsnap, mesh)
    sh_dyn = shard_dynamic_state(dyn, mesh)
    sh_aux = shard_host_auxes(host_auxes, mesh, dsnap.num_nodes)
    with mesh:
        auxes_sh = jax.jit(fw.prepare)(batch, sh_snap, sh_dyn, sh_aux)
        mask1, scores1 = jax.jit(fw.compute)(batch, sh_snap, sh_dyn, auxes_sh)

    assert np.array_equal(np.asarray(mask0), np.asarray(mask1))
    np.testing.assert_allclose(
        np.where(np.asarray(mask0), np.asarray(scores0), 0),
        np.where(np.asarray(mask1), np.asarray(scores1), 0),
        rtol=1e-5,
    )


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_greedy_assign_runs():
    rng = np.random.default_rng(12)
    fw, batch, snap, enc, dsnap, dyn, host_auxes = _pipeline_with_auxes(rng, 16, 4)
    auxes = jax.jit(fw.prepare)(batch, dsnap, dyn, host_auxes)
    res0 = fw.jit_greedy(batch, dsnap, dyn, auxes, jnp.arange(batch.size), None)

    mesh = node_sharded_mesh(jax.devices()[:8])
    sh_snap = shard_snapshot(dsnap, mesh)
    sh_dyn = shard_dynamic_state(dyn, mesh)
    sh_aux = shard_host_auxes(host_auxes, mesh, dsnap.num_nodes)
    with mesh:
        auxes_sh = jax.jit(fw.prepare)(batch, sh_snap, sh_dyn, sh_aux)
        res1 = jax.jit(fw.greedy_assign)(
            batch, sh_snap, sh_dyn, auxes_sh, jnp.arange(batch.size), None
        )
    assert np.array_equal(np.asarray(res0.node_row), np.asarray(res1.node_row))


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_auction_matches_unsharded():
    """batch_assign (the round-based auction) under the mesh: its while-loop
    argmax/min reductions over the sharded node axis are the program most
    likely to hide a sharded-reduction bug (VERDICT r3 weak #4)."""
    from kubernetes_tpu.framework.runtime import coupling_flags

    rng = np.random.default_rng(14)
    fw, batch, snap, enc, dsnap, dyn, host_auxes = _pipeline_with_auxes(rng, 16, 8)
    auxes = jax.jit(fw.prepare)(batch, dsnap, dyn, host_auxes)
    coupling = coupling_flags(batch)
    order = jnp.arange(batch.size)
    res0 = jax.jit(fw.batch_assign)(batch, dsnap, dyn, auxes, order, coupling)

    mesh = node_sharded_mesh(jax.devices()[:8])
    sh_snap = shard_snapshot(dsnap, mesh)
    sh_dyn = shard_dynamic_state(dyn, mesh)
    sh_aux = shard_host_auxes(host_auxes, mesh, dsnap.num_nodes)
    with mesh:
        auxes_sh = jax.jit(fw.prepare)(batch, sh_snap, sh_dyn, sh_aux)
        res1 = jax.jit(fw.batch_assign)(
            batch, sh_snap, sh_dyn, auxes_sh, order, coupling
        )
    assert np.array_equal(np.asarray(res0.node_row), np.asarray(res1.node_row))
    assert int((np.asarray(res0.node_row) >= 0).sum()) >= 1


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_assignment_parity_at_5k_nodes():
    """5000-node smoke over the 8-device mesh: full greedy assignment, real
    aux planes, sharded == unsharded bindings.  A scale where a sharded
    cross-node reduction bug (row max/min, domain scatter-add, argmax over
    the node axis) cannot hide (VERDICT r2 weak #4)."""
    rng = np.random.default_rng(13)
    cache = build_cluster(rng, n_nodes=5000)
    for i in range(8):
        w = (make_pod().name(f"aff{i}").uid(f"aff{i}").namespace("default")
             .label("app", "web")
             .req({"cpu": "1", "memory": "1Gi"})
             .pod_affinity("zone", {"app": "web"}, anti=(i % 2 == 0))
             .node(f"n{int(rng.integers(5000)):02d}"))
        cache.add_pod(w.obj())
    pods = pending_pods(rng, k=16)
    fw, batch, snap, enc, dsnap, dyn, _ = device_pipeline(cache, pods)
    host_auxes = fw.host_prepare(batch, snap, enc)
    assert host_auxes.get("InterPodAffinity") is not None
    assert dsnap.num_nodes % 8 == 0  # tier divides the mesh

    auxes = jax.jit(fw.prepare)(batch, dsnap, dyn, host_auxes)
    res0 = fw.jit_greedy(batch, dsnap, dyn, auxes, jnp.arange(batch.size), None)

    mesh = node_sharded_mesh(jax.devices()[:8])
    sh_snap = shard_snapshot(dsnap, mesh)
    sh_dyn = shard_dynamic_state(dyn, mesh)
    sh_aux = shard_host_auxes(host_auxes, mesh, dsnap.num_nodes)
    with mesh:
        auxes_sh = jax.jit(fw.prepare)(batch, sh_snap, sh_dyn, sh_aux)
        res1 = jax.jit(fw.greedy_assign)(
            batch, sh_snap, sh_dyn, auxes_sh, jnp.arange(batch.size), None
        )
    rows0 = np.asarray(res0.node_row)
    rows1 = np.asarray(res1.node_row)
    assert np.array_equal(rows0, rows1)
    # the anti-affinity-to-db pods are legitimately unschedulable (all 3
    # zones hold db pods); everything else must land at 5k nodes
    assert (rows0 >= 0).sum() >= len(pods) - 2
    assert (rows0 >= 0).sum() >= 1
