"""Node-axis mesh sharding: sharded compute must equal unsharded
(parallel/mesh.py; conftest provides 8 virtual CPU devices)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from kubernetes_tpu.parallel import node_sharded_mesh, shard_snapshot
from kubernetes_tpu.parallel.mesh import shard_dynamic_state

from tests.test_parity import build_cluster, default_framework, device_pipeline, pending_pods


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_compute_matches_unsharded():
    rng = np.random.default_rng(11)
    cache = build_cluster(rng, n_nodes=16)
    pods = pending_pods(rng, k=8)
    fw, batch, snap, enc, dsnap, dyn, _ = device_pipeline(cache, pods)

    # host_auxes=None on BOTH paths so the planes being compared are identical
    auxes = jax.jit(fw.prepare)(batch, dsnap, dyn, None)
    mask0, scores0 = fw.jit_compute(batch, dsnap, dyn, auxes)

    mesh = node_sharded_mesh(jax.devices()[:8])
    sh_snap = shard_snapshot(dsnap, mesh)
    sh_dyn = shard_dynamic_state(dyn, mesh)
    with mesh:
        auxes_sh = jax.jit(fw.prepare)(batch, sh_snap, sh_dyn, None)
        mask1, scores1 = jax.jit(fw.compute)(batch, sh_snap, sh_dyn, auxes_sh)

    # aux host planes (volume masks, IPA static) default to zeros without
    # host_prepare in both paths, so results must agree exactly
    assert np.array_equal(np.asarray(mask0), np.asarray(mask1))
    np.testing.assert_allclose(
        np.where(np.asarray(mask0), np.asarray(scores0), 0),
        np.where(np.asarray(mask1), np.asarray(scores1), 0),
        rtol=1e-5,
    )


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_greedy_assign_runs():
    rng = np.random.default_rng(12)
    cache = build_cluster(rng, n_nodes=16)
    pods = pending_pods(rng, k=4)
    fw, batch, snap, enc, dsnap, dyn, _ = device_pipeline(cache, pods)
    auxes = jax.jit(fw.prepare)(batch, dsnap, dyn, None)
    res0 = fw.jit_greedy(batch, dsnap, dyn, auxes, jnp.arange(batch.size), None)

    mesh = node_sharded_mesh(jax.devices()[:8])
    sh_snap = shard_snapshot(dsnap, mesh)
    sh_dyn = shard_dynamic_state(dyn, mesh)
    with mesh:
        auxes_sh = jax.jit(fw.prepare)(batch, sh_snap, sh_dyn, None)
        res1 = jax.jit(fw.greedy_assign)(
            batch, sh_snap, sh_dyn, auxes_sh, jnp.arange(batch.size), None
        )
    assert np.array_equal(np.asarray(res0.node_row), np.asarray(res1.node_row))
