"""Chaos harness: fault injection across store/apiserver/client, end-to-end
retry & degradation, and convergence-under-failure.

Reference behaviors exercised: client-go's Retry-After-honoring transport
(rest/request.go:927), reflector relist-on-watch-error (reflector.go:312),
leader-election renewal-failure → release → reacquire
(leaderelection.go:269-287), and the scheduler's failure handler routing
errors into pod backoff instead of dropping (schedule_one.go:921).  The
circuit breaker is this repo's degradation policy on top of the reference's
``ignorable`` extender flag.
"""

import io
import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.apiserver import APIServer, HTTPApiClient
from kubernetes_tpu.chaos import (
    FaultSchedule,
    InjectedConflict,
    RetryingStore,
    TransientApiError,
    steal_lease,
)
from kubernetes_tpu.client.informer import InformerFactory, Reflector
from kubernetes_tpu.client.leaderelection import LeaderElector, LeaseLock
from kubernetes_tpu.extender import (
    CIRCUIT_CLOSED,
    CIRCUIT_OPEN,
    CircuitBreaker,
    ExtenderConfig,
    HTTPExtender,
    TPUScoreExtenderServer,
)
from kubernetes_tpu.analysis import lockcheck
from kubernetes_tpu.metrics import default_registry
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod


@pytest.fixture(autouse=True)
def lock_order_monitor():
    """Every chaos test runs under the runtime lock-order monitor: stores,
    reflectors, and metric locks constructed during the test are
    instrumented (analysis/lockcheck.maybe_wrap), and any lock-order
    inversion observed across the test's threads fails it at teardown —
    the project's stand-in for running this battery under the Go race
    detector.  The access sanitizer rides the same fixture: every store /
    watch-cache field write is attributed to its thread + held locks, and
    any multi-thread unsynchronized pattern is checked against the static
    thread-ownership report (static says safe, runtime proves it)."""
    from kubernetes_tpu.sim.watchcache import WatchCache

    mon = lockcheck.activate()
    san = lockcheck.sanitize([ObjectStore, WatchCache])
    try:
        yield mon
    finally:
        lockcheck.unsanitize()
        lockcheck.deactivate()
    assert not mon.violations, mon.report()
    if san.needs_verify():  # lazy: clean runs never build the report
        from kubernetes_tpu.analysis.threads import repo_ownership_report
        san.assert_consistent(repo_ownership_report())


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _no_sleep(_seconds):
    pass


# --- gang scheduling under faults ---------------------------------------------


def test_gang_scheduling_converges_under_watch_drops_and_429_storm():
    """Gang all-or-nothing survives the chaos battery: under watch drops
    and a 429/conflict write storm, every gang binds ALL of its members,
    each member exactly once (no double bind), with no partial gang left
    behind — retries and Permit-timeout requeues may happen in between,
    but the end state converges."""
    from kubernetes_tpu.api import objects as v1
    from kubernetes_tpu.gang import POD_GROUP_LABEL
    from kubernetes_tpu.scheduler import TPUScheduler

    fault = FaultSchedule(
        13, watch_drop_rate=0.15, write_429_rate=0.35, write_500_rate=0.1,
        conflict_rate=0.1, retry_after=0.0, max_faults_per_key=3,
    )
    raw = ObjectStore(fault_injector=fault)
    store = RetryingStore(raw, sleep=_no_sleep)
    # exactly-once probe: count unbound→bound transitions per pod uid on
    # the RAW store (below the retry layer)
    bind_counts = {}
    bound_seen = set()

    def on_ev(ev):
        if ev.kind != "Pod" or not ev.obj.spec.node_name:
            return
        if ev.obj.uid not in bound_seen:
            bound_seen.add(ev.obj.uid)
            bind_counts[ev.obj.uid] = bind_counts.get(ev.obj.uid, 0) + 1

    raw.watch(on_ev)
    sched = TPUScheduler(store, batch_size=4, pod_initial_backoff=0.01,
                         pod_max_backoff=0.05, batch_wait=0)
    for i in range(8):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "4", "pods": "10"}).obj())
    for g in ("ga", "gb"):
        pg = v1.PodGroup(
            metadata=v1.ObjectMeta(name=g, namespace="default"),
            min_member=4, schedule_timeout_seconds=2,
        )
        store.create("PodGroup", pg)
        for i in range(4):
            store.create("Pod", make_pod().name(f"{g}-{i}").uid(f"{g}-{i}")
                         .namespace("default").label(POD_GROUP_LABEL, g)
                         .req({"cpu": "3"}).obj())
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        s = sched.run_until_idle(max_cycles=50, backoff_wait=1.0)
        done = sum(
            1 for g in ("ga", "gb") for i in range(4)
            if raw.get("Pod", "default", f"{g}-{i}").spec.node_name
        )
        if done == 8 and s.waiting == 0:
            break
        time.sleep(0.02)
    for g in ("ga", "gb"):
        members_bound = [
            bool(raw.get("Pod", "default", f"{g}-{i}").spec.node_name)
            for i in range(4)
        ]
        assert all(members_bound), (g, members_bound)  # all-or-none: ALL
        assert raw.get("PodGroup", "default", g).phase == \
            v1.POD_GROUP_SCHEDULED
    assert all(c == 1 for c in bind_counts.values()), bind_counts
    assert len(bind_counts) == 8
    injected = fault.injected_counts()
    assert sum(injected.values()) > 0  # the storm actually fired


# --- FaultSchedule ------------------------------------------------------------


def test_fault_schedule_deterministic_across_instances():
    """Same seed → identical fault decisions, independent of wall clock."""
    def probe(schedule):
        hits = []
        for i in range(50):
            try:
                schedule.write_fault("create", "Pod", f"p{i}")
                hits.append(None)
            except TransientApiError as e:
                hits.append(e.code)
            except InjectedConflict:
                hits.append(409)
        return hits

    kw = dict(write_429_rate=0.2, write_500_rate=0.1, conflict_rate=0.1)
    a, b = FaultSchedule(42, **kw), FaultSchedule(42, **kw)
    assert probe(a) == probe(b)
    assert a.injected_counts() == b.injected_counts()
    assert sum(a.injected_counts().values()) > 0  # rates actually fire


def test_fault_schedule_bounds_faults_per_key():
    """A bounded-retry client must always converge: no key faults forever."""
    f = FaultSchedule(1, write_429_rate=1.0, max_faults_per_key=3)
    seen = 0
    for _ in range(10):
        try:
            f.write_fault("update", "Pod", "hot")
        except TransientApiError:
            seen += 1
    assert seen == 3  # capped, then the key is left alone


def test_fault_schedule_exempt_kinds():
    f = FaultSchedule(1, write_429_rate=1.0)
    f.write_fault("create", "Event", "e1")  # Event exempt by default: no raise


def test_retrying_store_absorbs_faults_and_counts_retries():
    f = FaultSchedule(9, write_429_rate=0.5, write_500_rate=0.2,
                      conflict_rate=0.2, max_faults_per_key=2)
    raw = ObjectStore(fault_injector=f)
    store = RetryingStore(raw, sleep=_no_sleep)
    for i in range(30):
        store.create("Pod", make_pod().name(f"p{i}").uid(f"p{i}")
                     .namespace("default").req({"cpu": "1"}).obj())
    for i in range(30):
        store.bind_pod("default", f"p{i}", "n0")
    pods, _ = raw.list("Pod")
    assert len(pods) == 30 and all(p.spec.node_name == "n0" for p in pods)
    injected = f.injected_counts()
    write_faults = sum(v for k, v in injected.items()
                      if k.startswith("write_") or k == "conflict")
    assert write_faults > 0
    # each injected fault cost exactly one resend (faults are pre-mutation)
    assert store.retries == write_faults


def test_retrying_store_gives_up_past_max_retries():
    f = FaultSchedule(1, write_429_rate=1.0, max_faults_per_key=100)
    store = RetryingStore(ObjectStore(fault_injector=f), max_retries=2,
                          sleep=_no_sleep)
    with pytest.raises(TransientApiError):
        store.create("Pod", make_pod().name("p").uid("p")
                     .namespace("default").req({"cpu": "1"}).obj())


# --- informer relist ----------------------------------------------------------


def test_informer_relists_on_in_process_watch_drop():
    """A dropped watch stream costs a relist, never correctness."""
    f = FaultSchedule(5, watch_drop_rate=1.0, max_faults_per_key=100)
    store = ObjectStore(fault_injector=f)
    factory = InformerFactory(store)
    inf = factory.informer("Node")
    added = []
    inf.add_event_handler(on_add=lambda o: added.append(o.metadata.name))
    factory.start()
    for i in range(6):
        store.create("Node", make_node().name(f"n{i}").obj())
    assert {o.metadata.name for o in inf.list()} == {f"n{i}" for i in range(6)}
    # every event's stream was cut, so every node arrived via relist-diff
    assert inf.reflector.relists >= 6
    assert sorted(added) == sorted(f"n{i}" for i in range(6))
    assert default_registry.get("informer_relists_total").value(("Node",)) > 0
    factory.stop()


def test_watch_drop_callback_survives_raising_handler():
    """The deferred drop notification must reach the cut watcher even when
    another watcher's handler raises mid-fan-out: the stream is cut under
    the store lock, so losing the callback would strand the reflector
    unsubscribed and never-relisting."""
    f = FaultSchedule(5, watch_drop_rate=1.0, max_faults_per_key=100)
    store = ObjectStore(fault_injector=f)
    dropped = []
    store.watch(lambda ev: None, on_error=lambda e: dropped.append(e))

    def boom(ev):
        raise RuntimeError("handler bug")

    store.watch(boom)  # plain watcher: never cut, raises on delivery
    with pytest.raises(RuntimeError):
        store.create("Node", make_node().name("dw0").obj())
    assert len(dropped) == 1  # notified despite the raising handler


def test_watch_drop_one_raising_callback_does_not_strand_others():
    """When one event cuts TWO resumable watchers, a drop callback that
    raises must not prevent the other watcher's notification."""
    f = FaultSchedule(5, watch_drop_rate=1.0, max_faults_per_key=100)
    store = ObjectStore(fault_injector=f)
    got = []

    def bad_recovery(exc):
        raise RuntimeError("recovery bug")

    store.watch(lambda ev: None, on_error=bad_recovery)
    store.watch(lambda ev: None, on_error=lambda e: got.append(e))
    with pytest.raises(RuntimeError):
        store.create("Node", make_node().name("dw1").obj())
    assert len(got) == 1  # second watcher notified despite the first's bug


def test_reentrant_write_drains_drop_callbacks_outside_lock():
    """A watcher callback writing back into the store (same thread, RLock
    reentry) must not drain drop callbacks while the outer write still
    holds the store lock: the deferred notifications run once, at the
    outermost frame, after full release."""
    f = FaultSchedule(5, watch_drop_rate=1.0, max_faults_per_key=100)
    store = ObjectStore(fault_injector=f)
    lock_free_at_drop = []

    def probe_lock_from_other_thread() -> bool:
        # RLock.acquire succeeds from the OWNING thread even while held,
        # so probe from a second thread: acquirable there ⇔ fully released
        result = []

        def probe():
            ok = store._lock.acquire(blocking=False)
            if ok:
                store._lock.release()
            result.append(ok)

        t = threading.Thread(target=probe)
        t.start()
        t.join()
        return result == [True]

    def chained_writer(ev):
        # reentrant write from inside the fan-out (under the store lock)
        if ev.kind == "Node" and store.get("Pod", "default", "chained") is None:
            store.create("Pod", make_pod().name("chained")
                         .namespace("default").obj())

    store.watch(chained_writer)
    store.watch(lambda ev: None,
                on_error=lambda e: lock_free_at_drop.append(
                    probe_lock_from_other_thread()))
    store.create("Node", make_node().name("outer").obj())
    assert lock_free_at_drop == [True]


def test_reflector_signature_probe_no_double_subscribe():
    """Capability detection is by inspect.signature, not TypeError probing:
    a watch that raises TypeError AFTER registering must not end up
    subscribed twice (ADVICE round 5)."""
    class BareStore(ObjectStore):
        # no on_bookmark/on_error/var-kwargs: the probe must call watch
        # WITHOUT stream kwargs, exactly once
        def watch(self, handler, since_rv=0):
            self.calls = getattr(self, "calls", 0) + 1
            return super().watch(handler, since_rv=since_rv)

    store = BareStore()
    store.create("Node", make_node().name("a").obj())
    refl = Reflector(store, "Node")
    refl.run()
    assert store.calls == 1
    assert ("", "a") in refl.items

    class ExplodingStore(ObjectStore):
        # accepts the kwarg, registers, THEN raises TypeError — the old
        # TypeError-catch retry would re-subscribe and double every event
        def watch(self, handler, since_rv=0, on_bookmark=None, on_error=None):
            super().watch(handler, since_rv=since_rv)
            raise TypeError("internal bug, not a signature mismatch")

    store2 = ExplodingStore()
    refl2 = Reflector(store2, "Node")
    with pytest.raises(TypeError):
        refl2.run()
    assert len(store2._watchers) == 1  # registered once, not twice


def test_informer_relists_over_http_watch_drop():
    """Server-side stream cut (in-band ERROR event) → client relist."""
    f = FaultSchedule(3, watch_drop_rate=1.0, max_faults_per_key=1)
    store = ObjectStore()
    srv = APIServer(store, fault_injector=f).start()
    try:
        store.create("Pod", make_pod().name("a").uid("a")
                     .namespace("default").req({"cpu": "1"}).obj())
        client = HTTPApiClient(srv.url)
        refl = Reflector(client.for_kind("Pod"), "Pod",
                         relist_backoff_initial=0.01)
        refl.run()
        assert ("default", "a") in refl.items
        # this event's stream gets cut server-side; relist must recover it
        store.create("Pod", make_pod().name("b").uid("b")
                     .namespace("default").req({"cpu": "1"}).obj())
        deadline = time.monotonic() + 10
        while ("default", "b") not in refl.items and time.monotonic() < deadline:
            time.sleep(0.02)
        assert ("default", "b") in refl.items
        assert refl.relists >= 1
        refl.stop()
    finally:
        srv.stop()


# --- HTTP client retry / apiserver shedding -----------------------------------


def test_apiserver_sheds_with_retry_after_and_client_retries():
    f = FaultSchedule(2, write_429_rate=1.0, retry_after=0.01,
                      max_faults_per_key=2)
    store = ObjectStore()
    srv = APIServer(store, fault_injector=f).start()
    try:
        client = HTTPApiClient(srv.url, max_retries=4, retry_backoff=0.01)
        reply = client.create("Pod", make_pod().name("p").uid("p")
                              .namespace("default").req({"cpu": "1"}).obj())
        assert reply["metadata"]["name"] == "p"
        assert store.get("Pod", "default", "p") is not None
        assert f.injected_counts().get("http_429") == 2  # shed twice, then served
    finally:
        srv.stop()


def test_apiserver_shed_carries_retry_after_header():
    f = FaultSchedule(2, write_429_rate=1.0, retry_after=0.25,
                      max_faults_per_key=1)
    store = ObjectStore()
    srv = APIServer(store, fault_injector=f).start()
    try:
        body = json.dumps({"apiVersion": "v1", "kind": "Pod",
                           "metadata": {"name": "x"}}).encode()
        req = urllib.request.Request(
            f"{srv.url}/api/v1/namespaces/default/pods", data=body,
            method="POST", headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 429
        assert float(ei.value.headers["Retry-After"]) == pytest.approx(0.25)
    finally:
        srv.stop()


def test_http_client_surfaces_non_retryable_errors_unchanged():
    store = ObjectStore()
    srv = APIServer(store).start()
    try:
        client = HTTPApiClient(srv.url)
        assert client.get("Pod", "default", "missing") is None  # 404 → None
    finally:
        srv.stop()


# --- circuit breaker ----------------------------------------------------------


def test_circuit_breaker_opens_half_opens_and_recovers():
    clock = FakeClock()
    br = CircuitBreaker(failure_threshold=3, reset_seconds=10, clock=clock)
    assert br.allow() and br.state == CIRCUIT_CLOSED
    for _ in range(3):
        br.failure()
    assert br.state == CIRCUIT_OPEN
    assert not br.allow()  # open: calls refused
    clock.advance(10.1)
    assert br.allow()  # half-open: exactly one probe
    assert not br.allow()  # ...and only one
    br.failure()  # probe failed → re-open, timer restarts
    assert br.state == CIRCUIT_OPEN and not br.allow()
    clock.advance(10.1)
    assert br.allow()
    br.success()  # probe succeeded → closed
    assert br.state == CIRCUIT_CLOSED and br.allow()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_ignorable_extender_outage_skipped_then_recovers():
    """Acceptance: an ignorable extender that fails 3× is skipped (cycle
    proceeds, pods schedule) and recovers via the half-open probe."""
    from kubernetes_tpu.scheduler import TPUScheduler

    port = _free_port()
    clock = FakeClock()

    def steer_to_1(pod_dict, names):
        return [n for n in names if n.endswith("1")], {n: 0 for n in names}

    ext = HTTPExtender(ExtenderConfig(
        url_prefix=f"http://127.0.0.1:{port}", filter_verb="filter",
        node_cache_capable=True, ignorable=True, http_timeout=0.5,
        failure_threshold=3, circuit_reset_seconds=5.0,
    ), clock=clock)
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4, extenders=[ext])
    store.create("Node", make_node().name("n0").obj())
    store.create("Node", make_node().name("n1").obj())
    # phase 1: extender hard down (connection refused) — 3 pods each fail a
    # callout (ignorable → skipped), all still schedule, circuit opens
    for i in range(3):
        store.create("Pod", make_pod().name(f"down{i}").uid(f"down{i}")
                     .namespace("default").req({"cpu": "1"}).obj())
    stats = sched.run_until_idle()
    assert stats.scheduled == 3  # the cycle proceeded without the extender
    assert ext.breaker.state == CIRCUIT_OPEN
    gauge = default_registry.get("extender_circuit_state")
    assert gauge.value((ext.cfg.url_prefix,)) == CIRCUIT_OPEN
    # phase 2: while OPEN, callouts are skipped outright (pass-through);
    # the pod schedules without steering
    store.create("Pod", make_pod().name("skip").uid("skip")
                 .namespace("default").req({"cpu": "1"}).obj())
    assert sched.run_until_idle().scheduled == 1
    assert ext.breaker.state == CIRCUIT_OPEN
    # phase 3: extender back up + reset window elapsed → half-open probe
    # succeeds, circuit closes, steering applies again
    srv = TPUScoreExtenderServer(steer_to_1, port=port)
    srv.start()
    try:
        clock.advance(5.1)
        store.create("Pod", make_pod().name("steered").uid("steered")
                     .namespace("default").req({"cpu": "1"}).obj())
        assert sched.run_until_idle().scheduled == 1
        assert ext.breaker.state == CIRCUIT_CLOSED
        assert gauge.value((ext.cfg.url_prefix,)) == CIRCUIT_CLOSED
        assert store.get("Pod", "default", "steered").spec.node_name == "n1"
    finally:
        srv.stop()
        ext.close()


def test_non_ignorable_extender_outage_unschedulable_not_crash():
    """Acceptance: a non-ignorable outage marks pods unschedulable (they
    requeue with backoff), never raises out of the scheduling cycle."""
    from kubernetes_tpu.scheduler import TPUScheduler

    ext = HTTPExtender(ExtenderConfig(
        url_prefix=f"http://127.0.0.1:{_free_port()}", filter_verb="filter",
        node_cache_capable=True, ignorable=False, http_timeout=0.5,
        failure_threshold=2, circuit_reset_seconds=3600,
    ))
    store = ObjectStore()
    clock = FakeClock()
    sched = TPUScheduler(store, batch_size=4, clock=clock, batch_wait=0.0)
    sched.extenders = [ext]
    store.create("Node", make_node().name("n0").obj())
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1"}).obj())
    for _ in range(4):  # several attempts: fail, trip the circuit, fail fast
        sched.schedule_cycle()  # must not raise
        clock.advance(30)
    assert store.get("Pod", "default", "p").spec.node_name == ""
    active, backoff, unsched = sched.queue.pending_count()
    assert active + backoff + unsched == 1  # requeued, not dropped
    assert ext.breaker.state == CIRCUIT_OPEN  # failing fast, no more timeouts
    ext.close()


# --- leader election ----------------------------------------------------------


def test_leader_election_renewal_failure_release_reacquire():
    f = FaultSchedule(1, write_500_rate=1.0, max_faults_per_key=1,
                      exempt_kinds=frozenset())
    store = ObjectStore(fault_injector=f)
    clock = FakeClock()
    transitions = []
    el = LeaderElector(
        LeaseLock(store, "kube-system", "tpu-scheduler"), "a",
        lease_duration=15, clock=clock,
        on_started_leading=lambda: transitions.append("start"),
        on_stopped_leading=lambda: transitions.append("stop"),
    )
    assert not el.try_acquire_or_renew()  # create shed by chaos → not leader
    assert el.try_acquire_or_renew()  # retried tick acquires
    clock.advance(5)
    assert not el.try_acquire_or_renew()  # renewal update shed → RELEASE
    assert not el.is_leader() and el.renew_failures == 1
    clock.advance(1)
    assert el.try_acquire_or_renew()  # REACQUIRE (holder is still us)
    assert transitions == ["start", "stop", "start"]
    status = default_registry.get("leader_election_master_status")
    assert status.value(("a",)) == 1.0


def test_leader_election_lease_loss_to_usurper():
    store = ObjectStore()
    clock = FakeClock()
    el = LeaderElector(LeaseLock(store, "kube-system", "sched"), "a",
                       lease_duration=15, clock=clock)
    assert el.try_acquire_or_renew()
    assert steal_lease(store, "kube-system", "sched", clock=clock)
    assert not el.try_acquire_or_renew()  # foreign fresh holder → released
    assert not el.is_leader()
    clock.advance(16)  # usurper never renews → lease expires
    assert el.try_acquire_or_renew()  # stolen back via the expiry path
    lease = store.get("Lease", "kube-system", "sched")
    assert lease.holder_identity == "a"


def test_leader_election_cas_prevents_double_leader():
    """Two candidates CAS on the same read rv: exactly one wins."""
    from kubernetes_tpu.sim.store import StaleResourceVersion

    store = ObjectStore()
    clock = FakeClock()
    lock_a = LeaseLock(store, "kube-system", "s")
    lock_b = LeaseLock(store, "kube-system", "s")
    a = LeaderElector(lock_a, "a", lease_duration=15, clock=clock)
    assert a.try_acquire_or_renew()
    clock.advance(20)  # expired: both candidates see a stealable lease
    stale = lock_b.get()
    rv = stale.metadata.resource_version
    assert a.try_acquire_or_renew()  # a renews first (rv bumps)
    stale.holder_identity = "b"
    with pytest.raises(StaleResourceVersion):
        lock_b.update(stale, expected_rv=rv)  # b's CAS loses — no 2nd leader


# --- scheduler failure handler ------------------------------------------------


def test_cycle_failure_requeues_instead_of_dropping():
    from kubernetes_tpu.scheduler import TPUScheduler

    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    store.create("Node", make_node().name("n0").obj())
    for i in range(3):
        store.create("Pod", make_pod().name(f"p{i}").uid(f"p{i}")
                     .namespace("default").req({"cpu": "1"}).obj())
    retries = default_registry.get("scheduler_retries_total")
    before = retries.value(("cycle_error",))
    orig = sched._dispatch_batch
    calls = {"n": 0}

    def boom(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected dispatch failure")
        return orig(*a, **kw)

    sched._dispatch_batch = boom
    sched.schedule_cycle()  # must not raise; batch routed to backoff
    assert retries.value(("cycle_error",)) == before + 3
    stats = sched.run_until_idle()  # retried batch schedules normally
    assert stats.scheduled == 3
    pods, _ = store.list("Pod")
    assert all(p.spec.node_name for p in pods)


def test_bind_fault_rolls_back_and_retries():
    """A store bind that blows through retries takes the binding-error path
    (forget + requeue), and the pod binds on a later attempt."""
    from kubernetes_tpu.scheduler import TPUScheduler

    f = FaultSchedule(4, max_faults_per_key=100)
    raw = ObjectStore(fault_injector=f)
    store = RetryingStore(raw, max_retries=1, sleep=_no_sleep)
    sched = TPUScheduler(store, batch_size=4)
    store.create("Node", make_node().name("n0").obj())
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1"}).obj())
    # arm the fault AFTER the objects exist so only the bind is hit; rate
    # 1.0 with max_retries=1 guarantees the first bind attempts exhaust
    f.write_429_rate = 1.0
    sched.schedule_cycle()  # bind fails post-retries → rollback + requeue
    assert raw.get("Pod", "default", "p").spec.node_name == ""
    f.write_429_rate = 0.0  # fault clears
    stats = sched.run_until_idle()
    assert stats.scheduled >= 1
    assert raw.get("Pod", "default", "p").spec.node_name == "n0"
    assert default_registry.get(
        "scheduler_retries_total").value(("bind_error",)) > 0


# --- extender protocol satellites --------------------------------------------


def test_read_body_decodes_chunked_transfer_encoding():
    from kubernetes_tpu.extender import _read_body

    wire = b"7\r\n{\"noden\r\n10\r\names\": [\"n1\"]}  \r\n0\r\n\r\n"
    body = _read_body(io.BytesIO(wire),
                      {b"transfer-encoding": b"chunked"})
    assert json.loads(body) == {"nodenames": ["n1"]}
    # chunk extensions + trailers per RFC 7230 §4.1
    wire = b"5;ext=1\r\nhello\r\n0\r\nTrailer: x\r\n\r\n"
    assert _read_body(io.BytesIO(wire),
                      {b"transfer-encoding": b"chunked"}) == b"hello"
    # malformed size line → None (unsupported framing, not a crash)
    assert _read_body(io.BytesIO(b"zz\r\n"),
                      {b"transfer-encoding": b"chunked"}) is None


def test_extender_client_against_chunked_go_style_server():
    """A real Go extender writing through json.NewEncoder emits chunked
    replies; the hand-rolled client must interoperate (ADVICE round 5)."""
    import http.server

    class ChunkedHandler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            payload = json.dumps(
                {"nodenames": ["n1"], "failedNodes": {"n0": "no"}}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            # two chunks, Go-encoder style
            for part in (payload[:10], payload[10:]):
                self.wfile.write(f"{len(part):X}\r\n".encode() + part + b"\r\n")
            self.wfile.write(b"0\r\n\r\n")

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), ChunkedHandler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        ext = HTTPExtender(ExtenderConfig(
            url_prefix=f"http://127.0.0.1:{httpd.server_address[1]}",
            filter_verb="filter", node_cache_capable=True))
        pod = make_pod().name("p").uid("p").namespace("default") \
            .req({"cpu": "1"}).obj()
        names, failed = ext.filter(pod, ["n0", "n1"])
        assert names == ["n1"] and failed == {"n0": "no"}
        ext.close()
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_host_header_resolves_default_port():
    """url_prefix without an explicit port must render Host: host:80, not
    host:None (ADVICE round 5)."""
    sent = []

    class FakeSock:
        def sendall(self, data):
            sent.append(data)

        def close(self):
            pass

    body = json.dumps({"nodenames": ["n1"], "failedNodes": {}}).encode()
    reply = (b"HTTP/1.1 200 OK\r\nContent-Length: "
             + str(len(body)).encode() + b"\r\n\r\n" + body)
    ext = HTTPExtender(ExtenderConfig(url_prefix="http://example.com",
                                      filter_verb="filter"))
    ext._fresh_conn = lambda: (FakeSock(), io.BytesIO(reply))
    pod = make_pod().name("p").uid("p").namespace("default") \
        .req({"cpu": "1"}).obj()
    names, _ = ext.filter(pod, ["n0", "n1"])
    assert names == ["n1"]
    head = sent[0]
    assert b"Host: example.com:80\r\n" in head
    assert b"None" not in head


# --- metrics registry (acceptance: name-compatible spellings) -----------------


def test_chaos_metrics_registered_by_name():
    for name in (
        "scheduler_retries_total",
        "extender_circuit_state",
        "informer_relists_total",
        "client_request_retries_total",
        "chaos_faults_injected_total",
        "leader_election_master_status",
    ):
        assert default_registry.get(name) is not None, name


# --- convergence under failure ------------------------------------------------


def _assert_soak(result):
    assert result.converged, (
        f"bound {result.bound}/{result.pods}, dupes {result.duplicate_binds},"
        f" unbound {result.unbound[:5]}")
    assert result.duplicate_binds == 0
    assert result.informer_items == result.pods  # relisting cache converged
    assert result.circuit_state == CIRCUIT_OPEN  # outage tripped and held
    injected = result.injected
    assert injected.get("watch_drop", 0) >= 1
    write_faults = sum(v for k, v in injected.items()
                      if k.startswith("write_") or k == "conflict")
    assert write_faults >= 1
    # bounded retries: every injected write fault absorbed by exactly one
    # resend — none leaked into a crash, none retried forever
    assert result.store_retries == write_faults


def test_soak_small_converges_and_is_deterministic():
    """The acceptance workload at tier-1 scale: seeded faults (10% watch
    drops, 5% write 429s, conflict storm, one extender outage), every pod
    bound exactly once, and a replay with the same seed injects the same
    faults and costs the same retries."""
    from kubernetes_tpu.chaos.soak import run_soak

    kw = dict(n_pods=48, n_nodes=12, seed=11, batch_size=16,
              timeout_seconds=120)
    r1 = run_soak(**kw)
    _assert_soak(r1)
    r2 = run_soak(**kw)
    _assert_soak(r2)
    assert r1.determinism_signature() == r2.determinism_signature()


@pytest.mark.slow
def test_soak_full_500_pod_acceptance():
    """The full acceptance bar (500 pods, two seeded runs) — slow; tier-1
    runs the small variant above, tools/chaos_soak.py runs this locally."""
    from kubernetes_tpu.chaos.soak import run_soak

    kw = dict(n_pods=500, n_nodes=50, seed=7, batch_size=64,
              timeout_seconds=600)
    r1 = run_soak(**kw)
    _assert_soak(r1)
    r2 = run_soak(**kw)
    _assert_soak(r2)
    assert r1.determinism_signature() == r2.determinism_signature()


# --- descheduler under faults -------------------------------------------------


def _fragmented_for_defrag(store, gang_size=4):
    """2 slices × 4 hosts, every slice fragmented by stragglers so a
    4-member gang (3 cpu/host vs 2-cpu stragglers on 4-cpu hosts) cannot
    place anywhere without evictions."""
    from kubernetes_tpu.api import objects as v1
    from kubernetes_tpu.gang import POD_GROUP_LABEL, SLICE_LABEL

    for i in range(8):
        store.create("Node", make_node().name(f"n{i:02d}")
                     .capacity({"cpu": "4", "pods": "10"})
                     .label(SLICE_LABEL, f"s{i // 4}").obj())
    stragglers = []
    for i, node in enumerate(["n00", "n01", "n04", "n05", "n06"]):
        name = f"str-{i}"
        store.create("Pod", make_pod().name(name).uid(name)
                     .namespace("default").req({"cpu": "2"})
                     .node(node).obj())
        stragglers.append(name)
    pg = v1.PodGroup(
        metadata=v1.ObjectMeta(name="g", namespace="default"),
        min_member=gang_size, schedule_timeout_seconds=2)
    store.create("PodGroup", pg)
    for i in range(gang_size):
        store.create("Pod", make_pod().name(f"g-{i}").uid(f"g-{i}")
                     .namespace("default").label(POD_GROUP_LABEL, "g")
                     .req({"cpu": "3"}).obj())
    return stragglers


def test_descheduler_converges_under_watch_drops_and_429_storm():
    """Descheduler convergence under the chaos battery: watch drops + a
    429/500 write storm may delay evictions and requeues, but the end
    state converges — each straggler is evicted EXACTLY once (no pod is
    ever evicted twice), the freed slice is bound by the waiting gang
    all-or-nothing, and no partial gang placement survives."""
    from kubernetes_tpu.descheduler import (
        DeschedulerController,
        SliceDefragmentation,
    )
    from kubernetes_tpu.scheduler import TPUScheduler

    fault = FaultSchedule(
        21, watch_drop_rate=0.1, write_429_rate=0.3, write_500_rate=0.1,
        conflict_rate=0.1, retry_after=0.0, max_faults_per_key=3,
    )
    raw = ObjectStore(fault_injector=fault)
    store = RetryingStore(raw, sleep=_no_sleep)
    delete_counts = {}

    def on_ev(ev):
        from kubernetes_tpu.sim.store import DELETED

        if ev.kind == "Pod" and ev.type == DELETED:
            delete_counts[ev.obj.uid] = delete_counts.get(ev.obj.uid, 0) + 1

    raw.watch(on_ev)
    sched = TPUScheduler(store, batch_size=4, pod_initial_backoff=0.01,
                         pod_max_backoff=0.05, batch_wait=0)
    stragglers = _fragmented_for_defrag(store)
    ctrl = DeschedulerController(store, sched,
                                 policies=[SliceDefragmentation()])
    deadline = time.monotonic() + 60.0
    done = 0
    while time.monotonic() < deadline:
        s = sched.run_until_idle(max_cycles=50, backoff_wait=0.5)
        ctrl.sync_once()
        done = sum(
            1 for i in range(4)
            if raw.get("Pod", "default", f"g-{i}").spec.node_name
        )
        if done == 4 and s.waiting == 0:
            break
        time.sleep(0.02)
    assert done == 4
    # all-or-nothing into ONE slice
    from kubernetes_tpu.gang import SLICE_LABEL

    slices = {
        raw.get("Node", "",
                raw.get("Pod", "default", f"g-{i}").spec.node_name)
        .metadata.labels[SLICE_LABEL]
        for i in range(4)
    }
    assert len(slices) == 1
    # exactly-once evictions: every deleted straggler saw ONE delete event
    evicted = [s_ for s_ in stragglers
               if raw.get("Pod", "default", s_) is None]
    assert evicted, "defrag never evicted anything"
    for name in evicted:
        assert delete_counts.get(name, 0) == 1, (name, delete_counts)
    injected = fault.injected_counts()
    assert sum(injected.values()) > 0  # the storm actually fired


def test_descheduler_mid_plan_fault_abandons_plan():
    """A store fault mid-plan (delete blows through the client's retries)
    abandons the remainder of the plan instead of half-applying it: the
    surviving victims stay put, the outcome is counted 'abandoned', and
    the NEXT sync re-plans from live state and converges — the cluster
    ends schedulable."""
    from kubernetes_tpu.descheduler import (
        DeschedulerController,
        EvictionAPI,
        SliceDefragmentation,
    )
    from kubernetes_tpu.metrics import scheduler_metrics as m
    from kubernetes_tpu.scheduler import TPUScheduler

    store = ObjectStore()

    class FlakyDeleteStore:
        """Raises once on the delete of each named pod — the shape of a
        429 storm outlasting RetryingStore's max_retries."""

        def __init__(self, inner, fail_once):
            self._inner = inner
            self.fail_once = set(fail_once)

        def delete(self, kind, namespace, name):
            if kind == "Pod" and name in self.fail_once:
                self.fail_once.discard(name)
                raise TransientApiError(429, message="injected storm")
            return self._inner.delete(kind, namespace, name)

        def __getattr__(self, attr):
            return getattr(self._inner, attr)

    sched = TPUScheduler(store, batch_size=4, pod_initial_backoff=0.01,
                         pod_max_backoff=0.05, batch_wait=0)
    stragglers = _fragmented_for_defrag(store)
    # the cheapest plan is slice s0 (2 stragglers); fault its SECOND victim
    flaky = FlakyDeleteStore(store, ["str-1"])
    ctrl = DeschedulerController(
        store, sched, policies=[SliceDefragmentation()],
        eviction_api=EvictionAPI(flaky))
    before = m.descheduler_plans.value(("defrag", "abandoned"))
    sched.run_until_idle(max_cycles=20, backoff_wait=0.2)
    ctrl.sync_once()
    assert m.descheduler_plans.value(("defrag", "abandoned")) == before + 1.0
    # not half-applied: the faulted victim survived, and no further victim
    # of the plan was touched after the fault
    assert store.get("Pod", "default", "str-1") is not None
    # the cluster stays schedulable: later syncs re-plan from live state
    deadline = time.monotonic() + 30.0
    done = 0
    while time.monotonic() < deadline:
        s = sched.run_until_idle(max_cycles=50, backoff_wait=0.5)
        ctrl.sync_once()
        done = sum(
            1 for i in range(4)
            if store.get("Pod", "default", f"g-{i}").spec.node_name
        )
        if done == 4 and s.waiting == 0:
            break
        time.sleep(0.02)
    assert done == 4
