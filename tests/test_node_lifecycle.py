"""Partition-tolerant node lifecycle (ISSUE 13): zone-aware eviction
storms, the tolerationSeconds taint manager, gang-aware slice repair, the
NotReady encoder mask, the crash.mid_zone_evict kill-point, and the CLI
nodehealth view.

Reference behaviors exercised: nodelifecycle zoneStates + setLimiterInZone
(node_lifecycle_controller.go), RateLimitedTimedQueue node pops,
NoExecuteTaintManager tolerationSeconds countdowns anchored on
Taint.TimeAdded (taint_manager.go), and the taint-based eviction loop of
SURVEY §5 — plus this tree's documented deviation: per-zone FullDisruption
FREEZES evictions (a dark zone is indistinguishable from a partition).
"""

import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.chaos import (
    CRASH_MID_ZONE_EVICT,
    CRASH_POINTS,
    FaultSchedule,
    ProcessCrash,
    crash_schedule,
)
from kubernetes_tpu.chaos.partition import PartitionDriver, run_node_storm
from kubernetes_tpu.cli import Kubectl
from kubernetes_tpu.controllers.disruption import sync_pdbs
from kubernetes_tpu.controllers.nodelifecycle import (
    UNREACHABLE_TAINT,
    ZONE_FULL,
    ZONE_LABEL,
    ZONE_NORMAL,
    ZONE_PARTIAL,
    NodeLifecycleController,
    TokenBucket,
)
from kubernetes_tpu.gang import POD_GROUP_LABEL
from kubernetes_tpu.metrics import scheduler_metrics as m
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.hollow_node import HollowCluster
from kubernetes_tpu.sim.store import DELETED, ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _lease(store, node, renew_time, clock=None):
    from kubernetes_tpu.client.leaderelection import Lease

    lease = store.get("Lease", "kube-node-lease", node)
    if lease is None:
        lease = Lease(metadata=v1.ObjectMeta(name=node,
                                             namespace="kube-node-lease"),
                      renew_time=renew_time)
        store.create("Lease", lease)
    else:
        lease.renew_time = renew_time
        store.update("Lease", lease)


def _mk_node(store, name, zone=None):
    b = make_node().name(name).capacity({"cpu": "8", "pods": "32"})
    if zone is not None:
        b = b.label(ZONE_LABEL, zone)
    store.create("Node", b.obj())


def _mk_zone(store, zone, n, start=0):
    names = [f"{zone}-n{start + i}" for i in range(n)]
    for name in names:
        _mk_node(store, name, zone=zone)
        _lease(store, name, 0.0)
    return names


def _pod(name, node, labels=None, tol_seconds="absent"):
    b = (make_pod().name(name).uid(name).namespace("default")
         .req({"cpu": "1"}))
    for k, val in (labels or {}).items():
        b = b.label(k, val)
    if tol_seconds != "absent":
        b = b.toleration(key=UNREACHABLE_TAINT,
                         operator=v1.TOLERATION_OP_EXISTS,
                         effect="NoExecute",
                         toleration_seconds=tol_seconds)
    p = b.obj()
    p.spec.node_name = node
    return p


def _deleted(store):
    return [ev.obj.metadata.name for ev in store._log
            if ev.kind == "Pod" and ev.type == DELETED]


# --- token bucket / zone states -------------------------------------------------


def test_token_bucket_rates_and_freeze():
    clock = FakeClock()
    tb = TokenBucket(qps=0.1, burst=1, clock=clock)
    assert tb.try_take(clock())          # burst token
    assert not tb.try_take(clock())      # drained
    clock.advance(10.0)
    assert tb.try_take(clock())          # refilled at 0.1/s
    clock.advance(100.0)
    tb.set_rate(0.0, clock())            # freeze zeroes the bank
    assert not tb.try_take(clock())
    tb.set_rate(0.1, clock())
    clock.advance(10.0)
    assert tb.try_take(clock())


def test_zone_states_normal_partial_full():
    clock = FakeClock()
    store = ObjectStore()
    # zone-a: 6 nodes, 4 down → PartialDisruption (0.67 ≥ 0.55, >2 down)
    a = _mk_zone(store, "zone-a", 6)
    # zone-b: 4 nodes, all down → FullDisruption
    b = _mk_zone(store, "zone-b", 4)
    # zone-c: 4 nodes, 1 down → Normal (not >2 down)
    c = _mk_zone(store, "zone-c", 4)
    ctrl = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    clock.advance(100.0)
    for name in a[:2] + c[1:]:
        _lease(store, name, clock() - 1.0)
    ctrl.sync_once()
    assert ctrl.zone_mode("zone-a") == ZONE_PARTIAL
    assert ctrl.zone_mode("zone-b") == ZONE_FULL
    assert ctrl.zone_mode("zone-c") == ZONE_NORMAL
    assert m.node_lifecycle_zone_state.value(("zone-a",)) == 1
    assert m.node_lifecycle_zone_state.value(("zone-b",)) == 2
    assert m.node_lifecycle_zone_state.value(("zone-c",)) == 0


def test_never_heartbeat_node_detected_after_bounded_grace():
    """A node that registers but whose kubelet dies before the FIRST
    lease renewal must still be detected: grace anchors on the
    controller's first no-lease observation, not exempted forever."""
    clock = FakeClock()
    store = ObjectStore()
    _mk_node(store, "n0", zone="z")     # Node object, NO lease ever
    store.create("Pod", _pod("p0", "n0"))
    ctrl = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    ctrl.sync_once()                     # first observation at t=0
    assert not store.get("Node", "", "n0").spec.taints  # within grace
    clock.advance(50.0)
    ctrl.sync_once()
    node = store.get("Node", "", "n0")
    assert any(t.key == UNREACHABLE_TAINT for t in node.spec.taints)
    assert store.get("Pod", "default", "p0") is None


def test_tiny_zone_death_never_freezes():
    """A 1-node 'zone' dying is plain node death: the basic elastic loop
    (taint → evict → reschedule) must keep working, not freeze."""
    clock = FakeClock()
    store = ObjectStore()
    _mk_zone(store, "solo", 1)
    store.create("Pod", _pod("p0", "solo-n0"))
    ctrl = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    clock.advance(100.0)
    assert ctrl.sync_once()
    assert ctrl.zone_mode("solo") == ZONE_NORMAL
    assert store.get("Pod", "default", "p0") is None


# --- tolerationSeconds taint manager (the ISSUE-13 bugfix) ----------------------


def test_toleration_seconds_countdown_and_forever_regression():
    """Regression pin for the seed bug: toleration_seconds != None used to
    mean NOT tolerated (instant eviction).  Upstream semantics: unset
    seconds → tolerate forever; seconds=N → survive N seconds from
    Taint.TimeAdded, THEN evict."""
    clock = FakeClock()
    store = ObjectStore()
    _mk_zone(store, "z", 1)
    store.create("Pod", _pod("instant", "z-n0"))                     # no toleration
    store.create("Pod", _pod("forever", "z-n0", tol_seconds=None))   # unset = forever
    store.create("Pod", _pod("timed", "z-n0", tol_seconds=30))       # countdown
    ctrl = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    clock.advance(50.0)  # lease stale at t=50; taint lands now
    ctrl.sync_once()
    node = store.get("Node", "", "z-n0")
    taint = next(t for t in node.spec.taints if t.key == UNREACHABLE_TAINT)
    assert taint.time_added == 50.0  # anchored for successor controllers
    assert store.get("Pod", "default", "instant") is None   # swept now
    assert store.get("Pod", "default", "forever") is not None
    assert store.get("Pod", "default", "timed") is not None  # countdown live
    clock.advance(20.0)  # t=70 < 50+30
    ctrl.sync_once()
    assert store.get("Pod", "default", "timed") is not None
    clock.advance(15.0)  # t=85 ≥ 80: countdown fired
    ctrl.sync_once()
    assert store.get("Pod", "default", "timed") is None
    assert store.get("Pod", "default", "forever") is not None  # forever holds
    assert m.node_lifecycle_evictions.value((ZONE_NORMAL, "evicted")) >= 2


def test_lease_recovery_untaints_and_cancels_pending_evictions():
    """The flap contract: a node that comes back before its countdowns
    fire is untainted and every queued eviction is CANCELLED — flapping
    nodes stop churning workloads."""
    clock = FakeClock()
    store = ObjectStore()
    _mk_zone(store, "z", 1)
    store.create("Pod", _pod("timed", "z-n0", tol_seconds=60))
    ctrl = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    cancelled0 = m.node_lifecycle_evictions.value((ZONE_NORMAL, "cancelled"))
    for flap in range(3):
        clock.advance(50.0)           # stale → taint + countdown
        ctrl.sync_once()
        node = store.get("Node", "", "z-n0")
        assert any(t.key == UNREACHABLE_TAINT for t in node.spec.taints)
        assert len(ctrl.taint_manager) == 1
        _lease(store, "z-n0", clock())  # lease renews before the countdown
        ctrl.sync_once()
        node = store.get("Node", "", "z-n0")
        assert not any(t.key == UNREACHABLE_TAINT for t in node.spec.taints)
        assert next(c["status"] for c in node.status.conditions
                    if c["type"] == "Ready") == "True"
        assert len(ctrl.taint_manager) == 0  # countdown cancelled
    clock.advance(1000.0)
    ctrl.sync_once()  # long after every abandoned deadline
    assert store.get("Pod", "default", "timed") is not None  # never evicted
    assert _deleted(store) == []
    assert (m.node_lifecycle_evictions.value((ZONE_NORMAL, "cancelled"))
            - cancelled0) >= 3


def test_countdown_survives_controller_restart_without_reset():
    """Deadlines anchor on the persisted Taint.TimeAdded: a successor
    controller resumes the SAME countdown instead of granting a fresh
    window."""
    clock = FakeClock()
    store = ObjectStore()
    _mk_zone(store, "z", 1)
    store.create("Pod", _pod("timed", "z-n0", tol_seconds=100))
    ctrl = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    clock.advance(50.0)
    ctrl.sync_once()  # taint at t=50; deadline t=150
    clock.advance(60.0)  # t=110: controller dies here
    ctrl2 = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    ctrl2.sync_once()
    assert store.get("Pod", "default", "timed") is not None
    clock.advance(45.0)  # t=155 ≥ 150: the ORIGINAL deadline, not 110+100
    ctrl2.sync_once()
    assert store.get("Pod", "default", "timed") is None


# --- disruption modes gate evictions --------------------------------------------


def test_full_disruption_freezes_and_heals():
    clock = FakeClock()
    store = ObjectStore()
    names = _mk_zone(store, "dark", 4)
    for i, name in enumerate(names):
        store.create("Pod", _pod(f"p{i}", name))
        store.create("Pod", _pod(f"t{i}", name, tol_seconds=60))
    ctrl = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    clock.advance(50.0)
    ctrl.sync_once()
    assert ctrl.zone_mode("dark") == ZONE_FULL
    # hold the outage well past every countdown: still zero evictions
    for _ in range(10):
        clock.advance(60.0)
        ctrl.sync_once()
    assert _deleted(store) == []
    assert m.node_lifecycle_evictions.value((ZONE_FULL, "deferred")) > 0
    # heal: leases renew, taints drop, countdowns cancel, nothing evicted
    for name in names:
        _lease(store, name, clock())
    ctrl.sync_once()
    assert ctrl.zone_mode("dark") == ZONE_NORMAL
    for name in names:
        node = store.get("Node", "", name)
        assert not any(t.key == UNREACHABLE_TAINT for t in node.spec.taints)
    assert len(ctrl.taint_manager) == 0
    clock.advance(500.0)
    ctrl.sync_once()
    assert _deleted(store) == []


def test_partial_disruption_sweeps_at_secondary_rate():
    clock = FakeClock()
    store = ObjectStore()
    names = _mk_zone(store, "z", 8)
    for i, name in enumerate(names):
        store.create("Pod", _pod(f"p{i}", name))
    ctrl = NodeLifecycleController(
        store, grace_period=40.0, clock=clock,
        secondary_eviction_qps=0.01, large_zone_threshold=4)
    clock.advance(100.0)
    survivors = names[5:]  # 5/8 down = 0.625 ≥ 0.55, >2 down → Partial
    for name in survivors:
        _lease(store, name, clock())
    ctrl.sync_once()
    assert ctrl.zone_mode("z") == ZONE_PARTIAL
    # one banked burst token sweeps the first node immediately; then the
    # secondary rate (0.01/s) meters the rest: +100s → exactly one more
    assert len(ctrl.draining) == 1
    for expected in (2, 3):
        clock.advance(100.0)
        for name in survivors:  # survivors keep heartbeating
            _lease(store, name, clock())
        ctrl.sync_once()
        assert len(ctrl.draining) == expected
    assert m.node_lifecycle_queue_depth.value(("z",)) == 2.0


def test_pdb_refused_sweep_retries_without_tokens():
    """The PR-5 contract carried into the zone machinery: refused pods
    retry every sync as budget replenishes — no fresh tokens needed, and
    the budget is never violated."""
    clock = FakeClock()
    store = ObjectStore()
    _mk_zone(store, "z", 1)
    for i in range(3):
        store.create("Pod", _pod(f"web-{i}", "z-n0", labels={"app": "web"}))
    store.create("PodDisruptionBudget", v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="pdb", namespace="default"),
        selector=v1.LabelSelector(match_labels={"app": "web"}),
        min_available=2))
    sync_pdbs(store)
    ctrl = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    clock.advance(50.0)
    ctrl.sync_once()
    assert len(_deleted(store)) == 1  # one unit of budget, one eviction
    # replacement lands elsewhere; budget replenishes; NO clock advance —
    # the draining retry must not be gated on sweep tokens
    store.create("Pod", _pod("web-new", "n-else", labels={"app": "web"}))
    sync_pdbs(store)
    ctrl.sync_once()
    assert len(_deleted(store)) == 2


# --- gang-aware slice repair ----------------------------------------------------


def _mk_gang(store, name, nodes):
    store.create("PodGroup", v1.PodGroup(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        min_member=len(nodes)))
    for i, node in enumerate(nodes):
        store.create("Pod", _pod(f"{name}-{i}", node,
                                 labels={POD_GROUP_LABEL: name}))


def test_gang_repair_fails_whole_gang_atomically():
    clock = FakeClock()
    store = ObjectStore()
    _mk_zone(store, "z", 3)
    # gang spans all three nodes; a solo pod rides the healthy node
    _mk_gang(store, "g0", ["z-n0", "z-n1", "z-n2"])
    store.create("Pod", _pod("solo", "z-n1"))
    repairs0 = m.gang_repairs.value()
    ctrl = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    clock.advance(100.0)
    _lease(store, "z-n1", clock() - 1.0)
    _lease(store, "z-n2", clock() - 1.0)
    ctrl.sync_once()  # only z-n0 died
    # the WHOLE gang is gone — members on healthy hosts included — the
    # bystander solo pod is untouched, and the repair counted ONCE
    for i in range(3):
        assert store.get("Pod", "default", f"g0-{i}") is None
    assert store.get("Pod", "default", "solo") is not None
    assert m.gang_repairs.value() - repairs0 == 1
    assert store.get("PodGroup", "default", "g0").phase == v1.POD_GROUP_PENDING
    # later syncs find no bound members: exactly-once
    clock.advance(100.0)
    ctrl.sync_once()
    assert m.gang_repairs.value() - repairs0 == 1


def test_gang_repair_all_or_nothing_under_pdb():
    """One PDB-refused member defers the ENTIRE repair — never a
    half-evicted gang — and the repair completes when budget returns."""
    clock = FakeClock()
    store = ObjectStore()
    _mk_zone(store, "z", 2)
    _mk_gang(store, "g0", ["z-n0", "z-n1"])
    store.create("PodDisruptionBudget", v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="gpdb", namespace="default"),
        selector=v1.LabelSelector(
            match_expressions=[v1.LabelSelectorRequirement(
                key=POD_GROUP_LABEL, operator=v1.OP_IN, values=["g0"])]),
        min_available=2))
    sync_pdbs(store)  # 2 healthy, floor 2 → zero budget
    repairs0 = m.gang_repairs.value()
    ctrl = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    clock.advance(100.0)
    _lease(store, "z-n1", clock() - 1.0)
    ctrl.sync_once()
    assert store.get("Pod", "default", "g0-0") is not None  # deferred whole
    assert store.get("Pod", "default", "g0-1") is not None
    assert m.gang_repairs.value() == repairs0
    # budget arrives (replacement capacity elsewhere): repair completes
    pdb = store.get("PodDisruptionBudget", "default", "gpdb")
    pdb.min_available = 0
    store.update("PodDisruptionBudget", pdb)
    sync_pdbs(store)
    ctrl.sync_once()
    assert store.get("Pod", "default", "g0-0") is None
    assert store.get("Pod", "default", "g0-1") is None
    assert m.gang_repairs.value() - repairs0 == 1


def test_gang_repair_pdb_check_is_aggregate_not_per_member():
    """A PDB shared by the whole gang must have budget for EVERY member at
    once: per-member dry-runs each see the undrained budget and would
    half-evict (budget 1, members 2) — the aggregate check defers whole."""
    clock = FakeClock()
    store = ObjectStore()
    _mk_zone(store, "z", 2)
    _mk_gang(store, "g0", ["z-n0", "z-n1"])
    store.create("PodDisruptionBudget", v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="gpdb", namespace="default"),
        selector=v1.LabelSelector(
            match_expressions=[v1.LabelSelectorRequirement(
                key=POD_GROUP_LABEL, operator=v1.OP_IN, values=["g0"])]),
        min_available=1))
    sync_pdbs(store)  # 2 healthy, floor 1 → budget 1 < gang size 2
    repairs0 = m.gang_repairs.value()
    ctrl = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    clock.advance(100.0)
    _lease(store, "z-n1", clock())
    ctrl.sync_once()
    # budget covers one member but not both: NOTHING evicted
    assert store.get("Pod", "default", "g0-0") is not None
    assert store.get("Pod", "default", "g0-1") is not None
    assert m.gang_repairs.value() == repairs0


def test_expired_gang_member_countdown_never_lone_evicts():
    """A gang member whose tolerationSeconds expires may only leave via
    the atomic repair: while a sibling's PDB defers the repair, the
    expired member survives too (countdown re-armed), and the whole gang
    goes together once budget returns."""
    clock = FakeClock()
    store = ObjectStore()
    _mk_zone(store, "z", 2)
    store.create("PodGroup", v1.PodGroup(
        metadata=v1.ObjectMeta(name="g0", namespace="default"),
        min_member=2))
    store.create("Pod", _pod("g0-0", "z-n0",
                             labels={POD_GROUP_LABEL: "g0"},
                             tol_seconds=30))
    store.create("Pod", _pod("g0-1", "z-n1",
                             labels={POD_GROUP_LABEL: "g0",
                                     "protected": "yes"}))
    store.create("PodDisruptionBudget", v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="gpdb", namespace="default"),
        selector=v1.LabelSelector(match_labels={"protected": "yes"}),
        min_available=1))
    sync_pdbs(store)  # g0-1's budget is zero → repair must defer
    ctrl = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    clock.advance(50.0)   # z-n0 stale → taint at t=50, countdown t=80
    _lease(store, "z-n1", clock())
    ctrl.sync_once()
    clock.advance(50.0)   # t=100: countdown fired, repair deferred by PDB
    _lease(store, "z-n1", clock())
    ctrl.sync_once()
    assert store.get("Pod", "default", "g0-0") is not None  # NOT lone-evicted
    assert store.get("Pod", "default", "g0-1") is not None
    # budget returns: the re-armed countdown completes the atomic repair
    pdb = store.get("PodDisruptionBudget", "default", "gpdb")
    pdb.min_available = 0
    store.update("PodDisruptionBudget", pdb)
    sync_pdbs(store)
    clock.advance(1.0)
    _lease(store, "z-n1", clock())
    ctrl.sync_once()
    assert store.get("Pod", "default", "g0-0") is None
    assert store.get("Pod", "default", "g0-1") is None


# --- the scheduler-side mask -----------------------------------------------------


def test_scheduler_never_binds_onto_notready_node():
    """The encoder's node_ready plane: a host marked Ready=Unknown is out
    of the feasibility universe even for pods that would TOLERATE its
    taints (the in-flight-cycle guard)."""
    store = ObjectStore()
    _mk_node(store, "dead")
    _mk_node(store, "alive")
    dead = store.get("Node", "", "dead")
    dead.status.conditions.append({"type": "Ready", "status": "Unknown"})
    store.update("Node", dead)
    sched = TPUScheduler(store, batch_size=4, batch_wait=0)
    try:
        for i in range(3):
            p = (make_pod().name(f"p{i}").uid(f"p{i}").namespace("default")
                 .req({"cpu": "1"})
                 .toleration(key=UNREACHABLE_TAINT,
                             operator=v1.TOLERATION_OP_EXISTS).obj())
            store.create("Pod", p)
        sched.run_until_idle(max_cycles=5)
        pods, _ = store.list("Pod")
        assert all(p.spec.node_name == "alive" for p in pods)
        # recovery: condition back to True → host schedulable again
        dead.status.conditions = [{"type": "Ready", "status": "True"}]
        store.update("Node", dead)
        # only the recovered host has 6 free CPUs left (alive holds 3×1cpu
        # of its 8): rebinding there proves the mask lifted
        store.create("Pod", make_pod().name("px").uid("px")
                     .namespace("default").req({"cpu": "6"}).obj())
        sched.run_until_idle(max_cycles=5)
        assert store.get("Pod", "default", "px").spec.node_name == "dead"
    finally:
        sched.close(flush_events=False)


# --- crash.mid_zone_evict kill-point ---------------------------------------------


def test_mid_zone_evict_crash_successor_resumes_sweep_exactly_once():
    """PR-8 catalog extension: the controller dies between the taint write
    and the eviction sweep; a cold-started successor resumes the sweep
    from store truth alone — every pod evicted exactly once, the workload
    rescheduled exactly once."""
    from kubernetes_tpu.recovery import cold_start

    assert CRASH_MID_ZONE_EVICT in CRASH_POINTS
    clock = FakeClock()
    store = ObjectStore()
    cluster = HollowCluster(store, 2, clock=clock, zones=2)
    sched = TPUScheduler(store, batch_size=8, clock=clock)
    desired = [f"p{i}" for i in range(4)]
    for name in desired:
        store.create("Pod", make_pod().name(name).uid(f"{name}/r0")
                     .namespace("default").req({"cpu": "1"}).obj())
    sched.run_until_idle(max_cycles=5)
    victim = store.get("Pod", "default", "p0").spec.node_name
    next(n for n in cluster.nodes if n.name == victim).fail()
    survivor = next(n for n in cluster.nodes if n.name != victim)
    clock.advance(50.0)
    survivor.heartbeat()
    ctrl = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    fault = FaultSchedule(0, crash_points={CRASH_MID_ZONE_EVICT: 1})
    with crash_schedule(fault):
        with pytest.raises(ProcessCrash) as ei:
            ctrl.sync_once()
    assert ei.value.point == CRASH_MID_ZONE_EVICT
    # the taint write landed, the sweep did NOT run
    node = store.get("Node", "", victim)
    assert any(t.key == UNREACHABLE_TAINT for t in node.spec.taints)
    assert _deleted(store) == []
    sched.close(flush_events=False)
    # successor: scheduler cold-starts from the store, a FRESH controller
    # (fail-stop: no in-memory queue survives) resumes from the taint
    res = cold_start(store, batch_size=8, clock=clock)
    ctrl2 = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    for _ in range(4):
        survivor.heartbeat()
        ctrl2.sync_once()
        # stand-in workload controller: recreate evicted pods by name
        for name in desired:
            if store.get("Pod", "default", name) is None:
                store.create("Pod", make_pod().name(name).uid(f"{name}/r1")
                             .namespace("default").req({"cpu": "1"}).obj())
        res.scheduler.run_until_idle(max_cycles=5)
    deleted = _deleted(store)
    assert len(deleted) == len(set(deleted))  # each pod evicted ONCE
    pods, _ = store.list("Pod")
    assert len(pods) == 4
    assert all(p.spec.node_name == survivor.name for p in pods)
    res.scheduler.close(flush_events=False)


# --- the storm soak (fast shape; 3×100 acceptance shape is slow/tools) -----------


def test_node_storm_soak_fast_shape():
    r = run_node_storm(nodes_per_zone=6, n_zones=3, seed=7, gang_size=3)
    assert r.outage_zone_mode == "FullDisruption"
    assert r.outage_evictions == 0          # dark zone: evictions frozen
    assert r.cancelled_on_heal > 0          # heal cancelled the countdowns
    assert r.scattered_zone_mode == "PartialDisruption"
    assert r.scattered_swept <= r.scattered_budget  # secondary-rate bound
    assert r.gang_repairs == 1              # repaired exactly once
    assert all(c == 1 for c in r.gang_member_binds.values())
    assert r.pdb_floor_held and r.overridden_evictions == 0
    assert not r.unbound
    assert r.converged


def test_node_storm_soak_replays_deterministically():
    a = run_node_storm(nodes_per_zone=4, n_zones=3, seed=11, gang_size=3)
    b = run_node_storm(nodes_per_zone=4, n_zones=3, seed=11, gang_size=3)
    assert a.determinism_signature() == b.determinism_signature()


@pytest.mark.slow
def test_node_storm_soak_acceptance_shape():
    """The ISSUE-13 acceptance shape: 3 zones × 100 hollow nodes (also run
    standalone via tools/node_storm_soak.py)."""
    r = run_node_storm(nodes_per_zone=100, n_zones=3, seed=7,
                       web_replicas=400, gang_size=8,
                       large_zone_threshold=50)
    assert r.converged, r


# --- partition driver determinism -------------------------------------------------


def test_partition_driver_pick_is_seed_deterministic():
    clock = FakeClock()
    store = ObjectStore()
    cluster = HollowCluster(store, 12, clock=clock, zones=3)
    d1 = PartitionDriver(cluster, FaultSchedule(3), clock=clock)
    d2 = PartitionDriver(cluster, FaultSchedule(3), clock=clock)
    names = d1.zone_nodes("zone-1")
    assert d1.pick(names, 2) == d2.pick(list(reversed(names)), 2)
    assert d1.pick(names, 2) != PartitionDriver(
        cluster, FaultSchedule(4), clock=clock).pick(names, 2)


def test_partition_driver_second_flap_set_keeps_earlier_phase():
    """Registering a second flap set must not rephase the first: each
    name's cycle anchors on its own registration time."""
    clock = FakeClock()
    store = ObjectStore()
    cluster = HollowCluster(store, 2, clock=clock, zones=1)
    driver = PartitionDriver(cluster, FaultSchedule(0), clock=clock)
    a, b = cluster.nodes[0].name, cluster.nodes[1].name
    driver.flap([a], down_seconds=30.0, up_seconds=30.0)
    clock.advance(45.0)
    driver.step()
    assert cluster.nodes[0].alive  # a is mid-UP-phase
    driver.flap([b], down_seconds=10.0, up_seconds=10.0)
    assert cluster.nodes[0].alive  # a's phase unchanged by b's registration
    assert not cluster.nodes[1].alive


def test_partition_driver_flap_follows_injected_clock():
    clock = FakeClock()
    store = ObjectStore()
    cluster = HollowCluster(store, 2, clock=clock, zones=1)
    driver = PartitionDriver(cluster, FaultSchedule(0), clock=clock)
    name = cluster.nodes[0].name
    driver.flap([name], down_seconds=10.0, up_seconds=5.0)
    assert not cluster.nodes[0].alive        # phase 0: down
    clock.advance(12.0)
    driver.step()
    assert cluster.nodes[0].alive            # up window
    clock.advance(5.0)
    driver.step()
    assert not cluster.nodes[0].alive        # next cycle's down window
    assert cluster.nodes[1].alive            # bystander untouched


# --- CLI: get nodes ZONE column + nodehealth --------------------------------------


def test_cli_get_nodes_ready_zone_taints_columns():
    store = ObjectStore()
    _mk_node(store, "n0", zone="zone-a")
    node = store.get("Node", "", "n0")
    node.status.conditions.append({"type": "Ready", "status": "Unknown"})
    node.spec.taints.append(v1.Taint(key=UNREACHABLE_TAINT,
                                     effect=v1.TAINT_NO_EXECUTE))
    store.update("Node", node)
    out = Kubectl(store).get("nodes")
    head, row = out.splitlines()[0], out.splitlines()[1]
    for col in ("READY", "ZONE", "TAINTS"):
        assert col in head
    assert "Unknown" in row and "zone-a" in row
    assert f"{UNREACHABLE_TAINT}:NoExecute" in row


def test_cli_nodehealth_live_and_metrics_paths():
    clock = FakeClock()
    store = ObjectStore()
    _mk_zone(store, "zone-a", 4)
    store.create("Pod", _pod("p0", "zone-a-n0"))
    ctrl = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    clock.advance(100.0)  # whole (4-node) zone dark → FullDisruption
    ctrl.sync_once()
    k = Kubectl(store)
    live = k.nodehealth(controller=ctrl)
    assert "zone-a" in live and "FullDisruption" in live
    assert "EVICTION-QUEUE" in live
    # metrics path renders the same zone state from the emitted series
    # (what `ktpu nodehealth --server` parses out of /metrics)
    via_metrics = k.nodehealth()
    assert "zone-a" in via_metrics and "FullDisruption" in via_metrics


def test_cli_nodehealth_unlabeled_zone_survives_metrics_roundtrip():
    """Nodes without a zone label aggregate under zone "" — whose label
    value the text exposition drops; the --server parse path must still
    show the zone's real state, not a default Normal."""
    from kubernetes_tpu.metrics.registry import (
        default_registry, parse_text, render_text)

    clock = FakeClock()
    store = ObjectStore()
    for i in range(4):
        _mk_node(store, f"n{i}")          # NO zone label
        _lease(store, f"n{i}", 0.0)
    ctrl = NodeLifecycleController(store, grace_period=40.0, clock=clock)
    clock.advance(100.0)                   # all 4 dark → FullDisruption
    ctrl.sync_once()
    parsed = parse_text(render_text(default_registry))
    out = Kubectl(store).nodehealth(metrics=parsed)
    row = next(l for l in out.splitlines() if l.startswith("<none>"))
    assert "FullDisruption" in row


# --- serialization ----------------------------------------------------------------


def test_taint_time_added_roundtrips():
    from kubernetes_tpu.api.scheme import default_scheme
    from kubernetes_tpu.api.serialize import roundtrips, to_manifest

    scheme = default_scheme()
    node = make_node().name("n0").obj()
    node.spec.taints.append(v1.Taint(key=UNREACHABLE_TAINT,
                                     effect=v1.TAINT_NO_EXECUTE,
                                     time_added=123.5))
    manifest = to_manifest(node, scheme)
    assert manifest["spec"]["taints"][0]["timeAdded"] == 123.5
    assert roundtrips(node, scheme)
