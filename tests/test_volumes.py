"""Volume plugins through the full scheduler (reference scenarios from
volumebinding/volumezone/nodevolumelimits/volumerestrictions tests)."""

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod


def mk_pv(name, storage="10Gi", sc="", node_values=None, labels=None):
    pv = v1.PersistentVolume(capacity={"storage": storage}, storage_class_name=sc)
    pv.metadata.name = name
    pv.metadata.labels = dict(labels or {})
    if node_values:
        pv.node_affinity = v1.NodeSelector(node_selector_terms=[
            v1.NodeSelectorTerm(match_expressions=[
                v1.NodeSelectorRequirement(
                    key="kubernetes.io/hostname", operator=v1.OP_IN,
                    values=list(node_values),
                )
            ])
        ])
    return pv


def mk_pvc(name, ns="default", sc="", volume_name="", storage="5Gi"):
    pvc = v1.PersistentVolumeClaim(
        volume_name=volume_name, storage_class_name=sc, requested_storage=storage
    )
    pvc.metadata.name = name
    pvc.metadata.namespace = ns
    return pvc


def test_wait_for_first_consumer_binding():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    sc = v1.StorageClass(volume_binding_mode=v1.VOLUME_BINDING_WAIT)
    sc.metadata.name = "local"
    store.create("StorageClass", sc)
    store.create("Node", make_node().name("n0").obj())
    store.create("Node", make_node().name("n1").obj())
    # a local PV only available on n1
    store.create("PersistentVolume", mk_pv("pv1", sc="local", node_values=["n1"]))
    store.create("PersistentVolumeClaim", mk_pvc("data", sc="local"))
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1"}).pvc("data").obj())
    stats = sched.run_until_idle()
    assert stats.scheduled == 1
    assert store.get("Pod", "default", "p").spec.node_name == "n1"
    # binding persisted at PreBind
    assert store.get("PersistentVolumeClaim", "default", "data").volume_name == "pv1"
    assert store.get("PersistentVolume", "", "pv1").claim_ref == "default/data"


def test_unbound_immediate_pvc_unschedulable():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    store.create("Node", make_node().name("n0").obj())
    store.create("PersistentVolumeClaim", mk_pvc("data"))  # no class → immediate
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1"}).pvc("data").obj())
    stats = sched.run_until_idle()
    assert stats.unschedulable == 1


def test_bound_pv_node_affinity_gates_nodes():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    store.create("Node", make_node().name("n0").obj())
    store.create("Node", make_node().name("n1").obj())
    pv = mk_pv("pv1", node_values=["n0"])
    pv.claim_ref = "default/data"
    store.create("PersistentVolume", pv)
    store.create("PersistentVolumeClaim", mk_pvc("data", volume_name="pv1"))
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1"}).pvc("data").obj())
    sched.run_until_idle()
    assert store.get("Pod", "default", "p").spec.node_name == "n0"


def test_volume_zone_filter():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    store.create("Node", make_node().name("east")
                 .label("topology.kubernetes.io/zone", "us-east-1a").obj())
    store.create("Node", make_node().name("west")
                 .label("topology.kubernetes.io/zone", "us-west-1a").obj())
    pv = mk_pv("pv1", labels={"topology.kubernetes.io/zone": "us-east-1a"})
    pv.claim_ref = "default/data"
    store.create("PersistentVolume", pv)
    store.create("PersistentVolumeClaim", mk_pvc("data", volume_name="pv1"))
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1"}).pvc("data").obj())
    sched.run_until_idle()
    assert store.get("Pod", "default", "p").spec.node_name == "east"


def test_volume_restrictions_same_gce_pd():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    store.create("Node", make_node().name("n0").obj())
    store.create("Node", make_node().name("n1").obj())
    running = make_pod().name("holder").uid("holder").namespace("default").req({"cpu": "1"}).node("n0").obj()
    running.spec.volumes.append(v1.Volume(name="d", gce_pd_name="disk-1"))
    store.create("Pod", running)
    p = make_pod().name("p").uid("p").namespace("default").req({"cpu": "1"}).obj()
    p.spec.volumes.append(v1.Volume(name="d", gce_pd_name="disk-1"))
    store.create("Pod", p)
    sched.run_until_idle()
    assert store.get("Pod", "default", "p").spec.node_name == "n1"


def test_node_volume_limits():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    store.create("Node", make_node().name("full").obj())
    store.create("Node", make_node().name("free").obj())
    csin = v1.CSINode(driver_limits={"ebs.csi.aws.com": 1})
    csin.metadata.name = "full"
    store.create("CSINode", csin)
    holder = make_pod().name("holder").uid("holder").namespace("default").req({"cpu": "1"}).node("full").obj()
    holder.spec.volumes.append(v1.Volume(name="v", aws_ebs_volume_id="vol-1"))
    store.create("Pod", holder)
    p = make_pod().name("p").uid("p").namespace("default").req({"cpu": "1"}).obj()
    p.spec.volumes.append(v1.Volume(name="v", aws_ebs_volume_id="vol-2"))
    store.create("Pod", p)
    sched.run_until_idle()
    assert store.get("Pod", "default", "p").spec.node_name == "free"
