"""Volume plugins through the full scheduler (reference scenarios from
volumebinding/volumezone/nodevolumelimits/volumerestrictions tests)."""

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod


def mk_pv(name, storage="10Gi", sc="", node_values=None, labels=None):
    pv = v1.PersistentVolume(capacity={"storage": storage}, storage_class_name=sc)
    pv.metadata.name = name
    pv.metadata.labels = dict(labels or {})
    if node_values:
        pv.node_affinity = v1.NodeSelector(node_selector_terms=[
            v1.NodeSelectorTerm(match_expressions=[
                v1.NodeSelectorRequirement(
                    key="kubernetes.io/hostname", operator=v1.OP_IN,
                    values=list(node_values),
                )
            ])
        ])
    return pv


def mk_pvc(name, ns="default", sc="", volume_name="", storage="5Gi"):
    pvc = v1.PersistentVolumeClaim(
        volume_name=volume_name, storage_class_name=sc, requested_storage=storage
    )
    pvc.metadata.name = name
    pvc.metadata.namespace = ns
    return pvc


def test_wait_for_first_consumer_binding():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    sc = v1.StorageClass(volume_binding_mode=v1.VOLUME_BINDING_WAIT)
    sc.metadata.name = "local"
    store.create("StorageClass", sc)
    store.create("Node", make_node().name("n0").obj())
    store.create("Node", make_node().name("n1").obj())
    # a local PV only available on n1
    store.create("PersistentVolume", mk_pv("pv1", sc="local", node_values=["n1"]))
    store.create("PersistentVolumeClaim", mk_pvc("data", sc="local"))
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1"}).pvc("data").obj())
    stats = sched.run_until_idle()
    assert stats.scheduled == 1
    assert store.get("Pod", "default", "p").spec.node_name == "n1"
    # binding persisted at PreBind
    assert store.get("PersistentVolumeClaim", "default", "data").volume_name == "pv1"
    assert store.get("PersistentVolume", "", "pv1").claim_ref == "default/data"


def test_unbound_immediate_pvc_unschedulable():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    store.create("Node", make_node().name("n0").obj())
    store.create("PersistentVolumeClaim", mk_pvc("data"))  # no class → immediate
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1"}).pvc("data").obj())
    stats = sched.run_until_idle()
    assert stats.unschedulable == 1


def test_bound_pv_node_affinity_gates_nodes():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    store.create("Node", make_node().name("n0").obj())
    store.create("Node", make_node().name("n1").obj())
    pv = mk_pv("pv1", node_values=["n0"])
    pv.claim_ref = "default/data"
    store.create("PersistentVolume", pv)
    store.create("PersistentVolumeClaim", mk_pvc("data", volume_name="pv1"))
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1"}).pvc("data").obj())
    sched.run_until_idle()
    assert store.get("Pod", "default", "p").spec.node_name == "n0"


def test_volume_zone_filter():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    store.create("Node", make_node().name("east")
                 .label("topology.kubernetes.io/zone", "us-east-1a").obj())
    store.create("Node", make_node().name("west")
                 .label("topology.kubernetes.io/zone", "us-west-1a").obj())
    pv = mk_pv("pv1", labels={"topology.kubernetes.io/zone": "us-east-1a"})
    pv.claim_ref = "default/data"
    store.create("PersistentVolume", pv)
    store.create("PersistentVolumeClaim", mk_pvc("data", volume_name="pv1"))
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1"}).pvc("data").obj())
    sched.run_until_idle()
    assert store.get("Pod", "default", "p").spec.node_name == "east"


def test_volume_restrictions_same_gce_pd():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    store.create("Node", make_node().name("n0").obj())
    store.create("Node", make_node().name("n1").obj())
    running = make_pod().name("holder").uid("holder").namespace("default").req({"cpu": "1"}).node("n0").obj()
    running.spec.volumes.append(v1.Volume(name="d", gce_pd_name="disk-1"))
    store.create("Pod", running)
    p = make_pod().name("p").uid("p").namespace("default").req({"cpu": "1"}).obj()
    p.spec.volumes.append(v1.Volume(name="d", gce_pd_name="disk-1"))
    store.create("Pod", p)
    sched.run_until_idle()
    assert store.get("Pod", "default", "p").spec.node_name == "n1"


def test_node_volume_limits():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    store.create("Node", make_node().name("full").obj())
    store.create("Node", make_node().name("free").obj())
    csin = v1.CSINode(driver_limits={"ebs.csi.aws.com": 1})
    csin.metadata.name = "full"
    store.create("CSINode", csin)
    holder = make_pod().name("holder").uid("holder").namespace("default").req({"cpu": "1"}).node("full").obj()
    holder.spec.volumes.append(v1.Volume(name="v", aws_ebs_volume_id="vol-1"))
    store.create("Pod", holder)
    p = make_pod().name("p").uid("p").namespace("default").req({"cpu": "1"}).obj()
    p.spec.volumes.append(v1.Volume(name="v", aws_ebs_volume_id="vol-2"))
    store.create("Pod", p)
    sched.run_until_idle()
    assert store.get("Pod", "default", "p").spec.node_name == "free"


def mk_sc(name, mode=None, provisioner="", zones=None):
    sc = v1.StorageClass(
        volume_binding_mode=mode or v1.VOLUME_BINDING_WAIT,
        provisioner=provisioner,
    )
    sc.metadata.name = name
    if zones:
        sc.allowed_topologies = v1.NodeSelector(node_selector_terms=[
            v1.NodeSelectorTerm(match_expressions=[
                v1.NodeSelectorRequirement(
                    key="topology.kubernetes.io/zone", operator=v1.OP_IN,
                    values=list(zones),
                )
            ])
        ])
    return sc


def test_smallest_fitting_pv_chosen():
    """Capacity-aware matching (volume.FindMatchingVolume): the SMALLEST PV
    that fits is bound, leaving larger volumes for larger claims."""
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    store.create("StorageClass", mk_sc("local"))
    store.create("Node", make_node().name("n0").obj())
    for name, size in [("pv-big", "100Gi"), ("pv-small", "10Gi"), ("pv-mid", "50Gi")]:
        store.create("PersistentVolume", mk_pv(name, storage=size, sc="local"))
    store.create("PersistentVolumeClaim", mk_pvc("c0", sc="local", storage="5Gi"))
    store.create(
        "Pod",
        make_pod().name("p").uid("p").namespace("default")
        .req({"cpu": "1"}).pvc("c0").obj(),
    )
    sched.run_until_idle()
    pvc = store.get("PersistentVolumeClaim", "default", "c0")
    assert pvc.volume_name == "pv-small"


def test_provisioning_respects_allowed_topologies():
    """Topology-aware dynamic provisioning: only nodes inside the class's
    AllowedTopologies may host the pod, and the provisioned PV is pinned to
    the selected node's topology segment (binder.go checkVolumeProvisions)."""
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    store.create("StorageClass",
                 mk_sc("zonal", provisioner="ebs.csi", zones=["z1"]))
    store.create("Node", make_node().name("n0")
                 .label("topology.kubernetes.io/zone", "z0").obj())
    store.create("Node", make_node().name("n1")
                 .label("topology.kubernetes.io/zone", "z1").obj())
    store.create("PersistentVolumeClaim", mk_pvc("c0", sc="zonal"))
    store.create(
        "Pod",
        make_pod().name("p").uid("p").namespace("default")
        .req({"cpu": "1"}).pvc("c0").obj(),
    )
    sched.run_until_idle()
    pod = store.get("Pod", "default", "p")
    assert pod.spec.node_name == "n1"
    pvc = store.get("PersistentVolumeClaim", "default", "c0")
    pv = store.get("PersistentVolume", "", pvc.volume_name)
    assert pv.node_affinity is not None
    from kubernetes_tpu.api.labels import match_node_selector

    assert match_node_selector(pv.node_affinity, store.get("Node", "", "n1"))
    assert not match_node_selector(pv.node_affinity, store.get("Node", "", "n0"))


def test_multi_pvc_partial_bind_rollback():
    """Reserve failure on the SECOND claim unassumes the first claim's PV
    (AssumePodVolumes rollback), so another pod can still take it."""
    from kubernetes_tpu.plugins.volumes import StoreVolumeListers, VolumeBindingPlugin

    store = ObjectStore()
    listers = StoreVolumeListers(store)
    plug = VolumeBindingPlugin(listers)
    store.create("StorageClass", mk_sc("local"))
    store.create("Node", make_node().name("n0").obj())
    store.create("PersistentVolume", mk_pv("pv0", storage="10Gi", sc="local"))
    store.create("PersistentVolumeClaim", mk_pvc("c0", sc="local", storage="5Gi"))
    # c1 wants more than any PV offers → reserve must fail after assuming pv0
    store.create("PersistentVolumeClaim", mk_pvc("c1", sc="local", storage="500Gi"))
    pod = (make_pod().name("p").uid("p").namespace("default")
           .req({"cpu": "1"}).pvc("c0").pvc("c1").obj())
    status = plug.reserve(None, pod, "n0")
    assert status is not None and not status.is_success()
    plug.unreserve(None, pod, "n0")
    assert plug._assumed_pv == {}
    assert plug._decisions == {}


def test_volume_binding_parity_randomized():
    """Device-path VolumeBinding masks == oracle.volume_binding_feasible over
    randomized volume clusters (bound PVs, WFC static PVs, provisioned
    classes with topologies, immediate classes)."""
    import numpy as np

    from kubernetes_tpu.oracle import volume_binding_feasible
    from kubernetes_tpu.plugins.volumes import StoreVolumeListers, VolumeBindingPlugin
    from kubernetes_tpu.state.cache import Cache, Snapshot
    from kubernetes_tpu.state.encoding import ClusterEncoder
    from kubernetes_tpu.framework.podbatch import PodBatchCompiler

    rng = np.random.default_rng(21)
    for trial in range(4):
        store = ObjectStore()
        listers = StoreVolumeListers(store)
        zones = ["z0", "z1", "z2"]
        cache = Cache()
        nodes = []
        for i in range(8):
            nd = (make_node().name(f"n{i}")
                  .label("topology.kubernetes.io/zone", zones[i % 3])
                  .label("kubernetes.io/hostname", f"n{i}")
                  .capacity({"cpu": "8", "memory": "16Gi", "pods": "110"}).obj())
            nodes.append(nd)
            store.create("Node", nd)
            cache.add_node(nd)
        store.create("StorageClass", mk_sc("wfc"))
        store.create("StorageClass",
                     mk_sc("prov", provisioner="x.csi",
                           zones=[zones[int(rng.integers(3))]]))
        store.create("StorageClass", mk_sc("imm", mode=v1.VOLUME_BINDING_IMMEDIATE))
        for j in range(6):
            pin = [f"n{int(rng.integers(8))}"] if rng.random() < 0.7 else None
            store.create("PersistentVolume", mk_pv(
                f"pv{j}", storage=f"{int(rng.choice([5, 20, 80]))}Gi",
                sc="wfc", node_values=pin,
            ))
        pods = []
        for k in range(8):
            w = (make_pod().name(f"p{k}").uid(f"p{k}-{trial}")
                 .namespace("default").req({"cpu": "1"}))
            kind = k % 4
            if kind == 0:  # static WFC claim
                store.create("PersistentVolumeClaim", mk_pvc(
                    f"c{k}", sc="wfc",
                    storage=f"{int(rng.choice([1, 10, 50]))}Gi"))
                w = w.pvc(f"c{k}")
            elif kind == 1:  # provisioned, topology-limited
                store.create("PersistentVolumeClaim", mk_pvc(f"c{k}", sc="prov"))
                w = w.pvc(f"c{k}")
            elif kind == 2:  # immediate-mode unbound → unschedulable
                store.create("PersistentVolumeClaim", mk_pvc(f"c{k}", sc="imm"))
                w = w.pvc(f"c{k}")
            # kind 3: no volumes
            pods.append(w.obj())
        snap = Snapshot()
        cache.update_snapshot(snap)
        enc = ClusterEncoder()
        comp = PodBatchCompiler(enc)
        batch = comp.compile(pods)
        enc.full_sync(snap)
        plug = VolumeBindingPlugin(listers)
        host_aux = plug.host_prepare(batch, snap, enc)
        mask = (np.ones((batch.size, enc._n), bool) if host_aux is None
                else host_aux["mask"])
        rows = enc.node_rows
        for i, pod in enumerate(pods):
            for nd in nodes:
                want = volume_binding_feasible(pod, nd, listers)
                got = bool(mask[i, rows[nd.metadata.name]])
                assert got == want, (
                    f"trial {trial} pod p{i} node {nd.metadata.name}: "
                    f"device={got} oracle={want}"
                )
