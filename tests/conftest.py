"""Test config: force an 8-device virtual CPU platform BEFORE jax initializes.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU mesh (xla_force_host_platform_device_count), mirroring how the driver
dry-runs the multi-chip path.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# The axon TPU-tunnel environment pins JAX_PLATFORMS; JAX_PLATFORM_NAME still wins.
os.environ["JAX_PLATFORM_NAME"] = "cpu"
