"""Test config: force an 8-device virtual CPU platform BEFORE jax initializes.

Multi-chip hardware is not available in CI; sharding tests run on a virtual
8-device CPU mesh (xla_force_host_platform_device_count), mirroring how the driver
dry-runs the multi-chip path.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# The axon TPU-tunnel environment pins JAX_PLATFORMS; JAX_PLATFORM_NAME still wins.
os.environ["JAX_PLATFORM_NAME"] = "cpu"

# Persistent compile cache: identical programs (same shapes across tests/runs)
# compile once per machine, not once per test.
import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

# The axon sitecustomize imports jax BEFORE this conftest runs, so the env vars
# above are too late for jax.config's import-time reads — force via config.
# (XLA_FLAGS is still read lazily at CPU-client creation, so the device count
# takes effect as long as no backend has initialized yet.)
for _name, _val in (("jax_platforms", "cpu"), ("jax_platform_name", "cpu")):
    try:
        jax.config.update(_name, _val)
    except Exception:
        pass


def pytest_configure(config):
    # tier-1 runs with -m 'not slow' (ROADMAP.md): long chaos soaks and
    # other wall-clock-heavy batteries opt out of the 870s budget here and
    # run via their tools/ entry points (e.g. tools/chaos_soak.py)
    config.addinivalue_line(
        "markers", "slow: long soak/perf tests excluded from tier-1")
