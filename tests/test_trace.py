"""Span tracer battery (ISSUE-14): Tracer/Span semantics, exporters, the
scheduler's attempt span tree (sync + deep pipeline, cross-thread context
handoff), per-pod phase records tiling the attempt metric exactly,
WAL/apiserver spans, determinism under the injected clock, the legacy
log_if_long wrap bugfix, and the `ktpu trace` / `ktpu slo` verbs."""

import json
import logging
import threading

import pytest

from kubernetes_tpu.cli import Kubectl
from kubernetes_tpu.component_base.trace import (
    NOOP_SPAN,
    NOOP_TRACER,
    SPAN_CATALOG,
    ChromeTraceExporter,
    InMemoryExporter,
    ThresholdLogExporter,
    Trace,
    Tracer,
    render_tree,
)
from kubernetes_tpu.metrics import scheduler_metrics as m
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# --- L0: tracer/span semantics ------------------------------------------------


def test_span_parent_links_attributes_events_and_clock():
    clk = FakeClock()
    ring = InMemoryExporter()
    tr = Tracer(clock=clk, exporters=[ring])
    root = tr.span("attempt", cycle=3)
    clk.advance(0.5)
    child = tr.span("dispatch", parent=root)
    child.event("enqueued", rows=4)
    clk.advance(0.25)
    child.finish()
    root.set(pods=8).finish()
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert root.parent_id is None
    assert child.start == 1000.5 and child.end == 1000.75
    assert root.duration() == 0.75
    assert root.attrs == {"cycle": 3, "pods": 8}
    assert child.events[0][0] == "enqueued" and child.events[0][2] == {"rows": 4}
    # exporter saw both, child first (finish order)
    assert [s.name for s in ring.spans()] == ["dispatch", "attempt"]


def test_span_context_handoff_and_retroactive_start():
    clk = FakeClock()
    tr = Tracer(clock=clk)
    root = tr.span("attempt")
    ctx = root.context()
    # a different thread parents via the explicit context value
    out = {}

    def bg():
        out["span"] = tr.span("device_wait", parent=ctx, start=999.0)
        out["span"].finish(end=1001.0)

    t = threading.Thread(target=bg)
    t.start()
    t.join()
    s = out["span"]
    assert s.trace_id == root.trace_id and s.parent_id == root.span_id
    assert s.start == 999.0 and s.end == 1001.0
    assert s.thread != root.thread


def test_context_manager_and_idempotent_finish():
    clk = FakeClock()
    ring = InMemoryExporter()
    tr = Tracer(clock=clk, exporters=[ring])
    with tr.span("bind") as s:
        clk.advance(0.1)
    end = s.end
    s.finish()  # second finish is a no-op
    assert s.end == end
    assert len(ring.spans()) == 1


def test_noop_tracer_is_disabled_and_allocation_free():
    assert not NOOP_TRACER.enabled
    s = NOOP_TRACER.span("attempt", pods=4)
    assert s is NOOP_SPAN
    assert s.context() is None
    assert s.set(x=1) is s
    s.event("e")
    s.finish()
    with s:
        pass
    # a Tracer built disabled behaves the same
    assert Tracer(enabled=False).span("attempt") is NOOP_SPAN


def test_exporter_fault_does_not_break_finish(caplog):
    class Boom:
        def export(self, span):
            raise RuntimeError("boom")

    ring = InMemoryExporter()
    tr = Tracer(clock=FakeClock(), exporters=[Boom(), ring])
    with caplog.at_level(logging.WARNING, logger="kubernetes_tpu.trace"):
        tr.span("bind").finish()
    assert len(ring.spans()) == 1  # later exporters still ran
    assert "Boom" in caplog.text


def test_in_memory_ring_bound_and_trees():
    clk = FakeClock()
    ring = InMemoryExporter(max_spans=8)
    tr = Tracer(clock=clk, exporters=[ring])
    for i in range(6):
        root = tr.span("attempt", i=i)
        tr.span("dispatch", parent=root).finish()
        root.finish()
    assert len(ring.spans()) == 8  # bounded: oldest evicted
    trees = ring.trees(last=2, root_name="attempt")
    assert len(trees) == 2
    root, children = trees[-1]
    assert root.attrs["i"] == 5
    assert [c.name for c in children.get(root.span_id, [])] == ["dispatch"]


def test_chrome_trace_exporter_writes_loadable_json(tmp_path):
    path = str(tmp_path / "t.trace.jsonl")
    clk = FakeClock()
    ex = ChromeTraceExporter(path)
    tr = Tracer(clock=clk, exporters=[ex])
    root = tr.span("attempt", pods=2)
    clk.advance(0.002)
    tr.span("dispatch", parent=root).finish()
    root.finish()
    ex.close()
    with open(path) as f:
        events = json.load(f)  # the array terminates cleanly after close()
    names = [e["name"] for e in events]
    assert "attempt" in names and "dispatch" in names
    disp = next(e for e in events if e["name"] == "dispatch")
    assert disp["ph"] == "X" and disp["dur"] == pytest.approx(0.0)
    att = next(e for e in events if e["name"] == "attempt")
    assert att["dur"] == pytest.approx(2000.0)  # µs
    assert att["args"]["pods"] == 2
    # one JSON value per line: loadable line-wise too (JSONL contract)
    with open(path) as f:
        lines = [ln.rstrip(",\n") for ln in f if ln.strip() not in "[]"]
    assert all(json.loads(ln) for ln in lines)


def test_threshold_exporter_logs_only_slow_trees(caplog):
    clk = FakeClock()
    tr = Tracer(clock=clk, exporters=[ThresholdLogExporter(threshold=0.1)])
    with caplog.at_level(logging.INFO, logger="kubernetes_tpu.trace"):
        fast = tr.span("attempt", kind="fast")
        tr.span("dispatch", parent=fast).finish()
        fast.finish()
        assert "fast" not in caplog.text
        slow = tr.span("attempt", kind="slow")
        child = tr.span("dispatch", parent=slow)
        clk.advance(0.25)
        child.finish()
        slow.finish()
    assert "kind=slow" in caplog.text and "dispatch" in caplog.text


def test_render_tree_nests_and_reports_offsets():
    clk = FakeClock()
    tr = Tracer(clock=clk)
    root = tr.span("attempt")
    d = tr.span("dispatch", parent=root)
    clk.advance(0.01)
    inner = tr.span("snapshot", parent=d)
    clk.advance(0.02)
    inner.finish()
    d.finish()
    root.finish()
    txt = render_tree(root, [root, d, inner])
    lines = txt.splitlines()
    assert lines[0].startswith('span "attempt"')
    assert lines[1].strip().startswith("- dispatch")
    assert lines[2].strip().startswith("- snapshot +10.0ms")


# --- L1: scheduler attempt tree -----------------------------------------------


def _cluster(store, nodes=4, cpu="8"):
    for i in range(nodes):
        store.create(
            "Node",
            make_node().name(f"n{i}")
            .capacity({"cpu": cpu, "memory": "16Gi", "pods": "32"}).obj())


def _pods(store, n, cpu="1"):
    for i in range(n):
        store.create(
            "Pod",
            make_pod().name(f"p{i}").uid(f"p{i}").namespace("default")
            .req({"cpu": cpu}).obj())


def _run_traced(pipeline: bool, n_pods: int = 6):
    store = ObjectStore()
    ring = InMemoryExporter()
    tr = Tracer(exporters=[ring])
    s = TPUScheduler(store, batch_size=8, pipeline=pipeline, tracer=tr)
    _cluster(store)
    _pods(store, n_pods)
    stats = s.run_until_idle()
    s.close()
    return stats, ring


def test_attempt_tree_shape_and_records_sync():
    stats, ring = _run_traced(pipeline=False)
    assert stats.scheduled == 6
    trees = ring.trees(root_name="attempt")
    assert trees, "no attempt root spans recorded"
    root, children = trees[0]
    kids = [c.name for c in children.get(root.span_id, [])]
    assert kids == ["queue_wait", "dispatch", "device_wait", "complete",
                    "bind_phase"]
    disp = next(c for c in children[root.span_id] if c.name == "dispatch")
    sub = [c.name for c in children.get(disp.span_id, [])]
    assert sub == ["snapshot", "compile", "host_prepare", "device_enqueue"]
    bp = next(c for c in children[root.span_id] if c.name == "bind_phase")
    binds = [c for c in children.get(bp.span_id, []) if c.name == "bind"]
    assert len(binds) == 6
    assert all(b.attrs["outcome"] == "bound" for b in binds)
    # every span name emitted is in the catalog
    for s in ring.spans():
        assert s.name in SPAN_CATALOG


def test_pod_phase_records_tile_attempt_exactly():
    _stats, ring = _run_traced(pipeline=False)
    recs = ring.attempt_records()
    assert len(recs) == 6
    for r in recs:
        assert r["outcome"] == "scheduled"
        assert r["dispatch"] >= 0 and r["device"] >= 0 and r["bind"] >= 0
        # the three tiling phases sum EXACTLY to the attempt total
        assert r["dispatch"] + r["device"] + r["bind"] == pytest.approx(
            r["total"], abs=1e-12)


def test_phase_histograms_observed_and_slo_renders():
    n0 = m.attempt_phase_duration.count(("dispatch",))
    _stats, _ring = _run_traced(pipeline=False)
    assert m.attempt_phase_duration.count(("dispatch",)) == n0 + 6
    assert m.attempt_phase_duration.count(("device",)) >= 6
    assert m.attempt_phase_duration.count(("bind",)) >= 6
    assert m.attempt_phase_duration.count(("queue_wait",)) >= 6
    out = Kubectl(ObjectStore()).slo()
    assert "dispatch" in out and "device" in out and "bind" in out
    assert "coverage" in out


def test_slo_from_rendered_metrics_text():
    """The --server path: /metrics exposition → parse_text → bucket
    quantiles — depends on the registry's bucket round-trip."""
    from kubernetes_tpu.metrics.registry import (default_registry,
                                                 parse_text, render_text)

    _stats, _ring = _run_traced(pipeline=False)
    parsed = parse_text(render_text(default_registry))
    out = Kubectl(ObjectStore()).slo(metrics=parsed)
    assert "dispatch" in out and "P99-MS" in out
    # remote and live views agree on the p50 they print
    live = Kubectl(ObjectStore()).slo()
    remote_rows = {ln.split()[0]: ln.split()[1] for ln in out.splitlines()
                   if ln and not ln.startswith(("PHASE", "attempt"))}
    live_rows = {ln.split()[0]: ln.split()[1] for ln in live.splitlines()
                 if ln and not ln.startswith(("PHASE", "attempt"))}
    assert remote_rows == live_rows


def test_deep_pipeline_cross_thread_device_wait_span():
    stats, ring = _run_traced(pipeline=True, n_pods=12)
    assert stats.scheduled == 12
    trees = ring.trees(root_name="attempt")
    assert trees
    for root, children in trees:
        kids = [c.name for c in children.get(root.span_id, [])]
        assert "device_wait" in kids and "bind_phase" in kids
        dw = next(c for c in children[root.span_id]
                  if c.name == "device_wait")
        # emitted from the background fetch thread via the explicit
        # SpanContext handoff — not the dispatch thread
        assert dw.thread != root.thread
        assert dw.trace_id == root.trace_id


def test_default_tracer_records_nothing():
    store = ObjectStore()
    s = TPUScheduler(store, batch_size=8)  # NOOP tracer
    assert s.tracer is NOOP_TRACER
    _cluster(store)
    _pods(store, 3)
    stats = s.run_until_idle()
    s.close()
    assert stats.scheduled == 3


def test_span_tree_shape_deterministic_under_injected_clock():
    """Same seed (same store contents, same injected clocks) → identical
    span tree SHAPE (names, structure, per-pod record outcomes)."""

    def run():
        clk = FakeClock()
        store = ObjectStore()
        ring = InMemoryExporter()
        tr = Tracer(clock=clk, exporters=[ring])
        s = TPUScheduler(store, batch_size=8, clock=clk, tracer=tr,
                         batch_wait=0.0)
        _cluster(store)
        _pods(store, 6)
        s.schedule_cycle()
        s.close()

        def shape(root, children):
            return (root.name, tuple(
                shape(c, children) for c in children.get(root.span_id, ())))

        return ([shape(r, ch) for r, ch in ring.trees()],
                [(r["pod"], r["outcome"]) for r in ring.attempt_records()])

    assert run() == run()


def test_legacy_trace_wraps_whole_attempt(monkeypatch):
    """ISSUE-14 bugfix: log_if_long fires once per batch AFTER the bind
    phase, with the fetch/bind steps present — not at dispatch return."""
    calls = []
    orig = Trace.log_if_long

    def spy(self, threshold=0.1):
        calls.append([s.name for s in self.steps])
        return orig(self, threshold)

    monkeypatch.setattr(Trace, "log_if_long", spy)
    store = ObjectStore()
    s = TPUScheduler(store, batch_size=8, pipeline=True)
    _cluster(store)
    _pods(store, 4)
    s.run_until_idle()
    s.close()
    assert calls, "log_if_long never ran"
    for steps in calls:
        assert "Device dispatch" in steps
        assert "Decision fetch" in steps
        assert "Binding cycle" in steps  # i.e. called after bind, not dispatch


# --- L2: WAL + apiserver spans ------------------------------------------------


def test_wal_append_and_fsync_spans_link_to_attempt_tree(tmp_path):
    from kubernetes_tpu.sim.wal import WriteAheadLog

    ring = InMemoryExporter()
    tr = Tracer(exporters=[ring])
    wal = WriteAheadLog(str(tmp_path / "wal.log"), fsync_every=1, tracer=tr)
    store = ObjectStore(wal=wal)
    s = TPUScheduler(store, batch_size=8, tracer=tr)
    _cluster(store)
    _pods(store, 2)
    stats = s.run_until_idle()
    s.close()
    assert stats.scheduled == 2
    spans = ring.spans()
    appends = [x for x in spans if x.name == "wal_append"]
    fsyncs = [x for x in spans if x.name == "wal_fsync"]
    assert appends and fsyncs
    roots = {x.trace_id for x in spans
             if x.name == "attempt" and x.parent_id is None}
    bind_appends = [x for x in appends if x.attrs.get("op") == "bind"]
    assert bind_appends
    # the explicit trace_parent handoff landed them INSIDE attempt trees
    assert all(x.trace_id in roots for x in bind_appends)
    # a direct store write (no scheduler context) records a root span
    store.create("Pod", make_pod().name("solo").uid("solo")
                 .namespace("default").req({"cpu": "1"}).obj())
    solo = [x for x in ring.spans()
            if x.name == "wal_append" and x.attrs.get("op") == "create"
            and x.attrs.get("kind") == "Pod"]
    assert any(x.parent_id is None for x in solo)
    wal.close()


def test_apiserver_request_span_and_apf_wait(tmp_path):
    import urllib.request

    from kubernetes_tpu.apiserver.flowcontrol import FlowController
    from kubernetes_tpu.apiserver.server import APIServer

    ring = InMemoryExporter()
    tr = Tracer(exporters=[ring])
    store = ObjectStore()
    store.create("Node", make_node().name("n0")
                 .capacity({"cpu": "4", "memory": "8Gi", "pods": "8"}).obj())
    api = APIServer(store, tracer=tr).start()
    try:
        with urllib.request.urlopen(f"{api.url}/api/v1/nodes") as r:
            assert r.status == 200
        # health/metrics probes are NOT spanned
        with urllib.request.urlopen(f"{api.url}/healthz") as r:
            assert r.status == 200
    finally:
        api.stop()
    reqs = [s for s in ring.spans() if s.name == "apiserver_request"]
    assert len(reqs) == 1
    assert reqs[0].attrs == {"verb": "get", "path": "/api/v1/nodes"}

    # apf_wait: a seat that actually queued carries its wait out on the
    # seat, which the server turns into a child span — prove the seat
    # mechanics at the gate level (deterministic, no HTTP race)
    flow = FlowController(max_readonly_inflight=1, queue_timeout=2.0)
    seat1 = flow.admit("alice", mutating=False)
    got = {}

    def second():
        got["seat"] = flow.admit("bob", mutating=False)

    t = threading.Thread(target=second)
    t.start()
    import time as _t

    _t.sleep(0.05)
    seat1.release()
    t.join()
    assert got["seat"].waited > 0.0
    got["seat"].release()
    assert seat1.waited == 0.0  # fast path: no queue wait


def test_retrying_store_facade_without_trace_kwarg_still_binds():
    """Review regression: RetryingStore advertises trace_parent, so the
    scheduler probes True — but a wrapped facade without the kwarg must
    not be crashed by blind forwarding (every bind would TypeError into
    the transient-retry path forever)."""
    from kubernetes_tpu.chaos.retry import RetryingStore

    class Facade:
        """Minimal bind-capable store facade WITHOUT trace_parent."""

        def __init__(self, store):
            self._s = store

        def bind_pod(self, namespace, name, node_name):
            return self._s.bind_pod(namespace, name, node_name)

        def __getattr__(self, attr):
            return getattr(self._s, attr)

    inner = ObjectStore()
    store = RetryingStore(Facade(inner))
    ring = InMemoryExporter()
    s = TPUScheduler(store, batch_size=8,
                     tracer=Tracer(exporters=[ring]))
    assert s._bind_takes_trace  # the outer wrapper does take it…
    _cluster(inner)
    _pods(inner, 3)
    stats = s.run_until_idle()
    s.close()
    assert stats.scheduled == 3  # …and the facade still binds
    # and with a kwarg-capable inner store the context still flows
    store2 = RetryingStore(ObjectStore())
    assert store2.bind_pod("default", "nope", "n0") is False


def test_threshold_exporter_drops_late_children_of_flushed_traces(caplog):
    clk = FakeClock()
    ex = ThresholdLogExporter(threshold=0.1, max_traces=4)
    tr = Tracer(clock=clk, exporters=[ex])
    root = tr.span("attempt")
    clk.advance(0.2)
    root.finish()  # flushes + logs the trace
    for _ in range(8):  # late children: dropped, no dead buffer entries
        late = tr.span("permit_wait", parent=root.context())
        clk.advance(0.2)
        late.finish()
    assert ex._by_trace == {}


# --- L3: catalog/doc sync + CLI dump -----------------------------------------


def test_span_catalog_documented_in_components_md():
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "COMPONENTS.md")) as f:
        doc = f.read()
    for name in SPAN_CATALOG:
        assert f"`{name}`" in doc, (
            f"span {name!r} missing from the COMPONENTS.md span catalog")


def test_ktpu_trace_dump_renders_trees_and_pod_lines():
    _stats, ring = _run_traced(pipeline=False)
    k = Kubectl(ObjectStore())
    out = k.trace_dump(exporter=ring, last=2)
    assert 'span "attempt"' in out
    assert "- dispatch" in out and "- bind_phase" in out
    assert "pod default/p0" in out and "(scheduled)" in out
    # no exporter wired → actionable hint, not a crash
    assert "no in-process span exporter" in k.trace_dump()
    assert "no attempt spans" in k.trace_dump(exporter=InMemoryExporter())


def test_ktpu_trace_cli_verb(capsys):
    from kubernetes_tpu.cli import main

    assert main(["trace"]) == 0
    assert "no in-process span exporter" in capsys.readouterr().out
    assert main(["slo"]) in (0, None)
    out = capsys.readouterr().out
    assert "PHASE" in out or "no attempt-phase" in out
