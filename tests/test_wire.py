"""Binary wire plane: codec round-trip parity, encode-once payloads,
torn-record rejection, mixed-format WAL replay, HTTP negotiation.

The contract under test (ISSUE 19):
``scheme.decode(wire_decode(wire_encode(m))) == scheme.decode(m)`` for
every registered kind, across BOTH backends (pure Python and the native
extension), plus the serving-plane property that one write costs one
encode per codec no matter how many watchers fan out.
"""

import dataclasses
import io
import json
import random
import threading
import time

import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.api import wire
from kubernetes_tpu.api.scheme import default_scheme
from kubernetes_tpu.api.serialize import to_manifest
from kubernetes_tpu.metrics import scheduler_metrics as m
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.sim.wal import (
    WALRecord,
    WriteAheadLog,
    replay_on_boot,
    scan_records,
)
from kubernetes_tpu.sim.watchcache import WatchCache
from kubernetes_tpu.testutil import make_node, make_pod

SCHEME = default_scheme()

BACKENDS = [True] + ([False] if wire._native() is not None else [])


# --- value-level codec -------------------------------------------------------

VALUES = [
    None, True, False, 0, 1, -1, 7, -7, 127, 128, -128, 2**31, -(2**31),
    2**63 - 1, -(2**63), 0.0, -1.5, 3.14159, 1e300, "", "x", "pod",
    "üñïçødé-☃\U0001F600", "a" * 300, b"", b"\x00\xff raw",
    [], [1, 2, 3], ["a", "a", "a"], {}, {"k": "v"},
    {"kind": "Pod", "metadata": {"labels": {"app": "web", "tier": "web"}}},
    [{"deep": [{"deeper": [None, True, {"n": -42}]}]}],
    {"repeat": ["default", "default", "default-scheduler", "Pending"]},
]


@pytest.mark.parametrize("force_python", BACKENDS)
def test_value_roundtrip(force_python):
    for val in VALUES:
        blob = wire.wire_encode(val, force_python=force_python)
        assert blob[:3] == wire.WIRE_MAGIC
        assert wire.is_wire(blob)
        out = wire.wire_decode(blob, force_python=force_python)
        assert out == val, val
        assert type(out) is type(val) or isinstance(val, bool)


def test_cross_backend_byte_parity():
    """The native encoder must emit BYTE-IDENTICAL documents to the pure
    Python reference (cached bytes are shared between both backends)."""
    if wire._native() is None:
        pytest.skip("no native codec in this environment")
    for val in VALUES:
        assert wire.wire_encode(val) == wire.wire_encode(
            val, force_python=True), val
        # and each backend decodes the other's output
        blob = wire.wire_encode(val)
        assert wire.wire_decode(blob) == wire.wire_decode(
            blob, force_python=True)


def test_encode_rejects_unsupported():
    with pytest.raises((TypeError, wire.WireError)):
        wire.wire_encode(object(), force_python=True)
    with pytest.raises((ValueError, TypeError)):
        wire.wire_encode({1: "non-string key"}, force_python=True)
    with pytest.raises((OverflowError, ValueError)):
        wire.wire_encode(2**64, force_python=True)


@pytest.mark.parametrize("force_python", BACKENDS)
def test_strict_decode_rejects_torn_and_trailing(force_python):
    blob = wire.wire_encode(
        {"kind": "Pod", "items": [1, 2.5, "x", None, b"b"]},
        force_python=True)
    # every strict prefix is torn
    for cut in range(len(blob)):
        with pytest.raises(ValueError):
            wire.wire_decode(blob[:cut], force_python=force_python)
    with pytest.raises(ValueError):
        wire.wire_decode(blob + b"\x00", force_python=force_python)
    with pytest.raises(ValueError):  # JSON is never wire
        wire.wire_decode(b'{"kind": "Pod"}', force_python=force_python)
    with pytest.raises(ValueError):  # future format version
        wire.wire_decode(wire.WIRE_MAGIC + b"\x02" + blob[4:],
                         force_python=force_python)
    assert not wire.is_wire(b'{"json": true}')
    assert not wire.is_wire(b"")


# --- every registered kind, randomized ---------------------------------------

_UNICODE_POOL = ["web", "üñïçødé", "☃-snow", "data-\U0001F600",
                 "zone/a", "", "x" * 80]


def _randomize(obj, rng, depth=0):
    """Walk a dataclass instance and fill primitive fields with random
    values (property-style field population: serialize/decode are generic,
    so any value a field can hold must round-trip)."""
    if depth > 4 or not dataclasses.is_dataclass(obj):
        return
    for f in dataclasses.fields(obj):
        cur = getattr(obj, f.name, None)
        if f.name in ("resource_version", "owner_references"):
            continue
        if isinstance(cur, bool):
            setattr(obj, f.name, rng.random() < 0.5)
        elif isinstance(cur, int) and rng.random() < 0.7:
            setattr(obj, f.name, rng.randrange(-5, 10**6))
        elif isinstance(cur, float):
            setattr(obj, f.name, round(rng.uniform(0, 10**6), 3))
        elif isinstance(cur, str) and rng.random() < 0.7:
            setattr(obj, f.name, rng.choice(_UNICODE_POOL))
        elif (isinstance(cur, dict) and rng.random() < 0.5
              and f.name in ("labels", "annotations", "node_selector")):
            cur = dict(cur)
            cur[rng.choice(_UNICODE_POOL) or "k"] = rng.choice(_UNICODE_POOL)
            setattr(obj, f.name, cur)
        elif dataclasses.is_dataclass(cur):
            _randomize(cur, rng, depth + 1)
        elif isinstance(cur, list):
            for item in cur:
                _randomize(item, rng, depth + 1)


def _normalized(obj):
    d = to_manifest(obj, SCHEME)
    meta = d.setdefault("metadata", {})
    meta.pop("uid", None)  # decode regenerates when absent/falsy
    meta.pop("creationTimestamp", None)
    return d


@pytest.mark.parametrize("force_python", BACKENDS)
def test_every_registered_kind_roundtrips(force_python):
    """The tentpole contract, for all 25+ registered kinds with
    randomized field population and unicode labels, both codecs."""
    for entry in sorted(SCHEME.recognized()):
        kind = entry.split(":", 1)[1]
        rng = random.Random(entry)
        obj = SCHEME.decode({"kind": kind, "metadata": {
            "name": "obj-1", "namespace": "prod",
            "labels": {"app": "web", "ünïcode": "☃"},
            "annotations": {"note": "a" * 120},
        }})
        _randomize(obj, rng)
        manifest = to_manifest(obj, SCHEME)
        blob = wire.wire_encode(manifest, force_python=force_python)
        # value-exact round trip: the wire doc IS the manifest
        assert wire.wire_decode(blob, force_python=force_python) == manifest
        # decoded-object equivalence across codecs (the ISSUE contract)
        via_wire = SCHEME.decode(wire.wire_decode(blob))
        via_json = SCHEME.decode(json.loads(json.dumps(manifest)))
        assert _normalized(via_wire) == _normalized(via_json), kind
        if wire._native() is not None:
            assert blob == wire.wire_encode(manifest), kind


def test_defaults_present_vs_elided():
    """A manifest with defaults spelled out and one with them elided must
    decode to the same object through the wire codec."""
    elided = {"kind": "Pod", "metadata": {"name": "p"},
              "spec": {"containers": [{"name": "c", "image": "i"}]}}
    present = {"kind": "Pod", "apiVersion": "v1",
               "metadata": {"name": "p", "namespace": "default"},
               "spec": {"containers": [{"name": "c", "image": "i",
                                        "ports": []}],
                        "schedulerName": "default-scheduler",
                        "preemptionPolicy": "PreemptLowerPriority",
                        "hostNetwork": False, "nodeSelector": {}},
               "status": {"phase": "Pending"}}
    objs = []
    for manifest in (elided, present):
        for fp in BACKENDS:
            blob = wire.wire_encode(manifest, force_python=fp)
            objs.append(SCHEME.decode(wire.wire_decode(blob,
                                                       force_python=fp)))
    norm = [_normalized(o) for o in objs]
    assert all(n == norm[0] for n in norm)


# --- native object fast paths ------------------------------------------------

def _rich_pod(i=0):
    pod = (make_pod().name(f"web-{i}").uid(f"uid-{i}").namespace("prod")
           .label("app", "web").label("tier", "fe")
           .req({"cpu": "500m", "memory": "1Gi"}).priority(1000)
           .obj())
    pod.spec.containers[0].ports = [v1.ContainerPort(container_port=8080)]
    pod.spec.node_name = f"node-{i % 3}"
    pod.spec.node_selector = {"pool": "general"}
    pod.status.phase = "Running"
    pod.status.pod_ip = f"10.0.0.{i % 250}"
    pod.status.conditions = [{"type": "Ready", "status": "True"}]
    return pod


def _rich_node(i=0):
    node = (make_node().name(f"node-{i}").label("zone", "us-a")
            .capacity({"cpu": "16", "memory": "64Gi", "google.com/tpu": "4"})
            .taint("tpu", "v5e", "NoSchedule").obj())
    node.status.images = [v1.ContainerImage(names=["nginx:1.25"],
                                            size_bytes=187654321)]
    node.status.conditions = [{"type": "Ready", "status": "True"}]
    node.spec.pod_cidr = "10.4.0.0/24"
    return node


def test_fast_path_parity_with_reference():
    """encode_object must emit the SAME bytes as the pure-Python reference
    walking to_manifest, and decode_object must agree with scheme.decode —
    the native fast paths are behaviorally invisible."""
    for obj in [_rich_pod(0), _rich_pod(1), _rich_node(0),
                v1.Pod(metadata=v1.ObjectMeta(name="bare")),
                v1.Node(metadata=v1.ObjectMeta(name="bare-n"))]:
        manifest = to_manifest(obj, SCHEME)
        fast = wire.encode_object(obj, SCHEME)
        ref = wire.wire_encode(manifest, force_python=True)
        assert fast == ref, obj.kind
        got = wire.decode_object(fast, SCHEME)
        want = SCHEME.decode(manifest)
        assert _normalized(got) == _normalized(want), obj.kind


def test_fast_decode_quirk_parity():
    """from_dict quirks the native decoder must honor: empty allocatable
    copies capacity; absent namespace defaults; rv is dropped."""
    node = _rich_node(1)
    node.status.allocatable = {}
    blob = wire.encode_object(node, SCHEME)
    got = wire.decode_object(blob, SCHEME)
    want = SCHEME.decode(to_manifest(node, SCHEME))
    assert got.status.allocatable == want.status.allocatable
    assert got.status.allocatable == got.status.capacity
    assert got.status.allocatable is not got.status.capacity

    pod = _rich_pod(2)
    pod.metadata.resource_version = 77
    got = wire.decode_object(wire.encode_object(pod, SCHEME), SCHEME)
    assert got.metadata.resource_version == 0  # from_dict drops rv
    assert got.metadata.namespace == "prod"


def test_encode_object_bails_safely_on_stand_ins():
    """Objects outside the fast subset (odd attribute shapes) must fall
    back to the reference path, never emit wrong bytes."""
    pod = _rich_pod(3)
    pod.spec.affinity = v1.Affinity()  # non-None affinity → bail
    fast = wire.encode_object(pod, SCHEME)
    assert fast == wire.wire_encode(to_manifest(pod, SCHEME),
                                    force_python=True)


# --- EncodedPayload / encode-once --------------------------------------------

def test_encoded_payload_lazy_and_stable():
    pod = _rich_pod(4)
    p = wire.EncodedPayload.from_object(pod, SCHEME)
    wb = p.wire_bytes()
    jb = p.json_bytes()
    assert wire.is_wire(wb) and not wire.is_wire(jb)
    assert json.loads(jb) == wire.wire_decode(wb) == p.manifest()
    # identical objects on repeat (cached, not re-encoded)
    assert p.wire_bytes() is wb
    assert p.json_bytes() is jb
    assert p.bytes_for("wire") is wb and p.bytes_for("json") is jb


def test_payload_for_memoizes_per_rv():
    pod = _rich_pod(5)
    pod.metadata.resource_version = 3
    p1 = wire.payload_for(pod, SCHEME)
    assert wire.payload_for(pod, SCHEME) is p1
    pod.metadata.resource_version = 4  # store-mediated mutation
    p2 = wire.payload_for(pod, SCHEME)
    assert p2 is not p1


def test_watch_cache_encodes_once_per_event():
    """The headline fan-out property: N watchers of one event cost ONE
    json encode (and one wire encode), not N."""
    store = ObjectStore()
    cache = WatchCache(store, SCHEME)
    seen = [[] for _ in range(8)]
    for lane in seen:
        store_events = lane
        cache.watch(lane.append)
    base_uncached = m.apiserver_wire_encode.value(("json", "false"))
    pod = _rich_pod(6)
    store.create("Pod", pod)
    payloads = set()
    for lane in seen:
        assert len(lane) == 1
        assert lane[0].payload is not None
        lane[0].payload.json_bytes()
        payloads.add(id(lane[0].payload))
    assert len(payloads) == 1  # every watcher holds THE payload
    assert m.apiserver_wire_encode.value(("json", "false")) \
        == base_uncached + 1
    cache.close()


def test_watch_cache_rollback_from_prev_payload():
    """rv-consistent pagination still rolls back through the ring when
    entries hold payloads instead of manifests."""
    store = ObjectStore()
    cache = WatchCache(store, SCHEME)
    pod = _rich_pod(7)
    store.create("Pod", pod)
    rv1 = cache.current_rv()
    pod2 = _rich_pod(7)
    pod2.metadata.uid = pod.metadata.uid
    pod2.status.phase = "Succeeded"
    store.update("Pod", pod2)
    objs, rv, _ = cache.list_page("Pod", resource_version=rv1)
    assert rv == rv1 and len(objs) == 1
    assert objs[0].status.phase == "Running"
    assert objs[0].metadata.resource_version == rv1
    now_objs, _, _ = cache.list_page("Pod")
    assert now_objs[0].status.phase == "Succeeded"
    cache.close()


# --- watch frames ------------------------------------------------------------

def test_watch_frame_roundtrip_and_torn_rejection():
    doc = wire.wire_encode(to_manifest(_rich_pod(8), SCHEME))
    frames = (wire.encode_watch_frame("ADDED", doc, rv=12)
              + wire.encode_watch_frame("BOOKMARK", wire.wire_encode(
                  {"kind": "Pod"}), rv=13))
    stream = io.BytesIO(frames)
    t1, rv1, d1 = wire.read_watch_frame(stream)
    assert (t1, rv1, d1) == ("ADDED", 12, doc)
    t2, rv2, _ = wire.read_watch_frame(stream)
    assert (t2, rv2) == ("BOOKMARK", 13)
    assert wire.read_watch_frame(stream) is None  # clean EOF
    frame1_len = len(wire.encode_watch_frame("ADDED", doc, rv=12))
    for cut in range(1, len(frames) - 1):
        if cut == frame1_len:
            continue  # a whole frame + nothing is a clean EOF, not torn
        s = io.BytesIO(frames[:cut])
        with pytest.raises(wire.WireError):
            while wire.read_watch_frame(s) is not None:
                pass
    with pytest.raises(wire.WireError):
        wire.encode_watch_frame("NOPE", doc)


# --- WAL: binary records, mixed-format replay, torn tails --------------------

def _store_fingerprint(store):
    out = {}
    for kind in ("Pod", "Node"):
        objs, _ = store.list(kind)
        for o in objs:
            d = to_manifest(o, SCHEME)
            out[(kind, d["metadata"].get("namespace", ""),
                 d["metadata"]["name"])] = d
    return out


def test_wal_binary_records_roundtrip(tmp_path):
    path = str(tmp_path / "wal.log")
    wal = WriteAheadLog(path, scheme=SCHEME, fsync_every=0)
    store = ObjectStore(wal=wal)
    for i in range(4):
        store.create("Pod", _rich_pod(i))
    store.create("Node", _rich_node(0))
    pod_upd = _rich_pod(1)
    pod_upd.status.phase = "Succeeded"
    store.update("Pod", pod_upd)
    store.delete("Pod", "prod", "web-0")
    wal.close()
    records, good_end = scan_records(open(path, "rb").read())
    assert good_end == wal.size_bytes
    assert all(r.codec == "wire" for _, r in records)
    assert all(r.obj_bytes is not None for _, r in records
               if r.op in ("create", "update"))
    replayed = replay_on_boot(path, scheme=SCHEME).store
    assert _store_fingerprint(replayed) == _store_fingerprint(store)


def test_wal_mixed_format_replay_bit_identical(tmp_path):
    """A log with legacy JSON records followed by binary records (an
    in-place upgrade) reconstructs the exact store."""
    import struct
    import zlib

    path = str(tmp_path / "mixed.log")
    legacy, modern = _rich_pod(10), _rich_pod(11)
    with open(path, "wb") as f:
        for rec in [
            WALRecord(op="create", kind="Pod", namespace="prod",
                      name=legacy.metadata.name, rv=1,
                      manifest=to_manifest(legacy, SCHEME), codec="json"),
            WALRecord(op="create", kind="Pod", namespace="prod",
                      name=modern.metadata.name, rv=2,
                      obj_bytes=wire.encode_object(modern, SCHEME),
                      codec="wire"),
            WALRecord(op="bind", kind="Pod", namespace="prod",
                      name=modern.metadata.name, rv=3,
                      node_name="node-9", codec="wire"),
        ]:
            payload = rec.payload()
            f.write(struct.pack(">II", len(payload), zlib.crc32(payload))
                    + payload)
    result = replay_on_boot(path, scheme=SCHEME)
    assert result.records_applied == 3 and not result.truncated_tail
    never_crashed = ObjectStore()
    never_crashed.create("Pod", legacy)
    m2 = _rich_pod(11)
    m2.metadata.uid = modern.metadata.uid
    never_crashed.create("Pod", m2)
    never_crashed.bind_pod("prod", m2.metadata.name, "node-9")
    fp_replay = _store_fingerprint(result.store)
    fp_live = _store_fingerprint(never_crashed)
    for d in list(fp_replay.values()) + list(fp_live.values()):
        d["metadata"].pop("creationTimestamp", None)
    assert fp_replay == fp_live


def test_wal_torn_binary_tail_truncated(tmp_path):
    path = str(tmp_path / "torn.log")
    wal = WriteAheadLog(path, scheme=SCHEME, fsync_every=0)
    store = ObjectStore(wal=wal)
    for i in range(3):
        store.create("Pod", _rich_pod(i))
    wal.close()
    whole = open(path, "rb").read()
    # tear the last record mid-payload
    with open(path, "wb") as f:
        f.write(whole[:-7])
    result = replay_on_boot(path, scheme=SCHEME)
    assert result.truncated_tail
    assert result.records_applied == 2
    import os
    assert os.path.getsize(path) == result.truncated_at
    # corrupted byte inside a binary payload → crc refuses the record
    data = bytearray(whole)
    data[len(whole) // 2] ^= 0xFF
    records, good = scan_records(bytes(data))
    assert len(records) < 3


# --- HTTP negotiation end-to-end ---------------------------------------------

@pytest.fixture()
def server():
    from kubernetes_tpu.apiserver import APIServer

    store = ObjectStore()
    srv = APIServer(store, SCHEME).start()
    yield srv
    srv.stop()


def test_http_codec_negotiation_end_to_end(server):
    from kubernetes_tpu.apiserver import HTTPApiClient

    wire_client = HTTPApiClient(server.url, SCHEME, codec="wire")
    json_client = HTTPApiClient(server.url, SCHEME, codec="json")
    base_wire = m.apiserver_wire_requests.value(("wire",))
    base_json = m.apiserver_wire_requests.value(("json",))

    pod = _rich_pod(20)
    reply = wire_client.create("Pod", pod)  # wire body, wire response
    assert reply["metadata"]["name"] == pod.metadata.name
    json_client.create("Node", _rich_node(20))

    for client in (wire_client, json_client):
        got = client.get("Pod", "prod", pod.metadata.name)
        assert got.spec.containers[0].image == pod.spec.containers[0].image
        objs, rv = client.list("Pod")
        assert len(objs) == 1 and rv > 0
        assert _normalized(objs[0]) == _normalized(
            SCHEME.decode(to_manifest(pod, SCHEME)))
    # raw transport check: the wire client's LIST really is binary
    import urllib.request

    req = urllib.request.Request(server.url + "/api/v1/pods")
    req.add_header("Accept", wire.WIRE_CONTENT_TYPE)
    with urllib.request.urlopen(req, timeout=5) as resp:
        assert resp.headers.get("Content-Type") == wire.WIRE_CONTENT_TYPE
        body = resp.read()
    assert wire.is_wire(body)
    doc = wire.wire_decode(body)
    assert isinstance(doc["items"][0], bytes)
    assert m.apiserver_wire_requests.value(("wire",)) > base_wire
    assert m.apiserver_wire_requests.value(("json",)) > base_json


@pytest.mark.parametrize("codec", ["wire", "json"])
def test_http_watch_stream_both_codecs(server, codec):
    from kubernetes_tpu.apiserver import HTTPApiClient

    client = HTTPApiClient(server.url, SCHEME, codec=codec)
    events = []
    done = threading.Event()

    def handler(ev):
        events.append(ev)
        if len(events) >= 2:
            done.set()

    client.watch_kind("Pod", handler, since_rv=0, timeout_seconds=10)
    time.sleep(0.3)
    server.store.create("Pod", _rich_pod(30))
    upd = _rich_pod(30)
    upd.status.phase = "Succeeded"
    upd.metadata.resource_version = 0
    server.store.update("Pod", upd)
    assert done.wait(5), f"saw {len(events)} events over {codec}"
    assert [e.type for e in events[:2]] == ["ADDED", "MODIFIED"]
    assert events[0].resource_version > 0
    assert events[1].resource_version > events[0].resource_version
    assert events[0].obj.metadata.name == "web-30"
    assert events[1].obj.status.phase == "Succeeded"
