"""Crash-restart resilience: deterministic kill-points, cold-start state
reconstruction, drift detection/repair, leader failover with exactly-once
binding.

Reference behaviors exercised: the informer ListAndWatch restart as
checkpoint/resume (SURVEY §5), the scheduler assume-cache's
soft-state-rebuild property (pkg/scheduler/internal/cache), leader-election
handover with fencing (client-go tools/leaderelection + the classic
fencing-token construction), and kube-scheduler's exit-on-lost-lease
(cmd/kube-scheduler app/server.go:204-215).
"""

import traceback

import pytest

from kubernetes_tpu.analysis import lockcheck
from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.chaos import (
    CRASH_POINTS,
    FaultSchedule,
    ProcessCrash,
    crash_schedule,
    maybe_crash,
    steal_lease,
)
from kubernetes_tpu.client.events import RETAIN_CAP, EventRecorder
from kubernetes_tpu.client.leaderelection import LeaderElector, LeaseLock
from kubernetes_tpu.component_base.healthz import Readyz
from kubernetes_tpu.gang import POD_GROUP_LABEL
from kubernetes_tpu.metrics import scheduler_metrics as m
from kubernetes_tpu.recovery import (
    DriftDetector,
    canonical_state,
    cold_start,
    diff_canonical,
)
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import DELETED, ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod


@pytest.fixture(autouse=True)
def lock_order_monitor():
    """Same contract as the chaos battery's autouse monitor: recovery code
    paths (crash points under store locks, drift repair, failover) run with
    lock-order inversion detection, failing the test at teardown."""
    mon = lockcheck.activate()
    try:
        yield mon
    finally:
        lockcheck.deactivate()
    assert not mon.violations, mon.report()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _mk_cluster(store, n_nodes=4, cpu="4"):
    for i in range(n_nodes):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": cpu, "pods": "32"}).obj())


def _mk_pods(store, n, prefix="p", cpu="1", labels=None):
    for i in range(n):
        b = (make_pod().name(f"{prefix}{i}").uid(f"{prefix}{i}")
             .namespace("default").req({"cpu": cpu}))
        for k, val in (labels or {}).items():
            b = b.label(k, val)
        store.create("Pod", b.obj())


def _mk_gang(store, name, size, cpu="1", timeout=30.0):
    store.create("PodGroup", v1.PodGroup(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        min_member=size, schedule_timeout_seconds=timeout))
    _mk_pods(store, size, prefix=f"{name}-", cpu=cpu,
             labels={POD_GROUP_LABEL: name})


def _bound(store):
    pods, _ = store.list("Pod")
    return [p for p in pods if p.spec.node_name]


def _bind_transitions(store):
    """(name, incarnation) → count of unbound→bound transitions in the
    store's own event history; DELETE closes the incarnation, so a
    recreated name is a fresh key — the exactly-once probe."""
    node_of, incarnation, counts = {}, {}, {}
    for ev in store._log:
        if ev.kind != "Pod":
            continue
        name = ev.obj.metadata.name
        if ev.type == DELETED:
            node_of.pop(name, None)
            incarnation[name] = incarnation.get(name, 0) + 1
            continue
        nn = ev.obj.spec.node_name or None
        if nn is not None and node_of.get(name) is None:
            key = (name, incarnation.get(name, 0))
            counts[key] = counts.get(key, 0) + 1
        node_of[name] = nn
    return counts


def _crash_frames(excinfo):
    return [f.name for f in traceback.extract_tb(excinfo.value.__traceback__
                                                 if hasattr(excinfo, "value")
                                                 else excinfo.__traceback__)]


def _assert_recovery_parity(store, recovered):
    """Post-recovery state == a from-scratch store encode, exactly (the
    canonical keys decode dictionary ids and row numbers away; any value
    difference fails)."""
    scratch = TPUScheduler(store, batch_size=recovered.batch_size)
    try:
        assert diff_canonical(canonical_state(recovered),
                              canonical_state(scratch)) == {}
    finally:
        scratch.close()


# --- crash-point framework ----------------------------------------------------


def test_crash_point_catalog_and_arming():
    fault = FaultSchedule(0)
    with pytest.raises(ValueError):
        fault.arm_crash("crash.not_a_point")
    # unarmed schedule: hits count but nothing fires
    with crash_schedule(fault):
        maybe_crash("crash.mid_bind")
    assert fault.crashes_fired() == {}
    # no schedule installed: no-op even for armed-looking points
    maybe_crash("crash.mid_bind")


def test_crash_fires_at_exact_hit_once():
    fault = FaultSchedule(0, crash_points={"crash.mid_bind": 3})
    with crash_schedule(fault):
        maybe_crash("crash.mid_bind")
        maybe_crash("crash.mid_bind")
        with pytest.raises(ProcessCrash) as ei:
            maybe_crash("crash.mid_bind")
        assert ei.value.point == "crash.mid_bind"
        # fired once: later hits pass
        maybe_crash("crash.mid_bind")
    assert fault.injected_counts()["crash:crash.mid_bind"] == 1
    assert fault.crashes_fired() == {"crash.mid_bind": 2}


def test_arm_crash_is_relative_to_consumed_hits():
    fault = FaultSchedule(0)
    with crash_schedule(fault):
        maybe_crash("crash.after_assume")
        maybe_crash("crash.after_assume")
        fault.arm_crash("crash.after_assume", at_hit=1)
        with pytest.raises(ProcessCrash):
            maybe_crash("crash.after_assume")


def test_process_crash_passes_through_resilience_handlers():
    """ProcessCrash is BaseException: the scheduler's own cycle-failure
    handler (``except Exception``) must NOT absorb a simulated process
    death — it propagates to the harness like a real SIGKILL would."""
    assert not issubclass(ProcessCrash, Exception)
    assert issubclass(ProcessCrash, BaseException)
    for point in CRASH_POINTS:
        assert point.startswith("crash.")


# --- per-kill-point battery: fires where registered, recovery converges -------


def test_crash_after_assume_fires_in_complete_and_recovers():
    store = ObjectStore()
    _mk_cluster(store)
    _mk_pods(store, 6)
    fault = FaultSchedule(0, crash_points={"crash.after_assume": 1})
    sched = TPUScheduler(store, batch_size=8)
    with crash_schedule(fault):
        with pytest.raises(ProcessCrash) as ei:
            sched.run_until_idle(max_cycles=5)
    assert "_complete" in _crash_frames(ei)
    # assumes are memory only: the store saw ZERO binds
    assert len(_bound(store)) == 0
    sched.close(flush_events=False)
    res = cold_start(store, batch_size=8)
    assert res.outcome == "clean" and res.drift is not None
    res.scheduler.run_until_idle(max_cycles=10)
    assert len(_bound(store)) == 6
    assert all(c == 1 for c in _bind_transitions(store).values())
    _assert_recovery_parity(store, res.scheduler)
    res.scheduler.close()


def test_crash_mid_bind_fires_in_finish_bind_and_recovers():
    store = ObjectStore()
    _mk_cluster(store)
    _mk_pods(store, 6)
    fault = FaultSchedule(0, crash_points={"crash.mid_bind": 3})
    sched = TPUScheduler(store, batch_size=8)
    with crash_schedule(fault):
        with pytest.raises(ProcessCrash) as ei:
            sched.run_until_idle(max_cycles=5)
    assert "_finish_bind" in _crash_frames(ei)
    # the 3rd bind's store write landed before the death
    assert len(_bound(store)) == 3
    sched.close(flush_events=False)
    res = cold_start(store, batch_size=8)
    res.scheduler.run_until_idle(max_cycles=10)
    assert len(_bound(store)) == 6
    # already-bound pods were NEVER re-bound by the successor
    assert all(c == 1 for c in _bind_transitions(store).values())
    _assert_recovery_parity(store, res.scheduler)
    res.scheduler.close()


def test_crash_permit_held_never_half_binds_a_gang():
    store = ObjectStore()
    _mk_cluster(store)
    _mk_gang(store, "g0", 4)
    fault = FaultSchedule(0, crash_points={"crash.permit_held": 2})
    sched = TPUScheduler(store, batch_size=8)
    with crash_schedule(fault):
        with pytest.raises(ProcessCrash) as ei:
            sched.run_until_idle(max_cycles=5)
    assert "note_waiting" in _crash_frames(ei)
    # two members held Permits (assumed in the dead cache) — the store
    # must show ZERO binds: held permits die with the process, the gang
    # requeues whole on the successor
    assert len(_bound(store)) == 0
    sched.close(flush_events=False)
    res = cold_start(store, batch_size=8)
    assert res.partial_gangs == []
    res.scheduler.run_until_idle(max_cycles=10)
    assert len(_bound(store)) == 4
    assert all(c == 1 for c in _bind_transitions(store).values())
    pg = store.get("PodGroup", "default", "g0")
    assert pg.phase == v1.POD_GROUP_SCHEDULED
    _assert_recovery_parity(store, res.scheduler)
    res.scheduler.close()


def test_crash_mid_plan_apply_evicts_exactly_once():
    from kubernetes_tpu.descheduler.controller import DeschedulerController
    from kubernetes_tpu.descheduler.policies import DRAIN_ANNOTATION

    store = ObjectStore()
    _mk_cluster(store, n_nodes=3)
    _mk_pods(store, 4)
    sched = TPUScheduler(store, batch_size=8)
    sched.run_until_idle(max_cycles=5)
    assert len(_bound(store)) == 4
    # drain the node hosting at least one pod
    victim_node = _bound(store)[0].spec.node_name
    node = store.get("Node", "", victim_node)
    node.metadata.annotations[DRAIN_ANNOTATION] = "true"
    store.update("Node", node)
    fault = FaultSchedule(0, crash_points={"crash.mid_plan_apply": 1})
    desched = DeschedulerController(store, sched)
    with crash_schedule(fault):
        with pytest.raises(ProcessCrash) as ei:
            desched.sync_once()
    assert "_apply" in _crash_frames(ei)
    deleted = [ev.obj.metadata.name for ev in store._log
               if ev.kind == "Pod" and ev.type == DELETED]
    assert len(deleted) == 1  # exactly one victim left before the death
    sched.close(flush_events=False)
    # recovery: fresh replica re-plans from live state — fail-stop means
    # the old victim list is never resumed, and nothing is evicted twice
    res = cold_start(store, batch_size=8)
    desched2 = DeschedulerController(store, res.scheduler)
    for _ in range(6):
        desched2.sync_once()
        res.scheduler.run_until_idle(max_cycles=5)
    pods, _ = store.list("Pod")
    on_drained = [p for p in pods if p.spec.node_name == victim_node]
    assert on_drained == []  # drain completed across restarts
    all_deleted = [ev.obj.metadata.name for ev in store._log
                   if ev.kind == "Pod" and ev.type == DELETED]
    assert len(all_deleted) == len(set(all_deleted))  # exactly once each
    res.scheduler.close()


def test_crash_mid_scaleup_resumes_exactly_once():
    from kubernetes_tpu.autoscaler.api import NODE_GROUP_LABEL, NodeGroup
    from kubernetes_tpu.autoscaler.controller import ClusterAutoscaler

    store = ObjectStore()
    _mk_cluster(store, n_nodes=1, cpu="1")  # nearly no capacity
    store.create("NodeGroup", NodeGroup(
        metadata=v1.ObjectMeta(name="pool"), min_size=0, max_size=6,
        capacity={"cpu": "4", "pods": "32"}))
    _mk_gang(store, "g0", 4, cpu="3")  # needs the scale-up
    sched = TPUScheduler(store, batch_size=8)
    sched.run_until_idle(max_cycles=6)
    assert len(_bound(store)) == 0  # parked: no capacity yet
    fault = FaultSchedule(0, crash_points={"crash.mid_scaleup": 1})
    autoscaler = ClusterAutoscaler(store, sched)
    with crash_schedule(fault):
        with pytest.raises(ProcessCrash) as ei:
            autoscaler.sync_once()
    assert "_scale_up" in _crash_frames(ei)
    nodes_mid = [n.metadata.name for n in store.list("Node")[0]
                 if n.metadata.labels.get(NODE_GROUP_LABEL) == "pool"]
    assert nodes_mid == ["pool-0"]  # exactly the first deterministic name
    sched.close(flush_events=False)
    res = cold_start(store, batch_size=8)
    autoscaler2 = ClusterAutoscaler(store, res.scheduler)
    for _ in range(6):
        autoscaler2.sync_once()
        res.scheduler.run_until_idle(max_cycles=6)
        if len(_bound(store)) == 4:
            break
    assert len(_bound(store)) == 4  # gang placed on the resumed scale-up
    names = [n.metadata.name for n in store.list("Node")[0]
             if n.metadata.labels.get(NODE_GROUP_LABEL) == "pool"]
    # deterministic names resumed without duplication or gaps
    assert len(names) == len(set(names))
    assert "pool-0" in names and len(names) <= 6
    res.scheduler.close()


def test_crash_post_lease_renew_successor_waits_out_lease():
    clock = FakeClock()
    store = ObjectStore()
    lock = LeaseLock(store, "kube-system", "tpu-scheduler")
    a = LeaderElector(lock, "a", lease_duration=1.0, clock=clock)
    assert a.try_acquire_or_renew()
    fault = FaultSchedule(0, crash_points={"crash.post_lease_renew": 1})
    with crash_schedule(fault):
        clock.advance(0.1)
        with pytest.raises(ProcessCrash) as ei:
            a.try_acquire_or_renew()
    assert "_tick" in _crash_frames(ei)
    # the dead holder's fresh renewal pins the lease: a successor cannot
    # acquire until a FULL lease_duration elapses
    b = LeaderElector(lock, "b", lease_duration=1.0, clock=clock)
    assert not b.try_acquire_or_renew()
    clock.advance(0.5)
    assert not b.try_acquire_or_renew()
    clock.advance(0.61)
    assert b.try_acquire_or_renew()
    lease = lock.get()
    assert lease.holder_identity == "b"
    assert lease.lease_transitions == 1  # holder change bumped the fence
    assert b.fence_token == 1 and b.check_fence()


# --- cold-start reconstruction ------------------------------------------------


def test_cold_start_readyz_gates_until_verified():
    store = ObjectStore()
    _mk_cluster(store)
    _mk_pods(store, 4)
    rz = Readyz()
    seen_during = {}

    def factory(st, **kw):
        # mid-rebuild: relist done, later components still pending
        seen_during["ready"] = rz.ready
        return TPUScheduler(st, **kw)

    res = cold_start(store, readyz=rz, scheduler_factory=factory,
                     batch_size=8)
    assert seen_during["ready"] is False  # NotReady while rebuilding
    assert rz.ready is True  # ready only after the verify pass
    assert res.outcome == "clean"
    res.scheduler.close()


def test_cold_start_rederives_gang_phase_and_completes_partial_gang():
    store = ObjectStore()
    _mk_cluster(store)
    _mk_gang(store, "g0", 3)
    # simulate a crash mid-flush: one member bound in the store, the
    # PodGroup phase left claiming Scheduled
    store.bind_pod("default", "g0-0", "n0")
    pg = store.get("PodGroup", "default", "g0")
    pg.phase = v1.POD_GROUP_SCHEDULED
    store.update("PodGroup", pg)
    res = cold_start(store, batch_size=8)
    assert res.partial_gangs == ["default/g0"]
    assert res.gang_phase_repairs >= 1
    assert store.get("PodGroup", "default", "g0").phase == \
        v1.POD_GROUP_SCHEDULING
    # the gang COMPLETES (bound members stay, the rest join them) —
    # never unwinds, never stays half-bound
    res.scheduler.run_until_idle(max_cycles=10)
    assert len(_bound(store)) == 3
    assert all(c == 1 for c in _bind_transitions(store).values())
    assert store.get("PodGroup", "default", "g0").phase == \
        v1.POD_GROUP_SCHEDULED
    res.scheduler.close()


def test_cold_start_drops_stale_nominations():
    store = ObjectStore()
    _mk_cluster(store)
    _mk_pods(store, 2)
    pod = store.get("Pod", "default", "p0")
    pod.status.nominated_node_name = "n1"  # the dead leader's stale claim
    store.update("Pod", pod)
    res = cold_start(store, batch_size=8)
    assert res.nominations_dropped == 1
    assert store.get("Pod", "default", "p0").status.nominated_node_name \
        is None
    res.scheduler.close()


def test_cold_start_parity_after_churn():
    """Recovered snapshot == from-scratch store encode, bit-for-bit at the
    canonical keys, after a run with binds, deletes, and affinity terms."""
    store = ObjectStore()
    _mk_cluster(store, n_nodes=5)
    for i in range(4):
        store.create("Pod", make_pod().name(f"a{i}").uid(f"a{i}")
                     .namespace("default").req({"cpu": "1"})
                     .label("app", "web").obj())
    sched = TPUScheduler(store, batch_size=8)
    sched.run_until_idle(max_cycles=5)
    store.delete("Pod", "default", "a3")
    store.delete("Node", "", "n4")
    _mk_pods(store, 2, prefix="late-")
    sched.run_until_idle(max_cycles=5)
    sched.close()
    res = cold_start(store, batch_size=8)
    assert res.outcome == "clean"
    _assert_recovery_parity(store, res.scheduler)
    res.scheduler.close()


# --- drift detector / repairer ------------------------------------------------


def test_drift_detector_clean_on_healthy_scheduler():
    store = ObjectStore()
    _mk_cluster(store)
    _mk_pods(store, 4)
    sched = TPUScheduler(store, batch_size=8)
    sched.run_until_idle(max_cycles=5)
    report = DriftDetector(sched).check()
    assert report is not None and report.clean
    sched.close()


def test_drift_detector_repairs_each_component():
    store = ObjectStore()
    _mk_cluster(store)
    for i in range(3):
        store.create("Pod", make_pod().name(f"p{i}").uid(f"p{i}")
                     .namespace("default").req({"cpu": "1"})
                     .label("app", "web").obj())
    sched = TPUScheduler(store, batch_size=8)
    sched.run_until_idle(max_cycles=5)
    before = m.state_drift.value(("encoder_nodes",))
    det = DriftDetector(sched)
    assert det.check().clean  # settle the post-bind encoder sync first
    # corrupt the encoder's requested plane behind the scheduler's back
    sched.encoder.requested[sched.encoder.node_rows["n0"], 0] += 13
    report = det.check_and_repair()
    assert report.divergent == {"encoder_nodes": 1}
    assert report.unrepaired == {} and report.repaired
    assert m.state_drift.value(("encoder_nodes",)) == before + 1
    # cache-level corruption: drop a bound pod from the cache
    pod = _bound(store)[0]
    sched.cache.remove_pod(pod)
    report = det.check_and_repair()
    assert "cache_pods" in report.divergent
    assert report.unrepaired == {}
    # post-repair: clean, and scheduling still works on the repaired state
    assert det.check().clean
    _mk_pods(store, 1, prefix="extra-")
    sched.run_until_idle(max_cycles=5)
    assert len(_bound(store)) == 4
    sched.close()


def test_drift_detector_repairs_affinity_tables():
    store = ObjectStore()
    _mk_cluster(store)
    aff = v1.Affinity(pod_anti_affinity=v1.PodAffinity(required=[
        v1.PodAffinityTerm(
            label_selector=v1.LabelSelector(match_labels={"app": "web"}),
            topology_key="kubernetes.io/hostname")]))
    for i in range(3):
        p = (make_pod().name(f"w{i}").uid(f"w{i}").namespace("default")
             .req({"cpu": "1"}).label("app", "web").obj())
        p.spec.affinity = aff
        store.create("Pod", p)
    sched = TPUScheduler(store, batch_size=8)
    sched.run_until_idle(max_cycles=5)
    assert len(_bound(store)) == 3
    det = DriftDetector(sched)
    assert det.check().clean  # settle the post-bind encoder/affinity sync
    idx = sched.encoder.aff
    assert idx.live_groups > 0
    idx.aff_counts[0] += 5.0  # corrupt a count table
    report = det.check_and_repair()
    assert "affinity" in report.divergent
    assert report.unrepaired == {}
    sched.close()


# --- leader-election handover: fencing + stop-work ----------------------------


def test_fencing_token_refuses_bind_after_steal_lease():
    """Two live replicas + steal_lease: the outgoing leader's already-
    dispatched work must not produce binds racing the new leader."""
    clock = FakeClock()
    store = ObjectStore()
    _mk_cluster(store)
    lock = LeaseLock(store, "kube-system", "tpu-scheduler")
    el_a = LeaderElector(lock, "a", lease_duration=5.0, clock=clock)
    assert el_a.try_acquire_or_renew()
    sched_a = TPUScheduler(store, batch_size=4, clock=clock, pipeline=True,
                           fence=el_a.check_fence, batch_wait=0)
    el_a.on_stopped_leading = sched_a.abandon_inflight
    _mk_pods(store, 4)
    sched_a.schedule_cycle()  # dispatches a batch; pipeline → nothing bound
    assert sched_a._inflight_q
    assert steal_lease(store, "kube-system", "tpu-scheduler", usurper="b",
                       clock=clock)
    before = m.scheduler_retries.value(("fence_reject",))
    # completing the in-flight batch hits the bind fence: zero binds
    sched_a.schedule_cycle()
    sched_a.schedule_cycle()
    assert len(_bound(store)) == 0
    assert m.scheduler_retries.value(("fence_reject",)) > before
    # A's next renewal sees the foreign holder: releases + stops work
    assert not el_a.try_acquire_or_renew()
    assert not sched_a._inflight_q
    sched_a.close()
    # successor (fresh replica for "b") binds everything exactly once
    clock.advance(6.0)
    el_b = LeaderElector(lock, "b2", lease_duration=5.0, clock=clock)
    assert el_b.try_acquire_or_renew()
    res = cold_start(store, batch_size=4, clock=clock,
                     fence=el_b.check_fence, batch_wait=0)
    res.scheduler.run_until_idle(max_cycles=10)
    assert len(_bound(store)) == 4
    assert all(c == 1 for c in _bind_transitions(store).values())
    res.scheduler.close()


def test_abandon_inflight_requeues_and_rolls_back_holds():
    store = ObjectStore()
    _mk_cluster(store)
    sched = TPUScheduler(store, batch_size=4, pipeline=True, batch_wait=0)
    _mk_pods(store, 3)
    sched.schedule_cycle()
    assert sched._inflight_q
    sched.abandon_inflight()
    assert sched._inflight_q == []
    assert sched._nominated == {} and sched._waiting_binds == {}
    a, b, u = sched.queue.pending_count()
    assert a + b + u == 3  # every in-flight pod requeued, none lost
    # the abandoned work reschedules cleanly
    sched.run_until_idle(max_cycles=10)
    assert len(_bound(store)) == 3
    assert all(c == 1 for c in _bind_transitions(store).values())
    sched.close()


def test_fence_predicate_failure_is_fenced_out():
    store = ObjectStore()
    _mk_cluster(store)
    _mk_pods(store, 1)

    def broken_fence():
        raise RuntimeError("lease store down")

    sched = TPUScheduler(store, batch_size=4, fence=broken_fence)
    sched.run_until_idle(max_cycles=3)
    assert len(_bound(store)) == 0  # unprovable fence = failed fence
    sched.close()


# --- event-recorder durability ------------------------------------------------


class _FlakyEventStore:
    """Store wrapper failing Event writes while ``down`` is True."""

    def __init__(self, inner):
        self._inner = inner
        self.down = False

    def create(self, kind, obj):
        if kind == "Event" and self.down:
            raise RuntimeError("control plane down")
        return self._inner.create(kind, obj)

    def update(self, kind, obj, expected_rv=None):
        if kind == "Event" and self.down:
            raise RuntimeError("control plane down")
        return self._inner.update(kind, obj, expected_rv=expected_rv)

    def __getattr__(self, attr):
        return getattr(self._inner, attr)


def test_event_recorder_retains_then_flushes():
    raw = ObjectStore()
    store = _FlakyEventStore(raw)
    rec = EventRecorder(store)
    pod = make_pod().name("p0").uid("p0").namespace("default").obj()
    store.down = True
    rec.eventf(pod, "Warning", "FailedScheduling", "no nodes")
    assert rec.pending_writes == 1 and rec.dropped == 0
    store.down = False
    assert rec.flush() == 0  # retained write lands; nothing lost
    events, _ = raw.list("Event")
    assert len(events) == 1 and events[0].reason == "FailedScheduling"


def test_event_recorder_bounds_loss_and_counts_drops():
    raw = ObjectStore()
    store = _FlakyEventStore(raw)
    rec = EventRecorder(store)
    before = m.events_dropped.value()
    store.down = True
    pod = make_pod().name("p0").uid("p0").namespace("default").obj()
    n = RETAIN_CAP + 5
    for i in range(n):
        rec.eventf(pod, "Normal", f"R{i}", "msg")  # distinct reasons
    # the buffer is bounded: overflow evictions are counted drops
    assert rec.pending_writes == RETAIN_CAP
    assert rec.dropped == 5
    # flush against a still-down store: the rest are counted lost too
    lost = rec.flush()
    assert lost == RETAIN_CAP
    assert rec.dropped == 5 + RETAIN_CAP
    assert m.events_dropped.value() == before + rec.dropped
    assert rec.pending_writes == 0


def test_scheduler_close_flushes_events():
    raw = ObjectStore()
    store = _FlakyEventStore(raw)
    _mk_cluster(raw)
    _mk_pods(raw, 1)
    store.down = True
    sched = TPUScheduler(store, batch_size=4)
    sched.run_until_idle(max_cycles=5)
    assert sched.recorder.pending_writes > 0  # Scheduled event retained
    store.down = False
    sched.close()  # clean shutdown: flush lands the retained events
    assert sched.recorder.pending_writes == 0
    events, _ = raw.list("Event")
    assert any(e.reason == "Scheduled" for e in events)


# --- readiness gating ---------------------------------------------------------


def test_readyz_progress_and_render():
    rz = Readyz()
    assert rz.ready and rz.render() == "ok"
    rz.begin("encode", 10)
    rz.begin("gangs", 2)
    assert not rz.ready
    rz.progress("encode", 4)
    assert "encode: 4/10" in rz.render()
    assert "NotReady" in rz.render()
    rz.complete("encode")
    rz.complete("gangs")
    assert rz.ready and rz.render() == "ok"
    rz.reset()
    assert rz.ready


def test_apiserver_readyz_distinct_from_healthz():
    import urllib.error
    import urllib.request

    from kubernetes_tpu.apiserver import APIServer

    store = ObjectStore()
    rz = Readyz()
    rz.begin("encode", 3)
    server = APIServer(store, readyz=rz).start()
    try:
        base = server.url
        with urllib.request.urlopen(f"{base}/healthz") as r:
            assert r.status == 200  # alive regardless of readiness
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/readyz")
        assert ei.value.code == 503
        body = ei.value.read().decode()
        assert "NotReady" in body and "encode: 0/3" in body
        rz.complete("encode")
        with urllib.request.urlopen(f"{base}/readyz") as r:
            assert r.status == 200 and r.read() == b"ok"
    finally:
        server.stop()


def test_cli_readyz_status():
    from kubernetes_tpu.cli import Kubectl

    store = ObjectStore()
    k = Kubectl(store)
    assert k.readyz_status() == "ok"
    rz = Readyz()
    rz.begin("encode", 4)
    rz.progress("encode", 1)
    out = k.readyz_status(rz)
    assert "NotReady" in out and "encode" in out and "1/4" in out
    rz.complete("encode")
    assert k.readyz_status(rz).startswith("ok")


# --- TTLAfterFinished restart path --------------------------------------------


def test_ttl_after_finished_restart_counts_from_first_observation():
    from kubernetes_tpu.controllers.ttlafterfinished import (
        TTLAfterFinishedController,
    )

    store = ObjectStore()
    job = v1.Job(metadata=v1.ObjectMeta(name="j0", namespace="default"),
                 ttl_seconds_after_finished=10, completed=True)
    assert job.completion_time is None  # finished before the field existed
    store.create("Job", job)
    now = {"t": 100.0}
    # first controller observes it, stamps completion_time=now — the TTL
    # counts from FIRST OBSERVATION, not from some long-gone finish
    tc1 = TTLAfterFinishedController(store, clock=lambda: now["t"])
    assert tc1.sync_once()
    assert store.get("Job", "default", "j0").completion_time == 100.0
    # RESTART: a fresh controller instance must not re-stamp or delete early
    tc2 = TTLAfterFinishedController(store, clock=lambda: now["t"])
    assert not tc2.sync_once()
    assert store.get("Job", "default", "j0").completion_time == 100.0
    now["t"] = 109.9
    assert not tc2.sync_once()
    assert store.get("Job", "default", "j0") is not None
    now["t"] = 110.0
    assert tc2.sync_once()
    assert store.get("Job", "default", "j0") is None


# --- failover soak ------------------------------------------------------------


def test_failover_soak_fast():
    """The acceptance shape at battery size: leader killed at every
    registered crash point in turn, every pod bound exactly once per
    incarnation, no half-bound gang, bounded recovery, zero unrepaired
    drift."""
    from kubernetes_tpu.recovery.failover import KILL_ORDER, run_failover_soak

    r = run_failover_soak(seed=7)
    assert r.crashes == list(KILL_ORDER)  # every point fired, in turn
    assert r.converged, (r.unbound, r.duplicate_binds, r.gangs_partial,
                         r.drift_unrepaired)
    assert r.bound == r.pods and r.duplicate_binds == 0
    assert r.gangs_partial == []
    assert r.drift_unrepaired == 0
    assert r.recoveries >= len(KILL_ORDER)
    # bounded recovery: lease expiry + cold start, in driver iterations
    assert r.max_recovery_iterations <= 60


def test_failover_soak_deterministic_replay():
    """Same seed → same kill sequence, same fault decisions, same converged
    signature (kill decisions ride the per-key op counters, so replays
    cannot depend on wall clock)."""
    from kubernetes_tpu.recovery.failover import run_failover_soak

    kill_order = ("crash.permit_held", "crash.mid_bind",
                  "crash.post_lease_renew")
    runs = [
        run_failover_soak(
            n_plain=6, n_gangs=1, gang_size=3, n_nodes=4, seed=11,
            kill_order=kill_order, drift_every=0,
        ).determinism_signature()
        for _ in range(2)
    ]
    assert runs[0] == runs[1]
    assert runs[0]["crashes"] == list(kill_order)


@pytest.mark.slow
def test_failover_soak_full_500():
    """The full acceptance soak: 500-pod churn, leader killed at every
    registered crash point, exactly-once binding, all-or-nothing gangs,
    post-recovery snapshot == from-scratch encode (drift detector reports
    zero unrepaired divergence)."""
    from kubernetes_tpu.recovery.failover import KILL_ORDER, run_failover_soak

    r = run_failover_soak(
        n_plain=472, n_gangs=3, gang_size=4, overflow_gang_size=16,
        n_nodes=124, seed=7, batch_size=64, group_max_size=16,
        phase_cap=1500, max_iterations=20000,
    )
    assert r.pods >= 500
    assert r.crashes == list(KILL_ORDER)
    assert r.converged, (r.unbound[:10], r.duplicate_binds,
                         r.gangs_partial, r.drift_unrepaired)
    assert r.duplicate_binds == 0 and r.gangs_partial == []
    assert r.drift_unrepaired == 0
