"""Node-axis sharding as the LIVE runtime path (round 9).

tests/test_sharding.py pins the program-level parity (sharded compute /
assign == unsharded on hand-built arrays); this battery pins the RUNTIME:
a TPUScheduler with ``sharding=`` enabled — encoder-owned mesh, sharded
full uploads AND the incremental scatter/sync path, sharded whatif forks
— must produce bit-identical bindings to an unsharded scheduler over the
same store, and the identity-class dedup path must match the full path
live.  conftest provides 8 virtual CPU devices.
"""

import numpy as np
import jax
import pytest

from kubernetes_tpu.parallel import node_sharded_mesh, node_sharding
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.state.cache import Cache, Snapshot
from kubernetes_tpu.state.encoding import ClusterEncoder, apply_scatter
from kubernetes_tpu.state import encoding as encoding_mod
from kubernetes_tpu.testutil import make_node, make_pod

needs_devices = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs multiple (virtual) devices")


def _populate(store, n_nodes=12, n_pods=24):
    for i in range(n_nodes):
        store.create(
            "Node",
            make_node().name(f"n{i:03d}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "110"})
            .label("zone", f"z{i % 3}")
            .label("disk", "ssd" if i % 2 else "hdd")
            .obj(),
        )
    for i in range(n_pods):
        w = (make_pod().name(f"p{i:03d}").uid(f"p{i:03d}")
             .namespace("default").req({"cpu": "1", "memory": "1Gi"})
             .label("app", ["web", "db"][i % 2]))
        if i % 6 == 3:
            w = w.node_selector({"disk": "ssd"})
        if i % 6 == 5:
            w = w.preferred_node_affinity(10, "zone", ["z1"])
        store.create("Pod", w.obj())


def _bindings(store):
    pods, _ = store.list("Pod")
    return {p.uid: p.spec.node_name for p in pods}


@needs_devices
def test_live_scheduler_sharded_bindings_match_unsharded():
    """The acceptance oracle: same cluster, same pods — a sharded scheduler
    (encoder mesh + sharded fused cycle program + sharded host auxes) binds
    every pod to exactly the node the unsharded one picks."""
    results = []
    for sharding in ("off", 2):
        store = ObjectStore()
        sched = TPUScheduler(store, batch_size=16, sharding=sharding)
        _populate(store)
        sched.run_until_idle()
        results.append(_bindings(store))
        sched.close()
    off, sharded = results
    assert all(v is not None for v in off.values())
    assert off == sharded


@needs_devices
def test_live_sharded_dedup_and_full_paths_agree():
    """Identity-class dedup rides the sharded program too: sharded+dedup,
    sharded+full, and unsharded+dedup all agree bit-for-bit (dedup disabled
    by forcing the gate closed)."""
    results = []
    for sharding, dedup in (( 2, True), (2, False), ("off", True)):
        store = ObjectStore()
        sched = TPUScheduler(store, batch_size=16, sharding=sharding)
        if not dedup:
            sched._dedup_classes = lambda batch, host_auxes, fw=None: None
        _populate(store, n_nodes=8, n_pods=20)  # contention: identical pods
        sched.run_until_idle()
        results.append(_bindings(store))
        sched.close()
    assert results[0] == results[1] == results[2]


@needs_devices
def test_sharded_scatter_upload_equals_full_and_stays_sharded():
    """Incremental row-scatter into sharded buffers == a full re-upload,
    and the node-tier arrays keep their node-axis sharding afterwards —
    steady-state sync must never silently re-replicate the tier."""
    mesh = node_sharded_mesh(jax.devices()[:2])
    cache = Cache()
    for i in range(20):
        cache.add_node(
            make_node().name(f"n{i:03d}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "110"})
            .label("zone", f"z{i % 3}").obj())
    snap = Snapshot()
    cache.update_snapshot(snap)
    enc = ClusterEncoder()
    enc.set_mesh(mesh)
    enc.full_sync(snap)
    d0 = enc.to_device()
    assert d0.allocatable.sharding.is_equivalent_to(
        node_sharding(mesh, 2), 2)
    assert d0.node_valid.sharding.is_equivalent_to(
        node_sharding(mesh, 1), 1)
    # dirty a few nodes (bound pods) and take the eager scatter path
    for i in range(3):
        cache.add_pod(
            make_pod().name(f"sp{i}").uid(f"sp{i}").namespace("default")
            .req({"cpu": "2", "memory": "1Gi"}).node(f"n{i:03d}").obj())
    changed = cache.update_snapshot(snap)
    enc.sync(snap, changed)
    d1 = enc.to_device()  # scatter path (device present, shapes unchanged)
    assert d1.allocatable.sharding.is_equivalent_to(node_sharding(mesh, 2), 2)
    # oracle: a from-scratch full upload of the same mirrors
    d_full = enc.to_device(force_full=True)
    for name in ("node_valid", "allocatable", "requested",
                 "non_zero_requested", "pod_valid", "pod_node",
                 "pod_request"):
        assert np.array_equal(np.asarray(getattr(d1, name)),
                              np.asarray(getattr(d_full, name))), name


@needs_devices
def test_deferred_scatter_sharded(monkeypatch):
    """to_device_deferred + in-program apply_scatter under the mesh: the
    fused-cycle path's upload.  The small-tier fast path is pinned off so
    the deferred scatter actually runs at test size."""
    monkeypatch.setattr(encoding_mod, "_SMALL_NODE_TIER", 0)
    mesh = node_sharded_mesh(jax.devices()[:2])
    cache = Cache()
    for i in range(16):
        cache.add_node(
            make_node().name(f"n{i:03d}")
            .capacity({"cpu": "8", "memory": "16Gi", "pods": "110"}).obj())
    snap = Snapshot()
    cache.update_snapshot(snap)
    enc = ClusterEncoder()
    enc.set_mesh(mesh)
    enc.full_sync(snap)
    d0, upd0 = enc.to_device_deferred()
    assert upd0 is None  # first upload is full
    enc.commit_device(d0)
    for i in range(2):
        cache.add_pod(
            make_pod().name(f"sp{i}").uid(f"sp{i}").namespace("default")
            .req({"cpu": "1", "memory": "1Gi"}).node(f"n{i:03d}").obj())
    changed = cache.update_snapshot(snap)
    enc.sync(snap, changed)
    d, upd = enc.to_device_deferred()
    assert upd is not None  # steady state scatters
    out = jax.jit(apply_scatter)(d, upd)
    enc.commit_device(out)
    assert out.requested.sharding.is_equivalent_to(node_sharding(mesh, 2), 2)
    # oracle: the mirrors themselves
    assert np.array_equal(np.asarray(out.requested), enc.requested)
    assert np.array_equal(np.asarray(out.pod_valid), enc.pod_valid)


@needs_devices
def test_whatif_forks_sharded_parity():
    """Victim / node-add / node-remove forks over a SHARDED snapshot must
    predict the same placements as over the unsharded one — the
    preemption/descheduler/autoscaler consumers may not silently diverge
    under sharding."""
    from kubernetes_tpu.whatif import ForkSpec, WhatIfEngine

    preds = []
    for sharding in ("off", 2):
        store = ObjectStore()
        sched = TPUScheduler(store, batch_size=16, sharding=sharding)
        _populate(store, n_nodes=6, n_pods=10)
        sched.run_until_idle()
        pods, _ = store.list("Pod")
        victims = [p for p in pods if p.spec.node_name][:2]
        pending = [
            make_pod().name(f"w{i}").uid(f"w{i}").namespace("default")
            .req({"cpu": "2", "memory": "1Gi"}).obj()
            for i in range(4)
        ]
        add = make_node().name("fresh").capacity(
            {"cpu": "8", "memory": "16Gi", "pods": "110"}).obj()
        engine = WhatIfEngine(sched)
        out = engine.evaluate(pending, [
            ForkSpec(victims=victims, note="t"),
            ForkSpec(add_nodes=[add], note="t"),
            ForkSpec(remove_nodes=["n000"], note="t"),
        ])
        assert out is not None
        preds.append([p.placements for p in out])
        sched.close()
    assert preds[0] == preds[1]


@needs_devices
def test_config_plumb_node_axis_sharding():
    from kubernetes_tpu.config import load_config, scheduler_from_config

    cfg = load_config({"apiVersion": "kubescheduler.config.k8s.io/v1beta3",
                       "nodeAxisSharding": 2})
    sched = scheduler_from_config(ObjectStore(), cfg)
    assert sched.mesh is not None and sched.mesh.devices.size == 2
    sched.close()
    cfg_off = load_config({"apiVersion": "kubescheduler.config.k8s.io/v1beta3",
                           "nodeAxisSharding": "off"})
    sched_off = scheduler_from_config(ObjectStore(), cfg_off)
    assert sched_off.mesh is None
    sched_off.close()
    # "auto" on the CPU test backend resolves to off (backend gate)
    cfg_auto = load_config({"apiVersion": "kubescheduler.config.k8s.io/v1beta3"})
    sched_auto = scheduler_from_config(ObjectStore(), cfg_auto)
    assert sched_auto.mesh is None
    sched_auto.close()


def test_mesh_requires_pow2_devices():
    import jax.sharding as js

    enc = ClusterEncoder()
    if len(jax.devices()) >= 3:
        bad = js.Mesh(np.asarray(jax.devices()[:3]), ("nodes",))
        with pytest.raises(ValueError):
            enc.set_mesh(bad)


@pytest.mark.slow
def test_100k_live_smoke():
    """Slow 100k smoke: a LIVE TPUScheduler (store → watch → cache → sync →
    fused dedup cycle → bind) schedules real pods onto a 100,352-node
    HollowCluster — the suite-scale path at tier-1-verifiable size is
    NorthStar/100kNodes (perf/workloads.py); this pins that the runtime
    executes at the full tier at all."""
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=64)
    n = 100_352
    sched.presize(n, 256)
    for i in range(n):
        store.create(
            "Node",
            make_node().name(f"node-{i:06d}")
            .capacity({"cpu": "4", "memory": "32Gi", "pods": "110"}).obj())
    for i in range(64):
        store.create("Pod", make_pod().name(f"p{i}").uid(f"p{i}")
                     .namespace("default")
                     .req({"cpu": "100m", "memory": "500Mi"}).obj())
    stats = sched.run_until_idle()
    assert stats.scheduled == 64
    assert sched.encoder._n >= n
    sched.close()
