"""Multi-tenant API surface: CRD lifecycle, dynamic kind serving, the
TrainingJob custom workload, and registration convergence under faults.

Reference behaviors exercised: apiextensions-apiserver's crdHandler
(customresource_handler.go) — CRD create installs served storage at
runtime, CRD delete cascades the stored CRs and terminates their watches;
structural-schema validation (pkg/apiserver/validation); and the
exactly-once registration discipline a WAL-replayed boot must converge to.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.analysis import lockcheck
from kubernetes_tpu.api.scheme import SchemeError, default_scheme
from kubernetes_tpu.api.serialize import to_manifest
from kubernetes_tpu.apiextensions import (
    CustomResourceDefinition,
    DynamicKindRegistrar,
    attach_registrar,
    make_kind_type,
    validate_structural,
)
from kubernetes_tpu.apiserver import APIServer, HTTPApiClient
from kubernetes_tpu.apiserver.client import HTTPStoreFacade
from kubernetes_tpu.chaos import (
    CRASH_MID_CRD_REGISTER,
    FaultSchedule,
    ProcessCrash,
    WatchDropped,
    crash_schedule,
)
from kubernetes_tpu.controllers.trainingjob import (
    TRAININGJOB_CRD,
    TrainingJobController,
    install_trainingjob_crd,
)
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.sim.wal import WriteAheadLog, replay_on_boot


@pytest.fixture(autouse=True)
def lock_order_monitor():
    mon = lockcheck.activate()
    try:
        yield mon
    finally:
        lockcheck.deactivate()
    assert not mon.violations, mon.report()


WIDGET_CRD = {
    "apiVersion": "apiextensions.k8s.io/v1",
    "kind": "CustomResourceDefinition",
    "metadata": {"name": "widgets.example.com"},
    "spec": {
        "group": "example.com",
        "scope": "Namespaced",
        "names": {"plural": "widgets", "singular": "widget",
                  "kind": "Widget"},
        "versions": [{
            "name": "v1", "served": True, "storage": True,
            "schema": {"openAPIV3Schema": {
                "type": "object",
                "properties": {"spec": {
                    "type": "object",
                    "required": ["size"],
                    "properties": {
                        "size": {"type": "integer", "minimum": 1},
                        "color": {"type": "string",
                                  "enum": ["red", "blue"]},
                    },
                }},
            }},
        }],
    },
}


def widget_manifest(name, size=3, ns="default", **extra):
    spec = {"size": size, **extra}
    return {"apiVersion": "example.com/v1", "kind": "Widget",
            "metadata": {"name": name, "namespace": ns}, "spec": spec}


def _live(scheme=None):
    """store + scheme + attached registrar (the serving wiring)."""
    store = ObjectStore()
    scheme = scheme or default_scheme()
    reg = attach_registrar(store, scheme)
    return store, scheme, reg


# --- registrar: install / idempotency / conflict ------------------------------


def test_crd_create_installs_kind_and_delete_cascades():
    store, scheme, reg = _live()
    store.create("CustomResourceDefinition", scheme.decode(WIDGET_CRD))
    assert "Widget" in scheme.kind_types()
    assert reg.installed_kinds() == {"widgets.example.com": "Widget"}
    store.create("Widget", scheme.decode(widget_manifest("w1")))
    store.create("Widget", scheme.decode(widget_manifest("w2")))
    assert len(store.list("Widget")[0]) == 2
    store.delete("CustomResourceDefinition", "", "widgets.example.com")
    # kind gone from the scheme, stored CRs cascaded out
    assert "Widget" not in scheme.kind_types()
    assert store.list("Widget")[0] == []
    assert reg.installed_kinds() == {}


def test_replayed_crd_event_is_idempotent():
    store, scheme, reg = _live()
    crd = scheme.decode(WIDGET_CRD)
    store.create("CustomResourceDefinition", crd)
    typ0 = scheme.kind_types()["Widget"][2]
    # a second registrar attach replays history — same registration object
    reg2 = DynamicKindRegistrar(store, scheme).attach()
    assert scheme.kind_types()["Widget"][2] is typ0
    reg2.close()
    # resync (the recovery path) is equally a no-op
    reg.resync()
    assert scheme.kind_types()["Widget"][2] is typ0


def test_crd_shadowing_builtin_kind_is_refused():
    store, scheme, reg = _live()
    bad = {**WIDGET_CRD, "metadata": {"name": "pods.example.com"},
           "spec": {**WIDGET_CRD["spec"],
                    "names": {"plural": "pods", "singular": "pod",
                              "kind": "Pod"}}}
    store.create("CustomResourceDefinition", scheme.decode(bad))
    # built-in Pod still served by the hand-written type
    typ = scheme.kind_types()["Pod"][2]
    assert not getattr(typ, "_custom_resource", False)
    assert reg.installed_kinds() == {}


def test_crd_update_reinstalls_under_same_kind():
    store, scheme, reg = _live()
    store.create("CustomResourceDefinition", scheme.decode(WIDGET_CRD))
    typ0 = scheme.kind_types()["Widget"][2]
    upd = json.loads(json.dumps(WIDGET_CRD))
    upd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]["properties"][
        "spec"]["properties"]["size"]["minimum"] = 2
    crd = scheme.decode(upd)
    old = store.get("CustomResourceDefinition", "", "widgets.example.com")
    crd.metadata.resource_version = old.metadata.resource_version
    store.update("CustomResourceDefinition", crd)
    typ1 = scheme.kind_types()["Widget"][2]
    assert typ1 is not typ0 and typ1._fingerprint != typ0._fingerprint
    # the tightened schema is live
    with pytest.raises(ValueError):
        typ1.from_dict(widget_manifest("w", size=1))


# --- structural schema --------------------------------------------------------


def test_structural_schema_validation():
    schema = WIDGET_CRD["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
    assert validate_structural(schema, widget_manifest("ok")) == []
    assert validate_structural(
        schema, {"spec": {}})  # missing required size
    assert validate_structural(
        schema, {"spec": {"size": 0}})  # below minimum
    assert validate_structural(
        schema, {"spec": {"size": "three"}})  # wrong type
    assert validate_structural(
        schema, {"spec": {"size": 2, "color": "green"}})  # enum violation


# --- HTTP serving: CRUD / watch / pagination, both codecs ---------------------


@pytest.mark.parametrize("codec", ["wire", "json"])
def test_cr_crud_watch_pagination_over_http(codec):
    store, scheme, reg = _live()
    srv = APIServer(store, scheme).start()
    try:
        client = HTTPApiClient(srv.url, scheme=scheme, codec=codec)
        fac = HTTPStoreFacade(client)
        fac.create("CustomResourceDefinition", scheme.decode(WIDGET_CRD))
        events, errors = [], []
        done = threading.Event()
        stop = client.watch_kind(
            "Widget",
            lambda ev: (events.append((ev.type, ev.obj.metadata.name)),
                        done.set() if len(events) >= 4 else None),
            on_error=lambda e: errors.append(e))
        for i in range(3):
            fac.create("Widget",
                       scheme.decode(widget_manifest(f"w{i}", size=i + 1)))
        # update via CAS
        w0 = fac.get("Widget", "default", "w0")
        w0.body["spec"]["size"] = 9
        fac.update("Widget", w0)
        assert done.wait(5.0)
        assert [e for e in events if e[0] == "ADDED"] == [
            ("ADDED", "w0"), ("ADDED", "w1"), ("ADDED", "w2")]
        assert ("MODIFIED", "w0") in events
        stop()
        # rv-pinned pagination: 2-page walk over the 3 CRs
        page1, rv1, cont = client.list_page("Widget", limit=2)
        assert len(page1) == 2 and cont
        page2, rv2, cont2 = client.list_page("Widget", limit=2,
                                             continue_=cont)
        assert rv2 == rv1 and cont2 == ""
        names = {o.metadata.name for o in page1 + page2}
        assert names == {"w0", "w1", "w2"}
        assert fac.get("Widget", "default", "w0").body["spec"]["size"] == 9
        fac.delete("Widget", "default", "w2")
        assert fac.get("Widget", "default", "w2") is None
    finally:
        srv.stop()


def test_crd_delete_terminates_watch_and_404s():
    store, scheme, reg = _live()
    srv = APIServer(store, scheme).start()
    try:
        # client with its OWN scheme, minting the kind from the CRD
        # manifest — the realistic remote-tenant shape (no shared scheme)
        cscheme = default_scheme()
        crd = CustomResourceDefinition.from_dict(WIDGET_CRD)
        cscheme.add_known_type(crd.group, crd.storage_version,
                               make_kind_type(crd))
        client = HTTPApiClient(srv.url, scheme=cscheme)
        fac = HTTPStoreFacade(client)
        fac.create("CustomResourceDefinition",
                   cscheme.decode(WIDGET_CRD))
        fac.create("Widget", cscheme.decode(widget_manifest("w")))
        events, errors = [], []
        dropped = threading.Event()
        client.watch_kind(
            "Widget", lambda ev: events.append(ev.type),
            on_error=lambda e: (errors.append(e), dropped.set()))
        deadline = time.monotonic() + 5.0
        while "ADDED" not in events and time.monotonic() < deadline:
            time.sleep(0.02)
        fac.delete("CustomResourceDefinition", "", "widgets.example.com")
        assert dropped.wait(5.0)
        # ordered drain THEN termination: the cascade's DELETED arrived
        # before the stream dropped
        assert events == ["ADDED", "DELETED"]
        assert isinstance(errors[0], WatchDropped)
        # the plural no longer serves
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"{srv.url}/apis/example.com/v1/namespaces/default/widgets")
        assert e.value.code == 404
    finally:
        srv.stop()


def test_invalid_cr_rejected_over_http():
    store, scheme, reg = _live()
    srv = APIServer(store, scheme).start()
    try:
        client = HTTPApiClient(srv.url, scheme=scheme)
        HTTPStoreFacade(client).create(
            "CustomResourceDefinition", scheme.decode(WIDGET_CRD))
        bad = widget_manifest("w", size=0)  # minimum violation
        req = urllib.request.Request(
            f"{srv.url}/apis/example.com/v1/namespaces/default/widgets",
            method="POST", data=json.dumps(bad).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req)
        assert e.value.code == 400
    finally:
        srv.stop()


# --- WAL replay / cold start --------------------------------------------------


def test_wal_replay_rebuilds_dynamic_kinds_before_crs(tmp_path):
    path = str(tmp_path / "store.wal")
    scheme = default_scheme()
    store = ObjectStore(wal=WriteAheadLog(path))
    reg = attach_registrar(store, scheme)
    store.create("CustomResourceDefinition", scheme.decode(WIDGET_CRD))
    store.create("Widget", scheme.decode(widget_manifest("w1", size=5)))
    store.wal.close()
    # successor boot: FRESH scheme — the CRD record must install the kind
    # before the Widget record decodes
    scheme2 = default_scheme()
    replay = replay_on_boot(path, scheme=scheme2)
    assert replay.records_applied == 2
    assert "Widget" in scheme2.kind_types()
    w = replay.store.get("Widget", "default", "w1")
    assert w.body["spec"]["size"] == 5
    # the replayed registrar keeps serving: a new CRD installs live
    more = {**WIDGET_CRD, "metadata": {"name": "gauges.example.com"},
            "spec": {**WIDGET_CRD["spec"],
                     "names": {"plural": "gauges", "singular": "gauge",
                               "kind": "Gauge"}}}
    replay.store.create("CustomResourceDefinition", scheme2.decode(more))
    assert "Gauge" in scheme2.kind_types()


def test_crash_mid_crd_register_converges_exactly_once(tmp_path):
    """Kill between the CRD's durable write and the scheme registration;
    the successor's replay + resync must serve the kind exactly once."""
    path = str(tmp_path / "store.wal")
    scheme = default_scheme()
    store = ObjectStore(wal=WriteAheadLog(path))
    attach_registrar(store, scheme)
    sched = FaultSchedule(seed=7)
    sched.arm_crash(CRASH_MID_CRD_REGISTER, at_hit=1)
    with crash_schedule(sched):
        with pytest.raises(ProcessCrash):
            store.create("CustomResourceDefinition",
                         scheme.decode(WIDGET_CRD))
    store.wal.close()
    # pre-crash state: CRD durable+stored, kind NOT served
    assert "Widget" not in scheme.kind_types()
    scheme2 = default_scheme()
    replay = replay_on_boot(path, scheme=scheme2)
    assert replay.records_applied == 1
    assert "Widget" in scheme2.kind_types()
    m = replay.store.get("CustomResourceDefinition", "",
                         "widgets.example.com")
    assert m is not None
    # exactly once: the registration is the single live one and CRs serve
    replay.store.create("Widget",
                        scheme2.decode(widget_manifest("w", size=2)))
    assert len(replay.store.list("Widget")[0]) == 1


# --- chaos: registration convergence under a fault storm ----------------------


def test_crd_churn_under_fault_storm_leaves_zero_ghost_kinds():
    """Install/uninstall churn with injected 429s on the cascade path:
    after resync, served kinds == stored CRDs exactly (no ghosts)."""
    fault = FaultSchedule(seed=11, write_429_rate=0.3)
    store = ObjectStore(fault_injector=fault)
    scheme = default_scheme()
    reg = attach_registrar(store, scheme)
    kinds = [("sprockets.example.com", "Sprocket", "sprockets"),
             ("cogs.example.com", "Cog", "cogs"),
             ("flanges.example.com", "Flange", "flanges")]
    for crd_name, kind, plural in kinds:
        manifest = {**WIDGET_CRD, "metadata": {"name": crd_name},
                    "spec": {**WIDGET_CRD["spec"],
                             "names": {"plural": plural,
                                       "singular": plural[:-1],
                                       "kind": kind}}}
        for attempt in range(50):
            try:
                store.create("CustomResourceDefinition",
                             scheme.decode(manifest))
                break
            except Exception:
                continue
        for i in range(3):
            cr = {"apiVersion": "example.com/v1", "kind": kind,
                  "metadata": {"name": f"{plural}-{i}",
                               "namespace": "default"},
                  "spec": {"size": 1}}
            for attempt in range(50):
                try:
                    store.create(kind, scheme.decode(cr))
                    break
                except Exception:
                    continue
    # delete two CRDs under the storm: cascades may defer on 429
    for crd_name, _, _ in kinds[:2]:
        for attempt in range(50):
            try:
                store.delete("CustomResourceDefinition", "", crd_name)
                break
            except Exception:
                continue
    for _ in range(50):  # convergence loop: resync retries parked cascades
        reg.resync()
        if (not store.list("Sprocket")[0]
                and not store.list("Cog")[0]):
            break
    assert "Sprocket" not in scheme.kind_types()
    assert "Cog" not in scheme.kind_types()
    assert "Flange" in scheme.kind_types()
    assert store.list("Sprocket")[0] == []
    assert store.list("Cog")[0] == []
    assert len(store.list("Flange")[0]) == 3
    assert reg.installed_kinds() == {"flanges.example.com": "Flange"}


# --- TrainingJob: the custom workload rides the gang + claim path -------------


def _tpu_cluster(store):
    from kubernetes_tpu.dra.api import (ATTR_CHIP_INDEX, ATTR_HOST,
                                        ATTR_SLICE, Device, DeviceClass,
                                        ResourceSlice)
    from kubernetes_tpu.gang import SLICE_LABEL
    from kubernetes_tpu.testutil import make_node

    dc = DeviceClass()
    dc.metadata.name = "tpu"
    store.create("DeviceClass", dc)
    for i in range(4):
        pool = f"s{i // 2}"
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "4", "memory": "32Gi", "pods": "20"})
                     .label(SLICE_LABEL, pool).obj())
        sl = ResourceSlice(node_name=f"n{i}", pool=pool, devices=[
            Device(name=f"n{i}-chip{j}", attributes={
                ATTR_SLICE: pool, ATTR_HOST: f"n{i}",
                ATTR_CHIP_INDEX: str(j)}) for j in range(4)])
        sl.metadata.name = f"rs-n{i}"
        store.create("ResourceSlice", sl)


def test_trainingjob_expands_and_gang_schedules_end_to_end():
    from kubernetes_tpu.scheduler import TPUScheduler

    store, scheme, reg = _live()
    install_trainingjob_crd(store, scheme)
    assert "TrainingJob" in scheme.kind_types()
    _tpu_cluster(store)
    job = scheme.decode({
        "apiVersion": "workloads.tpu.dev/v1", "kind": "TrainingJob",
        "metadata": {"name": "mnist", "namespace": "default"},
        "spec": {"replicas": 2, "chipsPerReplica": 4}})
    store.create("TrainingJob", job)
    ctrl = TrainingJobController(store)
    assert ctrl.sync_once()          # expansion creates objects
    assert not ctrl.sync_once()      # steady state: exactly-once
    pg = store.get("PodGroup", "default", "tj-mnist")
    assert pg.min_member == 2
    pods, _ = store.list("Pod")
    assert sorted(p.metadata.name for p in pods) == \
        ["tj-mnist-0", "tj-mnist-1"]
    owner = pods[0].metadata.owner_references[0]
    assert owner.kind == "TrainingJob" and owner.name == "mnist"
    sched = TPUScheduler(store, batch_size=8, batch_wait=0)
    assert sched.run_until_idle(max_cycles=10).scheduled == 2
    slices = set()
    for i in range(2):
        p = store.get("Pod", "default", f"tj-mnist-{i}")
        c = store.get("ResourceClaim", "default", f"tj-mnist-{i}")
        assert p.spec.node_name and c.allocated_node == p.spec.node_name
        assert len(c.allocated_devices) == 4
        slices.add(p.spec.node_name)
    assert len(slices) == 2  # one member per host, whole chips each
    ctrl.sync_once()
    j = store.get("TrainingJob", "default", "mnist")
    assert j.body["status"] == {"phase": "Running", "boundReplicas": 2}


def test_trainingjob_schema_rejects_bad_spec():
    store, scheme, reg = _live()
    install_trainingjob_crd(store, scheme)
    typ = scheme.kind_types()["TrainingJob"][2]
    with pytest.raises(ValueError):
        typ.from_dict({"apiVersion": "workloads.tpu.dev/v1",
                       "kind": "TrainingJob",
                       "metadata": {"name": "bad"},
                       "spec": {"replicas": 0, "chipsPerReplica": 4}})
    with pytest.raises(ValueError):
        typ.from_dict({"apiVersion": "workloads.tpu.dev/v1",
                       "kind": "TrainingJob",
                       "metadata": {"name": "bad"},
                       "spec": {"replicas": 2}})  # chipsPerReplica required


def test_cli_dynamic_discovery_and_crd_get():
    from kubernetes_tpu.cli import Kubectl

    store, scheme, reg = _live()
    install_trainingjob_crd(store, scheme)
    store.create("TrainingJob", scheme.decode({
        "apiVersion": "workloads.tpu.dev/v1", "kind": "TrainingJob",
        "metadata": {"name": "mnist", "namespace": "default"},
        "spec": {"replicas": 2, "chipsPerReplica": 4}}))
    import kubernetes_tpu.cli as cli_mod
    cli_mod._scheme_cache.clear()
    try:
        k = Kubectl(store)
        out = k.get("trainingjobs")  # plural → dynamic discovery
        assert "mnist" in out and "NAME" in out and "AGE" in out
        assert "mnist" in k.describe("trainingjob", "default", "mnist")
    finally:
        cli_mod._scheme_cache.clear()
