"""End-to-end: sim store → watch → cache/queue → device cycle → binding.

Reference analog: test/integration/scheduler (real apiserver, API-object nodes,
no kubelet — util.go:56,76).
"""

import numpy as np
import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_schedule_and_bind_basic():
    store = ObjectStore()
    clock = FakeClock()
    sched = TPUScheduler(store, batch_size=8, clock=clock)
    for i in range(4):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "4", "memory": "8Gi", "pods": "110"}).obj())
    for i in range(6):
        store.create("Pod", make_pod().name(f"p{i}").uid(f"p{i}")
                     .namespace("default").req({"cpu": "1"}).obj())
    stats = sched.run_until_idle()
    assert stats.scheduled == 6
    pods, _ = store.list("Pod")
    assert all(p.spec.node_name for p in pods)
    # resources respected: 4 cpu per node, 1 cpu pods → ≤4 per node... with
    # spreading the 6 pods must land on ≥2 distinct nodes
    assert len({p.spec.node_name for p in pods}) >= 2


def test_unschedulable_requeued_on_node_add():
    store = ObjectStore()
    clock = FakeClock()
    sched = TPUScheduler(store, batch_size=8, clock=clock)
    store.create("Node", make_node().name("small")
                 .capacity({"cpu": "1", "memory": "1Gi", "pods": "10"}).obj())
    store.create("Pod", make_pod().name("big").uid("big").namespace("default")
                 .req({"cpu": "8"}).obj())
    stats = sched.run_until_idle()
    assert stats.unschedulable == 1
    assert sched.queue.pending_count()[2] == 1  # parked in unschedulableQ

    # adding a big node fires NodeAdd → pod requeues (Fit registered NodeAdd)
    store.create("Node", make_node().name("big-node")
                 .capacity({"cpu": "16", "memory": "32Gi", "pods": "110"}).obj())
    clock.advance(2.0)  # clear backoff
    stats = sched.run_until_idle()
    assert stats.scheduled == 1
    assert store.get("Pod", "default", "big").spec.node_name == "big-node"


def test_binding_confirmed_via_watch():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    store.create("Node", make_node().name("n0").obj())
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1"}).obj())
    sched.run_until_idle()
    # the bind write produced a MODIFIED event that confirms the assumed pod
    assert not sched.cache.is_assumed(store.get("Pod", "default", "p"))
    assert sched.cache.pod_count() == 1


def test_node_selector_respected_e2e():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    store.create("Node", make_node().name("ssd").label("disk", "ssd").obj())
    store.create("Node", make_node().name("hdd").label("disk", "hdd").obj())
    store.create("Pod", make_pod().name("p").uid("p").namespace("default")
                 .req({"cpu": "1"}).node_selector({"disk": "hdd"}).obj())
    stats = sched.run_until_idle()
    assert stats.scheduled == 1
    assert store.get("Pod", "default", "p").spec.node_name == "hdd"


def test_scheduler_emits_events():
    """Scheduled / FailedScheduling land in the store (scheduler.go:386,488)."""
    from kubernetes_tpu.sim.store import ObjectStore
    from kubernetes_tpu.scheduler import TPUScheduler
    from kubernetes_tpu.testutil import make_node, make_pod

    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    store.create("Node", make_node().name("n0").capacity(
        {"cpu": "2", "memory": "4Gi", "pods": "10"}).obj())
    store.create("Pod", make_pod().name("ok").uid("ok").namespace("default")
                 .req({"cpu": "1"}).obj())
    store.create("Pod", make_pod().name("huge").uid("huge").namespace("default")
                 .req({"cpu": "64"}).obj())
    sched.schedule_cycle()
    events, _ = store.list("Event")
    by_reason = {e.reason: e for e in events}
    assert "Scheduled" in by_reason
    assert "Pod/default/ok" == by_reason["Scheduled"].involved_object
    assert "n0" in by_reason["Scheduled"].message
    assert "FailedScheduling" in by_reason
    assert by_reason["FailedScheduling"].type == "Warning"
    assert "NodeResourcesFit" in by_reason["FailedScheduling"].message
