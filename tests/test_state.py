"""Cache (assume lifecycle, O(changed) snapshot) and device encoding tests."""

import numpy as np

from kubernetes_tpu.state.cache import Cache, Snapshot
from kubernetes_tpu.state.encoding import ClusterEncoder, EncodingConfig
from kubernetes_tpu.state import units
from kubernetes_tpu.testutil import make_node, make_pod


def _cluster(n=4):
    cache = Cache()
    for i in range(n):
        cache.add_node(
            make_node().name(f"n{i}").capacity({"cpu": "8", "memory": "16Gi", "pods": "110"})
            .label("zone", f"z{i % 2}").obj()
        )
    return cache


def test_snapshot_incremental():
    cache = _cluster(4)
    snap = Snapshot()
    changed = cache.update_snapshot(snap)
    assert sorted(changed) == ["n0", "n1", "n2", "n3"]
    assert snap.num_nodes() == 4

    # no changes -> no churn
    assert cache.update_snapshot(snap) == []

    # add a pod to n1 only -> only n1 changes
    p = make_pod().name("p1").uid("u1").req({"cpu": "2"}).obj()
    cache.assume_pod(p, "n1")
    assert cache.update_snapshot(snap) == ["n1"]
    assert snap.get("n1").requested.milli_cpu == 2000

    # remove node
    cache.remove_node("n3")
    changed = cache.update_snapshot(snap)
    assert "n3" in changed and snap.num_nodes() == 3


def test_assume_forget_expire():
    cache = _cluster(1)
    p = make_pod().name("p").uid("up").req({"cpu": "1"}).obj()
    cache.assume_pod(p, "n0")
    assert cache.is_assumed(p)
    snap = Snapshot()
    cache.update_snapshot(snap)
    assert snap.get("n0").requested.milli_cpu == 1000

    cache.forget_pod(p)
    cache.update_snapshot(snap)
    assert snap.get("n0").requested.milli_cpu == 0

    # assume again, finish binding, then expire
    now = [100.0]
    cache2 = Cache(ttl=10.0, clock=lambda: now[0])
    cache2.add_node(make_node().name("n0").obj())
    p2 = make_pod().name("p2").uid("up2").req({"cpu": "1"}).obj()
    cache2.assume_pod(p2, "n0")
    cache2.finish_binding(p2)
    assert cache2.cleanup_expired() == []
    now[0] = 111.0
    assert [q.uid for q in cache2.cleanup_expired()] == ["up2"]
    snap2 = Snapshot()
    cache2.update_snapshot(snap2)
    assert snap2.get("n0").requested.milli_cpu == 0


def test_add_confirms_assumed():
    cache = _cluster(2)
    p = make_pod().name("p").uid("u").req({"cpu": "1"}).obj()
    cache.assume_pod(p, "n0")
    # watch event confirms on a different node (another scheduler instance won)
    import copy

    confirmed = copy.deepcopy(p)
    confirmed.spec.node_name = "n1"
    cache.add_pod(confirmed)
    snap = Snapshot()
    cache.update_snapshot(snap)
    assert snap.get("n0").requested.milli_cpu == 0
    assert snap.get("n1").requested.milli_cpu == 1000
    assert not cache.is_assumed(p)


def test_encoding_units_and_incremental_sync():
    cache = _cluster(3)
    p = (
        make_pod().name("p").uid("u").namespace("prod")
        .req({"cpu": "1500m", "memory": "1Gi", "nvidia.com/gpu": "2"})
        .label("app", "web")
        .obj()
    )
    cache.assume_pod(p, "n1")
    snap = Snapshot()
    changed = cache.update_snapshot(snap)
    enc = ClusterEncoder(cfg=EncodingConfig(min_nodes=8, min_pods=8))
    enc.sync(snap, changed)
    dev = enc.to_device()

    row = enc.node_rows["n1"]
    assert bool(dev.node_valid[row])
    np.testing.assert_array_equal(
        np.asarray(dev.allocatable[row])[: units.NUM_BASE_DIMS],
        [8000, 16 * 1024 * 1024, 0, 110],  # cpu milli, mem KiB, eph MiB, pods
    )
    gpu_dim = enc.extended_index["nvidia.com/gpu"]
    assert int(dev.requested[row, units.DIM_CPU]) == 1500
    assert int(dev.requested[row, units.DIM_MEMORY]) == 1024 * 1024
    assert int(dev.requested[row, units.DIM_PODS]) == 1
    assert int(dev.requested[row, gpu_dim]) == 2

    # pod row encoded
    prow = enc.pod_rows["u"]
    assert bool(dev.pod_valid[prow])
    assert int(dev.pod_node[prow]) == row
    assert int(dev.pod_request[prow, units.DIM_CPU]) == 1500

    # incremental: second pod on n2; only n2's row is dirty
    p2 = make_pod().name("p2").uid("u2").req({"cpu": "250m"}).obj()
    cache.assume_pod(p2, "n2")
    changed = cache.update_snapshot(snap)
    assert changed == ["n2"]
    enc.sync(snap, changed)
    dev2 = enc.to_device()
    assert int(dev2.requested[enc.node_rows["n2"], units.DIM_CPU]) == 250
    # n1 untouched
    assert int(dev2.requested[row, units.DIM_CPU]) == 1500

    # remove the pod: row freed
    cache.remove_pod(p)
    changed = cache.update_snapshot(snap)
    enc.sync(snap, changed)
    dev3 = enc.to_device()
    assert not bool(dev3.pod_valid[prow])
    assert int(dev3.requested[row, units.DIM_CPU]) == 0


def test_encoder_growth():
    cache = Cache()
    enc = ClusterEncoder(cfg=EncodingConfig(min_nodes=8, min_pods=8))
    snap = Snapshot()
    for i in range(20):  # > min_nodes
        cache.add_node(make_node().name(f"n{i}").obj())
    changed = cache.update_snapshot(snap)
    enc.sync(snap, changed)
    dev = enc.to_device()
    assert dev.num_nodes >= 20
    assert int(np.asarray(dev.node_valid).sum()) == 20
