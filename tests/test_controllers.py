"""Controller loops + hollow nodes: reconcile, failure detection, elastic
rescheduling (reference scenarios: replicaset/deployment/job controller tests +
nodelifecycle NoExecute eviction)."""

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.controllers import (
    ControllerManager,
    DeploymentController,
    GarbageCollector,
    JobController,
    NodeLifecycleController,
    ReplicaSetController,
)
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.hollow_node import HollowCluster, HollowNode
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def mk_rs(name, replicas, labels=None):
    rs = v1.ReplicaSet(replicas=replicas)
    rs.metadata.name = name
    rs.template = v1.PodTemplateSpec(labels=dict(labels or {"app": name}))
    rs.template.spec.containers = [
        v1.Container(name="c0", image="pause",
                     resources=v1.ResourceRequirements(requests={"cpu": "1"}))
    ]
    return rs


def test_replicaset_scales_up_and_down():
    store = ObjectStore()
    rsc = ReplicaSetController(store)
    store.create("ReplicaSet", mk_rs("web", 3))
    rsc.sync_once()
    assert len(store.list("Pod")[0]) == 3
    rs = store.get("ReplicaSet", "default", "web")
    rs.replicas = 1
    store.update("ReplicaSet", rs)
    rsc.sync_once()
    assert len(store.list("Pod")[0]) == 1


def test_deployment_creates_rs_and_rolls():
    store = ObjectStore()
    dc, rsc = DeploymentController(store), ReplicaSetController(store)
    dep = v1.Deployment(replicas=2)
    dep.metadata.name = "api"
    dep.template = v1.PodTemplateSpec(labels={"app": "api"})
    dep.template.spec.containers = [v1.Container(name="c0", image="v1")]
    store.create("Deployment", dep)
    dc.sync_once()
    rsc.sync_once()
    assert len(store.list("ReplicaSet")[0]) == 1
    assert len(store.list("Pod")[0]) == 2
    # template change → new RS, old scaled to 0
    dep.template.spec.containers = [v1.Container(name="c0", image="v2")]
    store.update("Deployment", dep)
    dc.sync_once()
    rsc.sync_once()
    rss = store.list("ReplicaSet")[0]
    assert len(rss) == 2
    assert sorted(rs.replicas for rs in rss) == [0, 2]


def test_job_runs_to_completion():
    store = ObjectStore()
    jc = JobController(store)
    job = v1.Job(completions=2, parallelism=1)
    job.metadata.name = "batch"
    store.create("Job", job)
    node = HollowNode(store, "n0")
    node.register()
    for _ in range(6):
        jc.sync_once()
        for p in store.list("Pod")[0]:
            if p.status.phase != v1.POD_SUCCEEDED:
                p.spec.node_name = "n0"
                node.complete_pod(p)
    assert store.get("Job", "default", "batch").completed


def test_gc_cascades_on_owner_delete():
    store = ObjectStore()
    rsc, gc = ReplicaSetController(store), GarbageCollector(store)
    store.create("ReplicaSet", mk_rs("web", 2))
    rsc.sync_once()
    store.delete("ReplicaSet", "default", "web")
    gc.sync_once()
    assert len(store.list("Pod")[0]) == 0


def test_node_failure_evicts_and_reschedules():
    """The full elastic loop: node dies → lease stale → taint + evict →
    ReplicaSet recreates → scheduler places on the surviving node."""
    store = ObjectStore()
    clock = FakeClock()
    sched = TPUScheduler(store, batch_size=8, clock=clock)
    cluster = HollowCluster(store, 2, clock=clock)
    cm = ControllerManager(store, clock=clock)
    cm.register(ReplicaSetController(store))
    cm.register(NodeLifecycleController(store, grace_period=40.0, clock=clock))
    cm.register(GarbageCollector(store))

    store.create("ReplicaSet", mk_rs("web", 2))
    cm.sync_all()
    sched.run_until_idle()
    cluster.sync_all()
    pods = store.list("Pod")[0]
    assert all(p.spec.node_name for p in pods)
    victim_node = pods[0].spec.node_name
    survivor = next(n for n in cluster.nodes if n.name != victim_node)

    # the node holding pods[0] dies
    next(n for n in cluster.nodes if n.name == victim_node).fail()
    clock.advance(50.0)
    survivor.heartbeat()
    cm.sync_all()  # lifecycle taints + evicts; RS recreates
    sched.run_until_idle()
    pods = store.list("Pod")[0]
    assert len(pods) == 2
    assert all(p.spec.node_name == survivor.name for p in pods)
