"""kubectl-style CLI verbs + PriorityClass admission."""

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.cli import Kubectl
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod


def test_get_and_describe():
    store = ObjectStore()
    store.create("Node", make_node().name("n0").obj())
    store.create("Pod", make_pod().name("p").uid("p").node("n0").obj())
    k = Kubectl(store)
    out = k.get("pods")
    assert "NAME" in out and "p" in out and "n0" in out
    assert '"name": "p"' in k.describe("pod", "default", "p")


def test_apply_yaml_and_scale():
    store = ObjectStore()
    k = Kubectl(store)
    msgs = k.apply("""
apiVersion: apps/v1
kind: ReplicaSet
metadata:
  name: web
  namespace: default
spec:
  replicas: 2
  template:
    metadata:
      labels: {app: web}
    spec:
      containers:
        - name: c0
          image: pause
---
apiVersion: v1
kind: Node
metadata:
  name: n0
""")
    assert msgs == ["replicaset/web created", "node/n0 created"]
    assert "scaled to 5" in k.scale("rs", "default", "web", 5)
    assert store.get("ReplicaSet", "default", "web").replicas == 5


def test_cordon_taint_drain():
    store = ObjectStore()
    store.create("Node", make_node().name("n0").obj())
    store.create("Pod", make_pod().name("p").uid("p").node("n0").obj())
    k = Kubectl(store)
    k.cordon("n0")
    assert store.get("Node", "", "n0").spec.unschedulable
    k.taint("n0", "maintenance", effect=v1.TAINT_NO_EXECUTE)
    assert any(t.key == "maintenance" for t in store.get("Node", "", "n0").spec.taints)
    out = k.drain("n0")
    assert "1 pods evicted" in out
    assert store.get("Pod", "default", "p") is None


def test_priority_class_admission():
    store = ObjectStore()
    pc = v1.PriorityClass(value=1000)
    pc.metadata.name = "high"
    store.create("PriorityClass", pc)
    default_pc = v1.PriorityClass(value=7, global_default=True)
    default_pc.metadata.name = "default-pc"
    store.create("PriorityClass", default_pc)

    p1 = make_pod().name("p1").uid("p1").obj()
    p1.spec.priority_class_name = "high"
    store.create("Pod", p1)
    assert p1.spec.priority == 1000

    p2 = make_pod().name("p2").uid("p2").obj()
    store.create("Pod", p2)
    assert p2.spec.priority == 7  # global default applied


def test_scheduler_binary_entry():
    """python -m kubernetes_tpu --sim-nodes/--sim-pods runs end to end
    (cmd/kube-scheduler flag layer analog)."""
    from kubernetes_tpu.__main__ import main

    rc = main(["--sim-nodes", "8", "--sim-pods", "16", "--batch-size", "8"])
    assert rc == 0


def test_scheduler_binary_with_config(tmp_path):
    cfg = tmp_path / "cfg.json"
    cfg.write_text(
        '{"apiVersion": "kubescheduler.config.k8s.io/v1beta3",'
        ' "profiles": [{"schedulerName": "default-scheduler"}]}'
    )
    from kubernetes_tpu.__main__ import main

    rc = main(["--config", str(cfg), "--sim-nodes", "4", "--sim-pods", "4",
               "--batch-size", "4", "--leader-elect"])
    assert rc == 0


def test_label_annotate_patch_rollout_and_json():
    """Round-5 verb additions: label/annotate (add + remove), merge patch,
    rollout status, get -o json."""
    import json

    from kubernetes_tpu.api import objects as v1

    store = ObjectStore()
    k = Kubectl(store)
    store.create("Node", make_node().name("n1").capacity({"cpu": "4"}).obj())
    assert "labeled" in k.label("node", "", "n1", "tier", "gold")
    assert store.get("Node", "", "n1").metadata.labels["tier"] == "gold"
    assert "labeled" in k.label("node", "", "n1", "tier", None)
    assert "tier" not in store.get("Node", "", "n1").metadata.labels
    assert "annotated" in k.annotate("node", "", "n1", "note", "x")
    assert store.get("Node", "", "n1").metadata.annotations["note"] == "x"

    # merge patch through the scheme
    assert "patched" in k.patch(
        "node", "", "n1", json.dumps({"metadata": {"labels": {"zone": "a"}}}))
    assert store.get("Node", "", "n1").metadata.labels["zone"] == "a"

    # get -o json emits the wire manifest
    out = json.loads(k.get_json("node", "", "n1"))
    assert out["kind"] == "Node" and out["metadata"]["name"] == "n1"

    # rollout status: only the CURRENT-template-hash ReplicaSet counts (an
    # old RS's ready pods must not report the rollout done)
    from kubernetes_tpu.controllers.deployment import _template_hash

    dep = v1.Deployment(metadata=v1.ObjectMeta(name="web", namespace="default"),
                        replicas=2)
    store.create("Deployment", dep)
    stale = v1.ReplicaSet(metadata=v1.ObjectMeta(
        name="web-oldhash", namespace="default",
        owner_references=[v1.OwnerReference(kind="Deployment", name="web",
                                            uid=dep.metadata.uid)]),
        replicas=2)
    stale.status_ready_replicas = 2  # ready but NOT the current template
    store.create("ReplicaSet", stale)
    rs = v1.ReplicaSet(metadata=v1.ObjectMeta(
        name=f"web-{_template_hash(dep.template)}", namespace="default",
        owner_references=[v1.OwnerReference(kind="Deployment", name="web",
                                            uid=dep.metadata.uid)]),
        replicas=2)
    rs.status_ready_replicas = 0
    store.create("ReplicaSet", rs)
    assert "Waiting for rollout" in k.rollout_status("deploy", "default", "web")
    rs.status_ready_replicas = 2
    store.update("ReplicaSet", rs)
    assert "successfully rolled out" in k.rollout_status("deploy", "default", "web")


def test_topology_verb():
    """ktpu topology: device table + shard line, live mesh view when an
    in-process scheduler owns one."""
    import jax

    from kubernetes_tpu.scheduler import TPUScheduler

    store = ObjectStore()
    k = Kubectl(store)
    store.create("Node", make_node().name("n0").obj())
    out = k.topology()
    assert "DEVICE" in out and "node-axis sharding: off" in out
    assert "1 Node objects" in out
    if len(jax.devices()) >= 2:
        sched = TPUScheduler(store, sharding=2)
        out = k.topology(scheduler=sched)
        assert "node-axis sharding: on — 2 devices" in out
        rows_per_shard = sched.encoder._n // 2
        assert f"{rows_per_shard}/shard" in out
        status = k.autoscaler_status(controller=type(
            "C", (), {"last_decisions": [], "scheduler": sched})())
        assert "node-axis sharding: on" in status
        sched.close()


def test_cli_main_topology(capsys):
    from kubernetes_tpu.cli import main

    main(["topology"])
    assert "node-axis sharding" in capsys.readouterr().out


def test_cli_main_controlplane_status(capsys):
    from kubernetes_tpu.cli import main

    main(["controlplane", "status"])
    out = capsys.readouterr().out
    assert "wal" in out and "watch-cache" in out and "flow-" in out


def test_cli_controlplane_status_wire_rows():
    """The wire block (round 19): after real negotiated traffic the table
    shows the per-codec request split and the encode-cache hit rate."""
    import urllib.request

    from kubernetes_tpu.apiserver.client import HTTPApiClient
    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.metrics.registry import parse_text

    store = ObjectStore()
    api = APIServer(store).start()
    try:
        HTTPApiClient(api.url, codec="wire").create(
            "Pod", make_pod().name("wp").uid("wp").namespace("default").obj())
        HTTPApiClient(api.url, codec="json").list("Pod")
        with urllib.request.urlopen(f"{api.url}/metrics") as r:
            metrics = parse_text(r.read().decode())
        out = Kubectl(store).controlplane_status(metrics=metrics)
        assert "requests-wire" in out and "requests-json" in out
        assert "encode-cache-hit-rate" in out
    finally:
        api.stop()


def test_cli_controlplane_status_over_server():
    """--server path: the verb reads the apiserver's /metrics exposition
    and renders the same table the in-process path does."""
    import urllib.request

    from kubernetes_tpu.cli import Kubectl
    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.metrics.registry import parse_text
    from kubernetes_tpu.sim.store import ObjectStore
    from kubernetes_tpu.testutil import make_pod

    store = ObjectStore()
    api = APIServer(store).start()
    try:
        store.create("Pod", make_pod().name("cp0").uid("cp0")
                     .namespace("default").obj())
        with urllib.request.urlopen(f"{api.url}/metrics") as r:
            metrics = parse_text(r.read().decode())
        out = Kubectl(store).controlplane_status(metrics=metrics)
        assert "ring-occupancy" in out and "last-fsync-rv" in out
    finally:
        api.stop()
