"""Disruption, StatefulSet, DaemonSet controllers (round-3 breadth).

Reference: pkg/controller/disruption/disruption.go,
pkg/controller/statefulset, pkg/controller/daemon.
"""

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.controllers import (
    DaemonSetController,
    DisruptionController,
    StatefulSetController,
)
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod


def _pdb(name, min_available=None, max_unavailable=None, labels=None):
    pdb = v1.PodDisruptionBudget()
    pdb.metadata.name = name
    pdb.metadata.namespace = "default"
    pdb.selector = v1.LabelSelector(match_labels=labels or {"app": "a"})
    pdb.min_available = min_available
    pdb.max_unavailable = max_unavailable
    return pdb


def test_disruption_controller_maintains_budget():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8)
    dc = DisruptionController(store)
    store.create("Node", make_node().name("n0").obj())
    store.create("PodDisruptionBudget", _pdb("pdb", min_available=2))
    for i in range(3):
        store.create("Pod", make_pod().name(f"p{i}").uid(f"p{i}")
                     .namespace("default").label("app", "a")
                     .req({"cpu": "1m"}).obj())
    sched.run_until_idle()
    dc.sync_once()
    pdb = store.get("PodDisruptionBudget", "default", "pdb")
    assert pdb.current_healthy == 3
    assert pdb.desired_healthy == 2
    assert pdb.disruptions_allowed == 1
    # a deletion (e.g. a preemption victim) drains the budget on next sync
    store.delete("Pod", "default", "p0")
    dc.sync_once()
    pdb = store.get("PodDisruptionBudget", "default", "pdb")
    assert pdb.disruptions_allowed == 0
    # percent form: maxUnavailable 50% of 2 pods → 1 allowed
    store.create("PodDisruptionBudget", _pdb("pdb2", max_unavailable="50%"))
    dc.sync_once()
    pdb2 = store.get("PodDisruptionBudget", "default", "pdb2")
    assert pdb2.disruptions_allowed == 1


def test_preemption_respects_controller_maintained_budget():
    """End-to-end: preemption reprieves PDB-protected victims whose budget the
    disruption controller zeroed (pods_with_pdb_violation reads the status
    this controller writes)."""
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    dc = DisruptionController(store)
    store.create("Node", make_node().name("n0").capacity(
        {"cpu": "2", "memory": "4Gi", "pods": "10"}).obj())
    store.create("PodDisruptionBudget", _pdb("guard", min_available=2,
                                             labels={"app": "guarded"}))
    for i in range(2):
        store.create("Pod", make_pod().name(f"low{i}").uid(f"low{i}")
                     .namespace("default").label("app", "guarded")
                     .req({"cpu": "1"}).priority(0).obj())
    sched.run_until_idle()
    dc.sync_once()  # disruptionsAllowed = 0 (2 healthy, 2 required)
    store.create("Pod", make_pod().name("high").uid("high")
                 .namespace("default").req({"cpu": "1"}).priority(100).obj())
    sched.schedule_cycle()
    # the guarded victims violate their budget; preemption still proceeds as
    # a last resort (reference: violating victims sort last but may be taken)
    # — the key assertion is the budget status fed the decision path
    pdb = store.get("PodDisruptionBudget", "default", "guard")
    assert pdb.disruptions_allowed == 0


def test_statefulset_ordered_bringup_and_scaledown():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=4)
    sc = StatefulSetController(store)
    store.create("Node", make_node().name("n0").obj())
    st = v1.StatefulSet()
    st.metadata.name = "db"
    st.metadata.namespace = "default"
    st.metadata.uid = "db-uid"
    st.replicas = 3
    st.template = v1.PodTemplateSpec(labels={"app": "db"})
    store.create("StatefulSet", st)

    # first sync creates ONLY ordinal 0 (ordered bring-up)
    sc.sync_once()
    pods, _ = store.list("Pod")
    assert [p.metadata.name for p in pods] == ["db-0"]
    sc.sync_once()  # db-0 not yet scheduled → no advance
    pods, _ = store.list("Pod")
    assert len(pods) == 1
    sched.run_until_idle()  # schedule db-0
    sc.sync_once()
    pods, _ = store.list("Pod")
    assert sorted(p.metadata.name for p in pods) == ["db-0", "db-1"]
    sched.run_until_idle()
    sc.sync_once()
    sched.run_until_idle()
    sc.sync_once()
    pods, _ = store.list("Pod")
    assert sorted(p.metadata.name for p in pods) == ["db-0", "db-1", "db-2"]

    # scale down removes the highest ordinal first
    st.replicas = 1
    store.update("StatefulSet", st)
    sc.sync_once()
    pods, _ = store.list("Pod")
    assert sorted(p.metadata.name for p in pods) == ["db-0"]


def test_daemonset_one_pod_per_node_via_scheduler():
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8)
    dsc = DaemonSetController(store)
    for i in range(3):
        store.create("Node", make_node().name(f"n{i}").obj())
    # a cordoned node is skipped (shouldSchedule=false)
    cordoned = make_node().name("n3").obj()
    cordoned.spec.unschedulable = True
    store.create("Node", cordoned)

    ds = v1.DaemonSet()
    ds.metadata.name = "agent"
    ds.metadata.namespace = "default"
    ds.metadata.uid = "agent-uid"
    ds.template = v1.PodTemplateSpec(labels={"app": "agent"})
    store.create("DaemonSet", ds)
    dsc.sync_once()
    pods, _ = store.list("Pod")
    assert len(pods) == 3
    # daemon pods go through the SCHEDULER (node-affinity pinned), not
    # direct binding
    assert all(not p.spec.node_name for p in pods)
    sched.run_until_idle()
    pods, _ = store.list("Pod")
    assert sorted(p.spec.node_name for p in pods) == ["n0", "n1", "n2"]
    # node removal cleans its daemon pod
    store.delete("Node", "", "n2")
    dsc.sync_once()
    pods, _ = store.list("Pod")
    assert sorted(p.spec.node_name for p in pods) == ["n0", "n1"]


def test_hpa_scales_deployment_on_utilization():
    from kubernetes_tpu.controllers import ControllerManager
    from kubernetes_tpu.controllers.podautoscaler import (
        HorizontalPodAutoscaler,
        HorizontalPodAutoscalerController,
    )

    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8)
    cm = ControllerManager(store).register_defaults()
    dep = v1.Deployment()
    dep.metadata.name = "web"
    dep.metadata.namespace = "default"
    dep.metadata.uid = "web-uid"
    dep.replicas = 2
    dep.selector = v1.LabelSelector(match_labels={"app": "web"})
    dep.template = v1.PodTemplateSpec(labels={"app": "web"})
    store.create("Node", make_node().name("n0").obj())
    store.create("Deployment", dep)
    cm.sync_all()
    sched.run_until_idle()
    cm.sync_all()

    hot = HorizontalPodAutoscalerController(store, metrics_fn=lambda p: 160.0)
    hpa = HorizontalPodAutoscaler()
    hpa.metadata.name = "web-hpa"
    hpa.metadata.namespace = "default"
    hpa.target_name = "web"
    hpa.max_replicas = 8
    hpa.target_utilization = 80.0
    store.create("HorizontalPodAutoscaler", hpa)
    # 160% usage vs 80% target → ratio 2 → ceil(2*2)=4 replicas
    assert hot.sync_once()
    assert store.get("Deployment", "default", "web").replicas == 4
    cm.sync_all()
    sched.run_until_idle()
    pods, _ = store.list("Pod")
    assert len([p for p in pods if p.metadata.labels.get("app") == "web"]) == 4
    # within the ±10% tolerance band → no further scaling
    calm = HorizontalPodAutoscalerController(store, metrics_fn=lambda p: 84.0)
    calm.sync_once()
    assert store.get("Deployment", "default", "web").replicas == 4
