"""Preemption engine (reference: defaultpreemption tests' scenarios)."""

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.preemption import Evaluator, pods_with_pdb_violation
from kubernetes_tpu.state.cache import Cache, Snapshot
from kubernetes_tpu.testutil import make_node, make_pod


def snapshot_of(cache):
    s = Snapshot()
    cache.update_snapshot(s)
    return s


def test_select_victims_minimal_set():
    cache = Cache()
    cache.add_node(make_node().name("n0")
                   .capacity({"cpu": "4", "memory": "8Gi", "pods": "10"}).obj())
    # three low-priority 1-cpu pods fill to 3/4; preemptor wants 2 cpu
    for i in range(3):
        cache.add_pod(make_pod().name(f"v{i}").uid(f"v{i}").namespace("default")
                      .priority(i)  # distinct priorities 0,1,2
                      .req({"cpu": "1"}).node("n0").obj())
    snap = snapshot_of(cache)
    preemptor = make_pod().name("hi").uid("hi").namespace("default").priority(100).req({"cpu": "2"}).obj()

    ev = Evaluator()
    c = ev.select_victims_on_node(preemptor, snap.node_info_list[0], snap.node_info_list)
    assert c is not None
    # needs only 1 cpu freed → exactly one victim, the least important (prio 0)
    assert [p.metadata.name for p in c.victims] == ["v0"]


def test_pick_node_fewest_pdb_violations_then_lowest_priority():
    from kubernetes_tpu.preemption import Candidate

    a = Candidate("a", [make_pod().name("x").priority(5).obj()], num_pdb_violations=1)
    b = Candidate("b", [make_pod().name("y").priority(9).obj()], num_pdb_violations=0)
    c = Candidate("c", [make_pod().name("z").priority(3).obj()], num_pdb_violations=0)
    ev = Evaluator()
    assert ev.pick_one_node([a, b, c]).node_name == "c"


def test_pdb_violation_filter():
    pdb = v1.PodDisruptionBudget(
        selector=v1.LabelSelector(match_labels={"app": "web"}),
        disruptions_allowed=0,
    )
    pdb.metadata.namespace = "default"
    protected = make_pod().name("a").namespace("default").label("app", "web").obj()
    free = make_pod().name("b").namespace("default").label("app", "db").obj()
    violating, ok = pods_with_pdb_violation([protected, free], [pdb])
    assert [p.metadata.name for p in violating] == ["a"]
    assert [p.metadata.name for p in ok] == ["b"]


def test_preempt_end_to_end_pick():
    cache = Cache()
    for name, cpu in [("n0", "2"), ("n1", "2")]:
        cache.add_node(make_node().name(name)
                       .capacity({"cpu": cpu, "memory": "4Gi", "pods": "10"}).obj())
    # n0 holds a high-priority victim, n1 a low-priority one → prefer n1
    cache.add_pod(make_pod().name("imp").uid("imp").namespace("default")
                  .priority(50).req({"cpu": "2"}).node("n0").obj())
    cache.add_pod(make_pod().name("cheap").uid("cheap").namespace("default")
                  .priority(1).req({"cpu": "2"}).node("n1").obj())
    snap = snapshot_of(cache)
    preemptor = make_pod().name("hi").uid("hi").namespace("default").priority(100).req({"cpu": "2"}).obj()
    ev = Evaluator()
    c = ev.preempt(preemptor, snap, ["n0", "n1"])
    assert c is not None and c.node_name == "n1"
    assert [p.metadata.name for p in c.victims] == ["cheap"]
