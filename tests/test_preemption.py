"""Preemption engine (reference: defaultpreemption tests' scenarios)."""

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.preemption import Evaluator, pods_with_pdb_violation
from kubernetes_tpu.state.cache import Cache, Snapshot
from kubernetes_tpu.testutil import make_node, make_pod


def snapshot_of(cache):
    s = Snapshot()
    cache.update_snapshot(s)
    return s


def test_select_victims_minimal_set():
    cache = Cache()
    cache.add_node(make_node().name("n0")
                   .capacity({"cpu": "4", "memory": "8Gi", "pods": "10"}).obj())
    # three low-priority 1-cpu pods fill to 3/4; preemptor wants 2 cpu
    for i in range(3):
        cache.add_pod(make_pod().name(f"v{i}").uid(f"v{i}").namespace("default")
                      .priority(i)  # distinct priorities 0,1,2
                      .req({"cpu": "1"}).node("n0").obj())
    snap = snapshot_of(cache)
    preemptor = make_pod().name("hi").uid("hi").namespace("default").priority(100).req({"cpu": "2"}).obj()

    ev = Evaluator()
    c = ev.select_victims_on_node(preemptor, snap.node_info_list[0], snap.node_info_list)
    assert c is not None
    # needs only 1 cpu freed → exactly one victim, the least important (prio 0)
    assert [p.metadata.name for p in c.victims] == ["v0"]


def test_pick_node_fewest_pdb_violations_then_lowest_priority():
    from kubernetes_tpu.preemption import Candidate

    a = Candidate("a", [make_pod().name("x").priority(5).obj()], num_pdb_violations=1)
    b = Candidate("b", [make_pod().name("y").priority(9).obj()], num_pdb_violations=0)
    c = Candidate("c", [make_pod().name("z").priority(3).obj()], num_pdb_violations=0)
    ev = Evaluator()
    assert ev.pick_one_node([a, b, c]).node_name == "c"


def test_pdb_violation_filter():
    pdb = v1.PodDisruptionBudget(
        selector=v1.LabelSelector(match_labels={"app": "web"}),
        disruptions_allowed=0,
    )
    pdb.metadata.namespace = "default"
    protected = make_pod().name("a").namespace("default").label("app", "web").obj()
    free = make_pod().name("b").namespace("default").label("app", "db").obj()
    violating, ok = pods_with_pdb_violation([protected, free], [pdb])
    assert [p.metadata.name for p in violating] == ["a"]
    assert [p.metadata.name for p in ok] == ["b"]


def test_preempt_end_to_end_pick():
    cache = Cache()
    for name, cpu in [("n0", "2"), ("n1", "2")]:
        cache.add_node(make_node().name(name)
                       .capacity({"cpu": cpu, "memory": "4Gi", "pods": "10"}).obj())
    # n0 holds a high-priority victim, n1 a low-priority one → prefer n1
    cache.add_pod(make_pod().name("imp").uid("imp").namespace("default")
                  .priority(50).req({"cpu": "2"}).node("n0").obj())
    cache.add_pod(make_pod().name("cheap").uid("cheap").namespace("default")
                  .priority(1).req({"cpu": "2"}).node("n1").obj())
    snap = snapshot_of(cache)
    preemptor = make_pod().name("hi").uid("hi").namespace("default").priority(100).req({"cpu": "2"}).obj()
    ev = Evaluator()
    c = ev.preempt(preemptor, snap, ["n0", "n1"])
    assert c is not None and c.node_name == "n1"
    assert [p.metadata.name for p in c.victims] == ["cheap"]


def test_vectorized_victim_selection_matches_serial():
    """select_victims_vectorized must equal select_victims_on_node for plain
    preemptors across randomized clusters (same victims, same violation
    counts, same feasibility)."""
    import numpy as np

    from kubernetes_tpu.perf.workloads import node_default
    from kubernetes_tpu.preemption import Evaluator
    from kubernetes_tpu.state.cache import Cache, Snapshot
    from kubernetes_tpu.testutil import make_pod

    rng = np.random.default_rng(7)
    cache = Cache()
    for i in range(24):
        cache.add_node(node_default(i))
    for i in range(140):
        p = (make_pod().name(f"low{i}").uid(f"low{i}").namespace("default")
             .label("app", "guarded" if i % 3 == 0 else "plain")
             .req({"cpu": f"{int(rng.choice([2, 4, 9]))}",
                   "memory": "1Gi"})
             .priority(int(rng.choice([0, 1, 2])))
             .obj())
        p.spec.node_name = f"node-{int(rng.integers(24)):06d}"
        p.metadata.creation_timestamp = float(i)
        cache.add_pod(p)
    snap = Snapshot()
    cache.update_snapshot(snap)
    from kubernetes_tpu.api import objects as v1

    guard = v1.PodDisruptionBudget()
    guard.metadata.name = "g"
    guard.metadata.namespace = "default"
    guard.selector = v1.LabelSelector(match_labels={"app": "guarded"})
    guard.disruptions_allowed = 0
    pdbs = [guard]

    ev = Evaluator()
    preemptor = (make_pod().name("hi").uid("hi").namespace("default")
                 .req({"cpu": "3", "memory": "2Gi"}).priority(50).obj())
    infos = snap.node_info_list
    vec = ev.select_victims_vectorized(preemptor, infos, pdbs)
    # the scenario must actually exercise eviction: some nodes feasible only
    # via victims, with non-empty victim lists and PDB-violation counts
    non_none = [c for c in vec if c is not None]
    assert non_none, "test scenario produced no candidates — vacuous"
    assert any(c.victims for c in non_none)
    for info, got in zip(infos, vec):
        want = ev.select_victims_on_node(
            preemptor, info, infos, pdbs, cluster_has_req_anti_affinity=False
        )
        if want is None:
            assert got is None, info.node_name
        else:
            assert got is not None, info.node_name
            assert [p.uid for p in got.victims] == [p.uid for p in want.victims]
            assert got.num_pdb_violations == want.num_pdb_violations


def test_preempt_plain_tables_match_full_materialization():
    """preempt()'s shared-tables fast path must pick the SAME candidate (node,
    victims, violation count) as ranking the fully materialized
    select_victims_vectorized results through pick_one_node — across
    randomized clusters, PDBs, priorities, and nominated reservations."""
    import numpy as np

    from kubernetes_tpu.perf.workloads import node_default

    rng = np.random.default_rng(11)
    for trial in range(6):
        cache = Cache()
        n = int(rng.integers(8, 30))
        for i in range(n):
            cache.add_node(node_default(i))
        npods = int(rng.integers(40, 160))
        for i in range(npods):
            p = (make_pod().name(f"low{trial}-{i}").uid(f"low{trial}-{i}")
                 .namespace("default")
                 .label("app", "guarded" if i % 4 == 0 else "plain")
                 .req({"cpu": f"{int(rng.choice([1, 2, 4]))}",
                       "memory": "1Gi"})
                 .priority(int(rng.choice([0, 1, 2, 5])))
                 .obj())
            p.spec.node_name = f"node-{int(rng.integers(n)):06d}"
            p.metadata.creation_timestamp = float(rng.integers(1000))
            cache.add_pod(p)
        snap = snapshot_of(cache)

        guard = v1.PodDisruptionBudget()
        guard.metadata.name = "g"
        guard.metadata.namespace = "default"
        guard.selector = v1.LabelSelector(match_labels={"app": "guarded"})
        guard.disruptions_allowed = int(rng.integers(0, 2))
        pdbs = [guard] if trial % 2 == 0 else []

        preemptor = (make_pod().name("hi").uid("hi").namespace("default")
                     .req({"cpu": "3", "memory": "2Gi"}).priority(50).obj())
        nom_pod = (make_pod().name("nom").uid("nom").namespace("default")
                   .req({"cpu": "2", "memory": "1Gi"}).priority(60).obj())
        nominated = {f"node-{int(rng.integers(n)):06d}": [nom_pod]}

        names = [ni.node_name for ni in snap.node_info_list]
        ev = Evaluator()
        got = ev.preempt(preemptor, snap, names, pdbs, nominated=nominated)

        ref = Evaluator()
        infos = [snap.node_info_map[nm] for nm in names]
        results = ref.select_victims_vectorized(
            preemptor, infos, pdbs, nominated=nominated)
        want = ref.pick_one_node([c for c in results if c is not None])

        if want is None:
            assert got is None, f"trial {trial}: fast path found {got}"
        else:
            assert got is not None, f"trial {trial}: fast path found nothing"
            assert got.node_name == want.node_name, f"trial {trial}"
            assert [p.uid for p in got.victims] == [p.uid for p in want.victims]
            assert got.num_pdb_violations == want.num_pdb_violations


def test_candidate_mask_segment_sum_matches_einsum():
    """The priority-level segment-sum candidate mask must agree with the
    dense-einsum fallback on randomized clusters (same pods, same batch)."""
    import jax.numpy as jnp
    import numpy as np

    from kubernetes_tpu.framework.podbatch import PodBatchCompiler
    from kubernetes_tpu.preemption import (
        PRIORITY_LEVEL_CAP,
        candidate_mask_device,
    )
    from kubernetes_tpu.state.encoding import ClusterEncoder

    rng = np.random.default_rng(7)
    for trial in range(3):
        n, p_sched, b = 24, 80, 12
        enc = ClusterEncoder()
        cache = Cache()
        for i in range(n):
            cache.add_node(
                make_node().name(f"node-{i:03d}")
                .capacity({"cpu": "8", "memory": "16Gi", "pods": "110"}).obj()
            )
        prios = rng.choice([0, 1, 5, 20], size=p_sched)
        for i in range(p_sched):
            pod = (
                make_pod().name(f"sp{i}").uid(f"sp{i}").namespace("default")
                .req({"cpu": f"{int(rng.integers(100, 900))}m",
                      "memory": "256Mi"})
                .priority(int(prios[i])).obj()
            )
            pod.spec.node_name = f"node-{int(rng.integers(n)):03d}"
            cache.add_pod(pod)
        snap = Snapshot()
        changed = cache.update_snapshot(snap)
        enc.sync(snap, changed)
        dsnap = enc.to_device()
        from kubernetes_tpu.framework.runtime import initial_dynamic_state

        dyn = initial_dynamic_state(dsnap)
        pods = [
            make_pod().name(f"hp{i}").uid(f"hp{i}").namespace("default")
            .req({"cpu": "3", "memory": "512Mi"})
            .priority(int(rng.choice([0, 2, 10, 30]))).obj()
            for i in range(b)
        ]
        batch = PodBatchCompiler(enc, {}).compile(pods, pad_to=16)
        static_ok = jnp.asarray(
            np.ones((batch.valid.shape[0], dsnap.node_valid.shape[0]), bool)
        ) & dsnap.node_valid[None, :] & batch.valid[:, None]
        u = np.unique(np.asarray(enc.pod_priority)[np.asarray(enc.pod_valid)])
        levels = np.full(PRIORITY_LEVEL_CAP, np.iinfo(np.int32).max, np.int32)
        levels[: u.size] = u
        fast = np.asarray(candidate_mask_device(
            batch, dsnap, dyn, static_ok, jnp.asarray(levels)))
        dense = np.asarray(candidate_mask_device(batch, dsnap, dyn, static_ok))
        assert np.array_equal(fast, dense), f"trial {trial}"


def test_native_sweep_matches_numpy_oracle():
    """native/preempt_sweep.cpp == the numpy reprieve+ranking path on
    randomized inputs (valid rows only — invalid rows are never read)."""
    import numpy as np

    from kubernetes_tpu.native import load_preempt_sweep
    from kubernetes_tpu.preemption import _sweep_and_rank

    if load_preempt_sweep() is None:
        import pytest as _pytest

        _pytest.skip("no native toolchain")

    rng = np.random.default_rng(11)
    for trial in range(40):
        c = int(rng.integers(1, 24))
        vmax = int(rng.integers(1, 7))
        r = 4
        alloc = rng.integers(4, 4000, size=(c, r)).astype(np.int64)
        vr = rng.integers(0, 900, size=(c, vmax, r)).astype(np.int64)
        v_valid = rng.random((c, vmax)) < 0.8
        vr[~v_valid] = 0
        used_now = (vr * v_valid[:, :, None]).sum(axis=1) \
            + rng.integers(0, 500, size=(c, r))
        base = used_now - (vr * v_valid[:, :, None]).sum(axis=1)
        v_viol = rng.random((c, vmax)) < 0.3
        v_prio = rng.integers(0, 5, size=(c, vmax)).astype(np.int64)
        v_ts = rng.integers(0, 100, size=(c, vmax)).astype(np.float64)
        req_v = rng.integers(0, 1200, size=r).astype(np.int64)

        import os

        nat = _sweep_and_rank(base, alloc, vr, v_valid, v_viol, v_prio,
                              v_ts, req_v)
        os.environ["KTPU_NO_NATIVE"] = "1"
        try:
            import kubernetes_tpu.native as native_mod

            # force the numpy fallback regardless of the cached lib
            saved = native_mod.load_preempt_sweep
            native_mod.load_preempt_sweep = lambda: None
            ref = _sweep_and_rank(base, alloc, vr, v_valid, v_viol, v_prio,
                                  v_ts, req_v)
            native_mod.load_preempt_sweep = saved
        finally:
            os.environ.pop("KTPU_NO_NATIVE", None)

        n_mask, n_nviol, n_order, n_valid = nat
        r_mask, r_nviol, r_order, r_valid = ref
        if r_valid is None or not r_valid.any():
            assert n_valid is None or not n_valid.any(), f"trial {trial}"
            continue
        assert n_valid is not None, f"trial {trial}"
        assert np.array_equal(n_valid, r_valid), f"trial {trial}"
        # identical ranked prefix of VALID candidates, identical victim
        # sets + violation counts on them
        n_pref = [i for i in n_order if n_valid[i]]
        r_pref = [i for i in r_order if r_valid[i]]
        assert n_pref == r_pref, f"trial {trial}: {n_pref} != {r_pref}"
        for i in n_pref:
            assert np.array_equal(n_mask[i], r_mask[i]), f"trial {trial} c{i}"
            assert n_nviol[i] == r_nviol[i], f"trial {trial} c{i}"
