"""Zone-interleaved node ordering (node_tree.go:51-143): tested argument that
it is unnecessary under dense scoring.

The reference orders nodes zone-round-robin for TWO effects:
  1. fairness of SAMPLING — with percentageOfNodesToScore < 100 only a prefix
     of the node order is evaluated, so interleaving keeps that prefix
     zone-diverse (scheduler.go:852-872).  The device path scores ALL nodes
     densely (no sampling), so no prefix exists to bias.
  2. spreading among equal-score nodes — selectHost reservoir-samples
     UNIFORMLY among max-score ties (scheduler.go:827-848), which is
     order-independent: any tie, in any node order, is equally likely.
     select_host with a PRNG key reproduces exactly that distribution.

This test pins down effect 2: with two zones of identical nodes and maximal
ties, uniform tie-breaking picks both zones in proportion to their node
counts — the same marginal distribution zone interleaving would produce.
"""

import numpy as np
import jax
import jax.numpy as jnp

from kubernetes_tpu.framework.runtime import BatchedFramework


def test_uniform_tiebreak_spreads_across_zones_like_interleave():
    n = 64
    zone_of = np.array([0] * 32 + [1] * 32)  # contiguous zones — the WORST
    # ordering for a prefix-sampler, irrelevant for dense scoring
    scores = jnp.zeros(n)  # all nodes tie
    mask = jnp.ones(n, bool)

    picks = []
    key = jax.random.PRNGKey(7)
    for i in range(400):
        key, sub = jax.random.split(key)
        picks.append(int(BatchedFramework.select_host(scores, mask, sub)))
    zones = np.bincount(zone_of[picks], minlength=2)
    # uniform over 64 ties → each zone ≈ 200 ± noise; 4σ ≈ 40
    assert abs(zones[0] - zones[1]) < 80, zones
    # and every pick is a valid tie
    assert all(0 <= p < n for p in picks)


def test_deterministic_tiebreak_documented_bias():
    """Without a PRNG key the tie-break is lowest-row (deterministic) — the
    documented compat deviation; callers that need the reference's
    reservoir-sampling distribution pass rng_key to TPUScheduler."""
    scores = jnp.zeros(8)
    mask = jnp.ones(8, bool)
    assert int(BatchedFramework.select_host(scores, mask, None)) == 0
