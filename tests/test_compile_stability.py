"""Regression guard for the round-2 recompile storm.

Round 2's profile showed 90% of bench wall time was XLA recompilation: the
dirty-row scatter compiled ~23 fresh executables per cycle (varying row-count
shapes), pod-tier doubling recompiled the program suite mid-run, and batch
inner caps thrashed between pod kinds.  These tests pin the fixes:

  - steady-state scheduling cycles perform ZERO backend compiles;
  - to_device's scatter path compiles once per pow-2 dirty-count bucket;
  - PodBatchCompiler caps are sticky (monotone high-water marks), so batches
    alternating between pod kinds keep one shape.
"""

import numpy as np

from kubernetes_tpu.framework.podbatch import PodBatchCompiler
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.state.cache import Cache, Snapshot
from kubernetes_tpu.state.encoding import ClusterEncoder
from kubernetes_tpu.testutil import make_node, make_pod
from kubernetes_tpu.utils.compilemon import monitor


def _node(i):
    return (
        make_node().name(f"n-{i:03d}")
        .capacity({"cpu": "32", "memory": "64Gi", "pods": "110"})
        .label("topology.kubernetes.io/zone", f"z-{i % 4}")
        .obj()
    )


def _pod(k, cpu="100m"):
    return (
        make_pod().name(f"p-{k}").uid(f"p-{k}").namespace("default")
        .label("app", f"a-{k % 3}")
        .req({"cpu": cpu, "memory": "64Mi"})
        .obj()
    )


def test_steady_state_cycles_do_not_compile():
    monitor.install()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=16)
    sched.presize(64, 512)
    for i in range(40):
        store.create("Node", _node(i))
    # warmup: several cycles with varying partial batches + dirty-row sizes
    k = 0
    for cyc in range(4):
        for _ in range(3 + cyc * 5):
            store.create("Pod", _pod(k))
            k += 1
        sched.run_until_idle()
    c0, _ = monitor.snapshot()
    for cyc in range(3):
        for _ in range(4 + cyc * 3):
            store.create("Pod", _pod(k))
            k += 1
        sched.run_until_idle()
    c1, _ = monitor.snapshot()
    assert c1 - c0 == 0, f"steady-state cycles compiled {c1 - c0} executables"


def test_scatter_bucket_reuse():
    """to_device's incremental path compiles per pow-2 bucket, not per count."""
    monitor.install()
    cache = Cache()
    for i in range(64):
        cache.add_node(_node(i))
    snap = Snapshot()
    enc = ClusterEncoder()
    changed = cache.update_snapshot(snap)
    enc.sync(snap, changed)
    enc.to_device()  # full upload
    # touch 3 nodes → scatter bucket 32; then 5 nodes → same bucket
    def touch(names):
        for n in names:
            cache.update_node(snap.node_info_map[n].node)
        ch = cache.update_snapshot(snap)
        enc.sync(snap, ch)
        enc.to_device()

    touch([f"n-{i:03d}" for i in range(3)])  # first bucket-32 compile
    c0, _ = monitor.snapshot()
    touch([f"n-{i:03d}" for i in range(5)])
    touch([f"n-{i:03d}" for i in range(10, 12)])
    c1, _ = monitor.snapshot()
    assert c1 - c0 == 0, f"same-bucket scatters recompiled {c1 - c0}x"


def test_scatter_values_correct_after_padding():
    """Padded (duplicated) scatter rows write the same values as a full upload."""
    cache = Cache()
    for i in range(20):
        cache.add_node(_node(i))
    snap = Snapshot()
    enc = ClusterEncoder()
    enc.sync(snap, cache.update_snapshot(snap))
    enc.to_device()
    # mutate some nodes via new pods, then compare scatter vs fresh encoder
    for k in range(7):
        p = _pod(k)
        p.spec.node_name = f"n-{k:03d}"
        cache.add_pod(p)
    enc.sync(snap, cache.update_snapshot(snap))
    d = enc.to_device()

    enc2 = ClusterEncoder()
    # replay dictionary order so interned ids line up
    for i in range(len(enc.dic)):
        enc2.dic.intern(enc.dic.string(i))
    for key in enc.topo_key_strings:
        enc2.topo_slot(key)
    enc2.reserve(enc._n, enc._p)
    snap2 = Snapshot()
    enc2.sync(snap2, cache.update_snapshot(snap2))
    d2 = enc2.to_device()
    for name in ("requested", "non_zero_requested", "pod_valid", "pod_node"):
        a, b = np.asarray(getattr(d, name)), np.asarray(getattr(d2, name))
        assert a.shape == b.shape and (a == b).all(), name


def test_podbatch_sticky_caps():
    enc = ClusterEncoder()
    comp = PodBatchCompiler(enc)
    import kubernetes_tpu.api.objects as v1

    plain = [_pod(i) for i in range(4)]
    spread = []
    for i in range(4):
        p = _pod(100 + i)
        p.spec.topology_spread_constraints = [
            v1.TopologySpreadConstraint(
                max_skew=1, topology_key="topology.kubernetes.io/zone",
                when_unsatisfiable=v1.DO_NOT_SCHEDULE,
                label_selector=v1.LabelSelector(match_labels={"app": "a-1"}),
            )
        ]
        spread.append(p)
    b1 = comp.compile(plain, pad_to=8)
    b2 = comp.compile(spread, pad_to=8)
    b3 = comp.compile(plain, pad_to=8)
    # after seeing spread pods, the tsc dims stay at the high-water mark
    assert b2.tsc_valid.shape == b3.tsc_valid.shape
    assert b3.tsc_valid.shape[1] >= b1.tsc_valid.shape[1]
    # a later plain batch reuses every ARRAY shape of the mixed-era batch;
    # the static content flags (has_spread/has_affinity) differ by design —
    # they select between the with/without-constraint program variants
    import jax

    shapes2 = [np.shape(x) for x in jax.tree_util.tree_leaves(b2)]
    shapes3 = [np.shape(x) for x in jax.tree_util.tree_leaves(b3)]
    assert shapes2 == shapes3
    assert b2.has_spread and not b3.has_spread
