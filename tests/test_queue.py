"""PriorityQueue semantics (reference: scheduling_queue_test.go patterns)."""

from kubernetes_tpu.framework import events as ev
from kubernetes_tpu.framework.events import ActionType, ClusterEvent, EventResource
from kubernetes_tpu.queueing import PriorityQueue
from kubernetes_tpu.testutil import make_pod


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_priority_ordering():
    clock = FakeClock()
    q = PriorityQueue(clock=clock)
    q.add(make_pod().name("low").uid("low").priority(1).obj())
    q.add(make_pod().name("high").uid("high").priority(10).obj())
    q.add(make_pod().name("mid").uid("mid").priority(5).obj())
    assert [q.pop().pod.metadata.name for _ in range(3)] == ["high", "mid", "low"]
    assert q.pop() is None


def test_fifo_within_priority():
    clock = FakeClock()
    q = PriorityQueue(clock=clock)
    q.add(make_pod().name("a").uid("a").obj())
    clock.advance(1)
    q.add(make_pod().name("b").uid("b").obj())
    assert q.pop().pod.metadata.name == "a"
    assert q.pop().pod.metadata.name == "b"


def test_unschedulable_backoff_and_event_requeue():
    clock = FakeClock()
    event_map = {
        ClusterEvent(EventResource.NODE, ActionType.ADD): {"NodeResourcesFit"},
    }
    q = PriorityQueue(clock=clock, cluster_event_map=event_map)
    q.add(make_pod().name("p").uid("p").obj())
    info = q.pop()
    info.unschedulable_plugins = {"NodeResourcesFit"}
    q.add_unschedulable(info, q.scheduling_cycle())
    assert q.pop() is None
    assert q.pending_count() == (0, 0, 1)

    # an event NOT registered by the failing plugin does not requeue
    q.move_all_to_active_or_backoff(
        ClusterEvent(EventResource.PVC, ActionType.ADD)
    )
    assert q.pending_count()[2] == 1

    # a registered event moves it to backoff (still within backoff window)
    q.move_all_to_active_or_backoff(ev.NODE_ADD)
    assert q.pending_count() == (0, 1, 0)
    assert q.pop() is None  # backoff not expired
    clock.advance(2.0)  # initial backoff 1s
    assert q.pop().pod.metadata.name == "p"


def test_backoff_grows_exponentially():
    clock = FakeClock()
    q = PriorityQueue(clock=clock)
    q.add(make_pod().name("p").uid("p").obj())
    for attempt in range(1, 4):
        info = q.pop()
        assert info is not None and info.attempts == attempt
        q.add_unschedulable(info, q.scheduling_cycle())
        q.move_all_to_active_or_backoff(ev.WILDCARD_EVENT)
        # backoff = initial * 2^(attempts-1)
        expected = min(1.0 * 2 ** (attempt - 1), 10.0)
        clock.advance(expected - 0.01)
        assert q.pop() is None
        clock.advance(0.02)


def test_unschedulable_flush_after_limit():
    clock = FakeClock()
    q = PriorityQueue(clock=clock)
    q.add(make_pod().name("p").uid("p").obj())
    info = q.pop()
    q.add_unschedulable(info, q.scheduling_cycle())
    clock.advance(61.0)  # DEFAULT_UNSCHEDULABLE_TIME_LIMIT
    assert q.pop().pod.metadata.name == "p"


def test_move_during_cycle_goes_to_backoff():
    """AddUnschedulableIfNotPresent: a move since the pod's cycle → backoffQ."""
    clock = FakeClock()
    q = PriorityQueue(clock=clock)
    q.add(make_pod().name("p").uid("p").obj())
    cycle = q.scheduling_cycle()
    info = q.pop()
    q.move_all_to_active_or_backoff(ev.NODE_ADD)  # move happens mid-cycle
    q.add_unschedulable(info, cycle)
    assert q.pending_count() == (0, 1, 0)  # backoff, not unschedulable


def test_activate():
    clock = FakeClock()
    q = PriorityQueue(clock=clock)
    pod = make_pod().name("p").uid("p").obj()
    q.add(pod)
    info = q.pop()
    q.add_unschedulable(info, q.scheduling_cycle())
    q.activate([pod])
    assert q.pop().pod.metadata.name == "p"


def test_next_backoff_expiry_flushes_first():
    """next_backoff_expiry applies pending moves + expiries before peeking,
    so the scheduler's batch-formation hysteresis sees fresh state."""
    clock = FakeClock()
    q = PriorityQueue(clock=clock)
    q.add(make_pod().name("p").uid("p").obj())
    info = q.pop()
    q.add_unschedulable(info, q.scheduling_cycle())
    # the DEBOUNCED move (recorded, not yet applied) must be visible
    q.move_all_to_active_or_backoff(ev.WILDCARD_EVENT)
    assert q.next_backoff_expiry() == clock.t + 1.0  # initial backoff 1s
    clock.advance(1.5)
    assert q.next_backoff_expiry() is None  # expired → moved to active
    assert q.pending_count()[0] == 1


def test_scheduler_backoff_wave_coalesces_into_one_batch():
    """Batch-formation hysteresis (TPUScheduler.batch_wait): a retry wave
    whose backoffs expire within the window fills ONE device batch instead
    of trickling into fragmented cycles (the round-4 PreemptionBasic fix)."""

    from kubernetes_tpu.scheduler import TPUScheduler
    from kubernetes_tpu.sim.store import ObjectStore
    from kubernetes_tpu.testutil import make_node

    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=16, pod_initial_backoff=0.08,
                         batch_wait=0.5)
    # no nodes yet: the whole wave fails together and enters backoff
    for i in range(16):
        store.create("Pod", make_pod().name(f"w{i}").uid(f"w{i}")
                     .req({"cpu": "1"}).obj())
    s1 = sched.schedule_cycle()
    assert s1.attempted == 16 and s1.scheduled == 0
    # nodes appear; the NODE_ADD event moves the wave to the backoff queue
    for i in range(4):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "8", "memory": "32Gi", "pods": "110"})
                     .obj())
    # next cycle starts before the backoff expires: the hysteresis must wait
    # out the wave and dispatch all 16 retries as ONE batch (without it this
    # cycle pops only the few pods whose backoff happens to have expired)
    s2 = sched.schedule_cycle()
    assert s2.attempted == 16, f"wave fragmented: {s2.attempted} pods"
    assert s2.scheduled == 16
