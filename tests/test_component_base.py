"""Feature gates, healthz, configz, trace."""

import pytest

from kubernetes_tpu.component_base import Configz, FeatureGate, Healthz, Trace
from kubernetes_tpu.component_base.featuregate import FeatureSpec, default_feature_gate


def test_feature_gate_defaults_and_flag_parse():
    fg = FeatureGate()
    fg.register("Foo", FeatureSpec(default=True))
    fg.register("Bar", FeatureSpec(default=False))
    assert fg.enabled("Foo") and not fg.enabled("Bar")
    fg.set_from_string("Foo=false, Bar=true")
    assert not fg.enabled("Foo") and fg.enabled("Bar")


def test_feature_gate_locked():
    fg = FeatureGate()
    fg.register("Locked", FeatureSpec(default=True, lock_to_default=True))
    with pytest.raises(ValueError):
        fg.set("Locked", False)


def test_default_gates_registered():
    assert default_feature_gate.enabled("PodOverhead")
    assert not default_feature_gate.enabled("MinDomainsInPodTopologySpread")
    assert len(default_feature_gate.known()) >= 10


def test_healthz():
    h = Healthz()
    h.add_check("cache-synced", lambda: True)
    ok, results = h.check()
    assert ok and results == {"ping": True, "cache-synced": True}
    h.add_check("boom", lambda: 1 / 0)
    ok, results = h.check()
    assert not ok and results["boom"] is False


def test_configz_dump():
    c = Configz()
    c.install("kubescheduler.config.k8s.io", {"parallelism": 16})
    assert "parallelism" in c.dump()


def test_trace_logs_when_slow():
    t = [0.0]

    def clock():
        return t[0]

    tr = Trace("schedulePod", clock=clock, pod="default/p")
    t[0] = 0.05
    tr.step("filter")
    t[0] = 0.2
    tr.step("score")
    msg = tr.log_if_long(0.1)
    assert msg and "filter" in msg and "score" in msg
    fast = Trace("fast", clock=clock)
    assert fast.log_if_long(0.1) is None


def test_klog_verbosity_gating(caplog):
    import logging as pylog

    from kubernetes_tpu.component_base import logging as klog

    klog.set_verbosity(0)
    with caplog.at_level(pylog.INFO, logger="kubernetes_tpu"):
        klog.V(2).info_s("hidden", a=1)
        klog.info_s("shown", pod="default/p")
        klog.error_s(ValueError("boom"), "failed", node="n0")
    text = caplog.text
    assert "hidden" not in text
    assert "shown pod='default/p'" in text
    assert "failed" in text and "boom" in text
    klog.set_verbosity(2)
    with caplog.at_level(pylog.INFO, logger="kubernetes_tpu"):
        klog.V(2).info_s("now visible", n=3)
    assert "now visible" in caplog.text
    klog.set_verbosity(0)
