"""Fast (row-sliced) scan must match the dense full-recompute scan bit-for-bit."""

import numpy as np
import jax
import jax.numpy as jnp

from tests.test_parity import build_cluster, default_framework, device_pipeline, pending_pods


def test_fast_scan_matches_dense():
    rng = np.random.default_rng(7)
    cache = build_cluster(rng)
    pods = pending_pods(rng, k=8)
    fw, batch, snap, enc, dsnap, dyn, auxes = device_pipeline(cache, pods)
    order = jnp.arange(batch.size)
    fast = jax.jit(fw.greedy_assign)(batch, dsnap, dyn, auxes, order, None)
    dense = jax.jit(fw.greedy_assign_dense)(batch, dsnap, dyn, auxes, order, None)
    assert np.array_equal(np.asarray(fast.node_row), np.asarray(dense.node_row))
    assert np.array_equal(
        np.asarray(fast.feasible_count), np.asarray(dense.feasible_count)
    )
    assert np.array_equal(
        np.asarray(fast.dyn.requested), np.asarray(dense.dyn.requested)
    )
