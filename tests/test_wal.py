"""Durable store: write-ahead log append/replay, torn-tail truncation,
WAL-integrated crash points, and the kill -9 subprocess gate.

Reference behaviors exercised: etcd's WAL record format discipline
(length-prefixed + checksummed, torn tails truncated on boot —
server/storage/wal/decoder.go), durable-before-visible commit ordering,
and the commit-unknown outcome a retrying client must tolerate when the
log runs ahead of memory.
"""

import os
import subprocess
import sys

import pytest

from kubernetes_tpu.analysis import lockcheck
from kubernetes_tpu.api.scheme import default_scheme
from kubernetes_tpu.api.serialize import to_manifest
from kubernetes_tpu.chaos import (
    CRASH_POINTS,
    CRASH_PRE_WAL_FSYNC,
    CRASH_TORN_WAL_WRITE,
    FaultSchedule,
    ProcessCrash,
    TransientApiError,
    crash_schedule,
)
from kubernetes_tpu.metrics import scheduler_metrics as m
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.sim.wal import WriteAheadLog, read_records, replay_on_boot
from kubernetes_tpu.testutil import make_node, make_pod


@pytest.fixture(autouse=True)
def lock_order_monitor():
    mon = lockcheck.activate()
    try:
        yield mon
    finally:
        lockcheck.deactivate()
    assert not mon.violations, mon.report()


@pytest.fixture()
def scheme():
    return default_scheme()


def _wal_store(tmp_path, fsync_every=0, fault=None):
    wal = WriteAheadLog(str(tmp_path / "store.wal"), fsync_every=fsync_every)
    return ObjectStore(fault_injector=fault, wal=wal), wal


def _manifests(store, scheme):
    # Events excluded: best-effort by contract, exempt from the WAL (see
    # WriteAheadLog.exempt_kinds) — a replayed store starts event-empty
    return {k: to_manifest(o, scheme) for k, o in store._objects.items()
            if k[0] != "Event"}


def _mk_node(i):
    node = make_node().name(f"n{i}").capacity({"cpu": "8", "pods": "32"}).obj()
    node.metadata.uid = f"n{i}"
    node.metadata.creation_timestamp = float(i + 1)
    return node


def _mk_pod(i):
    return (make_pod().name(f"p{i}").uid(f"p{i}").namespace("default")
            .req({"cpu": "1"}).creation_timestamp(100.0 + i).obj())


# --- record format + replay ---------------------------------------------------


def test_replay_reconstructs_every_mutation_class(tmp_path, scheme):
    store, wal = _wal_store(tmp_path)
    store.create("Node", _mk_node(0))
    for i in range(3):
        store.create("Pod", _mk_pod(i))
    store.bind_pod("default", "p0", "n0")
    p1 = store.get("Pod", "default", "p1")
    p1.metadata.labels["tier"] = "batch"
    store.update("Pod", p1)
    store.delete("Pod", "default", "p2")
    wal.close()
    replay = replay_on_boot(str(tmp_path / "store.wal"), scheme=scheme)
    assert replay.records_applied == 7
    assert not replay.truncated_tail
    assert replay.last_rv == store.current_rv()
    assert _manifests(replay.store, scheme) == _manifests(store, scheme)
    # watch history is rebuilt too: the PR-8 cold-start watch replay works
    assert len(replay.store._log) == 7
    assert replay.store._log[-1].resource_version == replay.last_rv
    # the replayed store keeps serving: a successor write gets the next rv
    replay.store.create("Pod", _mk_pod(9))
    assert replay.store.current_rv() == replay.last_rv + 1


def test_replay_is_verbatim_not_readmitted(tmp_path, scheme):
    """Replay must not re-run admission: a pod admitted under a quota that
    was later deleted still replays (re-admission would reject it against
    history that no longer holds)."""
    from kubernetes_tpu.api import objects as v1

    store, wal = _wal_store(tmp_path)
    store.create("ResourceQuota", v1.ResourceQuota(
        metadata=v1.ObjectMeta(name="q", namespace="default"),
        hard={"pods": "1"}))
    store.create("Pod", _mk_pod(0))  # fills the quota
    store.delete("ResourceQuota", "default", "q")
    store.create("Pod", _mk_pod(1))  # admitted: quota gone
    wal.close()
    replay = replay_on_boot(str(tmp_path / "store.wal"), scheme=scheme)
    assert _manifests(replay.store, scheme) == _manifests(store, scheme)
    # derived admission caches were rebuilt from the final object map
    assert replay.store._quota_namespaces == set()


def test_torn_tail_is_truncated_and_log_reopens(tmp_path, scheme):
    store, wal = _wal_store(tmp_path)
    for i in range(4):
        store.create("Pod", _mk_pod(i))
    wal.close()
    path = str(tmp_path / "store.wal")
    good_size = os.path.getsize(path)
    with open(path, "ab") as f:
        f.write(b"\x00\x00\x01\x00corrupt-half-record")
    replay = replay_on_boot(path, scheme=scheme)
    assert replay.truncated_tail and replay.truncated_at == good_size
    assert os.path.getsize(path) == good_size  # file physically truncated
    assert replay.records_applied == 4
    assert _manifests(replay.store, scheme) == _manifests(store, scheme)
    # the truncated log accepts appends and a second replay verifies whole
    replay.store.wal = WriteAheadLog(path)
    replay.store.create("Pod", _mk_pod(7))
    replay.store.wal.close()
    records, good_end = read_records(path)
    assert len(records) == 5 and good_end == os.path.getsize(path)


def test_crc_corruption_mid_file_truncates_from_there(tmp_path, scheme):
    """A flipped byte INSIDE an earlier record cuts replay at that record
    (everything after it is unverifiable) — checksums, not lengths, are
    the authority."""
    store, wal = _wal_store(tmp_path)
    for i in range(5):
        store.create("Pod", _mk_pod(i))
    wal.close()
    path = str(tmp_path / "store.wal")
    records, _ = read_records(path)
    third_off = records[2][0]
    with open(path, "r+b") as f:
        f.seek(third_off + 12)  # inside record 3's payload
        b = f.read(1)
        f.seek(third_off + 12)
        f.write(bytes([b[0] ^ 0xFF]))
    replay = replay_on_boot(path, scheme=scheme)
    assert replay.truncated_tail and replay.records_applied == 2


def test_fsync_cadence(tmp_path):
    wal = WriteAheadLog(str(tmp_path / "w.wal"), fsync_every=2)
    store = ObjectStore(wal=wal)
    store.create("Pod", _mk_pod(0))
    assert wal.last_fsync_rv == 0  # below the cadence: not yet synced
    store.create("Pod", _mk_pod(1))
    assert wal.last_fsync_rv == 2  # every-2 cadence fired at rv 2
    store.create("Pod", _mk_pod(2))
    assert wal.last_fsync_rv == 2
    wal.sync(store.current_rv())  # explicit watermark (shutdown path)
    assert wal.last_fsync_rv == 3
    assert m.wal_last_fsync_rv.value(()) == 3.0
    assert wal.records_appended == 3
    assert wal.size_bytes == os.path.getsize(str(tmp_path / "w.wal"))
    wal.close()


# --- WAL crash points ---------------------------------------------------------


def test_pre_wal_fsync_point_is_registered():
    assert CRASH_PRE_WAL_FSYNC in CRASH_POINTS
    # the torn-write point is NOT armable via crash_points (arm_torn_write
    # owns it) — it only names the ProcessCrash the tear raises
    assert CRASH_TORN_WAL_WRITE not in CRASH_POINTS


def test_crash_pre_wal_fsync_log_runs_ahead_of_memory(tmp_path, scheme):
    """Death between append and fsync: the record is on disk, the store
    never applied — replay surfaces the write as committed (etcd's
    commit-unknown outcome) and a successor retry of the create would 409,
    never double-apply."""
    store, wal = _wal_store(tmp_path, fsync_every=1)
    fault = FaultSchedule(0, crash_points={CRASH_PRE_WAL_FSYNC: 2})
    with crash_schedule(fault):
        store.create("Pod", _mk_pod(0))
        with pytest.raises(ProcessCrash) as ei:
            store.create("Pod", _mk_pod(1))
    assert ei.value.point == CRASH_PRE_WAL_FSYNC
    assert store.get("Pod", "default", "p1") is None  # memory: not applied
    replay = replay_on_boot(str(tmp_path / "store.wal"), scheme=scheme)
    assert replay.store.get("Pod", "default", "p1") is not None  # log: ahead
    with pytest.raises(ValueError):
        replay.store.create("Pod", _mk_pod(1))  # retry → AlreadyExists


def test_torn_write_fault_is_deterministic_and_truncates(tmp_path, scheme):
    store, wal = _wal_store(tmp_path, fsync_every=1)
    store.create("Pod", _mk_pod(0))
    fault = FaultSchedule(0)
    fault.arm_torn_write(at_append=2)  # relative: 2nd FUTURE append
    with crash_schedule(fault):
        store.create("Pod", _mk_pod(1))
        with pytest.raises(ProcessCrash) as ei:
            store.create("Pod", _mk_pod(2))
    assert ei.value.point == CRASH_TORN_WAL_WRITE
    assert fault.injected_counts()["wal_torn_write"] == 1
    replay = replay_on_boot(str(tmp_path / "store.wal"), scheme=scheme)
    assert replay.truncated_tail
    assert replay.store.get("Pod", "default", "p1") is not None
    assert replay.store.get("Pod", "default", "p2") is None
    # the torn write was never acknowledged: the client retry is safe and
    # lands exactly once on the reopened log
    replay.store.wal = WriteAheadLog(str(tmp_path / "store.wal"))
    replay.store.create("Pod", _mk_pod(2))
    final = replay_on_boot(str(tmp_path / "store.wal"), scheme=scheme)
    assert final.store.get("Pod", "default", "p2") is not None


def test_wal_io_fault_is_retryable_and_never_half_applies(tmp_path):
    from kubernetes_tpu.chaos import RetryingStore

    fault = FaultSchedule(0, wal_error_rate=1.0, max_faults_per_key=2)
    store, wal = _wal_store(tmp_path, fault=fault)
    with pytest.raises(TransientApiError) as ei:
        store.create("Pod", _mk_pod(0))
    assert ei.value.code == 500
    assert store.get("Pod", "default", "p0") is None  # nothing half-applied
    # the PR-1 retrying transport rides through the bounded fault budget
    retrying = RetryingStore(store, max_retries=5, backoff_initial=0.001,
                             sleep=lambda s: None)
    retrying.create("Pod", _mk_pod(1))
    assert store.get("Pod", "default", "p1") is not None
    assert fault.injected_counts()["wal_error"] >= 2


# --- the real thing: kill -9 a subprocess, replay, exactly-once ---------------


def test_sigkill_subprocess_replay_exactly_once():
    """tools/wal_crash_gate.py IS the test: a child process dies by real
    SIGKILL mid-bind (clean and torn-tail variants); the parent replays
    the WAL and asserts exactly-once binds and bit-identical state vs a
    never-crashed replica.  Running the tool here keeps the CI gate and
    tier-1 pinned to the same assertions."""
    gate = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "wal_crash_gate.py")
    proc = subprocess.run([sys.executable, gate], timeout=300,
                          capture_output=True)
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]
    assert b'"wal_crash_gate": "PASS"' in proc.stdout


# --- scheduler end to end: crash.mid_bind + WAL replay + cold start -----------


def test_mid_bind_crash_wal_replay_cold_start_exactly_once(tmp_path, scheme):
    """The tentpole acceptance: SIGKILL-equivalent death at crash.mid_bind
    with ONLY the WAL surviving.  replay_on_boot must reproduce the dead
    replica's store bit-for-bit (the landed bind included, exactly once),
    and cold_start_from_wal's successor completes the remaining pods
    without ever re-binding one."""
    from kubernetes_tpu.recovery import cold_start_from_wal
    from kubernetes_tpu.scheduler import TPUScheduler

    store, wal = _wal_store(tmp_path, fsync_every=1)
    for i in range(4):
        store.create("Node", _mk_node(i))
    for i in range(6):
        store.create("Pod", _mk_pod(i))
    fault = FaultSchedule(0, crash_points={"crash.mid_bind": 3})
    sched = TPUScheduler(store, batch_size=8)
    with crash_schedule(fault):
        with pytest.raises(ProcessCrash):
            sched.run_until_idle(max_cycles=5)
    sched.close(flush_events=False)
    # the dead replica's store, reconstructed from nothing but the file,
    # must equal the store the process died holding — the 3rd bind landed
    # in the WAL before crash.mid_bind fired (bind logs before it applies)
    live = _manifests(store, scheme)
    replay = replay_on_boot(str(tmp_path / "store.wal"), scheme=scheme)
    assert _manifests(replay.store, scheme) == live
    bound_at_death = [p for p in replay.store.list("Pod")[0]
                      if p.spec.node_name]
    assert len(bound_at_death) == 3
    # successor: WAL-first cold start, then finish the work
    res, rep = cold_start_from_wal(str(tmp_path / "store.wal"),
                                   scheme=scheme, batch_size=8)
    assert rep.records_applied > 0 and not rep.truncated_tail
    assert res.outcome == "clean"
    res.scheduler.run_until_idle(max_cycles=10)
    pods, _ = res.scheduler.store.list("Pod")
    assert all(p.spec.node_name for p in pods)
    # exactly-once: the replayed history shows ONE unbound→bound
    # transition per pod — the successor never re-bound a survivor
    node_of, counts = {}, {}
    for ev in res.scheduler.store._log:
        if ev.kind != "Pod":
            continue
        name = ev.obj.metadata.name
        nn = ev.obj.spec.node_name or None
        if nn is not None and node_of.get(name) is None:
            counts[name] = counts.get(name, 0) + 1
        node_of[name] = nn
    assert counts == {f"p{i}": 1 for i in range(6)}
    # and the successor's own binds kept appending to the SAME log: a
    # final replay shows the complete world
    res.scheduler.close()
    final = replay_on_boot(str(tmp_path / "store.wal"), scheme=scheme)
    assert _manifests(final.store, scheme) == \
        _manifests(res.scheduler.store, scheme)
