"""WAL-shipped follower replicas: ship protocol, rv-gated serving,
promotion + fencing, unshipped-suffix discard, and the chaos soak.

Reference behaviors exercised: etcd's raft log shipping (a follower's
log is always a verified prefix of the leader's; apply is offset-
contiguous and exactly-once), the cacher's bookmark discipline extended
across processes (a follower never bookmarks past its replication
watermark), and lease-fenced promotion (exactly one winner per
incarnation, the loser's promote() refuses).
"""

import json
import os
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.analysis import lockcheck
from kubernetes_tpu.chaos.faults import (
    CRASH_MID_PROMOTE,
    CRASH_POINTS,
    FaultSchedule,
    ProcessCrash,
    crash_schedule,
)
from kubernetes_tpu.chaos.replication import ShipFaults, run_replication_soak
from kubernetes_tpu.client.leaderelection import LeaderElector, LeaseLock
from kubernetes_tpu.metrics import scheduler_metrics as m
from kubernetes_tpu.sim.replication import (
    FollowerReplica,
    LogShipper,
    PromotionFenced,
    discard_unshipped_suffix,
    divergence_probe,
    rebase_follower,
)
from kubernetes_tpu.sim.store import FollowerReadOnly, ObjectStore
from kubernetes_tpu.sim.wal import (
    WriteAheadLog,
    replay_on_boot,
    scan_records,
)
from kubernetes_tpu.testutil import make_node, make_pod


@pytest.fixture(autouse=True)
def lock_order_monitor():
    """deliver() holds the replica condition across store apply and cache
    fan-out; the bookmark gate reads it from the cache's bookmark path —
    every battery here runs with inversion detection.  The replica's
    _cond is constructed through maybe_wrap (the CheckedLock Condition
    protocol keeps wait()'s full reentrant release exact), so the access
    sanitizer can attribute watermark writes to the held condition and
    cross-check any unsynchronized pattern against the static
    thread-ownership report."""
    mon = lockcheck.activate()
    san = lockcheck.sanitize([FollowerReplica, LogShipper])
    try:
        yield mon
    finally:
        lockcheck.unsanitize()
        lockcheck.deactivate()
    assert not mon.violations, mon.report()
    if san.needs_verify():  # lazy: clean runs never build the report
        from kubernetes_tpu.analysis.threads import repo_ownership_report
        san.assert_consistent(repo_ownership_report())


def _pod(i, ns="default"):
    return (make_pod().name(f"p{i:03d}").uid(f"p{i:03d}").namespace(ns)
            .req({"cpu": "1"}).creation_timestamp(100.0 + i).obj())


def _leader(tmp_path, fsync_every=0):
    wal = WriteAheadLog(str(tmp_path / "leader.wal"), fsync_every=fsync_every)
    return ObjectStore(wal=wal), wal


def _follower(tmp_path, name="f1", **kw):
    return FollowerReplica(name, str(tmp_path / f"{name}.wal"), **kw)


# --- ship protocol ------------------------------------------------------------


def test_shipper_streams_records_and_follower_converges(tmp_path):
    store, wal = _leader(tmp_path)
    ship = LogShipper(wal.path, batch_max_records=3)
    f = _follower(tmp_path)
    ship.attach(f)
    for i in range(10):
        store.create("Pod", _pod(i))
    ship.pump_until_synced()
    assert f.applied_rv() == store.current_rv()
    assert f.lag_rv() == 0
    assert f.acked_offset() == os.path.getsize(wal.path)
    # the follower's file is a byte-identical prefix (here: copy) of the
    # leader's — the log-matching property offsets rely on
    assert open(f.wal_path, "rb").read() == open(wal.path, "rb").read()
    objs, rv = f.store.list("Pod")
    assert len(objs) == 10 and rv == store.current_rv()


def test_ship_delay_models_replication_lag(tmp_path):
    store, wal = _leader(tmp_path)
    ship = LogShipper(wal.path, ship_delay=3)
    f = _follower(tmp_path)
    ship.attach(f)
    store.create("Pod", _pod(0))
    ship.pump()  # batch cut at tick 1, due at tick 4
    assert f.applied_rv() == 0 and f.leader_rv() == 0
    ship.pump()
    ship.pump()
    assert f.applied_rv() == 0, "batch delivered before its ship delay"
    ship.pump()
    assert f.applied_rv() == store.current_rv()


def test_dropped_batches_resend_from_acked_offset(tmp_path):
    store, wal = _leader(tmp_path)
    faults = ShipFaults(seed=3, drop_rate=1.0, max_faults_per_stream=2)
    ship = LogShipper(wal.path, batch_max_records=2, faults=faults)
    f = _follower(tmp_path)
    ship.attach(f)
    for i in range(6):
        store.create("Pod", _pod(i))
    ship.pump_until_synced()
    assert f.applied_rv() == store.current_rv()
    assert faults.injected.get("ship_drop") == 2


def test_torn_batch_applies_verified_prefix_then_resends(tmp_path):
    store, wal = _leader(tmp_path)
    faults = ShipFaults(seed=5, torn_rate=1.0, max_faults_per_stream=1)
    ship = LogShipper(wal.path, batch_max_records=4, faults=faults)
    f = _follower(tmp_path)
    ship.attach(f)
    for i in range(8):
        store.create("Pod", _pod(i))
    ship.pump_until_synced()
    assert f.applied_rv() == store.current_rv()
    assert faults.injected.get("ship_torn") == 1
    # exactly-once despite the redelivery overlap: every rv applied once
    rvs = [ev.resource_version for ev in f.store._log]
    assert rvs == sorted(set(rvs))


def test_gap_batch_rejected_until_resend_fills_it(tmp_path):
    store, wal = _leader(tmp_path)
    store.create("Pod", _pod(0))
    data = open(wal.path, "rb").read()
    f = _follower(tmp_path)
    errs0 = m.replication_ship_errors.value(("gap",))
    # a batch from a FUTURE offset (its predecessor was dropped): rejected
    # whole, counted, watermark unmoved
    assert f.deliver(data, from_offset=100, leader_rv=1) == 0
    assert f.ship_errors == 1
    assert m.replication_ship_errors.value(("gap",)) == errs0 + 1
    assert f.applied_rv() == 0
    # the contiguous resend applies; a duplicate redelivery is a no-op
    assert f.deliver(data, from_offset=0, leader_rv=1) == 1
    assert f.deliver(data, from_offset=0, leader_rv=1) == 0
    assert f.applied_rv() == 1


def test_follower_store_rejects_direct_writes(tmp_path):
    f = _follower(tmp_path)
    with pytest.raises(FollowerReadOnly):
        f.store.create("Pod", _pod(0))
    with pytest.raises(FollowerReadOnly):
        f.store.bind_pod("default", "p000", "n0")
    # replay_record is exempt: it IS the replication apply path
    f.store.replay_record("create", "Pod", obj=_pod(0), rv=1)
    assert f.store.get("Pod", "default", "p000") is not None


def test_wait_for_rv_bounded(tmp_path):
    f = _follower(tmp_path)
    assert f.wait_for_rv(0, timeout=0.01)
    assert not f.wait_for_rv(5, timeout=0.05), \
        "wait_for_rv returned for an rv never applied"


# --- satellite 2: torn-tail truncation stays shippable ------------------------


def test_follower_attaching_mid_truncation_never_applies_torn_record(
        tmp_path):
    """replay_on_boot's torn-tail cut must leave the file re-openable for
    SHIPPING too: a follower attached across the truncation boundary never
    applies the torn record and resumes at the next clean append."""
    store, wal = _leader(tmp_path)
    for i in range(4):
        store.create("Pod", _pod(i))
    wal.close()
    good_size = os.path.getsize(wal.path)
    # crash mid-append: half a record lands past the verified tail
    with open(wal.path, "ab") as fh:
        fh.write(b"\x00\x00\x01\x00GARBAGE-TORN-TAIL")
    ship = LogShipper(wal.path, batch_max_records=2)
    f = _follower(tmp_path)
    ship.attach(f)
    ship.pump_until_synced()
    # only the verified prefix shipped; the torn bytes never advanced the
    # scan cursor (re-read every tick, never verified)
    assert f.applied_rv() == 4
    assert ship.verified_offset == good_size
    assert ship.scan_regressions == 0
    # boot-path recovery truncates the tail DURABLY and reopens for appends
    replay = replay_on_boot(wal.path, truncate=True)
    assert replay.truncated_tail and replay.truncated_at == good_size
    wal2 = WriteAheadLog(wal.path, fsync_every=0)
    store2 = replay.store
    store2.wal = wal2
    store2.create("Pod", _pod(9))
    # the clean append lands exactly where the torn record sat; the
    # follower ships and applies it with no gap, no garbage, no regress
    ship.pump_until_synced()
    assert f.applied_rv() == store2.current_rv() == 5
    assert f.store.get("Pod", "default", "p009") is not None
    assert ship.scan_regressions == 0
    assert b"GARBAGE" not in open(f.wal_path, "rb").read()


# --- promotion, fencing, divergence -------------------------------------------


def _elect(election_store, identity, clock, lease_duration=0.3):
    return LeaderElector(
        LeaseLock(election_store, "kube-system", "repl-lease"),
        identity=identity, lease_duration=lease_duration, clock=clock)


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_promotion_race_fences_single_winner(tmp_path):
    store, wal = _leader(tmp_path)
    ship = LogShipper(wal.path)
    f1, f2 = _follower(tmp_path, "f1"), _follower(tmp_path, "f2")
    ship.attach(f1)
    ship.attach(f2)
    for i in range(5):
        store.create("Pod", _pod(i))
    ship.pump_until_synced()
    wal.close()
    clock = _Clock()
    election = ObjectStore()
    e1, e2 = _elect(election, "f1", clock), _elect(election, "f2", clock)
    # both race: the lease CAS picks exactly one
    won1 = e1.try_acquire_or_renew()
    won2 = e2.try_acquire_or_renew()
    assert won1 and not won2
    with pytest.raises(PromotionFenced):
        f2.promote(elector=e2)
    assert f2.role == "follower" and f2.store.read_only
    res = f1.promote(elector=e1)
    assert f1.role == "leader" and not f1.store.read_only
    assert res.last_rv == 5
    # the promoted log takes appends at the truncation-checked tail
    f1.store.create("Pod", _pod(9))
    assert f1.store.current_rv() == 6


def test_unshipped_suffix_discard_exactly_once_and_divergence_probe(
        tmp_path):
    store, wal = _leader(tmp_path)
    store.create("Node", make_node().name("n0")
                 .capacity({"cpu": "8", "pods": "32"}).obj())
    ship = LogShipper(wal.path)
    f = _follower(tmp_path)
    ship.attach(f)
    for i in range(4):
        store.create("Pod", _pod(i))
    ship.pump_until_synced()
    shipped_rv = f.applied_rv()
    # acknowledged writes the stream never carries — including a bind,
    # the classic phantom the probe hunts
    store.create("Pod", _pod(7))
    store.bind_pod("default", "p007", "n0")
    wal.close()
    res = f.promote()
    d1 = discard_unshipped_suffix(wal.path, f.acked_offset())
    assert [r.op for r in d1.discarded] == ["create", "bind"]
    assert d1.truncated_bytes > 0
    # exactly-once: the second call finds nothing to cut
    d2 = discard_unshipped_suffix(wal.path, f.acked_offset())
    assert not d2.discarded and d2.truncated_bytes == 0
    assert divergence_probe(f.store, d1.discarded, res.last_rv) == []
    assert f.store.get("Pod", "default", "p007") is None
    assert shipped_rv == res.last_rv
    # a PHANTOM is detected: apply the discarded suffix as if it leaked
    for rec in d1.discarded:
        obj = (f.store.wal.scheme().decode(rec.manifest)
               if rec.manifest is not None else None)
        f.store.replay_record(rec.op, rec.kind, obj=obj,
                              namespace=rec.namespace, name=rec.name,
                              node_name=rec.node_name, rv=rec.rv)
    phantoms = divergence_probe(f.store, d1.discarded, res.last_rv)
    assert phantoms and any("phantom bind" in p for p in phantoms)


def test_crash_mid_promote_is_idempotent(tmp_path):
    assert CRASH_MID_PROMOTE in CRASH_POINTS
    store, wal = _leader(tmp_path)
    ship = LogShipper(wal.path)
    f = _follower(tmp_path)
    ship.attach(f)
    for i in range(6):
        store.create("Pod", _pod(i))
    ship.pump_until_synced()
    wal.close()
    fault = FaultSchedule(0, crash_points={CRASH_MID_PROMOTE: 1})
    with crash_schedule(fault):
        with pytest.raises(ProcessCrash):
            f.promote()
        # death between the durable tail fsync and the WAL reattach: the
        # replica object is gone, but everything promotion needs is in
        # the file — a fresh incarnation on the same path just promotes
        f2 = FollowerReplica("f1", f.wal_path)
        assert f2.applied_rv() == 6
        res = f2.promote()
    assert res.last_rv == 6 and f2.role == "leader"
    f2.store.create("Pod", _pod(9))
    assert f2.store.current_rv() == 7


def test_rebase_rolls_loser_back_to_winner_log_length(tmp_path):
    store, wal = _leader(tmp_path)
    ship = LogShipper(wal.path)
    slow, fast = _follower(tmp_path, "slow"), _follower(tmp_path, "fast")
    ship.attach(fast)
    for i in range(6):
        store.create("Pod", _pod(i))
    ship.pump_until_synced()
    # "slow" wins the race holding only a 3-record prefix; "fast" ran
    # ahead on the wire — deliver the prefix bytes directly
    data = open(wal.path, "rb").read()
    records, _ = scan_records(data)
    prefix_end = records[3][0]  # offset where record 4 begins
    assert slow.deliver(data[:prefix_end], 0, 3) == 3
    wal.close()
    win = slow.promote()
    cut = slow.acked_offset()
    assert fast.acked_offset() > cut
    rebased, rolled = rebase_follower(fast, cut)
    assert [r.rv for r in rolled] == list(range(win.last_rv + 1, 7))
    assert rebased.applied_rv() == win.last_rv
    assert os.path.getsize(rebased.wal_path) == cut
    # rebased follower resumes cleanly over the new leader's log
    ship3 = LogShipper(slow.wal_path)
    ship3.attach(rebased)
    slow.store.create("Pod", _pod(9))
    ship3.pump_until_synced()
    assert rebased.applied_rv() == slow.store.current_rv()


# --- follower HTTP serving ----------------------------------------------------


def _http_fixture(tmp_path, **server_kw):
    from kubernetes_tpu.apiserver.server import APIServer

    store, wal = _leader(tmp_path)
    ship = LogShipper(wal.path)
    f = _follower(tmp_path, **{k: v for k, v in server_kw.items()
                               if k == "ring_size"})
    ship.attach(f)
    api = APIServer(replica=f,
                    follower_wait_seconds=server_kw.get(
                        "follower_wait_seconds", 0.15)).start()
    return store, wal, ship, f, api


def test_follower_serves_rv_consistent_list_and_waits_then_504(tmp_path):
    store, wal, ship, f, api = _http_fixture(tmp_path)
    try:
        for i in range(5):
            store.create("Pod", _pod(i))
        ship.pump_until_synced()
        r = urllib.request.urlopen(
            f"{api.url}/api/v1/pods?resourceVersion={f.applied_rv()}")
        assert len(json.loads(r.read())["items"]) == 5
        # an rv the watermark has not reached: bounded wait, then 504
        # Timeout (NOT 410 — the rv is valid, just not here yet)
        store.create("Pod", _pod(9))
        rej0 = m.apiserver_rejected.value(("follower_lag",))
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{api.url}/api/v1/pods"
                f"?resourceVersion={store.current_rv()}")
        assert ei.value.code == 504
        assert ei.value.headers.get("Retry-After") is not None
        assert json.loads(ei.value.read())["reason"] == "Timeout"
        assert m.apiserver_rejected.value(("follower_lag",)) == rej0 + 1
        # once shipped, the same rv serves
        ship.pump_until_synced()
        r = urllib.request.urlopen(
            f"{api.url}/api/v1/pods?resourceVersion={store.current_rv()}")
        assert len(json.loads(r.read())["items"]) == 6
        # watch above the watermark gates the same way
        store.create("Pod", _pod(10))
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{api.url}/api/v1/pods?watch=true"
                f"&resourceVersion={store.current_rv()}&timeoutSeconds=1")
        assert ei.value.code == 504
    finally:
        api.stop()


def test_follower_rejects_writes_503_until_promoted(tmp_path):
    from kubernetes_tpu.api.serialize import to_manifest

    store, wal, ship, f, api = _http_fixture(tmp_path)
    try:
        manifest = to_manifest(_pod(0), f.scheme())
        req = urllib.request.Request(
            f"{api.url}/api/v1/namespaces/default/pods",
            data=json.dumps(manifest).encode(), method="POST")
        rej0 = m.apiserver_rejected.value(("follower_readonly",))
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After") is not None
        assert m.apiserver_rejected.value(("follower_readonly",)) == rej0 + 1
        # promotion opens writes on the SAME server — the role check is
        # live, no restart, no re-wiring
        wal.close()
        f.promote()
        assert urllib.request.urlopen(req).status == 201
    finally:
        api.stop()


def test_follower_shorter_ring_answers_410_for_relist(tmp_path):
    store, wal, ship, f, api = _http_fixture(tmp_path, ring_size=4)
    try:
        for i in range(14):
            store.create("Pod", _pod(i))
        ship.pump_until_synced()
        assert f.watch_cache.oldest_rv > 1
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{api.url}/api/v1/pods?watch=true&resourceVersion=1"
                f"&timeoutSeconds=1")
        assert ei.value.code == 410
        assert json.loads(ei.value.read())["reason"] == "Expired"
        # rv=0 ("serve current") still lists — the relist entry point
        r = urllib.request.urlopen(f"{api.url}/api/v1/pods?resourceVersion=0")
        assert len(json.loads(r.read())["items"]) == 14
    finally:
        api.stop()


def test_follower_bookmarks_clamp_to_replication_watermark(tmp_path):
    store, wal = _leader(tmp_path)
    ship = LogShipper(wal.path)
    f = _follower(tmp_path)
    ship.attach(f)
    for i in range(5):
        store.create("Pod", _pod(i))
    ship.pump_until_synced()
    marks = []
    unwatch = f.watch_cache.watch(lambda ev: None,
                                  since_rv=f.applied_rv(),
                                  on_bookmark=marks.append)
    assert f.watch_cache.bookmark_now() == 5
    # an artificially LOW gate (mid-apply watermark) clamps the bookmark
    # below fanned_rv — the cross-process no-overclaim rule, isolated
    f.watch_cache.bookmark_gate = lambda: 3
    assert f.watch_cache.bookmark_rv() == 3
    assert f.watch_cache.bookmark_now() == 3
    assert marks == [5, 3]
    unwatch()
    # promotion lifts the gate: leader bookmarks follow fanned_rv again
    wal.close()
    f.promote()
    assert f.watch_cache.bookmark_gate is None
    assert f.watch_cache.bookmark_rv() == f.watch_cache.fanned_rv()


# --- the soak (fast shapes; acceptance shape is slow-marked) ------------------


@pytest.mark.parametrize("kill_mode", ["shipped", "unshipped", "torn"])
def test_replication_soak_fast_shape(tmp_path, kill_mode):
    r = run_replication_soak(seed=11, workdir=str(tmp_path),
                             kill_mode=kill_mode)
    assert r.converged, r
    assert r.fenced_losers == 1
    assert r.promotion_ticks <= 60
    if kill_mode != "shipped":
        assert r.discarded_records > 0
    assert r.phantoms == []


def test_replication_soak_deterministic_replay(tmp_path):
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    a = run_replication_soak(seed=23, workdir=str(tmp_path / "a"),
                             kill_mode="unshipped")
    b = run_replication_soak(seed=23, workdir=str(tmp_path / "b"),
                             kill_mode="unshipped")
    assert a.determinism_signature() == b.determinism_signature()


@pytest.mark.slow
def test_replication_soak_thousand_watcher_acceptance_shape(tmp_path):
    """ISSUE 16 acceptance: 500 recording watchers per follower (1000
    total), heavy fault rates, leader killed with an unshipped suffix —
    zero lost/dup events, zero overclaimed bookmarks, exactly-once binds
    across the incarnation boundary (tools/replica_soak.py runs this
    same shape as the CI gate)."""
    r = run_replication_soak(seed=16, n_pods=120, n_watchers=500,
                             workdir=str(tmp_path), kill_mode="unshipped",
                             drop_rate=0.15, torn_rate=0.1, lag_rate=0.1)
    assert r.converged, r
    assert r.events_lost == 0 and r.events_duplicated == 0
    assert r.bookmark_overclaims == 0
    assert r.duplicate_binds == 0
