"""Cluster autoscaler: NodeGroup API, scale-up e2e (starved gang →
simulated → applied → all-or-nothing bind), scale-down gating (PDB,
replacement proof, min-size), chaos exactly-once, and the CLI surface."""

import time

import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.api.scheme import default_scheme
from kubernetes_tpu.api.serialize import roundtrips
from kubernetes_tpu.autoscaler import (
    NODE_GROUP_LABEL,
    ClusterAutoscaler,
    NodeGroup,
    member_nodes,
)
from kubernetes_tpu.analysis import lockcheck
from kubernetes_tpu.cli import Kubectl
from kubernetes_tpu.controllers.disruption import sync_pdbs
from kubernetes_tpu.gang import POD_GROUP_LABEL, SLICE_LABEL
from kubernetes_tpu.metrics import scheduler_metrics as m
from kubernetes_tpu.scheduler import TPUScheduler
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod


@pytest.fixture(autouse=True)
def lock_order_monitor():
    """Same contract as the chaos battery's autouse monitor: autoscaler
    syncs run under runtime lock-order instrumentation — the controller
    drives whatif solves, the eviction gate, store writes, and metrics in
    one call stack, so every lock constructed during the test (EvictionAPI,
    ObjectStore, reflectors, metric registries) reports acquired-after
    inversions at teardown."""
    mon = lockcheck.activate()
    try:
        yield mon
    finally:
        lockcheck.deactivate()
    assert not mon.violations, mon.report()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _group(name="tpu", min_size=0, max_size=8, cpu="4", slice_size=4,
           cost=1.0):
    return NodeGroup(
        metadata=v1.ObjectMeta(name=name, namespace="default"),
        min_size=min_size, max_size=max_size,
        capacity={"cpu": cpu, "pods": "10"}, slice_size=slice_size,
        cost_per_node=cost)


def _gang(store, name="g", members=4, cpu="3", created=100.0):
    pg = v1.PodGroup(metadata=v1.ObjectMeta(name=name, namespace="default"),
                     min_member=members, schedule_timeout_seconds=30)
    pg.metadata.creation_timestamp = created
    store.create("PodGroup", pg)
    for i in range(members):
        p = (make_pod().name(f"{name}-{i}").uid(f"{name}-{i}")
             .namespace("default").label(POD_GROUP_LABEL, name)
             .req({"cpu": cpu}).obj())
        p.metadata.creation_timestamp = created
        store.create("Pod", p)


def _starve(store, sched, clock, cycles=4):
    for _ in range(cycles):
        sched.schedule_cycle()
        clock.advance(0.5)
    clock.advance(40.0)  # fail any Permit hold so nothing stays assumed
    sched.schedule_cycle()


def _member_node(store, group_name, idx, cpu="4", slice_name="s0"):
    store.create("Node", make_node().name(f"{group_name}-{idx}")
                 .capacity({"cpu": cpu, "pods": "10"})
                 .label(NODE_GROUP_LABEL, group_name)
                 .label(SLICE_LABEL, slice_name).obj())


# --- API object ---------------------------------------------------------------


def test_nodegroup_scheme_roundtrip():
    s = default_scheme()
    ng = _group()
    ng.taints = [v1.Taint(key="tpu", value="1")]
    assert roundtrips(ng, s)
    # served under the autoscaling group; wrong group is rejected
    from kubernetes_tpu.api.serialize import to_manifest

    man = to_manifest(ng, s)
    assert man["apiVersion"] == "autoscaling.x-k8s.io/v1alpha1"
    assert man["spec"]["template"]["sliceSize"] == 4


# --- scale-up -----------------------------------------------------------------


def test_scale_up_starved_gang_binds_all_or_nothing():
    """THE acceptance scenario: a starved multi-host gang goes from
    Unschedulable to fully bound via a simulated-then-applied scale-up —
    the nodes the simulation forked are the nodes the apply creates."""
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, clock=clock, batch_wait=0)
    # an existing slice too small for the gang (2 hosts; gang needs 4)
    for i in range(2):
        store.create("Node", make_node().name(f"n{i}")
                     .capacity({"cpu": "4", "pods": "10"})
                     .label(SLICE_LABEL, "s0").obj())
    store.create("NodeGroup", _group(max_size=8, slice_size=4))
    _gang(store, "g", members=4, cpu="3")
    _starve(store, sched, clock)
    assert len(sched.queue.unschedulable_pods()) == 4
    ca = ClusterAutoscaler(store, sched)
    assert ca.sync_once() is True
    [d] = ca.last_decisions
    assert (d.direction, d.result, d.count) == ("up", "applied", 4)
    assert m.autoscaler_scale_decisions.value(("up", "applied")) >= 1.0
    # one whole fresh slice materialized with deterministic names
    added = member_nodes(store.get("NodeGroup", "default", "tpu"),
                         store.list("Node")[0])
    assert sorted(n.metadata.name for n in added) == \
        ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]
    assert {n.metadata.labels[SLICE_LABEL] for n in added} == \
        {"tpu-slice-0"}
    # a consecutive sync BEFORE the scheduler retries must not
    # over-provision: the zero-add baseline proves the demand now fits
    assert ca.sync_once() is False
    assert len(member_nodes(store.get("NodeGroup", "default", "tpu"),
                            store.list("Node")[0])) == 4
    sched.run_until_idle(backoff_wait=2.0)
    bound = [store.get("Pod", "default", f"g-{i}").spec.node_name
             for i in range(4)]
    assert all(bound), bound  # all-or-nothing: every member bound
    assert set(bound) == {n.metadata.name for n in added}
    assert store.get("PodGroup", "default", "g").phase == \
        v1.POD_GROUP_SCHEDULED
    # demand satisfied: the next sync decides nothing
    assert ca.sync_once() is False
    assert ca.last_decisions == []


def test_scale_up_bounded_by_max_size():
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, clock=clock, batch_wait=0)
    store.create("Node", make_node().name("n0")
                 .capacity({"cpu": "4", "pods": "10"}).obj())
    # max_size 2 < the 4 hosts the gang needs: no viable candidate
    store.create("NodeGroup", _group(max_size=2, slice_size=1))
    _gang(store, "g", members=4, cpu="3")
    _starve(store, sched, clock)
    ca = ClusterAutoscaler(store, sched)
    assert ca.sync_once() is False
    [d] = ca.last_decisions
    assert d.result == "no_fit"
    assert m.autoscaler_scale_decisions.value(("up", "no_fit")) >= 1.0
    assert all(NODE_GROUP_LABEL not in n.metadata.labels
               for n in store.list("Node")[0])
    # a group already at max reports at_max
    store.delete("NodeGroup", "default", "tpu")
    g0 = _group(name="full", max_size=1, slice_size=1)
    store.create("NodeGroup", g0)
    _member_node(store, "full", 0)
    sched.schedule_cycle()
    assert ca.sync_once() is False
    assert ca.last_decisions[-1].result == "at_max"


def test_scale_up_picks_cheapest_group():
    """Expander analog: two groups can seat the demand; the cheaper total
    cost (count × cost_per_node) wins."""
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, clock=clock, batch_wait=0)
    store.create("Node", make_node().name("n0")
                 .capacity({"cpu": "1", "pods": "10"}).obj())
    # big hosts: 2 nodes × cost 4 = 8; small hosts: 4 nodes × cost 1 = 4
    store.create("NodeGroup", _group(name="big", cpu="8", slice_size=1,
                                     cost=4.0, max_size=8))
    store.create("NodeGroup", _group(name="small", cpu="4", slice_size=1,
                                     cost=1.0, max_size=8))
    _gang(store, "g", members=4, cpu="3")
    _starve(store, sched, clock)
    ca = ClusterAutoscaler(store, sched)
    assert ca.sync_once() is True
    [d] = ca.last_decisions
    assert d.group == "small" and d.result == "applied"
    sched.run_until_idle(backoff_wait=2.0)
    assert all(store.get("Pod", "default", f"g-{i}").spec.node_name
               for i in range(4))


def test_scale_up_dry_run_creates_nothing():
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, clock=clock, batch_wait=0)
    store.create("Node", make_node().name("n0")
                 .capacity({"cpu": "4", "pods": "10"}).obj())
    store.create("NodeGroup", _group(max_size=8, slice_size=1))
    _gang(store, "g", members=2, cpu="3")
    _starve(store, sched, clock)
    nodes_before = {n.metadata.name for n in store.list("Node")[0]}
    ca = ClusterAutoscaler(store, sched, dry_run=True)
    assert ca.sync_once() is False
    assert ca.last_decisions[0].result == "dry_run"
    assert {n.metadata.name for n in store.list("Node")[0]} == nodes_before


# --- expander strategies ------------------------------------------------------


def test_waste_of_unit():
    """Waste = mean unused fraction of the ADDED capacity over declared
    dims; more nodes for the same demand is strictly more waste."""
    group = _group(name="g", cpu="4")  # caps: cpu 4000m, pods 10
    need = {"cpu": 4000.0, "pods": 4.0}
    ca = object.__new__(ClusterAutoscaler)
    w1 = ClusterAutoscaler._waste_of(ca, group, 1, need)
    assert w1 == pytest.approx((0.0 + 0.6) / 2)
    w2 = ClusterAutoscaler._waste_of(ca, group, 2, need)
    assert w2 > w1
    # over-demand clamps at full utilization, never negative waste
    w0 = ClusterAutoscaler._waste_of(ca, group, 1,
                                     {"cpu": 99999.0, "pods": 99.0})
    assert w0 == 0.0


def test_unknown_expander_rejected():
    with pytest.raises(ValueError):
        ClusterAutoscaler(ObjectStore(), TPUScheduler(ObjectStore()),
                          expander="cheapest")


def _two_group_env():
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, clock=clock, batch_wait=0)
    store.create("Node", make_node().name("n0")
                 .capacity({"cpu": "1", "pods": "10"}).obj())
    # 'small' is cheaper in TOTAL cost (8 × 1.0) but strands 90% of its
    # pods capacity; 'big' costs more (1 × 10.0) yet its one template
    # node is exactly filled by the demand
    store.create("NodeGroup", _group(name="small", cpu="2", slice_size=1,
                                     cost=1.0, max_size=8))
    store.create("NodeGroup", _group(name="big", cpu="16", slice_size=1,
                                     cost=10.0, max_size=8))
    _gang(store, "g", members=8, cpu="2")
    _starve(store, sched, clock)
    return store, sched


def test_expander_least_cost_default_picks_cheapest_total():
    store, sched = _two_group_env()
    ca = ClusterAutoscaler(store, sched)
    assert ca.expander == "least-cost"
    assert ca.sync_once() is True
    [d] = ca.last_decisions
    assert (d.group, d.result, d.count) == ("small", "applied", 8)


def test_expander_least_waste_prefers_filled_template():
    """ROADMAP item-2 follow-on: least-waste picks the group whose added
    nodes the demand actually fills, tie-breaking on cost — here the
    8×2cpu demand exactly fills ONE 16-cpu template, so 'big' wins even
    though its total cost is higher."""
    store, sched = _two_group_env()
    ca = ClusterAutoscaler(store, sched, expander="least-waste")
    assert ca.sync_once() is True
    [d] = ca.last_decisions
    assert (d.group, d.result, d.count) == ("big", "applied", 1)
    # and the demand then binds onto the new node
    sched.run_until_idle(backoff_wait=2.0)
    bound = {store.get("Pod", "default", f"g-{i}").spec.node_name
             for i in range(8)}
    assert bound == {"big-0"}


# --- scale-down ---------------------------------------------------------------


def _scaled_cluster(clock, idle_cpu="1"):
    """A 3-member group (min 1): two busy hosts (3/4 cpu) and one
    underutilized host carrying a single small pod."""
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, clock=clock, batch_wait=0)
    store.create("NodeGroup", _group(min_size=1, max_size=8, slice_size=0))
    for i in range(3):
        _member_node(store, "tpu", i)
    for i in range(2):
        store.create("Pod", make_pod().name(f"busy-{i}").uid(f"busy-{i}")
                     .namespace("default").req({"cpu": "3"})
                     .node(f"tpu-{i}").obj())
    store.create("Pod", make_pod().name("idle").uid("idle")
                 .namespace("default").label("app", "idle")
                 .req({"cpu": idle_cpu}).node("tpu-2").obj())
    sched.schedule_cycle()
    return store, sched


def test_scale_down_drains_underutilized_node():
    clock = FakeClock()
    store, sched = _scaled_cluster(clock)
    ca = ClusterAutoscaler(store, sched)
    assert ca.sync_once() is True
    [d] = ca.last_decisions
    assert (d.direction, d.result) == ("down", "applied")
    assert store.get("Node", "", "tpu-2") is None
    assert store.get("Pod", "default", "idle") is None  # drained via gate
    assert m.descheduler_evictions.value(("autoscaler", "evicted")) >= 1.0
    assert m.autoscaler_scale_decisions.value(("down", "applied")) >= 1.0


def test_scale_down_refused_when_pdb_blocks():
    clock = FakeClock()
    store, sched = _scaled_cluster(clock)
    pdb = v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="prot", namespace="default"),
        selector=v1.LabelSelector(match_labels={"app": "idle"}),
        min_available=1)
    store.create("PodDisruptionBudget", pdb)
    sync_pdbs(store)
    ca = ClusterAutoscaler(store, sched)
    assert ca.sync_once() is False
    [d] = ca.last_decisions
    assert d.result == "blocked" and "pdb" in d.note
    assert store.get("Node", "", "tpu-2") is not None
    assert store.get("Pod", "default", "idle") is not None
    assert m.autoscaler_scale_decisions.value(("down", "blocked")) >= 1.0


def test_scale_down_joint_pdb_budget_refuses_before_any_eviction():
    """Two pods on the candidate node share ONE PDB with budget 1: each
    alone would pass a per-pod check, but draining the node needs both —
    the joint pre-check refuses WITHOUT killing either pod."""
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, clock=clock, batch_wait=0)
    store.create("NodeGroup", _group(min_size=0, max_size=8, slice_size=0))
    for i in range(3):
        _member_node(store, "tpu", i)
    for i in range(2):  # busy hosts: not scale-down candidates
        store.create("Pod", make_pod().name(f"busy-{i}").uid(f"busy-{i}")
                     .namespace("default").req({"cpu": "3"})
                     .node(f"tpu-{i}").obj())
    for i in range(2):
        store.create("Pod", make_pod().name(f"pair-{i}").uid(f"pair-{i}")
                     .namespace("default").label("app", "pair")
                     .req({"cpu": "500m"}).node("tpu-2").obj())
    pdb = v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="pair", namespace="default"),
        selector=v1.LabelSelector(match_labels={"app": "pair"}),
        min_available=1)  # budget 1 < the 2 the drain needs
    store.create("PodDisruptionBudget", pdb)
    sync_pdbs(store)
    sched.schedule_cycle()
    ca = ClusterAutoscaler(store, sched)
    assert ca.sync_once() is False
    [d] = ca.last_decisions
    assert d.result == "blocked" and "afford" in d.note
    # nothing was evicted — the drain never started
    assert store.get("Pod", "default", "pair-0") is not None
    assert store.get("Pod", "default", "pair-1") is not None
    assert store.get("Node", "", "tpu-2") is not None


def test_scale_up_skips_unlabeled_name_squatter():
    """A node named like a group member but WITHOUT the membership label
    (operator-created) must not collide with the simulation's template
    encode — the next index skips past it."""
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, clock=clock, batch_wait=0)
    # name-squatter: tpu-0 exists, unlabeled, and is fully occupied
    store.create("Node", make_node().name("tpu-0")
                 .capacity({"cpu": "4", "pods": "10"}).obj())
    store.create("Pod", make_pod().name("squat").uid("squat")
                 .namespace("default").req({"cpu": "4"}).node("tpu-0").obj())
    store.create("NodeGroup", _group(max_size=8, slice_size=2))
    _gang(store, "g", members=2, cpu="3")
    _starve(store, sched, clock)
    ca = ClusterAutoscaler(store, sched)
    assert ca.sync_once() is True
    [d] = ca.last_decisions
    assert d.result == "applied"
    names = {n.metadata.name for n in store.list("Node")[0]}
    assert "tpu-1" in names and "tpu-2" in names  # skipped past tpu-0
    sched.run_until_idle(backoff_wait=2.0)
    assert all(store.get("Pod", "default", f"g-{i}").spec.node_name
               for i in range(2))


def test_scale_down_refused_when_displaced_pods_dont_replace():
    clock = FakeClock()
    # the idle host's pod needs 1.5 cpu (util 0.375 < threshold); the
    # surviving hosts have only 1 cpu free each — the what-if proves no
    # re-placement, so no scale-down
    store, sched = _scaled_cluster(clock, idle_cpu="1500m")
    ca = ClusterAutoscaler(store, sched)
    assert ca.sync_once() is False
    [d] = ca.last_decisions
    assert d.result == "no_replacement"
    assert store.get("Node", "", "tpu-2") is not None
    assert store.get("Pod", "default", "idle") is not None


def test_scale_down_respects_min_size():
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, clock=clock, batch_wait=0)
    store.create("NodeGroup", _group(min_size=2, max_size=8, slice_size=0))
    for i in range(2):
        _member_node(store, "tpu", i)  # both empty (util 0) but size == min
    sched.schedule_cycle()
    ca = ClusterAutoscaler(store, sched)
    assert ca.sync_once() is False
    assert len(store.list("Node")[0]) == 2


def test_scale_down_never_breaks_a_placed_gang():
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, clock=clock, batch_wait=0)
    store.create("NodeGroup", _group(min_size=0, max_size=8, slice_size=0))
    for i in range(2):
        _member_node(store, "tpu", i)
    # a bound gang member with a tiny request (utilization far below the
    # threshold) — still never a scale-down victim
    pg = v1.PodGroup(metadata=v1.ObjectMeta(name="g", namespace="default"),
                     min_member=1)
    store.create("PodGroup", pg)
    store.create("Pod", make_pod().name("g-0").uid("g-0")
                 .namespace("default").label(POD_GROUP_LABEL, "g")
                 .req({"cpu": "100m"}).node("tpu-0").obj())
    sched.schedule_cycle()
    ca = ClusterAutoscaler(store, sched, max_scale_downs_per_sync=4)
    ca.sync_once()
    assert store.get("Node", "", "tpu-0") is not None
    assert store.get("Pod", "default", "g-0") is not None


# --- chaos: exactly-once ------------------------------------------------------


def test_scale_up_applies_exactly_once_under_watch_drop_and_429_storm():
    """Chaos coverage: under watch drops and a 429/conflict write storm
    the scale decision applies exactly once — the group converges to
    EXACTLY the simulated node set (no duplicates, no overshoot) and the
    gang binds all-or-nothing."""
    from kubernetes_tpu.chaos.faults import FaultSchedule
    from kubernetes_tpu.chaos.retry import RetryingStore

    fault = FaultSchedule(
        13, watch_drop_rate=0.15, write_429_rate=0.35, conflict_rate=0.1,
        retry_after=0.0, max_faults_per_key=3,
    )
    raw = ObjectStore(fault_injector=fault)
    store = RetryingStore(raw, sleep=lambda _s: None)
    node_adds = {}

    def on_ev(ev):
        if ev.kind == "Node" and ev.type == "ADDED":
            node_adds[ev.obj.metadata.name] = \
                node_adds.get(ev.obj.metadata.name, 0) + 1

    raw.watch(on_ev)
    sched = TPUScheduler(store, batch_size=8, pod_initial_backoff=0.01,
                         pod_max_backoff=0.05, batch_wait=0)
    store.create("Node", make_node().name("n0")
                 .capacity({"cpu": "4", "pods": "10"}).obj())
    store.create("NodeGroup", _group(max_size=8, slice_size=4))
    _gang(store, "g", members=4, cpu="3", created=time.monotonic())
    ca = ClusterAutoscaler(store, sched)
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        sched.run_until_idle(max_cycles=50, backoff_wait=1.0)
        ca.sync_once()
        done = sum(1 for i in range(4)
                   if raw.get("Pod", "default", f"g-{i}").spec.node_name)
        if done == 4:
            break
        time.sleep(0.02)
    assert all(raw.get("Pod", "default", f"g-{i}").spec.node_name
               for i in range(4))
    # exactly once: the minimal viable slice (4 hosts), each created once
    group_nodes = [n for n in raw.list("Node")[0]
                   if n.metadata.labels.get(NODE_GROUP_LABEL) == "tpu"]
    assert sorted(n.metadata.name for n in group_nodes) == \
        ["tpu-0", "tpu-1", "tpu-2", "tpu-3"]
    assert all(c == 1 for name, c in node_adds.items()
               if name.startswith("tpu-")), node_adds
    assert sum(fault.injected_counts().values()) > 0  # the storm fired


# --- CLI ----------------------------------------------------------------------


def test_cli_get_nodegroups_and_status():
    clock = FakeClock()
    store = ObjectStore()
    sched = TPUScheduler(store, batch_size=8, clock=clock, batch_wait=0)
    store.create("NodeGroup", _group(min_size=1, max_size=8, slice_size=4))
    for i in range(2):
        _member_node(store, "tpu", i)
    store.create("Pod", make_pod().name("loose").uid("loose")
                 .namespace("default").req({"cpu": "1"}).obj())
    k = Kubectl(store)
    out = k.get("nodegroups")
    assert out.splitlines()[0].split() == \
        ["NAME", "SIZE", "MIN", "MAX", "TEMPLATE"]
    row = out.splitlines()[1].split()
    assert row[:4] == ["tpu", "2", "1", "8"]
    assert "slice=4" in row[4]
    status = k.autoscaler_status()
    assert "HEADROOM" in status and "tpu" in status
    assert "pending: 1 unbound pods" in status


def test_cli_main_autoscaler_status(capsys):
    from kubernetes_tpu.cli import main

    rc = main(["autoscaler", "status"])
    assert rc == 0
    assert "GROUP" in capsys.readouterr().out
