"""HTTP apiserver surface + manifest serialization.

Reference: staging/src/k8s.io/apiserver handlers (REST verbs, watch
streaming), pkg/registry/core/pod binding subresource, RBAC-shaped
authorization decisions.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.api import objects as v1
from kubernetes_tpu.api.scheme import default_scheme
from kubernetes_tpu.api.serialize import to_manifest
from kubernetes_tpu.apiserver import APIServer, HTTPApiClient, resource_of
from kubernetes_tpu.sim.store import ObjectStore
from kubernetes_tpu.testutil import make_node, make_pod

SCHEME = default_scheme()


@pytest.fixture()
def server():
    store = ObjectStore()
    srv = APIServer(store, SCHEME).start()
    yield srv
    srv.stop()


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return json.loads(r.read())


def test_roundtrip_all_served_kinds():
    """decode(to_manifest(obj)) == obj for a battery of objects covering
    every kind the scheme serves (status subresources of workload kinds are
    spec-split, matching the reference's write semantics)."""
    pod = (make_pod().name("p").uid("u1").namespace("ns")
           .label("app", "a").req({"cpu": "2", "memory": "1Gi"})
           .priority(7)
           .pod_affinity("zone", {"app": "a"}, anti=True)
           .toleration("k", value="v", effect="NoSchedule")
           .obj())
    pod.spec.topology_spread_constraints = [v1.TopologySpreadConstraint(
        max_skew=2, topology_key="zone", when_unsatisfiable="ScheduleAnyway",
        label_selector=v1.LabelSelector(match_labels={"app": "a"}),
    )]
    pod.spec.volumes = [v1.Volume(name="data", pvc_name="claim1")]
    node = (make_node().name("n").label("zone", "z1")
            .capacity({"cpu": "8", "memory": "32Gi", "pods": "110"})
            .taint("dedicated", "db", "NoSchedule").obj())
    svc = v1.Service(metadata=v1.ObjectMeta(name="s", namespace="ns"),
                     selector={"app": "a"})
    ns_obj = v1.Namespace(metadata=v1.ObjectMeta(name="team"))
    quota = v1.ResourceQuota(metadata=v1.ObjectMeta(name="q", namespace="ns"),
                             hard={"pods": "5"})
    pc = v1.PriorityClass(metadata=v1.ObjectMeta(name="high"), value=100,
                          global_default=True)
    pdb = v1.PodDisruptionBudget(
        metadata=v1.ObjectMeta(name="pdb", namespace="ns"),
        selector=v1.LabelSelector(match_labels={"app": "a"}),
        min_available=2, disruptions_allowed=1,
    )
    sa = v1.ServiceAccount(metadata=v1.ObjectMeta(name="default",
                                                  namespace="ns"))
    for obj in (pod, node, svc, ns_obj, quota, pc, pdb, sa):
        back = SCHEME.decode(to_manifest(obj, SCHEME))
        back.metadata.resource_version = obj.metadata.resource_version
        assert back == obj, f"{obj.kind} did not round-trip"


def test_resource_names():
    assert resource_of("Pod") == "pods"
    assert resource_of("Endpoints") == "endpoints"
    assert resource_of("StorageClass") == "storageclasses"
    assert resource_of("PriorityClass") == "priorityclasses"
    assert resource_of("EndpointSlice") == "endpointslices"


def test_crud_and_binding_over_http(server):
    base = server.url
    # POST a node and a pod
    node_m = to_manifest(make_node().name("n0").obj(), SCHEME)
    req = urllib.request.Request(f"{base}/api/v1/nodes", method="POST",
                                 data=json.dumps(node_m).encode())
    assert json.loads(urllib.request.urlopen(req).read())["kind"] == "Node"
    pod = make_pod().name("web").uid("w1").namespace("default") \
        .req({"cpu": "1"}).obj()
    req = urllib.request.Request(
        f"{base}/api/v1/namespaces/default/pods", method="POST",
        data=json.dumps(to_manifest(pod, SCHEME)).encode())
    urllib.request.urlopen(req)
    # duplicate POST → 409
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(urllib.request.Request(
            f"{base}/api/v1/namespaces/default/pods", method="POST",
            data=json.dumps(to_manifest(pod, SCHEME)).encode()))
    assert e.value.code == 409

    # GET it back; list with selectors
    got = _get(f"{base}/api/v1/namespaces/default/pods/web")
    assert got["metadata"]["name"] == "web"
    lst = _get(f"{base}/api/v1/namespaces/default/pods")
    assert len(lst["items"]) == 1 and lst["kind"] == "PodList"
    assert int(lst["metadata"]["resourceVersion"]) >= 2

    # binding subresource sets nodeName (fieldSelector finds it)
    req = urllib.request.Request(
        f"{base}/api/v1/namespaces/default/pods/web/binding", method="POST",
        data=json.dumps({"target": {"name": "n0"}}).encode())
    assert urllib.request.urlopen(req).status == 201
    lst = _get(f"{base}/api/v1/namespaces/default/pods"
               f"?fieldSelector=spec.nodeName%3Dn0")
    assert [i["metadata"]["name"] for i in lst["items"]] == ["web"]

    # PATCH (merge) adds a label; DELETE removes
    req = urllib.request.Request(
        f"{base}/api/v1/namespaces/default/pods/web", method="PATCH",
        data=json.dumps({"metadata": {"labels": {"tier": "web"}}}).encode())
    patched = json.loads(urllib.request.urlopen(req).read())
    assert patched["metadata"]["labels"]["tier"] == "web"
    assert server.store.get("Pod", "default", "web").metadata.labels["tier"] == "web"
    req = urllib.request.Request(
        f"{base}/api/v1/namespaces/default/pods/web", method="DELETE")
    urllib.request.urlopen(req)
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(f"{base}/api/v1/namespaces/default/pods/web")
    assert e.value.code == 404

    # health + discovery
    with urllib.request.urlopen(f"{base}/healthz") as r:
        assert r.read() == b"ok"
    assert "v1" in _get(f"{base}/api")["versions"]


def test_put_patch_stale_resource_version_conflict(server):
    """A PUT/PATCH carrying a stale metadata.resourceVersion gets 409
    Conflict (read-modify-write safety, etcd3 GuaranteedUpdate semantics);
    omitting resourceVersion or sending the current one succeeds."""
    base = server.url
    pod = make_pod().name("rv").uid("rv1").namespace("default") \
        .req({"cpu": "1"}).obj()
    urllib.request.urlopen(urllib.request.Request(
        f"{base}/api/v1/namespaces/default/pods", method="POST",
        data=json.dumps(to_manifest(pod, SCHEME)).encode()))
    cur = _get(f"{base}/api/v1/namespaces/default/pods/rv")
    rv = cur["metadata"]["resourceVersion"]

    # PUT with the CURRENT rv succeeds (and bumps it)
    cur["metadata"]["labels"] = {"gen": "1"}
    out = json.loads(urllib.request.urlopen(urllib.request.Request(
        f"{base}/api/v1/namespaces/default/pods/rv", method="PUT",
        data=json.dumps(cur).encode())).read())
    assert out["metadata"]["labels"]["gen"] == "1"

    # PUT with the now-STALE rv → 409 Conflict
    cur["metadata"]["resourceVersion"] = rv
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(urllib.request.Request(
            f"{base}/api/v1/namespaces/default/pods/rv", method="PUT",
            data=json.dumps(cur).encode()))
    assert e.value.code == 409
    assert json.loads(e.value.read())["reason"] == "Conflict"

    # PATCH with a stale rv → 409; without rv → merges fine
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(urllib.request.Request(
            f"{base}/api/v1/namespaces/default/pods/rv", method="PATCH",
            data=json.dumps({"metadata": {"resourceVersion": rv,
                                          "labels": {"gen": "2"}}}).encode()))
    assert e.value.code == 409
    patched = json.loads(urllib.request.urlopen(urllib.request.Request(
        f"{base}/api/v1/namespaces/default/pods/rv", method="PATCH",
        data=json.dumps({"metadata": {"labels": {"gen": "2"}}}).encode())).read())
    assert patched["metadata"]["labels"]["gen"] == "2"


def test_authn_and_admission_chain_over_http():
    """authn → authz → admission over real HTTP (the reference generic
    server's handler chain, apiserver/pkg/server/config.go:816): header +
    bearer authentication with 401 on unidentified requests, a mutating
    hook defaulting a label, and a validating hook denying by policy."""
    from kubernetes_tpu.apiserver import (
        APIServer,
        header_authenticator,
        token_authenticator,
    )

    def mutate(op, kind, obj, user):
        if kind == "Pod" and op == "CREATE":
            obj.metadata.labels = {**(obj.metadata.labels or {}),
                                   "injected-by": "mutating-admission",
                                   "created-by": user.name}
        return obj

    def validate(op, kind, obj, user):
        if kind == "Pod" and (obj.metadata.labels or {}).get("forbidden"):
            return f"pods with label 'forbidden' are not admitted (user {user.name})"
        return None

    store = ObjectStore()
    srv = APIServer(
        store, SCHEME,
        authenticators=[header_authenticator,
                        token_authenticator({"sekrit": "token-user"})],
        mutating_admission=[mutate],
        validating_admission=[validate],
    ).start()
    try:
        base = srv.url
        pod_m = to_manifest(
            make_pod().name("adm").uid("adm1").namespace("default")
            .req({"cpu": "1"}).obj(), SCHEME)

        # no identity → 401 (authenticators configured, none matched)
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/api/v1/namespaces/default/pods", method="POST",
                data=json.dumps(pod_m).encode()))
        assert e.value.code == 401

        # header identity → admitted; the mutating hook stamped it
        req = urllib.request.Request(
            f"{base}/api/v1/namespaces/default/pods", method="POST",
            data=json.dumps(pod_m).encode(),
            headers={"X-Remote-User": "alice"})
        out = json.loads(urllib.request.urlopen(req).read())
        assert out["metadata"]["labels"]["injected-by"] == "mutating-admission"
        assert out["metadata"]["labels"]["created-by"] == "alice"
        assert store.get("Pod", "default", "adm").metadata.labels[
            "created-by"] == "alice"

        # bearer identity works too, and the validating hook denies by policy
        bad = to_manifest(
            make_pod().name("bad").uid("bad1").namespace("default")
            .label("forbidden", "1").req({"cpu": "1"}).obj(), SCHEME)
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/api/v1/namespaces/default/pods", method="POST",
                data=json.dumps(bad).encode(),
                headers={"Authorization": "Bearer sekrit"}))
        assert e.value.code == 403
        body = json.loads(e.value.read())
        assert body["reason"] == "AdmissionDenied"
        assert "token-user" in body["message"]
        assert store.get("Pod", "default", "bad") is None

        # admission also gates UPDATE (PUT path)
        cur = json.loads(urllib.request.urlopen(urllib.request.Request(
            f"{base}/api/v1/namespaces/default/pods/adm",
            headers={"X-Remote-User": "alice"})).read())
        cur["metadata"]["labels"]["forbidden"] = "1"
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/api/v1/namespaces/default/pods/adm", method="PUT",
                data=json.dumps(cur).encode(),
                headers={"X-Remote-User": "alice"}))
        assert e.value.code == 403
    finally:
        srv.stop()


def test_watch_streams_events(server):
    base = server.url
    events = []
    done = threading.Event()

    def reader():
        req = urllib.request.Request(
            f"{base}/api/v1/namespaces/default/pods"
            f"?watch=true&resourceVersion=0&timeoutSeconds=5")
        with urllib.request.urlopen(req, timeout=10) as resp:
            for line in resp:
                line = line.strip()
                if not line:
                    continue
                events.append(json.loads(line))
                if len(events) >= 3:
                    done.set()
                    return

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    time.sleep(0.2)  # let the watch register
    pod = make_pod().name("w0").uid("w0").namespace("default") \
        .req({"cpu": "1"}).obj()
    server.store.create("Pod", pod)
    server.store.update("Pod", pod)
    server.store.delete("Pod", "default", "w0")
    assert done.wait(timeout=8), f"only {len(events)} events arrived"
    assert [e["type"] for e in events] == ["ADDED", "MODIFIED", "DELETED"]
    assert events[0]["object"]["metadata"]["name"] == "w0"


def test_watch_bookmarks(server):
    """allowWatchBookmarks=true yields periodic BOOKMARK events carrying
    only the current resourceVersion (the watch cache's bookmark machinery,
    cacher.go:56,161-185); the HTTP client consumes them via on_bookmark
    instead of surfacing object events."""
    from kubernetes_tpu.apiserver import HTTPApiClient

    base = server.url
    server.store.create("Node", make_node().name("bk0").obj())
    # raw stream: a bookmark arrives within ~2s of idle watching
    req = urllib.request.Request(
        f"{base}/api/v1/nodes?watch=true&resourceVersion=0"
        f"&timeoutSeconds=4&allowWatchBookmarks=true")
    types = []
    with urllib.request.urlopen(req, timeout=8) as resp:
        for raw in resp:
            line = raw.strip()
            if not line:
                continue
            ev = json.loads(line)
            types.append(ev["type"])
            if ev["type"] == "BOOKMARK":
                assert int(ev["object"]["metadata"]["resourceVersion"]) >= 1
                assert "spec" not in ev["object"]  # rv only, no object body
                break
    assert "BOOKMARK" in types and "ADDED" in types

    # client side: bookmarks advance the restart point, never reach handler
    client = HTTPApiClient(base)
    got, marks = [], []
    unwatch = client.watch_kind("Node", got.append, since_rv=0,
                                timeout_seconds=3,
                                on_bookmark=marks.append)
    deadline = time.monotonic() + 6
    while not marks and time.monotonic() < deadline:
        time.sleep(0.1)
    unwatch()
    assert marks and all(rv >= 1 for rv in marks)
    assert all(ev.type != "BOOKMARK" for ev in got)


def test_reflector_over_http(server):
    """The client-go shape: Reflector(list+watch) drives an informer cache
    over the wire, including events that happen after the initial list."""
    from kubernetes_tpu.client.informer import Reflector

    store = server.store
    store.create("Pod", make_pod().name("a").uid("a").namespace("default")
                 .req({"cpu": "1"}).obj())
    client = HTTPApiClient(server.url, SCHEME)
    refl = Reflector(client.for_kind("Pod"), "Pod")
    refl.run()
    assert refl.has_synced()
    assert ("default", "a") in refl.items
    store.create("Pod", make_pod().name("b").uid("b").namespace("default")
                 .req({"cpu": "1"}).obj())
    deadline = time.time() + 5
    while ("default", "b") not in refl.items and time.time() < deadline:
        time.sleep(0.05)
    assert ("default", "b") in refl.items
    refl.stop()


def test_rbac_authorizer_denies(server):
    """The authorization decision point: verb+resource+namespace+user."""
    def authorizer(user, verb, resource, ns):
        return not (verb == "delete" and user == "system:anonymous")
    server.authorizer = authorizer
    base = server.url
    pod = make_pod().name("locked").uid("l1").namespace("default") \
        .req({"cpu": "1"}).obj()
    server.store.create("Pod", pod)
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(urllib.request.Request(
            f"{base}/api/v1/namespaces/default/pods/locked",
            method="DELETE"))
    assert e.value.code == 403
    req = urllib.request.Request(
        f"{base}/api/v1/namespaces/default/pods/locked", method="DELETE")
    req.add_header("X-Remote-User", "admin")
    assert urllib.request.urlopen(req).status == 200


def test_quota_admission_over_http(server):
    base = server.url
    q = v1.ResourceQuota(metadata=v1.ObjectMeta(name="q", namespace="default"),
                         hard={"pods": "1"})
    server.store.create("ResourceQuota", q)
    p1 = to_manifest(make_pod().name("p1").uid("p1").namespace("default")
                     .req({"cpu": "1"}).obj(), SCHEME)
    p2 = to_manifest(make_pod().name("p2").uid("p2").namespace("default")
                     .req({"cpu": "1"}).obj(), SCHEME)
    urllib.request.urlopen(urllib.request.Request(
        f"{base}/api/v1/namespaces/default/pods", method="POST",
        data=json.dumps(p1).encode()))
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(urllib.request.Request(
            f"{base}/api/v1/namespaces/default/pods", method="POST",
            data=json.dumps(p2).encode()))
    assert e.value.code == 403  # quota exceeded → Forbidden


def test_kubectl_over_http(server):
    """kubectl --server: the CLI's verbs run over the HTTP facade."""
    from kubernetes_tpu.apiserver.client import HTTPStoreFacade
    from kubernetes_tpu.cli import Kubectl

    k = Kubectl(HTTPStoreFacade(HTTPApiClient(server.url)))
    out = k.apply(
        "apiVersion: v1\n"
        "kind: Pod\n"
        "metadata:\n"
        "  name: web\n"
        "  namespace: default\n"
        "spec:\n"
        "  containers:\n"
        "  - name: c\n"
        "    resources:\n"
        "      requests:\n"
        "        cpu: '1'\n"
    )
    assert out == ["pod/web created"]
    assert server.store.get("Pod", "default", "web") is not None
    assert "web" in k.get("Pod", "default")
    assert k.delete("Pod", "default", "web") == "pod/web deleted"
    assert server.store.get("Pod", "default", "web") is None
