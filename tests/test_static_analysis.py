"""Analyzer battery: per-check fixtures (positive + negative), the repo
ratchet gate, and the runtime lockcheck monitor.

The ratchet gate here IS the tier-1 enforcement of tools/analyze.py
--check: a new violation anywhere in scanned code fails this file.
"""

import os
import sys
import textwrap
import threading

import pytest

from kubernetes_tpu.analysis import baseline as baseline_mod
from kubernetes_tpu.analysis import lockcheck
from kubernetes_tpu.analysis.core import (
    DEFAULT_SCAN_PATHS,
    ModuleInfo,
    load_project,
    project_from_sources,
    run_checks,
)
from kubernetes_tpu.analysis.registry import CHECK_REGISTRY, default_checks

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def analyze(sources, checks=()):
    """Run checks over {path: source}; returns findings."""
    project = project_from_sources(
        {p: textwrap.dedent(s) for p, s in sources.items()})
    return run_checks(project, default_checks(checks))


def rules(findings):
    return sorted({(f.check, f.rule) for f in findings})


# --- registry ----------------------------------------------------------------


def test_all_ten_checks_registered():
    default_checks()  # imports the check modules
    assert {"trace-safety", "recompile-hazard", "lock-discipline",
            "exception-hygiene", "metrics-registration",
            # the dataflow engine's five (PR 7)
            "host-sync", "vmap-purity", "donation-aliasing",
            "shape-drift", "blocking-in-cycle",
            # the thread-ownership engine's four (PR 17)
            "thread-ownership", "handoff-discipline",
            "thread-local-context", "daemon-lifecycle"} <= set(CHECK_REGISTRY)


def test_unknown_check_rejected():
    with pytest.raises(KeyError):
        default_checks(["no-such-check"])


# --- trace-safety ------------------------------------------------------------


TRACE_POS = {
    "pkg/mod.py": """
    import time
    import numpy as np
    import jax

    @jax.jit
    def traced(x):
        t = time.time()
        y = np.asarray(x)
        z = x.sum().item()
        print("debug", z)
        return y * t + float(x)
    """
}


def test_trace_safety_flags_host_syncs():
    got = rules(analyze(TRACE_POS, ["trace-safety"]))
    assert ("trace-safety", "host-sync") in got
    assert ("trace-safety", "numpy-op") in got
    assert ("trace-safety", "impure") in got
    assert ("trace-safety", "side-effect") in got
    assert ("trace-safety", "concretize") in got


def test_trace_safety_wrap_form_and_transitive_calls():
    findings = analyze({
        "pkg/mod.py": """
        import jax

        def helper(x):
            return x.sum().item()

        def outer():
            def inner(x):
                return helper(x)
            return jax.jit(inner)
        """
    }, ["trace-safety"])
    assert any(f.rule == "host-sync" and "helper" in f.symbol
               for f in findings)


def test_trace_safety_clean_function_passes():
    findings = analyze({
        "pkg/mod.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def traced(x):
            k = int(x.shape[0])  # static shape read: fine
            return jnp.sum(x) * k
        """
    }, ["trace-safety"])
    assert findings == []


def test_trace_safety_ignores_untraced_functions():
    findings = analyze({
        "pkg/mod.py": """
        import time

        def host_only(x):
            return time.time() + x.item()
        """
    }, ["trace-safety"])
    assert findings == []


# --- recompile-hazard --------------------------------------------------------


def test_recompile_jit_in_loop_and_immediate():
    findings = analyze({
        "pkg/mod.py": """
        import jax

        def f(x):
            return x

        def run(xs):
            for x in xs:
                g = jax.jit(f)
                g(x)
            return jax.jit(f)(xs)
        """
    }, ["recompile-hazard"])
    got = rules(findings)
    assert ("recompile-hazard", "jit-in-loop") in got
    assert ("recompile-hazard", "jit-immediate") in got


def test_recompile_lambda_inside_function():
    findings = analyze({
        "pkg/mod.py": """
        import jax

        def per_call(x):
            g = jax.jit(lambda y: y + 1)
            return g(x)
        """
    }, ["recompile-hazard"])
    assert ("recompile-hazard", "jit-lambda") in rules(findings)


def test_recompile_uncached_builder_vs_cached():
    src = """
    import jax

    def build(fn):
        return jax.jit(fn)

    class Sched:
        def __init__(self, fn):
            self._progs = {}
            self._progs["main"] = self.rebuild(fn)  # cached: OK

        def rebuild(self, fn):
            return jax.jit(fn)

        def cycle(self, fn, x):
            prog = self.rebuild(fn)  # NOT cached: flagged
            return prog(x)

    TABLE = build(len)  # module-level cache: OK
    """
    findings = analyze({"pkg/mod.py": src}, ["recompile-hazard"])
    flagged_lines = [f.snippet for f in findings
                     if f.rule == "uncached-builder"]
    assert flagged_lines == ["prog = self.rebuild(fn)  # NOT cached: flagged"]


def test_recompile_unhashable_static_arg():
    findings = analyze({
        "pkg/mod.py": """
        import jax

        def f(x, cfg):
            return x

        g = jax.jit(f, static_argnums=(1,))
        out = g(1, [1, 2, 3])
        """
    }, ["recompile-hazard"])
    assert ("recompile-hazard", "unhashable-static") in rules(findings)


def test_recompile_init_cached_table_passes():
    findings = analyze({
        "pkg/mod.py": """
        import jax

        JITS = {name: jax.jit(fn) for name, fn in {"len": len}.items()}
        """
    }, ["recompile-hazard"])
    assert findings == []


# --- lock-discipline ---------------------------------------------------------


LOCK_POS = {
    "pkg/mod.py": """
    import threading

    class Store:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}

        def put(self, k, v):
            with self._lock:
                self._items[k] = v

        def sneak(self, k, v):
            self._items[k] = v  # mutated WITHOUT the lock: flagged
    """
}


def test_lock_discipline_mixed_use_flagged():
    findings = analyze(LOCK_POS, ["lock-discipline"])
    assert [f.rule for f in findings] == ["mixed-lock-use"]
    assert "sneak" in findings[0].message


def test_lock_discipline_propagated_helper_ok():
    findings = analyze({
        "pkg/mod.py": """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}

            def put(self, k, v):
                with self._lock:
                    self._emit(k, v)

            def delete(self, k):
                with self._lock:
                    self._emit(k, None)

            def _emit(self, k, v):
                self._items[k] = v  # only ever called under the lock
        """
    }, ["lock-discipline"])
    assert findings == []


def test_lock_discipline_mixed_helper_call_flagged():
    findings = analyze({
        "pkg/mod.py": """
        import threading

        class Refl:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = {}

            def apply(self, k, v):
                self.items[k] = v

            def locked_path(self, k, v):
                with self._lock:
                    self.apply(k, v)

            def unlocked_path(self, k, v):
                self.apply(k, v)  # same helper, no lock: flagged
        """
    }, ["lock-discipline"])
    assert [f.rule for f in findings] == ["mixed-helper-call"]
    assert "unlocked_path" in findings[0].message


def test_lock_discipline_contextmanager_wrapper_counts_as_locked():
    """`with self._locked_emit():` (a generator method yielding inside
    `with self._lock`) is lock-held context — the ObjectStore pattern."""
    findings = analyze({
        "pkg/mod.py": """
        import threading
        from contextlib import contextmanager

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}

            @contextmanager
            def _locked(self):
                with self._lock:
                    yield

            def put(self, k, v):
                with self._locked():
                    self._items[k] = v

            def put2(self, k, v):
                with self._locked():
                    self._items[k] = v
        """
    }, ["lock-discipline"])
    assert findings == []


def test_lock_discipline_init_exempt_and_lockless_class_ignored():
    findings = analyze({
        "pkg/mod.py": """
        import threading

        class WithLock:
            def __init__(self):
                self._lock = threading.RLock()
                self.x = 0  # __init__ mutation: exempt

            def bump(self):
                with self._lock:
                    self.x += 1

        class NoLock:
            def __init__(self):
                self.y = 0

            def bump(self):
                self.y += 1
        """
    }, ["lock-discipline"])
    assert findings == []


# --- exception-hygiene -------------------------------------------------------


def test_exception_hygiene_silent_flagged_loud_ok():
    findings = analyze({
        "pkg/mod.py": """
        from kubernetes_tpu.component_base import logging as klog

        def silent():
            try:
                risky()
            except Exception:
                return None  # flagged

        def reraises():
            try:
                risky()
            except Exception:
                raise

        def logs():
            try:
                risky()
            except Exception as e:
                klog.error_s(e, "boom")

        def narrow():
            try:
                risky()
            except (KeyError, ValueError):
                return None  # narrowed: not flagged
        """
    }, ["exception-hygiene"])
    assert len(findings) == 1
    assert findings[0].symbol == "silent"


def test_exception_hygiene_bare_except_flagged():
    findings = analyze({
        "pkg/mod.py": """
        def f():
            try:
                risky()
            except:
                pass
        """
    }, ["exception-hygiene"])
    assert [f.rule for f in findings] == ["silent-swallow"]


# --- metrics-registration ----------------------------------------------------


METRICS_SRC = """
from .registry import Counter, Gauge, default_registry

pods_scheduled = default_registry.register(
    Counter("pods_scheduled_total"))
queue_depth = default_registry.register(
    Gauge("queue_depth"))
"""


def test_metrics_unknown_attr_and_name():
    findings = analyze({
        "kubernetes_tpu/metrics/scheduler_metrics.py": METRICS_SRC,
        "kubernetes_tpu/worker.py": """
        from .metrics import scheduler_metrics as m

        def done(registry):
            m.pods_scheduled.inc()          # fine
            m.queue_depth.set(3.0)          # fine
            m.pod_scheduled.inc()           # typo: flagged
            registry.get("no_such_metric")  # flagged
            registry.get("queue_depth")     # fine
        """,
    }, ["metrics-registration"])
    got = rules(findings)
    assert ("metrics-registration", "unknown-attr") in got
    assert ("metrics-registration", "unknown-name") in got
    assert not any(f.rule == "registered-unused" for f in findings)


def test_metrics_duplicate_and_unused():
    findings = analyze({
        "kubernetes_tpu/metrics/scheduler_metrics.py": METRICS_SRC,
        "kubernetes_tpu/other.py": """
        from .metrics.registry import Counter

        shadow = Counter("pods_scheduled_total")  # duplicate: flagged
        """,
    }, ["metrics-registration"])
    got = rules(findings)
    assert ("metrics-registration", "duplicate-name") in got
    # neither metric is emitted by attr/name anywhere scanned
    unused = {f.message.split("`")[1] for f in findings
              if f.rule == "registered-unused"}
    assert "queue_depth" in unused


# --- the repo ratchet gate (tier-1 enforcement) ------------------------------


@pytest.fixture(scope="module")
def repo_findings():
    project = load_project(REPO_ROOT, DEFAULT_SCAN_PATHS)
    return run_checks(project, default_checks())


def test_repo_gate_zero_findings(repo_findings):
    """THE ratchet, burned to zero (PR 7): the committed baseline is an
    EMPTY dict, so ANY finding from any of the ten checks fails tier-1
    outright — no grandfathered hiding place.  Fix the site or add a
    justified `ktpu-analysis: ignore[check] -- why` suppression (which
    the engine itself lints)."""
    base = baseline_mod.load(
        os.path.join(REPO_ROOT, baseline_mod.BASELINE_FILENAME))
    assert base == {}, (
        "analysis_baseline.json must stay EMPTY — the grandfathered "
        "baseline was burned to zero; never re-grow it: %r" % (base,))
    assert repo_findings == [], (
        "static-analysis violation(s) — fix them or add a justified "
        "suppression (never re-grow the baseline):\n"
        + "\n".join(f"  {f.location()} [{f.check}/{f.rule}] {f.message}"
                    for f in repo_findings))
    new, stale = baseline_mod.diff(repo_findings, base)
    assert not new and not stale


def test_repo_gate_catches_fresh_violation(repo_findings):
    """Introducing a violation in a scratch module must fail the diff."""
    scratch = ModuleInfo("kubernetes_tpu/scratch_violation.py", textwrap.dedent("""
        def f():
            try:
                pass
            except Exception:
                pass
    """))
    project = load_project(REPO_ROOT, DEFAULT_SCAN_PATHS)
    project.modules.append(scratch)
    findings = run_checks(project, default_checks(["exception-hygiene"]))
    base = baseline_mod.load(
        os.path.join(REPO_ROOT, baseline_mod.BASELINE_FILENAME))
    new, _ = baseline_mod.diff(findings, base)
    assert any(f.path == "kubernetes_tpu/scratch_violation.py" for f in new)


def test_baseline_counts_are_count_matched():
    """A key with N baselined sites fails on the N+1th, not before."""
    src_one = {
        "pkg/mod.py": """
        def f():
            try:
                pass
            except Exception:
                pass
        """
    }
    findings = analyze(src_one, ["exception-hygiene"])
    base = baseline_mod.baseline_counts(findings)
    # same snippet appearing TWICE in the same scope exceeds the count
    doubled = analyze({
        "pkg/mod.py": """
        def f():
            try:
                pass
            except Exception:
                pass
            try:
                pass
            except Exception:
                pass
        """
    }, ["exception-hygiene"])
    new, stale = baseline_mod.diff(doubled, base)
    assert len(new) == 1 and not stale
    # and the original set stays clean against its own baseline
    new2, stale2 = baseline_mod.diff(findings, base)
    assert not new2 and not stale2


def test_hot_cycle_modules_clean_without_suppressions(repo_findings):
    """Acceptance contract: the hot-cycle modules are clean under
    host-sync and blocking-in-cycle with NO suppressions — their
    deliberate fetch sites live in the reviewable FETCH_BOUNDARIES
    config, not in inline escape hatches."""
    hot = ("kubernetes_tpu/scheduler.py",
           "kubernetes_tpu/whatif/engine.py",
           "kubernetes_tpu/state/encoding.py",
           "kubernetes_tpu/state/affinity_index.py")
    offenders = [f for f in repo_findings
                 if f.path in hot and f.check in ("host-sync",
                                                  "blocking-in-cycle")]
    assert offenders == []
    project = load_project(REPO_ROOT, DEFAULT_SCAN_PATHS)
    for path in hot:
        mod = project.by_path()[path]
        sups = [s for s in mod.suppressions
                if {"host-sync", "blocking-in-cycle"} & set(s.checks)]
        assert sups == [], (
            f"{path} suppresses a device-boundary check — hot-cycle "
            f"modules must be clean outright, or the crossing belongs "
            f"in FETCH_BOUNDARIES with a review")


def test_fetch_boundaries_resolve_to_real_functions():
    """Every sanctioned fetch site must still exist — a renamed function
    would otherwise silently widen the checks' blind spot."""
    from kubernetes_tpu.analysis import dataflow
    from kubernetes_tpu.analysis.checks.device_boundary import (
        CYCLE_ROOTS,
        FETCH_BOUNDARIES,
    )

    project = load_project(REPO_ROOT, DEFAULT_SCAN_PATHS)
    dfa = dataflow.analysis_for(project)
    for suffix, qual, why in FETCH_BOUNDARIES:
        assert why.strip(), f"boundary {suffix}::{qual} must be justified"
        if qual == "":
            assert any(p.endswith(suffix) for (p, _q) in dfa.functions), \
                f"boundary module {suffix} vanished"
        else:
            assert dfa.find_function(suffix, qual) is not None, \
                f"fetch boundary {suffix}::{qual} no longer exists"
    for suffix, qual in CYCLE_ROOTS:
        assert dfa.find_function(suffix, qual) is not None, \
            f"cycle root {suffix}::{qual} no longer exists"


# --- seeded regressions: each dataflow check fires at the right site ---------


def _patched_repo_project(path_suffix, anchor, injected):
    """Load the real repo project and insert ``injected`` directly above
    the first line starting with ``anchor`` in the module at
    ``path_suffix``; returns (project, 1-based injected lineno)."""
    project = load_project(REPO_ROOT, DEFAULT_SCAN_PATHS)
    mod = project.find(path_suffix)
    lines = mod.source.splitlines(keepends=True)
    at = next(i for i, ln in enumerate(lines) if ln.startswith(anchor))
    lines.insert(at, injected if injected.endswith("\n") else injected + "\n")
    patched = ModuleInfo(mod.path, "".join(lines))
    project.modules[project.modules.index(mod)] = patched
    return project, at + 1


def test_seeded_item_in_cycle_path_fires_blocking_in_cycle():
    """An injected ``.item()`` on a device value inside schedule_cycle —
    the exact bug class the check exists for — produces EXACTLY one
    blocking-in-cycle finding at the injected file:line (and one
    host-sync finding, the same site seen by the per-function check)."""
    project, lineno = _patched_repo_project(
        "kubernetes_tpu/scheduler.py",
        "        infos = self.queue.pop_batch(",
        "        _probe = self.encoder.to_device().requested.item()\n")
    bic = run_checks(project, default_checks(["blocking-in-cycle"]))
    assert [(f.path, f.line) for f in bic] == \
        [("kubernetes_tpu/scheduler.py", lineno)]
    hs = run_checks(project, default_checks(["host-sync"]))
    assert [(f.path, f.line) for f in hs] == \
        [("kubernetes_tpu/scheduler.py", lineno)]


def test_seeded_impure_vmapped_closure_fires_vmap_purity():
    """A vmapped closure mutating captured state — across a module
    boundary — produces exactly one vmap-purity finding at the mutation
    site."""
    findings = analyze({
        "pkg/solver.py": """
        import jax
        from .kernels import kernel

        def solve(xs):
            return jax.vmap(kernel)(xs)
        """,
        "pkg/kernels.py": """
        SEEN = {}

        def kernel(x):
            SEEN["last"] = x
            return x * 2
        """,
    }, ["vmap-purity"])
    assert [(f.path, f.line, f.rule) for f in findings] == \
        [("pkg/kernels.py", 5, "captured-mutation")]


def test_seeded_loop_grown_shape_fires_shape_drift():
    """A device array shaped by len() inside a loop — the PR-4 lazy-table
    mid-window-recompile hazard — produces exactly one finding at the
    constructor; the pow2_round_up-bucketized twin is exempt (that IS
    the mitigation)."""
    findings = analyze({
        "pkg/tables.py": """
        import jax.numpy as jnp
        from .units import pow2_round_up

        def grow(table, items):
            for it in items:
                table = jnp.zeros(len(items))
                ok = jnp.zeros(pow2_round_up(len(items), 8))
            return table
        """,
    }, ["shape-drift"])
    assert [(f.path, f.line, f.rule) for f in findings] == \
        [("pkg/tables.py", 7, "loop-grown-shape")]


def test_seeded_sync_in_state_module_fires_host_sync():
    """The same ratchet protects state/encoding.py: concretizing a device
    value outside a fetch boundary is exactly one finding at the site."""
    project, lineno = _patched_repo_project(
        "kubernetes_tpu/state/encoding.py",
        "        numeric, use_scatter = self._upload_gate()",
        "        _leak = bool(jnp.zeros(3).sum())\n")
    hs = run_checks(project, default_checks(["host-sync"]))
    assert [(f.path, f.line, f.rule) for f in hs] == \
        [("kubernetes_tpu/state/encoding.py", lineno, "concretize")]


# --- dataflow engine: interprocedural taint unit tests ------------------------


def _dfa(sources):
    from kubernetes_tpu.analysis import dataflow

    project = project_from_sources(
        {p: textwrap.dedent(s) for p, s in sources.items()})
    return dataflow.analysis_for(project)


def test_taint_crosses_module_boundaries_via_returns_and_params():
    dfa = _dfa({
        "pkg/prod.py": """
        import jax.numpy as jnp

        def make(n):
            return jnp.zeros(n)
        """,
        "pkg/cons.py": """
        from .prod import make

        def use():
            arr = make(4)
            return arr

        def sink(v):
            return v
        """,
    })
    from kubernetes_tpu.analysis.dataflow import DEVICE

    prod = dfa.functions[("pkg/prod.py", "make")]
    cons = dfa.functions[("pkg/cons.py", "use")]
    assert prod.returns == DEVICE
    assert cons.taint.get("arr") == DEVICE
    assert cons.returns == DEVICE


def test_taint_through_tuple_dict_packing_and_dataclass_fields():
    dfa = _dfa({
        "pkg/m.py": """
        import jax.numpy as jnp

        class Holder:
            def fill(self):
                self.table = {"a": jnp.ones(3)}
                self.pair = (jnp.ones(2), 1)

            def read(self):
                t = self.table
                row = t["a"]
                return row
        """,
    })
    from kubernetes_tpu.analysis.dataflow import DEVICE, LOOSE

    read = dfa.functions[("pkg/m.py", "Holder.read")]
    # the dict/tuple is a LOOSE container; pulling a member out of it
    # stays LOOSE (branching on it is host work, not a device sync)
    assert dfa.class_fields[("pkg/m.py", "Holder")]["table"] == LOOSE
    assert read.taint.get("t") == LOOSE
    # but a DEVICE value stays DEVICE through a plain local chain
    dfa2 = _dfa({
        "pkg/n.py": """
        import jax.numpy as jnp

        def f():
            a = jnp.ones(3)
            b = a
            c = b[0]
            return c
        """,
    })
    f = dfa2.functions[("pkg/n.py", "f")]
    assert f.taint.get("c") == DEVICE


def test_relative_imports_in_package_init_resolve():
    """`from .impl import make` inside pkg/__init__.py must resolve to
    pkg.impl (the package's own level, not its parent) — getting this
    wrong silently drops every re-export edge and fakes a clean report."""
    dfa = _dfa({
        "pkg/__init__.py": """
        from .impl import make

        def boot(n):
            return make(n)
        """,
        "pkg/impl.py": """
        import jax.numpy as jnp

        def make(n):
            return jnp.zeros(n)
        """,
    })
    from kubernetes_tpu.analysis.dataflow import DEVICE

    boot = dfa.functions[("pkg/__init__.py", "boot")]
    assert ("pkg/impl.py", "make") in boot.callees
    assert boot.returns == DEVICE


def test_exception_delegation_requires_passing_the_exception():
    """Delegation exempts a handler ONLY when the caught exception is
    handed to a (transitively) surfacing function — a bare helper call
    whose helper bumps a success metric is still a silent swallow."""
    findings = analyze({
        "pkg/deleg.py": """
        def _report_failure(self, err):
            self.m.failures.inc()

        class W:
            def _surface(self, err):
                self.metric.inc()

            def good(self):
                try:
                    risky()
                except Exception as e:
                    self._surface(e)

            def bad(self):
                try:
                    risky()
                except Exception:
                    self._tick()  # success-path metric: NOT surfacing

            def _tick(self):
                self.counter.inc()
        """,
    }, ["exception-hygiene"])
    assert [f.symbol for f in findings] == ["W.bad"]


def test_taint_fixpoint_terminates_on_call_graph_cycles():
    """Mutual recursion must converge (bounded fixpoint), and the taint
    still flows around the cycle."""
    dfa = _dfa({
        "pkg/cyc.py": """
        import jax.numpy as jnp

        def a(x, depth):
            if depth == 0:
                return jnp.asarray(x)
            return b(x, depth - 1)

        def b(x, depth):
            return a(x, depth)
        """,
    })
    from kubernetes_tpu.analysis.dataflow import DEVICE

    assert dfa.functions[("pkg/cyc.py", "a")].returns == DEVICE
    assert dfa.functions[("pkg/cyc.py", "b")].returns == DEVICE


def test_is_none_checks_and_loose_containers_do_not_sync():
    """The two-level lattice's precision contract: identity checks and
    host containers OF device values never count as syncs."""
    findings = analyze({
        "pkg/ok.py": """
        import jax.numpy as jnp

        def f(xs):
            arr = jnp.ones(3)
            box = [arr, None]
            if arr is not None:      # identity: host work
                pass
            if box:                  # LOOSE container: host work
                pass
            for item in box:         # iterating the host list: fine
                pass
            return box
        """,
    }, ["host-sync"])
    assert findings == []
    bad = analyze({
        "pkg/bad.py": """
        import jax.numpy as jnp

        def f():
            arr = jnp.ones(3)
            if arr:                  # device branch: sync
                pass
            for v in arr:            # device iteration: sync per element
                pass
            return bool(arr)         # concretize: sync
        """,
    }, ["host-sync"])
    assert sorted(f.rule for f in bad) == \
        ["branch-on-device", "concretize", "iterate-device"]


def test_block_until_ready_is_an_explicit_fetch_site():
    findings = analyze({
        "pkg/fetch.py": """
        import jax
        import numpy as np
        import jax.numpy as jnp

        def fetch():
            out = jnp.ones(3)
            jax.block_until_ready(out)
            host = np.asarray(out)  # explicitly synchronized: fine
            return host
        """,
    }, ["host-sync"])
    assert findings == []


# --- vmap-purity edge cases: partial wraps, aliases, decorators ---------------


def test_purity_functools_partial_wrapped_jit():
    """Both partial spellings reach the wrapped function:
    partial(jax.jit, ...)(f) and jax.jit(partial(f, ...))."""
    for src in (
        """
        import functools
        import jax

        def kernel(x, flag):
            print("trace", flag)
            return x

        PROG = functools.partial(jax.jit, static_argnums=1)(kernel)
        """,
        """
        import functools
        import jax

        def kernel(x, flag):
            print("trace", flag)
            return x

        PROG = jax.jit(functools.partial(kernel, flag=True))
        """,
    ):
        findings = analyze({"pkg/p.py": src}, ["vmap-purity"])
        assert any(f.rule == "io" and f.symbol == "kernel"
                   for f in findings), src


def test_purity_decorated_and_aliased_jit_names():
    findings = analyze({
        "pkg/d.py": """
        from functools import partial
        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def decorated(x):
            print("hi")
            return x

        def plain(x):
            global COUNT
            return x

        def wire():
            alias = plain
            return jax.vmap(alias)
        """,
    }, ["vmap-purity"])
    rules_by_sym = {(f.symbol, f.rule) for f in findings}
    assert ("decorated", "io") in rules_by_sym
    assert ("plain", "global-write") in rules_by_sym


def test_purity_shard_map_roots_and_impure_call():
    findings = analyze({
        "pkg/s.py": """
        import time
        from jax.experimental.shard_map import shard_map

        def body(x):
            t = time.monotonic()
            return x + t

        def launch(mesh, xs):
            return shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)(xs)
        """,
    }, ["vmap-purity"])
    assert [(f.symbol, f.rule) for f in findings] == [("body", "impure-call")]


# --- donation-aliasing edge cases --------------------------------------------


def test_array_metadata_reads_are_not_syncs():
    """.shape/.ndim/.dtype/.size are static metadata — branching on or
    int()-ing them never blocks on the device."""
    findings = analyze({
        "pkg/meta.py": """
        import jax.numpy as jnp

        def f():
            arr = jnp.ones((4, 2))
            if arr.shape[0] > 2:
                pass
            n = int(arr.ndim)
            return arr.size + n
        """,
    }, ["host-sync"])
    assert findings == []


def test_exception_delegation_does_not_cross_classes():
    """self.X resolves to the caller's OWN class when it defines X —
    another class's same-named surfacing method must not exempt a
    genuine swallow."""
    findings = analyze({
        "pkg/xclass.py": """
        class A:
            def _surface(self, err):
                self.log.error(err)

        class B:
            def _surface(self, err):
                self.count += 1  # does NOT surface

            def handler(self):
                try:
                    risky()
                except Exception as e:
                    self._surface(e)
        """,
    }, ["exception-hygiene"])
    assert [f.symbol for f in findings] == ["B.handler"]


def test_donation_multiline_call_not_self_flagged():
    """A donated call formatted across lines must not read its own
    argument as a use-after-donate."""
    findings = analyze({
        "pkg/donml.py": """
        import jax

        def step(x):
            return x

        def run(state):
            prog = jax.jit(step, donate_argnums=(0,))
            out = prog(
                state)
            return out
        """,
    }, ["donation-aliasing"])
    assert findings == []


def test_donation_reuse_flagged_and_clean_pass():
    findings = analyze({
        "pkg/don.py": """
        import jax

        def step(x):
            return x

        def run(state):
            prog = jax.jit(step, donate_argnums=(0,))
            out = prog(state)
            return state.sum()  # use-after-donate: flagged

        def run_clean(state):
            prog = jax.jit(step, donate_argnums=(0,))
            out = prog(state)
            return out.sum()
        """,
    }, ["donation-aliasing"])
    assert [(f.rule, f.symbol) for f in findings] == \
        [("donated-reuse", "run")]


def test_cross_module_uncached_builder_flagged_cached_ok():
    srcs = {
        "pkg/builder.py": """
        import jax

        def build_programs(fn):
            return {"main": jax.jit(fn)}
        """,
        "pkg/user.py": """
        from .builder import build_programs

        class Engine:
            def __init__(self, fn):
                self._progs = build_programs(fn)  # init cache: OK

            def cycle(self, fn, x):
                progs = build_programs(fn)  # per-call rebuild: flagged
                return progs["main"](x)
        """,
    }
    findings = analyze(srcs, ["donation-aliasing"])
    assert [(f.rule, f.path, f.symbol) for f in findings] == \
        [("uncached-builder", "pkg/user.py", "Engine.cycle")]


def test_self_caching_builder_exempt():
    """WhatIfEngine._programs_for's pattern: the builder memoizes into
    self state before returning — its call sites need no second cache."""
    findings = analyze({
        "pkg/builder2.py": """
        import jax

        class Engine:
            def __init__(self):
                self._cache = {}

            def programs_for(self, key, fn):
                cached = self._cache.get(key)
                if cached is not None:
                    return cached
                progs = {"one": jax.jit(fn)}
                self._cache[key] = progs
                return progs
        """,
        "pkg/user2.py": """
        def drive(engine, fn, x):
            progs = engine.programs_for("k", fn)
            return progs["one"](x)
        """,
    }, ["donation-aliasing"])
    assert findings == []


# --- suppression comments -----------------------------------------------------


def test_suppression_inline_and_standalone_silence_findings():
    findings = analyze({
        "pkg/sup.py": """
        def inline():
            try:
                pass
            except Exception:  # ktpu-analysis: ignore[exception-hygiene] -- probe is best-effort by contract
                pass

        def standalone():
            try:
                pass
            # ktpu-analysis: ignore[exception-hygiene] -- covered by the caller's circuit breaker
            except Exception:
                pass
        """,
    }, ["exception-hygiene"])
    assert findings == []


def test_suppression_requires_justification():
    findings = analyze({
        "pkg/sup2.py": """
        def f():
            try:
                pass
            except Exception:  # ktpu-analysis: ignore[exception-hygiene]
                pass
        """,
    }, ["exception-hygiene"])
    assert [(f.check, f.rule) for f in findings] == \
        [("suppression", "missing-justification")]


def test_suppression_unknown_check_and_unused_are_linted():
    findings = analyze({
        "pkg/sup3.py": """
        def f():
            # ktpu-analysis: ignore[no-such-check] -- misspelled
            x = 1
            # ktpu-analysis: ignore[exception-hygiene] -- nothing here to suppress
            y = 2
            return x + y
        """,
    }, ["exception-hygiene"])
    assert sorted(f.rule for f in findings) == ["unknown-check", "unused"]


def test_suppression_marker_in_docstring_is_not_a_suppression():
    findings = analyze({
        "pkg/sup4.py": '''
        def f():
            """Docs may explain `# ktpu-analysis: ignore[exception-hygiene] -- why` safely."""
            try:
                pass
            except Exception:
                pass
        ''',
    }, ["exception-hygiene"])
    assert [f.rule for f in findings] == ["silent-swallow"]


def test_suppression_cannot_hide_suppression_lint():
    findings = analyze({
        "pkg/sup5.py": """
        def f():
            try:
                pass
            # ktpu-analysis: ignore[exception-hygiene, suppression]
            except Exception:
                pass
        """,
    }, ["exception-hygiene"])
    assert ("suppression", "missing-justification") in \
        {(f.check, f.rule) for f in findings}


# --- analyzer CLI: --check all and --diff -------------------------------------


def test_cli_check_all_exits_zero():
    import importlib

    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    try:
        analyze_cli = importlib.import_module("analyze")
    finally:
        sys.path.pop(0)
    assert analyze_cli.main(["--check", "all"]) == 0
    # bare --check means --check all
    assert analyze_cli.main(["--check"]) == 0


def test_cli_diff_scopes_to_changed_files(capsys):
    import importlib

    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    try:
        analyze_cli = importlib.import_module("analyze")
    finally:
        sys.path.pop(0)
    # HEAD vs HEAD: the scope is exactly the working-tree changes; the
    # gate still exits 0 on a clean tree and never enforces stale entries
    rc = analyze_cli.main(["--diff", "HEAD", "--check"])
    assert rc == 0
    # an unresolvable ref falls back to the FULL-tree gate (fail closed)
    rc = analyze_cli.main(["--diff", "definitely-not-a-ref", "--check"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "falling back to the FULL-tree gate" in err


# --- runtime lockcheck -------------------------------------------------------


def test_lockcheck_detects_inversion():
    mon = lockcheck.LockMonitor()
    a = lockcheck.CheckedLock(threading.Lock(), "A", mon)
    b = lockcheck.CheckedLock(threading.Lock(), "B", mon)

    def order_ab():
        with a:
            with b:
                pass

    def order_ba():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=order_ab)
    t1.start()
    t1.join()
    t2 = threading.Thread(target=order_ba)
    t2.start()
    t2.join()
    assert mon.violations, "A->B then B->A must be reported"
    assert "inversion" in mon.report()
    # the inverted edge is NOT recorded: re-acquiring in the ORIGINAL
    # correct order afterwards must not pile on spurious violations
    n = len(mon.violations)
    t3 = threading.Thread(target=order_ab)
    t3.start()
    t3.join()
    assert len(mon.violations) == n
    with pytest.raises(lockcheck.LockOrderViolation):
        mon.assert_clean()


def test_lockcheck_transitive_inversion():
    mon = lockcheck.LockMonitor()
    a = lockcheck.CheckedLock(threading.Lock(), "A", mon)
    b = lockcheck.CheckedLock(threading.Lock(), "B", mon)
    c = lockcheck.CheckedLock(threading.Lock(), "C", mon)
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with a:  # A->B->C established; C->A closes the cycle
            pass
    assert mon.violations


def test_lockcheck_consistent_order_and_reentrancy_clean():
    mon = lockcheck.LockMonitor()
    a = lockcheck.CheckedLock(threading.Lock(), "A", mon)
    r = lockcheck.CheckedLock(threading.RLock(), "R", mon)
    for _ in range(3):
        with a:
            with r:
                with r:  # RLock reentry: no ordering edge
                    pass
    mon.assert_clean()


def test_lockcheck_strict_raises_at_site():
    mon = lockcheck.LockMonitor(strict=True)
    a = lockcheck.CheckedLock(threading.Lock(), "A", mon)
    b = lockcheck.CheckedLock(threading.Lock(), "B", mon)
    with a:
        with b:
            pass
    with pytest.raises(lockcheck.LockOrderViolation):
        with b:
            with a:
                pass


def test_maybe_wrap_inactive_is_passthrough():
    lockcheck.deactivate()
    raw = threading.Lock()
    assert lockcheck.maybe_wrap(raw, "X") is raw
    mon = lockcheck.activate()
    try:
        wrapped = lockcheck.maybe_wrap(raw, "X")
        assert isinstance(wrapped, lockcheck.CheckedLock)
        with wrapped:
            pass
        mon.assert_clean()
    finally:
        lockcheck.deactivate()


def test_lockcheck_nonblocking_acquire_failure_unwinds():
    mon = lockcheck.LockMonitor()
    a = lockcheck.CheckedLock(threading.Lock(), "A", mon)
    assert a.acquire()
    got = []

    def try_lock():
        got.append(a.acquire(blocking=False))

    t = threading.Thread(target=try_lock)
    t.start()
    t.join()
    assert got == [False]
    a.release()
    # the failed acquire left no phantom hold: ordering stays clean
    b = lockcheck.CheckedLock(threading.Lock(), "B", mon)
    with b:
        with a:
            pass
    mon.assert_clean()


def test_store_bind_pod_bumps_resource_version():
    """The deferred-drop-callback restructure of ObjectStore must preserve
    the bind subresource's rv bump: the bound pod carries the NEW
    resourceVersion (CAS and relist-diff correctness both read it)."""
    from kubernetes_tpu.sim.store import ObjectStore
    from kubernetes_tpu.testutil import make_pod

    store = ObjectStore()
    pod = make_pod().name("bp").namespace("default").obj()
    store.create("Pod", pod)
    rv_before = pod.metadata.resource_version
    assert store.bind_pod("default", "bp", "node-x")
    assert pod.metadata.resource_version == store.current_rv()
    assert pod.metadata.resource_version > rv_before


def test_instrumented_object_store_runs_clean():
    """A store + reflector exercising create/update/watch under an active
    monitor: real lock traffic, no inversions."""
    from kubernetes_tpu.client.informer import Reflector
    from kubernetes_tpu.perf.workloads import node_default
    from kubernetes_tpu.sim.store import ObjectStore

    mon = lockcheck.activate()
    try:
        store = ObjectStore()
        refl = Reflector(store, "Node")
        refl.run()
        for i in range(4):
            store.create("Node", node_default(i))
        assert len(refl.items) == 4
        refl.stop()
        mon.assert_clean()
    finally:
        lockcheck.deactivate()


# --- span-catalog (ISSUE-14) -------------------------------------------------


SPAN_TRACE_SRC = """
SPAN_CATALOG = frozenset({"attempt", "dispatch", "ghost_entry"})


class Tracer:
    def span(self, name, parent=None, **attrs):
        return name
"""


def test_span_catalog_unknown_dynamic_and_unused():
    findings = analyze({
        "kubernetes_tpu/component_base/trace.py": SPAN_TRACE_SRC,
        "kubernetes_tpu/sched.py": """
        def cycle(tracer, phase):
            tracer.span("attempt")            # fine
            tracer.span("dispatch")           # fine
            tracer.span("dispatchh")          # typo: unknown-span
            tracer.span(phase)                # dynamic-span
        """,
    }, ["span-catalog"])
    got = rules(findings)
    assert ("span-catalog", "unknown-span") in got
    assert ("span-catalog", "dynamic-span") in got
    # "ghost_entry" is cataloged but never emitted
    unused = [f for f in findings if f.rule == "unused-span"]
    assert len(unused) == 1 and "ghost_entry" in unused[0].message
    # catalog hits anchor at the emitting module, unused at the catalog
    assert all(f.path == "kubernetes_tpu/sched.py" for f in findings
               if f.rule in ("unknown-span", "dynamic-span"))
    assert unused[0].path.endswith("component_base/trace.py")


def test_span_catalog_clean_fixture_and_no_trace_module():
    clean = analyze({
        "kubernetes_tpu/component_base/trace.py": SPAN_TRACE_SRC,
        "kubernetes_tpu/sched.py": """
        def cycle(tracer):
            tracer.span("attempt")
            tracer.span("dispatch")
            tracer.span("ghost_entry")
        """,
    }, ["span-catalog"])
    assert clean == []
    # without the trace module (or its catalog), the check stays silent
    assert analyze({"kubernetes_tpu/x.py": """
    def f(t):
        t.span("whatever")
    """}, ["span-catalog"]) == []


def test_span_catalog_registered_and_repo_clean(repo_findings):
    assert "span-catalog" in CHECK_REGISTRY
    assert [f for f in repo_findings if f.check == "span-catalog"] == []
